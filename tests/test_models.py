"""Model-zoo tests (mirrors reference models/ specs — AlexNetSpec,
InceptionSpec, ResNetSpec, ModelGraientCheckSpec; SURVEY §4.5).

Shapes use small spatial inputs where the architecture allows; the ImageNet
models are exercised at full 224x224 with batch 1 (forward only) and are
marked slow-ish but still CPU-feasible.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import models
from bigdl_tpu.nn import ClassNLLCriterion


def fwd(model, x, training=False):
    model.materialize(jax.random.PRNGKey(0))
    y, _ = model.apply(model.params, model.state, x, training=training,
                       rng=jax.random.PRNGKey(1))
    return y


class TestLeNet5:
    def test_forward_shape(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 1, 28, 28))
        assert fwd(models.LeNet5(10), x).shape == (4, 10)

    def test_log_softmax_output(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 1, 28, 28))
        y = fwd(models.LeNet5(10), x)
        np.testing.assert_allclose(np.exp(np.asarray(y)).sum(-1), 1.0,
                                   rtol=1e-4)

    def test_trains_on_tiny_batch(self):
        """A few SGD steps must reduce NLL loss — gradient sanity for the
        whole stack (reference ModelGraientCheckSpec analogue)."""
        model = models.LeNet5(10)
        model.materialize(jax.random.PRNGKey(0))
        crit = ClassNLLCriterion()
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 1, 28, 28))
        t = jnp.arange(8) % 10 + 1  # ClassNLL targets are 1-based

        def loss_fn(params):
            y, _ = model.apply(params, model.state, x, training=False)
            return crit.apply(y, t)

        params = model.params
        l0 = loss_fn(params)
        g = jax.grad(loss_fn)(params)
        for _ in range(5):
            g = jax.grad(loss_fn)(params)
            params = jax.tree.map(lambda p, gi: p - 0.5 * gi, params, g)
        assert float(loss_fn(params)) < float(l0)


class TestAutoencoder:
    def test_reconstruction_shape_and_range(self):
        x = jax.random.uniform(jax.random.PRNGKey(0), (4, 1, 28, 28))
        y = fwd(models.Autoencoder(32), x)
        assert y.shape == (4, 784)
        assert float(jnp.min(y)) >= 0.0 and float(jnp.max(y)) <= 1.0


class TestInception:
    def test_v1_no_aux_shape(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 3, 224, 224))
        assert fwd(models.Inception_v1_NoAuxClassifier(100),
                   x).shape == (1, 100)

    def test_v1_aux_heads_concat(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 3, 224, 224))
        y = fwd(models.Inception_v1(50), x)
        # three LogSoftMax heads concatenated on features
        assert y.shape == (1, 150)
        p = np.exp(np.asarray(y)).reshape(3, 50)
        np.testing.assert_allclose(p.sum(-1), 1.0, rtol=1e-3)

    def test_layer_v1_channel_math(self):
        blk = models.Inception_Layer_v1(
            192, ((64,), (96, 128), (16, 32), (32,)))
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 192, 28, 28))
        assert fwd(blk, x).shape == (2, 64 + 128 + 32 + 32, 28, 28)

    def test_layer_v2_downsample(self):
        blk = models.Inception_Layer_v2(
            320, ((0,), (128, 160), (64, 96), ("max", 0)))
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 320, 28, 28))
        assert fwd(blk, x).shape == (2, 160 + 96 + 320, 14, 14)

    @pytest.mark.slow  # 224x224 compile ~9s; v1 + layer math pin the family
    def test_v2_no_aux_shape(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 3, 224, 224))
        assert fwd(models.Inception_v2_NoAuxClassifier(10), x).shape == (1, 10)


class TestVgg:
    def test_cifar_shape(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 32, 32))
        assert fwd(models.VggForCifar10(10), x).shape == (2, 10)

    @pytest.mark.slow  # 224x224 vgg16 compile ~13s; cifar pins the family
    def test_vgg16_shape(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 3, 224, 224))
        assert fwd(models.Vgg_16(10), x).shape == (1, 10)


class TestResNet:
    def test_cifar_depths(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 32, 32))
        for depth in (20, 32):
            assert fwd(models.ResNet(10, {"depth": depth}), x).shape == (2, 10)

    @pytest.mark.slow  # 224x224 compile ~11s; cifar depths pin the family
    def test_imagenet_bottleneck(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 3, 224, 224))
        m = models.ResNet(7, {"depth": 50,
                              "dataset": models.DatasetType.ImageNet})
        assert fwd(m, x).shape == (1, 7)

    def test_shortcut_type_a_zero_pads(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 32, 32))
        m = models.ResNet(10, {"depth": 20,
                               "shortcutType": models.ShortcutType.A})
        assert fwd(m, x).shape == (2, 10)

    def test_model_init_statistics(self):
        m = models.ResNet(10, {"depth": 20})
        models.model_init(m)
        # first conv: He std sqrt(2/(3*3*16))
        w = np.asarray(m.params["0"]["weight"])
        assert abs(w.std() - np.sqrt(2.0 / (3 * 3 * 16))) < 0.02
        assert np.all(np.asarray(m.params["1"]["weight"]) == 1.0)


class TestSimpleRNN:
    def test_reference_semantics(self):
        """batchSize=1: (1,T,I) -> (T,output) (reference SimpleRNN +
        Select(1,1), models/rnn/SimpleRNN.scala:22-35)."""
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 5, 20))
        assert fwd(models.SimpleRNN(20, 16, 20), x).shape == (5, 20)

    def test_batched_variant(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 5, 20))
        y = fwd(models.BatchedSimpleRNN(20, 16, 20), x)
        assert y.shape == (4, 5, 20)
        np.testing.assert_allclose(np.exp(np.asarray(y)).sum(-1), 1.0,
                                   rtol=1e-3)


class TestAlexNet:
    @pytest.mark.slow  # 224x224 compile ~17s; caffe pins the family
    def test_owt_shape(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 3, 224, 224))
        assert fwd(models.AlexNet_OWT(10), x).shape == (1, 10)

    def test_caffe_layout_groups(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 3, 227, 227))
        assert fwd(models.AlexNet(10), x).shape == (1, 10)

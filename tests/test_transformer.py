"""Transformer LM (models/transformer) — the long-context flagship."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn
from bigdl_tpu.models import TransformerLM
from bigdl_tpu.parallel.engine import Engine


def _tokens(b=2, s=16, vocab=50, seed=0):
    return jnp.asarray(np.random.default_rng(seed)
                       .integers(1, vocab + 1, size=(b, s)))


class TestTransformerLM:
    def test_forward_shape_and_logprobs(self):
        m = TransformerLM(50, d_model=32, num_heads=4, num_layers=2,
                          max_len=32)
        m.materialize(jax.random.PRNGKey(0))
        m.evaluate()
        y, _ = m.apply(m.params, m.state, _tokens())
        assert y.shape == (2, 16, 50)
        # log-softmax rows sum to 1 in prob space
        np.testing.assert_allclose(np.exp(np.asarray(y)).sum(-1),
                                   np.ones((2, 16)), rtol=1e-4)

    def test_causality(self):
        """Output at position t must not depend on tokens after t."""
        m = TransformerLM(50, d_model=32, num_heads=4, num_layers=2,
                          max_len=32)
        m.materialize(jax.random.PRNGKey(0))
        m.evaluate()
        x1 = np.asarray(_tokens(b=1))
        x2 = x1.copy()
        x2[0, 10:] = ((x2[0, 10:] + 7) % 50) + 1   # change the future
        y1, _ = m.apply(m.params, m.state, jnp.asarray(x1))
        y2, _ = m.apply(m.params, m.state, jnp.asarray(x2))
        np.testing.assert_allclose(np.asarray(y1)[0, :10],
                                   np.asarray(y2)[0, :10], rtol=1e-5,
                                   atol=1e-5)
        assert not np.allclose(np.asarray(y1)[0, 10:],
                               np.asarray(y2)[0, 10:])

    def test_learns_copy_task(self):
        """Next-token prediction on a repeated pattern goes to low loss."""
        vocab, s = 8, 16
        m = TransformerLM(vocab, d_model=32, num_heads=2, num_layers=2,
                          max_len=s)
        m.materialize(jax.random.PRNGKey(0))
        m.training()
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
        from bigdl_tpu.optim import SGD
        sgd = SGD(learning_rate=0.02)
        pattern = np.tile(np.arange(1, vocab + 1), 4)[:s + 1]
        x = jnp.asarray(pattern[None, :-1])
        t = jnp.asarray(pattern[None, 1:].astype(np.float32))
        params, state, ostate = m.params, m.state, sgd.init_state(m.params)

        @jax.jit
        def step(p, st, os_):
            def loss_fn(p):
                y, ns = m.apply(p, st, x, training=True,
                                rng=jax.random.PRNGKey(1))
                return crit.apply(y, t), ns
            (l, ns), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
            p2, os2 = sgd.update(g, p, os_)
            return p2, ns, os2, l

        losses = []
        for _ in range(300):
            params, state, ostate, l = step(params, state, ostate)
            losses.append(float(l))
        assert losses[-1] < 0.1, losses[-1]

    # the 8-way ring LM compile is ~36s on the single-core tier-1 box;
    # ulysses keeps the LM-level sequence-parallel seam in tier-1 and
    # test_train_main_with_sequence_parallel still trains with ring
    @pytest.mark.parametrize(
        "sp", [pytest.param("ring", marks=pytest.mark.slow), "ulysses"])
    def test_sequence_parallel_matches_local(self, sp):
        Engine.reset()
        Engine.init(axes={"seq": 8})
        local = TransformerLM(50, d_model=32, num_heads=8, num_layers=2,
                              max_len=32)
        local.materialize(jax.random.PRNGKey(2))
        local.evaluate()
        par = TransformerLM(50, d_model=32, num_heads=8, num_layers=2,
                            max_len=32, sequence_parallel=sp)
        x = _tokens(b=2, s=32)
        y_local, _ = local.apply(local.params, local.state, x)
        y_par, _ = par.apply(local.params, local.state, x)
        np.testing.assert_allclose(np.asarray(y_par), np.asarray(y_local),
                                   rtol=2e-4, atol=2e-4)
        Engine.reset()


def test_train_main_with_sequence_parallel(tmp_path):
    """The CLI's --sequenceParallel flag must build a seq-axis mesh and
    train (review finding: the data-only mesh crashed ring attention)."""
    import random

    from bigdl_tpu.models.transformer.train import main
    from bigdl_tpu.parallel.engine import Engine
    random.seed(0)
    words = ["a", "b", "c", "d", "e", "f"]
    with open(tmp_path / "input.txt", "w") as f:
        for _ in range(60):
            f.write(" ".join(random.choice(words)
                             for _ in range(10)) + ". ")
    Engine.reset()
    main(["-f", str(tmp_path), "-b", "8", "-e", "1", "--seqLength", "16",
          "--dModel", "32", "--numHeads", "8", "--numLayers", "1",
          "--sequenceParallel", "ring"])
    Engine.reset()


class TestRoPE:
    def test_rope_scores_are_relative(self):
        """q_m . k_n after rotation depends only on m - n — the property
        that makes RoPE length-extrapolable and cache-friendly."""
        from bigdl_tpu.nn.attention import apply_rope
        rs = np.random.default_rng(0)
        q = jnp.asarray(rs.standard_normal((1, 1, 2, 8)), jnp.float32)
        k = jnp.asarray(rs.standard_normal((1, 1, 2, 8)), jnp.float32)

        def score(m, n):
            qm = apply_rope(q, jnp.asarray([m]))
            kn = apply_rope(k, jnp.asarray([n]))
            return float(jnp.sum(qm[0, 0] * kn[0, 0]))

        np.testing.assert_allclose(score(3, 1), score(13, 11), rtol=1e-5)
        np.testing.assert_allclose(score(5, 5), score(40, 40), rtol=1e-5)
        assert abs(score(3, 1) - score(4, 1)) > 1e-6   # positions matter

    def test_rope_lm_trains(self):
        from bigdl_tpu.models import TransformerLM
        from bigdl_tpu import nn, optim as _o
        import bigdl_tpu.optim as optim
        V, S = 16, 8
        m = TransformerLM(V, d_model=32, num_heads=4, num_layers=2,
                          max_len=S, pos_encoding="rope")
        m.materialize(jax.random.PRNGKey(0))
        m.training()
        assert "pos" not in m.params["0"]        # no additive table
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        sgd = optim.SGD(learning_rate=0.1)
        rs = np.random.default_rng(0)
        data = jnp.asarray(rs.integers(1, V + 1, size=(4, S)))
        labels = jnp.roll(data, -1, axis=1)
        params, st = m.params, m.state
        ostate = sgd.init_state(params)

        @jax.jit
        def step(p, o):
            def loss_fn(p):
                y, s2 = m.apply(p, st, data, training=True)
                return crit.apply(y, labels), s2
            (loss, _), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
            p2, o2 = sgd.update(g, p, o)
            return p2, o2, loss

        losses = []
        for _ in range(12):
            params, ostate, loss = step(params, ostate)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8

    @pytest.mark.slow  # ring composition depth (~9s compile)
    def test_rope_ring_matches_local(self):
        """RoPE composes with ring attention: rotation happens on the
        global arrays before the seq-axis collective."""
        from bigdl_tpu.parallel import Engine
        from bigdl_tpu.parallel.engine import get_mesh
        from jax.sharding import NamedSharding, PartitionSpec as P
        Engine.reset()
        mesh = Engine.init(axes={"seq": 4},
                           devices=jax.devices()[:4])
        rs = np.random.default_rng(1)
        x = jnp.asarray(rs.standard_normal((2, 16, 32)), jnp.float32)
        local = nn.MultiHeadAttention(32, 4, causal=True, rope=True)
        local.materialize(jax.random.PRNGKey(0))
        ring = nn.MultiHeadAttention(32, 4, causal=True, rope=True,
                                     sequence_parallel="ring")
        want, _ = local.apply(local.params, {}, x)
        xs = jax.device_put(x, NamedSharding(mesh, P(None, "seq")))
        with mesh:
            got, _ = ring.apply(local.params, {}, xs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-3)
        Engine.reset()


class TestGQA:
    def test_gqa_matches_manual_kv_repeat(self):
        """Grouped attention == full attention run on explicitly
        repeated k/v heads (the defining GQA identity)."""
        from bigdl_tpu.nn import MultiHeadAttention
        rs = np.random.default_rng(0)
        m = MultiHeadAttention(32, 4, causal=True, num_kv_heads=2)
        m.materialize(jax.random.PRNGKey(0))
        x = jnp.asarray(rs.standard_normal((2, 8, 32)), jnp.float32)
        got, _ = m.apply(m.params, {}, x)

        # manual reference: widen k/v weights by repeating head blocks
        full = MultiHeadAttention(32, 4, causal=True)
        full.materialize(jax.random.PRNGKey(1))
        p = dict(m.params)
        hd = 8
        rep = lambda w: jnp.concatenate(      # block order [k0,k0,k1,k1]
            [w[i * hd:(i + 1) * hd] for i in (0, 0, 1, 1)], axis=0)
        fp = dict(full.params)
        fp.update(q_weight=p["q_weight"], out_weight=p["out_weight"],
                  q_bias=p["q_bias"], out_bias=p["out_bias"],
                  k_weight=rep(p["k_weight"]), v_weight=rep(p["v_weight"]),
                  k_bias=jnp.concatenate(
                      [p["k_bias"][i * hd:(i + 1) * hd]
                       for i in (0, 0, 1, 1)]),
                  v_bias=jnp.concatenate(
                      [p["v_bias"][i * hd:(i + 1) * hd]
                       for i in (0, 0, 1, 1)]))
        want, _ = full.apply(fp, {}, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_gqa_param_shapes(self):
        from bigdl_tpu.nn import MultiHeadAttention
        m = MultiHeadAttention(32, 4, num_kv_heads=1)   # multi-query
        m.materialize(jax.random.PRNGKey(0))
        assert m.params["k_weight"].shape == (8, 32)
        assert m.params["v_weight"].shape == (8, 32)
        assert m.params["q_weight"].shape == (32, 32)

    def test_gqa_lm_trains(self):
        from bigdl_tpu.models import TransformerLM
        import bigdl_tpu.optim as optim
        V, S = 16, 8
        m = TransformerLM(V, d_model=32, num_heads=4, num_layers=2,
                          max_len=S, num_kv_heads=2, pos_encoding="rope")
        m.materialize(jax.random.PRNGKey(0))
        m.training()
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        sgd = optim.SGD(learning_rate=0.1)
        rs = np.random.default_rng(0)
        data = jnp.asarray(rs.integers(1, V + 1, size=(4, S)))
        labels = jnp.roll(data, -1, axis=1)
        params, st = m.params, m.state
        ostate = sgd.init_state(params)

        @jax.jit
        def step(p, o):
            def loss_fn(p):
                y, s2 = m.apply(p, st, data, training=True)
                return crit.apply(y, labels), s2
            (loss, _), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
            p2, o2 = sgd.update(g, p, o)
            return p2, o2, loss

        losses = []
        for _ in range(12):
            params, ostate, loss = step(params, ostate)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8

    @pytest.mark.slow  # ring composition depth (~8s compile)
    def test_gqa_ring_matches_local(self):
        """Grouped k/v blocks ride the ring at kv width (widened only
        inside each hop) and must match the local grouped attention."""
        from bigdl_tpu.parallel import Engine
        from jax.sharding import NamedSharding, PartitionSpec as P
        Engine.reset()
        mesh = Engine.init(axes={"seq": 4}, devices=jax.devices()[:4])
        rs = np.random.default_rng(2)
        x = jnp.asarray(rs.standard_normal((2, 16, 32)), jnp.float32)
        local = nn.MultiHeadAttention(32, 4, causal=True, num_kv_heads=2,
                                      rope=True)
        local.materialize(jax.random.PRNGKey(0))
        ring = nn.MultiHeadAttention(32, 4, causal=True, num_kv_heads=2,
                                     rope=True, sequence_parallel="ring")
        want, _ = local.apply(local.params, {}, x)
        xs = jax.device_put(x, NamedSharding(mesh, P(None, "seq")))
        with mesh:
            got, _ = ring.apply(local.params, {}, xs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-3)
        Engine.reset()

    def test_bad_num_kv_heads_raises(self):
        import pytest as _pt
        with _pt.raises(ValueError, match="num_kv_heads"):
            nn.MultiHeadAttention(32, 4, num_kv_heads=0)

"""Example-app tests (reference example/ — SURVEY §2.10)."""
import numpy as np
import pytest

from bigdl_tpu import nn


def _make_val_tree(root, n_per_class=3, size=260):
    from PIL import Image
    rng = np.random.default_rng(0)
    for cls in ("daisy", "rose"):
        d = root / "val" / cls
        d.mkdir(parents=True)
        for i in range(n_per_class):
            arr = rng.integers(0, 256, (size, size, 3), np.uint8)
            Image.fromarray(arr).save(d / f"img{i}.png")


class TestModelValidator:
    def test_bigdl_model_end_to_end(self, tmp_path):
        """CLI path: save a bigdl snapshot, validate it over an image-folder
        val tree (reference ModelValidator bigdl branch)."""
        from bigdl_tpu.examples.loadmodel import model_validator
        _make_val_tree(tmp_path)
        model = (nn.Sequential()
                 .add(nn.SpatialAveragePooling(224, 224, 224, 224))
                 .add(nn.View(3))
                 .add(nn.Linear(3, 2))
                 .add(nn.LogSoftMax()))
        model.materialize()
        mpath = tmp_path / "model.bigdl"
        model.save(str(mpath))
        results = model_validator.main([
            "-f", str(tmp_path), "-m", "resnet", "-t", "bigdl",
            "--modelPath", str(mpath), "-b", "2"])
        assert len(results) == 2
        top1 = results[0][0].result()[0]
        assert 0.0 <= top1 <= 1.0

    def test_unknown_type_raises(self):
        from bigdl_tpu.examples.loadmodel import model_validator
        with pytest.raises(ValueError, match="torch, caffe or bigdl"):
            model_validator.main(["-m", "resnet", "-t", "mxnet"])


class TestImagePredictor:
    def test_predict_folder_end_to_end(self, tmp_path):
        """Reference ImagePredictor flow: folder of unlabeled images ->
        preprocess -> predict_class -> (name, class) pairs."""
        from PIL import Image
        from bigdl_tpu.examples.imageclassification import image_predictor
        rng = np.random.default_rng(1)
        img_dir = tmp_path / "imgs"
        img_dir.mkdir()
        for i in range(5):
            arr = rng.integers(0, 256, (260, 280, 3), np.uint8)
            Image.fromarray(arr).save(img_dir / f"photo_{i}.jpg")
        model = (nn.Sequential()
                 .add(nn.SpatialAveragePooling(224, 224, 224, 224))
                 .add(nn.View(3))
                 .add(nn.Linear(3, 4))
                 .add(nn.LogSoftMax()))
        model.materialize()
        mpath = tmp_path / "model.bigdl"
        model.save(str(mpath))
        results = image_predictor.main([
            "-f", str(img_dir), "--modelPath", str(mpath), "-b", "2"])
        assert len(results) == 5
        names = [n for n, _ in results]
        assert names == sorted(names)
        assert all(1 <= c <= 4 for _, c in results)

"""Example-app tests (reference example/ — SURVEY §2.10)."""
import numpy as np
import pytest

from bigdl_tpu import nn


def _make_val_tree(root, n_per_class=3, size=260):
    from PIL import Image
    rng = np.random.default_rng(0)
    for cls in ("daisy", "rose"):
        d = root / "val" / cls
        d.mkdir(parents=True)
        for i in range(n_per_class):
            arr = rng.integers(0, 256, (size, size, 3), np.uint8)
            Image.fromarray(arr).save(d / f"img{i}.png")


class TestModelValidator:
    def test_bigdl_model_end_to_end(self, tmp_path):
        """CLI path: save a bigdl snapshot, validate it over an image-folder
        val tree (reference ModelValidator bigdl branch)."""
        from bigdl_tpu.examples.loadmodel import model_validator
        _make_val_tree(tmp_path)
        model = (nn.Sequential()
                 .add(nn.SpatialAveragePooling(224, 224, 224, 224))
                 .add(nn.View(3))
                 .add(nn.Linear(3, 2))
                 .add(nn.LogSoftMax()))
        model.materialize()
        mpath = tmp_path / "model.bigdl"
        model.save(str(mpath))
        results = model_validator.main([
            "-f", str(tmp_path), "-m", "resnet", "-t", "bigdl",
            "--modelPath", str(mpath), "-b", "2"])
        assert len(results) == 2
        top1 = results[0][0].result()[0]
        assert 0.0 <= top1 <= 1.0

    def test_unknown_type_raises(self):
        from bigdl_tpu.examples.loadmodel import model_validator
        with pytest.raises(ValueError, match="torch, caffe or bigdl"):
            model_validator.main(["-m", "resnet", "-t", "mxnet"])

"""Text-classification example tests (reference example/textclassification —
BASELINE tracked config #5). Synthetic 3-class corpus + tiny GloVe file;
the real 20 Newsgroups run uses the same code path at scale."""
import numpy as np

from bigdl_tpu.examples.textclassification import (
    TextClassifier, build_model, shaping, to_tokens, vectorization)
from bigdl_tpu.utils.random import RandomGenerator


class TestSimpleTokenizer:
    def test_to_tokens(self):
        assert to_tokens("Hello, World! a bb ccc 123-xyz") == \
            ["hello", "world", "ccc", "xyz"]

    def test_shaping_pre_truncate_and_pad(self):
        assert shaping([1, 2, 3, 4], 2) == [3, 4]          # keep tail
        assert shaping([1, 2, 3, 4], 2, trunc="post") == [1, 2]
        assert shaping([1, 2], 4) == [1, 2, 0, 0]

    def test_vectorization_unknown_is_zero(self):
        w2v = {1: np.ones(3, np.float32)}
        out = vectorization([1, 2], 3, w2v)
        np.testing.assert_array_equal(out[0], 1.0)
        np.testing.assert_array_equal(out[1], 0.0)


def _write_corpus(root, n_per_class=40, seed=0):
    """3 classes with disjoint core vocabularies + shared filler words."""
    rng = np.random.default_rng(seed)
    vocabs = {
        "comp.graphics": ["pixel", "render", "shader", "texture", "vertex"],
        "rec.autos": ["engine", "wheel", "brake", "torque", "clutch"],
        "sci.space": ["orbit", "rocket", "lunar", "probe", "cosmos"],
    }
    filler = ["the", "with", "from", "about", "there", "which"]
    words = sorted({w for v in vocabs.values() for w in v} | set(filler))
    base = root / "20_newsgroup"
    for cat, vocab in vocabs.items():
        d = base / cat
        d.mkdir(parents=True)
        for i in range(n_per_class):
            toks = [str(rng.choice(vocab)) if rng.random() < 0.7
                    else str(rng.choice(filler)) for _ in range(60)]
            (d / str(10000 + i)).write_text(" ".join(toks))
    glove_dir = root / "glove.6B"
    glove_dir.mkdir()
    emb_rng = np.random.default_rng(7)
    lines = []
    for w in words:
        vec = emb_rng.normal(size=20).astype(np.float32)
        lines.append(w + " " + " ".join(f"{v:.5f}" for v in vec))
    (glove_dir / "glove.6B.20d.txt").write_text("\n".join(lines))


class TestTextClassifierEndToEnd:
    def test_trains_to_high_accuracy(self, tmp_path):
        _write_corpus(tmp_path)
        RandomGenerator.set_seed(2)
        # drop_top_words=0: the reference drops the ~10 most frequent words
        # of the real corpus; the tiny synthetic vocab can't spare them
        tc = TextClassifier(str(tmp_path), max_sequence_length=200,
                            max_words_num=1000, batch_size=16,
                            embedding_dim=20, drop_top_words=0)
        trained, optimizer = tc.train(max_epoch=8)
        assert tc.class_num == 3
        # evaluate on the held-out split captured by the optimizer
        from bigdl_tpu.optim import LocalValidator, Top1Accuracy
        res = LocalValidator(trained, optimizer.validation_dataset).test(
            [Top1Accuracy()])
        acc = res[0][0].result()[0]
        assert acc > 0.85, f"val accuracy {acc}"

    def test_build_model_reference_shape_1000(self):
        """The published recipe shape: seq 1000 ends in a 35-wide pool."""
        m = build_model(20, embedding_dim=100, sequence_len=1000)
        x = np.zeros((2, 100, 1000), np.float32)
        y = m.forward(x)
        assert y.shape == (2, 20)

"""Reference-optimizer oracle tests (SURVEY §4.4: the reference
cross-checks its optimized Local/Distri optimizers against naive
RefLocalOptimizer/RefDistriOptimizer implementations).

The oracle here is a hand-rolled, obviously-correct training loop (plain
jax.grad + explicit SGD update, no jit donation, no sharding) run with
the same seeds and data order; the production optimizers must reproduce
its loss trajectory and final parameters.
"""
import numpy as np
import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.dataset import dataset as ds
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.optim import SGD, max_iteration
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
from bigdl_tpu.optim.optimizer import LocalOptimizer
from bigdl_tpu.parallel.engine import Engine


def _model():
    return (nn.Sequential()
            .add(nn.Linear(16, 32)).add(nn.Tanh())
            .add(nn.Linear(32, 4)).add(nn.LogSoftMax()))


def _data(n=32, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, 16)).astype(np.float32),
            rng.integers(1, 5, size=(n,)))


def _oracle(n_steps, lr, momentum):
    """The naive loop: same init seed, same batch every step."""
    model = _model()
    model.materialize(jax.random.PRNGKey(0))
    model.training()
    crit = nn.ClassNLLCriterion()
    data, labels = _data()
    x, t = jnp.asarray(data), jnp.asarray(labels)
    params = model.params
    velocity = jax.tree.map(jnp.zeros_like, params)
    losses = []
    for _ in range(n_steps):
        def loss_fn(p):
            y, _ = model.apply(p, model.state, x, training=True)
            return crit.apply(y, t)
        l, g = jax.value_and_grad(loss_fn)(params)
        # plain SGD with Torch's dampening=momentum default, written out
        # longhand: v = m*v + (1-m)*g; p -= lr*v
        velocity = jax.tree.map(
            lambda v, gg: momentum * v + (1.0 - momentum) * gg,
            velocity, g)
        params = jax.tree.map(lambda p, v: p - lr * v, params, velocity)
        losses.append(float(l))
    return losses, jax.tree.map(np.asarray, params)


def _production(optimizer_cls, n_steps, lr, momentum, **kw):
    model = _model()
    data, labels = _data()
    dataset = ds.iterator_source(
        lambda: iter([MiniBatch(data, labels)]), size=len(labels))
    opt = optimizer_cls(model, dataset, nn.ClassNLLCriterion(), **kw)
    opt.set_optim_method(SGD(learning_rate=lr, momentum=momentum))
    opt.set_end_when(max_iteration(n_steps))
    trained = opt.optimize()
    return jax.tree.map(np.asarray, trained.params)


def test_local_optimizer_matches_oracle():
    losses, p_ref = _oracle(5, 0.1, 0.9)
    p = _production(LocalOptimizer, 5, 0.1, 0.9)
    for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(p_ref)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    assert losses[-1] < losses[0]


def test_distri_optimizer_matches_oracle():
    Engine.reset()
    mesh = Engine.init(axes={"data": 8})
    losses, p_ref = _oracle(5, 0.1, 0.9)
    p = _production(DistriOptimizer, 5, 0.1, 0.9, mesh=mesh)
    for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(p_ref)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    Engine.reset()

"""Recurrent layer tests vs torch (reference: nn/RNN/LSTM/GRU specs)."""
import jax
import jax.numpy as jnp
import numpy as np
import torch

import bigdl_tpu.nn as nn


def assert_close(a, b, tol=1e-4):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=tol,
                               atol=tol)


RS = np.random.RandomState(11)


class TestLSTM:
    def test_vs_torch(self):
        I, H, N, T = 4, 6, 3, 5
        cell = nn.LSTM(I, H)
        rec = nn.Recurrent(cell)
        rec.materialize(jax.random.PRNGKey(0))
        x = RS.randn(N, T, I).astype(np.float32)
        y = rec.forward(jnp.asarray(x))
        assert y.shape == (N, T, H)

        # map our fused weights into torch's LSTM (torch order i, f, g, o;
        # ours i, g, f, o following the reference's gate graph)
        p = rec.params["0"]
        w = np.asarray(p["i2h"]).T  # (4H, I)
        u = np.asarray(p["h2h"]).T
        b = np.asarray(p["bias"])
        perm = np.concatenate([np.arange(0, H),          # i
                               np.arange(2 * H, 3 * H),  # f
                               np.arange(H, 2 * H),      # g
                               np.arange(3 * H, 4 * H)])  # o
        tl = torch.nn.LSTM(I, H, batch_first=True)
        with torch.no_grad():
            tl.weight_ih_l0.copy_(torch.from_numpy(w[perm]))
            tl.weight_hh_l0.copy_(torch.from_numpy(u[perm]))
            tl.bias_ih_l0.copy_(torch.from_numpy(b[perm]))
            tl.bias_hh_l0.zero_()
        ref, _ = tl(torch.from_numpy(x))
        assert_close(y, ref.detach().numpy(), tol=1e-3)

    def test_masked_lengths(self):
        cell = nn.LSTM(3, 4)
        rec = nn.Recurrent(cell)
        rec.materialize(jax.random.PRNGKey(0))
        x = RS.randn(2, 6, 3).astype(np.float32)
        lengths = jnp.asarray([6, 3])
        y = rec.forward((jnp.asarray(x), lengths))
        # outputs past each length must be zero
        assert np.all(np.asarray(y[1, 3:]) == 0)
        assert np.any(np.asarray(y[1, :3]) != 0)


class TestGRU:
    def test_vs_manual_loop(self):
        # The reference GRU applies the reset gate BEFORE the h2h matmul
        # (nn/GRU.scala buildGRU: CMulTable on (h, r) feeds the Linear) —
        # unlike torch.nn.GRU — so the oracle is a manual numpy loop.
        I, H, N, T = 4, 5, 2, 4
        rec = nn.Recurrent(nn.GRU(I, H))
        rec.materialize(jax.random.PRNGKey(1))
        x = RS.randn(N, T, I).astype(np.float32)
        y = rec.forward(jnp.asarray(x))
        p = {k: np.asarray(v) for k, v in rec.params["0"].items()}

        def sigmoid(v):
            return 1.0 / (1.0 + np.exp(-v))

        h = np.zeros((N, H), np.float32)
        outs = []
        for t in range(T):
            rz = sigmoid(x[:, t] @ p["i2h_rz"] + h @ p["h2h_rz"]
                         + p["bias_rz"])
            r, z = rz[:, :H], rz[:, H:]
            cand = np.tanh(x[:, t] @ p["i2h_c"] + (r * h) @ p["h2h_c"]
                           + p["bias_c"])
            h = (1 - z) * cand + z * h
            outs.append(h)
        assert_close(y, np.stack(outs, axis=1), tol=1e-4)


class TestRnnCell:
    def test_vs_torch(self):
        I, H, N, T = 3, 4, 2, 5
        rec = nn.Recurrent(nn.RnnCell(I, H, "tanh"))
        rec.materialize(jax.random.PRNGKey(2))
        x = RS.randn(N, T, I).astype(np.float32)
        y = rec.forward(jnp.asarray(x))
        p = rec.params["0"]
        tr = torch.nn.RNN(I, H, batch_first=True, nonlinearity="tanh")
        with torch.no_grad():
            tr.weight_ih_l0.copy_(torch.from_numpy(np.asarray(p["i2h"]).T))
            tr.weight_hh_l0.copy_(torch.from_numpy(np.asarray(p["h2h"]).T))
            tr.bias_ih_l0.copy_(torch.from_numpy(np.asarray(p["bias"])))
            tr.bias_hh_l0.zero_()
        ref, _ = tr(torch.from_numpy(x))
        assert_close(y, ref.detach().numpy(), tol=1e-3)


class TestWrappers:
    def test_time_distributed(self):
        m = nn.TimeDistributed(nn.Linear(4, 2))
        y = m.forward(jnp.ones((3, 5, 4)))
        assert y.shape == (3, 5, 2)

    def test_birecurrent_with_lengths(self):
        m = nn.BiRecurrent(nn.LSTM(3, 4), nn.LSTM(3, 4))
        x = jnp.asarray(RS.randn(2, 6, 3).astype(np.float32))
        y = m.forward((x, jnp.asarray([6, 3])))
        assert y.shape == (2, 6, 8)

    def test_birecurrent(self):
        m = nn.BiRecurrent(nn.LSTM(3, 4), nn.LSTM(3, 4))
        y = m.forward(jnp.asarray(RS.randn(2, 5, 3).astype(np.float32)))
        assert y.shape == (2, 5, 8)

    def test_grad_flows_through_scan(self):
        rec = nn.Recurrent(nn.LSTM(3, 4))
        rec.materialize(jax.random.PRNGKey(0))
        x = jnp.asarray(RS.randn(2, 5, 3).astype(np.float32))

        def loss(p):
            y, _ = rec.apply(p, rec.state, x)
            return jnp.sum(jnp.square(y))

        g = jax.grad(loss)(rec.params)
        assert float(jnp.sum(jnp.abs(g["0"]["i2h"]))) > 0

"""Benchmark harness: one JSON line per metric, headline first.

Headline (line 1): Inception-v1 ImageNet training throughput per chip on
synthetic device-resident tensors — the roofline-audited number
(docs/PERF.md). Extra lines (VERDICT r3 #6, reference
models/utils/DistriOptimizerPerf.scala:33-70 multi-model harness):

  - inception_v1 REAL-DATA training: JPEG bytes from .brec shards through
    the native u8 decode path, normalize on-device (VERDICT r3 #1)
  - the same with the decoded-RAM cache warm (post-first-epoch rate)
  - resnet50 / vgg16 train throughput
  - transformer LM tokens/s + MFU (fused-CE head, flash attention)

Baseline derivation (BASELINE.md): the reference publishes NO quantitative
table; its README claims single-node Xeon training "comparable with
mainstream GPU" (README.md:9). A mainstream 2016 GPU (K80-class) trains
Inception-v1 at ~150 images/sec, so 150 img/s/device is the documented
stand-in baseline; ``vs_baseline`` = value / 150. MFU / achieved TFLOP/s
are reported so the gap stays honest.

Usage: ``python bench.py`` (all rows) / ``--headline-only`` (line 1 only).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

BASELINE_IMG_PER_SEC = 150.0
BATCH = 256
WARMUP = 3
ITERS = 30
SHARD_DIR = "/tmp/bigdl_tpu_bench_shards_v1"
SHARD_IMAGES = 4096
REAL_BATCH = 256

# bf16 peak TFLOP/s per chip by device kind substring
_PEAK_TFLOPS = {
    "v5 lite": 197.0, "v5e": 197.0,
    "v4": 275.0, "v5p": 459.0, "v5": 459.0,
    "v6 lite": 918.0, "v6e": 918.0,
}


def _chip_peak_tflops() -> float | None:
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for key, peak in _PEAK_TFLOPS.items():
        if key in kind:
            return peak
    return None


def _cost_dict(cost):
    """``Executable.cost_analysis()`` compat: newer jax returns a dict,
    older a [dict] per device — normalize to a dict (or None)."""
    if isinstance(cost, (list, tuple)):
        return cost[0] if cost else None
    return cost


def _set_bf16_policy():
    import jax.numpy as jnp
    from bigdl_tpu.tensor import DTypePolicy, set_policy
    # f32 params, bf16 MXU compute, bf16 activations in HBM — the TPU
    # equivalent of the reference's FP16-on-the-wire + f32 math split
    # (SURVEY §5.8), extended to the memory system because conv steps are
    # bandwidth-bound (docs/PERF.md)
    set_policy(DTypePolicy(param_dtype=jnp.float32,
                           compute_dtype=jnp.bfloat16,
                           activation_dtype=jnp.bfloat16))


def _publish_registry(row: dict):
    """Mirror a bench row into the process-wide metric registry
    (bigdl_tpu.observability) so bench results export beside the
    training/serving series — one gauge per metric name."""
    val = row.get("value")
    if "metric" not in row or not isinstance(val, (int, float)):
        return
    from bigdl_tpu.observability.registry import (default_registry,
                                                  sanitize_name)
    default_registry().gauge(
        "bench_" + sanitize_name(str(row["metric"])),
        f"bench.py row (unit: {row.get('unit', '')})").set(float(val))


def _emit(row: dict):
    _publish_registry(row)
    print(json.dumps(row), flush=True)


def _record_compile_telemetry(name: str, compiled) -> None:
    """Export an AOT executable's cost/memory table (FLOPs, bytes
    accessed, arg/output/temp + peak HBM bytes) as registry gauges so
    ``--metrics-out`` carries compile telemetry beside the rates."""
    from bigdl_tpu.observability import compile_watch
    try:
        compile_watch.record_executable(name, compiled)
    except Exception as e:          # telemetry must never fail a row
        print(f"compile telemetry for {name} unavailable: {e}",
              file=sys.stderr)


def _convnet_pieces(model_name: str):
    import jax
    from bigdl_tpu import models, nn
    from bigdl_tpu.optim import SGD
    builders = {
        "inception_v1": lambda: models.Inception_v1_NoAuxClassifier(1000),
        # the BN-Inception profile (reference Inception_v2.scala:25-103) —
        # the architecture-level lever past v1's bandwidth ceiling
        # (docs/PERF.md): BN after every conv, 3x3 factorized 5x5s.
        # NoAux variant for the same single-head profile as the headline
        "inception_v2": lambda: models.Inception_v2_NoAuxClassifier(1000),
        "resnet50": lambda: models.ResNet(
            1000, {"depth": 50, "dataset": "imagenet"}),
        "vgg16": lambda: models.Vgg_16(1000),
    }
    model = builders[model_name]()
    model.materialize(jax.random.PRNGKey(0))
    model.training()
    criterion = nn.ClassNLLCriterion()
    optim = SGD(learning_rate=0.0898, momentum=0.9)
    params, mstate = model.params, model.state
    opt_state = optim.init_state(params)

    def train_step(params, mstate, opt_state, rng, data, labels):
        def loss_fn(p):
            y, new_state = model.apply(p, mstate, data, training=True,
                                       rng=rng)
            return criterion.apply(y, labels), new_state

        (loss, new_mstate), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_params, new_opt_state = optim.update(grads, params, opt_state)
        return new_params, new_mstate, new_opt_state, loss

    return model, params, mstate, opt_state, train_step


def bench_convnet_synthetic(model_name: str, batch: int = BATCH,
                            iters: int = ITERS, headline: bool = False):
    import jax
    import jax.numpy as jnp
    _set_bf16_policy()
    model, params, mstate, opt_state, train_step = _convnet_pieces(
        model_name)
    jit_step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    rng = jax.random.PRNGKey(0)
    host = np.random.default_rng(0)
    data = jnp.asarray(host.standard_normal((batch, 3, 224, 224),
                                            np.float32))
    labels = jnp.asarray(host.integers(1, 1001, size=(batch,)))  # 1-based

    # AOT-compile once; the executable serves both XLA's FLOP count and
    # the timed loop (avoids any chance of a second trace/compile)
    compiled = jit_step.lower(params, mstate, opt_state, rng, data,
                              labels).compile()
    cost = _cost_dict(compiled.cost_analysis())
    step_flops = float(cost.get("flops", 0.0)) if cost else 0.0
    _record_compile_telemetry(f"bench_{model_name}_train_step", compiled)

    for _ in range(WARMUP):
        rng, k = jax.random.split(rng)
        params, mstate, opt_state, loss = compiled(params, mstate,
                                                   opt_state, k, data,
                                                   labels)
    float(loss)  # block_until_ready is a no-op through the axon tunnel

    t0 = time.perf_counter()
    for _ in range(iters):
        rng, k = jax.random.split(rng)
        params, mstate, opt_state, loss = compiled(params, mstate,
                                                   opt_state, k, data,
                                                   labels)
    float(loss)  # force a real device sync before stopping the clock
    dt = time.perf_counter() - t0

    value = batch * iters / dt
    achieved_tflops = step_flops * iters / dt / 1e12
    peak = _chip_peak_tflops()
    out = {
        "metric": f"{model_name}_train_images_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        "achieved_tflops": round(achieved_tflops, 1),
    }
    if headline:
        out["metric"] = "inception_v1_train_images_per_sec_per_chip"
        # The reference publishes no quantitative number; 150 img/s is a
        # documented K80-class stand-in (see module docstring).
        out["vs_baseline"] = round(value / BASELINE_IMG_PER_SEC, 3)
        out["baseline_is_standin"] = True
    if peak:
        out["mfu"] = round(achieved_tflops / peak, 3)
        out["chip_peak_tflops_bf16"] = peak
    return out


# headline synthetic run shared by the headline and train_mfu rows (the
# row fns are what tests monkeypatch; this cache is what makes requesting
# both cost one training run)
_headline_cache = None


def _headline_row() -> dict:
    global _headline_cache
    if _headline_cache is None:
        _headline_cache = bench_convnet_synthetic("inception_v1",
                                                  headline=True)
    return dict(_headline_cache)


def bench_train_mfu():
    """Training MFU as a first-class gated metric (ISSUE 7): achieved
    model FLOP utilization of the headline Inception-v1 synthetic train
    step against the chip's bf16 peak. Shares the headline row's run."""
    row = _headline_row()
    peak = row.get("chip_peak_tflops_bf16")
    return {
        "metric": "train_mfu",
        "value": row.get("mfu", 0.0) if peak else 0.0,
        "unit": "fraction of bf16 peak",
        "images_per_sec_per_chip": row.get("value"),
        "achieved_tflops": row.get("achieved_tflops"),
        "chip_peak_tflops_bf16": peak,
        "peak_known": bool(peak),
    }


# cold-start probe geometries: model -> (input shape, classes). The
# headline Inception geometry is the bench workload; lenet5 is the
# fast geometry the contract tests exercise end to end.
_COLD_START_GEOMETRIES = {
    "inception_v1": ((3, 224, 224), 1000),
    "lenet5": ((1, 28, 28), 10),
}


def _cold_start_probe_main(cache_dir: str, model_name: str,
                           batch: int = 2) -> None:
    """--cold-start-probe subprocess entry: build the train step through
    the AOT-cache pipeline (tuning/aot_cache.py), run ONE step, and emit
    the phase timings. First run against an empty ``cache_dir`` pays the
    XLA compile; a second process against the same dir loads the
    serialized executable instead."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.tuning.aot_cache import AOTCache, StepCompiler
    if jax.default_backend() == "tpu":
        # the bench policy. On CPU, bf16 EMULATION makes the one
        # executed step dominate both phases and mask the
        # construction-time difference being measured — f32 (the
        # default policy) keeps the probe about compile vs load there
        _set_bf16_policy()
    t0 = time.perf_counter()
    shape, classes = _COLD_START_GEOMETRIES[model_name]
    if model_name == "lenet5":
        from bigdl_tpu import models, nn
        from bigdl_tpu.optim import SGD
        model = models.LeNet5(classes)
        model.materialize(jax.random.PRNGKey(0))
        model.training()
        criterion = nn.ClassNLLCriterion()
        optim = SGD(learning_rate=0.0898, momentum=0.9)
        params, mstate = model.params, model.state
        opt_state = optim.init_state(params)

        def train_step(params, mstate, opt_state, rng, data, labels):
            def loss_fn(p):
                y, st = model.apply(p, mstate, data, training=True,
                                    rng=rng)
                return criterion.apply(y, labels), st
            (loss, st), g = jax.value_and_grad(loss_fn,
                                               has_aux=True)(params)
            p2, o2 = optim.update(g, params, opt_state)
            return p2, st, o2, loss
    else:
        _, params, mstate, opt_state, train_step = _convnet_pieces(
            model_name)
    host = np.random.default_rng(0)
    data = jnp.asarray(host.standard_normal((batch,) + shape,
                                            np.float32))
    labels = jnp.asarray(host.integers(1, classes + 1, size=(batch,)))
    rng = jax.random.PRNGKey(0)
    setup_s = time.perf_counter() - t0

    cache = AOTCache(cache_dir)
    pipeline = StepCompiler(
        jax.jit(train_step, donate_argnums=(0, 1, 2)),
        name="cold_start_probe", cache=cache, donate_argnums=(0, 1, 2),
        extra=f"bench cold-start probe v1 {model_name} b{batch}")
    # start-to-first-step for the phase the cache controls: step
    # construction (lower+compile on a cold dir, deserialize on a warm
    # one) plus the first executed step, host-synced
    t1 = time.perf_counter()
    args = (params, mstate, opt_state, rng, data, labels)
    compiled, _ = pipeline.get((data.shape, labels.shape), args)
    params, mstate, opt_state, loss = compiled(*args)
    loss_v = float(jax.device_get(loss))
    first_step_s = time.perf_counter() - t1
    _emit({"first_step_s": first_step_s, "setup_s": setup_s,
           "loss": loss_v, "cache_hits": cache.hits,
           "cache_misses": cache.misses})


def bench_compile_cold_start(model: str = "inception_v1",
                             batch: int = 2,
                             cache_dir: str | None = None):
    """Worker start-to-first-step with a cold vs warmed AOT executable
    cache (ISSUE 8): the same probe workload runs in two fresh
    subprocesses sharing one cache directory — the first compiles and
    serializes, the second deserializes. ``value`` is the speedup of
    the phase the cache controls (step construction + first step);
    model/data setup time is reported alongside so the whole-process
    ratio stays honest. The probe batch is small so the one EXECUTED
    step does not mask the construction-time difference on slow
    backends. Children run on the CPU backend (like the wire probe —
    the parent may hold the TPU), which is the conservative side: TPU
    compiles are longer, deserializes are not."""
    import subprocess
    import tempfile
    cache_dir = cache_dir or tempfile.mkdtemp(
        prefix="bigdl_tpu_aot_bench_")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = {}
    for phase in ("cold", "warm"):
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--cold-start-probe", cache_dir,
             "--cold-start-model", model,
             "--cold-start-batch", str(batch)],
            capture_output=True, text=True, timeout=1200, env=env)
        payload = None
        for line in p.stdout.splitlines():
            if line.startswith("{"):
                payload = json.loads(line)
        if payload is None:
            tail = (p.stderr or "").strip().splitlines()[-3:]
            raise RuntimeError(
                f"cold-start {phase} probe rc={p.returncode}: "
                + (" | ".join(tail) or "no output"))
        out[phase] = payload
    cold, warm = out["cold"], out["warm"]
    ratio = cold["first_step_s"] / max(warm["first_step_s"], 1e-9)
    wall_cold = cold["setup_s"] + cold["first_step_s"]
    wall_warm = warm["setup_s"] + warm["first_step_s"]
    return {
        "metric": "compile_cold_start",
        "value": round(ratio, 2),
        "unit": "x (cold / warm start-to-first-step)",
        "cold_first_step_s": round(cold["first_step_s"], 3),
        "warm_first_step_s": round(warm["first_step_s"], 3),
        "setup_s": round(warm["setup_s"], 3),
        "wall_ratio_incl_setup": round(wall_cold /
                                       max(wall_warm, 1e-9), 2),
        "warm_cache_hits": warm["cache_hits"],
        "warm_cache_misses": warm["cache_misses"],
        "loss_bit_identical": cold["loss"] == warm["loss"],
        "probe_model": model,
        "cache_dir": cache_dir,
    }


def _elastic_probe_dataset():
    """Shared trainer/resume dataset for the elastic probes: the tiny
    XOR geometry — steps are milliseconds, so the parent's SIGKILL
    lands mid-run and the resume cost measured is the elastic machinery
    (load + redistribute + step construction), not the model."""
    from bigdl_tpu.dataset import Sample, SampleToBatch, array
    rs = np.random.RandomState(0)
    x = rs.rand(128, 2).astype(np.float32)
    y = ((x[:, 0] > 0.5) ^ (x[:, 1] > 0.5)).astype(np.int64) + 1
    return array([Sample(x[i], y[i]) for i in range(128)],
                 num_shards=1) >> SampleToBatch(16, drop_remainder=True)


def _elastic_model_optim():
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    model = nn.Sequential(nn.Linear(2, 16), nn.Tanh(), nn.Linear(16, 2),
                          nn.LogSoftMax())
    return model, optim.SGD(learning_rate=0.3, momentum=0.9)


def _elastic_train_probe_main(ckpt_dir: str) -> None:
    """--elastic-train-probe subprocess entry: a distributed training
    run checkpointing asynchronously every 8 iterations into
    ``ckpt_dir``. It never finishes on its own — the parent SIGKILLs it
    once a complete manifest lands, the same failure the elastic
    subsystem exists to absorb."""
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.parallel import Engine
    from bigdl_tpu.utils.random import RandomGenerator

    RandomGenerator.set_seed(5)
    Engine.init()
    model, method = _elastic_model_optim()
    o = optim.Optimizer(model=model, dataset=_elastic_probe_dataset(),
                        criterion=nn.ClassNLLCriterion())
    o.set_optim_method(method)
    o.set_checkpoint(ckpt_dir, optim.several_iteration(8))
    o.set_end_when(optim.max_iteration(1_000_000))
    o.optimize()


def _elastic_resume_probe_main(ckpt_dir: str, cache_dir: str) -> None:
    """--elastic-resume-probe subprocess entry: time kill-to-first-step
    on a RESIZED mesh (the parent forces a different virtual device
    count): load the latest manifest-complete snapshot, redistribute
    onto this mesh, and run ONE training step through the persistent
    AOT executable cache."""
    import logging

    import jax

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu import elastic
    from bigdl_tpu.parallel import Engine
    from bigdl_tpu.utils.random import RandomGenerator

    RandomGenerator.set_seed(5)
    t0 = time.perf_counter()
    model, state, man = elastic.load_checkpoint(ckpt_dir)
    load_s = time.perf_counter() - t0
    Engine.init()
    _, method = _elastic_model_optim()
    o = optim.Optimizer(model=model, dataset=_elastic_probe_dataset(),
                        criterion=nn.ClassNLLCriterion())
    o.set_optim_method(method)
    o.set_state(state)
    o.set_aot_cache(cache_dir)
    resumed_neval = int(man["neval"])
    o.set_end_when(lambda s: s["neval"] > resumed_neval + 1)
    losses = []

    class _Rec(logging.Handler):
        def emit(self, record):
            msg = record.getMessage()
            if "loss is" in msg:
                losses.append(float(
                    msg.split("loss is ")[1].split(",")[0]))

    lg = logging.getLogger("bigdl_tpu.optim")
    lg.addHandler(_Rec())
    lg.setLevel(logging.INFO)
    t1 = time.perf_counter()
    o.optimize()
    first_step_s = time.perf_counter() - t1
    cache = o._aot_cache()
    _emit({"load_s": load_s, "first_step_s": first_step_s,
           "resume_to_first_step_s": load_s + first_step_s,
           "resumed_neval": resumed_neval,
           "loss": losses[-1] if losses else None,
           "cache_hits": cache.hits, "cache_misses": cache.misses,
           "mesh_devices": jax.device_count()})


def bench_elastic_resume_secs(train_devices: int = 8,
                              resume_devices: int = 4,
                              ckpt_dir: str | None = None,
                              timeout_s: float = 300.0):
    """Elastic restart latency (ISSUE 14): SIGKILL a checkpointing
    trainer mid-run, then resume on a RESIZED mesh from the latest
    manifest-complete snapshot. Two resume subprocesses share one AOT
    cache directory: the first pays the step compile (first restart of
    a geometry), the second deserializes (the steady-state fleet
    restart). ``value`` is the warm kill-to-first-resumed-step wall
    time in seconds — the window of lost work a preemption costs beyond
    the steps since the last checkpoint. Children run on the CPU
    backend (the parent may hold the TPU); mesh sizes are virtual
    device counts."""
    import subprocess
    import tempfile

    from bigdl_tpu.elastic import latest_checkpoint
    ckpt_dir = ckpt_dir or tempfile.mkdtemp(
        prefix="bigdl_tpu_elastic_bench_")
    cache_dir = tempfile.mkdtemp(prefix="bigdl_tpu_elastic_aot_")
    env_train = dict(
        os.environ, JAX_PLATFORMS="cpu",
        XLA_FLAGS=_xla_flags_with_device_count(int(train_devices)))
    p = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--elastic-train-probe", ckpt_dir],
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
        env=env_train)
    try:
        deadline = time.monotonic() + timeout_s
        man = None
        while time.monotonic() < deadline:
            man = latest_checkpoint(ckpt_dir)
            if man is not None:
                break
            if p.poll() is not None:
                tail = (p.stderr.read() or "").strip().splitlines()[-3:]
                raise RuntimeError(
                    f"elastic train probe exited rc={p.returncode} "
                    "before writing a checkpoint: "
                    + (" | ".join(tail) or "no output"))
            time.sleep(0.2)
        if man is None:
            raise RuntimeError("elastic train probe wrote no checkpoint "
                               f"within {timeout_s}s")
    finally:
        p.kill()
        p.wait(timeout=30)
    env_resume = dict(
        os.environ, JAX_PLATFORMS="cpu",
        XLA_FLAGS=_xla_flags_with_device_count(int(resume_devices)))
    out = {}
    for phase in ("cold", "warm"):
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--elastic-resume-probe", ckpt_dir,
             "--elastic-resume-cache", cache_dir],
            capture_output=True, text=True, timeout=1200, env=env_resume)
        payload = None
        for line in r.stdout.splitlines():
            if line.startswith("{"):
                payload = json.loads(line)
        if payload is None:
            tail = (r.stderr or "").strip().splitlines()[-3:]
            raise RuntimeError(
                f"elastic {phase} resume probe rc={r.returncode}: "
                + (" | ".join(tail) or "no output"))
        out[phase] = payload
    cold, warm = out["cold"], out["warm"]
    return {
        "metric": "elastic_resume_secs",
        "value": round(warm["resume_to_first_step_s"], 3),
        "unit": "s (kill -> first resumed step, warm AOT cache, "
                f"{train_devices}->{resume_devices} mesh)",
        "cold_resume_s": round(cold["resume_to_first_step_s"], 3),
        "warm_resume_s": round(warm["resume_to_first_step_s"], 3),
        "load_s": round(warm["load_s"], 3),
        "resumed_neval": warm["resumed_neval"],
        "warm_cache_hits": warm["cache_hits"],
        "warm_cache_misses": warm["cache_misses"],
        "loss_bit_identical": cold["loss"] == warm["loss"],
        "ckpt_dir": ckpt_dir,
    }


def bench_train_peak_hbm(**geometry):
    """Static peak-HBM accounting for the transformer train step across
    remat policies at FIXED effective batch (ISSUE 10 — the tentpole's
    measured receipt): runs ``optim.remat.train_memory_probe`` in a CPU
    SUBPROCESS (same pattern as the wire/HBM probes — static analysis
    only, the parent's TPU backend is never touched). Per policy the
    probe counts the saved-residual bytes the backward holds (abstract
    ``jax.vjp`` partial-eval — backend-independent; the CPU executable's
    buffer assignment CSEs remat away, so ``memory_analysis`` alone
    cannot show it) plus the policy-invariant persistent state, and
    compiles the k=1 vs k=N gradient-accumulation steps to show the
    scan bounding activation liveness in the executable itself.
    ``value`` is the peak-HBM reduction of ``nothing_saveable`` vs
    ``none``."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--train-hbm-probe",
         "--train-hbm-geometry", json.dumps(geometry)],
        capture_output=True, text=True, timeout=900, env=env)
    payload = None
    for line in out.stdout.splitlines():
        if line.startswith("{"):
            payload = json.loads(line)
    if payload is None:
        tail = (out.stderr or "").strip().splitlines()[-2:]
        raise RuntimeError(
            f"train-hbm probe subprocess rc={out.returncode}: "
            + (" | ".join(tail) or "no output"))
    peak = payload["peak_hbm_bytes"]
    resid = payload["saved_residual_bytes"]
    row = {
        "metric": "train_peak_hbm_bytes",
        "value": round(payload["reduction"], 2),
        "unit": "x (peak HBM none / nothing_saveable, fixed effective "
                "batch)",
        "persistent_bytes": payload["persistent_bytes"],
        "geometry": payload["geometry"],
    }
    for pol in sorted(peak):
        row[f"peak_hbm_bytes_{pol}"] = peak[pol]
        row[f"saved_residual_bytes_{pol}"] = resid[pol]
    for pol, r in sorted(payload.get("residual_reduction", {}).items()):
        if r is not None:
            row[f"residual_reduction_{pol}"] = round(r, 2)
    if payload.get("accum_temp_reduction") is not None:
        row["accum_k"] = payload.get("accum_k")
        row["accum_temp_reduction"] = round(
            payload["accum_temp_reduction"], 2)
        row["accum_executable_temp_bytes"] = {
            k: v.get("temp_bytes")
            for k, v in payload["accum_executable_stats"].items()}
    return row


def _train_hbm_probe_main(geometry_json: str):
    """--train-hbm-probe subprocess entry: run the static accounting on
    the CPU backend and emit the JSON payload."""
    from bigdl_tpu.optim.remat import train_memory_probe
    _emit(train_memory_probe(**json.loads(geometry_json or "{}")))


def _xla_flags_with_device_count(n: int) -> str:
    """This process's XLA_FLAGS with the virtual-device count forced to
    ``n`` (replacing any inherited setting)."""
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    return " ".join(flags)


def bench_multichip_scaling(device_counts=(1, 2, 4, 8),
                            batch_per_chip: int = 64, iters: int = 8):
    """Scaling curve over mesh sizes (ROADMAP item 5 remaining): the
    same data-parallel train step at fixed PER-CHIP batch on 1/2/4/8
    virtual CPU devices, one fresh subprocess per mesh size. ``value``
    is the per-chip throughput at the largest mesh relative to the
    1-device run (ideal weak scaling = 1.0). HONESTY NOTE: the CPU
    mesh emulates every chip on one host, so per-chip throughput falls
    roughly as 1/N here — the row exists to pin the wiring and the
    collective overhead TREND; on real ICI the same probe reads the
    scaling headroom."""
    import subprocess
    results = {}
    for n in device_counts:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS=_xla_flags_with_device_count(int(n)))
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--scaling-probe", str(int(n)),
             "--scaling-batch-per-chip", str(int(batch_per_chip)),
             "--scaling-iters", str(int(iters))],
            capture_output=True, text=True, timeout=600, env=env)
        payload = None
        for line in p.stdout.splitlines():
            if line.startswith("{"):
                payload = json.loads(line)
        if payload is None:
            tail = (p.stderr or "").strip().splitlines()[-2:]
            raise RuntimeError(
                f"scaling probe (n={n}) rc={p.returncode}: "
                + (" | ".join(tail) or "no output"))
        results[int(n)] = payload["images_per_sec"]
    counts = sorted(results)
    per_chip = {n: results[n] / n for n in counts}
    base = per_chip[counts[0]]
    ratio = {n: per_chip[n] / base for n in counts}
    top = counts[-1]
    return {
        "metric": "multichip_scaling",
        "value": round(ratio[top], 4),
        "unit": f"per-chip throughput ratio vs ideal at {top} devices",
        "device_counts": counts,
        "images_per_sec": {str(n): round(results[n], 1) for n in counts},
        "per_chip_img_per_sec": {str(n): round(per_chip[n], 1)
                                 for n in counts},
        "ratio_vs_ideal": {str(n): round(ratio[n], 4) for n in counts},
        "batch_per_chip": batch_per_chip,
        "cpu_mesh_emulated": True,
    }


def _scaling_probe_main(n: int, batch_per_chip: int, iters: int):
    """--scaling-probe subprocess entry: time the data-parallel train
    step on this process's ``n``-device CPU mesh and emit the rate."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.parallel.engine import Engine, data_sharding, \
        replicated

    mesh = Engine.init()
    assert int(np.prod(mesh.devices.shape)) == n, \
        f"mesh has {mesh.devices.shape} devices, wanted {n}"
    rs = np.random.RandomState(0)
    d_in, d_hidden = 256, 512
    params = {"w1": jnp.asarray(rs.randn(d_in, d_hidden)
                                .astype(np.float32) * 0.05),
              "b1": jnp.zeros((d_hidden,), jnp.float32),
              "w2": jnp.asarray(rs.randn(d_hidden, d_in)
                                .astype(np.float32) * 0.05),
              "b2": jnp.zeros((d_in,), jnp.float32)}
    batch = batch_per_chip * n
    data = jnp.asarray(rs.rand(batch, d_in).astype(np.float32))
    labels = jnp.asarray(rs.rand(batch, d_in).astype(np.float32))
    repl, shard = replicated(mesh), data_sharding(mesh)
    data = jax.device_put(data, shard)
    labels = jax.device_put(labels, shard)
    params = jax.device_put(params, repl)

    def step(p, x, y):
        def loss_fn(pp):
            h = jnp.tanh(x @ pp["w1"] + pp["b1"])
            o = h @ pp["w2"] + pp["b2"]
            # mean over the GLOBAL batch: the induced gradient
            # allreduce is the collective whose overhead the curve
            # measures
            return jnp.mean((o - y) ** 2)

        g = jax.grad(loss_fn)(p)
        return jax.tree.map(lambda pp, gg: pp - 0.1 * gg, p, g)

    jit_step = jax.jit(step, donate_argnums=(0,),
                       in_shardings=(repl, shard, shard),
                       out_shardings=repl)
    compiled = jit_step.lower(params, data, labels).compile()
    for _ in range(2):
        params = compiled(params, data, labels)
    jax.device_get(jax.tree.leaves(params)[0])   # real sync
    t0 = time.perf_counter()
    for _ in range(iters):
        params = compiled(params, data, labels)
    jax.device_get(jax.tree.leaves(params)[0])
    dt = time.perf_counter() - t0
    _emit({"devices": n, "images_per_sec": batch * iters / dt})


def _pipeline_bubble_geometry() -> dict:
    # tiny fixed (S, M) geometry: big enough that the modeled bubbles
    # separate (gpipe 3/11 vs interleaved-1F1B 3/19), small enough that
    # the probe's jitted units compile in seconds on one CPU core
    return dict(n_stages=4, num_microbatches=8, virtual_stages=2,
                d_model=16, mb_rows=4, layers_per_stage=2, reps=5)


def bench_pipeline_bubble(**geometry):
    """Measured pipeline-schedule bubble fractions (ISSUE 11): real
    per-stage forward/backward span timings (jitted chunk units on the
    CPU backend, median of reps) composed through each schedule's exact
    dependency graph (``parallel.pipeline.measure_pipeline_bubble``),
    vs the extended ``pipeline_schedule_stats`` model. Runs in a CPU
    SUBPROCESS like the other static probes. ``value`` is the measured
    interleaved-1F1B bubble fraction — the production schedule — which
    must land strictly below GPipe's at the same (S, M) and within
    tolerance of the model (test_bench_contract.py pins both). Lower is
    better; the gate knows."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    geo = dict(_pipeline_bubble_geometry(), **geometry)
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--pipeline-bubble-probe",
         "--pipeline-bubble-geometry", json.dumps(geo)],
        capture_output=True, text=True, timeout=600, env=env)
    payload = None
    for line in out.stdout.splitlines():
        if line.startswith("{"):
            payload = json.loads(line)
    if payload is None:
        tail = (out.stderr or "").strip().splitlines()[-2:]
        raise RuntimeError(
            f"pipeline-bubble probe subprocess rc={out.returncode}: "
            + (" | ".join(tail) or "no output"))
    sch = payload["schedules"]
    row = {
        "metric": "pipeline_bubble_fraction",
        "value": round(
            sch["interleaved_1f1b"]["measured_bubble_fraction"], 4),
        "unit": "measured interleaved-1F1B bubble fraction "
                "(fill-drain idle share; lower is better)",
        "n_stages": payload["n_stages"],
        "num_microbatches": payload["num_microbatches"],
        "virtual_stages": payload["virtual_stages"],
        "geometry": payload["geometry"],
    }
    for name, r in sch.items():
        row[f"measured_{name}"] = round(r["measured_bubble_fraction"], 4)
        row[f"modeled_{name}"] = round(r["modeled_bubble_fraction"], 4)
    row["fwd_span_us"] = round(
        sch["1f1b"]["fwd_span_s"] * 1e6, 1)
    row["bwd_span_us"] = round(
        sch["1f1b"]["bwd_span_s"] * 1e6, 1)
    return row


def _pipeline_bubble_probe_main(geometry_json: str):
    """--pipeline-bubble-probe subprocess entry: time the per-stage
    units on the CPU backend and emit the per-schedule measured/modeled
    bubble JSON."""
    from bigdl_tpu.parallel.pipeline import measure_pipeline_bubble
    _emit(measure_pipeline_bubble(**json.loads(geometry_json or "{}")))


def _wire_probe_geometry() -> dict:
    return dict(d_in=256, d_hidden=1024, layers=3, batch=512,
                bucket_kb=512)


def bench_collective_wire_bytes():
    """Static per-step collective wire accounting for the sharded-update
    step at fp32 vs bf16 vs int8 wire codecs (ISSUE 7): the compiled
    HLO's collective payloads under a ring schedule. Runs the lowering
    in a SUBPROCESS on the 8-virtual-CPU-device mesh — the accounting is
    static, backend-independent, and must not disturb (or hang on) this
    process's TPU backend."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8")
               .strip())
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--wire-probe"],
        capture_output=True, text=True, timeout=600, env=env)
    payload = None
    for line in out.stdout.splitlines():
        if line.startswith("{"):
            payload = json.loads(line)
    if payload is None:
        tail = (out.stderr or "").strip().splitlines()[-2:]
        raise RuntimeError(
            f"wire probe subprocess rc={out.returncode}: "
            + (" | ".join(tail) or "no output"))
    wb = payload["wire_bytes_per_chip"]
    red = payload["reduction_vs_fp32"]
    return {
        "metric": "collective_wire_bytes_per_step",
        "value": wb["int8"],
        "unit": "bytes/chip/step (int8 wire)",
        "wire_bytes_per_chip_fp32": wb["fp32"],
        "wire_bytes_per_chip_bf16": wb["bf16"],
        "wire_bytes_per_chip_int8": wb["int8"],
        "reduction_bf16_vs_fp32": round(red["bf16"], 3),
        "reduction_int8_vs_fp32": round(red["int8"], 3),
        "geometry": payload["geometry"],
        "n_shards": payload["n_shards"],
    }


def _wire_probe_main():
    """--wire-probe subprocess entry: lower the explicit sharded step on
    the virtual CPU mesh at each codec and emit the accounting JSON."""
    from bigdl_tpu.optim.sharded_update import wire_bytes_probe
    from bigdl_tpu.parallel import Engine
    Engine.init()
    _emit(wire_bytes_probe(**_wire_probe_geometry()))


def _ensure_shards() -> str:
    """Synthetic ImageNet-like JPEG shards (photo-statistics content,
    shorter side 256 like the reference's seqfile generator), built once
    and cached on disk."""
    import io

    from PIL import Image

    from bigdl_tpu.dataset.recordio import RecordWriter, SHARD_SUFFIX
    marker = os.path.join(SHARD_DIR, "done")
    if os.path.exists(marker):
        return SHARD_DIR
    os.makedirs(SHARD_DIR, exist_ok=True)
    rs = np.random.default_rng(0)
    num_shards = 4
    writers = [RecordWriter(os.path.join(
        SHARD_DIR, f"shard-{i:05d}-of-{num_shards:05d}{SHARD_SUFFIX}"))
        for i in range(num_shards)]
    for i in range(SHARD_IMAGES):
        h = 256
        w = int(rs.integers(256, 341))
        if rs.random() < 0.5:
            h, w = w, h
        base = rs.integers(0, 256, size=(h // 8, w // 8, 3), dtype=np.uint8)
        img = np.asarray(Image.fromarray(base).resize((w, h),
                                                      Image.BILINEAR))
        img = np.clip(img + rs.normal(0, 10, img.shape), 0,
                      255).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, "JPEG", quality=90)
        writers[i % num_shards].write(buf.getvalue(),
                                      float(i % 1000 + 1))
    for w_ in writers:
        w_.close()
    with open(marker, "w") as f:
        f.write("ok")
    return SHARD_DIR


def host_pipeline_probe(cache_gb: float) -> float:
    """Host-only pipeline rate (shards -> u8 batches): run in a process
    that has issued NO device work. Prints/returns img/s."""
    from bigdl_tpu.dataset.image.native_batch import NativeBRecToBatch
    from bigdl_tpu.dataset.recordio import RecordShardDataSet
    from bigdl_tpu.models.inception.train import MEAN_RGB, STD_RGB
    from bigdl_tpu.utils.random import RandomGenerator

    shards = _ensure_shards()
    RandomGenerator.seed_thread(0)
    ds = RecordShardDataSet(shards)
    batcher = NativeBRecToBatch(
        REAL_BATCH, 224, 224, train=True, mean_rgb=MEAN_RGB,
        std_rgb=STD_RGB, device_normalize=True,
        cache_bytes=int(cache_gb * 1e9))
    it = batcher(ds.data(train=True))
    warm = (SHARD_IMAGES // REAL_BATCH) if cache_gb > 0 else 2
    for _ in range(warm):
        next(it)
    t0 = time.perf_counter()
    for _ in range(8):
        next(it)
    return REAL_BATCH * 8 / (time.perf_counter() - t0)


def _host_pipeline_probe_subprocess(cache_gb: float) -> float:
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--host-probe", str(cache_gb)],
            capture_output=True, text=True, timeout=600, env=env)
        for line in out.stdout.splitlines():
            if line.startswith("{"):
                return float(json.loads(line)["host_pipeline_img_per_sec"])
    except Exception as e:
        print(f"host probe subprocess failed: {e}", file=sys.stderr)
    return float("nan")


def bench_real_data(cache_gb: float = 0.0, timed_steps: int = 16):
    """End-to-end Inception train rate with JPEG bytes in the loop:
    .brec shards -> native u8 decode (crop-window, uint8 HWC) ->
    DevicePrefetcher -> in-step normalize on device (VERDICT r3 #1).

    Reports the end-to-end rate AND its decomposition. In this dev
    environment the TPU sits behind the axon tunnel, whose host->device
    transfers degrade to ~25 MB/s once any computation has run
    (measured; docs/PERF.md round 4) — the end-to-end number here is
    tunnel-transfer-bound, NOT pipeline-bound. ``colocated_bound`` =
    min(host pipeline, device step) is the rate on a real TPU host,
    where the 285 MB/s this pipeline needs is ~2% of PCIe."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.dataset.image.native_batch import NativeBRecToBatch
    from bigdl_tpu.dataset.recordio import (DevicePrefetcher,
                                            RecordShardDataSet)
    from bigdl_tpu.models.inception.train import MEAN_RGB, STD_RGB
    from bigdl_tpu.utils.random import RandomGenerator

    _set_bf16_policy()
    shards = _ensure_shards()
    RandomGenerator.seed_thread(0)
    ds = RecordShardDataSet(shards)
    batcher = NativeBRecToBatch(
        REAL_BATCH, 224, 224, train=True, mean_rgb=MEAN_RGB,
        std_rgb=STD_RGB, device_normalize=True,
        cache_bytes=int(cache_gb * 1e9))
    transform = batcher.device_transform()

    model, params, mstate, opt_state, base_step = _convnet_pieces(
        "inception_v1")

    def train_step(params, mstate, opt_state, rng, data, labels):
        return base_step(params, mstate, opt_state, rng, transform(data),
                         labels.astype(jnp.int32))

    jit_step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    rng = jax.random.PRNGKey(0)

    # -- component 1: host pipeline rate (decode -> u8 batch, no device),
    # measured in a FRESH subprocess: once this process has run device
    # work, the axon tunnel's polling threads consume ~half the single
    # host core and halve the in-process decode rate (measured; the
    # subprocess number is the true host capability a co-located
    # deployment would see)
    host_ips = _host_pipeline_probe_subprocess(cache_gb)
    steps_per_epoch = SHARD_IMAGES // REAL_BATCH
    host_it = batcher(ds.data(train=True))
    warm_batches = steps_per_epoch if cache_gb > 0 else 2
    for _ in range(warm_batches):        # cache mode: fill on pass 1
        host_batch = next(host_it)

    # -- component 2: device step rate on a resident u8 batch
    dev_data = jax.device_put(host_batch.data)
    dev_labels = jax.device_put(host_batch.labels)
    compiled = jit_step.lower(params, mstate, opt_state, rng, dev_data,
                              dev_labels).compile()
    for _ in range(3):
        rng, k = jax.random.split(rng)
        params, mstate, opt_state, loss = compiled(
            params, mstate, opt_state, k, dev_data, dev_labels)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(10):
        rng, k = jax.random.split(rng)
        params, mstate, opt_state, loss = compiled(
            params, mstate, opt_state, k, dev_data, dev_labels)
    float(loss)
    device_ips = REAL_BATCH * 10 / (time.perf_counter() - t0)

    # -- end to end (includes host->device transfer, tunnel-bound here)
    pipe = DevicePrefetcher()(host_it)
    for _ in range(2):
        b = next(pipe)
        rng, k = jax.random.split(rng)
        params, mstate, opt_state, loss = compiled(
            params, mstate, opt_state, k, b.data, b.labels)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(timed_steps):
        b = next(pipe)
        rng, k = jax.random.split(rng)
        params, mstate, opt_state, loss = compiled(
            params, mstate, opt_state, k, b.data, b.labels)
    float(loss)
    dt = time.perf_counter() - t0
    value = REAL_BATCH * timed_steps / dt
    name = ("inception_v1_train_real_jpeg_cached"
            if cache_gb > 0 else "inception_v1_train_real_jpeg")
    import math
    have_host = not math.isnan(host_ips)
    bound = min(host_ips, device_ips) if have_host else None
    return {
        "metric": f"{name}_images_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        "host_pipeline_img_per_sec": round(host_ips, 1) if have_host
        else None,
        "device_step_img_per_sec": round(device_ips, 1),
        "colocated_bound_img_per_sec": round(bound, 1) if have_host
        else None,
        "transfer_limited_by_tunnel": bool(value < 0.8 * bound)
        if have_host else None,
        "host_decode": "ram-cache" if cache_gb > 0 else "jpeg",
        "host_cores": os.cpu_count(),
    }


def bench_transformer_lm(b: int = 4, s: int = 2048, vocab: int = 32768,
                         d_model: int = 1024, layers: int = 12,
                         iters: int = 40):
    """LM train-step tokens/s + MFU at the docs/PERF.md flagship geometry
    (GPT-2-medium width), fused-CE head + flash attention.

    MFU uses ANALYTIC step FLOPs (6 * matmul-params * tokens + attention)
    — XLA's cost analysis cannot see inside the Pallas flash-attention
    and fused-CE custom calls, so its count is only a lower bound
    (reported as ``xla_counted_tflops``; round 3's 55.6% flagship figure
    was this undercount). ``mfu`` counts attention at the full S^2
    matrices (the PaLM-convention number most MFU figures quote);
    ``mfu_causal_attn`` counts the causal halves actually computed."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.optim import SGD

    _set_bf16_policy()
    model = TransformerLM(vocab, d_model=d_model, num_heads=d_model // 128,
                          num_layers=layers, max_len=s,
                          with_log_softmax=False)
    model.materialize(jax.random.PRNGKey(0))
    model.training()
    optim = SGD(learning_rate=0.01)
    params, mstate = model.params, model.state
    opt_state = optim.init_state(params)
    fused = jax.default_backend() == "tpu"
    head_idx = str(len(model.modules) - 1)
    crit = nn.CrossEntropyCriterion()

    def step(params, mstate, opt_state, data, labels):
        def loss_fn(p):
            if fused:
                from bigdl_tpu.ops.pallas.fused_ce import \
                    linear_cross_entropy
                x, new_mstate = data, dict(mstate)
                for i, m in enumerate(model.modules[:-1]):
                    x, new_mstate[str(i)] = m.apply(
                        p[str(i)], mstate[str(i)], x, training=True)
                loss = linear_cross_entropy(
                    x.reshape(-1, x.shape[-1]),
                    p[head_idx]["weight"].astype(x.dtype),
                    p[head_idx].get("bias"), labels.reshape(-1))
                return loss, new_mstate
            y, st = model.apply(p, mstate, data, training=True)
            return crit.apply(y, labels), st

        (loss, s2), g = jax.value_and_grad(loss_fn, has_aux=True)(params)
        p2, o2 = optim.update(g, params, opt_state)
        return p2, s2, o2, loss

    host = np.random.default_rng(0)
    data = jnp.asarray(host.integers(1, vocab + 1, size=(b, s)))
    labels = jnp.asarray(host.integers(1, vocab + 1, size=(b, s)))
    c = jax.jit(step, donate_argnums=(0, 1, 2)).lower(
        params, mstate, opt_state, data, labels).compile()
    cost = _cost_dict(c.cost_analysis())
    xla_flops = float(cost.get("flops", 0.0)) if cost else 0.0
    _record_compile_telemetry("bench_transformer_lm_train_step", c)
    # analytic step FLOPs: matmul params = 2-D weight leaves minus the
    # embedding tables (lookups, not matmuls)
    p2d = sum(int(np.prod(l.shape))
              for l in jax.tree.leaves(params) if l.ndim == 2)
    p_matmul = p2d - vocab * d_model - s * d_model
    tokens = b * s
    dense_attn = 12 * layers * s * d_model * tokens
    flops_dense = 6 * p_matmul * tokens + dense_attn
    flops_causal = 6 * p_matmul * tokens + dense_attn // 2
    for _ in range(3):
        params, mstate, opt_state, loss = c(params, mstate, opt_state,
                                            data, labels)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, mstate, opt_state, loss = c(params, mstate, opt_state,
                                            data, labels)
    final = float(loss)
    dt = time.perf_counter() - t0
    if not np.isfinite(final):
        raise SystemExit(f"transformer bench diverged: loss={final}")
    peak = _chip_peak_tflops()
    out = {
        "metric": "transformer_lm_train_tokens_per_sec_per_chip",
        "value": round(b * s * iters / dt, 1),
        "unit": "tokens/sec/chip",
        "geometry": f"d{d_model} L{layers} B{b} S{s} V{vocab}",
        "achieved_tflops": round(flops_dense * iters / dt / 1e12, 1),
        "xla_counted_tflops": round(xla_flops * iters / dt / 1e12, 1),
    }
    if peak:
        out["mfu"] = round(flops_dense * iters / dt / 1e12 / peak, 3)
        out["mfu_causal_attn"] = round(
            flops_causal * iters / dt / 1e12 / peak, 3)
    return out


def bench_decode(b: int = 128, kv_heads: int | None = 1,
                 iters: int = 30):
    """KV-cache decode throughput: 27M LM, prompt 512, +128 greedy
    tokens. ``kv_heads=1`` is the multi-query config (docs/PERF.md round
    4: the cache was the decode bound; MQA runs 4.1x MHA)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.models.transformer.generate import (GenerationConfig,
                                                       generate)

    _set_bf16_policy()
    vocab, p_len, n_new = 8192, 512, 128
    model = TransformerLM(vocab, d_model=512, num_heads=4, num_layers=6,
                          max_len=p_len + n_new, with_log_softmax=False,
                          num_kv_heads=kv_heads)
    model.materialize(jax.random.PRNGKey(0))
    model.evaluate()
    host = np.random.default_rng(0)
    prompt = jnp.asarray(host.integers(1, vocab + 1, size=(b, p_len)))
    cfg = GenerationConfig(n_new)
    out = generate(model, prompt, cfg)          # compile + warm
    np.asarray(out)        # REAL sync (block_until_ready is a tunnel no-op)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = generate(model, prompt, cfg)
    int(np.asarray(out)[0, 0])                  # real sync
    dt = time.perf_counter() - t0
    return {
        "metric": "transformer_lm_decode_tokens_per_sec_per_chip",
        "value": round(b * n_new * iters / dt, 1),
        "unit": "tokens/sec/chip",
        "geometry": f"27M d512 L6 B{b} prompt{p_len} +{n_new} "
                    f"kv_heads={kv_heads or 4}",
    }


def bench_decode_ragged(b: int = 128, kv_heads: int | None = 1,
                        iters: int = 30):
    """Mixed-sequence-length serving decode (VERDICT r4 item 6): the same
    27M MQA geometry as ``bench_decode`` but with per-row prompt lengths
    drawn from [64, 512] through the ragged path
    (models/transformer/serving.py) — one compiled program, per-row
    positions/masks, no retrace across the length mix."""
    import jax

    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.models.transformer.generate import GenerationConfig
    from bigdl_tpu.models.transformer.serving import generate_ragged

    _set_bf16_policy()
    vocab, n_new = 8192, 128
    model = TransformerLM(vocab, d_model=512, num_heads=4, num_layers=6,
                          max_len=512 + n_new, with_log_softmax=False,
                          num_kv_heads=kv_heads)
    model.materialize(jax.random.PRNGKey(0))
    model.evaluate()
    host = np.random.default_rng(0)
    lengths = host.integers(64, 513, size=(b,)).astype(np.int32)
    prompts = [list(host.integers(1, vocab + 1, size=(n,)))
               for n in lengths]
    cfg = GenerationConfig(max_new_tokens=n_new, temperature=0.0)

    def run():
        return generate_ragged(model, prompts, cfg)

    np.asarray(run())      # compile + warm; REAL sync (tunnel no-op note)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run()
    int(np.asarray(out)[0, 0])                  # real sync
    dt = time.perf_counter() - t0
    return {
        "metric": "transformer_lm_ragged_decode_tokens_per_sec_per_chip",
        "value": round(b * n_new * iters / dt, 1),
        "unit": "tokens/sec/chip",
        "geometry": f"27M d512 L6 B{b} prompts 64..512 +{n_new} "
                    f"kv_heads={kv_heads or 4}",
        "mean_prompt_len": round(float(lengths.mean()), 1),
    }


def bench_decode_speculative(b: int = 32, iters: int = 10):
    """Speculative decoding with a measured acceptance rate (VERDICT r4
    item 6): 27M MQA target, 2-layer d128 draft, gamma=4. HONESTY NOTE:
    both models have random weights, so the draft's greedy choices rarely
    match the target's over an 8k vocab — the reported acceptance rate is
    a floor, and the tokens/s here is the COST of speculation at that
    floor. On trained models acceptance (and the speedup) is a property
    of the model pair, not the harness; the harness's exactness is pinned
    by tests/test_serving.py (spec output == target greedy, any draft)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.models.transformer.serving import speculative_generate

    _set_bf16_policy()
    vocab, n_new, gamma = 8192, 64, 4
    p_len = 128
    target = TransformerLM(vocab, d_model=512, num_heads=4, num_layers=6,
                           max_len=p_len + n_new + gamma + 1,
                           with_log_softmax=False, num_kv_heads=1)
    target.materialize(jax.random.PRNGKey(0))
    target.evaluate()
    draft = TransformerLM(vocab, d_model=128, num_heads=4, num_layers=2,
                          max_len=p_len + n_new + gamma + 1,
                          with_log_softmax=False, num_kv_heads=1)
    draft.materialize(jax.random.PRNGKey(1))
    draft.evaluate()
    host = np.random.default_rng(0)
    prompts = [list(host.integers(1, vocab + 1, size=(p_len,)))
               for _ in range(b)]
    out, stats = speculative_generate(target, draft, prompts,
                                      max_new_tokens=n_new, gamma=gamma)
    np.asarray(out)                             # compile + warm + sync
    t0 = time.perf_counter()
    for _ in range(iters):
        out, stats = speculative_generate(target, draft, prompts,
                                          max_new_tokens=n_new,
                                          gamma=gamma)
    int(np.asarray(out)[0, 0])                  # real sync
    dt = time.perf_counter() - t0
    return {
        "metric": "transformer_lm_speculative_decode_tokens_per_sec",
        "value": round(b * n_new * iters / dt, 1),
        "unit": "tokens/sec/chip",
        "geometry": f"target 27M d512 L6 MQA, draft d128 L2 MQA, B{b} "
                    f"prompt{p_len} +{n_new} gamma={gamma}",
        "acceptance_rate": round(stats["acceptance_rate"], 4),
        "accepted": stats["accepted"],
        "proposed": stats["proposed"],
        "rounds": stats["rounds"],
        "acceptance_is_floor": True,   # random weights; see docstring
    }


def bench_input_pipeline_overlap(iters: int = 12, batch: int = 64):
    """How much host-input latency the prefetch pipeline hides
    (ISSUE 5): run the same tiny training recipe at prefetch depth 0
    (synchronous input) and depth 2 (overlapped), and report the
    fraction of step wall time spent blocked in ``input wait`` for
    each. ``value`` is the overlap won (frac@0 - frac@2). A deliberate
    per-batch host transform gives the pipeline real work to hide, so
    the row is meaningful on any backend (CPU included)."""
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import Sample, SampleToBatch, Transformer, array
    from bigdl_tpu.utils.random import RandomGenerator

    class HostWork(Transformer):
        """Stand-in for decode/augment cost: a few ms of numpy per
        batch, comparable to a real decode stage."""

        def __call__(self, it):
            scratch = np.linspace(0.0, 1.0, 1 << 19, dtype=np.float32)
            for b in it:
                for _ in range(8):
                    scratch = np.tanh(scratch)
                yield b

    rs = np.random.RandomState(0)
    x = rs.rand(4 * batch, 64).astype(np.float32)
    y = rs.randint(1, 5, size=(4 * batch,)).astype(np.int64)
    samples = [Sample(x[i], y[i]) for i in range(len(x))]

    def run(depth: int) -> float:
        RandomGenerator.set_seed(0)
        ds = array(samples) >> SampleToBatch(batch) >> HostWork()
        # wide enough that the device step is real work to overlap with
        model = nn.Sequential(nn.Linear(64, 1024), nn.Tanh(),
                              nn.Linear(1024, 1024), nn.Tanh(),
                              nn.Linear(1024, 4), nn.LogSoftMax())
        o = optim.Optimizer(model=model, dataset=ds,
                            criterion=nn.ClassNLLCriterion())
        o.set_optim_method(optim.SGD(learning_rate=0.1))
        o.set_input_pipeline(depth=depth)
        o.set_end_when(optim.max_iteration(iters))
        o.optimize()
        # phase split from the loop's own honest metrics (input wait vs
        # device step, metrics.py), on medians: the one-off XLA compile
        # lands in step 1's device time and would swamp a sum at this
        # iteration count
        wait = o.metrics.stats("host input time")["p50"]
        dev = o.metrics.stats("device step time")["p50"]
        return wait / max(wait + dev, 1e-9)

    frac0 = run(0)
    frac2 = run(2)
    return {
        "metric": "input_pipeline_overlap",
        "value": round(max(frac0 - frac2, 0.0), 4),
        "unit": "fraction of step wall time",
        "input_wait_frac_depth0": round(frac0, 4),
        "input_wait_frac_depth2": round(frac2, 4),
        "iters": iters,
    }


def bench_input_pipeline_nhost(host_counts=(1, 2, 4), iters: int = 6,
                               batch: int = 32, chunk_records: int = 64):
    """The input_pipeline_overlap receipt at mesh scale (ISSUE 20): the
    same overlapped training recipe run as 1/2/4 parallel CPU "host"
    processes, each a shard of a ``DistributedShuffleDataSet`` over one
    shared chunked record store. ``value`` is the mean input-wait
    fraction at the LARGEST host count (lower is better); shard-local IO
    means it should stay flat as hosts scale — every host reads only its
    own chunks, so per-host input bandwidth does not shrink with N.

    Two hard receipts ride along and fail the row on violation:
    the reader open-accounting proves each host touched ONLY its pass-0
    assignment (pairwise-disjoint across hosts), and an in-process 4->2
    resize sub-drill proves the chunk-granular mid-epoch resume
    reconstructs the remaining stream bit-identically."""
    import subprocess
    import tempfile

    from bigdl_tpu.dataset.distributed import (chunk_assignment,
                                               chunk_record_order,
                                               redistribute_chunk_positions,
                                               DistributedShuffleDataSet)
    from bigdl_tpu.dataset.recordstore import (ChunkedRecordReader,
                                               write_sample_store)
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.utils.random import RandomGenerator

    # the probes seed 0; the parent-side assignment oracle and the
    # resize sub-drill must rotate from the same key
    RandomGenerator.set_seed(0)
    max_hosts = max(int(n) for n in host_counts)
    # size the store so each host's pulls (iters consumed + the depth-2
    # worker's bounded read-ahead) stay strictly inside pass 0 — the
    # shard-local receipt below pins opens against the PASS-0 assignment
    n_records = max_hosts * batch * (iters + 8)
    rs = np.random.RandomState(0)
    x = rs.rand(n_records, 64).astype(np.float32)
    y = rs.randint(1, 5, size=(n_records,)).astype(np.int64)
    tmp = tempfile.mkdtemp(prefix="bench_dataplane_")
    store = os.path.join(tmp, "train.bcs")
    write_sample_store(store, (Sample(x[i], y[i])
                               for i in range(n_records)),
                       chunk_records=chunk_records)
    n_chunks = ChunkedRecordReader(store).n_chunks

    wait_fracs = {}
    for n in sorted(int(c) for c in host_counts):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS=_xla_flags_with_device_count(1))
        procs = []
        for shard in range(n):
            cfg = json.dumps({"path": store, "num_shards": n,
                              "shard_index": shard, "batch": batch,
                              "iters": iters})
            procs.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--dataplane-probe", cfg],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env))
        payloads = []
        for shard, p in enumerate(procs):
            out, err = p.communicate(timeout=600)
            payload = None
            for line in out.splitlines():
                if line.startswith("{"):
                    payload = json.loads(line)
            if payload is None:
                tail = (err or "").strip().splitlines()[-2:]
                raise RuntimeError(
                    f"dataplane probe (n={n}, shard={shard}) "
                    f"rc={p.returncode}: "
                    + (" | ".join(tail) or "no output"))
            payloads.append(payload)
        # shard-local IO receipt: every host opened ONLY chunks from its
        # own pass-0 assignment — disjoint across hosts by construction
        assign = chunk_assignment(n_chunks, n, 0, seed=0)
        opened_all: set = set()
        for payload in payloads:
            opened = set(payload["chunks_opened"])
            shard = int(payload["shard"])
            if not opened <= set(assign[shard]):
                raise RuntimeError(
                    f"host {shard}/{n} opened chunks outside its "
                    f"assignment: {sorted(opened - set(assign[shard]))}")
            if opened & opened_all:
                raise RuntimeError(
                    f"chunks opened by more than one host at n={n}: "
                    f"{sorted(opened & opened_all)}")
            opened_all |= opened
        wait_fracs[n] = sum(p["wait_frac"] for p in payloads) / n

    # resize receipt (no subprocess needed — pure host machinery):
    # 4 hosts consume one chunk each mid-pass, positions redistribute to
    # 2 hosts, and the remaining stream must reconstruct bit-identically
    old_n, new_n = 4, 2
    dss = [DistributedShuffleDataSet(store, num_shards=old_n,
                                     shard_index=i, window_chunks=1)
           for i in range(old_n)]
    consumed = {}
    for i, ds in enumerate(dss):
        it = ds.data(train=True)
        cid = chunk_assignment(n_chunks, old_n, 0, seed=0)[i][0]
        for _ in range(ds.reader.chunk_record_count(cid)):
            next(it)
        consumed[i] = cid
    states = [ds.get_position_state() for ds in dss]
    new_states = redistribute_chunk_positions(states, new_n, seed=0)
    post = {}
    for st in new_states:
        ds2 = DistributedShuffleDataSet(store, num_shards=new_n,
                                        shard_index=int(st["shard_index"]),
                                        window_chunks=1)
        ds2.set_position_state(st, mid_pass=True)
        it = ds2.data(train=True)
        for cid in st["remaining_chunks"]:
            post[cid] = [bytes(memoryview(
                next(it).feature)) for _ in
                range(ds2.reader.chunk_record_count(cid))]
    base_reader = ChunkedRecordReader(store)
    for cid in set(range(n_chunks)) - set(consumed.values()):
        recs = base_reader.read_chunk(cid)
        from bigdl_tpu.dataset.recordstore import decode_sample
        expect = [bytes(memoryview(decode_sample(*recs[j]).feature))
                  for j in chunk_record_order(len(recs), 0, cid, seed=0)]
        if post.get(cid) != expect:
            raise RuntimeError(
                f"{old_n}->{new_n} resize resume NOT bit-identical at "
                f"chunk {cid}")

    counts = sorted(wait_fracs)
    return {
        "metric": "input_pipeline_nhost_wait_frac",
        "value": round(wait_fracs[counts[-1]], 4),
        "unit": f"mean input-wait fraction at {counts[-1]} hosts",
        "wait_frac_by_hosts": {str(n): round(wait_fracs[n], 4)
                               for n in counts},
        "wait_frac_spread": round(wait_fracs[counts[-1]]
                                  - wait_fracs[counts[0]], 4),
        "chunks": n_chunks,
        "shard_local_reads_verified": True,
        "resize_resume_bit_identical": True,
        "iters": iters,
    }


def _dataplane_probe_main(config_json: str):
    """--dataplane-probe subprocess entry: one emulated host of the
    N-host drill — train over its shard of the shared record store and
    emit the measured input-wait fraction plus the reader's chunk-open
    accounting (the shard-local-IO receipt)."""
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import SampleToBatch, Transformer
    from bigdl_tpu.dataset.distributed import DistributedShuffleDataSet
    from bigdl_tpu.utils.random import RandomGenerator

    cfg = json.loads(config_json)
    RandomGenerator.set_seed(0)

    class HostWork(Transformer):
        """Same decode/augment stand-in as the overlap row."""

        def __call__(self, it):
            scratch = np.linspace(0.0, 1.0, 1 << 19, dtype=np.float32)
            for b in it:
                for _ in range(8):
                    scratch = np.tanh(scratch)
                yield b

    ds = DistributedShuffleDataSet(cfg["path"],
                                   num_shards=int(cfg["num_shards"]),
                                   shard_index=int(cfg["shard_index"]))
    pipeline = ds >> SampleToBatch(int(cfg["batch"])) >> HostWork()
    model = nn.Sequential(nn.Linear(64, 1024), nn.Tanh(),
                          nn.Linear(1024, 1024), nn.Tanh(),
                          nn.Linear(1024, 4), nn.LogSoftMax())
    o = optim.Optimizer(model=model, dataset=pipeline,
                        criterion=nn.ClassNLLCriterion())
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    o.set_input_pipeline(depth=2)
    o.set_end_when(optim.max_iteration(int(cfg["iters"])))
    o.optimize()
    wait = o.metrics.stats("host input time")["p50"]
    dev = o.metrics.stats("device step time")["p50"]
    _emit({"shard": int(cfg["shard_index"]),
           "wait_frac": wait / max(wait + dev, 1e-9),
           "chunks_opened": sorted(ds.reader.chunks_opened)})


# shared result of the serving-router workload, keyed by its arguments:
# both serving rows report one run (the row fns are what tests monkeypatch)
_serving_run_cache = None


def _bench_serving_run(*, n_requests: int = 16, replicas: int = 2,
                       max_new: int = 32, d_model: int = 256,
                       num_layers: int = 4):
    """Mixed long-prefill / short-decode workload through a 2-replica
    Router at a FIXED SLO (ISSUE 6): every 4th request repeats a long
    "system prompt" (exercising the prefix cache and prefill/decode
    disaggregation), the rest are short random prompts. A
    bucket-covering warmup pays the XLA compiles outside the measured
    window; the second submission wave repeats the first's long prompt
    so prefill skips land inside it. Returns the raw numbers both
    serving rows report."""
    global _serving_run_cache
    key = (n_requests, replicas, max_new, d_model, num_layers)
    if _serving_run_cache is not None and _serving_run_cache[0] == key:
        return _serving_run_cache[1]
    import jax

    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.models.transformer.serving import ContinuousBatcher
    from bigdl_tpu.observability.exporter import HealthRegistry
    from bigdl_tpu.observability.registry import MetricRegistry
    from bigdl_tpu.serving import ReplicaPool, Router, SLOConfig

    _set_bf16_policy()
    vocab, max_len = 8192, 320
    slo = SLOConfig(ttft_p99_s=2.5, decode_token_p99_s=0.5,
                    max_queue_depth=8, long_prefill_tokens=128)
    model = TransformerLM(vocab, d_model=d_model, num_heads=4,
                          num_layers=num_layers, max_len=max_len,
                          with_log_softmax=False, num_kv_heads=1)
    model.materialize(jax.random.PRNGKey(0))
    model.evaluate()
    host = np.random.default_rng(0)
    long_prompt = list(host.integers(1, vocab + 1, size=(192,)))
    prompts = []
    for i in range(n_requests):
        if i % 4 == 0:
            prompts.append(list(long_prompt))
        else:
            n = int(host.integers(16, 97))
            prompts.append(list(host.integers(1, vocab + 1, size=(n,))))
    geo = dict(max_batch=4, num_pages=96, page_size=16,
               max_new_tokens=max_new, max_burst=8)
    # warmup batcher: one prompt per distinct prefill bucket + the
    # decode/adopt shapes (jit caches are module-level, so the replica
    # pool below reuses every compile)
    warm = ContinuousBatcher(model, registry=MetricRegistry(),
                             health=HealthRegistry(), **geo)
    for i, n in enumerate((16, 32, 64, 96, 192)):
        warm.submit(f"w{i}",
                    list(host.integers(1, vocab + 1, size=(n,))))
    warm.run_to_completion()
    warm.submit("ws", snapshot=warm.prefill_only("wp", long_prompt))
    warm.run_to_completion()
    health = HealthRegistry()
    pool = ReplicaPool(model, replicas, health=health, **geo)
    router = Router(pool, slo=slo, health=health,
                    registry=MetricRegistry())
    try:
        half = n_requests // 2
        t0 = time.perf_counter()
        for i in range(half):
            router.submit(i, prompts[i])
        router.wait_all(timeout=600)
        for i in range(half, n_requests):
            router.submit(i, prompts[i])
        router.wait_all(timeout=600)
        dt = time.perf_counter() - t0
        results = dict(router.finished())
        lat = router.latency_summary()
    finally:
        router.close()
        pool.close()
    if len(results) != n_requests:
        raise RuntimeError(f"router returned {len(results)} results "
                           f"for {n_requests} requests")
    out = {
        "wall_s": dt,
        "tokens_per_sec": n_requests * max_new / dt,
        "n_requests": n_requests, "replicas": replicas,
        "geometry": (f"{_fmt_params(d_model, num_layers)} MQA "
                     f"{replicas}x(4 slots, 96 pages x 16) "
                     f"prompts 16..192 +{max_new}"),
        "slo": {"ttft_p99_s": slo.ttft_p99_s,
                "decode_token_p99_s": slo.decode_token_p99_s,
                "max_queue_depth": slo.max_queue_depth,
                "long_prefill_tokens": slo.long_prefill_tokens},
        **lat,
    }
    _serving_run_cache = (key, out)
    return out


def _fmt_params(d_model: int, num_layers: int) -> str:
    return f"d{d_model} L{num_layers}"


def bench_serving_ttft(**kw):
    """Router-level TTFT percentiles at the fixed serving SLO —
    conservative (bucket-upper-bound) estimates merged across replica
    histograms. ``value`` is the p50; the p99 and the SLO verdict ride
    as fields."""
    r = _bench_serving_run(**kw)
    p50 = r["ttft_p50_s"] or 0.0
    p99 = r["ttft_p99_s"] or 0.0
    return {
        "metric": "serving_ttft",
        "value": round(p50, 4),
        "unit": "seconds",
        "ttft_p50_s": round(p50, 4),
        "ttft_p99_s": round(p99, 4),
        "within_slo": bool(p99 <= r["slo"]["ttft_p99_s"]),
        "prefix_prefill_skips": r["prefix_hits"],
        "disagg_prefills": r["disagg_prefills"],
        "n_requests": r["n_requests"],
        "replicas": r["replicas"],
        "geometry": r["geometry"],
        "slo": r["slo"],
    }


def bench_serving_tokens_per_sec(**kw):
    """End-to-end router throughput for the same fixed-SLO workload:
    generated tokens / wall clock across all replicas (queue wait,
    prefill, disaggregation handoffs and prefix skips included)."""
    r = _bench_serving_run(**kw)
    p99 = r["ttft_p99_s"] or 0.0
    return {
        "metric": "serving_tokens_per_sec",
        "value": round(r["tokens_per_sec"], 1),
        "unit": "tokens/sec",
        "wall_s": round(r["wall_s"], 3),
        "within_slo": bool(p99 <= r["slo"]["ttft_p99_s"]),
        "n_requests": r["n_requests"],
        "replicas": r["replicas"],
        "geometry": r["geometry"],
        "slo": r["slo"],
    }


def _bench_prefix_reuse_run(*, n_requests: int = 10, max_new: int = 8,
                            d_model: int = 256, num_layers: int = 4):
    """Shared-system-prompt workload through a 1-replica router, run
    twice: longest-prefix reuse ON vs exact-only matching. Every
    prompt is a common 3-page (48-token) prefix plus a distinct
    16-token suffix, so exact matching gets ZERO reuse while the radix
    index adopts the 3 shared pages and prefills only the suffix.
    Requests are submitted sequentially with the TTFT histogram's
    ``sum`` read around each one, so per-request TTFTs are exact (not
    bucket-upper-bound) and the p50/p99 comparison is meaningful at
    sub-bucket resolution. Compiles are paid by a warmup batcher
    (module-level jit caches) before either mode runs."""
    import jax

    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.models.transformer.serving import ContinuousBatcher
    from bigdl_tpu.observability.exporter import HealthRegistry
    from bigdl_tpu.observability.registry import MetricRegistry
    from bigdl_tpu.serving import (PrefixCache, ReplicaPool, Router,
                                   SLOConfig)

    _set_bf16_policy()
    vocab, page = 8192, 16
    model = TransformerLM(vocab, d_model=d_model, num_heads=4,
                          num_layers=num_layers, max_len=320,
                          with_log_softmax=False, num_kv_heads=1)
    model.materialize(jax.random.PRNGKey(0))
    model.evaluate()
    host = np.random.default_rng(7)
    shared = list(host.integers(1, vocab + 1, size=(3 * page,)))
    prompts = [shared + list(host.integers(1, vocab + 1, size=(page,)))
               for _ in range(n_requests + 1)]   # +1 seed
    geo = dict(max_batch=4, num_pages=96, page_size=page,
               max_new_tokens=max_new, max_burst=8)
    # warmup: pay the full-prefill (bucket 64), suffix-prefill
    # (bucket 16 at start 48), adopt and decode compiles once
    warm = ContinuousBatcher(model, registry=MetricRegistry(),
                             health=HealthRegistry(), **geo)
    warm.submit("wf", prompts[0])
    warm.run_to_completion()
    wsnap = warm.prefill_only("wp", prompts[0]).truncate(3 * page)
    warm.submit("ws", prompts[1], snapshot=wsnap,
                prefill_from=3 * page)
    warm.run_to_completion()
    warm.submit("wa", snapshot=warm.prefill_only("wq", prompts[0]))
    warm.run_to_completion()

    out = {}
    for mode in ("reuse", "exact"):
        health = HealthRegistry()
        reg = MetricRegistry()
        pool = ReplicaPool(model, 1, health=health, **geo)
        router = Router(
            pool, slo=SLOConfig(long_prefill_tokens=10_000),
            prefix_cache=PrefixCache(min_tokens=page, page_size=page,
                                     longest_match=(mode == "reuse"),
                                     registry=reg),
            registry=reg, health=health)
        try:
            router.submit("seed", prompts[0])
            router.wait_all(timeout=300)
            router.finished()

            def _ttft_sum():
                return sum(
                    r.histogram_snapshot("serving_ttft_seconds")["sum"]
                    for r in pool)

            partial0 = reg.get(
                "router_prefix_partial_hits_total").value()
            reused0 = reg.get(
                "router_prefix_tokens_reused_total").value()
            tokens0 = reg.get("router_prompt_tokens_total").value()
            ttfts, firsts = [], []
            for i in range(1, n_requests + 1):
                s0 = _ttft_sum()
                router.submit(i, prompts[i])
                router.wait_all(timeout=300)
                ttfts.append(_ttft_sum() - s0)
                firsts.append(int(dict(router.finished())[i][0]))
            out[mode] = {
                "ttft_p50_s": float(np.percentile(ttfts, 50)),
                "ttft_p99_s": float(np.percentile(ttfts, 99)),
                "firsts": firsts,
                "partial_hits": int(reg.get(
                    "router_prefix_partial_hits_total").value()
                    - partial0),
                "tokens_reused_fraction": float(
                    (reg.get("router_prefix_tokens_reused_total")
                     .value() - reused0)
                    / max(1.0, reg.get("router_prompt_tokens_total")
                          .value() - tokens0)),
            }
        finally:
            router.close()
            pool.close()
    return out, prompts, geo


def bench_prefix_reuse_ttft(**kw):
    """TTFT win from fleet-global longest-prefix KV reuse on the
    shared-system-prompt workload (ISSUE 18): ``value`` is the
    reuse-ON p50; the exact-only baseline p50/p99, the measured
    tokens-reused fraction and first-token parity ride as fields."""
    out, prompts, geo = _bench_prefix_reuse_run(**kw)
    reuse, exact = out["reuse"], out["exact"]
    params = _fmt_params(kw.get("d_model", 256), kw.get("num_layers", 4))
    return {
        "metric": "prefix_reuse_ttft",
        "value": round(reuse["ttft_p50_s"], 5),
        "unit": "seconds",
        "ttft_p50_s": round(reuse["ttft_p50_s"], 5),
        "ttft_p99_s": round(reuse["ttft_p99_s"], 5),
        "exact_ttft_p50_s": round(exact["ttft_p50_s"], 5),
        "exact_ttft_p99_s": round(exact["ttft_p99_s"], 5),
        "speedup_p50": round(exact["ttft_p50_s"]
                             / max(reuse["ttft_p50_s"], 1e-9), 2),
        "partial_hits": reuse["partial_hits"],
        "tokens_reused_fraction": round(
            reuse["tokens_reused_fraction"], 4),
        "first_tokens_match": bool(reuse["firsts"] == exact["firsts"]),
        "n_requests": len(prompts) - 1,
        "geometry": (f"{params} MQA 1x"
                     f"({geo['max_batch']} slots, {geo['num_pages']} "
                     f"pages x {geo['page_size']}) 48-token shared "
                     f"prefix + 16-token suffixes"),
    }


def _bench_request_trace_run(*, n_requests: int = 10, max_new: int = 8,
                             d_model: int = 256, num_layers: int = 4):
    """Per-request timeline cost + attribution drill (ISSUE 19).

    Overhead: the same single-bucket workload through a 1-replica
    router twice — request tracker ON (``sample_every=1``: every
    timeline retained, the worst case) vs OFF (``tracker=False``) —
    with per-request TTFTs read exactly off the TTFT histogram ``sum``
    around each sequential submit (the ``prefix_reuse`` measurement
    pattern). The modes run identical code paths except the tracker
    events, so the p50 ratio IS the tentpole's hot-path cost.

    Drill: a fresh tracker-ON plane whose replica driver is NOT
    started and whose admission gate allows one queued request, so
    submissions wait (router pending or replica queue) for an induced
    delay before the driver starts. ~All of the tail's latency is
    queue wait by construction, and the tracker's attribution must say
    so (the ISSUE 19 receipt wants >= 80% queue fraction)."""
    import jax

    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.models.transformer.serving import ContinuousBatcher
    from bigdl_tpu.observability.exporter import HealthRegistry
    from bigdl_tpu.observability.registry import MetricRegistry
    from bigdl_tpu.observability.request_trace import RequestTracker
    from bigdl_tpu.serving import ReplicaPool, Router, SLOConfig

    _set_bf16_policy()
    vocab, page = 8192, 16
    model = TransformerLM(vocab, d_model=d_model, num_heads=4,
                          num_layers=num_layers, max_len=320,
                          with_log_softmax=False, num_kv_heads=1)
    model.materialize(jax.random.PRNGKey(0))
    model.evaluate()
    host = np.random.default_rng(11)
    prompts = [list(host.integers(1, vocab + 1, size=(page,)))
               for _ in range(n_requests + 1)]
    geo = dict(max_batch=4, num_pages=96, page_size=page,
               max_new_tokens=max_new, max_burst=8)
    # pay the (bucket-16 prefill, decode) compiles once up front —
    # jit caches are module-level, so every plane below reuses them
    warm = ContinuousBatcher(model, registry=MetricRegistry(),
                             health=HealthRegistry(), **geo)
    warm.submit("wf", prompts[0])
    warm.run_to_completion()

    slo = SLOConfig(ttft_p99_s=2.5, decode_token_p99_s=0.5,
                    long_prefill_tokens=10_000)
    out = {}
    for mode in ("on", "off"):
        health = HealthRegistry()
        reg = MetricRegistry()
        pool = ReplicaPool(model, 1, health=health, **geo)
        tracker = (RequestTracker(slo=slo, sample_every=1)
                   if mode == "on" else False)
        router = Router(pool, slo=slo, registry=reg, health=health,
                        tracker=tracker, capture_prefixes=False)
        try:
            router.submit("seed", prompts[0])
            router.wait_all(timeout=300)
            router.finished()

            def _ttft_sum():
                return sum(
                    r.histogram_snapshot("serving_ttft_seconds")["sum"]
                    for r in pool)

            ttfts = []
            for i in range(1, n_requests + 1):
                s0 = _ttft_sum()
                router.submit(i, prompts[i])
                router.wait_all(timeout=300)
                ttfts.append(_ttft_sum() - s0)
            row = {"ttft_p50_s": float(np.percentile(ttfts, 50)),
                   "ttft_p99_s": float(np.percentile(ttfts, 99))}
            if mode == "on":
                st = tracker.stats()
                row["timelines"] = st["started"]
                row["retained"] = st["retained"]
            out[mode] = row
        finally:
            router.close()
            pool.close()

    # -- induced queue-delay drill --
    delay_s = 0.3
    drill_slo = SLOConfig(ttft_p99_s=2.5, decode_token_p99_s=0.5,
                          max_queue_depth=1,
                          long_prefill_tokens=10_000)
    health = HealthRegistry()
    pool = ReplicaPool(model, 1, health=health, start=False, **geo)
    tracker = RequestTracker(slo=drill_slo, sample_every=1)
    router = Router(pool, slo=drill_slo, registry=MetricRegistry(),
                    health=health, tracker=tracker,
                    capture_prefixes=False)
    try:
        for i in range(6):
            router.submit(f"d{i}", prompts[i])
        time.sleep(delay_s)
        pool.start()
        router.wait_all(timeout=300)
        router.finished()
        attr = tracker.attribution()
        out["drill"] = {"delay_s": delay_s,
                        "queue_fraction": attr["fractions"]["queue_s"],
                        "attribution": attr}
    finally:
        router.close()
        pool.close()
    return out, geo


def bench_request_trace_overhead(**kw):
    """What per-request timelines cost on the TTFT path: ``value`` is
    the tracker-ON p50 TTFT over the tracker-OFF p50 (1.0 = free; the
    ISSUE 19 acceptance wants <= 1.05), with the induced
    queue-delay drill's attribution verdict riding as fields."""
    out, geo = _bench_request_trace_run(**kw)
    on, off = out["on"], out["off"]
    ratio = on["ttft_p50_s"] / max(off["ttft_p50_s"], 1e-9)
    qfrac = out["drill"]["queue_fraction"]
    params = _fmt_params(kw.get("d_model", 256),
                         kw.get("num_layers", 4))
    return {
        "metric": "request_trace_overhead",
        "value": round(ratio, 4),
        "unit": "x (tracker-ON p50 TTFT / tracker-OFF)",
        "ttft_p50_on_s": round(on["ttft_p50_s"], 5),
        "ttft_p50_off_s": round(off["ttft_p50_s"], 5),
        "ttft_p99_on_s": round(on["ttft_p99_s"], 5),
        "ttft_p99_off_s": round(off["ttft_p99_s"], 5),
        "within_overhead_budget": bool(ratio <= 1.05),
        "timelines": on["timelines"],
        "retained": on["retained"],
        "drill_queue_fraction": round(qfrac, 4),
        "drill_queue_attributed": bool(qfrac >= 0.8),
        "drill_delay_s": out["drill"]["delay_s"],
        "n_requests": kw.get("n_requests", 10),
        "geometry": (f"{params} MQA 1x({geo['max_batch']} slots, "
                     f"{geo['num_pages']} pages x {geo['page_size']}) "
                     f"16-token prompts +{geo['max_new_tokens']}"),
    }


def bench_serving_decode_hbm(**geometry):
    """Static per-decode-step HBM accounting, dense view vs the Pallas
    paged kernel (ISSUE 9 — the tentpole's measured receipt): lowers
    one single-token decode step both ways in a CPU SUBPROCESS (same
    pattern as ``collective_wire_bytes_per_step``; lowering only, no
    execution, and the parent's TPU backend is never touched) and
    reports (a) the view-sized gather materializations each compiled
    HLO carries — exactly 2*layers for the dense path, ZERO for the
    kernel — and (b) the static attention-traffic model: dense pays 3x
    the (B, P*S, KV, D) view per k/v consumption, paged reads each
    row's live pages once. ``value`` is the dense/paged reduction."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--decode-hbm-probe",
         "--decode-hbm-geometry", json.dumps(geometry)],
        capture_output=True, text=True, timeout=600, env=env)
    payload = None
    for line in out.stdout.splitlines():
        if line.startswith("{"):
            payload = json.loads(line)
    if payload is None:
        tail = (out.stderr or "").strip().splitlines()[-2:]
        raise RuntimeError(
            f"decode-hbm probe subprocess rc={out.returncode}: "
            + (" | ".join(tail) or "no output"))
    mg = payload["materialized_gathers"]
    ab = payload["attn_hbm_bytes"]
    ex = payload["executable"]
    return {
        "metric": "serving_decode_hbm_bytes",
        "value": round(payload["reduction"], 2),
        "unit": "x (dense-view / paged attention HBM bytes per "
                "decode step)",
        "attn_hbm_bytes_dense": ab["dense"],
        "attn_hbm_bytes_paged": ab["paged"],
        "materialized_gather_ops_dense": mg["dense"]["ops"],
        "materialized_gather_bytes_dense": mg["dense"]["bytes"],
        "materialized_gather_ops_paged": mg["paged"]["ops"],
        "materialized_gather_bytes_paged": mg["paged"]["bytes"],
        "view_shape": payload["view_shape"],
        "view_bytes": payload["view_bytes"],
        "peak_view_bytes_per_layer_eliminated":
            payload["peak_view_bytes_per_layer"],
        "bytes_accessed_dense_exec": ex["dense"].get("bytes_accessed"),
        "peak_hbm_bytes_dense_exec": ex["dense"].get("peak_hbm_bytes"),
        # off-TPU the paged step compiles in interpreter mode, so its
        # executable numbers describe the emulation; the static rows
        # above are the backend-independent receipt
        "paged_compiled_as": payload["paged_compiled_as"],
        # int8 quantized serving (serving/quantized.py): resident
        # weight + KV-pool argument bytes, fp32 vs int8-at-rest
        "int8_weight_kv_bytes_fp32":
            payload["int8"]["weight_kv_bytes_fp32"],
        "int8_weight_kv_bytes_int8":
            payload["int8"]["weight_kv_bytes_int8"],
        "int8_kv_pool_bytes_fp32": payload["int8"]["kv_pool_bytes_fp32"],
        "int8_kv_pool_bytes_int8": payload["int8"]["kv_pool_bytes_int8"],
        "int8_reduction": round(payload["int8"]["reduction"], 2),
        "geometry": payload["geometry"],
    }


def _autoscale_drill(model, cache_dir, *, prompts, geo, slo, cfg,
                     target_replicas):
    """One autoscaler spin-up drill (ISSUE 15): a 1-replica AOT-cached
    pool behind a Router + Autoscaler, hit with a synthetic admission
    spike; the closed loop runs until the fleet reaches
    ``target_replicas``. Returns time-to-capacity plus the AOT cache
    counters (the warm-vs-cold receipt) and the conservation check."""
    from bigdl_tpu.observability.exporter import HealthRegistry
    from bigdl_tpu.observability.registry import MetricRegistry
    from bigdl_tpu.serving import (Autoscaler, ReplicaPool, Router)

    health = HealthRegistry()
    pool = ReplicaPool(model, 1, health=health, start=False,
                       aot_cache=cache_dir, **geo)
    t0 = time.perf_counter()
    pool["r0"].batcher.warmup(prompt_buckets=(16,))
    first_spinup_s = time.perf_counter() - t0
    pool.start()
    router = Router(pool, slo=slo, health=health,
                    registry=MetricRegistry(), capture_prefixes=False)
    asc = Autoscaler(router, config=cfg, registry=MetricRegistry())
    try:
        t_spike = time.perf_counter()
        for i, p in enumerate(prompts):
            router.submit(f"q{i}", p)
        t_capacity = None
        while time.perf_counter() - t_spike < 300:
            asc.evaluate()
            if len(pool) >= target_replicas:
                t_capacity = time.perf_counter() - t_spike
                break
            time.sleep(0.01)
        if t_capacity is None:
            raise RuntimeError(
                f"fleet never reached {target_replicas} replicas "
                f"(pending={router.pending_count})")
        router.wait_all(timeout=600)
        results = dict(router.finished())
        # quiet period: hysteresis retires the spike capacity via
        # drain/migrate (conservation across scale-down is the
        # wait_all/finished accounting above plus the late stragglers)
        scale_downs = 0
        for _ in range(cfg.hysteresis_evals * (cfg.cooldown_evals + 1)
                       + 12):
            if asc.evaluate().action == "down":
                scale_downs += 1
            if len(pool) <= cfg.min_replicas:
                break
        results.update(router.finished())
    finally:
        router.close()
        pool.close()
    if len(results) != len(prompts):
        raise RuntimeError(f"autoscale drill dropped/duplicated work: "
                           f"{len(results)} results for "
                           f"{len(prompts)} requests")
    return {
        "time_to_capacity_s": t_capacity,
        "first_spinup_s": first_spinup_s,
        "aot_hits": pool.aot.hits, "aot_misses": pool.aot.misses,
        "replicas_peak": max(target_replicas, len(pool)),
        "scale_downs": scale_downs,
        "n_results": len(results),
    }


def bench_autoscale_time_to_capacity(*, n_requests: int = 24,
                                     target_replicas: int = 3):
    """Fleet autoscaler receipt (ISSUE 15): seconds from a synthetic
    admission spike against a 1-replica pool until the closed loop has
    scaled the fleet to ``target_replicas``, warm vs cold AOT
    executable cache. The drill runs twice over ONE cache directory:
    the cold pass pays every prefill/decode compile; the warm pass is a
    fresh pool + compiler table over the same directory — the PR 8
    warm-restart machinery as time-to-capacity — and must report ZERO
    cache misses (every spin-up deserializes stored executables).
    ``value`` is the warm time-to-capacity (lower is better)."""
    import tempfile

    import jax

    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.serving import AutoscalerConfig, SLOConfig

    vocab = 256
    model = TransformerLM(vocab, d_model=64, num_heads=4, num_layers=2,
                          max_len=64, with_log_softmax=False)
    model.materialize(jax.random.PRNGKey(0))
    model.evaluate()
    host = np.random.default_rng(0)
    prompts = [list(host.integers(1, vocab + 1,
                                  size=(int(host.integers(5, 14)),)))
               for _ in range(n_requests)]
    geo = dict(max_batch=2, num_pages=64, page_size=4,
               max_new_tokens=8, max_burst=4)
    slo = SLOConfig(long_prefill_tokens=64, max_queue_depth=2)
    cfg = AutoscalerConfig(min_replicas=1, max_replicas=target_replicas,
                           pending_per_replica=2, hysteresis_evals=2,
                           cooldown_evals=0, interval_s=0.05)
    with tempfile.TemporaryDirectory() as cache_dir:
        drill = dict(prompts=prompts, geo=geo, slo=slo, cfg=cfg,
                     target_replicas=target_replicas)
        cold = _autoscale_drill(model, cache_dir, **drill)
        warm = _autoscale_drill(model, cache_dir, **drill)
    if warm["aot_misses"] != 0:
        raise RuntimeError(
            f"warm spin-up compiled: {warm['aot_misses']} AOT cache "
            "misses (expected 0 — every executable should load)")
    return {
        "metric": "autoscale_time_to_capacity",
        "value": round(warm["time_to_capacity_s"], 3),
        "unit": f"seconds to {target_replicas} replicas (warm AOT "
                "cache)",
        "cold_time_to_capacity_s": round(cold["time_to_capacity_s"], 3),
        "warm_time_to_capacity_s": round(warm["time_to_capacity_s"], 3),
        "cold_first_spinup_s": round(cold["first_spinup_s"], 3),
        "warm_first_spinup_s": round(warm["first_spinup_s"], 3),
        "cold_aot_misses": cold["aot_misses"],
        "warm_aot_misses": warm["aot_misses"],
        "warm_aot_hits": warm["aot_hits"],
        "warm_zero_misses": warm["aot_misses"] == 0,
        "scale_downs_warm": warm["scale_downs"],
        "n_requests": n_requests,
        "conserved": (cold["n_results"] == n_requests
                      and warm["n_results"] == n_requests),
        "geometry": (f"d64 L2 1->{target_replicas} replicas, "
                     f"{n_requests} reqs, 2 slots x 64 pages x 4"),
    }


def bench_publish_to_fleet(*, n_requests: int = 12):
    """Continuous-deployment receipt (ISSUE 16): seconds from a newly
    COMMITTED trainer checkpoint (manifest on disk) until 100% of a
    2-replica serving fleet serves it — warm canary qualification
    (pinned-prompt parity + zero compiles off the shared AOT cache),
    then a replica-by-replica drain -> reload -> resume rollout, with
    live traffic in flight the whole time. The drill asserts the
    zero-downtime contract: every request submitted before, during and
    after the publish is delivered exactly once, and the warm canary
    spin-up pays ZERO XLA compiles. A second, parity-failing commit
    then drills the rollback path: the canary fails and the fleet
    stays 100% on the published version. ``value`` is the measured
    commit-to-fleet latency (lower is better)."""
    import tempfile

    import jax

    from bigdl_tpu.deploy import (CanaryConfig, PublisherConfig,
                                  WeightPublisher,
                                  write_model_checkpoint)
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.models.transformer.generate import (GenerationConfig,
                                                       generate)
    from bigdl_tpu.observability.exporter import HealthRegistry
    from bigdl_tpu.observability.registry import MetricRegistry
    from bigdl_tpu.serving import (PrefixCache, ReplicaPool, Router,
                                   SLOConfig)

    vocab = 256

    def _lm(seed):
        m = TransformerLM(vocab, d_model=64, num_heads=4, num_layers=2,
                          max_len=64, with_log_softmax=False)
        m.materialize(jax.random.PRNGKey(seed))
        m.evaluate()
        return m

    model, model2 = _lm(0), _lm(1)
    host = np.random.default_rng(0)
    prompts = [list(host.integers(1, vocab + 1,
                                  size=(int(host.integers(5, 14)),)))
               for _ in range(n_requests)]
    pin = prompts[0]
    gen = GenerationConfig(max_new_tokens=8, temperature=0.0)
    expected_new = [int(t) for t in np.asarray(
        generate(model2, np.asarray([pin], np.int32), gen))[0]]
    geo = dict(max_batch=2, num_pages=64, page_size=4,
               max_new_tokens=8, max_burst=4)

    health = HealthRegistry()
    reg = MetricRegistry()
    with tempfile.TemporaryDirectory() as tmp:
        ck = os.path.join(tmp, "ck")
        cache_dir = os.path.join(tmp, "aot")
        write_model_checkpoint(ck, model, neval=1)
        pool = ReplicaPool(model, 2, health=health, aot_cache=cache_dir,
                           **geo)
        router = Router(pool, slo=SLOConfig(long_prefill_tokens=64),
                        prefix_cache=PrefixCache(min_tokens=4),
                        registry=reg, health=health)
        pub = WeightPublisher(
            router, ck,
            config=PublisherConfig(
                CanaryConfig(prompts=[(pin, expected_new)],
                             require_zero_compiles=True),
                drain_timeout_s=120),
            registry=reg, health=health)
        try:
            third = max(1, n_requests // 3)
            for i in range(third):                  # before the commit
                router.submit(f"q{i}", prompts[i])
            router.wait_all(timeout=600)
            # the trainer commits checkpoint N+1 mid-serving
            write_model_checkpoint(ck, model2, neval=2)
            for i in range(third, 2 * third):       # in flight/queued
                router.submit(f"q{i}", prompts[i])
            t0 = time.perf_counter()
            report = pub.poll_once()
            publish_s = time.perf_counter() - t0
            if report is None or report.outcome != "ok":
                raise RuntimeError(
                    "publish drill did not roll the fleet: "
                    f"{None if report is None else report.as_dict()}")
            for i in range(2 * third, n_requests):  # after the rollout
                router.submit(f"q{i}", prompts[i])
            router.wait_all(timeout=600)
            results = dict(router.finished())
            versions = {pool[n].weight_version for n in pool.names}
            # rollback sub-drill: commit a third checkpoint whose
            # canary CANNOT satisfy the pinned expectation (old
            # weights vs the v2 expectation) — the fleet must stay put
            write_model_checkpoint(ck, model, neval=3)
            rb = pub.poll_once()
            rb_versions = {pool[n].weight_version for n in pool.names}
        finally:
            pub.close()
            router.close()
            pool.close()
    if len(results) != n_requests:
        raise RuntimeError(
            f"publish drill dropped/duplicated work: {len(results)} "
            f"results for {n_requests} requests")
    if versions != {"v2"} or rb_versions != {"v2"}:
        raise RuntimeError(
            f"fleet not uniformly on the published version: {versions} "
            f"after publish, {rb_versions} after rollback drill")
    if report.canary.compiles != 0:
        raise RuntimeError(
            f"warm canary compiled: {report.canary.compiles} AOT "
            "misses (expected 0 — the candidate shares every "
            "executable)")
    return {
        "metric": "publish_to_fleet_secs",
        "value": round(publish_s, 3),
        "unit": "seconds committed checkpoint -> 100% of fleet "
                "(2 replicas, warm canary)",
        "canary_compiles": report.canary.compiles,
        "replicas_rolled": len(report.rolled),
        "rollback_drill_outcome": rb.outcome,
        "rollback_kept_fleet": rb_versions == {"v2"},
        "fleet_version": sorted(versions)[0],
        "n_requests": n_requests,
        "conserved": len(results) == n_requests,
        "aot_hits": int(pool.aot.hits),
        "aot_misses": int(pool.aot.misses),
        "geometry": ("d64 L2 2 replicas + canary, "
                     f"{n_requests} reqs, 2 slots x 64 pages x 4"),
    }


def _decode_hbm_probe_main(geometry_json: str):
    """--decode-hbm-probe subprocess entry: run the static accounting
    on the CPU backend and emit the JSON payload. ``geometry_json``
    overrides probe dimensions (the contract tests use a tiny one)."""
    from bigdl_tpu.models.transformer.serving import decode_hbm_probe
    _emit(decode_hbm_probe(**json.loads(geometry_json or "{}")))


def _probe_backend(timeout_s: float):
    """Init the default jax backend in a SUBPROCESS with a hard timeout.

    The container's axon TPU plugin can hang backend init forever when its
    tunnel is wedged (round-4 BENCH was rc=1/raw-traceback, MULTICHIP
    rc=124). Probing in a child process turns 'hang forever' into a
    structured, reportable failure without poisoning this process.
    Returns (info_str, None) on success or (None, error_str) on failure.
    """
    import subprocess
    code = ("import jax; ds = jax.devices(); "
            "import jax.numpy as jnp; "
            "jnp.ones(8).sum().block_until_ready(); "
            "print(ds[0].platform, getattr(ds[0], 'device_kind', ''), "
            "len(ds), sep='|')")
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None, (f"jax backend init timed out after {timeout_s:.0f}s "
                      f"(wedged TPU tunnel?)")
    if p.returncode != 0:
        tail = (p.stderr or "").strip().splitlines()[-3:]
        return None, (f"jax backend init failed rc={p.returncode}: "
                      + " | ".join(tail))
    return p.stdout.strip(), None


# ---------------------------------------------------------------------------
# regression gate (ROADMAP item 5): compare this run's rows against a
# recorded baseline with per-row thresholds; a real slowdown fails the
# run with a distinct exit code.
# ---------------------------------------------------------------------------

#: a row passes while value >= baseline * min_ratio (higher-is-better)
#: or value <= baseline / min_ratio (lower-is-better) — 20% headroom by
#: default so scheduler noise does not flap the gate; tighten per row
#: in the baseline file
GATE_DEFAULT_MIN_RATIO = 0.8

# metrics where a SMALLER value is the better one; everything else
# (throughput-style rows) gates higher-is-better. Baseline entries can
# override with an explicit "direction".
_GATE_LOWER_IS_BETTER = {"serving_ttft", "pipeline_bubble_fraction",
                         "collective_wire_bytes_per_step",
                         "autoscale_time_to_capacity",
                         "publish_to_fleet_secs",
                         "prefix_reuse_ttft",
                         "request_trace_overhead",
                         "input_pipeline_nhost_wait_frac"}

GATE_EXIT_CODE = 4

#: the committed baseline a plain ``python bench.py`` gates against by
#: default (ROADMAP item 5: record with ``--baseline-out BASELINE.json``,
#: opt out with ``--no-gate``; docs/PERFORMANCE.md has the refresh
#: procedure). Only armed for CLI invocations — embedding callers and
#: tests pass explicit argv and keep explicit gating.
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BASELINE.json")


def _is_gate_baseline(path: str) -> bool:
    """True when ``path`` is a recorded gate baseline (a ``rows``
    object). The repo's seed-era BASELINE.json predates the gate and
    carries reference metadata instead — gating against it would fail
    every run, so the default gate arms only on the real format."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        return isinstance(doc.get("rows"), dict)
    except Exception:
        return False

# row key -> emitted metric name, where they differ: a row that FAILS
# mid-run is recorded under its row key, so the gate must recognize a
# baselined metric behind either name
_ROW_METRICS = {
    "headline": "inception_v1_train_images_per_sec_per_chip",
    "inception_v2": "inception_v2_train_images_per_sec_per_chip",
    "resnet50": "resnet50_train_images_per_sec_per_chip",
    "vgg16": "vgg16_train_images_per_sec_per_chip",
    "real": "inception_v1_train_real_jpeg_images_per_sec_per_chip",
    "real_cached":
        "inception_v1_train_real_jpeg_cached_images_per_sec_per_chip",
    "transformer": "transformer_lm_train_tokens_per_sec_per_chip",
    "decode": "transformer_lm_decode_tokens_per_sec_per_chip",
    "decode_ragged":
        "transformer_lm_ragged_decode_tokens_per_sec_per_chip",
    "decode_spec": "transformer_lm_speculative_decode_tokens_per_sec",
    "input_pipeline": "input_pipeline_overlap",
    "input_pipeline_nhost": "input_pipeline_nhost_wait_frac",
}
_METRIC_TO_ROW = {v: k for k, v in _ROW_METRICS.items()}


def _gate_check(path: str, rows_out: list[dict]) -> tuple[dict, bool]:
    """Evaluate the recorded baseline at ``path`` against this run's
    rows. Returns (gate row, ok). Only metrics present in BOTH the
    baseline and the run are judged (the baseline may cover rows this
    invocation did not request — reported as skipped, never silently
    dropped); a baselined row that ERRORED this run is a failure."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        base = doc["rows"]
        if not isinstance(base, dict):
            raise ValueError("baseline 'rows' is not an object")
    except Exception as e:
        row = {"metric": "bench_gate", "value": 0.0, "unit": "1 = pass",
               "baseline": path,
               "error": f"unreadable baseline: {type(e).__name__}: {e}"}
        return row, False
    by_metric = {r.get("metric"): r for r in rows_out}
    checked, skipped, failures = [], [], []
    for metric, spec in sorted(base.items()):
        row = by_metric.get(metric) \
            or by_metric.get(_METRIC_TO_ROW.get(metric))
        if row is None:
            skipped.append(metric)
            continue
        if "error" in row:
            failures.append({"metric": metric,
                             "reason": f"row errored: {row['error']}"})
            continue
        val = row.get("value")
        bval = float(spec["value"])
        ratio = float(spec.get("min_ratio", GATE_DEFAULT_MIN_RATIO))
        direction = spec.get(
            "direction",
            "lower" if metric in _GATE_LOWER_IS_BETTER else "higher")
        checked.append(metric)
        if not isinstance(val, (int, float)):
            failures.append({"metric": metric,
                             "reason": f"non-numeric value {val!r}"})
            continue
        if direction == "lower":
            ok = val <= bval / max(ratio, 1e-9)
            reason = (f"{val} > baseline {bval} / min_ratio {ratio} "
                      f"(lower is better)")
        else:
            ok = val >= bval * ratio
            reason = f"{val} < baseline {bval} * min_ratio {ratio}"
        if not ok:
            failures.append({"metric": metric, "value": val,
                             "baseline": bval, "min_ratio": ratio,
                             "direction": direction, "reason": reason})
    row = {"metric": "bench_gate", "value": 0.0 if failures else 1.0,
           "unit": "1 = pass", "baseline": path, "checked": checked,
           "skipped": skipped, "failures": failures}
    return row, not failures


def _write_baseline(path: str, rows_out: list[dict]) -> None:
    """Record this run as the new gate baseline: every successful
    numeric row, with the default threshold and its direction spelled
    out so the file is hand-editable."""
    rows = {}
    for r in rows_out:
        val = r.get("value")
        if ("error" in r or "metric" not in r
                or r["metric"] in ("aggregate", "bench_gate")
                or not isinstance(val, (int, float))):
            continue
        rows[r["metric"]] = {
            "value": val,
            "min_ratio": GATE_DEFAULT_MIN_RATIO,
            "direction": ("lower" if r["metric"] in _GATE_LOWER_IS_BETTER
                          else "higher"),
            "unit": r.get("unit", ""),
        }
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "rows": rows}, f, indent=1,
                  sort_keys=True)
        f.write("\n")
    print(f"# gate baseline written to {path}", file=sys.stderr)


# the driver's parser keeps only the LAST JSON line (BENCH_r03 lesson), so
# after the per-row lines we re-emit everything in one aggregate line that
# carries the headline fields at top level plus every row under "rows"
def _emit_aggregate(rows_out: list[dict]) -> None:
    agg = {"metric": "aggregate", "value": 0.0, "unit": "",
           "vs_baseline": 0.0}
    # hoist only the FIRST requested row (the headline when present) and
    # only if it succeeded — promoting a different row's number into the
    # headline slot would misreport a degraded run as healthy
    if rows_out and "error" not in rows_out[0]:
        agg.update({k: rows_out[0][k] for k in
                    ("metric", "value", "unit", "vs_baseline")
                    if k in rows_out[0]})
    agg["rows"] = rows_out
    _emit(agg)


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--headline-only", action="store_true")
    parser.add_argument("--rows", default="all",
                        help="comma list: headline,inception_v2,real,"
                             "real_cached,resnet50,vgg16,transformer,"
                             "decode,decode_ragged,decode_spec,"
                             "input_pipeline,serving_ttft,"
                             "serving_tokens_per_sec,train_mfu,"
                             "collective_wire_bytes_per_step,"
                             "compile_cold_start,"
                             "serving_decode_hbm_bytes,"
                             "train_peak_hbm_bytes,multichip_scaling,"
                             "pipeline_bubble_fraction,"
                             "elastic_resume_secs,"
                             "autoscale_time_to_capacity,"
                             "input_pipeline_nhost")
    parser.add_argument("--gate", default=None, metavar="BASELINE_JSON",
                        help="compare this run's rows against a "
                             "recorded baseline (per-row thresholds); "
                             f"a real slowdown exits {GATE_EXIT_CODE}. "
                             "A CLI run with no --gate gates against "
                             f"{DEFAULT_BASELINE} automatically when "
                             "that file is a recorded baseline "
                             "(--no-gate opts out)")
    parser.add_argument("--no-gate", action="store_true",
                        help="skip the default BASELINE.json gate")
    parser.add_argument("--baseline-out", default=None, metavar="PATH",
                        help="record this run's rows as the new gate "
                             "baseline (written alongside "
                             "--metrics-out)")
    parser.add_argument("--probe-timeout", type=float,
                        # BENCH_r05: a wedged TPU tunnel hung backend init
                        # for the full 300 s — fail fast instead. The
                        # default stays well under the tier-1 budget;
                        # BIGDL_TPU_BENCH_INIT_TIMEOUT overrides it
                        # (BENCH_PROBE_TIMEOUT_S kept as the legacy name)
                        default=float(os.environ.get(
                            "BIGDL_TPU_BENCH_INIT_TIMEOUT",
                            os.environ.get("BENCH_PROBE_TIMEOUT_S",
                                           "120"))))
    parser.add_argument("--metrics-out", default=None,
                        help="write the metric-registry state here "
                             "after the run (.json -> JSON dump, else "
                             "Prometheus text exposition)")
    parser.add_argument("--serve-metrics", type=int, default=None,
                        metavar="PORT",
                        help="expose the live registry over HTTP for "
                             "the duration of the run (/metrics, "
                             "/metrics.json, /trace, /healthz, "
                             "/readyz; 0 = ephemeral port)")
    parser.add_argument("--host-probe", type=float, default=None,
                        help=argparse.SUPPRESS)   # subprocess entry
    parser.add_argument("--wire-probe", action="store_true",
                        help=argparse.SUPPRESS)   # subprocess entry
    parser.add_argument("--decode-hbm-probe", action="store_true",
                        help=argparse.SUPPRESS)   # subprocess entry
    parser.add_argument("--decode-hbm-geometry", default="{}",
                        help=argparse.SUPPRESS)
    parser.add_argument("--cold-start-probe", default=None,
                        metavar="CACHE_DIR",
                        help=argparse.SUPPRESS)   # subprocess entry
    parser.add_argument("--cold-start-model", default="inception_v1",
                        help=argparse.SUPPRESS)
    parser.add_argument("--cold-start-batch", type=int, default=16,
                        help=argparse.SUPPRESS)
    parser.add_argument("--elastic-train-probe", default=None,
                        metavar="CKPT_DIR",
                        help=argparse.SUPPRESS)   # subprocess entry
    parser.add_argument("--elastic-resume-probe", default=None,
                        metavar="CKPT_DIR",
                        help=argparse.SUPPRESS)   # subprocess entry
    parser.add_argument("--elastic-resume-cache", default=None,
                        help=argparse.SUPPRESS)
    parser.add_argument("--train-hbm-probe", action="store_true",
                        help=argparse.SUPPRESS)   # subprocess entry
    parser.add_argument("--train-hbm-geometry", default="{}",
                        help=argparse.SUPPRESS)
    parser.add_argument("--scaling-probe", type=int, default=None,
                        help=argparse.SUPPRESS)   # subprocess entry
    parser.add_argument("--pipeline-bubble-probe", action="store_true",
                        help=argparse.SUPPRESS)   # subprocess entry
    parser.add_argument("--pipeline-bubble-geometry", default="{}",
                        help=argparse.SUPPRESS)
    parser.add_argument("--scaling-batch-per-chip", type=int, default=64,
                        help=argparse.SUPPRESS)
    parser.add_argument("--scaling-iters", type=int, default=8,
                        help=argparse.SUPPRESS)
    parser.add_argument("--dataplane-probe", default=None,
                        metavar="CONFIG_JSON",
                        help=argparse.SUPPRESS)   # subprocess entry
    args = parser.parse_args(argv)
    if argv is None and args.gate is None and not args.no_gate:
        # ROADMAP item 5: the committed baseline is ENFORCED on plain
        # CLI runs once one is recorded; a legacy/non-gate file skips
        # with a note instead of failing every run
        if _is_gate_baseline(DEFAULT_BASELINE):
            args.gate = DEFAULT_BASELINE
            print(f"# gating against {DEFAULT_BASELINE} "
                  "(--no-gate to skip)", file=sys.stderr)
        elif os.path.exists(DEFAULT_BASELINE):
            print(f"# {DEFAULT_BASELINE} is not a recorded gate "
                  "baseline (no 'rows') — default gate skipped; record "
                  "one with --baseline-out", file=sys.stderr)
    if args.host_probe is not None:
        _emit({"host_pipeline_img_per_sec":
               round(host_pipeline_probe(args.host_probe), 1)})
        return
    if args.wire_probe:
        _wire_probe_main()
        return
    if args.decode_hbm_probe:
        _decode_hbm_probe_main(args.decode_hbm_geometry)
        return
    if args.cold_start_probe is not None:
        _cold_start_probe_main(args.cold_start_probe,
                               args.cold_start_model,
                               args.cold_start_batch)
        return
    if args.elastic_train_probe is not None:
        _elastic_train_probe_main(args.elastic_train_probe)
        return
    if args.elastic_resume_probe is not None:
        _elastic_resume_probe_main(args.elastic_resume_probe,
                                   args.elastic_resume_cache)
        return
    if args.train_hbm_probe:
        _train_hbm_probe_main(args.train_hbm_geometry)
        return
    if args.scaling_probe is not None:
        _scaling_probe_main(args.scaling_probe,
                            args.scaling_batch_per_chip,
                            args.scaling_iters)
        return
    if args.pipeline_bubble_probe:
        _pipeline_bubble_probe_main(args.pipeline_bubble_geometry)
        return
    if args.dataplane_probe is not None:
        _dataplane_probe_main(args.dataplane_probe)
        return
    global _metrics_server
    if args.serve_metrics is not None:
        from bigdl_tpu.observability.exporter import MetricsServer
        _metrics_server = MetricsServer(port=args.serve_metrics).start()
        print(f"# telemetry plane: {_metrics_server.url}",
              file=sys.stderr)
    try:
        return _run(args)
    finally:
        if _metrics_server is not None:
            _metrics_server.close()
            _metrics_server = None


# the live exporter for the current run (None outside one) — tests and
# embedding harnesses read the bound port here
_metrics_server = None


def _dump_bench_postmortem(exc: Exception, *, reason: str) -> str | None:
    """BENCH_r05: a wedged/dead backend must leave the same black box a
    crashed training run does — exception.json, registry.json (whatever
    rows DID land), trace, events — under
    ``$BIGDL_TPU_POSTMORTEM_DIR``/tmp. Returns the directory."""
    try:
        from bigdl_tpu.observability.flight_recorder import FlightRecorder
        return FlightRecorder().dump_postmortem(exc, reason=reason)
    except Exception as e:          # the postmortem must never mask the row
        print(f"bench postmortem failed: {e}", file=sys.stderr)
        return None


# error substrings that mean the jax backend itself is gone — every
# later row would crash or hang the same way (BENCH_r04: the inception
# row died in its first eager convert_element_type with this text and
# took the whole run down as a raw rc=1 traceback)
_BACKEND_DEATH_MARKERS = ("Unable to initialize backend",
                          "backend setup/compile error",
                          "UNAVAILABLE:")


def _backend_death(e: BaseException) -> bool:
    text = f"{e}"
    return any(m in text for m in _BACKEND_DEATH_MARKERS)


def _run(args):
    global _headline_cache
    _headline_cache = None      # per-invocation cache (tests re-enter)
    rows = (["headline"] if args.headline_only
            else [r.strip() for r in args.rows.split(",")])
    if args.rows == "all" and not args.headline_only:
        rows = ["headline", "train_mfu", "inception_v2", "real",
                "real_cached", "resnet50", "vgg16", "transformer",
                "decode", "decode_ragged", "decode_spec",
                "input_pipeline", "serving_ttft",
                "serving_tokens_per_sec",
                "collective_wire_bytes_per_step",
                "compile_cold_start", "serving_decode_hbm_bytes",
                "train_peak_hbm_bytes", "multichip_scaling",
                "pipeline_bubble_fraction", "elastic_resume_secs",
                "autoscale_time_to_capacity", "publish_to_fleet_secs",
                "prefix_reuse_ttft", "request_trace_overhead",
                "input_pipeline_nhost"]

    known = {"headline", "inception_v2", "real", "real_cached",
             "resnet50", "vgg16", "transformer", "decode",
             "decode_ragged", "decode_spec", "input_pipeline",
             "serving_ttft", "serving_tokens_per_sec", "train_mfu",
             "collective_wire_bytes_per_step", "compile_cold_start",
             "serving_decode_hbm_bytes", "train_peak_hbm_bytes",
             "multichip_scaling", "pipeline_bubble_fraction",
             "elastic_resume_secs", "autoscale_time_to_capacity",
             "publish_to_fleet_secs", "prefix_reuse_ttft",
             "request_trace_overhead", "input_pipeline_nhost"}
    unknown = set(rows) - known
    if unknown:
        raise SystemExit(f"unknown bench rows: {sorted(unknown)} "
                         f"(known: {sorted(known)})")

    info, err = _probe_backend(args.probe_timeout)
    if err is not None:
        # fail fast AND structured: one error row per REQUESTED metric,
        # emitted immediately, so the driver sees exactly which rows the
        # wedged backend cost it (BENCH_r05 hung 300 s and reported only
        # the headline) — plus a flight-recorder postmortem so the
        # failure is debuggable after the fact, not just counted
        pm = _dump_bench_postmortem(RuntimeError(err),
                                    reason="bench backend init failure")
        rows_out = []
        for row in rows:
            r = {"metric": ("inception_v1_train_images_per_sec_per_chip"
                            if row == "headline" else row),
                 "value": 0.0,
                 "unit": "images/sec/chip" if row == "headline" else "",
                 "error": err}
            if row == "headline":
                r["vs_baseline"] = 0.0
            if pm:
                r["postmortem"] = pm
            rows_out.append(r)
            _emit(r)
        _emit_aggregate(rows_out)
        raise SystemExit(3)
    print(f"# backend: {info}", file=sys.stderr)

    fns = {
        "headline": _headline_row,
        "train_mfu": bench_train_mfu,
        "collective_wire_bytes_per_step": bench_collective_wire_bytes,
        "compile_cold_start": bench_compile_cold_start,
        "inception_v2": lambda: bench_convnet_synthetic("inception_v2"),
        "real": lambda: bench_real_data(0.0),
        "real_cached": lambda: bench_real_data(2.0),
        "resnet50": lambda: bench_convnet_synthetic("resnet50"),
        "vgg16": lambda: bench_convnet_synthetic("vgg16"),
        "transformer": bench_transformer_lm,
        "decode": bench_decode,
        "decode_ragged": bench_decode_ragged,
        "decode_spec": bench_decode_speculative,
        "input_pipeline": bench_input_pipeline_overlap,
        "serving_ttft": bench_serving_ttft,
        "serving_tokens_per_sec": bench_serving_tokens_per_sec,
        "serving_decode_hbm_bytes": bench_serving_decode_hbm,
        "train_peak_hbm_bytes": bench_train_peak_hbm,
        "multichip_scaling": bench_multichip_scaling,
        "pipeline_bubble_fraction": bench_pipeline_bubble,
        "elastic_resume_secs": bench_elastic_resume_secs,
        "autoscale_time_to_capacity": bench_autoscale_time_to_capacity,
        "publish_to_fleet_secs": bench_publish_to_fleet,
        "prefix_reuse_ttft": bench_prefix_reuse_ttft,
        "request_trace_overhead": bench_request_trace_overhead,
        "input_pipeline_nhost": bench_input_pipeline_nhost,
    }
    rows_out: list[dict] = []
    headline_failed = False
    backend_died = None
    for i, row in enumerate(rows):
        try:
            out = fns[row]()
            rows_out.append(out)
            _emit(out)
        except Exception as e:   # a broken row must not lose the others
            err = f"{type(e).__name__}: {e}"
            rows_out.append({"metric": row, "error": err})
            print(f"bench row {row} failed: {e}", file=sys.stderr)
            if row == "headline":
                headline_failed = True
            if _backend_death(e):
                # BENCH_r04: the backend died under a row (a probe can
                # pass and the tunnel still wedge on the next init).
                # Every remaining row would crash or hang on the same
                # corpse — report them all as structured errors NOW and
                # stop touching the device
                backend_died = err
                for rest in rows[i + 1:]:
                    r = {"metric": rest, "value": 0.0, "unit": "",
                         "error": f"skipped: backend died in row "
                                  f"{row} ({err})"}
                    rows_out.append(r)
                    _emit(r)
                break
    gate_ok = True
    if args.gate:
        # the gate verdict rides INSIDE the aggregate (the driver keeps
        # only the last JSON line) as well as its own structured row
        gate_row, gate_ok = _gate_check(args.gate, rows_out)
        rows_out.append(gate_row)
        _emit(gate_row)
    _emit_aggregate(rows_out)
    if backend_died is not None:
        pm = _dump_bench_postmortem(RuntimeError(backend_died),
                                    reason="bench backend death mid-run")
        if pm:
            print(f"# postmortem: {pm}", file=sys.stderr)
        raise SystemExit(3)
    if args.baseline_out:
        _write_baseline(args.baseline_out, rows_out)
    if args.metrics_out:
        from bigdl_tpu.observability.registry import default_registry
        reg = default_registry()
        if args.metrics_out.endswith(".json"):
            reg.dump_json(args.metrics_out)
        else:
            with open(args.metrics_out, "w", encoding="utf-8") as f:
                f.write(reg.expose())
        print(f"# metrics registry written to {args.metrics_out}",
              file=sys.stderr)
    if not gate_ok:
        raise SystemExit(GATE_EXIT_CODE)
    if headline_failed:
        raise SystemExit(2)


if __name__ == "__main__":
    main()

"""Headline benchmark: Inception-v1 ImageNet training throughput per chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} with
the roofline context (achieved TFLOP/s and MFU) alongside images/sec.

Mirrors the reference's synthetic-data perf harness
(models/utils/DistriOptimizerPerf.scala:33-70 / LocalOptimizerPerf.scala —
inception_v1, random input, records/second averaged over timed iterations).

Baseline derivation (BASELINE.md): the reference publishes NO quantitative
table; its README claims single-node Xeon training "comparable with
mainstream GPU" (README.md:9). A mainstream 2016 GPU (K80-class) trains
Inception-v1 at ~150 images/sec, so 150 img/s/device is the documented
stand-in baseline; ``vs_baseline`` = value / 150.

Roofline (measured on TPU v5e, batch 128, see docs/PERF.md): the step is
HBM-bandwidth-bound, not FLOP-bound — XLA counts ~8.9 GFLOP/image
(fwd+bwd+update), which at v5e's 197 TFLOP/s bf16 peak would take ~6 ms,
but the step moves ~19 GB of HBM traffic (measured down from 29 GB via the
bf16 activation policy and the Pallas LRN kernel), bounding the step at
~23 ms at the 819 GB/s spec. MFU is reported so the
gap stays honest.
"""
from __future__ import annotations

import json
import time

import numpy as np

BASELINE_IMG_PER_SEC = 150.0
BATCH = 256
WARMUP = 3
ITERS = 30

# bf16 peak TFLOP/s per chip by device kind substring
_PEAK_TFLOPS = {
    "v5 lite": 197.0, "v5e": 197.0,
    "v4": 275.0, "v5p": 459.0, "v5": 459.0,
    "v6 lite": 918.0, "v6e": 918.0,
}


def _chip_peak_tflops() -> float | None:
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for key, peak in _PEAK_TFLOPS.items():
        if key in kind:
            return peak
    return None


def main():
    import jax
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.models import Inception_v1_NoAuxClassifier
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.tensor import DTypePolicy, set_policy

    # f32 params, bf16 MXU compute, bf16 activations in HBM — the TPU
    # equivalent of the reference's FP16-on-the-wire + f32 math split
    # (SURVEY §5.8), extended to the memory system because the step is
    # bandwidth-bound (docs/PERF.md)
    set_policy(DTypePolicy(param_dtype=jnp.float32,
                           compute_dtype=jnp.bfloat16,
                           activation_dtype=jnp.bfloat16))

    model = Inception_v1_NoAuxClassifier(1000)
    model.materialize(jax.random.PRNGKey(0))
    model.training()
    criterion = nn.ClassNLLCriterion()
    optim = SGD(learning_rate=0.0898, momentum=0.9)

    params, mstate = model.params, model.state
    opt_state = optim.init_state(params)

    def train_step(params, mstate, opt_state, rng, data, labels):
        def loss_fn(p):
            y, new_state = model.apply(p, mstate, data, training=True,
                                       rng=rng)
            return criterion.apply(y, labels), new_state

        (loss, new_mstate), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_params, new_opt_state = optim.update(grads, params, opt_state)
        return new_params, new_mstate, new_opt_state, loss

    jit_step = jax.jit(train_step, donate_argnums=(0, 1, 2))

    rng = jax.random.PRNGKey(0)
    host = np.random.default_rng(0)
    data = jnp.asarray(host.standard_normal((BATCH, 3, 224, 224), np.float32))
    labels = jnp.asarray(host.integers(1, 1001, size=(BATCH,)))  # 1-based

    # AOT-compile once; the executable serves both XLA's FLOP count and
    # the timed loop (avoids any chance of a second trace/compile)
    compiled = jit_step.lower(params, mstate, opt_state, rng, data,
                              labels).compile()
    cost = compiled.cost_analysis()
    step_flops = float(cost.get("flops", 0.0)) if cost else 0.0

    for _ in range(WARMUP):
        rng, k = jax.random.split(rng)
        params, mstate, opt_state, loss = compiled(params, mstate, opt_state,
                                                   k, data, labels)
    float(loss)  # block_until_ready is a no-op through the axon tunnel

    t0 = time.perf_counter()
    for _ in range(ITERS):
        rng, k = jax.random.split(rng)
        params, mstate, opt_state, loss = compiled(params, mstate, opt_state,
                                                   k, data, labels)
    float(loss)  # force a real device sync before stopping the clock
    dt = time.perf_counter() - t0

    value = BATCH * ITERS / dt
    achieved_tflops = step_flops * ITERS / dt / 1e12
    peak = _chip_peak_tflops()
    out = {
        "metric": "inception_v1_train_images_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        # The reference publishes no quantitative number; 150 img/s is a
        # documented K80-class stand-in (see module docstring). MFU and
        # achieved_tflops are the honest readout.
        "vs_baseline": round(value / BASELINE_IMG_PER_SEC, 3),
        "baseline_is_standin": True,
        "achieved_tflops": round(achieved_tflops, 1),
    }
    if peak:
        out["mfu"] = round(achieved_tflops / peak, 3)
        out["chip_peak_tflops_bf16"] = peak
    print(json.dumps(out))


if __name__ == "__main__":
    main()

from bigdl_tpu.models.transformer.generate import (GenerationConfig,
                                                    beam_search, generate)
from bigdl_tpu.models.transformer.model import (TransformerBlock,
                                                TransformerLM)

__all__ = ["TransformerBlock", "TransformerLM", "GenerationConfig",
           "generate", "beam_search"]

from bigdl_tpu.models.transformer.model import (TransformerBlock,
                                                TransformerLM)

__all__ = ["TransformerLM", "TransformerBlock"]

"""Autoregressive decoding for ``TransformerLM`` with a static KV cache.

TPU-native inference loop: the cache is a pre-allocated (L, B, max_len,
H, Dh) pair of arrays, the decode loop is a ``lax.scan`` over token
positions (one compiled program regardless of length), and every shape
is static — nothing retraces as the sequence grows. The reference has no
generation story (its RNN era predates it, SURVEY §5.7); this completes
the transformer family's API the way Test CLIs complete the conv
families'.

Implementation note: modules are pure init/apply, so the decode path
reuses the model's *param tree* directly (embed / blocks / final norm /
lm head, keyed by their Sequential positions) rather than threading a
cache through module classes — the module graph stays inference-free and
the cache layout stays an implementation detail of this file. The tree
layout is pinned by tests/test_generate.py's greedy-parity test: any
change to TransformerLM's structure that breaks these paths fails
loudly there.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from bigdl_tpu.tensor import activation_dtype, compute_dtype

__all__ = ["generate", "beam_search", "GenerationConfig"]


class GenerationConfig:
    """Decode knobs: temperature 0 = greedy; top_k limits the softmax
    support; max_new_tokens is a static bound (one compile per value)."""

    def __init__(self, max_new_tokens: int = 32, temperature: float = 0.0,
                 top_k: int | None = None):
        if top_k is not None and top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k


def _split_heads(x, num_heads):
    b, s, e = x.shape
    return x.reshape(b, s, num_heads, e // num_heads)


def _ln(p, x, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["weight"] + p["bias"]).astype(x.dtype)


def _proj(p, name, x):
    # mirrors MultiHeadAttention._proj: compute-dtype operands/output
    y = jnp.matmul(x.astype(compute_dtype()),
                   p[f"{name}_weight"].astype(compute_dtype()).T)
    if f"{name}_bias" in p:
        y = y + p[f"{name}_bias"].astype(compute_dtype())
    return y


def _linear(p, x):
    # mirrors nn.Linear.apply's dtype path
    y = jnp.matmul(x.astype(compute_dtype()),
                   p["weight"].astype(compute_dtype()).T)
    y = y + p["bias"].astype(compute_dtype())
    return y.astype(activation_dtype())


def _ffn(p, x):
    return _linear(p["2"], jax.nn.relu(_linear(p["0"], x)))


def _block_step(bp, x, ck, cv, pos, num_heads, max_len, rope=False,
                num_kv_heads=None):
    """One TransformerBlock on a (B, T) slice ending at absolute position
    ``pos`` (T==1 decode or T==P prefill with pos==P-1). Returns output
    and the updated (ck, cv) cache for this layer.

    Param paths (TransformerBlock): bp["0"] = _Residual(LN, MHA),
    bp["1"] = _Residual(LN, FFN-Sequential).

    ``rope=True`` rotates q/k at their absolute positions before caching
    — a key's rotation is fixed at its own position, so the cache holds
    rotated keys and decode steps never re-rotate history.
    """
    mha_p = bp["0"]["1"]
    kv = num_kv_heads or num_heads
    h = _ln(bp["0"]["0"], x)
    d = h.shape[-1]
    scale = (d // num_heads) ** -0.5
    q = _split_heads(_proj(mha_p, "q", h), num_heads)
    k = _split_heads(_proj(mha_p, "k", h), kv)
    v = _split_heads(_proj(mha_p, "v", h), kv)
    t = x.shape[1]
    start = pos - (t - 1)
    if rope:
        from bigdl_tpu.nn.attention import apply_rope
        positions = start + jnp.arange(t)
        q = apply_rope(q, positions)
        k = apply_rope(k, positions)
    ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                      (0, start, 0, 0))
    cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                      (0, start, 0, 0))
    # each query row i (absolute position start+i) sees cache <= start+i.
    # Operands stay in the cache dtype with f32 ACCUMULATION — an
    # .astype(f32) on the cache materialized a full f32 copy of the
    # static (B, max_len, H, Dh) buffers per layer per step, which is
    # what made batch-128 decode REGRESS below batch 64 (2 GB of
    # converts/step at B=128; round 3, docs/PERF.md)
    upto = start + jnp.arange(t)
    # one grouped path (g == 1 IS plain MHA: the (kv, g) reshape is
    # free): the cache stays at kv heads — the GQA memory/bandwidth win
    # — and queries group as (B, T, kv, G, D) so no repeated kv ever
    # materializes. Operands stay in the cache dtype with f32
    # ACCUMULATION (see the note above).
    g = num_heads // kv
    b_, hd = x.shape[0], q.shape[-1]
    qg = q.reshape(b_, t, kv, g, hd)
    s = jnp.einsum("btkgd,bmkd->bkgtm", qg.astype(ck.dtype), ck,
                   preferred_element_type=jnp.float32) * scale
    kpos = jnp.arange(max_len)[None, None, None, None, :]
    s = jnp.where(kpos > upto[None, None, None, :, None], -1e9, s)
    o = jnp.einsum("bkgtm,bmkd->btkgd",
                   jax.nn.softmax(s, axis=-1).astype(cv.dtype), cv,
                   preferred_element_type=jnp.float32)
    o = o.reshape(b_, t, num_heads, hd).astype(x.dtype)
    o = _proj(mha_p, "out",
              o.reshape(x.shape)).astype(activation_dtype())
    x = x + o
    x = x + _ffn(bp["1"]["1"], _ln(bp["1"]["0"], x))
    return x, ck, cv


def _model_parts(params, num_layers):
    """Sequential positions: 0 embed, 1..L blocks, L+1 final LN,
    L+2 lm head (L+3 LogSoftMax is parameterless)."""
    embed = params["0"]
    blocks = [params[str(1 + i)] for i in range(num_layers)]
    norm = params[str(num_layers + 1)]
    head = params[str(num_layers + 2)]
    return embed, blocks, norm, head


def _embed(ep, tokens, start):
    idx = tokens.astype(jnp.int32) - 1        # 1-based ids
    vocab = ep["tok"].shape[0]
    y = jnp.take(ep["tok"], jnp.clip(idx, 0, vocab - 1), axis=0)
    if "pos" in ep:        # learned positions; absent under RoPE
        y = y + jax.lax.dynamic_slice_in_dim(
            ep["pos"], start, tokens.shape[1], axis=0)
    return y


def _logits(params, num_layers, x):
    _, _, norm, head = _model_parts(params, num_layers)
    return _linear(head, _ln(norm, x[:, -1]))


def _prefill(params, prompt, num_layers, num_heads, max_len,
             rope=False, num_kv_heads=None):
    """Cache allocation + prompt prefill. Returns (ck, cv, x, pos0)."""
    embed, blocks, _, _ = _model_parts(params, num_layers)
    head_dim = embed["tok"].shape[1] // num_heads
    dtype = activation_dtype()
    b = prompt.shape[0]
    # per-layer cache TUPLES, not one stacked (L, ...) array: each layer's
    # cache is then its own scan-carry leaf, which XLA updates in place —
    # the stacked form's .at[li].set forced whole-cache copies per step
    # (measured: batch-64 decode 212 -> 4.06 ms/step)
    kv = num_kv_heads or num_heads
    zero = lambda: jnp.zeros((b, max_len, kv, head_dim), dtype)
    ck, cv = [], []
    x = _embed(embed, prompt, 0).astype(dtype)
    pos0 = prompt.shape[1] - 1
    for li in range(num_layers):
        x, k_l, v_l = _block_step(blocks[li], x, zero(), zero(),
                                  jnp.asarray(pos0), num_heads, max_len,
                                  rope, num_kv_heads)
        ck.append(k_l)
        cv.append(v_l)
    return tuple(ck), tuple(cv), x, pos0


def _decode_setup(model, prompt, n_new, params):
    """Shared eager preamble for generate/beam_search: meta + length
    validation and the dtype-policy jit-cache key (the compiled program
    bakes in the policy at trace time — keying on it makes set_policy()
    between calls retrace instead of silently reusing stale-dtype
    executables)."""
    params = model.params if params is None else params
    meta = getattr(model, "lm_meta", None)
    if meta is None:
        raise ValueError("model has no lm_meta — build it with "
                         "TransformerLM(...) to generate")
    prompt = jnp.asarray(prompt)
    if prompt.shape[1] + n_new > meta["max_len"]:
        raise ValueError(f"prompt {prompt.shape[1]} + new {n_new} exceeds "
                         f"the model's max_len {meta['max_len']}")
    policy_key = (str(activation_dtype()), str(compute_dtype()))
    return params, prompt, meta, policy_key


def _sample(logits, key, temperature, top_k):
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1) + 1          # back to 1-based
    logits = logits / temperature
    if top_k is not None:
        k_eff = min(top_k, logits.shape[-1])
        kth = jnp.sort(logits, axis=-1)[:, -k_eff][:, None]
        logits = jnp.where(logits < kth, -1e9, logits)
    return jax.random.categorical(key, logits, axis=-1) + 1


@functools.partial(jax.jit, static_argnames=(
    "num_layers", "num_heads", "max_len", "n_new", "temperature",
    "top_k", "policy_key", "rope", "num_kv_heads"))
def _generate_impl(params, prompt, rng, *, num_layers, num_heads,
                   max_len, n_new, temperature, top_k, policy_key,
                   rope=False, num_kv_heads=None):
    """The whole prefill+decode program as ONE module-level jitted
    function: repeated ``generate`` calls with the same shapes/config hit
    the jit cache instead of re-tracing a per-call closure (which
    recompiled the scan on every call — the dominant cost of the round-2
    decode numbers when used as an API rather than a one-shot)."""
    embed, blocks, _, _ = _model_parts(params, num_layers)
    dtype = activation_dtype()
    ck, cv, x, pos = _prefill(params, prompt, num_layers, num_heads,
                              max_len, rope, num_kv_heads)
    logits = _logits(params, num_layers, x)
    rng, key0 = jax.random.split(rng)
    first = _sample(logits, key0, temperature, top_k)

    # ---- decode: lax.scan over the remaining n_new - 1 positions ------
    def step(carry, key):
        tok, ck, cv, pos = carry
        x = _embed(embed, tok[:, None], pos + 1).astype(dtype)
        new_ck, new_cv = list(ck), list(cv)
        for li in range(num_layers):
            x, new_ck[li], new_cv[li] = _block_step(
                blocks[li], x, ck[li], cv[li], pos + 1, num_heads,
                max_len, rope, num_kv_heads)
        logits = _logits(params, num_layers, x)
        nxt = _sample(logits, key, temperature, top_k)
        return (nxt, tuple(new_ck), tuple(new_cv), pos + 1), nxt

    keys = jax.random.split(rng, max(n_new - 1, 1))
    (_, _, _, _), rest = jax.lax.scan(
        step, (first, ck, cv, jnp.asarray(pos)), keys[:n_new - 1])
    return jnp.concatenate([first[:, None], rest.T], axis=1)


def generate(model, prompt, config: GenerationConfig | None = None, *,
             rng=None, params=None):
    """Decode ``config.max_new_tokens`` tokens after ``prompt`` (B, P)
    1-based token ids. Returns (B, max_new_tokens) generated ids.

    ``model`` is a materialized ``TransformerLM`` (its ``num_layers``/
    ``num_heads``/``max_len`` attributes come from the builder); pass
    ``params`` to decode with externally-updated parameters. Activations
    and the KV cache follow the session dtype policy at first trace;
    repeated calls with the same prompt shape and config reuse the
    compiled program.
    """
    config = config or GenerationConfig()
    n_new = config.max_new_tokens
    params, prompt, meta, policy_key = _decode_setup(model, prompt,
                                                     n_new, params)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return _generate_impl(
        params, prompt, rng, num_layers=meta["num_layers"],
        num_heads=meta["num_heads"], max_len=meta["max_len"],
        n_new=n_new, temperature=config.temperature, top_k=config.top_k,
        policy_key=policy_key,
        rope=meta.get("pos_encoding", "learned") == "rope",
        num_kv_heads=meta.get("num_kv_heads"))


def beam_search(model, prompt, *, num_beams: int = 4,
                max_new_tokens: int = 32, length_penalty: float = 1.0,
                eos_id: int | None = None, params=None):
    """Length-normalized beam search with the same static KV cache.

    Returns ``(tokens, scores)``: (B, num_beams, max_new_tokens) 1-based
    ids and (B, num_beams) total log-probabilities divided by
    ``n_tokens ** length_penalty``, beams sorted best-first. Beams that
    emit ``eos_id`` freeze (their score stops accumulating; the eos
    position is part of the output).

    Beams fold into the batch dim (B*K rows) so every step is the same
    single-token cache step as ``generate``; each step's top-k reorders
    beam histories AND cache rows with one gather. Like ``generate``,
    the whole program is one module-level jitted function — repeated
    calls with the same shapes and knobs reuse the compiled executable.
    """
    params, prompt, meta, policy_key = _decode_setup(
        model, prompt, max_new_tokens, params)
    return _beam_search_impl(
        params, prompt, num_layers=meta["num_layers"],
        num_heads=meta["num_heads"], max_len=meta["max_len"],
        n_new=max_new_tokens, k=num_beams,
        length_penalty=length_penalty, eos_id=eos_id,
        policy_key=policy_key,
        rope=meta.get("pos_encoding", "learned") == "rope",
        num_kv_heads=meta.get("num_kv_heads"))


@functools.partial(jax.jit, static_argnames=(
    "num_layers", "num_heads", "max_len", "n_new", "k",
    "length_penalty", "eos_id", "policy_key", "rope", "num_kv_heads"))
def _beam_search_impl(params, prompt, *, num_layers, num_heads, max_len,
                      n_new, k, length_penalty, eos_id, policy_key,
                      rope=False, num_kv_heads=None):
    embed, blocks, _, _ = _model_parts(params, num_layers)
    dtype = activation_dtype()
    ck, cv, x, pos0 = _prefill(params, prompt, num_layers, num_heads,
                               max_len, rope, num_kv_heads)
    b = prompt.shape[0]
    logp0 = jax.nn.log_softmax(
        _logits(params, num_layers, x).astype(jnp.float32), axis=-1)

    # first expansion: top-k of the single distribution seeds the beams
    # (k > vocab: seed the extra beams at -inf; the next step's top-k
    # over k*vocab candidates never selects them)
    k0 = min(k, vocab := embed["tok"].shape[0])
    scores, tok0 = jax.lax.top_k(logp0, k0)           # (B, k0) each
    if k0 < k:
        scores = jnp.pad(scores, ((0, 0), (0, k - k0)),
                         constant_values=-jnp.inf)
        tok0 = jnp.pad(tok0, ((0, 0), (0, k - k0)))
    tok0 = tok0 + 1                                   # back to 1-based
    finished = (tok0 == eos_id) if eos_id is not None \
        else jnp.zeros((b, k), bool)
    lengths = jnp.ones((b, k), jnp.float32)   # real tokens incl. eos
    history = jnp.zeros((b, k, n_new), jnp.int32)
    history = history.at[:, :, 0].set(tok0)

    # beams share the prompt cache: tile rows to (B*K, M, H, Dh)
    ck = tuple(jnp.repeat(c, k, axis=0) for c in ck)
    cv = tuple(jnp.repeat(c, k, axis=0) for c in cv)
    batch_offset = (jnp.arange(b) * k)[:, None]       # (B, 1)

    def step(carry, i):
        tok, ck, cv, scores, finished, lengths, history = carry
        # the token fed was produced at step i-1: absolute position
        # p_len + i - 1 = pos0 + i
        pos = pos0 + i
        x = _embed(embed, tok.reshape(b * k, 1), pos).astype(dtype)
        new_ck, new_cv = list(ck), list(cv)
        for li in range(num_layers):
            x, new_ck[li], new_cv[li] = _block_step(
                blocks[li], x, ck[li], cv[li], pos, num_heads, max_len,
                rope, num_kv_heads)
        logp = jax.nn.log_softmax(
            _logits(params, num_layers, x).astype(jnp.float32), axis=-1)
        logp = logp.reshape(b, k, vocab)
        # frozen beams contribute exactly one continuation (token 1,
        # score unchanged) so they occupy one top-k slot, not V
        frozen = jnp.full((vocab,), -jnp.inf).at[0].set(0.0)
        logp = jnp.where(finished[..., None], frozen[None, None], logp)
        cand = (scores[..., None] + logp).reshape(b, k * vocab)
        # prune in NORMALIZED space (GNMT-style): a finished hypothesis
        # competes at its own length, so length_penalty can keep a short
        # eos'd beam alive against longer raw-score continuations
        cand_len = jnp.where(finished, lengths, lengths + 1.0)
        norm_cand = (cand.reshape(b, k, vocab)
                     / (cand_len ** length_penalty)[..., None]
                     ).reshape(b, k * vocab)
        _, flat = jax.lax.top_k(norm_cand, k)         # (B, K)
        scores = jnp.take_along_axis(cand, flat, axis=1)
        beam_idx = flat // vocab                      # (B, K) source beam
        tok_new = flat % vocab + 1                    # 1-based
        # reorder histories and caches to the chosen source beams
        history = jnp.take_along_axis(history, beam_idx[..., None],
                                      axis=1)
        finished = jnp.take_along_axis(finished, beam_idx, axis=1)
        lengths = jnp.take_along_axis(lengths, beam_idx, axis=1)
        # frozen beams emit padding id 0, not a real token
        history = history.at[:, :, i].set(
            jnp.where(finished, 0, tok_new))
        lengths = lengths + jnp.where(finished, 0.0, 1.0)
        if eos_id is not None:
            finished = finished | (tok_new == eos_id)
        rows = (batch_offset + beam_idx).reshape(-1)  # (B*K,)
        new_ck = tuple(c[rows] for c in new_ck)
        new_cv = tuple(c[rows] for c in new_cv)
        return (tok_new, new_ck, new_cv, scores, finished, lengths,
                history), None

    if n_new > 1:
        (tok, ck, cv, scores, finished, lengths, history), _ = \
            jax.lax.scan(step, (tok0, ck, cv, scores, finished, lengths,
                                history), jnp.arange(1, n_new))
    # normalize by each beam's ACTUAL emitted length (eos-frozen beams
    # stop growing), so length_penalty genuinely reorders beams
    norm = scores / (lengths ** length_penalty)
    order = jnp.argsort(-norm, axis=1)
    return (jnp.take_along_axis(history, order[..., None], axis=1),
            jnp.take_along_axis(norm, order, axis=1))

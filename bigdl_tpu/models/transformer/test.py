"""Transformer LM evaluation main (the rnn Test.scala counterpart):
loads a snapshot, evaluates per-token loss / perplexity on a text file.

Run: ``python -m bigdl_tpu.models.transformer.test -f <dir> --model <snap>``.
"""
from __future__ import annotations

import math

from bigdl_tpu.models.utils.cli import (base_test_parser, init_engine,
                                        setup_logging)


def main(argv=None):
    setup_logging()
    parser = base_test_parser("Evaluate a Transformer LM")
    parser.add_argument("--vocabSize", type=int, default=4000)
    parser.add_argument("--seqLength", type=int, default=128)
    args = parser.parse_args(argv)
    mesh = init_engine(getattr(args, "chips", None))

    from bigdl_tpu import nn
    from bigdl_tpu.models.utils.text_lm import build_text_lm_datasets
    from bigdl_tpu.optim import Loss
    from bigdl_tpu.optim.validator import LocalValidator
    from bigdl_tpu.utils import file as bfile

    _, val_set, _, _ = build_text_lm_datasets(
        args.folder, args.vocabSize, args.seqLength, args.batchSize,
        one_hot=False)
    model = bfile.load_module(args.model)
    # snapshots may end at log-probs (with_log_softmax=True) or raw
    # logits (the train main's memory-lean recipe) — same mean loss
    # either way; both criterions flatten (B, S, V) themselves, no
    # TimeDistributed vmap needed (docs/PERF.md round 3)
    if isinstance(model.modules[-1], nn.LogSoftMax):
        criterion = nn.ClassNLLCriterion()
    else:
        criterion = nn.CrossEntropyCriterion()
    validator = LocalValidator(model, val_set)
    results = validator.test([Loss(criterion)])
    for result, method in results:
        print(f"{type(method).__name__} is {result}")
        mean_loss = result.result()[0]
        print(f"perplexity is {math.exp(min(mean_loss, 20.0)):.3f}")


if __name__ == "__main__":
    main()

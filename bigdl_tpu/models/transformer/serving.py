"""Serving-depth decode paths (VERDICT r4 item 6 — beyond reference).

Three building blocks on top of ``generate.py``'s static-cache decode:

- **Ragged batches** (``generate_ragged``): one compiled program decodes a
  batch of prompts with DIFFERENT lengths. Prompts are right-padded to the
  batch max; each row carries its own absolute position, cache writes are
  per-row scatters, and attention masks per-row — so no retrace per length
  mix and no cross-row leakage (pinned against per-row ``generate`` in
  tests/test_serving.py).
- **Paged KV cache** (``PagedKVCache``): a vLLM-style block-table pool —
  (num_pages, page_size, kv_heads, head_dim) physical pages shared by all
  sequences, a (B, pages_per_seq) logical->physical table per row, and
  alloc/free for continuous batching. All shapes static; reads gather
  pages per row, writes scatter one slot. The TPU story is memory: a
  mixed-length batch holds pages for its ACTUAL lengths instead of
  B x max_len dense rows.
- **Speculative decoding** (``speculative_generate``): greedy
  draft-and-verify — a small draft model proposes ``gamma`` tokens, the
  target scores all of them in ONE parallel forward (the same T>1 cache
  step prefill uses), and the longest agreeing prefix (+1 correction
  token from the target) is accepted. Greedy acceptance is exact: output
  is BITWISE the target model's own greedy decode, only cheaper per
  token. Per-row accept counts ride the ragged machinery (rows advance
  at different rates). Reports the measured acceptance rate.

The reference has no serving story at all (SURVEY §5.7: its RNN era
predates LLM inference); this file is where the perf frontier of the
GQA/MQA decode path (BASELINE.md round-4: 190k tok/s) moves next.
"""
from __future__ import annotations

import functools
import logging
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models.transformer.generate import (
    GenerationConfig, _embed, _ffn, _linear, _ln, _logits, _model_parts,
    _proj, _sample, _split_heads)
from bigdl_tpu.observability import compile_watch as _compile_watch
from bigdl_tpu.observability import trace
from bigdl_tpu.observability.registry import default_registry
from bigdl_tpu.tensor import activation_dtype, compute_dtype

__all__ = ["generate_ragged", "PagedKVCache", "paged_prefill",
           "paged_suffix_prefill", "paged_decode",
           "paged_decode_step_stats", "decode_hbm_probe",
           "speculative_generate", "ContinuousBatcher", "KVSnapshot",
           "PAGED_KERNEL_ENV", "PagedStepCompilers"]


def _rope_rows(x, positions, theta: float = 10000.0):
    """Rotary embedding with PER-ROW positions: ``x`` (B, T, H, D),
    ``positions`` (B, T) absolute token positions (rows of a ragged batch
    sit at different offsets). Same split-half convention and f32 angle
    math as ``nn.attention.apply_rope``."""
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[..., None] * freqs   # (B, T, hf)
    cos = jnp.cos(ang)[:, :, None, :].astype(x.dtype)        # (B,T,1,hf)
    sin = jnp.sin(ang)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x2 * cos + x1 * sin], axis=-1)


def _qkv(bp, x, num_heads, num_kv_heads):
    """LN + q/k/v projections split to heads (shared by the ragged and
    paged steps)."""
    mha_p = bp["0"]["1"]
    kv = num_kv_heads or num_heads
    h = _ln(bp["0"]["0"], x)
    q = _split_heads(_proj(mha_p, "q", h), num_heads)
    k = _split_heads(_proj(mha_p, "k", h), kv)
    v = _split_heads(_proj(mha_p, "v", h), kv)
    return q, k, v


def _attend_grouped(q, ck, cv, upto, num_heads, scale):
    """Grouped causal attention of q (B,T,H,D) against a cached view
    (B, M, KV, D), masked to key positions <= ``upto`` (B, T) per row.
    Cache-dtype operands, f32 accumulation (docs/PERF.md)."""
    b, t, _, hd = q.shape
    kv = ck.shape[2]
    g = num_heads // kv
    qg = q.reshape(b, t, kv, g, hd)
    s = jnp.einsum("btkgd,bmkd->bkgtm", qg.astype(ck.dtype), ck,
                   preferred_element_type=jnp.float32) * scale
    kpos = jnp.arange(ck.shape[1])[None, None, None, None, :]
    s = jnp.where(kpos > upto[:, None, None, :, None], -1e9, s)
    o = jnp.einsum("bkgtm,bmkd->btkgd",
                   jax.nn.softmax(s, axis=-1).astype(cv.dtype), cv,
                   preferred_element_type=jnp.float32)
    return o.reshape(b, t, num_heads, hd)


def _ragged_block_step(bp, x, ck, cv, pos, num_heads, max_len,
                       rope=False, num_kv_heads=None,
                       paged_kernel=None):
    """One TransformerBlock on a (B, T, E) slice whose LAST column sits at
    per-row absolute position ``pos`` (B,). T==1 decode or T==gamma+1
    speculative verify. Cache writes are per-row scatters; attention
    masks per-row. ``paged_kernel`` in ("pallas", "interpret") routes
    the attention through the Pallas page-walk kernel, viewing the
    dense (B, M, KV, D) cache as contiguous pages (free reshape) so
    short rows skip their empty tail — the speculative path's half of
    the decode-kernel switch. Returns (x, ck, cv)."""
    b, t, e = x.shape
    scale = (e // num_heads) ** -0.5
    q, k, v = _qkv(bp, x, num_heads, num_kv_heads)
    # column j sits at per-row position pos - (T-1) + j
    cols = pos[:, None] - (t - 1) + jnp.arange(t)[None, :]      # (B, T)
    if rope:
        q = _rope_rows(q, cols)
        k = _rope_rows(k, cols)
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], (b, t))
    ck = ck.at[rows, cols].set(k.astype(ck.dtype), mode="drop")
    cv = cv.at[rows, cols].set(v.astype(cv.dtype), mode="drop")
    if paged_kernel in ("pallas", "interpret"):
        from bigdl_tpu.ops.pallas.paged_attention import \
            dense_cache_attention
        o = dense_cache_attention(q, ck, cv, pos - (t - 1), scale=scale,
                                  interpret=(paged_kernel == "interpret"))
    else:
        o = _attend_grouped(q, ck, cv, cols, num_heads, scale)
    o = o.reshape(b, t, e).astype(x.dtype)
    x = x + _proj(bp["0"]["1"], "out", o).astype(activation_dtype())
    x = x + _ffn(bp["1"]["1"], _ln(bp["1"]["0"], x))
    return x, ck, cv


def _embed_rows(ep, tokens, cols):
    """Token+position embedding with per-row positions ``cols`` (B, T)."""
    idx = tokens.astype(jnp.int32) - 1
    vocab = ep["tok"].shape[0]
    y = jnp.take(ep["tok"], jnp.clip(idx, 0, vocab - 1), axis=0)
    if "pos" in ep:          # learned positions; absent under RoPE
        y = y + jnp.take(ep["pos"], jnp.clip(cols, 0, ep["pos"].shape[0]
                                             - 1), axis=0)
    return y


def _row_logits(params, num_layers, x, col):
    """LM-head logits of per-row column ``col`` (B,) of x (B, T, E)."""
    _, _, norm, head = _model_parts(params, num_layers)
    b = x.shape[0]
    last = x[jnp.arange(b), col]
    return _linear(head, _ln(norm, last))


def _ragged_prefill(params, prompt, num_layers, num_heads,
                    max_len, rope, num_kv_heads):
    """Right-padded (B, Pmax) prompt -> caches + per-row last position.

    Padding columns (j >= lengths[i]) write junk cache slots, but decode
    overwrites slot ``lengths[i]`` first and masks everything beyond the
    per-row position, so the junk is never read (test-pinned)."""
    embed, blocks, _, _ = _model_parts(params, num_layers)
    head_dim = embed["tok"].shape[1] // num_heads
    dtype = activation_dtype()
    b, pmax = prompt.shape
    kv = num_kv_heads or num_heads
    x = _embed(embed, prompt, 0).astype(dtype)
    # prefill positions are row-uniform (0..Pmax-1): padding rows' junk is
    # overwritten/masked later, so the shared-position fast path is safe
    pos_last = jnp.full((b,), pmax - 1, jnp.int32)
    ck, cv = [], []
    for li in range(num_layers):
        c_k = jnp.zeros((b, max_len, kv, head_dim), dtype)
        c_v = jnp.zeros((b, max_len, kv, head_dim), dtype)
        x, c_k, c_v = _ragged_block_step(blocks[li], x, c_k, c_v,
                                         pos_last, num_heads, max_len,
                                         rope, num_kv_heads)
        ck.append(c_k)
        cv.append(c_v)
    return tuple(ck), tuple(cv), x


@functools.partial(jax.jit, static_argnames=(
    "num_layers", "num_heads", "max_len", "n_new", "temperature",
    "top_k", "policy_key", "rope", "num_kv_heads"))
def _generate_ragged_impl(params, prompt, lengths, rng, *, num_layers,
                          num_heads, max_len, n_new, temperature, top_k,
                          policy_key, rope=False, num_kv_heads=None):
    embed, blocks, _, _ = _model_parts(params, num_layers)
    dtype = activation_dtype()
    ck, cv, x = _ragged_prefill(params, prompt, num_layers,
                                num_heads, max_len, rope, num_kv_heads)
    logits = _row_logits(params, num_layers, x, lengths - 1)
    rng, key0 = jax.random.split(rng)
    first = _sample(logits, key0, temperature, top_k)
    pos0 = lengths - 1                                    # (B,)

    def step(carry, key):
        tok, ck, cv, pos = carry                          # pos (B,)
        cols = (pos + 1)[:, None]
        x = _embed_rows(embed, tok[:, None], cols).astype(dtype)
        new_ck, new_cv = list(ck), list(cv)
        for li in range(num_layers):
            x, new_ck[li], new_cv[li] = _ragged_block_step(
                blocks[li], x, ck[li], cv[li], pos + 1, num_heads,
                max_len, rope, num_kv_heads)
        logits = _row_logits(params, num_layers, x,
                             jnp.zeros_like(pos))
        nxt = _sample(logits, key, temperature, top_k)
        return (nxt, tuple(new_ck), tuple(new_cv), pos + 1), nxt

    keys = jax.random.split(rng, max(n_new - 1, 1))
    (_, _, _, _), rest = jax.lax.scan(
        step, (first, ck, cv, pos0), keys[:n_new - 1])
    return jnp.concatenate([first[:, None], rest.T], axis=1)


def generate_ragged(model, prompts, config: GenerationConfig | None = None,
                    *, rng=None, params=None):
    """Decode a MIXED-LENGTH batch in one compiled program.

    ``prompts``: list of 1-based id sequences (or a (B, Pmax) array +
    right-padding with any id, in which case pass per-row ``lengths`` via
    a (B, Pmax) array attribute is not needed — lists carry lengths).
    Returns (B, max_new_tokens) ids; row i's continuation is identical to
    ``generate(model, prompts[i:i+1])`` (pinned by tests/test_serving.py).
    """
    config = config or GenerationConfig()
    lengths = np.asarray([len(p) for p in prompts], np.int32)
    pmax = int(lengths.max())
    batch = np.zeros((len(prompts), pmax), np.int32)
    for i, p in enumerate(prompts):
        batch[i, :len(p)] = np.asarray(p, np.int32)
        batch[i, len(p):] = 1                    # in-vocab padding id
    params = model.params if params is None else params
    meta = model.lm_meta
    if pmax + config.max_new_tokens > meta["max_len"]:
        raise ValueError(f"longest prompt {pmax} + new "
                         f"{config.max_new_tokens} exceeds max_len "
                         f"{meta['max_len']}")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    policy_key = (str(activation_dtype()), str(compute_dtype()))
    return _generate_ragged_impl(
        params, jnp.asarray(batch), jnp.asarray(lengths), rng,
        num_layers=meta["num_layers"], num_heads=meta["num_heads"],
        max_len=meta["max_len"], n_new=config.max_new_tokens,
        temperature=config.temperature, top_k=config.top_k,
        policy_key=policy_key,
        rope=meta.get("pos_encoding", "learned") == "rope",
        num_kv_heads=meta.get("num_kv_heads"))


# ---------------------------------------------------------------------------
# Paged KV cache
# ---------------------------------------------------------------------------

class PagedKVCache:
    """Block-table KV pool for continuous batching (vLLM-style, TPU-
    static).

    Physical storage: per layer, (num_pages, page_size, kv_heads,
    head_dim) k/v pools shared by ALL sequences. Logical view: each row
    owns ``pages_per_seq`` table slots mapping logical page -> physical
    page. ``alloc``/``free`` manage the pool host-side between decode
    bursts (admission control); the decode step itself is fully
    compiled.

    Memory: a 100-row batch whose rows average 1/8 of max_len holds
    ~1/8 of the dense cache's HBM. Throughput: reads gather pages per
    row — on TPU the gather is an XLA dynamic-gather over the pool;
    for peak decode rate at uniform lengths the dense cache stays the
    faster path (documented trade-off, bench row reports both).
    """

    def __init__(self, num_layers, num_pages, page_size, kv_heads,
                 head_dim, dtype=None):
        dtype = dtype or activation_dtype()
        self.num_pages, self.page_size = num_pages, page_size
        self.kv_heads, self.head_dim = kv_heads, head_dim
        self.num_layers = num_layers
        shape = (num_pages, page_size, kv_heads, head_dim)
        self.kp = tuple(jnp.zeros(shape, dtype) for _ in range(num_layers))
        self.vp = tuple(jnp.zeros(shape, dtype) for _ in range(num_layers))
        self._free = list(range(num_pages - 1, -1, -1))   # host-side stack

    def alloc(self, n_tokens: int) -> list[int]:
        """Reserve enough physical pages for ``n_tokens`` more tokens."""
        n = -(-n_tokens // self.page_size)
        if n > len(self._free):
            raise RuntimeError(f"paged cache exhausted: want {n} pages, "
                               f"{len(self._free)} free")
        return [self._free.pop() for _ in range(n)]

    def free(self, pages) -> None:
        """Return a finished sequence's pages to the pool."""
        self._free.extend(int(p) for p in pages)

    @property
    def pages_free(self) -> int:
        return len(self._free)


def _paged_view(pool, table):
    """(num_pages, S, KV, D) pool + (B, P) table -> (B, P*S, KV, D)
    gathered per-row cache view (the logical dense cache). The
    FALLBACK consumption of the pool: an O(B*P*S*KV*D) HBM
    materialization per call — the Pallas paged kernel
    (ops/pallas/paged_attention.py) replaces it on the decode hot
    path; this stays as the off-TPU / explicitly-requested dense
    path."""
    b, p = table.shape
    g = pool[table.reshape(-1)]                  # (B*P, S, KV, D)
    s, kv, d = pool.shape[1:]
    return g.reshape(b, p * s, kv, d)


#: env override for the decode-kernel switch: "dense" | "pallas" |
#: "interpret" | "auto" (auto = Pallas on TPU when the geometry is
#: supported, dense-view otherwise)
PAGED_KERNEL_ENV = "BIGDL_TPU_PAGED_KERNEL"

_PAGED_KERNEL_MODES = ("auto", "dense", "pallas", "interpret")


def _resolve_paged_kernel(mode, supported) -> str:
    """Host-side resolution of the ``paged_kernel=`` switch to the
    static trace-time choice: ``None``/"auto" consults
    ``$BIGDL_TPU_PAGED_KERNEL`` then falls back to "pallas" iff
    ``supported()`` says the compiled kernel is legal here (TPU
    backend, tileable geometry), "dense" otherwise. Explicit modes are
    respected as given — "interpret" is the CPU parity path the tests
    pin."""
    if mode is None:
        mode = os.environ.get(PAGED_KERNEL_ENV) or "auto"
    if mode not in _PAGED_KERNEL_MODES:
        raise ValueError(f"paged_kernel must be one of "
                         f"{_PAGED_KERNEL_MODES}, got {mode!r}")
    if mode == "auto":
        return "pallas" if supported() else "dense"
    return mode


def _pool_kernel_supported(cache) -> bool:
    """auto-switch legality for this pool's geometry on the compiled
    TPU path (the interpret path has no constraints)."""
    from bigdl_tpu.ops.pallas.paged_attention import paged_supported
    return paged_supported(cache.head_dim, cache.page_size)


def _attend_paged(q, kp, vp, table, q_start, upto, num_heads, scale,
                  kernel: str):
    """One attention consumption of the page pool, switched: the
    Pallas kernel walks the block table page-by-page (no dense view);
    the dense path gathers ``_paged_view`` and reuses
    ``_attend_grouped``. Both return (B, T, H, D) f32."""
    if kernel in ("pallas", "interpret"):
        from bigdl_tpu.ops.pallas.paged_attention import paged_attention
        return paged_attention(q, kp, vp, table, q_start, scale=scale,
                               interpret=(kernel == "interpret"))
    ckv = _paged_view(kp, table)
    cvv = _paged_view(vp, table)
    return _attend_grouped(q, ckv, cvv, upto, num_heads, scale)


@functools.partial(jax.jit, donate_argnums=(1, 2), static_argnames=(
    "num_layers", "num_heads", "page_size", "policy_key", "rope",
    "num_kv_heads", "paged_kernel"))
def _paged_prefill_impl(params, kp, vp, table, prompt, lengths, *,
                        num_layers, num_heads, page_size, policy_key,
                        rope=False, num_kv_heads=None,
                        paged_kernel="dense"):
    """Prefill right-padded prompts (B, Pmax) INTO the page pool.

    Column j of row i writes physical slot (table[i, j//S], j%S); padding
    columns (j >= lengths[i]) scatter to an out-of-range page id and are
    dropped, so they can never corrupt pages the table maps for other
    rows. Returns (greedy first token (B,), kp, vp)."""
    embed, blocks, _, _ = _model_parts(params, num_layers)
    dtype = activation_dtype()
    b, pmax = prompt.shape
    num_pages = kp[0].shape[0]
    x = _embed(embed, prompt, 0).astype(dtype)
    cols = jnp.broadcast_to(jnp.arange(pmax)[None, :], (b, pmax))
    valid = cols < lengths[:, None]
    log_page = table[jnp.arange(b)[:, None], cols // page_size]
    phys = jnp.where(valid, log_page, num_pages)     # OOB -> drop
    slot = cols % page_size
    new_kp, new_vp = list(kp), list(vp)
    scale = (x.shape[-1] // num_heads) ** -0.5
    for li in range(num_layers):
        q, k, v = _qkv(blocks[li], x, num_heads, num_kv_heads)
        if rope:
            q = _rope_rows(q, cols)
            k = _rope_rows(k, cols)
        new_kp[li] = new_kp[li].at[phys, slot].set(
            k.astype(kp[li].dtype), mode="drop")
        new_vp[li] = new_vp[li].at[phys, slot].set(
            v.astype(vp[li].dtype), mode="drop")
        # prefill query columns are row-uniform (0..Pmax-1), so the
        # kernel's q_start is zero for every row; padding columns
        # produce junk either way (never read — see docstring)
        o = _attend_paged(q, new_kp[li], new_vp[li], table,
                          jnp.zeros((b,), jnp.int32), cols, num_heads,
                          scale, paged_kernel)
        o = o.reshape(x.shape).astype(x.dtype)
        x = x + _proj(blocks[li]["0"]["1"], "out",
                      o).astype(activation_dtype())
        x = x + _ffn(blocks[li]["1"]["1"], _ln(blocks[li]["1"]["0"], x))
    logits = _row_logits(params, num_layers, x, lengths - 1)
    first = jnp.argmax(logits.astype(jnp.float32), axis=-1) + 1
    return first, tuple(new_kp), tuple(new_vp)


def paged_prefill(model, cache: PagedKVCache, table, prompts, *,
                  lengths=None, params=None, paged_kernel=None,
                  compilers: "PagedStepCompilers | None" = None,
                  warm_only: bool = False):
    """Prefill a mixed-length prompt batch into the paged pool.

    ``table``: (B, pages_per_seq) physical-page ids covering at least
    each row's prompt AND the tokens to be decoded after it.
    ``prompts``: list of 1-based id sequences — or, with ``lengths``, an
    already right-padded (B, Pmax) array whose per-row true lengths are
    given explicitly (bucketed serving pads Pmax past the longest
    prompt so compilation count stays bounded; padding columns never
    write pages or logits). ``paged_kernel``: the decode-kernel switch
    ("auto"/None consults $BIGDL_TPU_PAGED_KERNEL, then picks the
    Pallas page-walk kernel on TPU when legal and the dense
    ``_paged_view`` path otherwise; "interpret" is the CPU parity
    mode). Returns (greedy first tokens (B,), lengths (B,)) — feed
    both straight into :func:`paged_decode`; pool arrays inside
    ``cache`` are rebound."""
    params = model.params if params is None else params
    meta = model.lm_meta
    if lengths is None:
        lengths = np.asarray([len(p) for p in prompts], np.int32)
        pmax = int(lengths.max())
        batch = np.ones((len(prompts), pmax), np.int32)
        for i, p in enumerate(prompts):
            batch[i, :len(p)] = np.asarray(p, np.int32)
    else:
        batch = np.asarray(prompts, np.int32)
        lengths = np.asarray(lengths, np.int32)
        if batch.ndim != 2 or lengths.shape != (batch.shape[0],):
            raise ValueError("explicit-lengths prefill needs a (B, Pmax) "
                             "array and (B,) lengths")
        if int(lengths.max()) > batch.shape[1]:
            raise ValueError(f"lengths {lengths.tolist()} exceed the "
                             f"padded width {batch.shape[1]}")
    table = np.asarray(table, np.int32)
    capacity = table.shape[1] * cache.page_size
    if int(lengths.max()) > capacity:
        # without this the cols//page_size gather clamps to the last
        # table column and valid tokens silently overwrite one page
        # (round-5 review finding)
        raise ValueError(
            f"prompt of {int(lengths.max())} tokens exceeds the table's "
            f"{table.shape[1]} pages x {cache.page_size} slots "
            f"= {capacity}-token capacity")
    policy_key = (str(activation_dtype()), str(compute_dtype()))
    kernel = _resolve_paged_kernel(
        paged_kernel, lambda: _pool_kernel_supported(cache))
    statics = dict(
        num_layers=meta["num_layers"], num_heads=meta["num_heads"],
        page_size=cache.page_size, policy_key=policy_key,
        rope=meta.get("pos_encoding", "learned") == "rope",
        num_kv_heads=meta.get("num_kv_heads"), paged_kernel=kernel)
    if compilers is not None:
        # AOT path: execute the compiled executable directly (jit
        # dispatch would recompile — .lower().compile() does not
        # populate the jit cache)
        args = (params, cache.kp, cache.vp,
                jnp.asarray(table, jnp.int32), jnp.asarray(batch),
                jnp.asarray(lengths))
        quick = ("prefill", batch.shape, np.asarray(table).shape)
        if warm_only:
            compilers.prepare("serving_prefill_step", _paged_prefill_impl,
                              (1, 2), statics, quick, args)
            return None
        first, kp, vp = compilers.run(
            "serving_prefill_step", _paged_prefill_impl, (1, 2), statics,
            quick, args)
    elif warm_only:
        raise ValueError("warm_only prefill needs compilers=")
    else:
        first, kp, vp = _paged_prefill_impl(
            params, cache.kp, cache.vp, jnp.asarray(table, jnp.int32),
            jnp.asarray(batch), jnp.asarray(lengths), **statics)
    cache.kp, cache.vp = kp, vp
    return first, lengths


@functools.partial(jax.jit, donate_argnums=(1, 2), static_argnames=(
    "num_layers", "num_heads", "page_size", "policy_key", "rope",
    "num_kv_heads", "paged_kernel"))
def _paged_suffix_prefill_impl(params, kp, vp, table, suffix, start,
                               lengths, *, num_layers, num_heads,
                               page_size, policy_key, rope=False,
                               num_kv_heads=None, paged_kernel="dense"):
    """Prefill only the SUFFIX of each row: column j of ``suffix``
    (B, Smax) sits at absolute position ``start[i] + j`` — the first
    ``start[i]`` tokens are already cached in the pages ``table`` maps
    (an adopted prefix snapshot). Writes scatter to the page/slot of
    the absolute position; attention runs with per-row ``q_start`` so
    each query column attends every cached prefix key plus the suffix
    keys at/before its own position — exactly what the full prefill
    computed for those columns, which is what makes adopt-prefix +
    prefill-suffix bitwise-equivalent to prefilling the whole prompt
    (causality: the KV of token j depends on tokens <= j only).
    ``lengths`` (B,) are ABSOLUTE total prompt lengths; padding columns
    (start + j >= lengths) scatter out-of-range and are dropped.
    Returns (greedy first token (B,), kp, vp)."""
    embed, blocks, _, _ = _model_parts(params, num_layers)
    dtype = activation_dtype()
    b, smax = suffix.shape
    num_pages = kp[0].shape[0]
    # absolute position of every suffix column, per row
    cols = start[:, None] + jnp.broadcast_to(jnp.arange(smax)[None, :],
                                             (b, smax))
    x = _embed_rows(embed, suffix, cols).astype(dtype)
    valid = cols < lengths[:, None]
    # clamp the table gather for padding columns past the row's page
    # allocation; their writes are dropped via the OOB page id anyway
    log_page = table[jnp.arange(b)[:, None],
                     jnp.minimum(cols // page_size,
                                 table.shape[1] - 1)]
    phys = jnp.where(valid, log_page, num_pages)     # OOB -> drop
    slot = cols % page_size
    new_kp, new_vp = list(kp), list(vp)
    scale = (x.shape[-1] // num_heads) ** -0.5
    for li in range(num_layers):
        q, k, v = _qkv(blocks[li], x, num_heads, num_kv_heads)
        if rope:
            q = _rope_rows(q, cols)
            k = _rope_rows(k, cols)
        new_kp[li] = new_kp[li].at[phys, slot].set(
            k.astype(kp[li].dtype), mode="drop")
        new_vp[li] = new_vp[li].at[phys, slot].set(
            v.astype(vp[li].dtype), mode="drop")
        # the kernel's per-row q_start IS the suffix offset; the dense
        # path masks to absolute key positions <= cols per query column
        o = _attend_paged(q, new_kp[li], new_vp[li], table, start,
                          cols, num_heads, scale, paged_kernel)
        o = o.reshape(x.shape).astype(x.dtype)
        x = x + _proj(blocks[li]["0"]["1"], "out",
                      o).astype(activation_dtype())
        x = x + _ffn(blocks[li]["1"]["1"], _ln(blocks[li]["1"]["0"], x))
    logits = _row_logits(params, num_layers, x, lengths - start - 1)
    first = jnp.argmax(logits.astype(jnp.float32), axis=-1) + 1
    return first, tuple(new_kp), tuple(new_vp)


def paged_suffix_prefill(model, cache: PagedKVCache, table, suffixes, *,
                         start, lengths, params=None, paged_kernel=None,
                         compilers: "PagedStepCompilers | None" = None,
                         warm_only: bool = False):
    """Prefill only the suffix of each row into the paged pool — the
    prefix-reuse fast path: the caller has already scattered a
    prefix-clean :class:`KVSnapshot`'s pages into ``table``'s rows and
    runs prefill for tokens ``start..lengths`` only.

    ``suffixes``: list of 1-based id sequences (row i holds tokens
    ``start[i]..lengths[i]`` of its prompt) — or, with 2-D input, an
    already right-padded (B, Smax) array. ``start`` (B,): tokens
    already cached per row (page-aligned on the batcher path);
    ``lengths`` (B,): ABSOLUTE total prompt lengths. Returns (greedy
    first tokens (B,), lengths (B,)) exactly like :func:`paged_prefill`
    — and BITWISE the same tokens full prefill would have produced,
    on the dense and kernel paths alike (test-pinned)."""
    params = model.params if params is None else params
    meta = model.lm_meta
    start = np.asarray(start, np.int32)
    lengths = np.asarray(lengths, np.int32)
    batch = np.asarray(suffixes, np.int32) \
        if not isinstance(suffixes, (list, tuple)) else None
    if batch is None:
        smax = max(len(s) for s in suffixes)
        batch = np.ones((len(suffixes), smax), np.int32)
        for i, s in enumerate(suffixes):
            batch[i, :len(s)] = np.asarray(s, np.int32)
    if batch.ndim != 2 or start.shape != (batch.shape[0],) \
            or lengths.shape != (batch.shape[0],):
        raise ValueError("suffix prefill needs a (B, Smax) array with "
                         "(B,) start and lengths")
    if bool(np.any(lengths - start < 1)):
        raise ValueError(f"empty suffix: start {start.tolist()} must "
                         f"leave >= 1 token of lengths "
                         f"{lengths.tolist()} to prefill")
    if bool(np.any(lengths - start > batch.shape[1])):
        raise ValueError(f"suffixes of {(lengths - start).tolist()} "
                         f"tokens exceed the padded width "
                         f"{batch.shape[1]}")
    table = np.asarray(table, np.int32)
    capacity = table.shape[1] * cache.page_size
    if int(lengths.max()) > capacity:
        raise ValueError(
            f"prompt of {int(lengths.max())} tokens exceeds the table's "
            f"{table.shape[1]} pages x {cache.page_size} slots "
            f"= {capacity}-token capacity")
    policy_key = (str(activation_dtype()), str(compute_dtype()))
    kernel = _resolve_paged_kernel(
        paged_kernel, lambda: _pool_kernel_supported(cache))
    statics = dict(
        num_layers=meta["num_layers"], num_heads=meta["num_heads"],
        page_size=cache.page_size, policy_key=policy_key,
        rope=meta.get("pos_encoding", "learned") == "rope",
        num_kv_heads=meta.get("num_kv_heads"), paged_kernel=kernel)
    if compilers is not None:
        args = (params, cache.kp, cache.vp,
                jnp.asarray(table, jnp.int32), jnp.asarray(batch),
                jnp.asarray(start), jnp.asarray(lengths))
        quick = ("suffix_prefill", batch.shape, np.asarray(table).shape)
        if warm_only:
            compilers.prepare("serving_suffix_prefill_step",
                              _paged_suffix_prefill_impl, (1, 2),
                              statics, quick, args)
            return None
        first, kp, vp = compilers.run(
            "serving_suffix_prefill_step", _paged_suffix_prefill_impl,
            (1, 2), statics, quick, args)
    elif warm_only:
        raise ValueError("warm_only suffix prefill needs compilers=")
    else:
        first, kp, vp = _paged_suffix_prefill_impl(
            params, cache.kp, cache.vp, jnp.asarray(table, jnp.int32),
            jnp.asarray(batch), jnp.asarray(start),
            jnp.asarray(lengths), **statics)
    cache.kp, cache.vp = kp, vp
    return first, lengths


@functools.partial(jax.jit, donate_argnums=(1, 2), static_argnames=(
    "num_layers", "num_heads", "n_new", "page_size", "temperature",
    "top_k", "policy_key", "rope", "num_kv_heads", "paged_kernel"))
def _paged_decode_impl(params, kp, vp, table, lengths, tok0, rng, *,
                       num_layers, num_heads, n_new, page_size,
                       temperature, top_k, policy_key, rope=False,
                       num_kv_heads=None, paged_kernel="dense"):
    """Scan ``n_new`` single-token steps through the paged pools.

    ``table`` (B, P) logical->physical page map, ``lengths`` (B,) tokens
    already cached per row, ``tok0`` (B,) the last sampled token."""
    embed, blocks, _, _ = _model_parts(params, num_layers)
    dtype = activation_dtype()

    def step(carry, key):
        tok, kp, vp, lengths = carry
        b = tok.shape[0]
        cols = lengths[:, None]                   # (B, 1) write position
        x = _embed_rows(embed, tok[:, None], cols).astype(dtype)
        scale = (x.shape[-1] // num_heads) ** -0.5
        new_kp, new_vp = list(kp), list(vp)
        # physical slot of this token: page table[b, len//S], row len%S
        log_page = lengths // page_size
        phys = table[jnp.arange(b), log_page]     # (B,)
        slot = lengths % page_size
        for li in range(num_layers):
            q, k, v = _qkv(blocks[li], x, num_heads, num_kv_heads)
            if rope:
                q = _rope_rows(q, cols)
                k = _rope_rows(k, cols)
            new_kp[li] = kp[li].at[phys, slot].set(
                k[:, 0].astype(kp[li].dtype))
            new_vp[li] = vp[li].at[phys, slot].set(
                v[:, 0].astype(vp[li].dtype))
            # the single query column sits at per-row position
            # ``lengths`` — the slot just written above
            o = _attend_paged(q, new_kp[li], new_vp[li], table,
                              lengths, cols, num_heads, scale,
                              paged_kernel)
            o = o.reshape(x.shape).astype(x.dtype)
            x = x + _proj(blocks[li]["0"]["1"], "out",
                          o).astype(activation_dtype())
            x = x + _ffn(blocks[li]["1"]["1"], _ln(blocks[li]["1"]["0"],
                                                   x))
        logits = _row_logits(params, num_layers, x,
                             jnp.zeros_like(lengths))
        nxt = _sample(logits, key, temperature, top_k)
        return (nxt, tuple(new_kp), tuple(new_vp), lengths + 1), nxt

    keys = jax.random.split(rng, n_new)
    (_, kp, vp, lengths), toks = jax.lax.scan(
        step, (tok0, kp, vp, lengths), keys)
    return toks.T, kp, vp, lengths


def paged_decode(model, cache: PagedKVCache, table, lengths, last_tokens,
                 n_new: int, *, config: GenerationConfig | None = None,
                 rng=None, params=None, paged_kernel=None,
                 compilers: "PagedStepCompilers | None" = None,
                 warm_only: bool = False):
    """Decode ``n_new`` tokens for every row through the paged pool.

    ``table``: (B, pages_per_seq) int32 physical-page ids from
    ``cache.alloc``; ``lengths``: (B,) tokens already cached (0 for a
    fresh row — its first "last token" is the prompt's last id after a
    ragged/dense prefill copied in, or the BOS id for from-scratch rows).
    ``paged_kernel``: "auto"/None (env-overridable) picks the Pallas
    page-walk kernel on TPU when legal, the dense ``_paged_view`` path
    otherwise; "dense"/"pallas"/"interpret" force a path. Returns
    (tokens (B, n_new), updated lengths); pool arrays inside ``cache``
    are replaced with the updated ones (functional update, rebinding —
    old arrays are donated garbage)."""
    config = config or GenerationConfig(max_new_tokens=n_new)
    params = model.params if params is None else params
    meta = model.lm_meta
    table = np.asarray(table, np.int32)
    lengths = np.asarray(lengths, np.int32)
    capacity = table.shape[1] * cache.page_size
    if int(lengths.max()) + n_new > capacity:
        raise ValueError(
            f"decoding {n_new} tokens past length {int(lengths.max())} "
            f"exceeds the table's {capacity}-token capacity "
            f"({table.shape[1]} pages x {cache.page_size} slots)")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    policy_key = (str(activation_dtype()), str(compute_dtype()))
    kernel = _resolve_paged_kernel(
        paged_kernel, lambda: _pool_kernel_supported(cache))
    statics = dict(
        num_layers=meta["num_layers"], num_heads=meta["num_heads"],
        n_new=n_new, page_size=cache.page_size,
        temperature=config.temperature, top_k=config.top_k,
        policy_key=policy_key,
        rope=meta.get("pos_encoding", "learned") == "rope",
        num_kv_heads=meta.get("num_kv_heads"), paged_kernel=kernel)
    if compilers is not None:
        args = (params, cache.kp, cache.vp,
                jnp.asarray(table, jnp.int32),
                jnp.asarray(lengths, jnp.int32),
                jnp.asarray(last_tokens, jnp.int32), rng)
        quick = ("decode", n_new, table.shape)
        if warm_only:
            compilers.prepare("serving_decode_step", _paged_decode_impl,
                              (1, 2), statics, quick, args)
            return None
        toks, kp, vp, new_len = compilers.run(
            "serving_decode_step", _paged_decode_impl, (1, 2), statics,
            quick, args)
    elif warm_only:
        raise ValueError("warm_only decode needs compilers=")
    else:
        toks, kp, vp, new_len = _paged_decode_impl(
            params, cache.kp, cache.vp, jnp.asarray(table, jnp.int32),
            jnp.asarray(lengths, jnp.int32),
            jnp.asarray(last_tokens, jnp.int32), rng, **statics)
    cache.kp, cache.vp = kp, vp
    return toks, new_len


class _StaticKwargLowerer:
    """Adapter giving ``StepCompiler`` the positional ``.lower(*args)``
    it calls, over a jitted fn that also needs static kwargs (the paged
    impls key compilation on ``n_new``/``page_size``/... keywords)."""

    def __init__(self, jit_fn, statics: dict):
        self.jit_fn = jit_fn
        self._statics = dict(statics)

    def lower(self, *args):
        return self.jit_fn.lower(*args, **self._statics)


class PagedStepCompilers:
    """Shared AOT ``lower -> compile -> cache`` front end for the paged
    prefill/decode steps (ROADMAP 3: warm replica spin-up).

    One instance per :class:`~bigdl_tpu.serving.replica_pool.ReplicaPool`,
    shared by its batchers: the first replica compiles each
    (signature, statics) step and stores the executable in the
    :class:`~bigdl_tpu.tuning.aot_cache.AOTCache`; every later replica of
    identical geometry either probes the in-process table (same pool) or
    — a fresh pool/process over the same cache directory — deserializes
    the stored executable in ~10 ms instead of recompiling. That is the
    measured 7.4x warm cold-start (PR 8) turned into time-to-capacity
    under a traffic spike: the Nth replica compiles nothing.

    Decode/prefill then EXECUTE through the compiled executables
    directly (``compiled(*args)``) rather than through jit dispatch —
    ``.lower().compile()`` does not populate the jit cache, so routing
    execution back through the jitted fn would recompile anyway.

    Thread contract: replica drivers may race on first sight of a new
    signature; the worst case is a duplicate compile whose cache store
    is atomic (last writer wins with an identical payload). Steady
    state is a single dict probe per call.
    """

    def __init__(self, cache=None, *, watch=None):
        from bigdl_tpu.tuning.aot_cache import AOTCache, env_cache
        if cache is None:
            # follow $BIGDL_TPU_AOT_CACHE_DIR; absent -> in-process
            # executable table only (still no jit dispatch recompiles)
            cache = env_cache()
        elif isinstance(cache, (str, os.PathLike)):
            cache = AOTCache(str(cache))
        self.cache = cache
        self._watch = watch
        self._lock = threading.Lock()
        self._compilers: dict = {}

    def _compiler(self, name, jit_fn, donate, statics):
        skey = tuple(sorted(statics.items(), key=lambda kv: kv[0]))
        with self._lock:
            sc = self._compilers.get((name, skey))
            if sc is None:
                from bigdl_tpu.tuning.aot_cache import StepCompiler
                sc = StepCompiler(_StaticKwargLowerer(jit_fn, statics),
                                  name=name,
                                  cache=(self.cache if self.cache
                                         is not None else False),
                                  donate_argnums=donate,
                                  extra=("paged_step", skey),
                                  watch=self._watch)
                self._compilers[(name, skey)] = sc
        return sc

    def prepare(self, name, jit_fn, donate, statics, quick, args):
        """Build (compile or cache-load) the executable for this
        signature WITHOUT executing it — warm-up is shape-only."""
        sc = self._compiler(name, jit_fn, donate, statics)
        return sc.get(quick, args)

    def run(self, name, jit_fn, donate, statics, quick, args):
        compiled, _ = self.prepare(name, jit_fn, donate, statics, quick,
                                   args)
        return compiled(*args)

    @property
    def hits(self) -> int:
        return self.cache.hits if self.cache is not None else 0

    @property
    def misses(self) -> int:
        return self.cache.misses if self.cache is not None else 0

    def __len__(self):
        return sum(len(sc) for sc in self._compilers.values())


def _compile_decode_step(model, cache: PagedKVCache, table, lengths,
                         last_tokens, *, paged_kernel=None, params=None):
    """Lower + AOT-compile ONE single-token decode step (no execution);
    returns ``(compiled, resolved_kernel)`` and records the executable
    into the process compile-watch table as
    ``paged_decode_step[<kernel>]`` — the routing that lets its
    cost/memory analysis prove what the step materializes."""
    params = model.params if params is None else params
    meta = model.lm_meta
    kernel = _resolve_paged_kernel(
        paged_kernel, lambda: _pool_kernel_supported(cache))
    policy_key = (str(activation_dtype()), str(compute_dtype()))
    compiled = _paged_decode_impl.lower(
        params, cache.kp, cache.vp, jnp.asarray(table, jnp.int32),
        jnp.asarray(lengths, jnp.int32),
        jnp.asarray(last_tokens, jnp.int32), jax.random.PRNGKey(0),
        num_layers=meta["num_layers"], num_heads=meta["num_heads"],
        n_new=1, page_size=cache.page_size, temperature=0.0, top_k=None,
        policy_key=policy_key,
        rope=meta.get("pos_encoding", "learned") == "rope",
        num_kv_heads=meta.get("num_kv_heads"),
        paged_kernel=kernel).compile()
    _compile_watch.record_executable(f"paged_decode_step[{kernel}]",
                                     compiled)
    return compiled, kernel


def paged_decode_step_stats(model, cache: PagedKVCache, table, lengths,
                            last_tokens, *, paged_kernel=None,
                            params=None):
    """:func:`compile_watch.executable_stats` of ONE compiled
    single-token decode step — FLOPs, bytes accessed, and the memory
    analysis (arg/output/temp/peak-HBM bytes). At
    ``paged_kernel="dense"`` the table includes the per-layer
    (B, P*S, KV, D) ``_paged_view`` materialization; with the Pallas
    kernel that temp is gone."""
    compiled, _ = _compile_decode_step(model, cache, table, lengths,
                                       last_tokens,
                                       paged_kernel=paged_kernel,
                                       params=params)
    return _compile_watch.executable_stats(compiled)


_HLO_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s32": 4,
                    "u32": 4, "s16": 2, "u16": 2, "s8": 1, "u8": 1,
                    "pred": 1}


def _hlo_gather_bytes(hlo_text: str, min_bytes: int) -> tuple[int, int]:
    """(count, total output bytes) of gather ops at/above ``min_bytes``
    in an HLO module — the dense-view materializations. The same
    text-level accounting idiom as ``collective_bench.collective_bytes``
    (wire probe): static, backend-independent, no execution."""
    import re
    count, total = 0, 0
    for line in hlo_text.splitlines():
        m = re.search(r"=\s*(\w+)\[([\d,]*)\][^=]*?\bgather\(",
                      line.strip())
        if not m:
            continue
        dt = _HLO_DTYPE_BYTES.get(m.group(1))
        if dt is None or not m.group(2):
            continue
        n = dt
        for d in m.group(2).split(","):
            n *= int(d)
        if n >= min_bytes:
            count += 1
            total += n
    return count, total


def decode_hbm_probe(*, b: int = 8, pages_per_seq: int = 16,
                     page_size: int = 16, d_model: int = 256,
                     num_heads: int = 4, num_kv_heads: int = 1,
                     num_layers: int = 2, vocab: int = 512) -> dict:
    """Static per-decode-step HBM accounting, dense view vs paged
    kernel (the tentpole's measured receipt, ISSUE 9). Lowers ONE
    single-token decode step both ways — no execution, so it runs on
    any backend — and reports:

    - ``materialized_gather_{ops,bytes}``: gather instructions at/above
      the (B, P*S, KV, D) view size in each compiled HLO. The dense
      path carries exactly ``2 * num_layers`` of them (k and v view per
      layer); the kernel path carries ZERO — the materialization is
      gone, statically provable.
    - ``attn_hbm_bytes``: the static attention-traffic model per step —
      dense = 3x the view per consumption (pool gather read + view
      write + attention re-read); paged = each row's LIVE pages read
      once (rows skip their unallocated/out-of-length tail).
    - ``executable``: cost/memory analysis of both compiled steps
      (``compile_watch.executable_stats``). Off-TPU the paged step
      compiles in interpreter mode, so its executable numbers describe
      the emulation, not the kernel — the static rows above are the
      backend-independent receipt.
    """
    import jax as _jax

    from bigdl_tpu.models import TransformerLM
    model = TransformerLM(vocab, d_model=d_model, num_heads=num_heads,
                          num_layers=num_layers,
                          max_len=2 * pages_per_seq * page_size,
                          with_log_softmax=False,
                          num_kv_heads=num_kv_heads)
    model.materialize(_jax.random.PRNGKey(0))
    model.evaluate()
    kv = num_kv_heads or num_heads
    head_dim = d_model // num_heads
    cache = PagedKVCache(num_layers, num_pages=b * pages_per_seq + 1,
                         page_size=page_size, kv_heads=kv,
                         head_dim=head_dim)
    table = np.arange(b * pages_per_seq, dtype=np.int32).reshape(
        b, pages_per_seq)
    rs = np.random.default_rng(0)
    cap = pages_per_seq * page_size
    lengths = rs.integers(1, cap - 2, size=(b,)).astype(np.int32)
    last = np.ones((b,), np.int32)
    itemsize = jnp.dtype(cache.kp[0].dtype).itemsize
    view_bytes = b * pages_per_seq * page_size * kv * head_dim * itemsize
    consumptions = 2 * num_layers                      # k and v, per layer
    live_pages = int(np.sum(-(-(lengths + 1) // page_size)))
    paged_bytes = live_pages * page_size * kv * head_dim * itemsize \
        * consumptions
    dense_bytes = 3 * view_bytes * consumptions
    out = {"geometry": f"B{b} P{pages_per_seq} S{page_size} d{d_model} "
                       f"L{num_layers} kv{kv} hd{head_dim}",
           "view_shape": [b, pages_per_seq * page_size, kv, head_dim],
           "view_bytes": int(view_bytes),
           "attn_hbm_bytes": {"dense": int(dense_bytes),
                              "paged": int(paged_bytes)},
           "reduction": dense_bytes / max(paged_bytes, 1),
           "peak_view_bytes_per_layer": int(2 * view_bytes),
           "executable": {}, "materialized_gathers": {}}
    kernels = {"dense": "dense",
               "paged": "pallas" if _pool_kernel_supported(cache)
               else "interpret"}
    for label, kernel in kernels.items():
        compiled, _ = _compile_decode_step(model, cache, table, lengths,
                                           last, paged_kernel=kernel)
        ops, byts = _hlo_gather_bytes(compiled.as_text(), view_bytes)
        out["materialized_gathers"][label] = {"ops": ops, "bytes": byts}
        out["executable"][label] = _compile_watch.executable_stats(
            compiled)
    out["paged_compiled_as"] = kernels["paged"]
    # int8 quantized serving (serving/quantized.py): static accounting
    # of the decode step's resident weight + KV-pool arguments after
    # quantization — the bytes a replica parks in HBM between bursts
    from bigdl_tpu.serving.quantized import quantized_byte_report
    out["int8"] = quantized_byte_report(model, cache)
    return out


# ---------------------------------------------------------------------------
# Speculative decoding (greedy draft-and-verify)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=(
    "t_layers", "t_heads", "t_kv", "t_rope", "d_layers", "d_heads",
    "d_kv", "d_rope", "max_len", "n_new", "gamma", "temperature",
    "policy_key", "paged_kernel"))
def _speculative_impl(t_params, d_params, prompt, lengths, rng, *,
                      t_layers, t_heads, t_kv, t_rope, d_layers, d_heads,
                      d_kv, d_rope, max_len, n_new, gamma,
                      temperature, policy_key, paged_kernel="dense"):
    """Speculative loop. Per outer round: draft proposes gamma tokens
    one-by-one, target verifies all gamma+1 positions in ONE T=gamma+1
    cache step, rows accept a prefix plus one correction/bonus token.
    Rows advance at different rates, so positions/caches are the ragged
    machinery.

    ``temperature == 0``: greedy draft-and-verify — accept the longest
    prefix where draft argmax == target argmax; output is BITWISE the
    target's greedy decode. ``temperature > 0``: Leviathan-style
    rejection sampling — draft token x_j accepted with probability
    min(1, p_t(x_j)/p_d(x_j)); on rejection the replacement is drawn
    from the normalized residual max(p_t - p_d, 0), and after a fully
    accepted window the bonus is drawn from p_t at the next position.
    Either way the output distribution IS the target model's (the
    distribution-exactness statistical test lives in
    tests/test_serving.py). Returns (tokens (B, n_new),
    accepted_draft_total, rounds)."""
    embed_t, blocks_t, _, _ = _model_parts(t_params, t_layers)
    embed_d, blocks_d, _, _ = _model_parts(d_params, d_layers)
    dtype = activation_dtype()
    b = prompt.shape[0]

    tck, tcv, tx = _ragged_prefill(t_params, prompt, t_layers,
                                   t_heads, max_len, t_rope, t_kv)
    dck, dcv, dx = _ragged_prefill(d_params, prompt, d_layers,
                                   d_heads, max_len, d_rope, d_kv)
    t_logits = _row_logits(t_params, t_layers, tx, lengths - 1)
    rng, key0 = jax.random.split(rng)
    if temperature == 0.0:
        first = jnp.argmax(t_logits.astype(jnp.float32), axis=-1) + 1
    else:
        first = jax.random.categorical(
            key0, t_logits.astype(jnp.float32) / temperature, axis=-1) + 1

    out = jnp.zeros((b, n_new), jnp.int32).at[:, 0].set(first)
    # n_done counts emitted tokens per row; pos = position of the last
    # CACHED token (the prompt end); `first` is emitted but not yet cached
    n_done = jnp.ones((b,), jnp.int32)
    pos = lengths - 1
    vocab = embed_t["tok"].shape[0]

    def d_step(tok, dck, dcv, p, key):
        """One draft step at per-row position p+1: greedy token when
        temperature==0, else a sample plus the full draft distribution
        (needed for the acceptance ratio and the residual)."""
        x = _embed_rows(embed_d, tok[:, None], (p + 1)[:, None]
                        ).astype(dtype)
        nck, ncv = list(dck), list(dcv)
        for li in range(d_layers):
            x, nck[li], ncv[li] = _ragged_block_step(
                blocks_d[li], x, dck[li], dcv[li], p + 1, d_heads,
                max_len, d_rope, d_kv, paged_kernel)
        lg = _row_logits(d_params, d_layers, x,
                         jnp.zeros_like(p)).astype(jnp.float32)
        if temperature == 0.0:
            return (jnp.argmax(lg, axis=-1) + 1, None,
                    tuple(nck), tuple(ncv))
        probs = jax.nn.softmax(lg / temperature, axis=-1)
        tok = jax.random.categorical(key, lg / temperature, axis=-1) + 1
        return tok, probs, tuple(nck), tuple(ncv)

    def round_body(carry):
        (out, n_done, pos, tck, tcv, dck, dcv, acc, proposed, rounds,
         rng) = carry
        rng, r_draft, r_acc, r_bonus = jax.random.split(rng, 4)
        # proposals only count for rows still filling their budget —
        # finished rows keep riding the lockstep loop but their masked
        # proposals must not deflate the acceptance rate (ADVICE.md)
        proposed = proposed + gamma * jnp.sum(
            (n_done < n_new).astype(jnp.int32))
        # rows already finished keep proposing into masked positions;
        # their writes land beyond max_len-1? No: clamp via mode="drop"
        # in the scatter and the emit mask below.
        last = jnp.take_along_axis(out, (n_done - 1)[:, None],
                                   axis=1)[:, 0]
        # --- draft: gamma proposals, PLUS one extra step whose only job
        # is caching props[gamma-1] (its proposal is discarded) —
        # without it a fully-accepted round would leave the next round's
        # draft attending a hole at that position
        proposals, d_probs = [], []
        dtok = last
        dp = pos
        dkeys = jax.random.split(r_draft, gamma + 1)
        for gi in range(gamma + 1):
            dtok, dprob, dck, dcv = d_step(dtok, dck, dcv, dp, dkeys[gi])
            if gi < gamma:
                proposals.append(dtok)
                d_probs.append(dprob)
            dp = dp + 1
        props = jnp.stack(proposals, axis=1)              # (B, gamma)
        # --- target: ONE T=gamma+1 cache step over [last, props] scores
        # every draft position AND the bonus position past them
        seq = jnp.concatenate([last[:, None], props], axis=1)
        cols_last = pos + gamma + 1                       # (B,)
        x = _embed_rows(
            embed_t, seq,
            pos[:, None] + 1
            + jnp.arange(gamma + 1)[None, :]).astype(dtype)
        ntck, ntcv = list(tck), list(tcv)
        for li in range(t_layers):
            x, ntck[li], ntcv[li] = _ragged_block_step(
                blocks_t[li], x, tck[li], tcv[li], cols_last, t_heads,
                max_len, t_rope, t_kv, paged_kernel)
        _, _, norm_p, head_p = _model_parts(t_params, t_layers)
        tg = _linear(head_p, _ln(norm_p, x)).astype(jnp.float32)
        if temperature == 0.0:
            t_choice = jnp.argmax(tg, axis=-1) + 1        # (B, gamma+1)
            # --- accept longest agreeing prefix ----------------------
            a = (props == t_choice[:, :gamma])            # (B, gamma)
            acc_len = jnp.sum(jnp.cumprod(a, axis=1), axis=1)   # (B,)
            bonus = t_choice[jnp.arange(b), acc_len]
        else:
            # --- Leviathan rejection sampling ------------------------
            pt = jax.nn.softmax(tg / temperature, axis=-1)  # (B,γ+1,V)
            pd = jnp.stack(d_probs, axis=1)                 # (B,γ,V)
            pidx = (props - 1)[..., None]                   # 0-based
            pt_x = jnp.take_along_axis(pt[:, :gamma], pidx,
                                       axis=-1)[..., 0]     # (B,γ)
            pd_x = jnp.take_along_axis(pd, pidx, axis=-1)[..., 0]
            u = jax.random.uniform(r_acc, (b, gamma))
            # u < min(1, pt/pd)  <=>  u*pd < pt (division-free)
            a = u * pd_x < pt_x
            acc_len = jnp.sum(jnp.cumprod(a, axis=1), axis=1)
            # replacement at the reject position: residual
            # max(pt - pd, 0) normalized; after a fully accepted window
            # (acc_len==gamma) pd is zero-padded there, so the residual
            # IS pt[gamma] — one uniform rule covers both cases
            pd_pad = jnp.concatenate(
                [pd, jnp.zeros((b, 1, vocab), pd.dtype)], axis=1)
            pt_at = pt[jnp.arange(b), acc_len]              # (B, V)
            pd_at = pd_pad[jnp.arange(b), acc_len]
            resid = jnp.maximum(pt_at - pd_at, 0.0)
            z = jnp.sum(resid, axis=-1, keepdims=True)
            resid = jnp.where(z > 0, resid / jnp.maximum(z, 1e-20),
                              pt_at)
            bonus = jax.random.categorical(
                r_bonus, jnp.log(jnp.maximum(resid, 1e-37)),
                axis=-1) + 1
        # emitted this round = accepted drafts + 1 correction/bonus
        # token (column gamma exists because verify is T=γ+1)
        emit_n = acc_len + 1
        # ragged emit into `out`: row b writes tokens at n_done..+emit_n
        cols = n_done[:, None] + jnp.arange(gamma + 1)[None, :]
        vals = jnp.concatenate([props, bonus[:, None]], axis=1)
        # the accepted drafts then the bonus: position j<acc_len ->
        # props[j]; j==acc_len -> bonus
        vals = jnp.where(jnp.arange(gamma + 1)[None, :]
                         < acc_len[:, None], vals,
                         jnp.where(jnp.arange(gamma + 1)[None, :]
                                   == acc_len[:, None],
                                   bonus[:, None], 0))
        keep = (jnp.arange(gamma + 1)[None, :] <= acc_len[:, None]) \
            & (cols < n_new)
        rows_ix = jnp.broadcast_to(jnp.arange(b)[:, None], cols.shape)
        out = out.at[rows_ix, jnp.where(keep, cols, n_new)].set(
            jnp.where(keep, vals, 0), mode="drop")
        # accepted-draft count, clipped to what fit in the output budget
        acc = acc + jnp.sum(jnp.minimum(
            acc_len, jnp.maximum(n_new - n_done, 0)))
        n_done = jnp.minimum(n_done + emit_n, n_new)
        # --- caches: target cached all gamma verify positions; the per
        # -row valid prefix is pos + 1 + acc_len (last+accepted drafts);
        # junk beyond is overwritten next round (masked meanwhile).
        # Draft cached gamma proposals; valid prefix pos + 1 + acc_len
        # too (the draft's own tokens up to the disagreement point).
        pos = pos + 1 + acc_len
        return (out, n_done, pos, tuple(ntck), tuple(ntcv), dck, dcv,
                acc, proposed, rounds + 1, rng)

    def cond(carry):
        n_done = carry[1]
        return jnp.any(n_done < n_new)

    zero_acc = jnp.zeros((), jnp.int32)
    carry = (out, n_done, pos, tck, tcv, dck, dcv, zero_acc,
             jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32), rng)
    (out, n_done, pos, _, _, _, _, acc, proposed, rounds,
     _) = jax.lax.while_loop(cond, round_body, carry)
    return out, acc, proposed, rounds


def speculative_generate(model, draft_model, prompts, *,
                         max_new_tokens: int = 32, gamma: int = 4,
                         temperature: float = 0.0, rng=None,
                         params=None, draft_params=None,
                         paged_kernel=None):
    """Speculative decoding with ~1 target forward per ``accepted+1``
    tokens instead of per token.

    ``temperature == 0`` (default): greedy draft-and-verify — output is
    EXACTLY the target model's greedy continuation, whatever the draft
    proposes. ``temperature > 0``: Leviathan rejection sampling — the
    output DISTRIBUTION is exactly the target model's sampling
    distribution at that temperature (both pinned by
    tests/test_serving.py).

    ``prompts``: list of 1-based id sequences (mixed lengths ride the
    ragged path). Returns ``(tokens (B, max_new_tokens), stats)`` where
    stats reports ``accepted`` / ``proposed`` / ``rounds`` and
    ``acceptance_rate`` = accepted / proposed. Proposals are counted
    only for rows still short of their token budget at each round's
    start (rows that finished early keep riding the lockstep loop but
    their masked proposals no longer deflate the rate — ADVICE.md,
    mixed-progress batches).

    ``paged_kernel``: the same decode-kernel switch as
    :func:`paged_decode` — the draft's per-token steps and the
    target's T=gamma+1 verify step attend through the Pallas
    page-walk kernel (dense caches viewed as contiguous pages) instead
    of the masked full-cache einsum, so the speculative path does not
    silently keep paying the dense gather. "auto"/None engages it on
    TPU when BOTH models' geometries are legal."""
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    t_meta, d_meta = model.lm_meta, draft_model.lm_meta
    lengths = np.asarray([len(p) for p in prompts], np.int32)
    pmax = int(lengths.max())
    if pmax + max_new_tokens + gamma > min(t_meta["max_len"],
                                           d_meta["max_len"]):
        raise ValueError("prompt + new tokens + gamma exceeds max_len")
    batch = np.ones((len(prompts), pmax), np.int32)
    for i, p in enumerate(prompts):
        batch[i, :len(p)] = np.asarray(p, np.int32)
    t_params = model.params if params is None else params
    d_params = draft_model.params if draft_params is None else draft_params
    policy_key = (str(activation_dtype()), str(compute_dtype()))
    if rng is None:
        rng = jax.random.PRNGKey(0)
    max_len_eff = min(t_meta["max_len"], d_meta["max_len"])

    def _both_supported():
        from bigdl_tpu.ops.pallas.paged_attention import \
            dense_cache_supported
        dims = (t_params["0"]["tok"].shape[1] // t_meta["num_heads"],
                d_params["0"]["tok"].shape[1] // d_meta["num_heads"])
        return all(dense_cache_supported(hd, max_len_eff)
                   for hd in dims)

    kernel = _resolve_paged_kernel(paged_kernel, _both_supported)
    out, acc, proposed, rounds = _speculative_impl(
        t_params, d_params, jnp.asarray(batch), jnp.asarray(lengths),
        rng,
        t_layers=t_meta["num_layers"], t_heads=t_meta["num_heads"],
        t_kv=t_meta.get("num_kv_heads"),
        t_rope=t_meta.get("pos_encoding", "learned") == "rope",
        d_layers=d_meta["num_layers"], d_heads=d_meta["num_heads"],
        d_kv=d_meta.get("num_kv_heads"),
        d_rope=d_meta.get("pos_encoding", "learned") == "rope",
        max_len=max_len_eff,
        n_new=max_new_tokens, gamma=gamma,
        temperature=float(temperature), policy_key=policy_key,
        paged_kernel=kernel)
    rounds_i = max(int(rounds), 1)
    proposed_i = int(proposed)
    stats = {"acceptance_rate": float(int(acc)) / max(proposed_i, 1),
             "accepted": int(acc), "proposed": proposed_i,
             "rounds": rounds_i}
    return out, stats


# ---------------------------------------------------------------------------
# KV handoff
# ---------------------------------------------------------------------------

class KVSnapshot:
    """Host-side export of one request's KV state — the handoff unit
    for prefix-cache reuse, prefill/decode disaggregation, and drain
    migration (the serving router, ``bigdl_tpu/serving/``).

    ``kv`` is a per-layer list of ``(k, v)`` numpy arrays shaped
    ``(n_pages, page_size, kv_heads, head_dim)``: the request's pages
    gathered off the pool in one packed ``jax.device_get``. The first
    ``n_cached`` token positions are valid; ``emitted`` tokens (always
    starting with the prefill's first sampled token) have already been
    produced; ``last_token`` is the next decode step's input. Adopting
    a snapshot re-allocates pages and scatters the data back in —
    greedy decode then continues bitwise identically to the exporting
    batcher, because the continuation is a pure function of
    (params, KV state, last token) (test-pinned in
    tests/test_serving_router.py).

    ``weight_version`` stamps WHICH params the KV was computed under
    (the deploy plane's rolling weight publishes,
    ``bigdl_tpu/deploy/``): adoption validates it against the target
    batcher's version, because continuing a sequence under different
    weights would silently mix versions mid-answer. ``None`` means
    unversioned (a fleet that never published) and matches anything."""

    __slots__ = ("prompt", "n_cached", "kv", "last_token", "emitted",
                 "page_size", "weight_version")

    def __init__(self, prompt, n_cached, kv, last_token, emitted,
                 page_size, weight_version=None):
        self.prompt = list(prompt)
        self.n_cached = int(n_cached)
        self.kv = kv
        self.last_token = int(last_token)
        self.emitted = list(emitted)
        self.page_size = int(page_size)
        self.weight_version = weight_version

    @property
    def n_pages(self) -> int:
        return int(self.kv[0][0].shape[0]) if self.kv else 0

    @property
    def nbytes(self) -> int:
        return sum(int(k.nbytes) + int(v.nbytes) for k, v in self.kv)

    @property
    def is_prefix_only(self) -> bool:
        """True for a truncated prefix snapshot: it carries cached KV
        pages but no sampled token, so it can only enter a batcher
        through ``submit(..., snapshot=, prefill_from=)`` — the suffix
        prefill produces the first token."""
        return not self.emitted

    def truncate(self, n_tokens: int) -> "KVSnapshot":
        """A page-boundary prefix of this snapshot: keep the full pages
        covering at most ``n_tokens`` PROMPT tokens (the partial page is
        dropped — its slots would mix in tokens past the boundary) and
        return a new prefix-only snapshot whose ``prompt``/``n_cached``/
        page list are mutually consistent. Causality makes the kept
        pages exact: the KV of token j is a function of tokens <= j
        only, so the prefix pages of a longer prefill ARE the prefill
        of the prefix. Raises ``ValueError`` when no full page fits."""
        limit = min(int(n_tokens), self.n_cached, len(self.prompt))
        p = (limit // self.page_size) * self.page_size
        if p <= 0:
            raise ValueError(
                f"cannot truncate to {n_tokens} tokens: no full "
                f"{self.page_size}-slot page fits (n_cached="
                f"{self.n_cached}, prompt_len={len(self.prompt)})")
        n_pages = p // self.page_size
        # real copies, not views: the point of truncation is that the
        # retained entry's bytes actually shrink
        kv = [(np.ascontiguousarray(k[:n_pages]),
               np.ascontiguousarray(v[:n_pages])) for k, v in self.kv]
        return KVSnapshot(self.prompt[:p], p, kv,
                          last_token=self.prompt[p - 1], emitted=[],
                          page_size=self.page_size,
                          weight_version=self.weight_version)

    def __repr__(self):
        return (f"KVSnapshot(prompt_len={len(self.prompt)}, "
                f"n_cached={self.n_cached}, n_pages={self.n_pages}, "
                f"emitted={len(self.emitted)}, "
                f"weight_version={self.weight_version!r})")


class _SuffixJob:
    """Queued adopt-prefix + prefill-suffix admission: the full prompt,
    the page-aligned prefix snapshot to adopt, and the token offset the
    suffix prefill starts at (``start == snapshot.n_cached``)."""

    __slots__ = ("prompt", "snapshot", "start")

    def __init__(self, prompt, snapshot, start):
        self.prompt = list(prompt)
        self.snapshot = snapshot
        self.start = int(start)


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_pages(pool, idx, data):
    """Adopt-side scatter: write snapshot pages ``data`` into pool rows
    ``idx``. Donated so adoption does not copy the whole pool; compiles
    once per (pool geometry, page count) — counts are bucketed by the
    export side, so signatures stay O(log max_len)."""
    return pool.at[idx].set(data.astype(pool.dtype))


# ---------------------------------------------------------------------------
# Continuous batching
# ---------------------------------------------------------------------------

class ContinuousBatcher:
    """Host-side continuous-batching loop over the paged cache.

    The orchestration layer that turns the paged primitives into a
    server: ``submit()`` queues requests, each ``step()`` admits queued
    requests into free slots (prompt prefilled into freshly allocated
    pages, lengths bucketed to powers of two so compilations stay
    O(log max_len)), decodes one fixed-shape burst for ALL slots in one
    compiled program, retires rows that hit ``eos_id`` or their token
    budget (pages returned to the pool), and ``finished()`` hands back
    completed generations. Greedy decode: each result equals the
    model's own per-prompt greedy continuation (test-pinned).

    Fixed shapes are the TPU contract: the slot batch is always
    ``max_batch`` rows — free slots decode into a dedicated scratch page
    and their outputs are discarded (documented demo trade-off; a
    production server would compact instead). vLLM's scheduler plays
    this role on GPU stacks; the reference has no serving story at all.

    Observability (bigdl_tpu.observability): every session records into
    a metric registry (``registry=`` — the process default unless
    given) — ``serving_ttft_seconds`` (submit -> first token),
    ``serving_decode_token_seconds`` (burst wall clock / burst),
    ``serving_queue_depth`` / ``serving_active_slots`` /
    ``serving_kv_page_utilization`` gauges, and admission / retirement
    / token counters. ``summary=`` (any Summary) adds a per-``step()``
    scalar event log (QueueDepth / ActiveSlots / KVPageUtilization /
    DecodeTokensPerSec). All instrumentation is host-side around the
    compiled programs — it adds no dispatches and no device syncs
    beyond the token readback the loop already does (test-pinned by a
    compile/dispatch count).

    Telemetry plane (docs/OBSERVABILITY.md): the batcher registers a
    ``serving_batcher`` READINESS check (``health=`` — the process
    default unless given; one batcher per process answers it, the
    latest registration wins) reporting admitting/saturated, and wraps
    its prefill/decode step fns in ``compile_watch`` — prompt-bucket
    explosion or a burst-size churn shows up as
    ``compile_watch_compiles_total{name="serving_prefill"|
    "serving_decode"}`` and storm-warns instead of silently paying an
    XLA compile per request.
    """

    def __init__(self, model, *, max_batch: int, num_pages: int,
                 page_size: int = 16, max_new_tokens: int = 32,
                 max_burst: int = 8, eos_id: int | None = None,
                 registry=None, summary=None, health=None,
                 watch=None, health_name: str = "serving_batcher",
                 on_complete=None, on_prefill=None, paged_kernel=None,
                 aot_cache=None, weight_version=None):
        meta = model.lm_meta
        self.model = model
        # which published weight set this batcher serves (deploy plane;
        # None = unversioned). Exported KVSnapshots carry it and
        # adoption validates it — see _validate_snapshot/set_weights.
        self.weight_version = weight_version
        self.max_batch = max_batch
        self.max_new = max_new_tokens
        self.max_burst = max_burst
        self.eos_id = eos_id
        self.page_size = page_size
        # decode-kernel switch, forwarded to every prefill/decode call;
        # None keeps the callee's own "auto" resolution AND keeps the
        # kwarg off the wire (tests monkeypatch paged_prefill/
        # paged_decode with fakes that predate it)
        self.paged_kernel = paged_kernel
        self._kernel_kw = ({} if paged_kernel is None
                           else {"paged_kernel": paged_kernel})
        # AOT spin-up (ROADMAP 3): route prefill/decode through the
        # explicit lower->compile->cache pipeline and execute the
        # compiled executables directly. ``aot_cache`` accepts a
        # PagedStepCompilers (the pool shares ONE across replicas so
        # the Nth replica compiles nothing), an AOTCache, or a cache
        # directory path. None keeps the legacy jit dispatch path AND
        # keeps the kwarg off the wire for monkeypatched fakes.
        self.aot = None
        if aot_cache is not None and aot_cache is not False:
            self.aot = (aot_cache
                        if isinstance(aot_cache, PagedStepCompilers)
                        else PagedStepCompilers(aot_cache))
            self._kernel_kw = dict(self._kernel_kw, compilers=self.aot)
        kv = meta.get("num_kv_heads") or meta["num_heads"]
        head_dim = model.params["0"]["tok"].shape[1] // meta["num_heads"]
        self.cache = PagedKVCache(meta["num_layers"], num_pages,
                                  page_size, kv, head_dim)
        self._scratch = self.cache.alloc(page_size)[0]
        self._pool_pages = self.cache.pages_free   # after the scratch
        # the longest admissible prompt: bucket + budget must fit the
        # model's positions; per-row allocations include max_burst-1
        # slack because a fixed burst can overshoot max_new before the
        # retire check runs (overshoot tokens are discarded, but their
        # cache writes must land in the row's OWN pages)
        self.max_prompt = meta["max_len"] - max_new_tokens
        self._max_len = meta["max_len"]
        self.pages_per_slot = -(-(self.max_prompt + max_new_tokens
                                  + max_burst) // page_size)
        self.table = np.full((max_batch, self.pages_per_slot),
                             self._scratch, np.int32)
        self.lengths = np.zeros((max_batch,), np.int32)
        self.last = np.ones((max_batch,), np.int32)
        # slot -> (request_id, prompt tokens, [tokens so far]) or None
        self.slots: list = [None] * max_batch
        self._pages: list = [None] * max_batch
        self.queue: list = []
        self._done: list = []
        self.summary = summary
        self._step_count = 0
        # router hooks: on_complete(request_id, tokens) fires at retire;
        # on_prefill(request_id, prompt, snapshot_fn) fires right after
        # a real prefill, with a LAZY exporter the callee may invoke to
        # capture the clean prefix KV (assignable attributes — the
        # router wires them after construction)
        self.on_complete = on_complete
        self.on_prefill = on_prefill
        # request-timeline plumbing (observability/request_trace.py):
        # the router assigns ``tracker`` after construction (like the
        # hooks above); ``replica_name`` is stamped by
        # ``Replica.__init__`` the same way ``weight_version`` is by
        # the pool, so events carry fleet identity. ``_trace_rid`` is
        # the request whose prefill is on the device RIGHT NOW — the
        # compile-watch tap below uses it to pin a recompile to the
        # exact request that paid for it.
        self.tracker = None
        self.replica_name = None
        self._trace_rid = None
        reg = default_registry() if registry is None else registry
        self._m_queue = reg.gauge(
            "serving_queue_depth", "requests waiting for a slot")
        self._m_active = reg.gauge(
            "serving_active_slots", "slots decoding this step")
        self._m_util = reg.gauge(
            "serving_kv_page_utilization",
            "fraction of KV pool pages in use (incl. scratch)")
        self._m_admit = reg.counter(
            "serving_admissions_total", "requests admitted to a slot")
        self._m_retire = reg.counter(
            "serving_retirements_total",
            "requests finished (eos or budget)")
        self._m_tokens = reg.counter(
            "serving_generated_tokens_total",
            "decoded tokens kept for active rows")
        self._m_ttft = reg.histogram(
            "serving_ttft_seconds",
            "submit -> first token (queue wait + prefill)")
        self._m_tok_lat = reg.histogram(
            "serving_decode_token_seconds",
            "per-token decode latency: burst wall clock / burst")
        self._m_skips = reg.counter(
            "serving_prefill_skips_total",
            "admissions that adopted a KV snapshot instead of "
            "running prefill")
        self._m_suffix = reg.counter(
            "serving_suffix_prefills_total",
            "admissions that adopted a prefix snapshot and prefilled "
            "only the suffix (partial prefix-cache hits)")
        self._m_cancel = reg.counter(
            "serving_cancelled_total",
            "requests cancelled before completion (queued or in-flight)")
        self._m_export = reg.counter(
            "serving_exports_total",
            "KV snapshots exported for handoff/migration")
        # compile telemetry: signature-keyed compile counting on the
        # two step fns (module globals resolve at call time, so tests
        # that monkeypatch paged_prefill/paged_decode still intercept)
        self._watch = watch or _compile_watch.CompileWatch(registry=reg)
        self._prefill_fn = self._watch.watch(
            lambda *a, **k: paged_prefill(*a, **k),
            name="serving_prefill")
        self._suffix_fn = self._watch.watch(
            lambda *a, **k: paged_suffix_prefill(*a, **k),
            name="serving_suffix_prefill")
        self._decode_fn = self._watch.watch(
            lambda *a, **k: paged_decode(*a, **k),
            name="serving_decode")
        # a NEW signature during a request's prefill = that request
        # paid an XLA compile; land it on its timeline (no-op until
        # the router wires a tracker)
        self._watch.add_tap(self._compile_tap)
        # serving readiness: the load-balancer gate (/readyz)
        if health is None:
            from bigdl_tpu.observability.exporter import default_health
            health = default_health()
        self._health = health
        # ``health_name`` lets N replicas in one process each answer a
        # distinct /readyz check (the router names them per replica)
        self.health_name = str(health_name)
        self._health.register(self.health_name, self._ready,
                              kind="readiness")

    # -- request timelines (tracker lock is a leaf; no-ops when off) --
    def _tev(self, rid, event, **fields) -> None:
        tr = self.tracker
        if tr is not None:
            tr.event(rid, event, replica=self.replica_name,
                     weight_version=self.weight_version, **fields)

    def _compile_tap(self, name: str, n_signatures: int) -> None:
        rid = self._trace_rid
        if rid is not None:
            self._tev(rid, "compile", watch=name,
                      signatures=n_signatures)

    def _ready(self):
        """Readiness = admitting: a free slot exists, or nothing is
        waiting (back-pressure flips this off when every slot is busy
        AND requests queue behind them)."""
        free_slots = sum(s is None for s in self.slots)
        if free_slots > 0:
            return True, (f"admitting ({free_slots}/{self.max_batch} "
                          f"slots, {self.cache.pages_free} pages free)")
        return (not self.queue,
                f"saturated: 0/{self.max_batch} slots free, "
                f"{len(self.queue)} queued")

    @staticmethod
    def _bucket(n: int) -> int:
        b = 8
        while b < n:
            b *= 2
        return b

    def _need_pages(self, prompt_len: int) -> int:
        # the bucket clamps to max_prompt (not max_len): that keeps every
        # admissible request inside pages_per_slot AND the positional
        # range (round-5 review: a >pow2 prompt otherwise over-allocated
        # past the table width)
        bucket = min(self._bucket(prompt_len), self.max_prompt)
        return -(-(bucket + self.max_new + self.max_burst)
                 // self.page_size)

    def request_ids(self) -> set:
        """Ids currently queued or in flight (ids of FINISHED requests
        may be reused once collected)."""
        ids = {e[0] for e in self.queue}
        ids.update(s[0] for s in self.slots if s is not None)
        return ids

    def _validate_snapshot(self, snap: KVSnapshot) -> None:
        snap_version = getattr(snap, "weight_version", None)
        if (snap_version is not None and self.weight_version is not None
                and snap_version != self.weight_version):
            # a version-mismatched snapshot is never adopted silently:
            # the KV was computed under different params, so continuing
            # it here would mix weight versions inside one answer
            raise ValueError(
                f"snapshot weight_version {snap_version!r} != batcher "
                f"weight_version {self.weight_version!r} — finish the "
                "request on an old-version replica or resubmit its "
                "prompt fresh (docs/DEPLOYMENT.md, version skew)")
        if snap.page_size != self.page_size:
            raise ValueError(f"snapshot page_size {snap.page_size} != "
                             f"batcher page_size {self.page_size}")
        if len(snap.kv) != self.cache.num_layers:
            raise ValueError(f"snapshot has {len(snap.kv)} layers, "
                             f"cache has {self.cache.num_layers}")
        want = (self.page_size, self.cache.kv_heads, self.cache.head_dim)
        for li, (k, v) in enumerate(snap.kv):
            if tuple(k.shape[1:]) != want or tuple(v.shape[1:]) != want:
                raise ValueError(
                    f"snapshot layer {li} page shape {k.shape[1:]} != "
                    f"cache page shape {want}")
        if snap.n_pages > self._need_pages(len(snap.prompt)):
            raise ValueError(
                f"snapshot carries {snap.n_pages} pages but this "
                f"batcher allocates {self._need_pages(len(snap.prompt))}"
                f" for a {len(snap.prompt)}-token prompt — exporter "
                "geometry (max_new/max_burst/page_size) must match")
        if snap.n_cached > snap.n_pages * self.page_size:
            raise ValueError(
                f"snapshot n_cached {snap.n_cached} exceeds its "
                f"{snap.n_pages} pages x {self.page_size} slots")

    def set_weights(self, model, weight_version) -> None:
        """Swap the served weights in place (the deploy plane's reload
        step after a drain, ``bigdl_tpu/deploy/``). Only legal while
        idle: an in-flight sequence's KV was computed under the OLD
        params, and decoding it further under new ones would silently
        mix versions — the router drains first (finish-on-old or
        migrate), then swaps, then resumes. Geometry must match the
        construction model: the compiled prefill/decode executables key
        on abstract shapes with params as runtime arguments, so a
        same-geometry swap re-uses every executable and compiles
        nothing."""
        if not self.idle:
            raise RuntimeError(
                f"cannot swap weights with {len(self.queue)} queued and "
                f"{sum(s is not None for s in self.slots)} in-flight "
                "requests — drain the replica first")
        new, old = model.lm_meta, self.model.lm_meta
        keys = ("num_layers", "num_heads", "num_kv_heads", "max_len")
        if any(new.get(k) != old.get(k) for k in keys):
            raise ValueError(
                "set_weights requires identical model geometry: "
                + "; ".join(f"{k}: {old.get(k)} -> {new.get(k)}"
                            for k in keys if new.get(k) != old.get(k)))
        self.model = model
        self.weight_version = weight_version

    def submit(self, request_id, prompt=None, *,
               snapshot: KVSnapshot | None = None,
               prefill_from: int | None = None) -> None:
        """Queue one request (list of 1-based token ids) — or, with
        ``snapshot=``, a :class:`KVSnapshot` to ADOPT: admission then
        allocates pages and scatters the cached KV back in instead of
        running prefill (prefix-cache hits, disaggregated prefills and
        drain migration all enter here). With BOTH ``prompt`` and
        ``snapshot`` plus ``prefill_from=p``, the snapshot is a
        page-aligned PREFIX of the prompt (``KVSnapshot.truncate``):
        admission adopts its pages and prefills only tokens ``p..n`` at
        ``q_start=p`` — the partial prefix-cache hit. Raises on a
        ``request_id`` still queued or in flight — the router's
        timeout/retry story needs duplicate submission to be loud, not
        silently doubled."""
        if request_id in self.request_ids():
            raise ValueError(f"duplicate request_id {request_id!r}: "
                             "still queued or in flight")
        if prefill_from is not None:
            if snapshot is None or prompt is None:
                raise ValueError("prefill_from= needs BOTH the full "
                                 "prompt and the prefix snapshot")
            prompt = list(prompt)
            p = int(prefill_from)
            self._validate_snapshot(snapshot)
            if p != snapshot.n_cached:
                raise ValueError(
                    f"prefill_from {p} != snapshot n_cached "
                    f"{snapshot.n_cached} — truncate() the snapshot to "
                    "the adopted boundary first")
            if p <= 0 or p % self.page_size != 0:
                raise ValueError(f"prefill_from {p} must be a positive "
                                 f"multiple of page_size "
                                 f"{self.page_size}")
            if p >= len(prompt):
                raise ValueError(
                    f"prefill_from {p} leaves no suffix of the "
                    f"{len(prompt)}-token prompt to prefill (an exact "
                    "hit adopts the snapshot without prefill_from)")
            if list(snapshot.prompt) != prompt[:p]:
                raise ValueError(
                    "snapshot prefix tokens differ from prompt[:"
                    f"{p}] — adopting them would silently change the "
                    "output")
        elif snapshot is not None:
            if prompt is not None:
                raise ValueError("pass prompt OR snapshot, not both "
                                 "(both only with prefill_from=)")
            if snapshot.is_prefix_only:
                raise ValueError(
                    "prefix-only snapshot (no emitted token) needs "
                    "prefill_from= and the full prompt — direct "
                    "adoption has no first token to continue from")
            self._validate_snapshot(snapshot)
            prompt = snapshot.prompt
        elif prompt is None:
            raise ValueError("submit needs a prompt or a snapshot")
        if len(prompt) > self.max_prompt:
            raise ValueError(f"prompt of {len(prompt)} tokens exceeds "
                             f"max_prompt {self.max_prompt}")
        if self._need_pages(len(prompt)) > self._pool_pages:
            # head-of-line admission would otherwise livelock on a
            # request the pool can NEVER satisfy (round-5 review)
            raise ValueError(
                f"request needs {self._need_pages(len(prompt))} pages "
                f"but the pool holds {self._pool_pages} — enlarge "
                "num_pages or shorten the prompt/budget")
        if prefill_from is not None:
            payload = _SuffixJob(prompt, snapshot, prefill_from)
        elif snapshot is not None:
            payload = snapshot
        else:
            payload = list(prompt)
        self.queue.append((request_id, payload, time.monotonic()))
        self._m_queue.set(len(self.queue))

    def cancel(self, request_id) -> bool:
        """Cancel a request: queued -> removed from the queue; in
        flight -> the slot is released and its pages freed. Nothing is
        reported through ``finished()`` or ``on_complete``. Returns
        False for an unknown (or already finished) id — cancellation
        racing completion is a benign no-op, which is exactly what the
        router's timeout/retry path needs."""
        for i, entry in enumerate(self.queue):
            if entry[0] == request_id:
                self.queue.pop(i)
                self._m_queue.set(len(self.queue))
                self._m_cancel.inc()
                return True
        for slot, s in enumerate(self.slots):
            if s is not None and s[0] == request_id:
                self._release(slot)
                self._m_cancel.inc()
                return True
        return False

    def _admit(self) -> None:
        for slot in range(self.max_batch):
            if self.slots[slot] is not None or not self.queue:
                continue
            rid, payload, t_submit = self.queue[0]
            if isinstance(payload, KVSnapshot):
                if not self._admit_snapshot(slot, rid, payload,
                                            t_submit):
                    break                 # admit in arrival order only
                continue
            if isinstance(payload, _SuffixJob):
                if not self._admit_suffix(slot, rid, payload, t_submit):
                    break                 # admit in arrival order only
                continue
            prompt = payload
            bucket = min(self._bucket(len(prompt)), self.max_prompt)
            pages_needed = self._need_pages(len(prompt))
            if pages_needed > self.cache.pages_free:
                break                     # admit in arrival order only
            self.queue.pop(0)
            pages = self.cache.alloc(pages_needed * self.page_size)
            self._pages[slot] = pages
            row = np.full((self.pages_per_slot,), self._scratch,
                          np.int32)
            row[:len(pages)] = pages
            self.table[slot] = row
            # bucketed single-row prefill: the array pads to the bucket
            # width (bounds compilations to O(log max_len) shapes) while
            # the explicit length keeps positions/logits at the true
            # prompt end; padding columns never write pages
            padded = np.ones((1, bucket), np.int32)
            padded[0, :len(prompt)] = prompt
            self._tev(rid, "prefill_start", kind="full", bucket=bucket,
                      prompt_len=len(prompt))
            self._trace_rid = rid
            t_p0 = time.monotonic()
            with trace.span("prefill", cat="serving", bucket=bucket,
                            prompt_len=len(prompt),
                            host_sync="first-token readback"):
                # lengths as an ARRAY: it is a traced operand, so the
                # compile-watch signature must key on its shape, not
                # the per-request value
                first, _ = self._prefill_fn(
                    self.model, self.cache, row[None, :], padded,
                    lengths=np.asarray([len(prompt)], np.int32),
                    **self._kernel_kw)
                # deliberate sync: TTFT is DEFINED by this readback
                tok0 = int(np.asarray(first)[0])  # jaxlint: disable=JX1
            self._trace_rid = None
            t_p1 = time.monotonic()
            # TTFT = queue wait + prefill, closed by the readback above;
            # the exemplar links the bucket to /requests/<id>
            self._m_ttft.observe(t_p1 - t_submit, exemplar=str(rid))
            self._tev(rid, "first_token", via="prefill")
            self._tev(rid, "prefill_end",
                      dur_s=round(t_p1 - t_p0, 9),
                      queue_s=round(t_p0 - t_submit, 9))
            self._m_admit.inc()
            self.slots[slot] = (rid, list(prompt), [tok0])
            self.lengths[slot] = len(prompt)
            self.last[slot] = tok0
            if self.on_prefill is not None:
                # fired BEFORE any decode write lands in the partial
                # page, so a captured snapshot is prefix-clean
                try:
                    self.on_prefill(rid, list(prompt),
                                    functools.partial(self._export_slot,
                                                      slot))
                except Exception:
                    logging.getLogger(__name__).exception(
                        "on_prefill hook failed for %r", rid)
            if self.eos_id is not None and tok0 == self.eos_id:
                self._retire(slot)

    def _admit_snapshot(self, slot: int, rid, snap: KVSnapshot,
                        t_submit) -> bool:
        """Adopt a :class:`KVSnapshot` into ``slot`` — allocation and
        bookkeeping as a normal admit, but the KV pages are scattered
        back from the snapshot and NO prefill runs (the measured
        "prefill skip")."""
        pages_needed = self._need_pages(len(snap.prompt))
        if pages_needed > self.cache.pages_free:
            return False
        self.queue.pop(0)
        pages = self.cache.alloc(pages_needed * self.page_size)
        self._pages[slot] = pages
        row = np.full((self.pages_per_slot,), self._scratch, np.int32)
        row[:len(pages)] = pages
        self.table[slot] = row
        with trace.span("adopt", cat="serving",
                        prompt_len=len(snap.prompt),
                        n_cached=snap.n_cached, n_pages=snap.n_pages):
            self._adopt_kv(pages, snap)
        # TTFT for an adopted request is queue wait alone: its first
        # token arrived with the snapshot (prefill was paid elsewhere —
        # or skipped entirely on a prefix-cache hit)
        wait = time.monotonic() - t_submit
        self._m_ttft.observe(wait, exemplar=str(rid))
        self._tev(rid, "adopt", n_cached=snap.n_cached,
                  queue_s=round(wait, 9))
        self._tev(rid, "first_token", via="adopt")
        self._m_admit.inc()
        self._m_skips.inc()
        got = list(snap.emitted)
        self.slots[slot] = (rid, list(snap.prompt), got)
        self.lengths[slot] = snap.n_cached
        self.last[slot] = snap.last_token
        hit_eos = (self.eos_id is not None
                   and self.eos_id in got[:self.max_new])
        if hit_eos or len(got) >= self.max_new:
            self._retire(slot)        # migrated right at the finish line
        return True

    def _admit_suffix(self, slot: int, rid, job: "_SuffixJob",
                      t_submit) -> bool:
        """Adopt a page-aligned prefix snapshot into ``slot`` and
        prefill ONLY the suffix at ``q_start=job.start`` — the partial
        prefix-cache hit. Pages cover the FULL prompt (the suffix
        writes land past the adopted pages); the first token comes off
        the suffix prefill's logits exactly where full prefill would
        have read them."""
        prompt, snap, p = job.prompt, job.snapshot, job.start
        pages_needed = self._need_pages(len(prompt))
        if pages_needed > self.cache.pages_free:
            return False
        self.queue.pop(0)
        pages = self.cache.alloc(pages_needed * self.page_size)
        self._pages[slot] = pages
        row = np.full((self.pages_per_slot,), self._scratch, np.int32)
        row[:len(pages)] = pages
        self.table[slot] = row
        suffix = prompt[p:]
        bucket = min(self._bucket(len(suffix)), self.max_prompt)
        padded = np.ones((1, bucket), np.int32)
        padded[0, :len(suffix)] = suffix
        self._tev(rid, "prefill_start", kind="suffix", bucket=bucket,
                  prompt_len=len(prompt), prefill_from=p)
        self._trace_rid = rid
        t_p0 = time.monotonic()
        with trace.span("suffix prefill", cat="serving", bucket=bucket,
                        prompt_len=len(prompt), prefill_from=p,
                        host_sync="first-token readback"):
            self._adopt_kv(pages, snap)
            first, _ = self._suffix_fn(
                self.model, self.cache, row[None, :], padded,
                start=np.asarray([p], np.int32),
                lengths=np.asarray([len(prompt)], np.int32),
                **self._kernel_kw)
            # deliberate sync: TTFT is DEFINED by this readback
            tok0 = int(np.asarray(first)[0])  # jaxlint: disable=JX1
        self._trace_rid = None
        t_p1 = time.monotonic()
        self._m_ttft.observe(t_p1 - t_submit, exemplar=str(rid))
        self._tev(rid, "first_token", via="suffix")
        self._tev(rid, "prefill_end", dur_s=round(t_p1 - t_p0, 9),
                  queue_s=round(t_p0 - t_submit, 9))
        self._m_admit.inc()
        self._m_suffix.inc()
        self.slots[slot] = (rid, list(prompt), [tok0])
        self.lengths[slot] = len(prompt)
        self.last[slot] = tok0
        if self.on_prefill is not None:
            # the FULL prompt is now cached and prefix-clean: capture
            # extends the fleet index to the longer prefix
            try:
                self.on_prefill(rid, list(prompt),
                                functools.partial(self._export_slot,
                                                  slot))
            except Exception:
                logging.getLogger(__name__).exception(
                    "on_prefill hook failed for %r", rid)
        if self.eos_id is not None and tok0 == self.eos_id:
            self._retire(slot)
        return True

    def _adopt_kv(self, pages, snap: KVSnapshot) -> None:
        idx = jnp.asarray(np.asarray(pages[:snap.n_pages], np.int32))
        kp, vp = list(self.cache.kp), list(self.cache.vp)
        for li, (k, v) in enumerate(snap.kv):
            kp[li] = _scatter_pages(kp[li], idx, jnp.asarray(k))
            vp[li] = _scatter_pages(vp[li], idx, jnp.asarray(v))
        self.cache.kp, self.cache.vp = tuple(kp), tuple(vp)

    def _export_kv(self, pages, n_cached: int):
        """Gather the pages covering ``n_cached`` tokens to host in ONE
        packed readback. The exported page count is bucketed (next
        power of two of the token count, clamped to the allocation) so
        gather shapes stay O(log max_len) per pool geometry."""
        n_exp = min(-(-self._bucket(n_cached) // self.page_size),
                    len(pages))
        idx = jnp.asarray(np.asarray(pages[:n_exp], np.int32))
        kvs = [(self.cache.kp[li][idx], self.cache.vp[li][idx])
               for li in range(self.cache.num_layers)]
        # deliberate sync: the snapshot IS a host artifact; one packed
        # readback for all layers (jaxlint JX1's sanctioned shape)
        return jax.device_get(kvs)

    def _export_slot(self, slot: int) -> KVSnapshot:
        rid, prompt, got = self.slots[slot]
        n_cached = int(self.lengths[slot])
        with trace.span("export", cat="serving", prompt_len=len(prompt),
                        n_cached=n_cached,
                        host_sync="packed KV page readback"):
            kv = self._export_kv(self._pages[slot], n_cached)
        self._m_export.inc()
        return KVSnapshot(prompt, n_cached, kv, int(self.last[slot]),
                          got, self.page_size,
                          weight_version=self.weight_version)

    def export_request(self, request_id) -> KVSnapshot:
        """Export one IN-FLIGHT request for handoff: gathers its KV
        pages to host, frees the slot, and returns the snapshot —
        ``submit(rid, snapshot=...)`` on another identically configured
        batcher resumes it mid-decode, bitwise. Queued requests cannot
        be exported (there is nothing cached yet — ``pop_queued`` and
        resubmit instead); raises KeyError for unknown ids."""
        for slot, s in enumerate(self.slots):
            if s is not None and s[0] == request_id:
                t0 = time.monotonic()
                snap = self._export_slot(slot)
                self._release(slot)
                self._tev(request_id, "export", n_cached=snap.n_cached,
                          dur_s=round(time.monotonic() - t0, 9))
                return snap
        raise KeyError(f"request {request_id!r} is not in flight")

    def export_requests(self) -> list:
        """Export EVERY in-flight request (drain migration): returns
        ``[(request_id, KVSnapshot), ...]`` and leaves all slots
        free."""
        out = []
        for slot, s in enumerate(self.slots):
            if s is not None:
                t0 = time.monotonic()
                snap = self._export_slot(slot)
                self._release(slot)
                self._tev(s[0], "export", n_cached=snap.n_cached,
                          dur_s=round(time.monotonic() - t0, 9))
                out.append((s[0], snap))
        return out

    def pop_queued(self) -> list:
        """Remove and return every still-QUEUED entry as
        ``[(request_id, prompt_or_snapshot), ...]`` — on drain the
        router re-dispatches these to the surviving replicas. A queued
        suffix job unwraps to its FULL prompt: re-dispatch re-queries
        the fleet prefix index, which recovers the reuse (or better)
        on whichever replica admits it."""
        out = [(rid, payload.prompt if isinstance(payload, _SuffixJob)
                else payload) for rid, payload, _ in self.queue]
        self.queue = []
        self._m_queue.set(0)
        return out

    def prefill_only(self, request_id, prompt) -> KVSnapshot:
        """Run ONLY the prefill for ``prompt`` and hand the resulting
        KV back as a :class:`KVSnapshot`; the pages are freed again
        before returning, so this batcher keeps nothing. The
        disaggregation primitive: a long prompt prefills on a
        designated/low-load replica and the snapshot is adopted by a
        decode replica, whose decode bursts never stall behind it."""
        if len(prompt) > self.max_prompt:
            raise ValueError(f"prompt of {len(prompt)} tokens exceeds "
                             f"max_prompt {self.max_prompt}")
        bucket = min(self._bucket(len(prompt)), self.max_prompt)
        n_table = -(-bucket // self.page_size)
        n_real = min(n_table, -(-len(prompt) // self.page_size))
        pages = self.cache.alloc(n_real * self.page_size)
        try:
            row = np.full((n_table,), self._scratch, np.int32)
            row[:len(pages)] = pages
            padded = np.ones((1, bucket), np.int32)
            padded[0, :len(prompt)] = prompt
            with trace.span("prefill_only", cat="serving", bucket=bucket,
                            prompt_len=len(prompt),
                            host_sync="first-token readback"):
                first, _ = self._prefill_fn(
                    self.model, self.cache, row[None, :], padded,
                    lengths=np.asarray([len(prompt)], np.int32),
                    **self._kernel_kw)
                # deliberate sync: the first token rides the snapshot
                tok0 = int(np.asarray(first)[0])  # jaxlint: disable=JX1
            kv = self._export_kv(pages, len(prompt))
            self._m_export.inc()
        finally:
            self.cache.free(pages)
        return KVSnapshot(prompt, len(prompt), kv, tok0, [tok0],
                          self.page_size,
                          weight_version=self.weight_version)

    def _release(self, slot: int) -> None:
        """Free a slot's pages and reset its row — no result
        recorded (shared by retire / cancel / export)."""
        self.cache.free(self._pages[slot])
        self._pages[slot] = None
        self.slots[slot] = None
        self.table[slot] = self._scratch
        self.lengths[slot] = 0
        self.last[slot] = 1

    def _retire(self, slot: int) -> None:
        rid, _, toks = self.slots[slot]
        if self.eos_id is not None and self.eos_id in toks:
            toks = toks[:toks.index(self.eos_id) + 1]
        result = toks[:self.max_new]
        self._done.append((rid, result))
        self._release(slot)
        self._m_retire.inc()
        self._tev(rid, "retire", tokens=len(result))
        if self.on_complete is not None:
            # a crashing hook must not take the step loop down with it
            try:
                self.on_complete(rid, result)
            except Exception:
                logging.getLogger(__name__).exception(
                    "on_complete hook failed for %r", rid)

    def _resolve_burst(self, burst: int | None) -> int:
        """``None`` -> the largest default the construction allows
        (``min(8, max_burst)`` — a ``max_burst < 8`` batcher must work
        with no-arg calls, ADVICE.md)."""
        if burst is None:
            burst = min(8, self.max_burst)
        if burst > self.max_burst:
            raise ValueError(f"burst {burst} exceeds max_burst "
                             f"{self.max_burst} (page allocations carry "
                             "max_burst-1 overshoot slack)")
        return burst

    def warmup(self, *, bursts=(None,), prompt_buckets=()) -> dict:
        """Pre-build (compile or AOT-cache-load) the decode
        executable(s) — and, per entry in ``prompt_buckets``, the
        admission-shaped prefill executable — WITHOUT executing
        anything: lowering is shape-only, so a freshly added replica is
        ready before it takes traffic. With a warm cache the cost is
        deserialize time (~10 ms/step), not XLA compile time; with a
        cold one this pays the compile up front and stores it for every
        later replica. No-op without ``aot_cache``. Returns
        ``{"prepared": n, "hits": h, "misses": m}`` (cache counters are
        pool-lifetime totals)."""
        if self.aot is None:
            return {"prepared": 0, "hits": 0, "misses": 0}
        prepared = 0
        for b in bursts:
            burst = self._resolve_burst(b)
            paged_decode(self.model, self.cache, self.table,
                         self.lengths, self.last, burst,
                         warm_only=True, **self._kernel_kw)
            prepared += 1
        for n_tokens in prompt_buckets:
            bucket = min(self._bucket(int(n_tokens)), self.max_prompt)
            padded = np.ones((1, bucket), np.int32)
            row = np.full((1, self.pages_per_slot), self._scratch,
                          np.int32)
            paged_prefill(self.model, self.cache, row, padded,
                          lengths=np.asarray([bucket], np.int32),
                          warm_only=True, **self._kernel_kw)
            prepared += 1
        return {"prepared": prepared, "hits": self.aot.hits,
                "misses": self.aot.misses}

    def step(self, burst: int | None = None) -> int:
        """Admit + decode one fixed-shape burst; returns the number of
        ACTIVE rows that decoded. ``burst=None`` resolves to
        ``min(8, max_burst)``."""
        burst = self._resolve_burst(burst)
        self._admit()
        active = [i for i, s in enumerate(self.slots) if s is not None]
        self._m_queue.set(len(self.queue))
        self._m_active.set(len(active))
        used = self.cache.num_pages - self.cache.pages_free
        self._m_util.set(used / self.cache.num_pages)
        if not active:
            return 0
        # free slots re-decode into the scratch page from length 0 every
        # burst so their positions never outgrow the capacity check
        for i in range(self.max_batch):
            if self.slots[i] is None:
                self.lengths[i] = 0
        # a decode burst is batch-wide: its compiles attribute to no
        # single request
        self._trace_rid = None
        t0 = time.monotonic()
        with trace.span("decode burst", cat="serving", burst=burst,
                        active=len(active),
                        host_sync="token readback"):
            toks, new_len = self._decode_fn(self.model, self.cache,
                                            self.table, self.lengths,
                                            self.last, n_new=burst,
                                            **self._kernel_kw)
            toks = np.asarray(toks)
        dt = time.monotonic() - t0
        self._m_tok_lat.observe(dt / burst)
        self._m_tokens.inc(len(active) * burst)
        # stall detection: a burst whose per-token latency blows past
        # the tracker's threshold (stall_factor x the SLO per-token
        # target) books the excess as stall seconds on every active
        # request — the attribution component that separates "decode
        # was busy" from "decode was stuck"
        stall = 0.0
        tr = self.tracker
        if tr is not None:
            th = tr.stall_threshold_s
            if th != float("inf") and dt / burst > th:
                stall = dt - th * burst
        self.lengths = np.asarray(new_len, np.int32).copy()
        for i in active:
            rid, prompt, got = self.slots[i]
            got.extend(int(t) for t in toks[i])
            self.last[i] = int(toks[i, -1])
            self.slots[i] = (rid, prompt, got)
            self._tev(rid, "decode", tokens=burst, dur_s=round(dt, 9),
                      stall_s=round(stall, 9))
            hit_eos = (self.eos_id is not None
                       and self.eos_id in got[:self.max_new])
            if hit_eos or len(got) >= self.max_new:
                self._retire(i)
        self._step_count += 1
        used = self.cache.num_pages - self.cache.pages_free
        self._m_util.set(used / self.cache.num_pages)
        self._m_active.set(sum(s is not None for s in self.slots))
        if self.summary is not None:
            s, n = self.summary, self._step_count
            s.add_scalar("ActiveSlots", len(active), n)
            s.add_scalar("QueueDepth", len(self.queue), n)
            s.add_scalar("KVPageUtilization",
                         used / self.cache.num_pages, n)
            s.add_scalar("DecodeTokensPerSec",
                         len(active) * burst / max(dt, 1e-9), n)
        return len(active)

    def finished(self):
        """Pop (request_id, tokens) results completed so far."""
        out, self._done = self._done, []
        return out

    @property
    def idle(self) -> bool:
        return not self.queue and all(s is None for s in self.slots)

    def run_to_completion(self, burst: int | None = None,
                          max_steps: int = 10000):
        """Drive step() until every submitted request finishes.
        ``burst=None`` resolves to ``min(8, max_burst)`` per step."""
        steps = 0
        while not self.idle:
            self.step(burst)
            steps += 1
            if steps > max_steps:
                raise RuntimeError("continuous batcher did not converge "
                                   f"in {max_steps} steps")
        return self.finished()

"""Transformer language model — the long-context flagship.

Beyond the reference's scope (its era ends at scan RNNs, SURVEY §5.7),
but the capability target this framework treats as first-class: a causal
decoder whose attention core can run locally, ring-parallel, or
Ulysses-parallel over the mesh ``seq`` axis (nn/attention.py +
parallel/sequence.py) without touching the parameters. Pre-LN blocks,
learned positional embeddings, weight-tied-free output head.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Container, Module
from bigdl_tpu.tensor import activation_dtype, default_dtype

__all__ = ["TransformerLM", "TransformerBlock"]


class _Residual(Container):
    """y = x + inner(norm(x)) — pre-LN residual wrapper."""

    def __init__(self, d_model: int, inner: Module):
        super().__init__(nn.LayerNorm(d_model), inner)

    def apply(self, params, state, x, *, training=False, rng=None):
        h, s0 = self.modules[0].apply(params["0"], state["0"], x,
                                      training=training)
        h, s1 = self.modules[1].apply(params["1"], state["1"], h,
                                      training=training, rng=rng)
        return x + h, {"0": s0, "1": s1}


def TransformerBlock(d_model: int, num_heads: int, ffn_mult: int = 4,
                     dropout: float = 0.0,
                     sequence_parallel: str | None = None,
                     rope: bool = False,
                     num_kv_heads: int | None = None):
    """Pre-LN block: x + MHA(LN(x)); x + FFN(LN(x))."""
    mha = nn.MultiHeadAttention(d_model, num_heads, causal=True,
                                sequence_parallel=sequence_parallel,
                                rope=rope, num_kv_heads=num_kv_heads)
    ffn = (nn.Sequential()
           .add(nn.Linear(d_model, ffn_mult * d_model))
           .add(nn.ReLU())
           .add(nn.Linear(ffn_mult * d_model, d_model)))
    if dropout > 0:
        ffn.add(nn.Dropout(dropout))
    return (nn.Sequential()
            .add(_Residual(d_model, mha))
            .add(_Residual(d_model, ffn)))


class _TokenAndPosition(Module):
    """LookupTable embedding + learned positional embedding (or token
    embedding alone under ``with_pos=False`` — the RoPE recipe, where
    position enters through the attention rotation instead)."""

    def __init__(self, vocab: int, d_model: int, max_len: int,
                 with_pos: bool = True):
        super().__init__()
        self.vocab, self.d_model, self.max_len = vocab, d_model, max_len
        self.with_pos = with_pos

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        scale = 1.0 / np.sqrt(self.d_model)
        p = {"tok": jax.random.normal(
            k1, (self.vocab, self.d_model), default_dtype()) * scale}
        if self.with_pos:
            p["pos"] = jax.random.normal(
                k2, (self.max_len, self.d_model), default_dtype()) * scale
        return p

    def apply(self, params, state, x, *, training=False, rng=None):
        # x: (batch, seq) 1-based token ids (LookupTable convention)
        idx = x.astype(jnp.int32) - 1
        s = x.shape[1]
        y = jnp.take(params["tok"], jnp.clip(idx, 0, self.vocab - 1),
                     axis=0)
        if self.with_pos:
            y = y + params["pos"][:s]
        return y.astype(activation_dtype()), state


def TransformerLM(vocab_size: int, d_model: int = 128, num_heads: int = 4,
                  num_layers: int = 2, max_len: int = 512,
                  ffn_mult: int = 4, dropout: float = 0.0,
                  sequence_parallel: str | None = None,
                  with_log_softmax: bool = True,
                  pos_encoding: str = "learned",
                  num_kv_heads: int | None = None) -> nn.Sequential:
    """Causal LM: tokens (B, S) -> log-probs (B, S, vocab).

    ``with_log_softmax=False`` ends at raw logits — pair it with
    ``CrossEntropyCriterion`` to skip materializing the f32 log-prob
    tensor (the memory-lean LM training recipe, docs/PERF.md).

    ``pos_encoding``: "learned" (additive table, capped at ``max_len``)
    or "rope" (rotary q/k rotation inside attention — no additive table,
    no hard length cap beyond the decode cache's allocation).

    ``num_kv_heads`` < ``num_heads`` selects grouped-query attention:
    the decode KV cache shrinks by num_heads/num_kv_heads (the
    batch-scaling lever for serving; generate.py keeps the cache at kv
    heads and groups queries instead of repeating keys).
    """
    if pos_encoding not in ("learned", "rope"):
        raise ValueError(f"pos_encoding={pos_encoding!r}")
    rope = pos_encoding == "rope"
    model = (nn.Sequential()
             .add(_TokenAndPosition(vocab_size, d_model, max_len,
                                    with_pos=not rope)
                  .set_name("embed")))
    for i in range(num_layers):
        model.add(TransformerBlock(
            d_model, num_heads, ffn_mult, dropout,
            sequence_parallel, rope=rope,
            num_kv_heads=num_kv_heads).set_name(f"block_{i}"))
    model.add(nn.LayerNorm(d_model).set_name("final_norm"))
    model.add(nn.Linear(d_model, vocab_size,
                        init_method=init_mod.Xavier).set_name("lm_head"))
    if with_log_softmax:
        model.add(nn.LogSoftMax())
    # decode-path metadata (models/transformer/generate.py)
    model.lm_meta = {"num_layers": num_layers, "num_heads": num_heads,
                     "max_len": max_len, "d_model": d_model,
                     "vocab": vocab_size, "pos_encoding": pos_encoding,
                     "num_kv_heads": num_kv_heads}
    return model

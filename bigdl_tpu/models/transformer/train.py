"""Transformer LM training main — the long-context counterpart of the
SimpleRNN main (models/rnn/train.py): same text pipeline (tokenize, pad,
dictionary-encode), causal next-token objective, but attention blocks
that can shard the sequence over the mesh ``seq`` axis.

Run: ``python -m bigdl_tpu.models.transformer.train -f <dir_with_input.txt>
[--seqLength 128] [--sequenceParallel ring|ulysses]``.
"""
from __future__ import annotations

import os

from bigdl_tpu.models.utils.cli import (base_train_parser, init_engine,
                                        setup_logging)


def main(argv=None):
    setup_logging()
    parser = base_train_parser("Train a Transformer LM")
    parser.add_argument("--vocabSize", type=int, default=4000)
    parser.add_argument("--dModel", type=int, default=128)
    parser.add_argument("--numHeads", type=int, default=4)
    parser.add_argument("--numLayers", type=int, default=2)
    parser.add_argument("--seqLength", type=int, default=128)
    parser.add_argument("--dropout", type=float, default=0.0)
    parser.add_argument("--sequenceParallel", default=None,
                        choices=[None, "ring", "ulysses"])
    args = parser.parse_args(argv)
    mesh = init_engine(args.chips)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset.dataset import LocalArrayDataSet
    from bigdl_tpu.dataset.text import (Dictionary, LabeledSentenceToSample,
                                        SentenceBiPadding, SentenceSplitter,
                                        SentenceTokenizer,
                                        TextToLabeledSentence)
    from bigdl_tpu.dataset.transformer import SampleToBatch
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.optim import (Loss, Optimizer, SGD, every_epoch,
                                 max_epoch)
    from bigdl_tpu.utils import file as bfile

    text_path = os.path.join(args.folder, "input.txt")
    with open(text_path) as f:
        text = f.read()
    sentences = list(SentenceSplitter()(iter([text])))
    tokens = list(SentenceTokenizer()(iter(sentences)))
    tokens = list(SentenceBiPadding()(iter(tokens)))
    dictionary = Dictionary(tokens, args.vocabSize)
    dictionary.save(args.checkpoint or args.folder)
    vocab = dictionary.get_vocab_size() + 1   # + OOV bucket

    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.dataset.transformer import Transformer

    class ToTokenIds(Transformer):
        """0-based dictionary indices -> the 1-based ids LookupTable-style
        embeddings consume (the RNN main feeds one-hots instead)."""

        def __call__(self, it):
            for s in it:
                yield Sample(s.feature.astype("int32") + 1, s.label)

    to_sample = (TextToLabeledSentence(dictionary)
                 >> LabeledSentenceToSample(
                     vocab, fixed_data_length=args.seqLength,
                     fixed_label_length=args.seqLength, one_hot=False)
                 >> ToTokenIds())
    samples = list(to_sample(iter(tokens)))
    split = max(1, int(len(samples) * 0.8))
    batch = args.batchSize or 32
    train_set = LocalArrayDataSet(samples[:split]) >> SampleToBatch(
        batch, drop_remainder=True)
    val_set = LocalArrayDataSet(samples[split:] or samples[:1]) \
        >> SampleToBatch(batch)

    model = (bfile.load_module(args.model) if args.model
             else TransformerLM(vocab, d_model=args.dModel,
                                num_heads=args.numHeads,
                                num_layers=args.numLayers,
                                max_len=args.seqLength,
                                dropout=args.dropout,
                                sequence_parallel=args.sequenceParallel))
    criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                            size_average=True)
    optimizer = Optimizer(model, train_set, criterion, mesh=mesh)
    optimizer.set_optim_method(SGD(
        learning_rate=args.learningRate or 0.02,
        learning_rate_decay=0.001))
    if args.state:
        optimizer.set_state(bfile.load(args.state))
    optimizer.set_validation(every_epoch(), val_set,
                             [Loss(criterion.clone_criterion())])
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, every_epoch())
        if args.overWrite:
            optimizer.overwrite_checkpoint()
    optimizer.set_end_when(max_epoch(args.maxEpoch or 10))
    optimizer.optimize()


if __name__ == "__main__":
    main()

"""Transformer LM training main — the long-context counterpart of the
SimpleRNN main (models/rnn/train.py): same text pipeline (shared in
models/utils/text_lm.py), causal next-token objective, but attention
blocks that can shard the sequence over the mesh ``seq`` axis.

Run: ``python -m bigdl_tpu.models.transformer.train -f <dir_with_input.txt>
[--seqLength 128] [--sequenceParallel ring|ulysses]``. With
``--sequenceParallel`` the mesh is built as {data: 1, seq: n_chips}; the
chip count must divide ``seqLength`` (and, for ulysses, ``numHeads``).
"""
from __future__ import annotations

from bigdl_tpu.models.utils.cli import (base_train_parser, init_engine,
                                        setup_logging)


def main(argv=None):
    setup_logging()
    parser = base_train_parser("Train a Transformer LM")
    parser.add_argument("--vocabSize", type=int, default=4000)
    parser.add_argument("--dModel", type=int, default=128)
    parser.add_argument("--numHeads", type=int, default=4)
    parser.add_argument("--numLayers", type=int, default=2)
    parser.add_argument("--seqLength", type=int, default=128)
    parser.add_argument("--dropout", type=float, default=0.0)
    parser.add_argument("--posEncoding", default="learned",
                        choices=["learned", "rope"])
    parser.add_argument("--numKvHeads", type=int, default=None,
                        help="< numHeads selects grouped-query attention")
    parser.add_argument("--sequenceParallel", default=None,
                        choices=[None, "ring", "ulysses"])
    args = parser.parse_args(argv)

    # ring/ulysses attention shards dim 1 over a 'seq' mesh axis — the
    # default data-only mesh cannot carry it
    mesh = init_engine(
        args.chips,
        axes=(lambda n: {"data": 1, "seq": n})
        if args.sequenceParallel else None)

    from bigdl_tpu import nn
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.models.utils.text_lm import build_text_lm_datasets
    from bigdl_tpu.optim import (Loss, Optimizer, SGD, every_epoch,
                                 max_epoch)
    from bigdl_tpu.utils import file as bfile

    batch = args.batchSize or 32
    train_set, val_set, vocab, _ = build_text_lm_datasets(
        args.folder, args.vocabSize, args.seqLength, batch,
        one_hot=False, dictionary_dir=args.checkpoint)

    # raw-logits head + flat CrossEntropy — the memory-lean LM recipe
    # (docs/PERF.md): no (B, S, V) f32 log-prob residual, and no
    # TimeDistributed vmap (CrossEntropyCriterion flattens (B, S, V)
    # itself; the vmap-over-T variant materialized a time-major f32
    # transpose of the logits)
    model = (bfile.load_module(args.model) if args.model
             else TransformerLM(vocab, d_model=args.dModel,
                                num_heads=args.numHeads,
                                num_layers=args.numLayers,
                                max_len=args.seqLength,
                                dropout=args.dropout,
                                sequence_parallel=args.sequenceParallel,
                                with_log_softmax=False,
                                pos_encoding=args.posEncoding,
                                num_kv_heads=args.numKvHeads))
    if isinstance(model.modules[-1], nn.LogSoftMax):
        # legacy snapshot with a log-softmax head: CE(log_softmax(x)) ==
        # CE(x) exactly (logsumexp of log-probs is 0), but keeping the
        # layer would materialize the (B, S, V) f32 log-prob tensor the
        # lean recipe exists to avoid — strip it (parameter-free)
        import logging
        logging.getLogger("bigdl_tpu").info(
            "stripping LogSoftMax head from loaded snapshot "
            "(raw-logits + CrossEntropy training recipe)")
        idx = str(len(model.modules) - 1)
        model.modules.pop()
        for tree in (model.params, model.state, model.grad_params):
            if isinstance(tree, dict):
                tree.pop(idx, None)
    criterion = nn.CrossEntropyCriterion()
    optimizer = Optimizer(model, train_set, criterion, mesh=mesh)
    optimizer.set_optim_method(SGD(
        learning_rate=args.learningRate or 0.02,
        learning_rate_decay=0.001))
    if args.state:
        optimizer.set_state(bfile.load(args.state))
    optimizer.set_validation(every_epoch(), val_set,
                             [Loss(criterion.clone_criterion())])
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, every_epoch())
        if args.overWrite:
            optimizer.overwrite_checkpoint()
    optimizer.set_end_when(max_epoch(args.maxEpoch or 10))
    optimizer.optimize()


if __name__ == "__main__":
    main()

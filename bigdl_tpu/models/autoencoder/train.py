"""Autoencoder MNIST training main (reference models/autoencoder/Train.scala
— MSE reconstruction, target = input image)."""
from __future__ import annotations

import numpy as np

from bigdl_tpu.models.lenet.train import find
from bigdl_tpu.models.utils.cli import (base_train_parser, init_engine,
                                        setup_logging)


def main(argv=None):
    setup_logging()
    parser = base_train_parser("Train Autoencoder on MNIST")
    args = parser.parse_args(argv)
    mesh = init_engine(args.chips)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import mnist
    from bigdl_tpu.dataset.dataset import LocalArrayDataSet
    from bigdl_tpu.dataset.sample import MiniBatch
    from bigdl_tpu.dataset.transformer import Transformer
    from bigdl_tpu.models import Autoencoder
    from bigdl_tpu.optim import Adagrad, Optimizer, max_epoch
    from bigdl_tpu.utils import file as bfile

    class GreyImgToReconstructionBatch(Transformer):
        """Batch with labels == flattened inputs (reference
        autoencoder/Train.scala toAutoencoderBatch)."""

        def __init__(self, batch_size):
            self.batch_size = batch_size

        def __call__(self, it):
            feats = []
            for img in it:
                feats.append(img.content[None])
                if len(feats) == self.batch_size:
                    data = np.stack(feats)
                    yield MiniBatch(data, data.reshape(len(feats), -1))
                    feats = []

    batch = args.batchSize or 150
    train = LocalArrayDataSet(mnist.load(
        find(args.folder,
             ["train-images-idx3-ubyte",
              "train-images.idx3-ubyte"]),
        find(args.folder,
             ["train-labels-idx1-ubyte",
              "train-labels.idx1-ubyte"])))
    train_set = train >> GreyImgToReconstructionBatch(batch)

    model = (bfile.load_module(args.model) if args.model
             else Autoencoder(class_num=32))
    optimizer = Optimizer(model, train_set, nn.MSECriterion(), mesh=mesh)
    optimizer.set_optim_method(Adagrad(
        learning_rate=args.learningRate or 0.01,
        learning_rate_decay=0.0))
    if args.checkpoint:
        from bigdl_tpu.optim import every_epoch
        optimizer.set_checkpoint(args.checkpoint, every_epoch())
    optimizer.set_end_when(max_epoch(args.maxEpoch or 10))
    optimizer.optimize()


if __name__ == "__main__":
    main()

"""MNIST autoencoder (reference models/autoencoder/Autoencoder.scala)."""
from __future__ import annotations

from bigdl_tpu.nn import Linear, ReLU, Reshape, Sequential, Sigmoid

__all__ = ["Autoencoder", "ROW_N", "COL_N", "FEATURE_SIZE"]

ROW_N = 28
COL_N = 28
FEATURE_SIZE = ROW_N * COL_N


def Autoencoder(class_num: int) -> Sequential:
    """784 -> classNum -> 784 sigmoid reconstruction net
    (reference Autoencoder.scala:27-35)."""
    return (Sequential()
            .add(Reshape((FEATURE_SIZE,)))
            .add(Linear(FEATURE_SIZE, class_num))
            .add(ReLU())
            .add(Linear(class_num, FEATURE_SIZE))
            .add(Sigmoid()))

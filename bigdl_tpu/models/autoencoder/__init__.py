"""autoencoder model family (reference models/autoencoder/)."""
from bigdl_tpu.models.autoencoder.model import *  # noqa: F401,F403

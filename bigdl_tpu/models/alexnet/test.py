"""AlexNet ImageNet evaluation main (mirrors the reference's per-model Test
shape, models/*/Test.scala; AlexNet lives in example/loadmodel there).

Run: ``python -m bigdl_tpu.models.alexnet.test -f <dir> --model <snap>``.
"""
from __future__ import annotations

from bigdl_tpu.models.utils.cli import (base_test_parser, init_engine,
                                        setup_logging)


def main(argv=None):
    setup_logging()
    parser = base_test_parser("Test AlexNet on ImageNet")
    parser.add_argument("--meanFile", default=None,
                        help=".npy per-pixel mean (AlexNet preprocessing)")
    args = parser.parse_args(argv)
    mesh = init_engine()

    from bigdl_tpu.examples.loadmodel.dataset_util import (
        AlexNetPreprocessor, ResNetPreprocessor)
    from bigdl_tpu.optim import Top1Accuracy, Top5Accuracy, Validator
    from bigdl_tpu.utils import file as bfile

    import os
    val_path = os.path.join(args.folder, "val")
    if not os.path.isdir(val_path):
        val_path = args.folder
    if args.meanFile:
        val_set = AlexNetPreprocessor(val_path, args.batchSize,
                                      args.meanFile)
    else:
        val_set = ResNetPreprocessor(val_path, args.batchSize)

    model = bfile.load_module(args.model)
    results = Validator(model, val_set, mesh=mesh).test(
        [Top1Accuracy(), Top5Accuracy()])
    for result, method in results:
        print(f"{method!r} is {result!r}")
    return results


if __name__ == "__main__":
    main()

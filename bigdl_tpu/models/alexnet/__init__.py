from bigdl_tpu.models.alexnet.model import AlexNet, AlexNet_OWT

"""AlexNet variants (reference example/loadmodel/AlexNet.scala).

``AlexNet_OWT`` — the "one weird trick" single-tower variant; ``AlexNet`` —
the original Caffe-compatible grouped model used for Caffe import
validation (reference AlexNet.scala:22-90).
"""
from __future__ import annotations

from bigdl_tpu.nn import (Dropout, Linear, LogSoftMax, ReLU, Sequential,
                          SpatialConvolution, SpatialCrossMapLRN,
                          SpatialMaxPooling, View)

__all__ = ["AlexNet", "AlexNet_OWT"]


def AlexNet_OWT(class_num: int, has_dropout: bool = True,
                first_layer_propagate_back: bool = False) -> Sequential:
    """(reference AlexNet.scala:24-53)"""
    model = Sequential()
    model.add(SpatialConvolution(3, 64, 11, 11, 4, 4, 2, 2, 1,
                                 propagate_back=first_layer_propagate_back)
              .set_name("conv1"))
    model.add(ReLU().set_name("relu1"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool1"))
    model.add(SpatialConvolution(64, 192, 5, 5, 1, 1, 2, 2).set_name("conv2"))
    model.add(ReLU().set_name("relu2"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool2"))
    model.add(SpatialConvolution(192, 384, 3, 3, 1, 1, 1, 1).set_name("conv3"))
    model.add(ReLU().set_name("relu3"))
    model.add(SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1).set_name("conv4"))
    model.add(ReLU().set_name("relu4"))
    model.add(SpatialConvolution(256, 256, 3, 3, 1, 1, 1, 1).set_name("conv5"))
    model.add(ReLU().set_name("relu5"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool5"))
    model.add(View(256 * 6 * 6))
    model.add(Linear(256 * 6 * 6, 4096).set_name("fc6"))
    model.add(ReLU().set_name("relu6"))
    if has_dropout:
        model.add(Dropout(0.5).set_name("drop6"))
    model.add(Linear(4096, 4096).set_name("fc7"))
    model.add(ReLU().set_name("relu7"))
    if has_dropout:
        model.add(Dropout(0.5).set_name("drop7"))
    model.add(Linear(4096, class_num).set_name("fc8"))
    model.add(LogSoftMax())
    return model


def AlexNet(class_num: int) -> Sequential:
    """Caffe-layout AlexNet with grouped convolutions and LRN
    (reference AlexNet.scala:56-90)."""
    model = Sequential()
    model.add(SpatialConvolution(3, 96, 11, 11, 4, 4, 0, 0, 1,
                                 propagate_back=False).set_name("conv1"))
    model.add(ReLU().set_name("relu1"))
    model.add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("norm1"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool1"))
    model.add(SpatialConvolution(96, 256, 5, 5, 1, 1, 2, 2, 2)
              .set_name("conv2"))
    model.add(ReLU().set_name("relu2"))
    model.add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("norm2"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool2"))
    model.add(SpatialConvolution(256, 384, 3, 3, 1, 1, 1, 1).set_name("conv3"))
    model.add(ReLU().set_name("relu3"))
    model.add(SpatialConvolution(384, 384, 3, 3, 1, 1, 1, 1, 2)
              .set_name("conv4"))
    model.add(ReLU().set_name("relu4"))
    model.add(SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1, 2)
              .set_name("conv5"))
    model.add(ReLU().set_name("relu5"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool5"))
    model.add(View(256 * 6 * 6))
    model.add(Linear(256 * 6 * 6, 4096).set_name("fc6"))
    model.add(ReLU().set_name("relu6"))
    model.add(Dropout(0.5).set_name("drop6"))
    model.add(Linear(4096, 4096).set_name("fc7"))
    model.add(ReLU().set_name("relu7"))
    model.add(Dropout(0.5).set_name("drop7"))
    model.add(Linear(4096, class_num).set_name("fc8"))
    model.add(LogSoftMax())
    return model

"""resnet model family (reference models/resnet/)."""
from bigdl_tpu.models.resnet.model import *  # noqa: F401,F403

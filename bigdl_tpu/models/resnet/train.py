"""ResNet CIFAR-10 training main (reference models/resnet/Train.scala).

Run: ``python -m bigdl_tpu.models.resnet.train -f <cifar10_binary_dir>``.
"""
from __future__ import annotations

import argparse

from bigdl_tpu.models.utils.cli import (base_train_parser, init_engine,
                                        setup_logging)


def main(argv=None):
    setup_logging()
    parser = base_train_parser("Train ResNet on CIFAR-10")
    parser.add_argument("--depth", type=int, default=20)
    parser.add_argument("--shortcutType", default="A")
    parser.add_argument("--nesterov", action=argparse.BooleanOptionalAction,
                        default=True)
    args = parser.parse_args(argv)
    mesh = init_engine(args.chips)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import cifar
    from bigdl_tpu.dataset.dataset import LocalArrayDataSet
    from bigdl_tpu.dataset.image import (BGRImgNormalizer, BGRImgRdmCropper,
                                         BGRImgToBatch, HFlip)
    from bigdl_tpu.models import ResNet, model_init
    from bigdl_tpu.optim import (EpochDecay, Optimizer, SGD, Top1Accuracy,
                                 every_epoch, max_epoch)
    from bigdl_tpu.utils import file as bfile

    batch = args.batchSize or 128
    train = LocalArrayDataSet(cifar.load_folder(args.folder, train=True))
    val = LocalArrayDataSet(cifar.load_folder(args.folder, train=False))
    train_set = train >> BGRImgRdmCropper(32, 32, 4) >> HFlip(0.5) \
        >> BGRImgNormalizer(cifar.TRAIN_MEAN, std_r=cifar.TRAIN_STD) \
        >> BGRImgToBatch(batch, drop_remainder=True)
    val_set = val >> BGRImgNormalizer(cifar.TRAIN_MEAN,
                                      std_r=cifar.TRAIN_STD) \
        >> BGRImgToBatch(batch)

    if args.model:
        model = bfile.load_module(args.model)
    else:
        model = ResNet(10, {"depth": args.depth,
                            "shortcutType": args.shortcutType,
                            "dataset": "cifar10"})
        model_init(model)   # He init sweep (reference ResNet.modelInit)

    # reference Train.scala: lr 0.1, wd 1e-4, momentum 0.9, nesterov,
    # lr x0.1 at epochs 81 and 122 (fb.resnet.torch recipe); the exponent
    # must be traceable since the schedule runs inside the jitted step
    import jax.numpy as jnp

    def fb_decay(epoch):
        return jnp.where(epoch >= 122, 2.0,
                         jnp.where(epoch >= 81, 1.0, 0.0))

    optimizer = Optimizer(model, train_set, nn.ClassNLLCriterion(), mesh=mesh)
    optimizer.set_optim_method(SGD(
        learning_rate=args.learningRate or 0.1,
        weight_decay=1e-4, momentum=0.9, dampening=0.0,
        nesterov=args.nesterov,
        learning_rate_schedule=EpochDecay(fb_decay)))
    if args.state:
        optimizer.set_state(bfile.load(args.state))
    optimizer.set_validation(every_epoch(), val_set, [Top1Accuracy()])
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, every_epoch())
        if args.overWrite:
            optimizer.overwrite_checkpoint()
    optimizer.set_end_when(max_epoch(args.maxEpoch or 165))
    optimizer.optimize()


if __name__ == "__main__":
    main()

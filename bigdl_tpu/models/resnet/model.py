"""ResNet for CIFAR-10 and ImageNet (reference models/resnet/ResNet.scala).

Reference parity: ``basicBlock``/``bottleneck`` residual builders
(ResNet.scala:161-199), shortcut types A/B/C (:142-159), depth configs
(:211-263), He ``modelInit`` (:102-130: conv ~ N(0, sqrt(2/(k*k*nOut))),
BN gamma=1 beta=0, linear bias=0).

TPU-first: the reference's ``optnet``/``shareGradInput`` buffer-sharing
(ResNet.scala:33-100) has no equivalent — XLA's buffer assignment already
reuses HBM across non-overlapping live ranges.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn import (CAddTable, Concat, ConcatTable, Identity, Linear,
                          MulConstant, ReLU, Sequential,
                          SpatialAveragePooling, SpatialBatchNormalization,
                          SpatialConvolution, SpatialMaxPooling, View)
from bigdl_tpu.nn.module import Container, Module
from bigdl_tpu.tensor import default_dtype

__all__ = ["ResNet", "ShortcutType", "DatasetType", "model_init"]


class ShortcutType:
    A = "A"  # zero-padded identity (CIFAR style)
    B = "B"  # 1x1 conv when shape changes (default)
    C = "C"  # 1x1 conv always


class DatasetType:
    CIFAR10 = "cifar10"
    ImageNet = "imagenet"


def _shortcut(n_input_plane, n_output_plane, stride, shortcut_type):
    """(reference ResNet.scala:142-159)"""
    use_conv = shortcut_type == ShortcutType.C or (
        shortcut_type == ShortcutType.B and n_input_plane != n_output_plane)
    if use_conv:
        return (Sequential()
                .add(SpatialConvolution(n_input_plane, n_output_plane, 1, 1,
                                        stride, stride))
                .add(SpatialBatchNormalization(n_output_plane)))
    if n_input_plane != n_output_plane:
        # type A: stride then zero-pad channels by concat with a zeroed copy
        return (Sequential()
                .add(SpatialAveragePooling(1, 1, stride, stride))
                .add(Concat(1)
                     .add(Identity())
                     .add(MulConstant(0.0))))
    return Identity()


def _residual(body, n_input_plane, n, stride, shortcut_type):
    return (Sequential()
            .add(ConcatTable()
                 .add(body)
                 .add(_shortcut(n_input_plane, n, stride, shortcut_type)))
            .add(CAddTable())
            .add(ReLU()))


def ResNet(class_num: int, opt: dict | None = None) -> Sequential:
    """Build ResNet (reference ResNet.scala:133-265).

    ``opt`` keys: depth (default 18), shortcutType (default B), dataset
    (default CIFAR10), optnet (accepted, ignored — XLA shares buffers).
    """
    opt = dict(opt or {})
    dataset = opt.get("dataset", DatasetType.CIFAR10)
    # reference default depth is 18, but 18 is invalid for its default
    # CIFAR-10 path ((depth-2)%6 != 0) — default to the smallest valid
    # depth per dataset instead of crashing
    depth = opt.get("depth", 18 if dataset == DatasetType.ImageNet else 20)
    shortcut_type = opt.get("shortcutType", ShortcutType.B)

    i_channels = [0]

    def basic_block(n, stride):
        """(reference ResNet.scala:161-177)"""
        n_input_plane = i_channels[0]
        i_channels[0] = n
        s = (Sequential()
             .add(SpatialConvolution(n_input_plane, n, 3, 3, stride, stride,
                                     1, 1))
             .add(SpatialBatchNormalization(n))
             .add(ReLU())
             .add(SpatialConvolution(n, n, 3, 3, 1, 1, 1, 1))
             .add(SpatialBatchNormalization(n)))
        return _residual(s, n_input_plane, n, stride, shortcut_type)

    def bottleneck(n, stride):
        """(reference ResNet.scala:179-199)"""
        n_input_plane = i_channels[0]
        i_channels[0] = n * 4
        s = (Sequential()
             .add(SpatialConvolution(n_input_plane, n, 1, 1, 1, 1, 0, 0))
             .add(SpatialBatchNormalization(n))
             .add(ReLU())
             .add(SpatialConvolution(n, n, 3, 3, stride, stride, 1, 1))
             .add(SpatialBatchNormalization(n))
             .add(ReLU())
             .add(SpatialConvolution(n, n * 4, 1, 1, 1, 1, 0, 0))
             .add(SpatialBatchNormalization(n * 4)))
        return _residual(s, n_input_plane, n * 4, stride, shortcut_type)

    def layer(block, features, count, stride=1):
        s = Sequential()
        for i in range(count):
            s.add(block(features, stride if i == 0 else 1))
        return s

    model = Sequential()
    if dataset == DatasetType.ImageNet:
        cfg = {18: ((2, 2, 2, 2), 512, basic_block),
               34: ((3, 4, 6, 3), 512, basic_block),
               50: ((3, 4, 6, 3), 2048, bottleneck),
               101: ((3, 4, 23, 3), 2048, bottleneck),
               152: ((3, 8, 36, 3), 2048, bottleneck),
               200: ((3, 24, 36, 3), 2048, bottleneck)}
        assert depth in cfg, f"Invalid depth {depth}"
        loop_config, n_features, block = cfg[depth]
        i_channels[0] = 64
        (model.add(SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3))
              .add(SpatialBatchNormalization(64))
              .add(ReLU())
              .add(SpatialMaxPooling(3, 3, 2, 2, 1, 1))
              .add(layer(block, 64, loop_config[0]))
              .add(layer(block, 128, loop_config[1], 2))
              .add(layer(block, 256, loop_config[2], 2))
              .add(layer(block, 512, loop_config[3], 2))
              .add(SpatialAveragePooling(7, 7, 1, 1))
              .add(View(n_features))
              .add(Linear(n_features, class_num)))
    elif dataset == DatasetType.CIFAR10:
        assert (depth - 2) % 6 == 0, \
            "depth should be one of 20, 32, 44, 56, 110, 1202"
        n = (depth - 2) // 6
        i_channels[0] = 16
        (model.add(SpatialConvolution(3, 16, 3, 3, 1, 1, 1, 1))
              .add(SpatialBatchNormalization(16))
              .add(ReLU())
              .add(layer(basic_block, 16, n))
              .add(layer(basic_block, 32, n, 2))
              .add(layer(basic_block, 64, n, 2))
              .add(SpatialAveragePooling(8, 8, 1, 1))
              .add(View(64))
              .add(Linear(64, class_num)))
    else:
        raise ValueError(f"Invalid dataset {dataset}")
    return model


def model_init(model: Module, rng=None):
    """He init sweep (reference ResNet.modelInit, ResNet.scala:102-130):
    conv weights ~ N(0, sqrt(2/(kW*kW*nOutputPlane))), bias 0; BN gamma 1,
    beta 0; Linear bias 0. Mutates the materialized params in place."""
    model.materialize()
    rng = rng if rng is not None else jax.random.PRNGKey(42)
    counter = [0]

    def sweep(m: Module):
        if isinstance(m, Container):
            for child in m.modules:
                sweep(child)
            return
        if isinstance(m, SpatialConvolution) and m.params:
            counter[0] += 1
            k = jax.random.fold_in(rng, counter[0])
            n = m.kw * m.kw * m.n_output_plane
            std = np.sqrt(2.0 / n)
            m.params["weight"] = std * jax.random.normal(
                k, m.params["weight"].shape, default_dtype())
            if "bias" in m.params:
                m.params["bias"] = jnp.zeros_like(m.params["bias"])
        elif isinstance(m, (SpatialBatchNormalization,)) and m.params:
            if "weight" in m.params:
                m.params["weight"] = jnp.ones_like(m.params["weight"])
            if "bias" in m.params:
                m.params["bias"] = jnp.zeros_like(m.params["bias"])
        elif isinstance(m, Linear) and m.params and "bias" in m.params:
            m.params["bias"] = jnp.zeros_like(m.params["bias"])

    sweep(model)
    # sweep assigns into the same per-module dicts the container tree
    # references, so model.params is already updated
    model.grad_params = jax.tree.map(jnp.zeros_like, model.params)
    return model

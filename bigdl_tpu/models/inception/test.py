"""Inception ImageNet evaluation main (reference models/inception/Test.scala).

Run: ``python -m bigdl_tpu.models.inception.test -f <imagenet_dir> --model
<snap>`` — ``--folder`` holds a ``val/`` class-per-subfolder tree (or is
itself such a tree).
"""
from __future__ import annotations

from bigdl_tpu.models.utils.cli import (base_test_parser, init_engine,
                                        setup_logging)


def main(argv=None):
    setup_logging()
    args = base_test_parser("Test Inception on ImageNet").parse_args(argv)
    mesh = init_engine()

    from bigdl_tpu.models.inception.train import build_pipeline
    from bigdl_tpu.optim import Top1Accuracy, Top5Accuracy, Validator
    from bigdl_tpu.utils import file as bfile

    val_set = build_pipeline(args.folder, args.batchSize, train=False)
    model = bfile.load_module(args.model)
    results = Validator(model, val_set, mesh=mesh).test(
        [Top1Accuracy(), Top5Accuracy()])
    for result, method in results:
        print(f"{method!r} is {result!r}")
    return results


if __name__ == "__main__":
    main()

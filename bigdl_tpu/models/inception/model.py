"""GoogLeNet Inception v1 / v2 (reference models/inception/).

Reference parity:
- ``Inception_Layer_v1`` (inception/Inception_v1.scala:24-56): four branches
  (1x1 / 3x3-reduce+3x3 / 5x5-reduce+5x5 / pool+proj) concatenated on the
  channel axis, Xavier init, ceil-mode pooling.
- ``Inception_v1`` with two auxiliary classifier heads whose LogSoftMax
  outputs concat with the main head (Inception_v1.scala:96-176); training
  uses a criterion over the (N, 3*classNum) concat.
- ``Inception_Layer_v2`` (inception/Inception_v2.scala:25-103): BN after
  every conv, double-3x3 tower instead of 5x5, avg/max pool switch, and
  downsample blocks (first-branch width 0 → stride-2, no 1x1/pool-proj).
- ``Inception_v2`` (Inception_v2.scala:151-236).

TPU-first: models are built from the pure-module combinators; one jit of
``model.apply`` compiles the whole branch-concat graph so XLA fuses the
reference's hand-threaded Concat copies (nn/Concat.scala:42-80) away.
"""
from __future__ import annotations

from bigdl_tpu.nn import (Concat, Dropout, Linear, LogSoftMax, ReLU,
                          ReLUCrossMapLRN, Remat,
                          Sequential, SpatialAveragePooling,
                          SpatialBatchNormalization, SpatialConvolution,
                          SpatialCrossMapLRN, SpatialMaxPooling, View)
from bigdl_tpu.nn import init as init_mod

__all__ = ["Inception_Layer_v1", "Inception_v1",
           "Inception_v1_NoAuxClassifier", "Inception_Layer_v2",
           "Inception_v2", "Inception_v2_NoAuxClassifier"]


def Inception_Layer_v1(input_size, config, name_prefix=""):
    """Branch-concat block (reference Inception_v1.scala:24-56).

    ``config`` = ((n1x1,), (n3x3r, n3x3), (n5x5r, n5x5), (npool,)).
    """
    concat = Concat(1).set_name(name_prefix + "output")
    conv1 = (Sequential()
             .add(SpatialConvolution(input_size, config[0][0], 1, 1, 1, 1,
                                     init_method=init_mod.Xavier)
                  .set_name(name_prefix + "1x1"))
             .add(ReLU().set_name(name_prefix + "relu_1x1")))
    concat.add(conv1)
    conv3 = (Sequential()
             .add(SpatialConvolution(input_size, config[1][0], 1, 1, 1, 1,
                                     init_method=init_mod.Xavier)
                  .set_name(name_prefix + "3x3_reduce"))
             .add(ReLU().set_name(name_prefix + "relu_3x3_reduce"))
             .add(SpatialConvolution(config[1][0], config[1][1], 3, 3, 1, 1,
                                     1, 1, init_method=init_mod.Xavier)
                  .set_name(name_prefix + "3x3"))
             .add(ReLU().set_name(name_prefix + "relu_3x3")))
    concat.add(conv3)
    conv5 = (Sequential()
             .add(SpatialConvolution(input_size, config[2][0], 1, 1, 1, 1,
                                     init_method=init_mod.Xavier)
                  .set_name(name_prefix + "5x5_reduce"))
             .add(ReLU().set_name(name_prefix + "relu_5x5_reduce"))
             .add(SpatialConvolution(config[2][0], config[2][1], 5, 5, 1, 1,
                                     2, 2, init_method=init_mod.Xavier)
                  .set_name(name_prefix + "5x5"))
             .add(ReLU().set_name(name_prefix + "relu_5x5")))
    concat.add(conv5)
    pool = (Sequential()
            .add(SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil()
                 .set_name(name_prefix + "pool"))
            .add(SpatialConvolution(input_size, config[3][0], 1, 1, 1, 1,
                                    init_method=init_mod.Xavier)
                 .set_name(name_prefix + "pool_proj"))
            .add(ReLU().set_name(name_prefix + "relu_pool_proj")))
    concat.add(pool)
    return concat


def _v1_stem():
    """conv1..pool2 shared stem (reference Inception_v1.scala:97-115)."""
    return (Sequential()
            .add(SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3, 1,
                                    propagate_back=False,
                                    init_method=init_mod.Xavier)
                 .set_name("conv1/7x7_s2"))
            # ReLU AFTER the stride-2 pool: relu(maxpool(x)) ==
            # maxpool(relu(x)) exactly (max commutes with any monotone
            # map), and the elementwise pass runs on 56x56 instead of
            # 112x112 — 4x less traffic on the model's biggest
            # activation. The reference order (Inception_v1.scala:100) is
            # relu-then-pool; outputs and gradients are identical.
            .add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool1/3x3_s2"))
            # ...which lands the ReLU next to norm1: one fused HBM pass
            .add(ReLUCrossMapLRN(
                ReLU().set_name("conv1/relu_7x7"),
                SpatialCrossMapLRN(5, 0.0001, 0.75)
                .set_name("pool1/norm1")))
            .add(SpatialConvolution(64, 64, 1, 1, 1, 1,
                                    init_method=init_mod.Xavier)
                 .set_name("conv2/3x3_reduce"))
            .add(ReLU().set_name("conv2/relu_3x3_reduce"))
            .add(SpatialConvolution(64, 192, 3, 3, 1, 1, 1, 1,
                                    init_method=init_mod.Xavier)
                 .set_name("conv2/3x3"))
            # single-HBM-pass ReLU+LRN (nn.ReLUCrossMapLRN docstring);
            # child modules keep the reference names
            .add(ReLUCrossMapLRN(
                ReLU().set_name("conv2/relu_3x3"),
                SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("conv2/norm2")))
            .add(SpatialMaxPooling(3, 3, 2, 2).ceil()
                 .set_name("pool2/3x3_s2")))


def Inception_v1_NoAuxClassifier(class_num: int,
                                 remat: bool = False) -> Sequential:
    """(reference Inception_v1.scala:60-94)

    ``remat=True`` wraps each inception block in ``nn.Remat`` —
    pytree-transparent, so imports/fixtures are unaffected; backward
    recomputes block interiors instead of loading saved activations
    (measured on v5e: see docs/PERF.md remat section).
    """
    wrap = Remat if remat else (lambda m: m)
    model = _v1_stem()
    model.add(wrap(Inception_Layer_v1(
        192, ((64,), (96, 128), (16, 32), (32,)), "inception_3a/")))
    model.add(wrap(Inception_Layer_v1(
        256, ((128,), (128, 192), (32, 96), (64,)), "inception_3b/")))
    model.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool3/3x3_s2"))
    model.add(wrap(Inception_Layer_v1(
        480, ((192,), (96, 208), (16, 48), (64,)), "inception_4a/")))
    model.add(wrap(Inception_Layer_v1(
        512, ((160,), (112, 224), (24, 64), (64,)), "inception_4b/")))
    model.add(wrap(Inception_Layer_v1(
        512, ((128,), (128, 256), (24, 64), (64,)), "inception_4c/")))
    model.add(wrap(Inception_Layer_v1(
        512, ((112,), (144, 288), (32, 64), (64,)), "inception_4d/")))
    model.add(wrap(Inception_Layer_v1(
        528, ((256,), (160, 320), (32, 128), (128,)), "inception_4e/")))
    model.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool4/3x3_s2"))
    model.add(wrap(Inception_Layer_v1(
        832, ((256,), (160, 320), (32, 128), (128,)), "inception_5a/")))
    model.add(wrap(Inception_Layer_v1(
        832, ((384,), (192, 384), (48, 128), (128,)), "inception_5b/")))
    model.add(SpatialAveragePooling(7, 7, 1, 1).set_name("pool5/7x7_s1"))
    model.add(Dropout(0.4).set_name("pool5/drop_7x7_s1"))
    model.add(View(1024))
    model.add(Linear(1024, class_num, init_method=init_mod.Xavier)
              .set_name("loss3/classifier"))
    model.add(LogSoftMax().set_name("loss3/loss3"))
    return model


def Inception_v1(class_num: int) -> Sequential:
    """Full training graph with two auxiliary heads whose outputs concat
    with the main head on the feature axis (reference
    Inception_v1.scala:96-176);
    output shape (N, 3*classNum), head order [main, aux2, aux1]."""
    feature1 = _v1_stem()
    feature1.add(Inception_Layer_v1(
        192, ((64,), (96, 128), (16, 32), (32,)), "inception_3a/"))
    feature1.add(Inception_Layer_v1(
        256, ((128,), (128, 192), (32, 96), (64,)), "inception_3b/"))
    feature1.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool3/3x3_s2"))
    feature1.add(Inception_Layer_v1(
        480, ((192,), (96, 208), (16, 48), (64,)), "inception_4a/"))

    output1 = (Sequential()
               .add(SpatialAveragePooling(5, 5, 3, 3).ceil()
                    .set_name("loss1/ave_pool"))
               .add(SpatialConvolution(512, 128, 1, 1, 1, 1,
                                       init_method=init_mod.Xavier)
                    .set_name("loss1/conv"))
               .add(ReLU().set_name("loss1/relu_conv"))
               .add(View(128 * 4 * 4))
               .add(Linear(128 * 4 * 4, 1024, init_method=init_mod.Xavier)
                    .set_name("loss1/fc"))
               .add(ReLU().set_name("loss1/relu_fc"))
               .add(Dropout(0.7).set_name("loss1/drop_fc"))
               .add(Linear(1024, class_num, init_method=init_mod.Xavier)
                    .set_name("loss1/classifier"))
               .add(LogSoftMax().set_name("loss1/loss")))

    feature2 = Sequential()
    feature2.add(Inception_Layer_v1(
        512, ((160,), (112, 224), (24, 64), (64,)), "inception_4b/"))
    feature2.add(Inception_Layer_v1(
        512, ((128,), (128, 256), (24, 64), (64,)), "inception_4c/"))
    feature2.add(Inception_Layer_v1(
        512, ((112,), (144, 288), (32, 64), (64,)), "inception_4d/"))

    output2 = (Sequential()
               .add(SpatialAveragePooling(5, 5, 3, 3)
                    .set_name("loss2/ave_pool"))
               .add(SpatialConvolution(528, 128, 1, 1, 1, 1,
                                       init_method=init_mod.Xavier)
                    .set_name("loss2/conv"))
               .add(ReLU().set_name("loss2/relu_conv"))
               .add(View(128 * 4 * 4))
               .add(Linear(128 * 4 * 4, 1024, init_method=init_mod.Xavier)
                    .set_name("loss2/fc"))
               .add(ReLU().set_name("loss2/relu_fc"))
               .add(Dropout(0.7).set_name("loss2/drop_fc"))
               .add(Linear(1024, class_num, init_method=init_mod.Xavier)
                    .set_name("loss2/classifier"))
               .add(LogSoftMax().set_name("loss2/loss")))

    output3 = Sequential()
    output3.add(Inception_Layer_v1(
        528, ((256,), (160, 320), (32, 128), (128,)), "inception_4e/"))
    output3.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool4/3x3_s2"))
    output3.add(Inception_Layer_v1(
        832, ((256,), (160, 320), (32, 128), (128,)), "inception_5a/"))
    output3.add(Inception_Layer_v1(
        832, ((384,), (192, 384), (48, 128), (128,)), "inception_5b/"))
    output3.add(SpatialAveragePooling(7, 7, 1, 1).set_name("pool5/7x7_s1"))
    output3.add(Dropout(0.4).set_name("pool5/drop_7x7_s1"))
    output3.add(View(1024))
    output3.add(Linear(1024, class_num, init_method=init_mod.Xavier)
                .set_name("loss3/classifier"))
    output3.add(LogSoftMax().set_name("loss3/loss3"))

    split2 = Concat(1).set_name("split2")
    split2.add(output3)
    split2.add(output2)

    main_branch = Sequential().add(feature2).add(split2)

    split1 = Concat(1).set_name("split1")
    split1.add(main_branch)
    split1.add(output1)

    return Sequential().add(feature1).add(split1)


def Inception_Layer_v2(input_size, config, name_prefix=""):
    """BN-everywhere v2 block (reference Inception_v2.scala:25-103).

    ``config`` = ((n1x1,), (n3x3r, n3x3), (nd3x3r, nd3x3), (pool, nproj))
    where pool is "avg"/"max"; n1x1 == 0 marks a stride-2 downsample block
    (no 1x1 branch, no pool projection).
    """
    concat = Concat(1).set_name(name_prefix + "output")
    downsample = config[0][0] == 0
    if not downsample:
        conv1 = (Sequential()
                 .add(SpatialConvolution(input_size, config[0][0], 1, 1, 1, 1)
                      .set_name(name_prefix + "1x1"))
                 .add(SpatialBatchNormalization(config[0][0], 1e-3)
                      .set_name(name_prefix + "1x1/bn"))
                 .add(ReLU().set_name(name_prefix + "1x1/bn/sc/relu")))
        concat.add(conv1)

    stride = 2 if downsample else 1
    conv3 = (Sequential()
             .add(SpatialConvolution(input_size, config[1][0], 1, 1, 1, 1)
                  .set_name(name_prefix + "3x3_reduce"))
             .add(SpatialBatchNormalization(config[1][0], 1e-3)
                  .set_name(name_prefix + "3x3_reduce/bn"))
             .add(ReLU().set_name(name_prefix + "3x3_reduce/bn/sc/relu"))
             .add(SpatialConvolution(config[1][0], config[1][1], 3, 3,
                                     stride, stride, 1, 1)
                  .set_name(name_prefix + "3x3"))
             .add(SpatialBatchNormalization(config[1][1], 1e-3)
                  .set_name(name_prefix + "3x3/bn"))
             .add(ReLU().set_name(name_prefix + "3x3/bn/sc/relu")))
    concat.add(conv3)

    conv3xx = (Sequential()
               .add(SpatialConvolution(input_size, config[2][0], 1, 1, 1, 1)
                    .set_name(name_prefix + "double3x3_reduce"))
               .add(SpatialBatchNormalization(config[2][0], 1e-3)
                    .set_name(name_prefix + "double3x3_reduce/bn"))
               .add(ReLU()
                    .set_name(name_prefix + "double3x3_reduce/bn/sc/relu"))
               .add(SpatialConvolution(config[2][0], config[2][1], 3, 3,
                                       1, 1, 1, 1)
                    .set_name(name_prefix + "double3x3a"))
               .add(SpatialBatchNormalization(config[2][1], 1e-3)
                    .set_name(name_prefix + "double3x3a/bn"))
               .add(ReLU().set_name(name_prefix + "double3x3a/bn/sc/relu"))
               .add(SpatialConvolution(config[2][1], config[2][1], 3, 3,
                                       stride, stride, 1, 1)
                    .set_name(name_prefix + "double3x3b"))
               .add(SpatialBatchNormalization(config[2][1], 1e-3)
                    .set_name(name_prefix + "double3x3b/bn"))
               .add(ReLU().set_name(name_prefix + "double3x3b/bn/sc/relu")))
    concat.add(conv3xx)

    pool = Sequential()
    pool_kind = config[3][0]
    if pool_kind == "max":
        if downsample:
            pool.add(SpatialMaxPooling(3, 3, 2, 2).ceil()
                     .set_name(name_prefix + "pool"))
        else:
            pool.add(SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil()
                     .set_name(name_prefix + "pool"))
    elif pool_kind == "avg":
        pool.add(SpatialAveragePooling(3, 3, 1, 1, 1, 1).ceil()
                 .set_name(name_prefix + "pool"))
    else:
        raise ValueError(f"unknown pool kind {pool_kind}")
    if config[3][1] != 0:
        pool.add(SpatialConvolution(input_size, config[3][1], 1, 1, 1, 1)
                 .set_name(name_prefix + "pool_proj"))
        pool.add(SpatialBatchNormalization(config[3][1], 1e-3)
                 .set_name(name_prefix + "pool_proj/bn"))
        pool.add(ReLU().set_name(name_prefix + "pool_proj/bn/sc/relu"))
    concat.add(pool)
    return concat


def _v2_stem():
    """conv1..pool2 with BN (reference Inception_v2.scala:107-119)."""
    return (Sequential()
            .add(SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3, 1,
                                    propagate_back=False)
                 .set_name("conv1/7x7_s2"))
            .add(SpatialBatchNormalization(64, 1e-3)
                 .set_name("conv1/7x7_s2/bn"))
            .add(ReLU().set_name("conv1/7x7_s2/bn/sc/relu"))
            .add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool1/3x3_s2"))
            .add(SpatialConvolution(64, 64, 1, 1).set_name("conv2/3x3_reduce"))
            .add(SpatialBatchNormalization(64, 1e-3)
                 .set_name("conv2/3x3_reduce/bn"))
            .add(ReLU().set_name("conv2/3x3_reduce/bn/sc/relu"))
            .add(SpatialConvolution(64, 192, 3, 3, 1, 1, 1, 1)
                 .set_name("conv2/3x3"))
            .add(SpatialBatchNormalization(192, 1e-3).set_name("conv2/3x3/bn"))
            .add(ReLU().set_name("conv2/3x3/bn/sc/relu"))
            .add(SpatialMaxPooling(3, 3, 2, 2).ceil()
                 .set_name("pool2/3x3_s2")))


def Inception_v2_NoAuxClassifier(class_num: int) -> Sequential:
    """(reference Inception_v2.scala:105-148)"""
    model = _v2_stem()
    model.add(Inception_Layer_v2(
        192, ((64,), (64, 64), (64, 96), ("avg", 32)), "inception_3a/"))
    model.add(Inception_Layer_v2(
        256, ((64,), (64, 96), (64, 96), ("avg", 64)), "inception_3b/"))
    model.add(Inception_Layer_v2(
        320, ((0,), (128, 160), (64, 96), ("max", 0)), "inception_3c/"))
    model.add(Inception_Layer_v2(
        576, ((224,), (64, 96), (96, 128), ("avg", 128)), "inception_4a/"))
    model.add(Inception_Layer_v2(
        576, ((192,), (96, 128), (96, 128), ("avg", 128)), "inception_4b/"))
    model.add(Inception_Layer_v2(
        576, ((160,), (128, 160), (128, 160), ("avg", 96)), "inception_4c/"))
    model.add(Inception_Layer_v2(
        576, ((96,), (128, 192), (160, 192), ("avg", 96)), "inception_4d/"))
    model.add(Inception_Layer_v2(
        576, ((0,), (128, 192), (192, 256), ("max", 0)), "inception_4e/"))
    model.add(Inception_Layer_v2(
        1024, ((352,), (192, 320), (160, 224), ("avg", 128)), "inception_5a/"))
    model.add(Inception_Layer_v2(
        1024, ((352,), (192, 320), (192, 224), ("max", 128)), "inception_5b/"))
    model.add(SpatialAveragePooling(7, 7, 1, 1).ceil()
              .set_name("pool5/7x7_s1"))
    model.add(View(1024))
    model.add(Linear(1024, class_num).set_name("loss3/classifier"))
    model.add(LogSoftMax().set_name("loss3/loss"))
    return model


def Inception_v2(class_num: int) -> Sequential:
    """Full v2 training graph with two aux heads (reference
    Inception_v2.scala:151-236); output (N, 3*classNum), heads
    [main, aux2, aux1]."""
    features1 = _v2_stem()
    features1.add(Inception_Layer_v2(
        192, ((64,), (64, 64), (64, 96), ("avg", 32)), "inception_3a/"))
    features1.add(Inception_Layer_v2(
        256, ((64,), (64, 96), (64, 96), ("avg", 64)), "inception_3b/"))
    features1.add(Inception_Layer_v2(
        320, ((0,), (128, 160), (64, 96), ("max", 0)), "inception_3c/"))

    output1 = (Sequential()
               .add(SpatialAveragePooling(5, 5, 3, 3).ceil()
                    .set_name("pool3/5x5_s3"))
               .add(SpatialConvolution(576, 128, 1, 1, 1, 1)
                    .set_name("loss1/conv"))
               .add(SpatialBatchNormalization(128, 1e-3)
                    .set_name("loss1/conv/bn"))
               .add(ReLU().set_name("loss1/conv/bn/sc/relu"))
               .add(View(128 * 4 * 4))
               .add(Linear(128 * 4 * 4, 1024).set_name("loss1/fc"))
               .add(ReLU().set_name("loss1/fc/bn/sc/relu"))
               .add(Linear(1024, class_num).set_name("loss1/classifier"))
               .add(LogSoftMax().set_name("loss1/loss")))

    features2 = Sequential()
    features2.add(Inception_Layer_v2(
        576, ((224,), (64, 96), (96, 128), ("avg", 128)), "inception_4a/"))
    features2.add(Inception_Layer_v2(
        576, ((192,), (96, 128), (96, 128), ("avg", 128)), "inception_4b/"))
    features2.add(Inception_Layer_v2(
        576, ((160,), (128, 160), (128, 160), ("avg", 96)), "inception_4c/"))
    features2.add(Inception_Layer_v2(
        576, ((96,), (128, 192), (160, 192), ("avg", 96)), "inception_4d/"))
    features2.add(Inception_Layer_v2(
        576, ((0,), (128, 192), (192, 256), ("max", 0)), "inception_4e/"))

    output2 = (Sequential()
               .add(SpatialAveragePooling(5, 5, 3, 3).ceil()
                    .set_name("pool4/5x5_s3"))
               .add(SpatialConvolution(1024, 128, 1, 1, 1, 1)
                    .set_name("loss2/conv"))
               .add(SpatialBatchNormalization(128, 1e-3)
                    .set_name("loss2/conv/bn"))
               .add(ReLU().set_name("loss2/conv/bn/sc/relu"))
               .add(View(128 * 2 * 2))
               .add(Linear(128 * 2 * 2, 1024).set_name("loss2/fc"))
               .add(ReLU().set_name("loss2/fc/bn/sc/relu"))
               .add(Linear(1024, class_num).set_name("loss2/classifier"))
               .add(LogSoftMax().set_name("loss2/loss")))

    output3 = Sequential()
    output3.add(Inception_Layer_v2(
        1024, ((352,), (192, 320), (160, 224), ("avg", 128)), "inception_5a/"))
    output3.add(Inception_Layer_v2(
        1024, ((352,), (192, 320), (192, 224), ("max", 128)), "inception_5b/"))
    output3.add(SpatialAveragePooling(7, 7, 1, 1).ceil()
                .set_name("pool5/7x7_s1"))
    output3.add(View(1024))
    output3.add(Linear(1024, class_num).set_name("loss3/classifier"))
    output3.add(LogSoftMax().set_name("loss3/loss"))

    split2 = Concat(1).set_name("split2")
    split2.add(output3)
    split2.add(output2)

    main_branch = Sequential().add(features2).add(split2)

    split1 = Concat(1).set_name("split1")
    split1.add(main_branch)
    split1.add(output1)

    return Sequential().add(features1).add(split1)

"""Inception-v1/v2 ImageNet training main (reference
models/inception/Train.scala + Options.scala).

Run: ``python -m bigdl_tpu.models.inception.train -f <imagenet_dir>`` where
the folder holds class-per-subdirectory images (train/ and val/). The
reference consumed Hadoop SequenceFiles of raw JPEGs; the TPU pipeline
reads image files directly with threaded decode + prefetch (MTImgToBatch).
"""
from __future__ import annotations

from bigdl_tpu.models.utils.cli import (base_train_parser, init_engine,
                                        setup_logging)

# ImageNet BGR pixel means used by the reference pipeline
# (inception/ImageNet2012.scala normalizer)
MEAN_RGB = (0.485, 0.456, 0.406)
STD_RGB = (0.229, 0.224, 0.225)


def build_pipeline(folder, batch, train, image_size=224, threads=None,
                   prefetch_sharding=None, device_normalize=True,
                   cache_bytes=0):
    """ImageNet input pipeline. Sharded record files (``*.brec``, produced
    by ``models.utils.imagenet_gen``) feed at pod speed — raw JPEG bytes
    stream from disk through per-worker decode threads with bounded
    prefetch (reference ImageNet2012.scala:25-100: SeqFiles ->
    MTLabeledBGRImgToBatch); a plain image folder is the small-scale
    fallback."""
    import glob as _glob
    import os

    from bigdl_tpu.dataset.dataset import LocalArrayDataSet
    from bigdl_tpu.dataset.image import (BGRImgCropper, BGRImgNormalizer,
                                         BytesToBGRImg, CropCenter,
                                         CropRandom, HFlip, LocalImageFiles,
                                         LocalImgReader, MTImgToBatch)
    from bigdl_tpu.dataset.recordio import (DevicePrefetcher,
                                            RecordShardDataSet,
                                            SHARD_SUFFIX)

    sub = os.path.join(folder, "train" if train else "val")
    root = sub if os.path.isdir(sub) else folder
    shards = sorted(_glob.glob(os.path.join(root, "*" + SHARD_SUFFIX)))

    augment = (BGRImgCropper(image_size, image_size,
                             CropRandom if train else CropCenter)
               >> HFlip(0.5 if train else 0.0)
               >> BGRImgNormalizer(MEAN_RGB, std_r=STD_RGB))
    if shards:
        import jax

        from bigdl_tpu import native
        ds = RecordShardDataSet(shards,
                                process_index=jax.process_index(),
                                process_count=jax.process_count())
        if native.available():
            # C++ decode core: no GIL, one call per batch; u8 crops out,
            # normalize on-device (dataset/image/native_batch.py — pair
            # with Optimizer.set_input_transform)
            from bigdl_tpu.dataset.image.native_batch import \
                NativeBRecToBatch
            out = ds >> NativeBRecToBatch(batch, image_size, image_size,
                                          train, MEAN_RGB, STD_RGB,
                                          num_threads=threads,
                                          device_normalize=device_normalize,
                                          cache_bytes=cache_bytes
                                          if train else 0)
            if prefetch_sharding is not None:
                out = out >> DevicePrefetcher(prefetch_sharding)
            return out
        inner = BytesToBGRImg() >> augment
    else:
        paths = LocalImageFiles.paths(root, shuffle=train)
        ds = LocalArrayDataSet(paths)
        inner = LocalImgReader(scale_to=256) >> augment
    out = ds >> MTImgToBatch(batch, inner, num_threads=threads)
    if prefetch_sharding is not None:
        out = out >> DevicePrefetcher(prefetch_sharding)
    return out


def main(argv=None):
    setup_logging()
    parser = base_train_parser("Train Inception on ImageNet")
    parser.add_argument("--modelName", default="inception-v1",
                        choices=["inception-v1", "inception-v2"])
    parser.add_argument("--classNum", type=int, default=1000)
    parser.add_argument("--maxIteration", type=int, default=62000)
    parser.add_argument("--decodeCacheGB", type=float, default=0.0,
                        help="decoded-image RAM cache budget (0 = off); "
                             "post-warm epochs skip JPEG decode")
    args = parser.parse_args(argv)
    mesh = init_engine(args.chips)

    from bigdl_tpu import nn
    from bigdl_tpu.models import (Inception_v1_NoAuxClassifier,
                                  Inception_v2_NoAuxClassifier)
    from bigdl_tpu.optim import (Optimizer, Poly, SGD, Top1Accuracy,
                                 Top5Accuracy, max_epoch, max_iteration,
                                 several_iteration)
    from bigdl_tpu.utils import file as bfile

    from bigdl_tpu.parallel.engine import data_sharding

    batch = args.batchSize or 256
    # prefetch train batches onto the mesh so host->device transfer
    # overlaps the device step (validation goes through eval_fn's own
    # padded placement)
    train_set = build_pipeline(args.folder, batch, train=True,
                               prefetch_sharding=data_sharding(mesh),
                               cache_bytes=int(args.decodeCacheGB * 1e9))
    val_set = build_pipeline(args.folder, batch, train=False)

    if args.model:
        model = bfile.load_module(args.model)
    elif args.modelName == "inception-v2":
        model = Inception_v2_NoAuxClassifier(args.classNum)
    else:
        model = Inception_v1_NoAuxClassifier(args.classNum)

    optimizer = Optimizer(model, train_set, nn.ClassNLLCriterion(), mesh=mesh)
    # u8 batches normalize on-device; f32 batches pass through unchanged
    from bigdl_tpu.dataset.image.device_transform import u8_to_model_input
    optimizer.set_input_transform(u8_to_model_input(MEAN_RGB, STD_RGB))
    # reference recipe (inception/Train.scala:70-88): lr 0.0898,
    # Poly(0.5, maxIteration). When the run ends on --maxEpoch instead,
    # the Poly horizon must follow it, or LR hits 0 mid-run and the rest
    # of the budget trains at lr=0.
    if args.maxEpoch:
        import math

        import jax
        # iterations/epoch uses the GLOBAL batch: every host consumes
        # `batch` records per step (distri_optimizer counts
        # batch * process_count toward the epoch)
        global_batch = batch * jax.process_count()
        poly_max = math.ceil(train_set.size() / global_batch) \
            * args.maxEpoch
    else:
        poly_max = args.maxIteration
    optimizer.set_optim_method(SGD(
        learning_rate=args.learningRate or 0.0898,
        weight_decay=0.0001, momentum=0.9,
        learning_rate_schedule=Poly(0.5, poly_max)))
    if args.state:
        optimizer.set_state(bfile.load(args.state))
    optimizer.set_validation(several_iteration(620), val_set,
                             [Top1Accuracy(), Top5Accuracy()])
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, several_iteration(620))
        if args.overWrite:
            optimizer.overwrite_checkpoint()
    # the reference recipe ends on iteration count (Train.scala:83);
    # honor an explicit --maxEpoch when the user passes one
    optimizer.set_end_when(max_epoch(args.maxEpoch) if args.maxEpoch
                           else max_iteration(args.maxIteration))
    optimizer.optimize()


if __name__ == "__main__":
    main()

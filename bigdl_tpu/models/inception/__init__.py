"""inception model family (reference models/inception/)."""
from bigdl_tpu.models.inception.model import *  # noqa: F401,F403

"""Character/word-level simple RNN LM (reference models/rnn/SimpleRNN.scala).

The reference model is ``Recurrent(RnnCell) -> Select(1,1) -> Linear`` and is
trained with batchSize=1 padded pipelines (rnn/Train.scala:57-68): Select
drops the singleton batch dim so Linear maps each timestep's hidden state to
vocab logits. ``SimpleRNN`` mirrors that exactly (0-based ``Select(0, 0)``);
``BatchedSimpleRNN`` is the TPU-friendly variant that keeps the batch dim via
``TimeDistributed`` so large batches feed the MXU.
"""
from __future__ import annotations

from bigdl_tpu.nn import (Linear, LogSoftMax, Recurrent, RnnCell, Select,
                          Sequential, TimeDistributed)

__all__ = ["SimpleRNN", "BatchedSimpleRNN"]


def SimpleRNN(input_size: int, hidden_size: int,
              output_size: int) -> Sequential:
    """(reference SimpleRNN.scala:22-35; batch-size-1 semantics)"""
    return (Sequential()
            .add(Recurrent(RnnCell(input_size, hidden_size, "tanh")))
            .add(Select(0, 0))
            .add(Linear(hidden_size, output_size)))


def BatchedSimpleRNN(input_size: int, hidden_size: int,
                     output_size: int) -> Sequential:
    """Batch-preserving variant: (N, T, I) -> (N, T, output) log-probs."""
    return (Sequential()
            .add(Recurrent(RnnCell(input_size, hidden_size, "tanh")))
            .add(TimeDistributed(Linear(hidden_size, output_size)))
            .add(LogSoftMax()))

"""SimpleRNN character/word LM training main (reference
models/rnn/Train.scala — WordTokenizer preprocessing, batchSize=1 padded
pipeline; SURVEY §5.7).

Run: ``python -m bigdl_tpu.models.rnn.train -f <dir_with_input.txt>``.
The TPU pipeline pads every sentence to the longest length and keeps the
batch dimension (BatchedSimpleRNN + TimeDistributedCriterion) so the MXU
sees real batches instead of the reference's batch-1 worst case.
"""
from __future__ import annotations


from bigdl_tpu.models.utils.cli import (base_train_parser, init_engine,
                                        setup_logging)


def main(argv=None):
    setup_logging()
    parser = base_train_parser("Train SimpleRNN LM")
    parser.add_argument("--vocabSize", type=int, default=4000)
    parser.add_argument("--hiddenSize", type=int, default=40)
    parser.add_argument("--seqLength", type=int, default=25)
    args = parser.parse_args(argv)
    mesh = init_engine(args.chips)

    from bigdl_tpu import nn
    from bigdl_tpu.models import BatchedSimpleRNN
    from bigdl_tpu.models.utils.text_lm import build_text_lm_datasets
    from bigdl_tpu.optim import (Loss, Optimizer, SGD, every_epoch, max_epoch)
    from bigdl_tpu.utils import file as bfile

    batch = args.batchSize or 32
    train_set, val_set, vocab, _ = build_text_lm_datasets(
        args.folder, args.vocabSize, args.seqLength, batch,
        one_hot=True, dictionary_dir=args.checkpoint)

    model = (bfile.load_module(args.model) if args.model
             else BatchedSimpleRNN(vocab, args.hiddenSize, vocab))
    criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                            size_average=True)
    optimizer = Optimizer(model, train_set, criterion, mesh=mesh)
    # reference rnn/Train.scala: SGD lr 0.1, decay 0.001, wd 0, momentum 0
    optimizer.set_optim_method(SGD(
        learning_rate=args.learningRate or 0.1,
        learning_rate_decay=0.001))
    if args.state:
        optimizer.set_state(bfile.load(args.state))
    optimizer.set_validation(every_epoch(), val_set,
                             [Loss(criterion.clone_criterion())])
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, every_epoch())
        if args.overWrite:
            optimizer.overwrite_checkpoint()
    optimizer.set_end_when(max_epoch(args.maxEpoch or 30))
    optimizer.optimize()


if __name__ == "__main__":
    main()

"""SimpleRNN text-generation main (reference models/rnn/Test.scala:38-92 —
load the saved Dictionary, read seed sentences from ``test.txt``, and
repeatedly sample the next word from the model's softmax distribution,
appending ``--numOfWords`` words per sentence).
"""
from __future__ import annotations

import logging
import os

import numpy as np

from bigdl_tpu.models.utils.cli import (base_test_parser, init_engine,
                                        setup_logging)

logger = logging.getLogger("bigdl_tpu.models.rnn")


def generate(model, dictionary, token_lists, num_words: int):
    """Autoregressive sampling loop (reference Test.scala:60-92: forward,
    softmax at the last step, inverse-CDF sample against a uniform)."""
    from bigdl_tpu.utils.random import RandomGenerator
    vocab = dictionary.get_vocab_size() + 1
    rng = RandomGenerator.RNG()
    seqs = [[dictionary.get_index(w) for w in toks] for toks in token_lists]
    for _ in range(num_words):
        nxt = []
        for seq in seqs:
            onehot = np.zeros((1, len(seq), vocab), np.float32)
            onehot[0, np.arange(len(seq)), np.asarray(seq, int)] = 1.0
            out = np.asarray(model.forward(onehot))     # (1, T, V) log-probs
            probs = np.exp(out[0, -1])
            probs = probs / probs.sum()
            cdf = np.cumsum(probs)
            # clamp: float32 rounding can leave cdf[-1] just under 1.0, and
            # searchsorted == len(cdf) would overflow the one-hot dim
            nxt.append(min(int(np.searchsorted(cdf, float(rng.uniform()))),
                           vocab - 1))
        seqs = [s + [w] for s, w in zip(seqs, nxt)]
    return [[dictionary.get_word(min(w, dictionary.get_vocab_size() - 1))
             for w in seq] for seq in seqs]


def main(argv=None):
    setup_logging()
    parser = base_test_parser("Test SimpleRNN LM (text generation)")
    parser.add_argument("--numOfWords", type=int, default=10)
    args = parser.parse_args(argv)
    init_engine()

    from bigdl_tpu.dataset.text import (Dictionary, SentenceSplitter,
                                        SentenceTokenizer)
    from bigdl_tpu.utils import file as bfile

    dictionary = Dictionary.load(args.folder)
    with open(os.path.join(args.folder, "test.txt")) as f:
        text = f.read()
    sentences = list(SentenceSplitter()(iter([text])))
    tokens = list(SentenceTokenizer()(iter(sentences)))

    model = bfile.load_module(args.model)
    model.evaluate()
    results = generate(model, dictionary, tokens, args.numOfWords)
    for words in results:
        logger.info(",".join(words))
        print(" ".join(words))
    return results


if __name__ == "__main__":
    main()

"""Autoregressive sampling for recurrent char/word LMs.

The reference's rnn example trains ``SimpleRNN`` on tokenized text
(models/rnn/Train.scala); this completes the family with the decode
loop, mirroring models/transformer/generate.py: hidden state is the
"cache", the decode step is one cell application, and the whole loop is
a single ``lax.scan`` — works for any ``Cell`` (RnnCell/LSTM/GRU)
inside the ``BatchedSimpleRNN`` shape
``Sequential(Recurrent(cell), TimeDistributed(Linear), LogSoftMax)``.

Inputs are 1-based token ids; the model consumes one-hot rows of width
``cell.input_size`` (the reference's LabeledSentence one-hot encoding).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["generate"]


def _parts(model, params):
    from bigdl_tpu.nn import Recurrent, TimeDistributed
    if not (len(model) >= 2 and isinstance(model[0], Recurrent)
            and isinstance(model[1], TimeDistributed)):
        raise ValueError(
            "generate expects Sequential(Recurrent(cell), "
            "TimeDistributed(Linear), ...) — the BatchedSimpleRNN shape")
    cell = model[0].cell
    return cell, params["0"]["0"], params["1"]["0"]


def generate(model, prompt, max_new_tokens: int = 32, *,
             temperature: float = 0.0, top_k: int | None = None,
             rng=None, params=None):
    """Decode ``max_new_tokens`` 1-based token ids after ``prompt``
    (B, P). temperature 0 = greedy; ``top_k`` truncates the softmax
    support when sampling."""
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got "
                         f"{max_new_tokens}")
    params = model.params if params is None else params
    cell, cell_p, lin_p = _parts(model, params)
    prompt = jnp.asarray(prompt)
    b, p_len = prompt.shape
    width = cell.input_size
    vocab = lin_p["weight"].shape[0]

    def onehot(tok):
        return jax.nn.one_hot(tok.astype(jnp.int32) - 1, width,
                              dtype=lin_p["weight"].dtype)

    def project(out):
        logits = out @ lin_p["weight"].T
        if "bias" in lin_p:                 # Linear(with_bias=False)
            logits = logits + lin_p["bias"]
        return logits.astype(jnp.float32)

    def cell_step(h, tok):
        (out, h_new), _ = cell.apply(cell_p, {}, (onehot(tok), h))
        return h_new, project(out)

    # prefill: scan the prompt through the cell, projecting ONLY the
    # final step's output (a (P, B, V) logits stack would be pure waste)
    h0 = cell.init_hidden(b, lin_p["weight"].dtype)

    def prefill(carry, tok):
        h, _ = carry
        (out, h_new), _ = cell.apply(cell_p, {}, (onehot(tok), h))
        return (h_new, out), None

    (h, last_out), _ = jax.lax.scan(prefill, (h0, jnp.zeros(
        (b, cell.hidden_size), lin_p["weight"].dtype)), prompt.T)
    logits = project(last_out)

    if rng is None:
        rng = jax.random.PRNGKey(0)

    def sample(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1) + 1
        logits = logits / temperature
        if top_k is not None:
            k_eff = min(top_k, vocab)
            kth = jnp.sort(logits, axis=-1)[:, -k_eff][:, None]
            logits = jnp.where(logits < kth, -1e9, logits)
        return jax.random.categorical(key, logits, axis=-1) + 1

    rng, k0 = jax.random.split(rng)
    first = sample(logits, k0)

    def step(carry, key):
        tok, h = carry
        h_new, logits = cell_step(h, tok)
        nxt = sample(logits, key)
        return (nxt, h_new), nxt

    keys = jax.random.split(rng, max(max_new_tokens - 1, 1))
    _, rest = jax.lax.scan(step, (first, h), keys[:max_new_tokens - 1])
    return jnp.concatenate([first[:, None], rest.T], axis=1)

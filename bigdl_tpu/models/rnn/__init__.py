"""rnn model family (reference models/rnn/)."""
from bigdl_tpu.models.rnn.model import *  # noqa: F401,F403
from bigdl_tpu.models.rnn.generate import generate  # noqa: F401,E402

"""Model zoo (reference dl/.../bigdl/models/, SURVEY §2.9)."""

from bigdl_tpu.models.lenet.model import LeNet5
from bigdl_tpu.models.alexnet import AlexNet, AlexNet_OWT
from bigdl_tpu.models.autoencoder.model import Autoencoder
from bigdl_tpu.models.inception.model import (Inception_Layer_v1, Inception_v1,
                                        Inception_v1_NoAuxClassifier,
                                        Inception_Layer_v2, Inception_v2,
                                        Inception_v2_NoAuxClassifier)
from bigdl_tpu.models.vgg.model import VggForCifar10, Vgg_16, Vgg_19
from bigdl_tpu.models.resnet.model import (ResNet, ShortcutType, DatasetType,
                                     model_init)
from bigdl_tpu.models.rnn.model import SimpleRNN, BatchedSimpleRNN
from bigdl_tpu.models.transformer.model import (TransformerBlock,
                                                TransformerLM)

__all__ = [
    "LeNet5", "AlexNet", "AlexNet_OWT", "Autoencoder",
    "Inception_Layer_v1", "Inception_v1", "Inception_v1_NoAuxClassifier",
    "Inception_Layer_v2", "Inception_v2", "Inception_v2_NoAuxClassifier",
    "VggForCifar10", "Vgg_16", "Vgg_19",
    "ResNet", "ShortcutType", "DatasetType", "model_init",
    "SimpleRNN", "BatchedSimpleRNN",
    "TransformerLM", "TransformerBlock",
]

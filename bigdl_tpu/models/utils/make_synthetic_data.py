"""Generate datasets in the reference's on-disk formats for smoke runs.

The reference's ``run.example.sh`` downloads MNIST/CIFAR/ImageNet before
training. In offline environments this module synthesizes the same file
formats instead, so the one-command train path works anywhere:

- mnist:    idx files (train/t10k images+labels) per Yann LeCun layout
- cifar:    data_batch_{1..5}.bin / test_batch.bin (3073-byte records)
- imagenet: class-per-subfolder JPEG tree (feed to imagenet_gen for shards)

Run: ``python -m bigdl_tpu.models.utils.make_synthetic_data mnist -o DIR``
"""
from __future__ import annotations

import argparse
import os
import struct

import numpy as np


def make_mnist(out: str, n_train: int = 2048, n_test: int = 512):
    os.makedirs(out, exist_ok=True)
    rng = np.random.default_rng(0)

    def write_pair(prefix, n):
        imgs = rng.integers(0, 256, (n, 28, 28), dtype=np.uint8)
        labels = rng.integers(0, 10, (n,), dtype=np.uint8)
        with open(os.path.join(out, f"{prefix}-images-idx3-ubyte"),
                  "wb") as f:
            f.write(struct.pack(">IIII", 2051, n, 28, 28))
            f.write(imgs.tobytes())
        with open(os.path.join(out, f"{prefix}-labels-idx1-ubyte"),
                  "wb") as f:
            f.write(struct.pack(">II", 2049, n))
            f.write(labels.tobytes())

    write_pair("train", n_train)
    write_pair("t10k", n_test)


def make_cifar(out: str, per_batch: int = 512):
    os.makedirs(out, exist_ok=True)
    rng = np.random.default_rng(0)

    def write_bin(name, n):
        with open(os.path.join(out, name), "wb") as f:
            labels = rng.integers(0, 10, (n,), dtype=np.uint8)
            imgs = rng.integers(0, 256, (n, 3072), dtype=np.uint8)
            for lab, img in zip(labels, imgs):
                f.write(bytes([lab]))
                f.write(img.tobytes())

    for i in range(1, 6):
        write_bin(f"data_batch_{i}.bin", per_batch)
    write_bin("test_batch.bin", per_batch)


def make_imagenet(out: str, classes: int = 10, per_class: int = 20,
                  size: int = 256):
    from PIL import Image
    rng = np.random.default_rng(0)
    for split in ("train", "val"):
        for c in range(1, classes + 1):
            d = os.path.join(out, split, f"n{c:08d}")
            os.makedirs(d, exist_ok=True)
            for i in range(per_class):
                arr = rng.integers(0, 256, (size, size, 3), dtype=np.uint8)
                Image.fromarray(arr).save(
                    os.path.join(d, f"img_{i:04d}.jpg"), "JPEG")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("dataset", choices=["mnist", "cifar", "imagenet"])
    p.add_argument("-o", "--output", required=True)
    p.add_argument("-n", type=int, default=None,
                   help="records per split/batch/class (format-dependent)")
    args = p.parse_args(argv)
    if args.dataset == "mnist":
        make_mnist(args.output, *( (args.n, max(args.n // 4, 1))
                                   if args.n else ()))
    elif args.dataset == "cifar":
        make_cifar(args.output, *((args.n,) if args.n else ()))
    else:
        make_imagenet(args.output, per_class=args.n or 20)
    print(f"synthetic {args.dataset} written to {args.output}")


if __name__ == "__main__":
    main()

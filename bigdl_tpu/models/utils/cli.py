"""Shared CLI plumbing for model Train/Test mains.

Reference parity: the scopt parsers in models/*/Utils.scala /
models/inception/Options.scala (SURVEY §5.6.4) — common flags -f/--folder,
-b/--batchSize, --model/--state snapshots, --checkpoint, --overWrite,
--maxEpoch, --learningRate. The reference's ``--core``/``--node`` topology
flags become ``--chips`` (mesh size; default = every visible device).
"""
from __future__ import annotations

import argparse
import logging

__all__ = ["base_train_parser", "base_test_parser", "init_engine",
           "setup_logging"]


def setup_logging():
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s - %(message)s")


def base_train_parser(description: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("-f", "--folder", default="./",
                   help="where the training data lives")
    p.add_argument("-b", "--batchSize", type=int, default=None,
                   help="global batch size")
    p.add_argument("--model", default=None,
                   help="model snapshot to resume from")
    p.add_argument("--state", default=None,
                   help="state snapshot to resume from")
    p.add_argument("--checkpoint", default=None,
                   help="where to cache the model/state each epoch")
    p.add_argument("--overWrite", action="store_true",
                   help="overwrite existing checkpoint files")
    p.add_argument("-e", "--maxEpoch", type=int, default=None)
    p.add_argument("-r", "--learningRate", type=float, default=None)
    p.add_argument("--chips", type=int, default=None,
                   help="devices in the mesh (default: all visible)")
    return p


def base_test_parser(description: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("-f", "--folder", default="./")
    p.add_argument("--model", required=True, help="model snapshot path")
    p.add_argument("-b", "--batchSize", type=int, default=128)
    return p


def init_engine(chips: int | None = None, axes=None):
    """Build the device mesh (reference Engine.init, SURVEY §2.4).

    ``axes``: callable n_chips -> axes dict for non-default topologies
    (e.g. ``lambda n: {"data": 1, "seq": n}`` for sequence parallelism);
    default is pure data parallelism.
    """
    import jax

    from bigdl_tpu.parallel.engine import Engine

    devs = jax.devices()
    n = chips or len(devs)
    Engine.reset()
    axes_dict = axes(n) if axes is not None else {"data": n}
    return Engine.init(axes=axes_dict, devices=devs[:n])

"""Synthetic-data training throughput harness (reference
models/utils/DistriOptimizerPerf.scala:33-70 / LocalOptimizerPerf.scala —
models inception_v1/v2, vgg16/19, random input, records/s per iteration).

Run: ``python -m bigdl_tpu.models.utils.perf -m inception_v1 -b 128``.
"""
from __future__ import annotations

import argparse
import time

import numpy as np


MODELS = {
    "inception_v1": ("Inception_v1_NoAuxClassifier", 224),
    "inception_v2": ("Inception_v2_NoAuxClassifier", 224),
    "vgg16": ("Vgg_16", 224),
    "vgg19": ("Vgg_19", 224),
    "alexnet": ("AlexNet_OWT", 224),
    "resnet50": (lambda models: lambda n: models.ResNet(
        n, {"depth": 50, "dataset": "imagenet"}), 224),
    "lenet5": ("LeNet5", 28),
}


def _attention_perf(args):
    """Long-context attention: fused Pallas kernel vs the XLA path,
    fwd+bwd per sequence (the long-context hot loop, docs/PERF.md)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.parallel.sequence import dot_product_attention

    b, s, h, d = args.batchSize, args.seqLen, args.heads, args.headDim
    dtype = jnp.bfloat16 if args.dataType == "bf16" else jnp.float32
    host = np.random.default_rng(0)
    q, k, v, ct = (jnp.asarray(0.3 * host.standard_normal(
        (b, s, h, d)).astype(np.float32), dtype) for _ in range(4))

    def bench(flash):
        fn = jax.jit(jax.grad(lambda q, k, v: jnp.vdot(
            dot_product_attention(q, k, v, causal=True,
                                  flash=flash).astype(jnp.float32),
            ct.astype(jnp.float32)), argnums=(0, 1, 2)))
        try:
            g = fn(q, k, v)
        except Exception as e:  # XLA path OOMs at long S — report it
            return None, type(e).__name__
        for _ in range(args.warmUp - 1):
            g = fn(q, k, v)
        jax.tree.map(lambda a: float(jnp.sum(a.astype(jnp.float32))), g)
        t0 = time.perf_counter()
        for _ in range(args.iteration):
            g = fn(q, k, v)
        jax.tree.map(lambda a: float(jnp.sum(a.astype(jnp.float32))), g)
        return (time.perf_counter() - t0) / args.iteration * 1e3, None

    # flash=True (not "auto") so an unsupported config prints FAILED
    # instead of silently benchmarking the XLA path under the flash label
    for name, flash in (("flash", True), ("xla", False)):
        ms, err = bench(flash)
        if ms is None:
            print(f"attention[{name}] B{b} S{s} H{h} D{d}: FAILED ({err})")
        else:
            print(f"attention[{name}] B{b} S{s} H{h} D{d}: {ms:.2f} "
                  f"ms/iteration fwd+bwd ({b * s / ms:.0f} tokens/ms)")


def _transformer_perf(args):
    """LM train-step throughput (tokens/s) — the docs/PERF.md flagship
    config: d_model 512, 6 layers, 4x128 heads, vmapped
    TimeDistributedCriterion, flash attention via auto dispatch."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.tensor import DTypePolicy, set_policy

    if args.dataType == "bf16":
        set_policy(DTypePolicy(param_dtype=jnp.float32,
                               compute_dtype=jnp.bfloat16,
                               activation_dtype=jnp.bfloat16))
    vocab, s, b = args.classNum, args.seqLen, args.batchSize
    # logits head + lse-form CrossEntropy (the memory-lean recipe);
    # size-averaged loss and a sane lr keep the synthetic run finite
    model = TransformerLM(vocab, d_model=args.dModel,
                          num_heads=args.dModel // 128,
                          num_layers=args.numLayers,
                          max_len=s, with_log_softmax=False,
                          pos_encoding=args.posEncoding,
                          num_kv_heads=args.numKvHeads)
    model.materialize(jax.random.PRNGKey(0))
    model.training()
    # CrossEntropyCriterion flattens (B, S, V) itself; wrapping it in
    # TimeDistributedCriterion is semantically identical (same mean) but
    # the vmap-over-T made XLA materialize a TIME-MAJOR f32 transpose of
    # the logits (2.15 GB at vocab 32k — round-3 trace, docs/PERF.md)
    crit = nn.CrossEntropyCriterion()
    optim = SGD(learning_rate=0.01)
    params, mstate = model.params, model.state
    opt_state = optim.init_state(params)

    # fused head+loss: run the body to hidden states and hand the lm_head
    # weight to the chunked-vocab kernel — full (B, S, V) logits never
    # materialize (ops/pallas/fused_ce.py; round-3 trace found ~10 ms of
    # the 44.5 ms step in the three logits materializations at vocab 32k)
    import jax as _jx
    fused = (args.fusedHeadLoss != "off"
             and _jx.default_backend() == "tpu")
    head_idx = str(len(model.modules) - 1)   # lm_head Linear

    def step(params, mstate, opt_state, data, labels):
        def loss_fn(p):
            if fused:
                from bigdl_tpu.ops.pallas.fused_ce import \
                    linear_cross_entropy
                x, new_mstate = data, dict(mstate)
                for i, m in enumerate(model.modules[:-1]):
                    x, new_mstate[str(i)] = m.apply(
                        p[str(i)], mstate[str(i)], x, training=True)
                d_model = x.shape[-1]
                # head weight rides the MXU in the activation dtype (the
                # unfused Linear does the same via DTypePolicy); grads
                # flow back to the f32 param through the cast's VJP
                loss = linear_cross_entropy(
                    x.reshape(-1, d_model),
                    p[head_idx]["weight"].astype(x.dtype),
                    p[head_idx].get("bias"), labels.reshape(-1))
                return loss, new_mstate
            y, st = model.apply(p, mstate, data, training=True)
            return crit.apply(y, labels), st
        (loss, s2), g = jax.value_and_grad(loss_fn, has_aux=True)(params)
        p2, o2 = optim.update(g, params, opt_state)
        return p2, s2, o2, loss

    host = np.random.default_rng(0)
    data = jnp.asarray(host.integers(1, vocab + 1, size=(b, s)))
    labels = jnp.asarray(host.integers(1, vocab + 1, size=(b, s)))
    c = jax.jit(step, donate_argnums=(0, 1, 2)).lower(
        params, mstate, opt_state, data, labels).compile()
    for _ in range(max(args.warmUp, 1)):   # >=1: bind loss for the sync
        params, mstate, opt_state, loss = c(params, mstate, opt_state,
                                            data, labels)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(args.iteration):
        params, mstate, opt_state, loss = c(params, mstate, opt_state,
                                            data, labels)
    final = float(loss)
    dt = time.perf_counter() - t0
    if not np.isfinite(final):
        raise SystemExit(f"transformer perf run diverged: loss={final} "
                         f"(throughput would be meaningless)")
    from bigdl_tpu.observability.compile_watch import executable_stats
    cost = executable_stats(c)
    line = (f"transformer: {b * s * args.iteration / dt:,.0f} tokens/s "
            f"({dt / args.iteration * 1000:.1f} ms/step, B{b} S{s} "
            f"vocab {vocab}, final loss {final:.3f})")
    if cost and cost.get("flops"):
        line += (f" [{cost['flops'] * args.iteration / dt / 1e12:.1f} "
                 f"TFLOP/s achieved]")
    print(line)


def _decode_perf(args):
    """KV-cache decode throughput (the docs/PERF.md decode table):
    27M LM, prompt 512, 128 new tokens, greedy."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.models.transformer.generate import (GenerationConfig,
                                                       generate)
    from bigdl_tpu.tensor import DTypePolicy, set_policy

    if args.dataType == "bf16":
        set_policy(DTypePolicy(param_dtype=jnp.float32,
                               compute_dtype=jnp.bfloat16,
                               activation_dtype=jnp.bfloat16))
    vocab, b = args.classNum, args.batchSize
    p_len, n_new = 512, 128
    model = TransformerLM(vocab, d_model=512, num_heads=4, num_layers=6,
                          max_len=p_len + n_new, with_log_softmax=False,
                          pos_encoding=args.posEncoding,
                          num_kv_heads=args.numKvHeads)
    model.materialize(jax.random.PRNGKey(0))
    model.evaluate()
    host = np.random.default_rng(0)
    prompt = jnp.asarray(host.integers(1, vocab + 1, size=(b, p_len)))
    cfg = GenerationConfig(max_new_tokens=n_new)
    out = generate(model, prompt, cfg)           # compile + warm
    np.asarray(out)
    t0 = time.perf_counter()
    for _ in range(args.iteration):
        out = generate(model, prompt, cfg)
    np.asarray(out)
    dt = (time.perf_counter() - t0) / args.iteration
    print(f"decode: B{b} prompt {p_len} +{n_new} new: "
          f"{b * n_new / dt:,.0f} tokens/s ({dt / n_new * 1e3:.2f} "
          f"ms/step)")


def main(argv=None):
    parser = argparse.ArgumentParser(description="training perf harness")
    parser.add_argument("-m", "--module", default="inception_v1",
                        choices=sorted(MODELS) + ["attention",
                                                  "transformer", "decode"])
    parser.add_argument("-b", "--batchSize", type=int, default=None,
                        help="default: 128 (conv models), 4 (attention), "
                             "8 (transformer)")
    parser.add_argument("-i", "--iteration", type=int, default=30)
    parser.add_argument("--warmUp", type=int, default=5)
    parser.add_argument("--classNum", type=int, default=None,
                        help="default: 1000 (conv models), vocab 8192 "
                             "(transformer)")
    parser.add_argument("--dataType", default="bf16",
                        choices=["f32", "bf16"])
    parser.add_argument("--seqLen", type=int, default=None,
                        help="sequence length; default 4096 (attention), "
                             "2048 (transformer, the docs/PERF.md "
                             "flagship config)")
    parser.add_argument("--heads", type=int, default=8,
                        help="attention mode: heads")
    parser.add_argument("--headDim", type=int, default=128,
                        help="attention mode: head dim")
    parser.add_argument("--fusedHeadLoss", default="auto",
                        choices=["auto", "off"],
                        help="transformer mode: chunked-vocab fused "
                             "head+CE kernel (auto: on TPU)")
    parser.add_argument("--dModel", type=int, default=512,
                        help="transformer mode: model width (heads = "
                             "dModel/128)")
    parser.add_argument("--posEncoding", default="learned",
                        choices=["learned", "rope"],
                        help="transformer position encoding")
    parser.add_argument("--numKvHeads", type=int, default=None,
                        help="< heads selects grouped-query attention")
    parser.add_argument("--numLayers", type=int, default=6,
                        help="transformer mode: layers")
    args = parser.parse_args(argv)

    if args.batchSize is None:
        args.batchSize = {"attention": 4, "transformer": 8,
                          "decode": 64}.get(
            args.module, 128)
    if args.seqLen is None:
        args.seqLen = 2048 if args.module == "transformer" else 4096
    if args.classNum is None:
        args.classNum = (8192 if args.module in ("transformer", "decode")
                         else 1000)
    if args.module == "attention":
        return _attention_perf(args)
    if args.module == "transformer":
        return _transformer_perf(args)
    if args.module == "decode":
        return _decode_perf(args)

    import jax
    import jax.numpy as jnp

    from bigdl_tpu import models, nn
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.tensor import DTypePolicy, set_policy

    if args.dataType == "bf16":
        set_policy(DTypePolicy(param_dtype=jnp.float32,
                               compute_dtype=jnp.bfloat16,
                               activation_dtype=jnp.bfloat16))

    spec, size = MODELS[args.module]
    if callable(spec):
        model = spec(models)(args.classNum)
    else:
        model = getattr(models, spec)(
            10 if args.module == "lenet5" else args.classNum)
    channels = 1 if args.module == "lenet5" else 3

    model.materialize(jax.random.PRNGKey(0))
    model.training()
    criterion = nn.ClassNLLCriterion()
    optim = SGD(learning_rate=0.01, momentum=0.9)
    params, mstate = model.params, model.state
    opt_state = optim.init_state(params)

    def step(params, mstate, opt_state, rng, data, labels):
        def loss_fn(p):
            y, s = model.apply(p, mstate, data, training=True, rng=rng)
            return criterion.apply(y, labels), s
        (loss, s2), g = jax.value_and_grad(loss_fn, has_aux=True)(params)
        p2, o2 = optim.update(g, params, opt_state)
        return p2, s2, o2, loss

    jit_step = jax.jit(step, donate_argnums=(0, 1, 2))
    host = np.random.default_rng(0)
    data = jnp.asarray(host.standard_normal(
        (args.batchSize, channels, size, size), np.float32))
    labels = jnp.asarray(host.integers(
        1, (10 if args.module == "lenet5" else args.classNum) + 1,
        size=(args.batchSize,)))

    rng = jax.random.PRNGKey(0)
    for _ in range(args.warmUp):
        rng, k = jax.random.split(rng)
        params, mstate, opt_state, loss = jit_step(params, mstate,
                                                   opt_state, k, data,
                                                   labels)
    float(loss)
    t0 = time.perf_counter()
    for i in range(args.iteration):
        rng, k = jax.random.split(rng)
        t1 = time.perf_counter()
        params, mstate, opt_state, loss = jit_step(params, mstate,
                                                   opt_state, k, data,
                                                   labels)
        print(f"Iteration {i + 1} queued in "
              f"{time.perf_counter() - t1:.4f}s")
    float(loss)
    dt = time.perf_counter() - t0
    line = (f"{args.module}: {args.batchSize * args.iteration / dt:.2f} "
            f"records/second ({dt / args.iteration * 1000:.2f} ms/iteration)")
    # reuses the dispatch-cache entry populated by the loop above — no
    # second compile (verified on jax 0.9)
    from bigdl_tpu.observability.compile_watch import executable_stats
    cost = executable_stats(jit_step.lower(params, mstate, opt_state,
                                           rng, data, labels).compile())
    if cost and cost.get("flops"):
        tflops = cost["flops"] * args.iteration / dt / 1e12
        line += f" [{tflops:.1f} TFLOP/s achieved]"
    print(line)


if __name__ == "__main__":
    main()

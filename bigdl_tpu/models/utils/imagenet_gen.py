"""ImageNet shard generator CLI (reference
models/utils/ImageNetSeqFileGenerator.scala — folder -> N record shards of
resized JPEG bytes + labels).

Run::

    python -m bigdl_tpu.models.utils.imagenet_gen \
        -f <imagenet_root> -o <output_dir> -p 8 [--scaleTo 256]

``<imagenet_root>`` holds ``train/`` and/or ``val/`` class-per-subfolder
trees (or is itself one tree).
"""
from __future__ import annotations

import argparse
import logging
import os

logger = logging.getLogger("bigdl_tpu.models.utils.imagenet_gen")


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser("ImageNet record-shard generator")
    p.add_argument("-f", "--folder", required=True)
    p.add_argument("-o", "--output", required=True)
    p.add_argument("-p", "--parallel", type=int, default=8,
                   help="number of shard files per split")
    p.add_argument("--scaleTo", type=int, default=256,
                   help="shorter-side resize before writing (0 = raw copy)")
    args = p.parse_args(argv)

    from bigdl_tpu.dataset.recordio import generate_shards

    scale = args.scaleTo or None
    written = {}
    for split in ("train", "val"):
        src = os.path.join(args.folder, split)
        if os.path.isdir(src):
            out = os.path.join(args.output, split)
            paths = generate_shards(src, out, args.parallel,
                                    shuffle=split == "train",
                                    scale_to=scale)
            written[split] = paths
            logger.info("%s: wrote %d shards under %s", split, len(paths),
                        out)
    if not written:   # the folder itself is a class tree
        paths = generate_shards(args.folder, args.output, args.parallel,
                                scale_to=scale)
        written["train"] = paths
        logger.info("wrote %d shards under %s", len(paths), args.output)
    return written


if __name__ == "__main__":
    main()

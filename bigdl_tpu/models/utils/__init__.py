"""Model-zoo utilities: CLI plumbing + perf harnesses (reference
models/utils/ — DistriOptimizerPerf, LocalOptimizerPerf, ModelBroadcast)."""

from bigdl_tpu.models.utils.cli import (base_train_parser, base_test_parser,
                                        init_engine, setup_logging)

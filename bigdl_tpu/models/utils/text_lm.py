"""Shared text-LM data pipeline for the rnn and transformer train mains
(read -> sentence split -> tokenize -> pad markers -> Dictionary ->
fixed-length samples -> batches). One home so the two mains cannot
diverge (reference models/rnn/Train.scala preprocessing)."""
from __future__ import annotations

import os


def build_text_lm_datasets(folder: str, vocab_size: int, seq_length: int,
                           batch: int, *, one_hot: bool,
                           dictionary_dir: str | None = None):
    """Returns (train_set, val_set, vocab, dictionary).

    ``one_hot=True`` feeds (T, vocab) dense rows (the SimpleRNN input);
    ``one_hot=False`` feeds 1-based token ids (embedding-table input).
    """
    from bigdl_tpu.dataset.dataset import LocalArrayDataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.dataset.text import (Dictionary, LabeledSentenceToSample,
                                        SentenceBiPadding, SentenceSplitter,
                                        SentenceTokenizer,
                                        TextToLabeledSentence)
    from bigdl_tpu.dataset.transformer import SampleToBatch, Transformer

    with open(os.path.join(folder, "input.txt")) as f:
        text = f.read()
    sentences = list(SentenceSplitter()(iter([text])))
    tokens = list(SentenceTokenizer()(iter(sentences)))
    tokens = list(SentenceBiPadding()(iter(tokens)))
    dictionary = Dictionary(tokens, vocab_size)
    dictionary.save(dictionary_dir or folder)
    vocab = dictionary.get_vocab_size() + 1   # + OOV bucket

    class ToTokenIds(Transformer):
        """0-based dictionary indices -> 1-based LookupTable-style ids."""

        def __call__(self, it):
            for s in it:
                yield Sample(s.feature.astype("int32") + 1, s.label)

    to_sample = (TextToLabeledSentence(dictionary)
                 >> LabeledSentenceToSample(
                     vocab, fixed_data_length=seq_length,
                     fixed_label_length=seq_length, one_hot=one_hot))
    if not one_hot:
        to_sample = to_sample >> ToTokenIds()
    samples = list(to_sample(iter(tokens)))
    split = max(1, int(len(samples) * 0.8))
    train_set = LocalArrayDataSet(samples[:split]) >> SampleToBatch(
        batch, drop_remainder=True)
    val_set = LocalArrayDataSet(samples[split:] or samples[:1]) \
        >> SampleToBatch(batch)
    return train_set, val_set, vocab, dictionary

"""VGG CIFAR-10 training main (reference models/vgg/Train.scala).

Run: ``python -m bigdl_tpu.models.vgg.train -f <cifar10_binary_dir>``.
Expects data_batch_{1..5}.bin / test_batch.bin under ``--folder``.
"""
from __future__ import annotations

from bigdl_tpu.models.utils.cli import (base_train_parser, init_engine,
                                        setup_logging)


def main(argv=None):
    setup_logging()
    parser = base_train_parser("Train VGG on CIFAR-10")
    args = parser.parse_args(argv)
    mesh = init_engine(args.chips)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import cifar
    from bigdl_tpu.dataset.dataset import LocalArrayDataSet
    from bigdl_tpu.dataset.image import (BGRImgNormalizer, BGRImgRdmCropper,
                                         BGRImgToBatch, HFlip)
    from bigdl_tpu.models import VggForCifar10
    from bigdl_tpu.optim import (EpochStep, Optimizer, SGD, Top1Accuracy,
                                 every_epoch, max_epoch)
    from bigdl_tpu.utils import file as bfile

    batch = args.batchSize or 128
    train = LocalArrayDataSet(cifar.load_folder(args.folder, train=True))
    val = LocalArrayDataSet(cifar.load_folder(args.folder, train=False))

    # reference Train.scala pipeline: crop(32,32,pad 4) -> hflip(0.5) ->
    # normalize(trainMean, trainStd) -> batch
    train_set = train >> BGRImgRdmCropper(32, 32, 4) >> HFlip(0.5) \
        >> BGRImgNormalizer(cifar.TRAIN_MEAN, std_r=cifar.TRAIN_STD) \
        >> BGRImgToBatch(batch, drop_remainder=True)
    val_set = val >> BGRImgNormalizer(cifar.TRAIN_MEAN,
                                      std_r=cifar.TRAIN_STD) \
        >> BGRImgToBatch(batch)

    model = (bfile.load_module(args.model) if args.model
             else VggForCifar10(class_num=10))
    optimizer = Optimizer(model, train_set, nn.ClassNLLCriterion(), mesh=mesh)
    # reference: SGD lr 0.01, decay 0, wd 0.0005, momentum 0.9,
    # EpochStep(25, 0.5)
    optimizer.set_optim_method(SGD(
        learning_rate=args.learningRate or 0.01,
        weight_decay=0.0005, momentum=0.9,
        learning_rate_schedule=EpochStep(25, 0.5)))
    if args.state:
        optimizer.set_state(bfile.load(args.state))
    optimizer.set_validation(every_epoch(), val_set, [Top1Accuracy()])
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, every_epoch())
        if args.overWrite:
            optimizer.overwrite_checkpoint()
    optimizer.set_end_when(max_epoch(args.maxEpoch or 90))
    optimizer.optimize()


if __name__ == "__main__":
    main()

"""vgg model family (reference models/vgg/)."""
from bigdl_tpu.models.vgg.model import *  # noqa: F401,F403

"""VGG nets (reference models/vgg/VggForCifar10.scala).

``VggForCifar10`` — conv-BN-ReLU blocks with dropout (reference :22-68);
``Vgg_16``/``Vgg_19`` — ImageNet variants used by the perf harness
(reference :70-187, models/utils/DistriOptimizerPerf.scala:33-70).
"""
from __future__ import annotations

from bigdl_tpu.nn import (BatchNormalization, Dropout, Linear, LogSoftMax,
                          ReLU, Sequential, SpatialBatchNormalization,
                          SpatialConvolution, SpatialMaxPooling, Threshold,
                          View)

__all__ = ["VggForCifar10", "Vgg_16", "Vgg_19"]


def VggForCifar10(class_num: int) -> Sequential:
    model = Sequential()

    def conv_bn_relu(n_in, n_out):
        model.add(SpatialConvolution(n_in, n_out, 3, 3, 1, 1, 1, 1))
        model.add(SpatialBatchNormalization(n_out, 1e-3))
        model.add(ReLU())
        return model

    conv_bn_relu(3, 64).add(Dropout(0.3))
    conv_bn_relu(64, 64)
    model.add(SpatialMaxPooling(2, 2, 2, 2).ceil())

    conv_bn_relu(64, 128).add(Dropout(0.4))
    conv_bn_relu(128, 128)
    model.add(SpatialMaxPooling(2, 2, 2, 2).ceil())

    conv_bn_relu(128, 256).add(Dropout(0.4))
    conv_bn_relu(256, 256).add(Dropout(0.4))
    conv_bn_relu(256, 256)
    model.add(SpatialMaxPooling(2, 2, 2, 2).ceil())

    conv_bn_relu(256, 512).add(Dropout(0.4))
    conv_bn_relu(512, 512).add(Dropout(0.4))
    conv_bn_relu(512, 512)
    model.add(SpatialMaxPooling(2, 2, 2, 2).ceil())

    conv_bn_relu(512, 512).add(Dropout(0.4))
    conv_bn_relu(512, 512).add(Dropout(0.4))
    conv_bn_relu(512, 512)
    model.add(SpatialMaxPooling(2, 2, 2, 2).ceil())
    model.add(View(512))

    classifier = (Sequential()
                  .add(Dropout(0.5))
                  .add(Linear(512, 512))
                  .add(BatchNormalization(512))
                  .add(ReLU())
                  .add(Dropout(0.5))
                  .add(Linear(512, class_num))
                  .add(LogSoftMax()))
    model.add(classifier)
    return model


def _vgg_imagenet(conv_counts, class_num: int) -> Sequential:
    """Shared VGG-16/19 body; conv_counts = convs per block."""
    model = Sequential()
    n_in = 3
    for n_out, count in zip((64, 128, 256, 512, 512), conv_counts):
        for _ in range(count):
            model.add(SpatialConvolution(n_in, n_out, 3, 3, 1, 1, 1, 1))
            model.add(ReLU())
            n_in = n_out
        model.add(SpatialMaxPooling(2, 2, 2, 2))
    model.add(View(512 * 7 * 7))
    model.add(Linear(512 * 7 * 7, 4096))
    model.add(Threshold(0, 1e-6))
    model.add(Dropout(0.5))
    model.add(Linear(4096, 4096))
    model.add(Threshold(0, 1e-6))
    model.add(Dropout(0.5))
    model.add(Linear(4096, class_num))
    model.add(LogSoftMax())
    return model


def Vgg_16(class_num: int) -> Sequential:
    """(reference VggForCifar10.scala:70-127)"""
    return _vgg_imagenet((2, 2, 3, 3, 3), class_num)


def Vgg_19(class_num: int) -> Sequential:
    """(reference VggForCifar10.scala:130-187)"""
    return _vgg_imagenet((2, 2, 4, 4, 4), class_num)

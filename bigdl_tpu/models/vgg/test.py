"""VGG CIFAR-10 evaluation main (reference models/vgg/Test.scala:26-56).

Run: ``python -m bigdl_tpu.models.vgg.test -f <cifar_dir> --model <snap>``.
"""
from __future__ import annotations

from bigdl_tpu.models.utils.cli import (base_test_parser, init_engine,
                                        setup_logging)


def main(argv=None):
    setup_logging()
    args = base_test_parser("Test Vgg on Cifar10").parse_args(argv)
    mesh = init_engine()

    from bigdl_tpu.dataset import cifar
    from bigdl_tpu.dataset.dataset import LocalArrayDataSet
    from bigdl_tpu.dataset.image import BGRImgNormalizer, BGRImgToBatch
    from bigdl_tpu.optim import Top1Accuracy, Validator
    from bigdl_tpu.utils import file as bfile

    val = LocalArrayDataSet(cifar.load_folder(args.folder, train=False))
    val_set = val >> BGRImgNormalizer(cifar.TEST_MEAN,
                                      std_r=cifar.TEST_STD) \
        >> BGRImgToBatch(args.batchSize)

    model = bfile.load_module(args.model)
    results = Validator(model, val_set, mesh=mesh).test([Top1Accuracy()])
    for result, method in results:
        print(f"{method!r} is {result!r}")
    return results


if __name__ == "__main__":
    main()

"""LeNet-5 MNIST training main (reference models/lenet/Train.scala:40-101).

Run: ``python -m bigdl_tpu.models.lenet.train -f <mnist_dir> -b 128``.
Expects train-images-idx3-ubyte[.gz] / train-labels-idx1-ubyte[.gz] (and the
t10k files for validation) under ``--folder``, like the reference.
"""
from __future__ import annotations

import os

from bigdl_tpu.models.utils.cli import (base_train_parser, init_engine,
                                        setup_logging)


def find(folder, names):
    for n in names:
        p = os.path.join(folder, n)
        if os.path.exists(p):
            return p
        if os.path.exists(p + ".gz"):
            return p + ".gz"
    raise FileNotFoundError(f"none of {names} under {folder}")


def main(argv=None):
    setup_logging()
    parser = base_train_parser("Train LeNet-5 on MNIST")
    args = parser.parse_args(argv)
    mesh = init_engine(args.chips)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import mnist
    from bigdl_tpu.dataset.dataset import LocalArrayDataSet
    from bigdl_tpu.dataset.image import GreyImgNormalizer, GreyImgToBatch
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.optim import (Optimizer, SGD, Top1Accuracy, every_epoch,
                                 max_epoch)
    from bigdl_tpu.utils import file as bfile

    batch = args.batchSize or 128
    train = LocalArrayDataSet(mnist.load(
        find(args.folder,
             ["train-images-idx3-ubyte",
              "train-images.idx3-ubyte"]),
        find(args.folder,
             ["train-labels-idx1-ubyte",
              "train-labels.idx1-ubyte"])))
    val = LocalArrayDataSet(mnist.load(
        find(args.folder,
             ["t10k-images-idx3-ubyte",
              "t10k-images.idx3-ubyte"]),
        find(args.folder,
             ["t10k-labels-idx1-ubyte",
              "t10k-labels.idx1-ubyte"])))

    train_set = train >> GreyImgNormalizer(mnist.TRAIN_MEAN, mnist.TRAIN_STD) \
        >> GreyImgToBatch(batch, drop_remainder=True)
    val_set = val >> GreyImgNormalizer(mnist.TEST_MEAN, mnist.TEST_STD) \
        >> GreyImgToBatch(batch)

    model = (bfile.load_module(args.model) if args.model
             else LeNet5(class_num=10))
    optimizer = Optimizer(model, train_set, nn.ClassNLLCriterion(), mesh=mesh)
    optimizer.set_optim_method(SGD(
        learning_rate=args.learningRate or 0.05,
        learning_rate_decay=0.0))
    if args.state:
        optimizer.set_state(bfile.load(args.state))
    optimizer.set_validation(every_epoch(), val_set, [Top1Accuracy()])
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, every_epoch())
        if args.overWrite:
            optimizer.overwrite_checkpoint()
    optimizer.set_end_when(max_epoch(args.maxEpoch or 15))
    optimizer.optimize()


if __name__ == "__main__":
    main()

"""LeNet-5 (reference models/lenet/LeNet5.scala:23-39)."""
from __future__ import annotations

from bigdl_tpu.nn import (Linear, LogSoftMax, Reshape, Sequential,
                          SpatialConvolution, SpatialMaxPooling, Tanh)

__all__ = ["LeNet5"]


def LeNet5(class_num: int) -> Sequential:
    """Classic LeNet-5 over 28x28 grey images, exact layer sequence of the
    reference (models/lenet/LeNet5.scala:24-38)."""
    return (Sequential()
            .add(Reshape((1, 28, 28)))
            .add(SpatialConvolution(1, 6, 5, 5).set_name("conv1_5x5"))
            .add(Tanh())
            .add(SpatialMaxPooling(2, 2, 2, 2))
            .add(Tanh())
            .add(SpatialConvolution(6, 12, 5, 5).set_name("conv2_5x5"))
            .add(SpatialMaxPooling(2, 2, 2, 2))
            .add(Reshape((12 * 4 * 4,)))
            .add(Linear(12 * 4 * 4, 100).set_name("fc1"))
            .add(Tanh())
            .add(Linear(100, class_num).set_name("fc2"))
            .add(LogSoftMax()))

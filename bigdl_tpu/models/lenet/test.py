"""LeNet-5 MNIST evaluation main (reference models/lenet/Test.scala:38-62)."""
from __future__ import annotations

from bigdl_tpu.models.lenet.train import find
from bigdl_tpu.models.utils.cli import (base_test_parser, init_engine,
                                        setup_logging)


def main(argv=None):
    setup_logging()
    args = base_test_parser("Test LeNet-5 on MNIST").parse_args(argv)
    init_engine()

    from bigdl_tpu.dataset import mnist
    from bigdl_tpu.dataset.dataset import LocalArrayDataSet
    from bigdl_tpu.dataset.image import GreyImgNormalizer, GreyImgToBatch
    from bigdl_tpu.optim import Top1Accuracy, Validator
    from bigdl_tpu.utils import file as bfile

    val = LocalArrayDataSet(mnist.load(
        find(args.folder,
             ["t10k-images-idx3-ubyte",
              "t10k-images.idx3-ubyte"]),
        find(args.folder,
             ["t10k-labels-idx1-ubyte",
              "t10k-labels.idx1-ubyte"])))
    val_set = val >> GreyImgNormalizer(mnist.TEST_MEAN, mnist.TEST_STD) \
        >> GreyImgToBatch(args.batchSize)

    model = bfile.load_module(args.model)
    results = Validator(model, val_set).test([Top1Accuracy()])
    for result, method in results:
        print(f"{method!r} is {result!r}")


if __name__ == "__main__":
    main()

"""lenet model family (reference models/lenet/)."""
from bigdl_tpu.models.lenet.model import *  # noqa: F401,F403

"""bigdl_tpu — a TPU-native distributed deep-learning framework.

A brand-new framework with the capabilities of early BigDL (reference:
jebtang/BigDL, surveyed in SURVEY.md), re-designed for TPU:

- ``bigdl_tpu.nn``        Torch-style layer & criterion library over a pure
                          init/apply core (JAX autodiff; no hand-written
                          backward passes like the reference's
                          ``updateGradInput``/``accGradParameters``).
- ``bigdl_tpu.optim``     Training loops (Local/Distri optimizer), optim
                          methods (SGD/Adagrad/LBFGS), triggers, validation.
- ``bigdl_tpu.dataset``   Composable Transformer data pipelines (images, text).
- ``bigdl_tpu.parallel``  Mesh construction, data/tensor/sequence-parallel
                          shardings, XLA-collective allreduce (replaces the
                          reference's Spark BlockManager parameter server,
                          parameters/AllReduceParameter.scala:53-229).
- ``bigdl_tpu.models``    LeNet, VGG, Inception v1/v2, ResNet, RNN, ...
- ``bigdl_tpu.utils``     Table, checkpoint File IO, Torch .t7 / Caffe import.
- ``bigdl_tpu.observability``  Metric registry, span tracer (Chrome trace
                          JSON), Train/ValidationSummary event logs —
                          host-only (never imports jax at module level).
"""

__version__ = "0.1.0"

from bigdl_tpu import nn, optim, dataset, parallel, utils, models, tensor  # noqa: F401,E402
from bigdl_tpu import observability  # noqa: F401,E402

"""Checkpoint / snapshot IO.

Reference parity: utils/File.scala:26-130 — Java-serialization save/load with
HDFS support, the backend of ``Optimizer.setCheckpoint`` and
``Module.save``. Here: arrays are stored in an ``.npz`` member and object
structure in a pickle member inside one zip file — portable, versioned, and
free of Java-serialization's fragility. GCS/remote paths are accepted via
fsspec-style prefixes when available; local FS always works.
"""
from __future__ import annotations

import io
import os
import pickle
import zipfile

import jax
import numpy as np

__all__ = ["save", "load", "save_module", "load_module"]

_MAGIC = "bigdl_tpu.v1"


def _to_host(obj):
    """Replace jax arrays with numpy arrays throughout a pytree/object.

    Sharded leaves spanning several processes (tensor-parallel params,
    ZeRO-1 optimizer state) are not addressable for a plain np.asarray —
    gather the full value first so checkpoints always hold global
    arrays."""

    def leaf(v):
        if isinstance(v, jax.Array) and not v.is_fully_addressable:
            from jax.experimental import multihost_utils
            return np.asarray(multihost_utils.process_allgather(
                v, tiled=True))
        return np.asarray(v) if hasattr(v, "__array__") else v

    return jax.tree.map(leaf, obj)


def save(obj, path: str, overwrite: bool = False) -> None:
    """Serialize ``obj`` (modules, Tables, pytrees) to ``path``
    (reference File.save, utils/File.scala:62-90)."""
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(
            f"{path} already exists (pass overwrite=True, reference "
            "File.save 'file exists' semantics)")
    host_obj = _to_host(obj)
    leaves, treedef = jax.tree.flatten(host_obj)
    arrays = {}
    placeholders = []
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, np.ndarray) or np.isscalar(leaf):
            arrays[f"a{i}"] = np.asarray(leaf)
            placeholders.append(("arr", f"a{i}"))
        else:
            placeholders.append(("obj", leaf))
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with zipfile.ZipFile(tmp, "w") as z:
        z.writestr("magic", _MAGIC)
        z.writestr("arrays.npz", buf.getvalue())
        z.writestr("structure.pkl",
                   pickle.dumps((treedef, placeholders),
                                protocol=pickle.HIGHEST_PROTOCOL))
    os.replace(tmp, path)


def load(path: str):
    """Inverse of :func:`save` (reference File.load)."""
    with zipfile.ZipFile(path) as z:
        assert z.read("magic").decode() == _MAGIC, "not a bigdl_tpu file"
        npz = np.load(io.BytesIO(z.read("arrays.npz")), allow_pickle=False)
        treedef, placeholders = pickle.loads(z.read("structure.pkl"))
    leaves = [npz[key] if kind == "arr" else key
              for kind, key in placeholders]
    return jax.tree.unflatten(treedef, leaves)


def _strip_runtime(module) -> None:
    """Drop gradients/rng recursively before serialization."""
    module.grad_params = None
    module._rng = None
    for child in getattr(module, "modules", []):
        _strip_runtime(child)


def _reset_grads(module) -> None:
    import jax.numpy as jnp
    if module.params is not None:
        module.grad_params = jax.tree.map(jnp.zeros_like, module.params)
    for child in getattr(module, "modules", []):
        _reset_grads(child)


def save_module(module, path: str, overwrite: bool = False) -> None:
    """Persist a module with its params/state (reference
    AbstractModule.save, nn/abstractnn/AbstractModule.scala:305-310).

    The module object itself is pickled (topology + hyperparams) with its
    arrays moved to host memory, so ``load_module`` restores a working
    module without re-materialization.
    """
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(f"{path} already exists")
    module = module.clone_module()
    _strip_runtime(module)
    module.params = _to_host(module.params)
    module.state = _to_host(module.state)
    if module.params is not None:
        # rebind children onto subtrees of the host copies — without this
        # the pickle stores a second (device-array) copy per child
        module.sync(module.params, module.state)
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "wb") as f:
        pickle.dump((_MAGIC, module), f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)


def load_module(path: str):
    """(reference Module.load, nn/Module.scala:27-29)"""
    with open(path, "rb") as f:
        magic, module = pickle.load(f)
    assert magic == _MAGIC, "not a bigdl_tpu module file"
    if module.params is not None:
        import jax.numpy as jnp
        module.params = jax.tree.map(jnp.asarray, module.params)
        module.state = jax.tree.map(jnp.asarray, module.state)
        module.sync(module.params, module.state)
        _reset_grads(module)
    return module

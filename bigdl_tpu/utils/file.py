"""Checkpoint / snapshot IO.

Reference parity: utils/File.scala:26-130 — Java-serialization save/load with
HDFS support (``File.scala:62-113`` routes any non-local URI through the
Hadoop FileSystem API), the backend of ``Optimizer.setCheckpoint`` and
``Module.save``. Here: arrays are stored in an ``.npz`` member and object
structure in a pickle member inside one zip file — portable, versioned, and
free of Java-serialization's fragility. Paths with a URL scheme
(``file://``, ``gs://``, ``hdfs://``, ``s3://``, ``memory://`` …) are
routed through fsspec — the Python ecosystem's Hadoop-FileSystem
equivalent; plain paths use the local FS directly and never import
fsspec. Crash safety: both branches stage to a sibling ``.tmp`` then
move, so the target name never holds a torn file — on local FS the move
is an atomic rename, on object stores it is copy(atomic PUT)+delete,
which at worst strands a ``.tmp`` object; a failed write discards the
staged upload (or deletes the partial ``.tmp``) and leaves the previous
checkpoint untouched.
"""
from __future__ import annotations

import contextlib
import os
import pickle
import re
import zipfile

import jax
import numpy as np

__all__ = ["save", "load", "save_module", "load_module",
           "ensure_writable_dir"]

_MAGIC = "bigdl_tpu.v1"

_SCHEME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.-]*://")


def _is_url(path) -> bool:
    return isinstance(path, str) and bool(_SCHEME_RE.match(path))


def _fs_for(path: str):
    try:
        import fsspec
    except ImportError as e:  # covered only when fsspec is absent
        raise ImportError(
            f"checkpoint path {path!r} has a URL scheme, which needs the "
            "'fsspec' package (pip install fsspec; plus the protocol's "
            "driver, e.g. gcsfs for gs://)") from e
    fs, _ = fsspec.core.url_to_fs(path)
    return fs


def _exists(path: str) -> bool:
    if _is_url(path):
        return _fs_for(path).exists(path)
    return os.path.exists(path)


@contextlib.contextmanager
def _open_read(path: str):
    if _is_url(path):
        with _fs_for(path).open(path, "rb") as f:
            yield f
    else:
        with open(path, "rb") as f:
            yield f


@contextlib.contextmanager
def _open_write_atomic(path: str):
    """Yield a writable binary stream that lands at ``path`` only on a
    clean exit (reference File.scala:62-113 saveToHdfs semantics)."""
    if _is_url(path):
        # stage to a sibling name on every backend: write-in-place
        # filesystems (file://, memory://) would otherwise truncate the
        # previous checkpoint at open() and lose it on a failed write
        fs = _fs_for(path)
        dirname = path.rsplit("/", 1)[0]
        if dirname and dirname != path:
            fs.makedirs(dirname, exist_ok=True)
        url_tmp = path + ".tmp"
        f = fs.open(url_tmp, "wb")
        try:
            yield f
        except BaseException:
            import fsspec
            if isinstance(f, fsspec.spec.AbstractBufferedFile):
                f.discard()        # abort the staged upload
            else:
                f.close()
            with contextlib.suppress(Exception):
                fs.rm(url_tmp)
            raise
        f.close()
        fs.mv(url_tmp, path)
        return
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    try:
        with open(tmp, "wb") as f:
            yield f
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise
    os.replace(tmp, path)


def ensure_writable_dir(path: str) -> None:
    """Eagerly verify that ``path`` is (or can become) a writable
    directory — the ``set_checkpoint`` guard that turns "training died
    minutes in at the first trigger fire" into an immediate, clear
    error. Creates the directory when absent; probes writability with a
    scratch file on local filesystems (object stores have no cheap
    probe — their makedirs is authoritative enough)."""
    if _is_url(path):
        try:
            _fs_for(path).makedirs(path, exist_ok=True)
        except Exception as e:
            raise ValueError(
                f"checkpoint path {path!r} is not usable: could not "
                f"create the directory ({e})") from e
        return
    try:
        os.makedirs(path, exist_ok=True)
    except OSError as e:
        raise ValueError(
            f"checkpoint path {path!r} is not a creatable directory "
            f"({e}) — set_checkpoint needs a directory it can write "
            "model/state/manifest files into") from e
    probe = os.path.join(path, f".bigdl_tpu_write_probe_{os.getpid()}")
    try:
        with open(probe, "wb"):
            pass
        os.unlink(probe)
    except OSError as e:
        raise ValueError(
            f"checkpoint path {path!r} is not writable ({e})") from e


def _to_host(obj):
    """Replace jax arrays with numpy arrays throughout a pytree/object.

    Sharded leaves spanning several processes (tensor-parallel params,
    ZeRO-1 optimizer state) are not addressable for a plain np.asarray —
    gather the full value first so checkpoints always hold global
    arrays."""

    def leaf(v):
        if isinstance(v, jax.Array) and not v.is_fully_addressable:
            from jax.experimental import multihost_utils
            return np.asarray(multihost_utils.process_allgather(
                v, tiled=True))
        return np.asarray(v) if hasattr(v, "__array__") else v

    return jax.tree.map(leaf, obj)


def save(obj, path: str, overwrite: bool = False) -> None:
    """Serialize ``obj`` (modules, Tables, pytrees) to ``path``
    (reference File.save, utils/File.scala:62-90)."""
    if _exists(path) and not overwrite:
        raise FileExistsError(
            f"{path} already exists (pass overwrite=True, reference "
            "File.save 'file exists' semantics)")
    host_obj = _to_host(obj)
    leaves, treedef = jax.tree.flatten(host_obj)
    arrays = {}
    placeholders = []
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, np.ndarray) or np.isscalar(leaf):
            arrays[f"a{i}"] = np.asarray(leaf)
            placeholders.append(("arr", f"a{i}"))
        else:
            placeholders.append(("obj", leaf))
    with _open_write_atomic(path) as f, zipfile.ZipFile(f, "w") as z:
        z.writestr("magic", _MAGIC)
        with z.open("arrays.npz", "w", force_zip64=True) as member:
            np.savez(member, **arrays)
        z.writestr("structure.pkl",
                   pickle.dumps((treedef, placeholders),
                                protocol=pickle.HIGHEST_PROTOCOL))


def load(path: str):
    """Inverse of :func:`save` (reference File.load)."""
    with _open_read(path) as f, zipfile.ZipFile(f) as z:
        assert z.read("magic").decode() == _MAGIC, "not a bigdl_tpu file"
        with z.open("arrays.npz") as member:
            npz = np.load(member, allow_pickle=False)
            npz = {k: npz[k] for k in npz.files}
        treedef, placeholders = pickle.loads(z.read("structure.pkl"))
    leaves = [npz[key] if kind == "arr" else key
              for kind, key in placeholders]
    return jax.tree.unflatten(treedef, leaves)


def _strip_runtime(module) -> None:
    """Drop gradients/rng recursively before serialization."""
    module.grad_params = None
    module._rng = None
    for child in getattr(module, "modules", []):
        _strip_runtime(child)


def _reset_grads(module) -> None:
    import jax.numpy as jnp
    if module.params is not None:
        module.grad_params = jax.tree.map(jnp.zeros_like, module.params)
    for child in getattr(module, "modules", []):
        _reset_grads(child)


def save_module(module, path: str, overwrite: bool = False, *,
                prepared: bool = False) -> None:
    """Persist a module with its params/state (reference
    AbstractModule.save, nn/abstractnn/AbstractModule.scala:305-310).

    The module object itself is pickled (topology + hyperparams) with its
    arrays moved to host memory, so ``load_module`` restores a working
    module without re-materialization.

    ``prepared=True`` skips the clone/strip/host-copy pass: the caller
    guarantees ``module`` is already a detached snapshot holding host
    arrays only (the async checkpoint writer's path,
    ``Optimizer._snapshot_module`` — the clone must happen on the
    training thread, the pickling must not).
    """
    if _exists(path) and not overwrite:
        raise FileExistsError(f"{path} already exists")
    if not prepared:
        module = module.clone_module()
        _strip_runtime(module)
        module.params = _to_host(module.params)
        module.state = _to_host(module.state)
        if module.params is not None:
            # rebind children onto subtrees of the host copies — without
            # this the pickle stores a second (device-array) copy per
            # child
            module.sync(module.params, module.state)
    with _open_write_atomic(path) as f:
        pickle.dump((_MAGIC, module), f,
                    protocol=pickle.HIGHEST_PROTOCOL)


def load_module(path: str):
    """(reference Module.load, nn/Module.scala:27-29)"""
    with _open_read(path) as f:
        magic, module = pickle.load(f)
    assert magic == _MAGIC, "not a bigdl_tpu module file"
    if module.params is not None:
        import jax.numpy as jnp
        module.params = jax.tree.map(jnp.asarray, module.params)
        module.state = jax.tree.map(jnp.asarray, module.state)
        module.sync(module.params, module.state)
        _reset_grads(module)
    return module

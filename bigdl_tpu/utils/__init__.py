"""Utilities: Table, checkpoint IO, RNG, interop loaders (reference:
dl/.../bigdl/utils/)."""

from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils.random import RandomGenerator
from bigdl_tpu.utils import file  # noqa: F401

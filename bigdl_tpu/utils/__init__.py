"""Utilities: Table, checkpoint IO, RNG, interop loaders (reference:
dl/.../bigdl/utils/)."""

from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils.random import RandomGenerator
from bigdl_tpu.utils import file  # noqa: F401
from bigdl_tpu.utils.caffe import load_caffe
from bigdl_tpu.utils.torchfile import load_torch, save_torch

"""Table — the universal config/state container.

Reference parity: utils/Table.scala:34-328 and the ``T`` constructor object
(:285-327) — a Lua-style hybrid map/array used for optimizer config, training
state and nested activations. Here it is a thin dict subclass with attribute
access and the reference's 1-based array part; JAX pytrees (tuples/dicts)
cover the nested-activation role.
"""
from __future__ import annotations

__all__ = ["Table", "T"]


class Table(dict):
    """dict with attribute access and 1-based integer array part."""

    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError as e:
            raise AttributeError(k) from e

    def __setattr__(self, k, v):
        self[k] = v

    def insert(self, value):
        """Append to the array part (1-based, reference Table.insert)."""
        i = 1
        while i in self:
            i += 1
        self[i] = value
        return self

    def length(self) -> int:
        n = 0
        while (n + 1) in self:
            n += 1
        return n

    def update_with(self, other: dict):
        self.update(other)
        return self

    def clone(self) -> "Table":
        import copy
        return copy.deepcopy(self)


def T(*args, **kwargs) -> Table:
    """Build a Table: positional args go to the 1-based array part,
    keyword args to the map part (reference object T, Table.scala:285-327)."""
    t = Table()
    for i, a in enumerate(args, start=1):
        t[i] = a
    t.update(kwargs)
    return t

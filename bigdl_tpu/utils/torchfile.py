"""Torch7 ``.t7`` binary reader/writer — pure Python.

Reference parity: utils/TorchFile.scala:35-1047 — the binary-compatible
Torch serialization used for Torch interop (``Module.loadTorch`` /
``saveTorch``) and test fixtures. Format (little-endian):

    object   := int32 type, payload
    type     := NIL 0 | NUMBER 1 | STRING 2 | TABLE 3 | TORCH 4 | BOOLEAN 5
    NUMBER   := float64
    STRING   := int32 len, bytes
    BOOLEAN  := int32 (1/0)
    TABLE    := int32 index, int32 size, size * (object key, object value)
    TORCH    := int32 index, STRING version ("V 1"), STRING class, body
    Tensor   := int32 ndim, int64[ndim] size, int64[ndim] stride,
                int64 storageOffset (1-based), object storage
    Storage  := int64 size, raw elements

Indices form a shared-object registry: a TORCH/TABLE with an
already-seen index is a back-reference (TorchFile.scala:213-249).

Supported module classes cover the reference writer's set
(TorchFile.scala:443-620) and the full CNN zoo: Sequential, Concat,
ConcatTable, Linear, SpatialConvolution(+MM), max/avg pooling,
ReLU/Tanh/Sigmoid/SoftMax/LogSoftMax/Threshold/PReLU, View, Reshape,
Dropout, (Spatial)BatchNormalization, SpatialCrossMapLRN, CAddTable,
CMulTable, CAdd/CMul, LookupTable, SplitTable/JoinTable,
SpatialZeroPadding, Mul/AddConstant, Identity (Remat wrappers serialize
as their forward-equivalent inner module). Tensors map to/from numpy;
torch (out,in[,kH,kW]) layouts match this repo's parameter layouts
directly. save_torch/load_torch round-trips every CNN zoo model
including ResNet (tests/test_torchfile.py TestZooRoundTrip).
"""
from __future__ import annotations

import os
import struct
from typing import Any

import numpy as np

__all__ = ["load", "save", "load_torch", "save_torch", "TorchTable"]

TYPE_NIL = 0
TYPE_NUMBER = 1
TYPE_STRING = 2
TYPE_TABLE = 3
TYPE_TORCH = 4
TYPE_BOOLEAN = 5
TYPE_FUNCTION = 6
LEGACY_TYPE_RECUR_FUNCTION = 7
TYPE_RECUR_FUNCTION = 8

_STORAGE_DTYPES = {
    "torch.FloatStorage": np.float32, "torch.CudaStorage": np.float32,
    "torch.DoubleStorage": np.float64, "torch.CudaDoubleStorage": np.float64,
    "torch.LongStorage": np.int64, "torch.CudaLongStorage": np.int64,
    "torch.IntStorage": np.int32, "torch.ByteStorage": np.uint8,
    "torch.CharStorage": np.int8, "torch.ShortStorage": np.int16,
}
_TENSOR_DTYPES = {
    "torch.FloatTensor": np.float32, "torch.CudaTensor": np.float32,
    "torch.DoubleTensor": np.float64, "torch.CudaDoubleTensor": np.float64,
    "torch.LongTensor": np.int64, "torch.CudaLongTensor": np.int64,
    "torch.IntTensor": np.int32, "torch.ByteTensor": np.uint8,
    "torch.CharTensor": np.int8, "torch.ShortTensor": np.int16,
}


class TorchTable(dict):
    """A lua table: string and 1-based integer keys. ``array()`` gives the
    contiguous 1..n slice as a list (module lists etc.)."""

    def array(self) -> list:
        out = []
        i = 1
        while i in self or float(i) in self:
            out.append(self.get(i, self.get(float(i))))
            i += 1
        return out


# ---------------------------------------------------------------------------
# reading
# ---------------------------------------------------------------------------

class _Reader:
    def __init__(self, buf: bytes, build_modules: bool):
        self.buf = buf
        self.pos = 0
        self.objects: dict[int, Any] = {}
        self.build_modules = build_modules

    def _unpack(self, fmt: str, n: int):
        val = struct.unpack_from("<" + fmt, self.buf, self.pos)[0]
        self.pos += n
        return val

    def read_int(self) -> int:
        return self._unpack("i", 4)

    def read_long(self) -> int:
        return self._unpack("q", 8)

    def read_number(self) -> float:
        return self._unpack("d", 8)

    def read_string(self) -> str:
        n = self.read_int()
        s = self.buf[self.pos:self.pos + n].decode("latin-1")
        self.pos += n
        return s

    def read_storage(self, dtype) -> np.ndarray:
        n = self.read_long()
        itemsize = np.dtype(dtype).itemsize
        arr = np.frombuffer(self.buf, dtype, count=n, offset=self.pos).copy()
        self.pos += n * itemsize
        return arr

    def read_tensor(self, dtype) -> np.ndarray:
        ndim = self.read_int()
        sizes = [self.read_long() for _ in range(ndim)]
        strides = [self.read_long() for _ in range(ndim)]
        offset = self.read_long()          # 1-based
        storage = self.read_object()
        if ndim == 0 or storage is None:
            return np.zeros(sizes, dtype)
        itemsize = np.dtype(dtype).itemsize
        view = np.lib.stride_tricks.as_strided(
            storage[offset - 1:], shape=sizes,
            strides=[s * itemsize for s in strides])
        return view.copy()

    def read_table(self) -> TorchTable:
        size = self.read_int()
        out = TorchTable()
        for _ in range(size):
            k = self.read_object()
            v = self.read_object()
            if isinstance(k, float) and k.is_integer():
                k = int(k)
            out[k] = v
        return out

    def read_version_and_class(self) -> tuple[int, str]:
        """(TorchFile.scala:719-726): 'V <n>' then class, or legacy
        class-only (version 0)."""
        s = self.read_string()
        if s.startswith("V ") and s[2:].isdigit():
            return int(s[2:]), self.read_string()
        return 0, s

    def read_object(self) -> Any:
        type_id = self.read_int()
        if type_id == TYPE_NIL:
            return None
        if type_id == TYPE_NUMBER:
            return self.read_number()
        if type_id == TYPE_STRING:
            return self.read_string()
        if type_id == TYPE_BOOLEAN:
            return bool(self.read_int())
        if type_id == TYPE_TABLE:
            idx = self.read_int()
            if idx in self.objects:
                return self.objects[idx]
            result = TorchTable()
            self.objects[idx] = result   # register BEFORE recursing
            size = self.read_int()
            for _ in range(size):
                k = self.read_object()
                v = self.read_object()
                if isinstance(k, float) and k.is_integer():
                    k = int(k)
                result[k] = v
            return result
        if type_id == TYPE_TORCH:
            idx = self.read_int()
            if idx in self.objects:
                return self.objects[idx]
            _, cls = self.read_version_and_class()
            if cls in _TENSOR_DTYPES:
                result = self.read_tensor(_TENSOR_DTYPES[cls])
            elif cls in _STORAGE_DTYPES:
                result = self.read_storage(_STORAGE_DTYPES[cls])
            else:
                elements = self.read_object()
                result = (_build_module(cls, elements)
                          if self.build_modules else elements)
            self.objects[idx] = result
            return result
        raise ValueError(f"unsupported t7 type id {type_id} "
                         f"at byte {self.pos - 4}")


# ---------------------------------------------------------------------------
# torch table -> bigdl_tpu module (reference readModuleWithType, :135-181)
# ---------------------------------------------------------------------------

def _set_params(module, **arrays):
    import jax.numpy as jnp
    module.materialize()
    for key, val in arrays.items():
        if val is not None:
            module.params[key] = jnp.asarray(
                np.asarray(val, np.float32).reshape(
                    module.params[key].shape))
    return module


def _build_module(cls_name: str, e: TorchTable):
    from bigdl_tpu import nn
    name = cls_name.replace("cudnn.", "nn.")
    if name == "nn.Sequential":
        seq = nn.Sequential()
        for child in e["modules"].array():
            seq.add(child)
        return seq
    if name == "nn.Concat":
        c = nn.Concat(int(e["dimension"]) - 1)   # torch dims are 1-based
        for child in e["modules"].array():
            c.add(child)
        return c
    if name == "nn.ConcatTable":
        c = nn.ConcatTable()
        for child in e["modules"].array():
            c.add(child)
        return c
    if name == "nn.Linear":
        w, b = e["weight"], e.get("bias")
        m = nn.Linear(w.shape[1], w.shape[0], with_bias=b is not None)
        return _set_params(m, weight=w, bias=b)
    if name in ("nn.SpatialConvolution", "nn.SpatialConvolutionMM"):
        m = nn.SpatialConvolution(
            int(e["nInputPlane"]), int(e["nOutputPlane"]),
            int(e["kW"]), int(e["kH"]), int(e.get("dW", 1)),
            int(e.get("dH", 1)), int(e.get("padW", 0)),
            int(e.get("padH", 0)),
            with_bias=e.get("bias") is not None)
        return _set_params(m, weight=e["weight"], bias=e.get("bias"))
    if name == "nn.SpatialMaxPooling":
        m = nn.SpatialMaxPooling(
            int(e["kW"]), int(e["kH"]), int(e.get("dW", 1)),
            int(e.get("dH", 1)), int(e.get("padW", 0)),
            int(e.get("padH", 0)))
        if e.get("ceil_mode"):
            m.ceil()
        return m
    if name == "nn.SpatialAveragePooling":
        return nn.SpatialAveragePooling(
            int(e["kW"]), int(e["kH"]), int(e.get("dW", 1)),
            int(e.get("dH", 1)), int(e.get("padW", 0)),
            int(e.get("padH", 0)))
    if name in ("nn.BatchNormalization", "nn.SpatialBatchNormalization"):
        import jax.numpy as jnp
        ctor = (nn.SpatialBatchNormalization
                if name.endswith("SpatialBatchNormalization")
                else nn.BatchNormalization)
        mean = e["running_mean"]
        m = ctor(int(mean.shape[0]), eps=float(e.get("eps", 1e-5)),
                 momentum=float(e.get("momentum", 0.1)),
                 affine=bool(e.get("affine", True)))
        m = _set_params(m, weight=e.get("weight"), bias=e.get("bias"))
        m.state["running_mean"] = jnp.asarray(mean, jnp.float32)
        var = e.get("running_var")
        if var is not None:
            m.state["running_var"] = jnp.asarray(var, jnp.float32)
        return m
    if name == "nn.ReLU":
        return nn.ReLU(bool(e.get("inplace", False)))
    if name == "nn.Tanh":
        return nn.Tanh()
    if name == "nn.Sigmoid":
        return nn.Sigmoid()
    if name == "nn.LogSoftMax":
        return nn.LogSoftMax()
    if name == "nn.SoftMax":
        return nn.SoftMax()
    if name == "nn.Threshold":
        return nn.Threshold(float(e.get("threshold", 1e-6)),
                            float(e.get("val", 0.0)))
    if name == "nn.View":
        sizes = e["size"]
        sizes = ([int(s) for s in np.asarray(sizes).reshape(-1)]
                 if not isinstance(sizes, TorchTable)
                 else [int(s) for s in sizes.array()])
        return nn.View(*sizes)
    if name == "nn.Reshape":
        sizes = e["size"]
        sizes = ([int(s) for s in np.asarray(sizes).reshape(-1)]
                 if not isinstance(sizes, TorchTable)
                 else [int(s) for s in sizes.array()])
        return nn.Reshape(sizes)
    if name == "nn.Dropout":
        return nn.Dropout(float(e.get("p", 0.5)))
    if name == "nn.CAddTable":
        return nn.CAddTable(bool(e.get("inplace", False)))
    if name == "nn.CMulTable":
        return nn.CMulTable()
    if name == "nn.Identity":
        return nn.Identity()
    if name == "nn.LookupTable":
        w = e["weight"]
        m = nn.LookupTable(w.shape[0], w.shape[1],
                           padding_value=float(e.get("paddingValue", 0)),
                           max_norm=e.get("maxNorm"))
        return _set_params(m, weight=w)
    if name == "nn.PReLU":
        return _set_params(nn.PReLU(int(e.get("nOutputPlane", 0))),
                           weight=e["weight"])
    if name == "nn.CMul":
        return _set_params(nn.CMul(_int_sizes(e["size"])),
                           weight=e["weight"])
    if name == "nn.CAdd":
        return _set_params(nn.CAdd(_int_sizes(e["size"])), bias=e["bias"])
    if name == "nn.SpatialCrossMapLRN":
        return nn.SpatialCrossMapLRN(
            int(e.get("size", 5)), float(e.get("alpha", 1.0)),
            float(e.get("beta", 0.75)), float(e.get("k", 1.0)))
    if name == "nn.SplitTable":
        return nn.SplitTable(int(e["dimension"]) - 1,
                             int(e.get("nInputDims", -1)))
    if name == "nn.JoinTable":
        return nn.JoinTable(int(e["dimension"]) - 1,
                            int(e.get("nInputDims", -1)))
    if name == "nn.SpatialZeroPadding":
        return nn.SpatialZeroPadding(
            int(e["pad_l"]), int(e["pad_r"]), int(e["pad_t"]),
            int(e["pad_b"]))
    if name == "nn.MulConstant":
        return nn.MulConstant(float(e["constant_scalar"]),
                              bool(e.get("inplace", False)))
    if name == "nn.AddConstant":
        return nn.AddConstant(float(e["constant_scalar"]),
                              bool(e.get("inplace", False)))
    raise ValueError(f"unsupported torch module {cls_name}")


def _int_sizes(v) -> tuple:
    """A torch size field arrives as a LongStorage tensor or a lua
    table/array — normalize to a tuple of ints."""
    if isinstance(v, TorchTable):
        return tuple(int(s) for s in v.array())
    return tuple(int(s) for s in np.asarray(v).reshape(-1))


# ---------------------------------------------------------------------------
# writing
# ---------------------------------------------------------------------------

class _Writer:
    def __init__(self):
        self.parts: list[bytes] = []
        self.index = 0
        self.seen: dict[int, int] = {}   # id(obj) -> registry index
        self._refs: list = []            # pin objects: id() keys must not
                                         # be reused by freed temporaries

    def put(self, fmt: str, *vals):
        self.parts.append(struct.pack("<" + fmt, *vals))

    def write_string(self, s: str):
        raw = s.encode("latin-1")
        self.put("i", len(raw))
        self.parts.append(raw)

    def _next_index(self, obj) -> tuple[int, bool]:
        key = id(obj)
        if key in self.seen:
            return self.seen[key], True
        self.index += 1
        self.seen[key] = self.index
        self._refs.append(obj)
        return self.index, False

    def write_tensor(self, arr: np.ndarray):
        arr = np.ascontiguousarray(arr)
        cls = {np.dtype(np.float32): ("torch.FloatTensor",
                                      "torch.FloatStorage"),
               np.dtype(np.float64): ("torch.DoubleTensor",
                                      "torch.DoubleStorage"),
               np.dtype(np.int64): ("torch.LongTensor",
                                    "torch.LongStorage")}[arr.dtype]
        self.put("i", TYPE_TORCH)
        idx, seen = self._next_index(arr)
        self.put("i", idx)
        if seen:
            return
        self.write_string("V 1")
        self.write_string(cls[0])
        self.put("i", arr.ndim)
        for s in arr.shape:
            self.put("q", s)
        stride = 1
        strides = []
        for s in reversed(arr.shape):
            strides.append(stride)
            stride *= s
        for s in reversed(strides):
            self.put("q", s)
        self.put("q", 1)                   # storageOffset, 1-based
        # storage object
        self.put("i", TYPE_TORCH)
        self.index += 1
        self.put("i", self.index)
        self.write_string("V 1")
        self.write_string(cls[1])
        self.put("q", arr.size)
        self.parts.append(arr.tobytes())

    def write_table(self, table: dict):
        self.put("i", TYPE_TABLE)
        idx, seen = self._next_index(table)
        self.put("i", idx)
        if seen:
            return
        self.put("i", len(table))
        for k, v in table.items():
            self.write_object(float(k) if isinstance(k, int) else k)
            self.write_object(v)

    def write_module(self, module):
        self.put("i", TYPE_TORCH)
        idx, seen = self._next_index(module)
        self.put("i", idx)
        if seen:
            return
        cls, table = _module_to_table(module)
        self.write_string("V 1")
        self.write_string(cls)
        self.write_table(table)

    def write_object(self, obj):
        if obj is None:
            self.put("i", TYPE_NIL)
        elif isinstance(obj, bool):
            self.put("i", TYPE_BOOLEAN)
            self.put("i", 1 if obj else 0)
        elif isinstance(obj, (int, float)):
            self.put("i", TYPE_NUMBER)
            self.put("d", float(obj))
        elif isinstance(obj, str):
            self.put("i", TYPE_STRING)
            self.write_string(obj)
        elif isinstance(obj, np.ndarray) or hasattr(obj, "__array__"):
            self.write_tensor(np.asarray(obj))
        elif isinstance(obj, dict):
            self.write_table(obj)
        else:
            self.write_module(obj)


def _np(x):
    return None if x is None else np.asarray(x)


def _module_to_table(m) -> tuple[str, dict]:
    """bigdl_tpu module -> (torch class name, field table) (reference
    write<Module> family, TorchFile.scala:443-620)."""
    from bigdl_tpu import nn
    if isinstance(m, nn.Remat):
        # torch7 has no remat wrapper; the inner module is
        # forward-equivalent (nn/containers.py Remat is pytree-transparent)
        return _module_to_table(m.modules[0])
    t: dict = {"_type": "torch.FloatTensor", "train": m.is_training()}
    p = m.params or {}
    if isinstance(m, (nn.Sequential, nn.Concat, nn.ConcatTable)):
        mods = {i + 1: child for i, child in enumerate(m.modules)}
        t["modules"] = mods
        if isinstance(m, nn.Concat):
            t["dimension"] = m.dimension + 1   # torch is 1-based
            return "nn.Concat", t
        if isinstance(m, nn.ConcatTable):
            return "nn.ConcatTable", t
        return "nn.Sequential", t
    m.materialize()
    p = m.params
    if isinstance(m, nn.SpatialConvolution):
        t.update(nInputPlane=float(m.n_input_plane),
                 nOutputPlane=float(m.n_output_plane),
                 kW=float(m.kw), kH=float(m.kh), dW=float(m.dw),
                 dH=float(m.dh), padW=float(m.pw), padH=float(m.ph),
                 weight=_np(p["weight"]),
                 gradWeight=np.zeros_like(_np(p["weight"])))
        if "bias" in p:
            t["bias"] = _np(p["bias"])
            t["gradBias"] = np.zeros_like(t["bias"])
        return "nn.SpatialConvolution", t
    if isinstance(m, nn.Linear):
        t.update(weight=_np(p["weight"]),
                 gradWeight=np.zeros_like(_np(p["weight"])))
        if "bias" in p:
            t["bias"] = _np(p["bias"])
            t["gradBias"] = np.zeros_like(t["bias"])
        return "nn.Linear", t
    if isinstance(m, nn.SpatialMaxPooling):
        t.update(kW=float(m.kw), kH=float(m.kh), dW=float(m.dw),
                 dH=float(m.dh), padW=float(m.pw), padH=float(m.ph),
                 ceil_mode=bool(getattr(m, "ceil_mode", False)))
        return "nn.SpatialMaxPooling", t
    if isinstance(m, nn.SpatialAveragePooling):
        t.update(kW=float(m.kw), kH=float(m.kh), dW=float(m.dw),
                 dH=float(m.dh), padW=float(m.pw), padH=float(m.ph),
                 ceil_mode=False)
        return "nn.SpatialAveragePooling", t
    if isinstance(m, nn.BatchNormalization):   # covers Spatial variant
        t.update(eps=float(m.eps), momentum=float(m.momentum),
                 affine=bool(m.affine),
                 running_mean=_np(m.state["running_mean"]),
                 running_var=_np(m.state["running_var"]))
        if m.affine:
            t["weight"] = _np(p["weight"])
            t["bias"] = _np(p["bias"])
        cls = ("nn.SpatialBatchNormalization"
               if isinstance(m, nn.SpatialBatchNormalization)
               else "nn.BatchNormalization")
        return cls, t
    if isinstance(m, nn.ReLU):
        t.update(inplace=False, val=0.0, threshold=0.0)
        return "nn.ReLU", t
    if isinstance(m, nn.Tanh):
        return "nn.Tanh", t
    if isinstance(m, nn.Sigmoid):
        return "nn.Sigmoid", t
    if isinstance(m, nn.LogSoftMax):
        return "nn.LogSoftMax", t
    if isinstance(m, nn.View):
        t["size"] = np.asarray(m.sizes, np.int64)
        t["numElements"] = float(int(np.prod(
            [s for s in m.sizes if s > 0])))
        return "nn.View", t
    if isinstance(m, nn.Reshape):
        t["size"] = np.asarray(m.size, np.int64)
        return "nn.Reshape", t
    if isinstance(m, nn.Dropout):
        t["p"] = float(m.p)
        t["noise"] = np.zeros((0,), np.float32)
        return "nn.Dropout", t
    if isinstance(m, nn.Identity):
        return "nn.Identity", t
    if isinstance(m, nn.SoftMax):
        return "nn.SoftMax", t
    if isinstance(m, nn.Threshold):
        t.update(threshold=float(m.th), val=float(m.value), inplace=False)
        return "nn.Threshold", t
    if isinstance(m, nn.CAddTable):
        t["inplace"] = bool(getattr(m, "inplace", False))
        return "nn.CAddTable", t
    if isinstance(m, nn.CMulTable):
        return "nn.CMulTable", t
    if isinstance(m, nn.LookupTable):
        t.update(weight=_np(p["weight"]),
                 gradWeight=np.zeros_like(_np(p["weight"])),
                 nIndex=float(m.n_index), nOutput=float(m.n_output),
                 paddingValue=float(m.padding_value))
        if m.max_norm is not None:
            t["maxNorm"] = float(m.max_norm)
        return "nn.LookupTable", t
    if isinstance(m, nn.PReLU):
        t.update(weight=_np(p["weight"]),
                 gradWeight=np.zeros_like(_np(p["weight"])),
                 nOutputPlane=float(m.n_output_plane))
        return "nn.PReLU", t
    if isinstance(m, nn.CMul):
        t.update(weight=_np(p["weight"]),
                 gradWeight=np.zeros_like(_np(p["weight"])),
                 size=np.asarray(m.size, np.int64))
        return "nn.CMul", t
    if isinstance(m, nn.CAdd):
        t.update(bias=_np(p["bias"]),
                 gradBias=np.zeros_like(_np(p["bias"])),
                 size=np.asarray(m.size, np.int64))
        return "nn.CAdd", t
    if isinstance(m, nn.SpatialCrossMapLRN):
        t.update(size=float(m.size), alpha=float(m.alpha),
                 beta=float(m.beta), k=float(m.k))
        return "nn.SpatialCrossMapLRN", t
    if isinstance(m, nn.SplitTable):
        t.update(dimension=float(m.dimension + 1),
                 nInputDims=float(m.n_input_dims))
        return "nn.SplitTable", t
    if isinstance(m, nn.JoinTable):
        t.update(dimension=float(m.dimension + 1),
                 nInputDims=float(m.n_input_dims))
        return "nn.JoinTable", t
    if isinstance(m, nn.SpatialZeroPadding):
        t.update(pad_l=float(m.pl), pad_r=float(m.pr), pad_t=float(m.pt),
                 pad_b=float(m.pb))
        return "nn.SpatialZeroPadding", t
    if isinstance(m, nn.MulConstant):
        t.update(constant_scalar=float(m.constant), inplace=False)
        return "nn.MulConstant", t
    if isinstance(m, nn.AddConstant):
        t.update(constant_scalar=float(m.constant), inplace=False)
        return "nn.AddConstant", t
    raise ValueError(f"saveTorch: unsupported module {type(m).__name__}")


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def load(path: str, build_modules: bool = True):
    """Read a .t7 file (reference TorchFile.load, :72-78). Tensors come
    back as numpy arrays, tables as TorchTable, nn classes as bigdl_tpu
    modules (or raw field tables when ``build_modules=False``)."""
    with open(path, "rb") as f:
        buf = f.read()
    return _Reader(buf, build_modules).read_object()


def save(obj, path: str, overwrite: bool = False):
    """Write tensors/tables/modules as .t7 (reference TorchFile.save)."""
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(path)
    w = _Writer()
    w.write_object(obj)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(b"".join(w.parts))
    os.replace(tmp, path)


def load_torch(path: str):
    """(reference Module.loadTorch, nn/Module.scala:31-33)"""
    module = load(path, build_modules=True)
    if not hasattr(module, "apply"):
        raise ValueError(f"{path} does not contain an nn module")
    return module


def save_torch(module, path: str, overwrite: bool = False):
    """(reference AbstractModule.saveTorch, :311-315)"""
    save(module, path, overwrite)

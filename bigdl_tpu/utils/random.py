"""Deterministic RNG for host-side code (data pipeline, shuffling, init seeds).

Reference parity: utils/RandomGenerator.scala:20-265 — a thread-local,
Torch-compatible Mersenne-Twister used for reproducible init and shuffling.
Here device-side randomness uses ``jax.random`` keys (threaded explicitly
through init/apply — the idiomatic JAX design), while host-side shuffling and
data augmentation use this MT19937 generator for reproducibility.
"""
from __future__ import annotations

import threading

import numpy as np

__all__ = ["RandomGenerator"]


class RandomGenerator:
    """Thread-local seeded MT19937 (reference: RandomGenerator.scala:22-33)."""

    _local = threading.local()
    _default_seed = 1

    def __init__(self, seed: int | None = None):
        self._rng = np.random.Generator(np.random.MT19937(
            seed if seed is not None else self._default_seed))

    # -- thread-local singleton (reference `RNG`) --
    @classmethod
    def RNG(cls) -> "RandomGenerator":
        inst = getattr(cls._local, "inst", None)
        if inst is None:
            inst = cls(cls._default_seed)
            cls._local.inst = inst
        return inst

    @classmethod
    def set_seed(cls, seed: int) -> "RandomGenerator":
        cls._default_seed = seed
        return cls.seed_thread(seed)

    @classmethod
    def seed_thread(cls, seed: int) -> "RandomGenerator":
        """Seed ONLY the calling thread's generator (the class default
        stays untouched)."""
        cls._local.inst = cls(seed)
        return cls._local.inst

    @classmethod
    def adopt(cls, inst: "RandomGenerator") -> "RandomGenerator":
        """Bind THIS thread's ``RNG()`` to an existing generator
        instance. The prefetch worker (dataset/prefetch.py) adopts its
        creator thread's generator so pipeline augmentation draws
        continue the exact stream the synchronous loop would have used
        — thread-local isolation would silently fork it."""
        cls._local.inst = inst
        return inst

    @classmethod
    def seed_worker(cls, worker_index: int, invocation: int = 0
                    ) -> "RandomGenerator":
        """Seed a worker thread's generator with a stream distinct per
        worker AND per pipeline invocation: workers must not duplicate
        each other's crops/flips, and epoch N must not replay epoch 1's
        augmentation (pipelines are re-created per epoch)."""
        return cls.seed_thread(cls._default_seed
                               + 0x9E3779B1 * (worker_index + 1)
                               + 0x85EBCA77 * invocation)

    # -- draws (reference RandomGenerator.scala:49-265) --
    def uniform(self, a: float = 0.0, b: float = 1.0, size=None):
        return self._rng.uniform(a, b, size)

    def normal(self, mean: float = 0.0, stdv: float = 1.0, size=None):
        return self._rng.normal(mean, stdv, size)

    def bernoulli(self, p: float, size=None):
        return (self._rng.random(size) < p).astype(np.float32)

    def random_int(self, low: int, high: int, size=None):
        return self._rng.integers(low, high, size)

    def shuffle(self, seq):
        """In-place Fisher-Yates (reference RandomGenerator.scala:36-47)."""
        self._rng.shuffle(seq)
        return seq

    def permutation(self, n: int):
        return self._rng.permutation(n)

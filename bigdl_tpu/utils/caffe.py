"""Caffe model import — pure-Python prototxt + caffemodel readers.

Reference parity: utils/CaffeLoader.scala:38-162 — parse the prototxt
(protobuf text format) and the binary caffemodel (protobuf wire format,
fields per the generated caffe protobuf in the reference's
dl/src/main/java/caffe/Caffe.java), then copy each layer's blobs into the
model's ``get_parameters_table()`` entries by LAYER NAME: blob 0 → weight,
blob 1 → bias, matched by element count and reshaped to the target
parameter's shape (the reference copies into the flat Torch storage the
same way). ``match_all`` raises when a parameterized module has no
same-named caffe layer.

No protobuf runtime is needed: the wire format is five primitive field
encodings, and the loader touches only four message types (NetParameter,
LayerParameter / V1LayerParameter, BlobProto, BlobShape).

Layout compatibility notes (why a flat copy is correct):
- Caffe convolution blobs are (out, in/group, kH, kW) — exactly this
  repo's SpatialConvolution weight layout (nn/conv.py).
- Caffe InnerProduct blobs are (out, in) — exactly Linear's (y = x W^T).
- Caffe splits Torch-style BN across TWO layers: a ``BatchNorm`` layer
  holding [mean, variance, scale_factor] (the statistics must be divided
  by scale_factor[0] — caffe accumulates unnormalized sums there) and a
  following ``Scale`` layer holding [gamma, beta]. When the target module
  is a BatchNormalization, the loader detects either layer by name,
  resolves its companion through the prototxt topology (the Scale whose
  bottom is the BatchNorm's top, or vice versa), writes the normalized
  statistics into running_mean/running_var, and gamma/beta into
  weight/bias (γ=1, β=0 when no Scale companion exists — caffe's
  BatchNorm alone applies no affine). This goes beyond the reference
  loader (CaffeLoader.scala:85-151 copies blob0→weight blob1→bias
  blindly, which silently mis-imports real ResNet BN statistics).
"""
from __future__ import annotations

import logging
from typing import Iterator

import numpy as np

logger = logging.getLogger("bigdl_tpu.utils.caffe")

__all__ = ["CaffeLoader", "load_caffe", "parse_caffemodel", "parse_prototxt"]


# ---------------------------------------------------------------------------
# protobuf wire-format primitives
# ---------------------------------------------------------------------------

def _varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf: bytes) -> Iterator[tuple[int, int, bytes | int]]:
    """Yield (field_number, wire_type, payload). Length-delimited payloads
    come back as bytes; varints as int; fixed32/fixed64 as raw bytes."""
    pos, end = 0, len(buf)
    while pos < end:
        key, pos = _varint(buf, pos)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:                       # varint
            val, pos = _varint(buf, pos)
            yield fnum, wtype, val
        elif wtype == 1:                     # 64-bit
            yield fnum, wtype, buf[pos:pos + 8]
            pos += 8
        elif wtype == 2:                     # length-delimited
            ln, pos = _varint(buf, pos)
            yield fnum, wtype, buf[pos:pos + ln]
            pos += ln
        elif wtype == 5:                     # 32-bit
            yield fnum, wtype, buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wtype} "
                             f"(field {fnum} at byte {pos})")


def _f32s(payload) -> np.ndarray:
    """Float field payloads arrive either packed (wire type 2: N*4 bytes)
    or as repeated single fixed32 fields (wire type 5: 4 bytes each via
    ``_fields``); both are raw little-endian f32 bytes."""
    return np.frombuffer(payload, "<f4")


# ---------------------------------------------------------------------------
# message readers (field numbers from the reference's generated Caffe.java)
# ---------------------------------------------------------------------------

class Blob:
    """BlobProto: shape=7 (BlobShape.dim=1), data=5 (packed float),
    double_data=8; legacy dims num=1 channels=2 height=3 width=4."""

    __slots__ = ("shape", "data")

    def __init__(self, shape: tuple[int, ...], data: np.ndarray):
        self.shape = shape
        self.data = data

    @classmethod
    def parse(cls, buf: bytes) -> "Blob":
        data_parts: list[np.ndarray] = []
        legacy = {}
        shape: tuple[int, ...] | None = None
        for fnum, wtype, payload in _fields(buf):
            if fnum == 5:        # float data
                data_parts.append(_f32s(payload))
            elif fnum == 8:      # double data
                data_parts.append(
                    np.frombuffer(payload, "<f8").astype(np.float32))
            elif fnum == 7:      # BlobShape
                dims = []
                pos = 0
                for f2, w2, p2 in _fields(payload):
                    if f2 == 1:
                        if w2 == 2:   # packed varints
                            pos = 0
                            while pos < len(p2):
                                d, pos = _varint(p2, pos)
                                dims.append(d)
                        else:
                            dims.append(p2)
                shape = tuple(dims)
            elif fnum in (1, 2, 3, 4) and wtype == 0:
                legacy[fnum] = payload
        if shape is None and legacy:
            shape = tuple(legacy.get(k, 1) for k in (1, 2, 3, 4))
        data = (np.concatenate(data_parts) if data_parts
                else np.zeros(0, np.float32))
        return cls(shape or (data.size,), data)


# V1LayerParameter enum type values -> canonical caffe type strings (only
# the types the zoo needs; others render as "V1:<n>")
_V1_TYPES = {
    3: "Concat", 4: "Convolution", 5: "Data", 6: "Dropout",
    14: "InnerProduct", 15: "LRN", 17: "Pooling", 18: "ReLU",
    19: "Sigmoid", 20: "Softmax", 21: "SoftmaxWithLoss", 22: "Split",
    23: "TanH", 25: "Eltwise", 33: "Slice",
}


class Layer:
    __slots__ = ("name", "type", "blobs")

    def __init__(self, name: str, type_: str, blobs: list[Blob]):
        self.name = name
        self.type = type_
        self.blobs = blobs

    @classmethod
    def parse_v2(cls, buf: bytes) -> "Layer":
        """LayerParameter: name=1, type=2, blobs=7."""
        name = type_ = ""
        blobs = []
        for fnum, wtype, payload in _fields(buf):
            if fnum == 1:
                name = payload.decode("utf-8", "replace")
            elif fnum == 2:
                type_ = payload.decode("utf-8", "replace")
            elif fnum == 7:
                blobs.append(Blob.parse(payload))
        return cls(name, type_, blobs)

    @classmethod
    def parse_v1(cls, buf: bytes) -> "Layer":
        """V1LayerParameter: name=4, type=5 (enum), blobs=6."""
        name, type_ = "", ""
        blobs = []
        for fnum, wtype, payload in _fields(buf):
            if fnum == 4:
                name = payload.decode("utf-8", "replace")
            elif fnum == 5 and wtype == 0:
                type_ = _V1_TYPES.get(payload, f"V1:{payload}")
            elif fnum == 6:
                blobs.append(Blob.parse(payload))
        return cls(name, type_, blobs)


def parse_caffemodel(path: str) -> dict[str, Layer]:
    """Read a binary caffemodel (NetParameter: layers(V1)=2, layer=100)
    into name -> Layer. V2 entries win over V1 on name collision, matching
    the reference's map-build order (CaffeLoader.scala:49-60)."""
    with open(path, "rb") as f:
        buf = f.read()
    v1, v2 = {}, {}
    for fnum, wtype, payload in _fields(buf):
        if fnum == 2 and wtype == 2:
            layer = Layer.parse_v1(payload)
            v1[layer.name] = layer
        elif fnum == 100 and wtype == 2:
            layer = Layer.parse_v2(payload)
            v2[layer.name] = layer
    out = dict(v1)
    out.update(v2)
    return out


# ---------------------------------------------------------------------------
# prototxt (protobuf text format) — minimal recursive parser
# ---------------------------------------------------------------------------

def _tokenize(text: str) -> list[str]:
    tokens = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c in " \t\r\n":
            i += 1
        elif c == "#":
            while i < n and text[i] != "\n":
                i += 1
        elif c in "{}:":
            tokens.append(c)
            i += 1
        elif c in "\"'":
            j = i + 1
            while j < n and text[j] != c:
                j += 1 + (text[j] == "\\")
            tokens.append(text[i:j + 1])
            i = j + 1
        else:
            j = i
            while j < n and text[j] not in " \t\r\n{}:#":
                j += 1
            tokens.append(text[i:j])
            i = j
    return tokens


def _parse_value(tok: str):
    if tok and tok[0] in "\"'":
        return tok[1:-1]
    if tok in ("true", "false"):
        return tok == "true"
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        return tok     # enum identifier


def _parse_block(tokens: list[str], pos: int) -> tuple[dict, int]:
    """Parse `key: value` / `key { ... }` pairs until '}' or EOF. Repeated
    keys accumulate into lists."""
    out: dict = {}

    def put(k, v):
        if k in out:
            if not isinstance(out[k], list):
                out[k] = [out[k]]
            out[k].append(v)
        else:
            out[k] = v

    while pos < len(tokens) and tokens[pos] != "}":
        key = tokens[pos]
        pos += 1
        if pos < len(tokens) and tokens[pos] == ":":
            pos += 1
            if tokens[pos] == "{":      # message after colon (legal)
                sub, pos = _parse_block(tokens, pos + 1)
                pos += 1                # consume '}'
                put(key, sub)
            else:
                put(key, _parse_value(tokens[pos]))
                pos += 1
        elif pos < len(tokens) and tokens[pos] == "{":
            sub, pos = _parse_block(tokens, pos + 1)
            pos += 1
            put(key, sub)
        else:
            raise ValueError(f"prototxt parse error near token {pos}: "
                             f"{tokens[max(0, pos - 3):pos + 3]}")
    return out, pos


def parse_prototxt(path: str) -> dict:
    """Parse a .prototxt into nested dicts (repeated keys -> lists);
    net['layer'] / net['layers'] hold the layer definitions."""
    with open(path, "r", encoding="ascii", errors="replace") as f:
        tokens = _tokenize(f.read())
    net, _ = _parse_block(tokens, 0)
    return net


def _aslist(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


def _named_modules(model) -> dict:
    """name -> module for every node of the model tree (LAST wins on
    duplicate names, matching Container.get_parameters_table's
    dict.update order so the BN branch pairs state and params from the
    same module)."""
    out = {}

    def walk(m):
        out[m.get_name()] = m
        for child in getattr(m, "modules", []):
            walk(child)

    walk(model)
    return out


def _is_bn_module(module) -> bool:
    from bigdl_tpu.nn.normalization import BatchNormalization
    return isinstance(module, BatchNormalization)


# ---------------------------------------------------------------------------
# the loader
# ---------------------------------------------------------------------------

class CaffeLoader:
    """Copy caffe parameters into a bigdl_tpu model by layer name
    (reference CaffeLoader.scala:38-162)."""

    def __init__(self, prototxt_path: str, model_path: str,
                 match_all: bool = True):
        self.prototxt_path = prototxt_path
        self.model_path = model_path
        self.match_all = match_all
        self._layers: dict[str, Layer] | None = None
        self._net_def: dict | None = None

    def _load(self):
        if self._layers is None:
            self._net_def = parse_prototxt(self.prototxt_path)
            logger.info("start loading caffe model from %s", self.model_path)
            self._layers = parse_caffemodel(self.model_path)
            logger.info("load caffe model done (%d layers with blobs: %s)",
                        len(self._layers),
                        [n for n, l in self._layers.items() if l.blobs])
            self._proto = {}
            for ldef in (_aslist(self._net_def.get("layer")) +
                         _aslist(self._net_def.get("layers"))):
                if isinstance(ldef, dict) and "name" in ldef:
                    self._proto.setdefault(ldef["name"], ldef)

    # -- BatchNorm/Scale pairing via prototxt topology -------------------

    def _proto_type(self, name: str) -> str:
        ldef = self._proto.get(name, {})
        t = ldef.get("type", "")
        return t if isinstance(t, str) else ""

    def _layer_type(self, name: str) -> str:
        layer = self._layers.get(name)
        binary_type = layer.type if layer is not None else ""
        return binary_type or self._proto_type(name)

    def _companion(self, name: str, want_type: str,
                   direction: str) -> str | None:
        """Find the prototxt layer of ``want_type`` wired directly
        after (direction='down': its bottom == name's top) or before
        (direction='up': its top == name's bottom) layer ``name``."""
        ldef = self._proto.get(name)
        if ldef is None:
            return None
        key, other = (("top", "bottom") if direction == "down"
                      else ("bottom", "top"))
        anchors = _aslist(ldef.get(key))
        if not anchors:
            return None
        for cand in self._proto.values():
            if cand.get("type") == want_type and \
                    _aslist(cand.get(other))[:1] == anchors[:1]:
                return cand.get("name")
        return None

    def _copy_batchnorm(self, name: str, module, params: dict):
        """Import caffe's split BatchNorm(+Scale) into one torch-style BN
        module: statistics normalized by blob[2]'s scale factor, affine
        from the companion Scale layer (see module docstring)."""
        import jax.numpy as jnp
        if self._layer_type(name) == "BatchNorm":
            bn_name, scale_name = name, self._companion(name, "Scale",
                                                        "down")
        else:   # matched by the Scale layer's name
            bn_name = self._companion(name, "BatchNorm", "up")
            scale_name = name
        if bn_name is not None and self._get_blob(bn_name, 0) is not None:
            mean = self._get_blob(bn_name, 0).data
            var_blob = self._get_blob(bn_name, 1)
            var = (var_blob.data if var_blob is not None
                   else np.ones_like(mean))
            sf_blob = self._get_blob(bn_name, 2)
            # caffe BatchNormLayer: factor = sf==0 ? 0 : 1/sf, stats are
            # blob * factor (blobs hold unnormalized running sums)
            if sf_blob is not None and sf_blob.data.size:
                sf = float(sf_blob.data[0])
                factor = 0.0 if sf == 0.0 else 1.0 / sf
                mean, var = mean * factor, var * factor
            state = module.state
            for key, val in (("running_mean", mean), ("running_var", var)):
                tgt = state[key]
                if int(np.prod(tgt.shape)) != val.size:
                    raise ValueError(
                        f"{key} element number is not equal between caffe "
                        f"layer {bn_name} and bigdl module {name}")
                state[key] = jnp.asarray(val.reshape(tgt.shape), tgt.dtype)
            logger.info("load BN statistics for %s from %s (scale factor "
                        "normalized)", name, bn_name)
        if "weight" in params:
            if scale_name is not None and \
                    self._get_blob(scale_name, 0) is not None:
                self._copy_one(scale_name, params, "weight", 0,
                               log_name=name)
                if self._get_blob(scale_name, 1) is not None:
                    self._copy_one(scale_name, params, "bias", 1,
                                   log_name=name)
            else:
                # caffe BatchNorm without a Scale layer applies no affine
                params["weight"] = jnp.ones_like(params["weight"])
                if "bias" in params:
                    params["bias"] = jnp.zeros_like(params["bias"])

    def _get_blob(self, name: str, ind: int) -> Blob | None:
        layer = self._layers.get(name)
        if layer is not None and len(layer.blobs) > ind:
            return layer.blobs[ind]
        return None

    def _copy_one(self, name: str, params: dict, key: str, ind: int,
                  log_name: str | None = None):
        blob = self._get_blob(name, ind)
        if blob is None:
            return
        if key not in params:
            raise ValueError(f"{name} should contain {key}")
        target = params[key]
        if int(np.prod(target.shape)) != blob.data.size:
            raise ValueError(
                f"{key} element number is not equal between caffe layer and "
                f"bigdl module {log_name or name}, data shape in caffe is "
                f"{blob.shape}, while data shape in bigdl is {target.shape}")
        import jax.numpy as jnp
        params[key] = jnp.asarray(
            blob.data.reshape(target.shape), dtype=target.dtype)

    def copy_parameters(self, model):
        """(reference copyParameters, :132-151) — mutates the model's
        parameter table in place and returns the model."""
        self._load()
        if hasattr(model, "materialize"):
            model.materialize()
        table = model.get_parameters_table()
        named = _named_modules(model)
        # affine=False BatchNormalization has NO weight/bias entry in the
        # table, but its statistics still import — walk it by module
        for name, module in named.items():
            if _is_bn_module(module) and not module.affine and \
                    name in self._layers and \
                    self._layer_type(name) == "BatchNorm":
                logger.info("load parameters for %s ...", name)
                self._copy_batchnorm(name, module, {})
        for name, params in table.items():
            if not isinstance(params, dict) or \
                    ("weight" not in params and "bias" not in params):
                continue
            if name not in self._layers:
                if self.match_all:
                    raise ValueError(
                        f"module {name} cannot map a layer in caffe model")
                logger.info("%s uses initialized parameters", name)
                continue
            logger.info("load parameters for %s ...", name)
            module = named.get(name)
            if _is_bn_module(module) and \
                    self._layer_type(name) in ("BatchNorm", "Scale"):
                self._copy_batchnorm(name, module, params)
                continue
            self._copy_one(name, params, "weight", 0)
            self._copy_one(name, params, "bias", 1)
        # re-sync facades: container params reference the mutated child
        # dicts, so rebinding the root is enough to refresh views
        model.sync(model.params, model.state)
        return model


def load_caffe(model, def_path: str, model_path: str,
               match_all: bool = True):
    """(reference Module.loadCaffe / object CaffeLoader.load)"""
    return CaffeLoader(def_path, model_path, match_all).copy_parameters(model)

"""Caffe model import — pure-Python prototxt + caffemodel readers.

Reference parity: utils/CaffeLoader.scala:38-162 — parse the prototxt
(protobuf text format) and the binary caffemodel (protobuf wire format,
fields per the generated caffe protobuf in the reference's
dl/src/main/java/caffe/Caffe.java), then copy each layer's blobs into the
model's ``get_parameters_table()`` entries by LAYER NAME: blob 0 → weight,
blob 1 → bias, matched by element count and reshaped to the target
parameter's shape (the reference copies into the flat Torch storage the
same way). ``match_all`` raises when a parameterized module has no
same-named caffe layer.

No protobuf runtime is needed: the wire format is five primitive field
encodings, and the loader touches only four message types (NetParameter,
LayerParameter / V1LayerParameter, BlobProto, BlobShape).

Layout compatibility notes (why a flat copy is correct):
- Caffe convolution blobs are (out, in/group, kH, kW) — exactly this
  repo's SpatialConvolution weight layout (nn/conv.py).
- Caffe InnerProduct blobs are (out, in) — exactly Linear's (y = x W^T).
- BatchNorm/Scale layers differ structurally from Torch BN; import those
  by name into SpatialBatchNormalization's weight/bias the same way.
"""
from __future__ import annotations

import logging
from typing import Iterator

import numpy as np

logger = logging.getLogger("bigdl_tpu.utils.caffe")

__all__ = ["CaffeLoader", "load_caffe", "parse_caffemodel", "parse_prototxt"]


# ---------------------------------------------------------------------------
# protobuf wire-format primitives
# ---------------------------------------------------------------------------

def _varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf: bytes) -> Iterator[tuple[int, int, bytes | int]]:
    """Yield (field_number, wire_type, payload). Length-delimited payloads
    come back as bytes; varints as int; fixed32/fixed64 as raw bytes."""
    pos, end = 0, len(buf)
    while pos < end:
        key, pos = _varint(buf, pos)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:                       # varint
            val, pos = _varint(buf, pos)
            yield fnum, wtype, val
        elif wtype == 1:                     # 64-bit
            yield fnum, wtype, buf[pos:pos + 8]
            pos += 8
        elif wtype == 2:                     # length-delimited
            ln, pos = _varint(buf, pos)
            yield fnum, wtype, buf[pos:pos + ln]
            pos += ln
        elif wtype == 5:                     # 32-bit
            yield fnum, wtype, buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wtype} "
                             f"(field {fnum} at byte {pos})")


def _packed_or_single_f32(out: list, wtype, payload):
    if wtype == 2:       # packed
        out.append(np.frombuffer(payload, "<f4"))
    else:                # unpacked single
        out.append(np.frombuffer(payload, "<f4"))


# ---------------------------------------------------------------------------
# message readers (field numbers from the reference's generated Caffe.java)
# ---------------------------------------------------------------------------

class Blob:
    """BlobProto: shape=7 (BlobShape.dim=1), data=5 (packed float),
    double_data=8; legacy dims num=1 channels=2 height=3 width=4."""

    __slots__ = ("shape", "data")

    def __init__(self, shape: tuple[int, ...], data: np.ndarray):
        self.shape = shape
        self.data = data

    @classmethod
    def parse(cls, buf: bytes) -> "Blob":
        data_parts: list[np.ndarray] = []
        legacy = {}
        shape: tuple[int, ...] | None = None
        for fnum, wtype, payload in _fields(buf):
            if fnum == 5:        # float data
                _packed_or_single_f32(data_parts, wtype, payload)
            elif fnum == 8:      # double data
                data_parts.append(
                    np.frombuffer(payload, "<f8").astype(np.float32))
            elif fnum == 7:      # BlobShape
                dims = []
                pos = 0
                for f2, w2, p2 in _fields(payload):
                    if f2 == 1:
                        if w2 == 2:   # packed varints
                            pos = 0
                            while pos < len(p2):
                                d, pos = _varint(p2, pos)
                                dims.append(d)
                        else:
                            dims.append(p2)
                shape = tuple(dims)
            elif fnum in (1, 2, 3, 4) and wtype == 0:
                legacy[fnum] = payload
        if shape is None and legacy:
            shape = tuple(legacy.get(k, 1) for k in (1, 2, 3, 4))
        data = (np.concatenate(data_parts) if data_parts
                else np.zeros(0, np.float32))
        return cls(shape or (data.size,), data)


# V1LayerParameter enum type values -> canonical caffe type strings (only
# the types the zoo needs; others render as "V1:<n>")
_V1_TYPES = {
    3: "Concat", 4: "Convolution", 5: "Data", 6: "Dropout",
    14: "InnerProduct", 15: "LRN", 17: "Pooling", 18: "ReLU",
    19: "Sigmoid", 20: "Softmax", 21: "SoftmaxWithLoss", 22: "Split",
    23: "TanH", 25: "Eltwise", 33: "Slice",
}


class Layer:
    __slots__ = ("name", "type", "blobs")

    def __init__(self, name: str, type_: str, blobs: list[Blob]):
        self.name = name
        self.type = type_
        self.blobs = blobs

    @classmethod
    def parse_v2(cls, buf: bytes) -> "Layer":
        """LayerParameter: name=1, type=2, blobs=7."""
        name = type_ = ""
        blobs = []
        for fnum, wtype, payload in _fields(buf):
            if fnum == 1:
                name = payload.decode("utf-8", "replace")
            elif fnum == 2:
                type_ = payload.decode("utf-8", "replace")
            elif fnum == 7:
                blobs.append(Blob.parse(payload))
        return cls(name, type_, blobs)

    @classmethod
    def parse_v1(cls, buf: bytes) -> "Layer":
        """V1LayerParameter: name=4, type=5 (enum), blobs=6."""
        name, type_ = "", ""
        blobs = []
        for fnum, wtype, payload in _fields(buf):
            if fnum == 4:
                name = payload.decode("utf-8", "replace")
            elif fnum == 5 and wtype == 0:
                type_ = _V1_TYPES.get(payload, f"V1:{payload}")
            elif fnum == 6:
                blobs.append(Blob.parse(payload))
        return cls(name, type_, blobs)


def parse_caffemodel(path: str) -> dict[str, Layer]:
    """Read a binary caffemodel (NetParameter: layers(V1)=2, layer=100)
    into name -> Layer. V2 entries win over V1 on name collision, matching
    the reference's map-build order (CaffeLoader.scala:49-60)."""
    with open(path, "rb") as f:
        buf = f.read()
    v1, v2 = {}, {}
    for fnum, wtype, payload in _fields(buf):
        if fnum == 2 and wtype == 2:
            layer = Layer.parse_v1(payload)
            v1[layer.name] = layer
        elif fnum == 100 and wtype == 2:
            layer = Layer.parse_v2(payload)
            v2[layer.name] = layer
    out = dict(v1)
    out.update(v2)
    return out


# ---------------------------------------------------------------------------
# prototxt (protobuf text format) — minimal recursive parser
# ---------------------------------------------------------------------------

def _tokenize(text: str) -> list[str]:
    tokens = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c in " \t\r\n":
            i += 1
        elif c == "#":
            while i < n and text[i] != "\n":
                i += 1
        elif c in "{}:":
            tokens.append(c)
            i += 1
        elif c in "\"'":
            j = i + 1
            while j < n and text[j] != c:
                j += 1 + (text[j] == "\\")
            tokens.append(text[i:j + 1])
            i = j + 1
        else:
            j = i
            while j < n and text[j] not in " \t\r\n{}:#":
                j += 1
            tokens.append(text[i:j])
            i = j
    return tokens


def _parse_value(tok: str):
    if tok and tok[0] in "\"'":
        return tok[1:-1]
    if tok in ("true", "false"):
        return tok == "true"
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        return tok     # enum identifier


def _parse_block(tokens: list[str], pos: int) -> tuple[dict, int]:
    """Parse `key: value` / `key { ... }` pairs until '}' or EOF. Repeated
    keys accumulate into lists."""
    out: dict = {}

    def put(k, v):
        if k in out:
            if not isinstance(out[k], list):
                out[k] = [out[k]]
            out[k].append(v)
        else:
            out[k] = v

    while pos < len(tokens) and tokens[pos] != "}":
        key = tokens[pos]
        pos += 1
        if pos < len(tokens) and tokens[pos] == ":":
            pos += 1
            if tokens[pos] == "{":      # message after colon (legal)
                sub, pos = _parse_block(tokens, pos + 1)
                pos += 1                # consume '}'
                put(key, sub)
            else:
                put(key, _parse_value(tokens[pos]))
                pos += 1
        elif pos < len(tokens) and tokens[pos] == "{":
            sub, pos = _parse_block(tokens, pos + 1)
            pos += 1
            put(key, sub)
        else:
            raise ValueError(f"prototxt parse error near token {pos}: "
                             f"{tokens[max(0, pos - 3):pos + 3]}")
    return out, pos


def parse_prototxt(path: str) -> dict:
    """Parse a .prototxt into nested dicts (repeated keys -> lists);
    net['layer'] / net['layers'] hold the layer definitions."""
    with open(path, "r", encoding="ascii", errors="replace") as f:
        tokens = _tokenize(f.read())
    net, _ = _parse_block(tokens, 0)
    return net


def _aslist(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


# ---------------------------------------------------------------------------
# the loader
# ---------------------------------------------------------------------------

class CaffeLoader:
    """Copy caffe parameters into a bigdl_tpu model by layer name
    (reference CaffeLoader.scala:38-162)."""

    def __init__(self, prototxt_path: str, model_path: str,
                 match_all: bool = True):
        self.prototxt_path = prototxt_path
        self.model_path = model_path
        self.match_all = match_all
        self._layers: dict[str, Layer] | None = None
        self._net_def: dict | None = None

    def _load(self):
        if self._layers is None:
            self._net_def = parse_prototxt(self.prototxt_path)
            logger.info("start loading caffe model from %s", self.model_path)
            self._layers = parse_caffemodel(self.model_path)
            logger.info("load caffe model done (%d layers with blobs: %s)",
                        len(self._layers),
                        [n for n, l in self._layers.items() if l.blobs])

    def _get_blob(self, name: str, ind: int) -> Blob | None:
        layer = self._layers.get(name)
        if layer is not None and len(layer.blobs) > ind:
            return layer.blobs[ind]
        return None

    def _copy_one(self, name: str, params: dict, key: str, ind: int):
        blob = self._get_blob(name, ind)
        if blob is None:
            return
        if key not in params:
            raise ValueError(f"{name} should contain {key}")
        target = params[key]
        if int(np.prod(target.shape)) != blob.data.size:
            raise ValueError(
                f"{key} element number is not equal between caffe layer and "
                f"bigdl module {name}, data shape in caffe is {blob.shape}, "
                f"while data shape in bigdl is {target.shape}")
        import jax.numpy as jnp
        params[key] = jnp.asarray(
            blob.data.reshape(target.shape), dtype=target.dtype)

    def copy_parameters(self, model):
        """(reference copyParameters, :132-151) — mutates the model's
        parameter table in place and returns the model."""
        self._load()
        if hasattr(model, "materialize"):
            model.materialize()
        table = model.get_parameters_table()
        for name, params in table.items():
            if not isinstance(params, dict) or \
                    ("weight" not in params and "bias" not in params):
                continue
            if name not in self._layers:
                if self.match_all:
                    raise ValueError(
                        f"module {name} cannot map a layer in caffe model")
                logger.info("%s uses initialized parameters", name)
                continue
            logger.info("load parameters for %s ...", name)
            self._copy_one(name, params, "weight", 0)
            self._copy_one(name, params, "bias", 1)
        # re-sync facades: container params reference the mutated child
        # dicts, so rebinding the root is enough to refresh views
        model.sync(model.params, model.state)
        return model


def load_caffe(model, def_path: str, model_path: str,
               match_all: bool = True):
    """(reference Module.loadCaffe / object CaffeLoader.load)"""
    return CaffeLoader(def_path, model_path, match_all).copy_parameters(model)

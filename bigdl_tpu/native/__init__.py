"""ctypes bridge to the native (C++) input-pipeline core.

The reference's native layer is C behind JNI (SURVEY §2.1); here the
compute path is XLA/jaxlib and the native seam that still earns its keep
is the data loader: ``native/btr_loader.cpp`` does threaded JPEG decode +
augment + NCHW batch assembly without the GIL. This module compiles it on
first use (g++ + libjpeg, cached as ``libbtr_loader.so`` next to this
file) and exposes ``decode_crop_batch``. Everything degrades gracefully:
``available()`` is False when the toolchain or libjpeg is missing and
callers fall back to the pure-Python pipeline.
"""
from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import threading

import numpy as np

__all__ = ["available", "decode_crop_batch", "lib_path"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, os.pardir, os.pardir, "native", "btr_loader.cpp")
_SO = os.path.join(_HERE, "libbtr_loader.so")
_lock = threading.Lock()
_lib = None
_tried = False


def lib_path() -> str:
    return _SO


def _build() -> bool:
    if not (shutil.which("g++") and os.path.exists(_SRC)):
        return False
    # compile to a private temp file and rename into place: several host
    # processes race to first-use on a fresh node, and rename is atomic —
    # nobody can CDLL a half-written library
    tmp = f"{_SO}.build.{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC,
           "-o", tmp, "-ljpeg", "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.btr_decode_batch.restype = ctypes.c_int
        lib.btr_decode_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),              # jpegs
            ctypes.POINTER(ctypes.c_size_t),              # sizes
            ctypes.c_int, ctypes.c_int, ctypes.c_int,     # n, crop_h, crop_w
            ctypes.c_int, ctypes.c_float,                 # random_crop, flip
            ctypes.POINTER(ctypes.c_float),               # mean_bgr
            ctypes.POINTER(ctypes.c_float),               # std_bgr
            ctypes.c_uint64, ctypes.c_int,                # seed, threads
            ctypes.POINTER(ctypes.c_float),               # out
            ctypes.POINTER(ctypes.c_int8),                # status
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def decode_crop_batch(jpegs, crop_h: int, crop_w: int, *,
                      random_crop: bool = False, flip_prob: float = 0.0,
                      mean_bgr=(0.0, 0.0, 0.0), std_bgr=(1.0, 1.0, 1.0),
                      seed: int = 0, num_threads: int = 8):
    """Decode a list of JPEG byte strings into an (N, 3, H, W) f32 BGR
    batch (scaled 1/255, normalized per channel). Returns (batch, status)
    where status[i] != 0 marks a corrupt record (its slot is zeros)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native loader unavailable (no g++/libjpeg?)")
    n = len(jpegs)
    out = np.empty((n, 3, crop_h, crop_w), np.float32)
    status = np.empty((n,), np.int8)
    arr = (ctypes.c_char_p * n)(*jpegs)
    sizes = (ctypes.c_size_t * n)(*[len(j) for j in jpegs])
    mean = (ctypes.c_float * 3)(*[float(v) for v in mean_bgr])
    std = (ctypes.c_float * 3)(*[float(v) for v in std_bgr])
    lib.btr_decode_batch(
        ctypes.cast(arr, ctypes.POINTER(ctypes.c_char_p)), sizes, n,
        crop_h, crop_w, int(random_crop), float(flip_prob), mean, std,
        int(seed) & (2 ** 64 - 1), num_threads,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        status.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)))
    return out, status

"""ctypes bridge to the native (C++) input-pipeline core.

The reference's native layer is C behind JNI (SURVEY §2.1); here the
compute path is XLA/jaxlib and the native seam that still earns its keep
is the data loader: ``native/btr_loader.cpp`` does threaded JPEG decode +
augment + NCHW batch assembly without the GIL. This module compiles it on
first use (g++ + libjpeg, cached as ``libbtr_loader.so`` next to this
file) and exposes ``decode_crop_batch``. Everything degrades gracefully:
``available()`` is False when the toolchain or libjpeg is missing and
callers fall back to the pure-Python pipeline.
"""
from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import threading

import numpy as np

__all__ = ["available", "decode_crop_batch", "decode_crop_batch_u8",
           "jpeg_dims", "crop_batch_from_raw", "record_seeds",
           "default_threads", "lib_path"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, os.pardir, os.pardir, "native", "btr_loader.cpp")
_SO = os.path.join(_HERE, "libbtr_loader.so")
_lock = threading.Lock()
_lib = None
_tried = False


def lib_path() -> str:
    return _SO


def _build() -> bool:
    if not (shutil.which("g++") and os.path.exists(_SRC)):
        return False
    # compile to a private temp file and rename into place: several host
    # processes race to first-use on a fresh node, and rename is atomic —
    # nobody can CDLL a half-written library
    tmp = f"{_SO}.build.{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC,
           "-o", tmp, "-ljpeg", "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.btr_decode_batch.restype = ctypes.c_int
        lib.btr_decode_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),              # jpegs
            ctypes.POINTER(ctypes.c_size_t),              # sizes
            ctypes.c_int, ctypes.c_int, ctypes.c_int,     # n, crop_h, crop_w
            ctypes.c_int, ctypes.c_float,                 # random_crop, flip
            ctypes.POINTER(ctypes.c_float),               # mean_bgr
            ctypes.POINTER(ctypes.c_float),               # std_bgr
            ctypes.c_uint64, ctypes.c_int,                # seed, threads
            ctypes.POINTER(ctypes.c_float),               # out
            ctypes.POINTER(ctypes.c_int8),                # status
        ]
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.btr_decode_batch_u8.restype = ctypes.c_int
        lib.btr_decode_batch_u8.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),              # jpegs
            ctypes.POINTER(ctypes.c_size_t),              # sizes
            ctypes.c_int, ctypes.c_int, ctypes.c_int,     # n, crop_h, crop_w
            ctypes.c_int, ctypes.c_float, ctypes.c_int,   # rand, flip, fast
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,  # seeds, threads
            u8p,                                          # out (n,h,w,3)
            ctypes.POINTER(u8p),                          # full_outs | None
            ctypes.POINTER(ctypes.c_int8),                # status
        ]
        lib.btr_jpeg_dims.restype = None
        lib.btr_jpeg_dims.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_size_t), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ]
        lib.btr_crop_batch_from_raw.restype = None
        lib.btr_crop_batch_from_raw.argtypes = [
            ctypes.POINTER(u8p),                          # raws
            ctypes.POINTER(ctypes.c_int32),               # hs
            ctypes.POINTER(ctypes.c_int32),               # ws
            ctypes.c_int, ctypes.c_int, ctypes.c_int,     # n, crop_h, crop_w
            ctypes.c_int, ctypes.c_float,                 # random, flip
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,  # seeds, threads
            u8p,                                          # out
        ]
        _lib = lib
        return _lib


def default_threads() -> int:
    """Decode threads sized to the host (the reference sizes its decode
    pool to the executor's core count, Engine.coreNumber)."""
    return max(2, os.cpu_count() or 1)


def record_seeds(seed: int, indices) -> np.ndarray:
    """Per-record augment-stream seeds: the same (seed, index) mix the
    in-C scheme used, hoisted to Python so batches split across the
    cache and decode paths keep the draws of an unsplit batch."""
    idx = np.asarray(indices, np.uint64) + np.uint64(1)
    return (np.uint64(seed & (2 ** 64 - 1))
            ^ (np.uint64(0xd1342543de82ef95) * idx))


def available() -> bool:
    return _load() is not None


def decode_crop_batch(jpegs, crop_h: int, crop_w: int, *,
                      random_crop: bool = False, flip_prob: float = 0.0,
                      mean_bgr=(0.0, 0.0, 0.0), std_bgr=(1.0, 1.0, 1.0),
                      seed: int = 0, num_threads: int = 8):
    """Decode a list of JPEG byte strings into an (N, 3, H, W) f32 BGR
    batch (scaled 1/255, normalized per channel). Returns (batch, status)
    where status[i] != 0 marks a corrupt record (its slot is zeros)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native loader unavailable (no g++/libjpeg?)")
    n = len(jpegs)
    out = np.empty((n, 3, crop_h, crop_w), np.float32)
    status = np.empty((n,), np.int8)
    arr = (ctypes.c_char_p * n)(*jpegs)
    sizes = (ctypes.c_size_t * n)(*[len(j) for j in jpegs])
    mean = (ctypes.c_float * 3)(*[float(v) for v in mean_bgr])
    std = (ctypes.c_float * 3)(*[float(v) for v in std_bgr])
    lib.btr_decode_batch(
        ctypes.cast(arr, ctypes.POINTER(ctypes.c_char_p)), sizes, n,
        crop_h, crop_w, int(random_crop), float(flip_prob), mean, std,
        int(seed) & (2 ** 64 - 1), num_threads,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        status.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)))
    return out, status


def decode_crop_batch_u8(jpegs, crop_h: int, crop_w: int, *,
                         random_crop: bool = False, flip_prob: float = 0.0,
                         fast_dct: bool = False, seed: int = 0,
                         num_threads: int | None = None, full_outs=None):
    """Decode JPEG byte strings into an (N, H, W, 3) uint8 RGB batch —
    crop + flip only; normalize/BGR/NCHW runs on-device
    (``dataset.image.device_transform``). The same (seed, index) splitmix
    stream as ``decode_crop_batch`` cuts identical windows.

    ``full_outs``: optional list (len N) whose non-None entries are
    C-contiguous uint8 (h, w, 3) arrays (sized via ``jpeg_dims``) that
    receive the FULL decoded image — the decoded-RAM-cache fill path.

    ``seed`` may be an int (expanded via ``record_seeds`` over 0..N-1) or
    a length-N uint64 array of explicit per-record seeds.
    Returns (batch, status); status[i] != 0 marks a corrupt record."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native loader unavailable (no g++/libjpeg?)")
    n = len(jpegs)
    out = np.empty((n, crop_h, crop_w, 3), np.uint8)
    status = np.empty((n,), np.int8)
    arr = (ctypes.c_char_p * n)(*jpegs)
    sizes = (ctypes.c_size_t * n)(*[len(j) for j in jpegs])
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    seeds = (record_seeds(seed, range(n)) if np.isscalar(seed)
             or isinstance(seed, int) else
             np.ascontiguousarray(seed, np.uint64))
    fo = None
    if full_outs is not None:
        fo = (u8p * n)(*[
            (a.ctypes.data_as(u8p) if a is not None else
             ctypes.cast(None, u8p)) for a in full_outs])
    lib.btr_decode_batch_u8(
        ctypes.cast(arr, ctypes.POINTER(ctypes.c_char_p)), sizes, n,
        crop_h, crop_w, int(random_crop), float(flip_prob), int(fast_dct),
        seeds.ctypes.data_as(u64p),
        num_threads if num_threads else default_threads(),
        out.ctypes.data_as(u8p),
        ctypes.cast(fo, ctypes.POINTER(u8p)) if fo is not None
        else ctypes.cast(None, ctypes.POINTER(u8p)),
        status.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)))
    return out, status


def jpeg_dims(jpegs):
    """(heights, widths) int32 arrays from JPEG headers only; corrupt
    records report (0, 0)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native loader unavailable (no g++/libjpeg?)")
    n = len(jpegs)
    hs = np.empty((n,), np.int32)
    ws = np.empty((n,), np.int32)
    arr = (ctypes.c_char_p * n)(*jpegs)
    sizes = (ctypes.c_size_t * n)(*[len(j) for j in jpegs])
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.btr_jpeg_dims(ctypes.cast(arr, ctypes.POINTER(ctypes.c_char_p)),
                      sizes, n, hs.ctypes.data_as(i32p),
                      ws.ctypes.data_as(i32p))
    return hs, ws


def crop_batch_from_raw(raws, crop_h: int, crop_w: int, *,
                        random_crop: bool = False, flip_prob: float = 0.0,
                        seed: int = 0, num_threads: int | None = None):
    """Crop/flip an (N, H, W, 3)-per-item list of C-contiguous uint8
    images (the decoded-RAM cache) into an (N, crop_h, crop_w, 3) batch —
    the post-warm path: no JPEG decode at all. ``seed`` as in
    ``decode_crop_batch_u8``."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native loader unavailable (no g++/libjpeg?)")
    n = len(raws)
    out = np.empty((n, crop_h, crop_w, 3), np.uint8)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    seeds = (record_seeds(seed, range(n)) if np.isscalar(seed)
             or isinstance(seed, int) else
             np.ascontiguousarray(seed, np.uint64))
    ptrs = (u8p * n)(*[a.ctypes.data_as(u8p) for a in raws])
    hs = (ctypes.c_int32 * n)(*[a.shape[0] for a in raws])
    ws = (ctypes.c_int32 * n)(*[a.shape[1] for a in raws])
    lib.btr_crop_batch_from_raw(
        ctypes.cast(ptrs, ctypes.POINTER(u8p)), hs, ws, n, crop_h, crop_w,
        int(random_crop), float(flip_prob), seeds.ctypes.data_as(u64p),
        num_threads if num_threads else default_threads(),
        out.ctypes.data_as(u8p))
    return out

"""Recurrent layers.

Reference parity: Recurrent container (nn/Recurrent.scala, 240 LoC — unrolls
a Cell over time, cloning cells per step with shared parameter storage),
Cell (nn/Cell.scala:34-49), RnnCell (nn/RNN.scala:36-48), LSTM
(nn/LSTM.scala:47-135), GRU (nn/GRU.scala), TimeDistributed.

TPU-first: the reference's per-timestep cell clones become a single
``jax.lax.scan`` over the time axis — one compiled cell body, parameters
naturally shared, no Python-loop unrolling in the compiled graph. Gates are
fused into one GEMM per step (the reference composes the same math from
Linear(in, 4*hidden) + split, nn/LSTM.scala:47-135), which is exactly the
layout the MXU wants. Masking support (``seq_lengths``) replaces the
reference's padded-batch semantics (SURVEY §5.7).

Layout: (N, T, feature) batch-first, like the reference's batched Recurrent.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Module, Container, _fold
from bigdl_tpu.tensor import default_dtype

__all__ = ["Cell", "RnnCell", "RNN", "LSTM", "GRU", "Recurrent",
           "TimeDistributed", "BiRecurrent"]

_ACT = {"tanh": jnp.tanh, "relu": jax.nn.relu,
        "sigmoid": jax.nn.sigmoid}


class Cell(Module):
    """Abstract recurrent cell (reference nn/Cell.scala).

    ``apply(params, state, (x_t, hidden)) -> ((out_t, new_hidden), state)``.
    ``hid_shape(batch)`` declares the hidden pytree shapes (reference
    ``hidResize``).
    """

    hidden_size: int

    def hid_shape(self, batch: int):
        raise NotImplementedError

    def init_hidden(self, batch: int, dtype=None):
        """Zero hidden state matching ``hid_shape`` (handles nested
        tuples like LSTM's ((B,H),(B,H)): a leaf is a tuple of ints,
        not any tuple)."""
        return jax.tree.map(
            lambda s: jnp.zeros(s, dtype or default_dtype()),
            self.hid_shape(batch),
            is_leaf=lambda v: isinstance(v, tuple) and all(
                isinstance(e, int) for e in v))


class RnnCell(Cell):
    """Elman cell: act(W_i x + W_h h + b) (reference nn/RNN.scala:36-48)."""

    def __init__(self, input_size: int, hidden_size: int,
                 activation: str = "tanh"):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation  # name, so the module pickles

    @property
    def act(self):
        return _ACT[self.activation]

    def hid_shape(self, batch):
        return (batch, self.hidden_size)

    def init(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        stdv = 1.0 / np.sqrt(self.hidden_size)
        return {
            "i2h": init_mod.uniform_reset(k1, (self.input_size,
                                               self.hidden_size), stdv),
            "h2h": init_mod.uniform_reset(k2, (self.hidden_size,
                                               self.hidden_size), stdv),
            "bias": init_mod.uniform_reset(k3, (self.hidden_size,), stdv),
        }

    def apply(self, params, state, x, *, training=False, rng=None):
        xt, h = x
        h_new = self.act(xt @ params["i2h"] + h @ params["h2h"]
                         + params["bias"])
        return (h_new, h_new), state


class LSTM(Cell):
    """LSTM cell with fused 4-gate GEMM (reference nn/LSTM.scala:47-135 —
    gate order i, g(candidate), f, o following the reference's graph)."""

    def __init__(self, input_size: int, hidden_size: int):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size

    def hid_shape(self, batch):
        return ((batch, self.hidden_size), (batch, self.hidden_size))

    def init(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        stdv = 1.0 / np.sqrt(self.hidden_size)
        H = self.hidden_size
        return {
            "i2h": init_mod.uniform_reset(k1, (self.input_size, 4 * H), stdv),
            "h2h": init_mod.uniform_reset(k2, (H, 4 * H), stdv),
            "bias": init_mod.uniform_reset(k3, (4 * H,), stdv),
        }

    def apply(self, params, state, x, *, training=False, rng=None):
        xt, (h, c) = x
        H = self.hidden_size
        gates = xt @ params["i2h"] + h @ params["h2h"] + params["bias"]
        i = jax.nn.sigmoid(gates[:, 0 * H:1 * H])
        g = jnp.tanh(gates[:, 1 * H:2 * H])
        f = jax.nn.sigmoid(gates[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(gates[:, 3 * H:4 * H])
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return (h_new, (h_new, c_new)), state


class GRU(Cell):
    """GRU cell (reference nn/GRU.scala; gates r, z then candidate)."""

    def __init__(self, input_size: int, hidden_size: int):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size

    def hid_shape(self, batch):
        return (batch, self.hidden_size)

    def init(self, rng):
        ks = jax.random.split(rng, 6)
        stdv = 1.0 / np.sqrt(self.hidden_size)
        H, I = self.hidden_size, self.input_size
        u = init_mod.uniform_reset
        return {
            "i2h_rz": u(ks[0], (I, 2 * H), stdv),
            "h2h_rz": u(ks[1], (H, 2 * H), stdv),
            "bias_rz": u(ks[2], (2 * H,), stdv),
            "i2h_c": u(ks[3], (I, H), stdv),
            "h2h_c": u(ks[4], (H, H), stdv),
            "bias_c": u(ks[5], (H,), stdv),
        }

    def apply(self, params, state, x, *, training=False, rng=None):
        xt, h = x
        H = self.hidden_size
        rz = jax.nn.sigmoid(xt @ params["i2h_rz"] + h @ params["h2h_rz"]
                            + params["bias_rz"])
        r, z = rz[:, :H], rz[:, H:]
        cand = jnp.tanh(xt @ params["i2h_c"] + (r * h) @ params["h2h_c"]
                        + params["bias_c"])
        h_new = (1 - z) * cand + z * h
        return (h_new, h_new), state


class Recurrent(Container):
    """Scan a Cell over the time axis (reference nn/Recurrent.scala:60-107).

    Input (N, T, I) -> output (N, T, H). ``seq_lengths`` (optional per-batch
    int array, passed as a table input ``(x, lengths)``) freezes the hidden
    state past each sequence's end — the masked-scan equivalent of the
    reference's padded batching.
    """

    def __init__(self, cell: Cell | None = None):
        super().__init__()
        if cell is not None:
            self.add(cell)

    @property
    def cell(self) -> Cell:
        return self.modules[0]

    def apply(self, params, state, x, *, training=False, rng=None):
        lengths = None
        if isinstance(x, (tuple, list)):
            x, lengths = x
        cell = self.cell
        h0 = cell.init_hidden(x.shape[0], x.dtype)
        xs = jnp.swapaxes(x, 0, 1)  # (T, N, I) for scan
        p0, s0 = params["0"], state["0"]

        def step(carry, inp):
            h, t = carry
            (out, h_new), _ = cell.apply(p0, s0, (inp, h), training=training,
                                         rng=rng)
            if lengths is not None:
                active = (t < lengths)[:, None]
                h_new = jax.tree.map(
                    lambda new, old: jnp.where(active, new, old), h_new, h)
                out = jnp.where(active, out, jnp.zeros_like(out))
            return (h_new, t + 1), out

        (_, _), outs = jax.lax.scan(step, (h0, jnp.int32(0)), xs)
        return jnp.swapaxes(outs, 0, 1), state


class BiRecurrent(Container):
    """Bidirectional recurrent wrapper: forward + time-reversed cell, outputs
    merged (concat by default) — reference nn/BiRecurrent.scala."""

    def __init__(self, fwd_cell: Cell, bwd_cell: Cell, merge: str = "concat"):
        super().__init__(Recurrent(fwd_cell), Recurrent(bwd_cell))
        self.merge = merge

    @staticmethod
    def _reverse_padded(x, lengths):
        """Reverse each sequence within its own length, keeping padding at
        the tail (so the backward pass starts at each sequence's true end)."""
        T = x.shape[1]
        t = jnp.arange(T)[None, :]
        rev_idx = jnp.where(t < lengths[:, None],
                            lengths[:, None] - 1 - t, t)
        return jnp.take_along_axis(
            x, rev_idx[..., None].astype(jnp.int32), axis=1)

    def apply(self, params, state, x, *, training=False, rng=None):
        lengths = None
        if isinstance(x, (tuple, list)):
            x, lengths = x
        fwd_in = x if lengths is None else (x, lengths)
        fwd, _ = self.modules[0].apply(params["0"], state["0"], fwd_in,
                                       training=training, rng=_fold(rng, 0))
        if lengths is None:
            rev_in = jnp.flip(x, axis=1)
        else:
            rev_in = (self._reverse_padded(x, lengths), lengths)
        bwd, _ = self.modules[1].apply(params["1"], state["1"], rev_in,
                                       training=training, rng=_fold(rng, 1))
        if lengths is None:
            bwd = jnp.flip(bwd, axis=1)
        else:
            bwd = self._reverse_padded(bwd, lengths)
        if self.merge == "concat":
            return jnp.concatenate([fwd, bwd], axis=-1), state
        return fwd + bwd, state


class TimeDistributed(Container):
    """Apply a module independently at each timestep
    (reference nn/TimeDistributed.scala). Implemented by folding time into
    the batch dim — one big fused op instead of T small ones."""

    def __init__(self, module: Module):
        super().__init__(module)

    def apply(self, params, state, x, *, training=False, rng=None):
        N, T = x.shape[0], x.shape[1]
        flat = x.reshape((N * T,) + x.shape[2:])
        y, s = self.modules[0].apply(params["0"], state["0"], flat,
                                     training=training, rng=rng)
        return y.reshape((N, T) + y.shape[1:]), {"0": s}


# the reference file nn/RNN.scala names its cell class RnnCell; RNN is the
# name users reach for
RNN = RnnCell

"""Normalization layers.

Reference parity: BatchNormalization (nn/BatchNormalization.scala:30-104 —
eps=1e-5, momentum=0.1, optional affine, runningMean/runningVar updated in
train and used in eval), SpatialBatchNormalization, SpatialCrossMapLRN,
SpatialContrastiveNormalization, SpatialDivisiveNormalization,
SpatialSubtractiveNormalization, Normalize.

BN under data parallelism: the reference's statistics are per-replica
(per-core model clone, SURVEY §7 "hard parts"). Here statistics are computed
over the device-local batch by default; pass ``axis_name`` to sync across a
mesh axis with ``lax.pmean`` (the idiomatic TPU upgrade).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.containers import Sequential as _Sequential
from bigdl_tpu.nn.module import Module
from bigdl_tpu.ops import pow_neg_beta as _pow_neg_beta
from bigdl_tpu.tensor import default_dtype

__all__ = ["BatchNormalization", "SpatialBatchNormalization",
           "SpatialCrossMapLRN", "ReLUCrossMapLRN", "Normalize", "LayerNorm",
           "SpatialDivisiveNormalization", "SpatialSubtractiveNormalization",
           "SpatialContrastiveNormalization"]


class BatchNormalization(Module):
    """1-D batch norm over (N, C) (reference nn/BatchNormalization.scala)."""

    n_dim = 2
    _one_pass_stats = False   # exact two-pass variance (see apply)

    def __init__(self, n_output: int, eps: float = 1e-5,
                 momentum: float = 0.1, affine: bool = True,
                 axis_name: str | None = None):
        super().__init__()
        self.n_output = n_output
        self.eps, self.momentum, self.affine = eps, momentum, affine
        self.axis_name = axis_name

    def init(self, rng):
        if not self.affine:
            return {}
        # reference reset(): weight ~ U(0,1), bias = 0
        return {"weight": jax.random.uniform(rng, (self.n_output,),
                                             default_dtype()),
                "bias": jnp.zeros((self.n_output,), default_dtype())}

    def init_state(self):
        return {"running_mean": jnp.zeros((self.n_output,), default_dtype()),
                "running_var": jnp.ones((self.n_output,), default_dtype())}

    def _reduce_axes(self, x):
        return tuple(i for i in range(x.ndim) if i != 1)

    def apply(self, params, state, x, *, training=False, rng=None):
        squeeze = x.ndim == self.n_dim - 1  # unbatched input
        if squeeze:
            x = x[None]
        axes = self._reduce_axes(x)
        # statistics always accumulate in >= f32 even when activations flow
        # bf16 (the reference's MKL path is f32 throughout); running stats
        # stay at param precision
        stat_dtype = jnp.promote_types(x.dtype, jnp.float32)
        if training:
            xs = x.astype(stat_dtype)
            mean = jnp.mean(xs, axis=axes)
            if self._one_pass_stats:
                # one fused pass: E[x] and E[x^2] reduce together, where
                # jnp.var's (x - mean)^2 form needs a SECOND sequential
                # read of the activation after the mean lands — profiled
                # at 33% of a ResNet-50 step (98 convert_reduce fusions,
                # 18.8 ms; docs/PERF.md round 3). Spatial variant only:
                # conv outputs are near-zero-mean, so the f32
                # cancellation the two-pass form guards against is
                # absent; the generic (N, C) module keeps the exact form
                # (raw feature columns can have mean/std ratios where
                # E[x^2]-E[x]^2 rounds to zero).
                mean2 = jnp.mean(jnp.square(xs), axis=axes)
                if self.axis_name is not None:
                    # pmean of per-device moments is EXACT for E[x]/E[x^2]
                    # (it was only approximate for per-device variances)
                    mean = jax.lax.pmean(mean, self.axis_name)
                    mean2 = jax.lax.pmean(mean2, self.axis_name)
                var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
            else:
                var = jnp.var(xs, axis=axes)
                if self.axis_name is not None:
                    mean = jax.lax.pmean(mean, self.axis_name)
                    var = jax.lax.pmean(var, self.axis_name)
            n = np.prod([x.shape[a] for a in axes])
            if self.axis_name is not None and self._one_pass_stats:
                # the fused form's variance is GLOBAL over all devices'
                # samples; Bessel must use the global count too
                n = n * jax.lax.psum(1, self.axis_name)
            unbiased = var * n / jnp.maximum(n - 1, 1)
            m = self.momentum
            new_state = {
                "running_mean": (1 - m) * state["running_mean"] + m * mean,
                "running_var": (1 - m) * state["running_var"] + m * unbiased,
            }
        else:
            mean, var = state["running_mean"], state["running_var"]
            new_state = state
        shape = [1] * x.ndim
        shape[1] = self.n_output
        scale = jax.lax.rsqrt(var.astype(stat_dtype) + self.eps)
        if self.affine:
            scale = scale * params["weight"].astype(stat_dtype)
        shift = -mean.astype(stat_dtype) * scale
        if self.affine:
            shift = shift + params["bias"].astype(stat_dtype)
        # one fused multiply-add; f32 in registers, output in the activation
        # dtype (XLA fuses the whole elementwise chain, nothing f32 hits HBM)
        y = (x.astype(stat_dtype) * scale.reshape(shape)
             + shift.reshape(shape)).astype(x.dtype)
        if squeeze:
            y = y[0]
        return y, new_state

    def __repr__(self):
        return f"{type(self).__name__}({self.n_output})"


class SpatialBatchNormalization(BatchNormalization):
    """4-D (N, C, H, W) wrapper (reference
    nn/SpatialBatchNormalization.scala).

    ``one_pass_stats=True`` (default) fuses E[x]/E[x^2] into one
    activation read — right for near-zero-mean conv outputs. A stem BN
    fed raw, non-centered inputs can lose precision to E[x^2]-E[x]^2
    cancellation in f32; pass ``one_pass_stats=False`` there to get the
    exact two-pass variance of the base class."""

    n_dim = 4

    def __init__(self, *args, one_pass_stats: bool = True, **kwargs):
        super().__init__(*args, **kwargs)
        self._one_pass_stats = one_pass_stats


def _lrn_window_sum(v, size, adjoint=False):
    """Sum over a size-wide window along the channel axis (NCHW axis 1).

    ``adjoint`` transposes the (asymmetric, for even sizes) padding: the
    forward window at j covers [j-half, j+size-1-half], so the backward
    sum over {j : i in win(j)} covers [i-(size-1-half), i+half].
    """
    half = (size - 1) // 2
    lo, hi = (size - 1 - half, half) if adjoint else (half, size - 1 - half)
    return jax.lax.reduce_window(
        v, 0.0, jax.lax.add,
        window_dimensions=(1, size, 1, 1),
        window_strides=(1, 1, 1, 1),
        padding=((0, 0), (lo, hi), (0, 0), (0, 0)))


def _lrn_impl(x, size, alpha, beta, k):
    f32 = jnp.promote_types(x.dtype, jnp.float32)
    s = k + (alpha / size) * _lrn_window_sum(jnp.square(x.astype(f32)), size)
    return (x.astype(f32) * _pow_neg_beta(s, beta)).astype(x.dtype)


def _lrn_fwd(x, size, alpha, beta, k):
    f32 = jnp.promote_types(x.dtype, jnp.float32)
    s = k + (alpha / size) * _lrn_window_sum(jnp.square(x.astype(f32)), size)
    sb = _pow_neg_beta(s, beta)
    y = (x.astype(f32) * sb).astype(x.dtype)
    # residuals at activation precision: autodiff through the naive graph
    # keeps ~5 full-size f32 buffers live; this saves x plus two factors
    # in the activation dtype
    return y, (x, sb.astype(x.dtype), (sb / s).astype(x.dtype))


def _lrn_bwd(size, alpha, beta, k, res, g):
    # dx_i = g_i*s_i^-b - (2ab/n) * x_i * sum_win(g_j * x_j * s_j^-(b+1))
    x, sb, sb1 = res
    f32 = jnp.promote_types(x.dtype, jnp.float32)
    acc = _lrn_window_sum(g.astype(f32) * x.astype(f32) * sb1.astype(f32),
                          size, adjoint=True)
    dx = g.astype(f32) * sb.astype(f32) \
        - (2.0 * alpha * beta / size) * x.astype(f32) * acc
    return (dx.astype(x.dtype),)


_lrn = jax.custom_vjp(_lrn_impl, nondiff_argnums=(1, 2, 3, 4))
_lrn.defvjp(_lrn_fwd, _lrn_bwd)


class SpatialCrossMapLRN(Module):
    """AlexNet/Inception local response normalization across channels
    (reference nn/SpatialCrossMapLRN.scala, threaded; here one
    reduce_window over the channel axis with an analytic custom VJP).

    y = x / (k + alpha/size * sum_{local} x^2)^beta

    The hand-written backward matters on TPU: autodiff of the naive graph
    materializes ~5 full-size f32 tensors per LRN (profiled №1 HBM consumer
    of an Inception train step); the analytic form needs one window-sum and
    keeps residuals in the activation dtype.
    """

    def __init__(self, size: int = 5, alpha: float = 1.0,
                 beta: float = 0.75, k: float = 1.0):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def apply(self, params, state, x, *, training=False, rng=None):
        from bigdl_tpu.ops.pallas import lrn as plrn
        if plrn.lrn_supported(x):
            # fused single-HBM-pass kernel (ops/pallas/lrn.py) — profiled
            # ~4x less LRN traffic than the reduce_window path below
            y = plrn.lrn(x, self.size, self.alpha, self.beta, self.k)
        else:
            y = _lrn(x, self.size, self.alpha, self.beta, self.k)
        return y, state


class ReLUCrossMapLRN(_Sequential):
    """TPU fusion of ReLU -> SpatialCrossMapLRN in ONE HBM pass.

    A Sequential of the two child modules — child names, the (name-keyed)
    parameter table, and .t7 export stay reference-faithful, and the
    fused forward is equivalent to running the children in order (both
    are parameter-free). Note: introducing the wrapper into a model DOES
    shift that model's index-keyed Sequential pytree (sibling indices
    change), like any structural edit — raw ``save``d checkpoints from
    before the edit don't line up, name-based flows (Caffe/Torch import,
    parameter table) do. On TPU the Pallas kernel applies the ReLU in
    VMEM, eliminating
    the standalone elementwise read+write of the activation (profiled on
    Inception-v1: the conv2/relu_3x3 pass alone moves ~620 MB/step at
    batch 256); elsewhere the Sequential fallback runs the children.
    """

    def __init__(self, relu, lrn):
        super().__init__(relu, lrn)

    def apply(self, params, state, x, *, training=False, rng=None):
        from bigdl_tpu.ops.pallas import lrn as plrn
        m = self.modules[1]
        if plrn.lrn_supported(x):
            return plrn.lrn(x, m.size, m.alpha, m.beta, m.k,
                            relu=True), state
        return super().apply(params, state, x, training=training, rng=rng)


class Normalize(Module):
    """Lp-normalize over the feature axis (reference nn/Normalize.scala)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10):
        super().__init__()
        self.p, self.eps = p, eps

    def apply(self, params, state, x, *, training=False, rng=None):
        if np.isinf(self.p):
            n = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
        else:
            n = jnp.power(jnp.sum(jnp.power(jnp.abs(x), self.p), axis=-1,
                                  keepdims=True), 1.0 / self.p)
        return x / jnp.maximum(n, self.eps), state


def _gaussian_kernel(kernel_size: int) -> np.ndarray:
    """Default 2-D gaussian used by the reference's subtractive/divisive
    normalization (Torch image.gaussian semantics)."""
    sigma = 0.25 * kernel_size  # torch default sigma=0.25 relative
    ax = np.arange(kernel_size) - (kernel_size - 1) / 2.0
    g = np.exp(-(ax ** 2) / (2 * sigma ** 2))
    k = np.outer(g, g)
    return (k / k.sum()).astype(np.float32)


class SpatialSubtractiveNormalization(Module):
    """Subtract local weighted mean (reference
    nn/SpatialSubtractiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None):
        super().__init__()
        self.n_input_plane = n_input_plane
        k = np.asarray(kernel, np.float32) if kernel is not None \
            else _gaussian_kernel(9)
        self.kernel = k / (k.sum() * n_input_plane)

    def _local_mean(self, x):
        kh, kw = self.kernel.shape
        w = jnp.asarray(self.kernel)[None, None].repeat(
            self.n_input_plane, axis=1)
        mean = jax.lax.conv_general_dilated(
            x, w.astype(x.dtype), (1, 1),
            padding=[((kh - 1) // 2, kh // 2), ((kw - 1) // 2, kw // 2)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # divide by local window mass (border correction, as Torch does via
        # convolving a ones image; the kernel is already normalized by
        # ksum * n_input_plane, so interior coef == 1 — dividing by
        # coef * n again would shrink the mean n-fold, caught by
        # test_subtractive_normalization_zeroes_constant_input)
        ones = jnp.ones((1, self.n_input_plane) + x.shape[2:], x.dtype)
        coef = jax.lax.conv_general_dilated(
            ones, w.astype(x.dtype), (1, 1),
            padding=[((kh - 1) // 2, kh // 2), ((kw - 1) // 2, kw // 2)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return mean / coef

    def apply(self, params, state, x, *, training=False, rng=None):
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        y = x - self._local_mean(x)
        if squeeze:
            y = y[0]
        return y, state


class SpatialDivisiveNormalization(SpatialSubtractiveNormalization):
    """Divide by local weighted std (reference
    nn/SpatialDivisiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4):
        super().__init__(n_input_plane, kernel)
        self.threshold, self.thresval = threshold, thresval

    def apply(self, params, state, x, *, training=False, rng=None):
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        local_std = jnp.sqrt(jnp.maximum(self._local_mean(jnp.square(x)),
                                         0.0))
        mean_std = jnp.mean(local_std, axis=(2, 3), keepdims=True)
        den = jnp.maximum(local_std, mean_std)
        den = jnp.where(den < self.threshold, self.thresval, den)
        y = x / den
        if squeeze:
            y = y[0]
        return y, state


class SpatialContrastiveNormalization(Module):
    """Subtractive then divisive normalization (reference
    nn/SpatialContrastiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4):
        super().__init__()
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.div = SpatialDivisiveNormalization(n_input_plane, kernel,
                                                threshold, thresval)

    def apply(self, params, state, x, *, training=False, rng=None):
        y, _ = self.sub.apply({}, {}, x, training=training)
        y, _ = self.div.apply({}, {}, y, training=training)
        return y, state


class LayerNorm(Module):
    """Per-sample normalization over the trailing feature axis.

    Not in the reference (its era normalized with BatchNorm only); carried
    as the TPU-era extension the transformer stack (nn/attention.py,
    models/transformer) requires. Statistics in f32 like BatchNorm."""

    def __init__(self, n_output: int, eps: float = 1e-5,
                 affine: bool = True):
        super().__init__()
        self.n_output, self.eps, self.affine = n_output, eps, affine

    def init(self, rng):
        if not self.affine:
            return {}
        return {"weight": jnp.ones((self.n_output,), default_dtype()),
                "bias": jnp.zeros((self.n_output,), default_dtype())}

    def apply(self, params, state, x, *, training=False, rng=None):
        f32 = jnp.promote_types(x.dtype, jnp.float32)
        xs = x.astype(f32)
        mean = jnp.mean(xs, axis=-1, keepdims=True)
        var = jnp.var(xs, axis=-1, keepdims=True)
        y = (xs - mean) * jax.lax.rsqrt(var + self.eps)
        if self.affine:
            y = y * params["weight"].astype(f32) \
                + params["bias"].astype(f32)
        return y.astype(x.dtype), state

"""Pooling layers (NCHW).

Reference parity: SpatialMaxPooling (nn/SpatialMaxPooling.scala, 275 LoC,
threaded), SpatialAveragePooling (threaded), RoiPooling (Fast-RCNN support).
TPU-first: ``lax.reduce_window`` — XLA fuses and parallelizes; ceil_mode is
reproduced by asymmetric extra padding.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import Module

__all__ = ["SpatialMaxPooling", "SpatialAveragePooling", "RoiPooling"]


def _pool_out(size, k, d, pad, ceil_mode):
    if ceil_mode:
        return int(np.ceil((size + 2 * pad - k) / d)) + 1
    return int(np.floor((size + 2 * pad - k) / d)) + 1


class _Pool2d(Module):
    def __init__(self, kw, kh, dw=None, dh=None, pad_w=0, pad_h=0):
        super().__init__()
        self.kw, self.kh = kw, kh
        self.dw, self.dh = dw or kw, dh or kh
        self.pw, self.ph = pad_w, pad_h
        self.ceil_mode = False

    def ceil(self):
        """(reference SpatialMaxPooling.ceil())"""
        self.ceil_mode = True
        return self

    def floor(self):
        self.ceil_mode = False
        return self

    def _padding(self, h, w):
        """(lo, hi) padding per spatial dim, extending for ceil_mode."""
        oh = _pool_out(h, self.kh, self.dh, self.ph, self.ceil_mode)
        ow = _pool_out(w, self.kw, self.dw, self.pw, self.ceil_mode)
        # Torch clamps so the last window starts inside the (padded) input
        if self.ph > 0 or self.pw > 0:
            if (oh - 1) * self.dh >= h + self.ph:
                oh -= 1
            if (ow - 1) * self.dw >= w + self.pw:
                ow -= 1
        hi_h = max((oh - 1) * self.dh + self.kh - h - self.ph, self.ph)
        hi_w = max((ow - 1) * self.dw + self.kw - w - self.pw, self.pw)
        return (self.ph, hi_h), (self.pw, hi_w)


class SpatialMaxPooling(_Pool2d):
    """(reference nn/SpatialMaxPooling.scala)

    Backward is XLA's select-and-scatter via autodiff, which also matches
    Torch's first-max tie rule. FOUR hand-written VJPs for the stride-1
    pools have now been benchmarked and all measured SLOWER end-to-end
    than select-and-scatter: round 2's three XLA-graph rewrites (shifted
    equality sums, tie-splitting, stacked argmax), and round 4's fused
    Pallas backward kernel (``ops/pallas/maxpool.py`` — bit-exact
    first-max semantics, but 4,437 vs 5,056-5,252 img/s on the Inception
    bench: the mask formulation needs ~45 VPU ops per element and is
    compute-bound where S&S's hardware path is not; docs/PERF.md round
    4). The kernel stays in-tree with interpret-mode parity tests but is
    NOT dispatched — don't re-enable without a fresh whole-model win.
    """

    def apply(self, params, state, x, *, training=False, rng=None):
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        ph, pw = self._padding(x.shape[2], x.shape[3])
        y = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            window_dimensions=(1, 1, self.kh, self.kw),
            window_strides=(1, 1, self.dh, self.dw),
            padding=((0, 0), (0, 0), ph, pw))
        if squeeze:
            y = y[0]
        return y, state


class SpatialAveragePooling(_Pool2d):
    """(reference nn/SpatialAveragePooling.scala; ``count_include_pad``
    matches Torch's default True)."""

    def __init__(self, kw, kh, dw=None, dh=None, pad_w=0, pad_h=0,
                 count_include_pad: bool = True, divide: bool = True):
        super().__init__(kw, kh, dw, dh, pad_w, pad_h)
        self.count_include_pad = count_include_pad
        self.divide = divide

    def apply(self, params, state, x, *, training=False, rng=None):
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        ph, pw = self._padding(x.shape[2], x.shape[3])
        pad = ((0, 0), (0, 0), ph, pw)
        y = jax.lax.reduce_window(
            x, 0.0, jax.lax.add,
            window_dimensions=(1, 1, self.kh, self.kw),
            window_strides=(1, 1, self.dh, self.dw), padding=pad)
        if self.divide:
            if self.count_include_pad:
                y = y / (self.kh * self.kw)
            else:
                ones = jnp.ones_like(x)
                cnt = jax.lax.reduce_window(
                    ones, 0.0, jax.lax.add,
                    window_dimensions=(1, 1, self.kh, self.kw),
                    window_strides=(1, 1, self.dh, self.dw), padding=pad)
                y = y / cnt
        if squeeze:
            y = y[0]
        return y, state


class RoiPooling(Module):
    """Region-of-interest max pooling (reference nn/RoiPooling.scala).

    Input: (features NCHW, rois (R, 5) of [batch_idx, x1, y1, x2, y2]).
    Fixed-size loop over pooled cells keeps shapes static for XLA.
    """

    def __init__(self, pooled_w: int, pooled_h: int, spatial_scale: float):
        super().__init__()
        self.pw, self.ph = pooled_w, pooled_h
        self.scale = spatial_scale

    def apply(self, params, state, x, *, training=False, rng=None):
        feats, rois = x
        H, W = feats.shape[2], feats.shape[3]

        def pool_one(roi):
            b = roi[0].astype(jnp.int32)
            x1 = jnp.round(roi[1] * self.scale).astype(jnp.int32)
            y1 = jnp.round(roi[2] * self.scale).astype(jnp.int32)
            x2 = jnp.round(roi[3] * self.scale).astype(jnp.int32)
            y2 = jnp.round(roi[4] * self.scale).astype(jnp.int32)
            rh = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
            rw = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)
            bin_h, bin_w = rh / self.ph, rw / self.pw
            fmap = feats[b]

            ys = jnp.arange(H)[None, :]
            xs = jnp.arange(W)[None, :]
            # (ph, H) / (pw, W) membership masks per pooled cell
            i = jnp.arange(self.ph)[:, None].astype(jnp.float32)
            j = jnp.arange(self.pw)[:, None].astype(jnp.float32)
            hs = jnp.floor(i * bin_h).astype(jnp.int32) + y1
            he = jnp.ceil((i + 1) * bin_h).astype(jnp.int32) + y1
            ws = jnp.floor(j * bin_w).astype(jnp.int32) + x1
            we = jnp.ceil((j + 1) * bin_w).astype(jnp.int32) + x1
            hmask = (ys >= hs) & (ys < jnp.minimum(he, H))  # (ph, H)
            wmask = (xs >= ws) & (xs < jnp.minimum(we, W))  # (pw, W)
            m = hmask[:, None, :, None] & wmask[None, :, None, :]
            vals = jnp.where(m[None], fmap[:, None, None, :, :], -jnp.inf)
            out = vals.max(axis=(-1, -2))  # (C, ph, pw)
            return jnp.where(jnp.isfinite(out), out, 0.0)

        return jax.vmap(pool_one)(rois.astype(jnp.float32)), state

"""Container modules.

Reference parity: Sequential (nn/Sequential.scala:28-52), Concat
(nn/Concat.scala:42-80), ConcatTable, ParallelTable, Bottle
(all in dl/.../bigdl/nn/). The reference threads output-copies through
``Engine.model.invoke``; here XLA fuses the concatenation — no manual
threading.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Container, Module, _fold

__all__ = ["Sequential", "Concat", "ConcatTable", "ParallelTable", "Bottle",
           "MapTable", "Remat"]


class Sequential(Container):
    """Chain children (reference nn/Sequential.scala:28-52)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        new_state = {}
        for i, m in enumerate(self.modules):
            x, s = m.apply(params[str(i)], state[str(i)], x,
                           training=training, rng=_fold(rng, i))
            new_state[str(i)] = s
        return x, new_state


class Concat(Container):
    """Run children on the same input, concat outputs along ``dimension``
    (reference nn/Concat.scala; 1-based dim in the reference, here 0-based
    with the batch at axis 0 — reference dim=2 on NCHW == axis=1 here)."""

    def __init__(self, dimension: int = 1):
        super().__init__()
        self.dimension = dimension

    def apply(self, params, state, x, *, training=False, rng=None):
        outs, new_state = [], {}
        for i, m in enumerate(self.modules):
            y, s = m.apply(params[str(i)], state[str(i)], x,
                           training=training, rng=_fold(rng, i))
            outs.append(y)
            new_state[str(i)] = s
        return jnp.concatenate(outs, axis=self.dimension), new_state


class ConcatTable(Container):
    """Run children on the same input, return tuple of outputs
    (reference nn/ConcatTable.scala)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        outs, new_state = [], {}
        for i, m in enumerate(self.modules):
            y, s = m.apply(params[str(i)], state[str(i)], x,
                           training=training, rng=_fold(rng, i))
            outs.append(y)
            new_state[str(i)] = s
        return tuple(outs), new_state


class ParallelTable(Container):
    """i-th child consumes i-th element of the input table
    (reference nn/ParallelTable.scala)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        outs, new_state = [], {}
        for i, m in enumerate(self.modules):
            y, s = m.apply(params[str(i)], state[str(i)], x[i],
                           training=training, rng=_fold(rng, i))
            outs.append(y)
            new_state[str(i)] = s
        return tuple(outs), new_state


class MapTable(Container):
    """Apply the single child to every element of the input table
    (reference nn/MapTable.scala). Parameters are shared across elements."""

    def __init__(self, module: Module | None = None):
        super().__init__()
        if module is not None:
            self.add(module)

    def init(self, rng):
        return {"0": self.modules[0].init(rng)}

    def init_state(self):
        return {"0": self.modules[0].init_state()}

    def apply(self, params, state, x, *, training=False, rng=None):
        m = self.modules[0]
        outs = []
        s = state["0"]
        for i, xi in enumerate(x):
            y, s = m.apply(params["0"], s, xi, training=training,
                           rng=_fold(rng, i))
            outs.append(y)
        return tuple(outs), {"0": s}


class Bottle(Container):
    """Collapse leading dims, apply child, restore (reference nn/Bottle.scala).

    ``n_input_dim`` is the child's expected input rank.
    """

    def __init__(self, module: Module, n_input_dim: int = 2,
                 n_output_dim: int | None = None):
        super().__init__(module)
        self.n_input_dim = n_input_dim
        self.n_output_dim = n_output_dim or n_input_dim

    def apply(self, params, state, x, *, training=False, rng=None):
        shape = x.shape
        lead = shape[:len(shape) - self.n_input_dim + 1]
        squashed = x.reshape((-1,) + shape[len(shape) - self.n_input_dim + 1:])
        y, s = self.modules[0].apply(params["0"], state["0"], squashed,
                                     training=training, rng=rng)
        y = y.reshape(lead + y.shape[1:])
        return y, {"0": s}


class Remat(Container):
    """Rematerialize the child in backward (``jax.checkpoint``).

    TPU-first memory lever with no reference counterpart: the reference
    caches every module's ``output``/``gradInput`` (AbstractModule.scala:48-53)
    because its backward consumes them; under autodiff those cached
    activations become XLA-saved residuals and, for bandwidth-bound models,
    HBM traffic. Wrapping a block in ``Remat`` saves only the block
    boundary and recomputes the interior during backward — trading MXU
    FLOPs (usually idle in memory-bound steps) for HBM bytes.

    Transparent to the param/state pytree: the child's tree IS this
    module's tree, so wrapping changes no checkpoint layout, golden
    fixture, or Caffe/Torch name-matched import.
    """

    def __init__(self, module: Module, policy=None):
        super().__init__(module)
        self.policy = policy

    def init(self, rng):
        return self.modules[0].init(rng)

    def init_state(self):
        return self.modules[0].init_state()

    def apply(self, params, state, x, *, training=False, rng=None):
        child = self.modules[0]

        def inner(p, s, xx, r):
            return child.apply(p, s, xx, training=training, rng=r)

        return jax.checkpoint(inner, policy=self.policy)(params, state, x,
                                                         rng)

    def sync(self, params, state=None):
        Module.sync(self, params, state)
        self.modules[0].sync(params, state)
        return self

    def materialize(self, rng=None):
        if self.params is None:
            if rng is None:
                rng = jax.random.PRNGKey(0)
            self._rng = rng
            self.modules[0].materialize(rng)
            self.params = self.modules[0].params
            self.state = self.modules[0].state
            self.grad_params = jax.tree.map(jnp.zeros_like, self.params)
        return self

    def __repr__(self):
        return f"Remat({self.modules[0]!r})"

"""Layer & criterion library (reference: dl/.../bigdl/nn/, 138 files)."""

from bigdl_tpu.nn.module import Module, Container, Criterion, Identity, Echo
from bigdl_tpu.nn.containers import (Sequential, Concat, ConcatTable,
                                     ParallelTable, MapTable, Bottle, Remat)
from bigdl_tpu.nn.linear import (Linear, Bilinear, LookupTable, Cosine,
                                 Euclidean, Add, CAdd, CMul, Mul, MM, MV)
from bigdl_tpu.nn.activations import (
    ReLU, ReLU6, PReLU, RReLU, LeakyReLU, ELU, Tanh, TanhShrink, Sigmoid,
    LogSigmoid, SoftMax, SoftMin, LogSoftMax, SoftPlus, SoftSign, HardTanh,
    HardShrink, SoftShrink, Threshold, Clamp, Power, Sqrt, Square, Abs, Log,
    Exp, GradientReversal, Scale, MulConstant, AddConstant)
from bigdl_tpu.nn.conv import (SpatialConvolution, SpatialShareConvolution,
                               SpatialFullConvolution,
                               SpatialDilatedConvolution,
                               SpatialConvolutionMap)
from bigdl_tpu.nn.pooling import (SpatialMaxPooling, SpatialAveragePooling,
                                  RoiPooling)
from bigdl_tpu.nn.normalization import (
    BatchNormalization, SpatialBatchNormalization, SpatialCrossMapLRN,
    ReLUCrossMapLRN, Normalize, SpatialDivisiveNormalization,
    SpatialSubtractiveNormalization, SpatialContrastiveNormalization,
    LayerNorm)
from bigdl_tpu.nn.dropout import Dropout, L1Penalty
from bigdl_tpu.nn.structural import (
    Reshape, InferReshape, View, Transpose, Squeeze, Unsqueeze, Select,
    SelectTable, Narrow, NarrowTable, Index, JoinTable, SplitTable,
    FlattenTable, Replicate, Padding, SpatialZeroPadding, Copy, Contiguous,
    Sum, Mean, Max, Min)
from bigdl_tpu.nn.table_ops import (CAddTable, CSubTable, CMulTable,
                                    CDivTable, CMaxTable, CMinTable,
                                    DotProduct, PairwiseDistance,
                                    CosineDistance, MixtureTable,
                                    MaskedSelect)
from bigdl_tpu.nn.recurrent import (Cell, RnnCell, RNN, LSTM, GRU, Recurrent,
                                    BiRecurrent, TimeDistributed)
from bigdl_tpu.nn.attention import MultiHeadAttention
from bigdl_tpu.nn.criterion import (
    ClassNLLCriterion, MSECriterion, BCECriterion, CrossEntropyCriterion,
    ClassSimplexCriterion, AbsCriterion, CosineEmbeddingCriterion,
    DistKLDivCriterion, HingeEmbeddingCriterion, L1Cost,
    L1HingeEmbeddingCriterion, MarginCriterion, MarginRankingCriterion,
    MultiCriterion, MultiLabelMarginCriterion, MultiLabelSoftMarginCriterion,
    MultiMarginCriterion, SmoothL1Criterion, SmoothL1CriterionWithWeights,
    SoftMarginCriterion, SoftmaxWithCriterion, ParallelCriterion,
    TimeDistributedCriterion, CriterionTable, MaskedCriterion)
from bigdl_tpu.nn.detection import Nms, nms
from bigdl_tpu.nn import init  # noqa: F401

"""Structural / shape-manipulation modules.

Reference parity (all in dl/.../bigdl/nn/): Reshape, InferReshape, View,
Transpose, Squeeze, Unsqueeze, Select, SelectTable, Narrow, NarrowTable,
Index, JoinTable, SplitTable, FlattenTable, Replicate, Padding,
SpatialZeroPadding, Copy, Contiguous, Sum, Mean, Max, Min.

Dim conventions: the reference is 1-based Torch; here dims are 0-based
Python/JAX, and negative dims count from the end.
"""
from __future__ import annotations

import jax.numpy as jnp

from bigdl_tpu.nn.module import Module

__all__ = ["Reshape", "InferReshape", "View", "Transpose", "Squeeze",
           "Unsqueeze", "Select", "SelectTable", "Narrow", "NarrowTable",
           "Index", "JoinTable", "SplitTable", "FlattenTable", "Replicate",
           "Padding", "SpatialZeroPadding", "Copy", "Contiguous",
           "Sum", "Mean", "Max", "Min"]


class Reshape(Module):
    """Reshape non-batch dims (reference nn/Reshape.scala; ``batch_mode``
    None=infer like the reference)."""

    def __init__(self, size, batch_mode: bool | None = None):
        super().__init__()
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def apply(self, params, state, x, *, training=False, rng=None):
        import numpy as np
        n = int(np.prod(self.size))
        # infer like the reference (Reshape.scala): batched when the
        # element count is batch*n, even at batch 1 (x.size == n alone is
        # ambiguous there — require the leading dim to account for it)
        batch = (self.batch_mode if self.batch_mode is not None
                 else x.ndim > len(self.size)
                 and x.size == x.shape[0] * n)
        if batch:
            return x.reshape((x.shape[0],) + self.size), state
        return x.reshape(self.size), state


class InferReshape(Module):
    """Reshape with -1 inference and 0 = copy-input-dim
    (reference nn/InferReshape.scala)."""

    def __init__(self, size, batch_mode: bool = False):
        super().__init__()
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def apply(self, params, state, x, *, training=False, rng=None):
        offset = 1 if self.batch_mode else 0
        out = []
        for i, s in enumerate(self.size):
            out.append(x.shape[i + offset] if s == 0 else s)
        if self.batch_mode:
            out = [x.shape[0]] + out
        return x.reshape(tuple(out)), state


class View(Module):
    """(reference nn/View.scala; keeps batch dim, supports num_input_dims)"""

    def __init__(self, *sizes):
        super().__init__()
        if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)):
            sizes = tuple(sizes[0])
        self.sizes = tuple(sizes)
        self.num_input_dims = None

    def set_num_input_dims(self, n: int):
        """(reference View.setNumInputDims) — inputs with more than ``n``
        dims carry a leading batch axis that is preserved."""
        self.num_input_dims = n
        return self

    def apply(self, params, state, x, *, training=False, rng=None):
        import numpy as np
        n = int(np.prod([s for s in self.sizes if s > 0]))
        if self.num_input_dims is not None:
            batched = x.ndim > self.num_input_dims
        elif -1 in self.sizes:
            # -1 absorbs any element count, so the non-batched reshape is
            # always valid; without num_input_dims a bare View(-1) is the
            # Torch full-flatten, never an implicit batch split
            batched = False
        else:
            # treat dim 0 as batch whenever the target accounts for the rest
            batched = (x.ndim > len(self.sizes)
                       and x.size == x.shape[0] * n) or x.size != n
        if batched:
            return x.reshape((x.shape[0],) + self.sizes), state
        return x.reshape(self.sizes), state


class Transpose(Module):
    """Sequence of pairwise dim swaps (reference nn/Transpose.scala)."""

    def __init__(self, permutations):
        super().__init__()
        self.permutations = [tuple(p) for p in permutations]

    def apply(self, params, state, x, *, training=False, rng=None):
        for d1, d2 in self.permutations:
            x = jnp.swapaxes(x, d1, d2)
        return x, state


class Squeeze(Module):
    def __init__(self, dim: int | None = None, num_input_dims: int = -1):
        super().__init__()
        self.dim = dim

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.squeeze(x, self.dim), state


class Unsqueeze(Module):
    def __init__(self, pos: int, num_input_dims: int = -1):
        super().__init__()
        self.pos = pos

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.expand_dims(x, self.pos), state


class Select(Module):
    """Select ``index`` along ``dim`` (reference nn/Select.scala)."""

    def __init__(self, dim: int, index: int):
        super().__init__()
        self.dim, self.index = dim, index

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.take(x, self.index, axis=self.dim), state


class SelectTable(Module):
    """Select the i-th element of a table (reference nn/SelectTable.scala)."""

    def __init__(self, index: int):
        super().__init__()
        self.index = index

    def apply(self, params, state, x, *, training=False, rng=None):
        return x[self.index], state


class Narrow(Module):
    """Slice ``length`` elements from ``offset`` along ``dim``
    (reference nn/Narrow.scala; offset 0-based here)."""

    def __init__(self, dim: int, offset: int, length: int = 1):
        super().__init__()
        self.dim, self.offset, self.length = dim, offset, length

    def apply(self, params, state, x, *, training=False, rng=None):
        length = self.length
        if length < 0:
            length = x.shape[self.dim] - self.offset + length + 1
        idx = [slice(None)] * x.ndim
        idx[self.dim] = slice(self.offset, self.offset + length)
        return x[tuple(idx)], state


class NarrowTable(Module):
    def __init__(self, offset: int, length: int = 1):
        super().__init__()
        self.offset, self.length = offset, length

    def apply(self, params, state, x, *, training=False, rng=None):
        return tuple(x[self.offset:self.offset + self.length]), state


class Index(Module):
    """index_select along dim by the second table element
    (reference nn/Index.scala; indices 1-based in the reference)."""

    def __init__(self, dimension: int):
        super().__init__()
        self.dimension = dimension

    def apply(self, params, state, x, *, training=False, rng=None):
        t, idx = x
        return jnp.take(t, idx.astype(jnp.int32) - 1, axis=self.dimension), \
            state


class JoinTable(Module):
    """Concat table elements along ``dimension``
    (reference nn/JoinTable.scala; n_input_dims enables batch-dim shift)."""

    def __init__(self, dimension: int, n_input_dims: int = -1):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def apply(self, params, state, x, *, training=False, rng=None):
        dim = self.dimension
        if self.n_input_dims > 0 and x[0].ndim > self.n_input_dims:
            dim += 1
        return jnp.concatenate(list(x), axis=dim), state


class SplitTable(Module):
    """Split along ``dimension`` into a table (reference
    nn/SplitTable.scala)."""

    def __init__(self, dimension: int, n_input_dims: int = -1):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def apply(self, params, state, x, *, training=False, rng=None):
        dim = self.dimension
        if self.n_input_dims > 0 and x.ndim > self.n_input_dims:
            dim += 1
        n = x.shape[dim]
        parts = jnp.split(x, n, axis=dim)
        return tuple(jnp.squeeze(p, axis=dim) for p in parts), state


def _flatten(table, out):
    for v in table:
        if isinstance(v, (tuple, list)):
            _flatten(v, out)
        else:
            out.append(v)
    return out


class FlattenTable(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return tuple(_flatten(x, [])), state


class Replicate(Module):
    """Insert a new dim of size nFeatures by replication
    (reference nn/Replicate.scala)."""

    def __init__(self, n_features: int, dim: int = 0, n_dim: int = -1):
        super().__init__()
        self.n_features, self.dim = n_features, dim

    def apply(self, params, state, x, *, training=False, rng=None):
        y = jnp.expand_dims(x, self.dim)
        reps = [1] * y.ndim
        reps[self.dim] = self.n_features
        return jnp.tile(y, reps), state


class Padding(Module):
    """Pad ``pad`` entries (sign = side) along ``dim`` with ``value``
    (reference nn/Padding.scala)."""

    def __init__(self, dim: int, pad: int, n_input_dim: int = -1,
                 value: float = 0.0, n_index: int = 1):
        super().__init__()
        self.dim, self.pad, self.value = dim, pad, value
        self.n_input_dim = n_input_dim

    def apply(self, params, state, x, *, training=False, rng=None):
        dim = self.dim
        if self.n_input_dim > 0 and x.ndim > self.n_input_dim:
            dim += 1
        cfg = [(0, 0)] * x.ndim
        cfg[dim] = (abs(self.pad), 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(x, cfg, constant_values=self.value), state


class SpatialZeroPadding(Module):
    """(reference nn/SpatialZeroPadding.scala; negative pad crops)"""

    def __init__(self, pad_left: int, pad_right: int | None = None,
                 pad_top: int | None = None, pad_bottom: int | None = None):
        super().__init__()
        self.pl = pad_left
        self.pr = pad_right if pad_right is not None else pad_left
        self.pt = pad_top if pad_top is not None else pad_left
        self.pb = pad_bottom if pad_bottom is not None else pad_left

    def apply(self, params, state, x, *, training=False, rng=None):
        def padcrop(arr, axis, lo, hi):
            if lo < 0:
                idx = [slice(None)] * arr.ndim
                idx[axis] = slice(-lo, None)
                arr = arr[tuple(idx)]
                lo = 0
            if hi < 0:
                idx = [slice(None)] * arr.ndim
                idx[axis] = slice(None, hi)
                arr = arr[tuple(idx)]
                hi = 0
            cfg = [(0, 0)] * arr.ndim
            cfg[axis] = (lo, hi)
            return jnp.pad(arr, cfg)

        x = padcrop(x, x.ndim - 2, self.pt, self.pb)
        x = padcrop(x, x.ndim - 1, self.pl, self.pr)
        return x, state


class Copy(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.array(x), state


class Contiguous(Module):
    """No-op under XLA (reference nn/Contiguous.scala)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        return x, state


class _Reduce(Module):
    def __init__(self, dimension: int = 0, n_input_dims: int = -1,
                 size_average: bool = False):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims
        self.size_average = size_average

    def _dim(self, x):
        d = self.dimension
        if self.n_input_dims > 0 and x.ndim > self.n_input_dims:
            d += 1
        return d


class Sum(_Reduce):
    """(reference nn/Sum.scala; size_average divides by dim size)"""

    def apply(self, params, state, x, *, training=False, rng=None):
        d = self._dim(x)
        y = jnp.sum(x, axis=d)
        if self.size_average:
            y = y / x.shape[d]
        return y, state


class Mean(_Reduce):
    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.mean(x, axis=self._dim(x)), state


class Max(_Reduce):
    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.max(x, axis=self._dim(x)), state


class Min(_Reduce):
    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.min(x, axis=self._dim(x)), state

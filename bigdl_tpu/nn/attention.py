"""Attention modules.

The reference predates transformers — its long-sequence story is scan
RNNs (SURVEY §5.7). On TPU, attention is the long-context workhorse, so
the module library carries a MultiHeadAttention whose core can run
locally, ring-parallel, or Ulysses-parallel over the mesh ``seq`` axis
(parallel/sequence.py) without changing the module's parameters.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Module
from bigdl_tpu.tensor import activation_dtype, compute_dtype, default_dtype

__all__ = ["MultiHeadAttention", "apply_rope"]


def apply_rope(x, positions, theta: float = 10000.0):
    """Rotary position embedding over the head dim (GPT-NeoX split-half
    convention: pairs are (x[..., i], x[..., i + D/2])).

    ``x``: (..., S, H, D) with D even (any number of leading batch dims);
    ``positions``: (S,) absolute token
    positions (int). Rotation depends only on a token's own absolute
    position, so scores q_m . k_n depend only on m - n (pinned by
    tests/test_transformer.py) — the property that lets a KV cache store
    rotated keys and lets ring/Ulysses sharding rotate before the
    collective. Computed in f32, returned in x's dtype."""
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[:, None] * freqs[None, :]  # (S, hf)
    # angles in f32 (bf16 positions would alias beyond ~256), the
    # rotation itself in x's dtype — the f32 variant cost ~8 ms/step on
    # the d1024/12L flagship (24 widened elementwise passes)
    # broadcast shape built from x.ndim so any number of leading batch
    # dims aligns (S, hf) onto x's (S, ..., D/2) axes, not a hard-coded 4-D
    bshape = (1,) * (x.ndim - 3) + (ang.shape[0], 1, half)
    cos = jnp.cos(ang).reshape(bshape).astype(x.dtype)
    sin = jnp.sin(ang).reshape(bshape).astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x2 * cos + x1 * sin], axis=-1)


class MultiHeadAttention(Module):
    """Self-attention over (batch, seq, embed).

    ``sequence_parallel`` selects the attention core: None (local),
    "ring" or "ulysses" (sequence-sharded over ``mesh_axis``; inputs must
    then be seq-sharded arrays under an active mesh, and seq/heads must
    divide the axis size — see parallel/sequence.py).

    ``rope=True`` rotates q/k by absolute position (``apply_rope``)
    before the attention core — pair with a model that skips additive
    positional embeddings (``TransformerLM(pos_encoding="rope")``).
    Composes with the sequence-parallel cores: rotation happens on the
    (GSPMD-sharded) global arrays before the collective, and positions
    are the global ``arange(S)``.

    ``num_kv_heads`` < ``num_heads`` selects grouped-query attention
    (GQA; num_kv_heads=1 is multi-query): k/v project to num_kv_heads
    heads and are repeated across each query group before the core. The
    parameter saving is in the k/v projections; the decode path's win is
    the num_heads/num_kv_heads-times smaller KV cache
    (models/transformer/generate.py).
    """

    def __init__(self, embed_dim: int, num_heads: int,
                 causal: bool = False, with_bias: bool = True,
                 sequence_parallel: str | None = None,
                 mesh_axis: str = "seq", rope: bool = False,
                 num_kv_heads: int | None = None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.head_dim = embed_dim // num_heads
        if num_kv_heads is not None and num_kv_heads < 1:
            raise ValueError(f"num_kv_heads={num_kv_heads} must be >= 1 "
                             "(or None for full MHA)")
        self.num_kv_heads = num_kv_heads or num_heads
        if num_heads % self.num_kv_heads != 0:
            raise ValueError(f"num_heads={num_heads} must be a multiple "
                             f"of num_kv_heads={self.num_kv_heads}")
        self.causal = causal
        self.with_bias = with_bias
        if sequence_parallel not in (None, "ring", "ulysses"):
            raise ValueError(
                f"sequence_parallel={sequence_parallel!r} — expected "
                "None, 'ring' or 'ulysses'")
        self.sequence_parallel = sequence_parallel
        self.mesh_axis = mesh_axis
        self.rope = rope
        if rope:
            assert self.head_dim % 2 == 0, "rope needs an even head_dim"

    def init(self, rng):
        ks = jax.random.split(rng, 4)
        kv_dim = self.num_kv_heads * self.head_dim
        p = {}
        for name, k in zip(("q", "k", "v", "out"), ks):
            out_dim = kv_dim if name in ("k", "v") else self.embed_dim
            w = init_mod.init_weight(init_mod.Xavier, k,
                                     (out_dim, self.embed_dim),
                                     fan_in=self.embed_dim,
                                     fan_out=out_dim)
            p[f"{name}_weight"] = w
            if self.with_bias:
                p[f"{name}_bias"] = jnp.zeros((out_dim,), default_dtype())
        return p

    def _proj(self, params, name, x):
        y = jnp.matmul(x.astype(compute_dtype()),
                       params[f"{name}_weight"].astype(compute_dtype()).T)
        if self.with_bias:
            y = y + params[f"{name}_bias"].astype(compute_dtype())
        return y

    def apply(self, params, state, x, *, training=False, rng=None):
        from bigdl_tpu.parallel import sequence as seq
        b, s, e = x.shape
        heads = (self.num_heads, self.head_dim)
        q = self._proj(params, "q", x).reshape(b, s, *heads)
        k = self._proj(params, "k", x).reshape(
            b, s, self.num_kv_heads, self.head_dim)
        v = self._proj(params, "v", x).reshape(
            b, s, self.num_kv_heads, self.head_dim)
        if self.rope:
            pos = jnp.arange(s)
            q = apply_rope(q, pos)
            k = apply_rope(k, pos)
        group = self.num_heads // self.num_kv_heads
        if group > 1 and self.sequence_parallel is None:
            # GQA: each kv head serves `group` query heads. The ring and
            # Ulysses cores take the NARROW k/v and widen inside — ring
            # per hop, Ulysses after its all_to_all — so grouped blocks
            # travel the wire at kv width; only the local core (flash
            # kernel assumes matching H) needs full-width heads here
            k = jnp.repeat(k, group, axis=2)
            v = jnp.repeat(v, group, axis=2)
        if self.sequence_parallel == "ring":
            o = seq.ring_attention(q, k, v, causal=self.causal,
                                   axis=self.mesh_axis, kv_groups=group)
        elif self.sequence_parallel == "ulysses":
            o = seq.ulysses_attention(q, k, v, causal=self.causal,
                                      axis=self.mesh_axis,
                                      kv_groups=group)
        else:
            o = seq.dot_product_attention(q, k, v, causal=self.causal)
        y = self._proj(params, "out", o.reshape(b, s, e))
        return y.astype(activation_dtype()), state

    def __repr__(self):
        return (f"MultiHeadAttention({self.embed_dim}, "
                f"heads={self.num_heads}, causal={self.causal}, "
                f"sp={self.sequence_parallel})")

"""Criterion (loss) library.

Reference parity (dl/.../bigdl/nn/): ClassNLLCriterion, MSECriterion,
BCECriterion, CrossEntropyCriterion, ClassSimplexCriterion, AbsCriterion,
CosineEmbeddingCriterion, DistKLDivCriterion, HingeEmbeddingCriterion,
L1Cost, L1HingeEmbeddingCriterion, MarginCriterion, MarginRankingCriterion,
MultiCriterion, MultiLabelMarginCriterion, MultiLabelSoftMarginCriterion,
MultiMarginCriterion, SmoothL1Criterion, SmoothL1CriterionWithWeights,
SoftMarginCriterion, SoftmaxWithCriterion, ParallelCriterion,
TimeDistributedCriterion, CriterionTable.

Conventions: class targets are **1-based** like the reference/Torch; losses
are pure scalar functions, gradients via autodiff (the reference hand-writes
``updateGradInput`` per criterion).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import Criterion

__all__ = ["ClassNLLCriterion", "MSECriterion", "BCECriterion",
           "CrossEntropyCriterion", "ClassSimplexCriterion", "AbsCriterion",
           "CosineEmbeddingCriterion", "DistKLDivCriterion",
           "HingeEmbeddingCriterion", "L1Cost", "L1HingeEmbeddingCriterion",
           "MarginCriterion", "MarginRankingCriterion", "MultiCriterion",
           "MultiLabelMarginCriterion", "MultiLabelSoftMarginCriterion",
           "MultiMarginCriterion", "SmoothL1Criterion",
           "SmoothL1CriterionWithWeights", "SoftMarginCriterion",
           "SoftmaxWithCriterion", "ParallelCriterion",
           "TimeDistributedCriterion", "CriterionTable", "MaskedCriterion"]


def _avg(v, n, size_average):
    return v / n if size_average else v


def _nll_reduce(per, t, weights, size_average):
    """Shared NLL reduction: ``per`` is the per-sample loss, ``t`` the
    0-based class index (for per-class weights)."""
    if weights is not None:
        w = jnp.take(weights, t)
        total = jnp.sum(w * per)
        return total / jnp.sum(w) if size_average else total
    return _avg(jnp.sum(per), t.shape[0], size_average)


class ClassNLLCriterion(Criterion):
    """NLL over log-probabilities; 1-based integer targets
    (reference nn/ClassNLLCriterion.scala, threaded per sample)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def apply(self, x, target):
        t = target.astype(jnp.int32).reshape(-1) - 1
        logp = x.reshape(-1, x.shape[-1])
        picked = jnp.take_along_axis(logp, t[:, None], axis=1)[:, 0]
        return _nll_reduce(-picked, t, self.weights, self.size_average)


class MSECriterion(Criterion):
    """(reference nn/MSECriterion.scala)"""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def apply(self, x, target):
        return _avg(jnp.sum(jnp.square(x - target)), x.size,
                    self.size_average)


class AbsCriterion(Criterion):
    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def apply(self, x, target):
        return _avg(jnp.sum(jnp.abs(x - target)), x.size, self.size_average)


class BCECriterion(Criterion):
    """(reference nn/BCECriterion.scala; eps clamp like Torch)"""

    eps = 1e-12

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def apply(self, x, target):
        l = target * jnp.log(x + self.eps) + \
            (1 - target) * jnp.log(1 - x + self.eps)
        if self.weights is not None:
            l = l * self.weights
        return _avg(-jnp.sum(l), x.size, self.size_average)


class CrossEntropyCriterion(Criterion):
    """LogSoftMax + ClassNLL fused (reference nn/CrossEntropyCriterion.scala).

    TPU note: computed as ``logsumexp(x) - x[target]`` rather than
    composing ``log_softmax`` + NLL: the composition materializes the
    (N, V) log-prob tensor in f32 as a saved residual, while the lse
    form's backward is ``softmax(x) - onehot`` fused into the one
    cotangent buffer that must exist anyway — at LM vocab sizes this is
    the difference between several extra (B, S, V) buffers and none
    (docs/PERF.md transformer section)."""

    def __init__(self, weights=None, size_average: bool = True,
                 label_smoothing: float = 0.0):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average
        if not 0.0 <= label_smoothing < 1.0:
            raise ValueError(f"label_smoothing must be in [0, 1), got "
                             f"{label_smoothing}")
        self.label_smoothing = label_smoothing

    def apply(self, x, target):
        t = target.astype(jnp.int32).reshape(-1) - 1
        logits = x.reshape(-1, x.shape[-1]).astype(
            jnp.promote_types(x.dtype, jnp.float32))
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, t[:, None], axis=1)[:, 0]
        per = lse - picked
        eps = self.label_smoothing
        if eps > 0.0 and self.weights is not None:
            # torch convention with class weights: the target term is
            # weighted by w[t] but the smoothing term by each class's
            # own weight (-(logp * w).sum / K); mean divides by sum w[t]
            w = self.weights.astype(logits.dtype)
            w_t = jnp.take(w, t)
            smooth = (lse * jnp.sum(w) - logits @ w) / logits.shape[-1]
            total = jnp.sum((1.0 - eps) * w_t * per + eps * smooth)
            return total / jnp.sum(w_t) if self.size_average else total
        if eps > 0.0:
            # (1-eps)*CE(target) + eps*mean_c CE(c)
            per = (1.0 - eps) * per + eps * (lse - jnp.mean(logits,
                                                            axis=-1))
        return _nll_reduce(per, t, self.weights, self.size_average)


class ClassSimplexCriterion(Criterion):
    """MSE against a regular-simplex embedding of the classes
    (reference nn/ClassSimplexCriterion.scala)."""

    def __init__(self, n_classes: int):
        super().__init__()
        self.n_classes = n_classes
        self.simplex = jnp.asarray(self._regular_simplex(n_classes))
        self.mse = MSECriterion()

    @staticmethod
    def _regular_simplex(n):
        """n unit vertices in R^n with pairwise dot -1/(n-1) — the regular
        simplex the reference embeds classes into."""
        a = np.zeros((n, n), np.float32)
        for k in range(n - 1):
            a[k, k] = np.sqrt(max(1.0 - np.sum(a[k, :k] ** 2), 0.0))
            for j in range(k + 1, n):
                a[j, k] = (-1.0 / (n - 1) - np.dot(a[j, :k], a[k, :k])) \
                    / a[k, k]
        return a

    def apply(self, x, target):
        t = target.astype(jnp.int32).reshape(-1) - 1
        goal = jnp.take(self.simplex, t, axis=0)
        return self.mse.apply(x, goal)


class CosineEmbeddingCriterion(Criterion):
    """(reference nn/CosineEmbeddingCriterion.scala; y=1 similar, y=-1
    dissimilar with margin)"""

    def __init__(self, margin: float = 0.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def apply(self, x, target):
        a, b = x
        y = target.reshape(-1)
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        l = jnp.where(y > 0, 1 - cos, jnp.maximum(0.0, cos - self.margin))
        return _avg(jnp.sum(l), y.shape[0], self.size_average)


class DistKLDivCriterion(Criterion):
    """KL(target || exp(input)) with log-prob input
    (reference nn/DistKLDivCriterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def apply(self, x, target):
        l = jnp.where(target > 0, target * (jnp.log(jnp.maximum(target, 1e-30))
                                            - x), 0.0)
        n = x.shape[0] if x.ndim > 1 else 1
        return _avg(jnp.sum(l), x.size if x.ndim == 1 else n,
                    self.size_average)


class HingeEmbeddingCriterion(Criterion):
    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def apply(self, x, target):
        l = jnp.where(target > 0, x, jnp.maximum(0.0, self.margin - x))
        return _avg(jnp.sum(l), x.size, self.size_average)


class L1Cost(Criterion):
    """(reference nn/L1Cost.scala)"""

    def apply(self, x, target=None):
        return jnp.sum(jnp.abs(x))


class L1HingeEmbeddingCriterion(Criterion):
    """Hinge on L1 distance of a pair (reference
    nn/L1HingeEmbeddingCriterion.scala)."""

    def __init__(self, margin: float = 1.0):
        super().__init__()
        self.margin = margin

    def apply(self, x, target):
        a, b = x
        d = jnp.sum(jnp.abs(a - b))
        y = jnp.reshape(target, ())
        return jnp.where(y > 0, d, jnp.maximum(0.0, self.margin - d))


class MarginCriterion(Criterion):
    """Hinge loss (reference nn/MarginCriterion.scala; squared option)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True,
                 squared: bool = False):
        super().__init__()
        self.margin, self.size_average, self.squared = \
            margin, size_average, squared

    def apply(self, x, target):
        l = jnp.maximum(0.0, self.margin - x * target)
        if self.squared:
            l = jnp.square(l)
        return _avg(jnp.sum(l), x.size, self.size_average)


class MarginRankingCriterion(Criterion):
    """(reference nn/MarginRankingCriterion.scala)"""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin, self.size_average = margin, size_average

    def apply(self, x, target):
        a, b = x
        y = jnp.reshape(target, -1)
        l = jnp.maximum(0.0, -y * (a.reshape(-1) - b.reshape(-1))
                        + self.margin)
        return _avg(jnp.sum(l), l.size, self.size_average)


class MultiCriterion(Criterion):
    """Weighted sum of criterions on the same (input, target)
    (reference nn/MultiCriterion.scala)."""

    def __init__(self):
        super().__init__()
        self.criterions: list[Criterion] = []
        self.weights: list[float] = []

    def add(self, criterion: Criterion, weight: float = 1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def apply(self, x, target):
        return sum(w * c.apply(x, target)
                   for c, w in zip(self.criterions, self.weights))


class ParallelCriterion(Criterion):
    """i-th criterion on (input[i], target[i]) weighted sum
    (reference nn/ParallelCriterion.scala; repeatTarget broadcasts)."""

    def __init__(self, repeat_target: bool = False):
        super().__init__()
        self.repeat_target = repeat_target
        self.criterions: list[Criterion] = []
        self.weights: list[float] = []

    def add(self, criterion: Criterion, weight: float = 1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def apply(self, x, target):
        total = 0.0
        for i, (c, w) in enumerate(zip(self.criterions, self.weights)):
            t = target if self.repeat_target else target[i]
            total = total + w * c.apply(x[i], t)
        return total


class MultiLabelMarginCriterion(Criterion):
    """(reference nn/MultiLabelMarginCriterion.scala; targets are 1-based
    label lists padded with 0)"""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def apply(self, x, target):
        x2 = jnp.atleast_2d(x)
        t2 = jnp.atleast_2d(target).astype(jnp.int32)
        n, c = x2.shape

        def per_sample(xi, ti):
            valid = ti > 0
            idx = jnp.clip(ti - 1, 0, c - 1)
            # padding entries scatter out-of-range and are dropped
            is_target = jnp.zeros((c,), bool).at[
                jnp.where(valid, idx, c)].set(True, mode="drop")
            tgt_scores = jnp.where(valid, xi[idx], 0.0)
            # sum over target j, non-target k of max(0, 1 - (x_j - x_k))
            margins = 1.0 - (tgt_scores[:, None] - xi[None, :])
            mask = valid[:, None] & (~is_target)[None, :]
            return jnp.sum(jnp.where(mask, jnp.maximum(margins, 0.0), 0.0)) / c

        l = jax.vmap(per_sample)(x2, t2)
        return _avg(jnp.sum(l), n, self.size_average)


class MultiLabelSoftMarginCriterion(Criterion):
    """Sigmoid + BCE per label (reference
    nn/MultiLabelSoftMarginCriterion.scala)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def apply(self, x, target):
        l = target * jax.nn.log_sigmoid(x) + \
            (1 - target) * jax.nn.log_sigmoid(-x)
        if self.weights is not None:
            l = l * self.weights
        n = x.shape[0] if x.ndim > 1 else 1
        per = -jnp.sum(l) / x.shape[-1]
        return _avg(per, n, self.size_average)


class MultiMarginCriterion(Criterion):
    """Multi-class hinge (reference nn/MultiMarginCriterion.scala)."""

    def __init__(self, p: int = 1, weights=None, margin: float = 1.0,
                 size_average: bool = True):
        super().__init__()
        self.p, self.margin, self.size_average = p, margin, size_average
        self.weights = None if weights is None else jnp.asarray(weights)

    def apply(self, x, target):
        x2 = jnp.atleast_2d(x)
        t = jnp.reshape(target, -1).astype(jnp.int32) - 1
        n, c = x2.shape
        tgt = jnp.take_along_axis(x2, t[:, None], axis=1)
        m = jnp.maximum(0.0, self.margin - tgt + x2)
        if self.p == 2:
            m = jnp.square(m)
        if self.weights is not None:
            m = m * jnp.take(self.weights, t)[:, None]
        onehot = jax.nn.one_hot(t, c, dtype=bool)
        per = jnp.sum(jnp.where(onehot, 0.0, m), axis=1) / c
        return _avg(jnp.sum(per), n, self.size_average)


class SmoothL1Criterion(Criterion):
    """Huber (reference nn/SmoothL1Criterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def apply(self, x, target):
        d = jnp.abs(x - target)
        l = jnp.where(d < 1.0, 0.5 * jnp.square(d), d - 0.5)
        return _avg(jnp.sum(l), x.size, self.size_average)


class SmoothL1CriterionWithWeights(Criterion):
    """Fast-RCNN bbox regression loss with inside/outside weights
    (reference nn/SmoothL1CriterionWithWeights.scala).

    Target is (t, inside_w, outside_w); sigma scales the transition point.
    """

    def __init__(self, sigma: float = 1.0, num: int = 0):
        super().__init__()
        self.sigma2 = sigma * sigma
        self.num = num

    def apply(self, x, target):
        t, w_in, w_out = target
        d = w_in * (x - t)
        ad = jnp.abs(d)
        l = jnp.where(ad < 1.0 / self.sigma2,
                      0.5 * self.sigma2 * jnp.square(d),
                      ad - 0.5 / self.sigma2)
        total = jnp.sum(w_out * l)
        return total / self.num if self.num > 0 else total


class SoftMarginCriterion(Criterion):
    """log(1 + exp(-y*x)) (reference nn/SoftMarginCriterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def apply(self, x, target):
        l = jnp.log1p(jnp.exp(-x * target))
        return _avg(jnp.sum(l), x.size, self.size_average)


class SoftmaxWithCriterion(Criterion):
    """Caffe-style SoftmaxWithLoss over NCHW logits with optional
    ignore_label and normalization modes (reference
    nn/SoftmaxWithCriterion.scala)."""

    def __init__(self, ignore_label: int | None = None,
                 normalize_mode: str = "valid"):
        super().__init__()
        self.ignore_label = ignore_label
        self.normalize_mode = normalize_mode

    def apply(self, x, target):
        # x: (N, C, ...); target 1-based labels (N, ...)
        logp = jax.nn.log_softmax(x, axis=1)
        t = target.astype(jnp.int32) - 1
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(jnp.clip(t, 0, x.shape[1] - 1), 1),
            axis=1).squeeze(1)
        if self.ignore_label is not None:
            mask = (target.astype(jnp.int32) != self.ignore_label)
            picked = jnp.where(mask, picked, 0.0)
            count = jnp.sum(mask)
        else:
            count = picked.size
        total = -jnp.sum(picked)
        if self.normalize_mode == "valid":
            return total / jnp.maximum(count, 1)
        if self.normalize_mode == "full":
            return total / picked.size
        if self.normalize_mode == "batch_size":
            return total / x.shape[0]
        return total  # "none"


class TimeDistributedCriterion(Criterion):
    """Apply a criterion at every timestep of (N, T, ...) input
    (reference nn/TimeDistributedCriterion.scala)."""

    def __init__(self, critrn: Criterion, size_average: bool = False):
        super().__init__()
        self.critrn = critrn
        self.size_average = size_average

    def apply(self, x, target):
        # vmap over the time axis instead of a Python loop: identical
        # per-timestep semantics for any inner criterion, but ONE fused
        # graph — the unrolled loop put T separate gathers in the HLO
        # (T=2048 made the transformer LM step 9x slower and the compile
        # pathological; docs/PERF.md)
        T = x.shape[1]
        losses = jax.vmap(self.critrn.apply, in_axes=1)(x, target)
        total = jnp.sum(losses)
        return total / T if self.size_average else total


class CriterionTable(Criterion):
    """Adapt a criterion to table input (x, target)
    (reference nn/CriterionTable.scala)."""

    def __init__(self, critrn: Criterion):
        super().__init__()
        self.critrn = critrn

    def apply(self, x, target=None):
        inp, t = x
        return self.critrn.apply(inp, t)


class MaskedCriterion(Criterion):
    """Row-validity mask around any per-sample-decomposable criterion.

    The input-pipeline's partial-batch padding
    (``dataset.prefetch.PadPartialBatches``) keeps the train step at ONE
    compiled signature by padding short batches to the full shape; this
    wrapper guarantees the padded rows contribute exactly zero to the
    loss AND its gradient: the base criterion is vmapped over the batch
    axis (each row evaluated as its own batch of one — valid for any
    criterion whose batch loss is a mean/sum of per-row terms), the
    per-row losses are multiplied by ``mask``, and the reduction honors
    the base's ``size_average`` (mean over VALID rows, or masked sum).
    """

    def __init__(self, criterion: Criterion):
        super().__init__()
        self.criterion = criterion

    def apply(self, x, target, mask):
        total, count = self.masked_sum(x, target, mask)
        if getattr(self.criterion, "size_average", True):
            return total / jnp.maximum(count, 1.0)
        return total

    def masked_sum(self, x, target, mask):
        """Unnormalized ``(masked loss sum, valid-row count)`` — the
        accumulation seam (optim/accumulation.py): gradient accumulation
        sums numerator and denominator across microbatches separately
        and divides ONCE, so a short batch split into microbatches with
        uneven valid counts still reproduces the full batch's masked
        mean exactly."""
        per_row = jax.vmap(
            lambda xi, ti: self.criterion.apply(xi[None], ti[None]))(
                x, target)
        m = mask.astype(per_row.dtype)
        return jnp.sum(per_row * m), jnp.sum(m)

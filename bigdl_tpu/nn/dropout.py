"""Regularization layers.

Reference parity: Dropout (nn/Dropout.scala:28-100 — initP=0.5, scale by
1/(1-p) in train, pass-through in eval, bernoulli noise), L1Penalty.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module

__all__ = ["Dropout", "L1Penalty"]


class Dropout(Module):
    """(reference nn/Dropout.scala)"""

    def __init__(self, init_p: float = 0.5, inplace: bool = False,
                 scale: bool = True):
        super().__init__()
        self.p = init_p
        self.scale = scale

    def set_p(self, p: float):
        self.p = p
        return self

    def apply(self, params, state, x, *, training=False, rng=None):
        if not training or self.p <= 0.0:
            return x, state
        if rng is None:
            raise ValueError("Dropout needs an rng key in training mode")
        keep = jax.random.bernoulli(rng, 1.0 - self.p, jnp.shape(x))
        y = jnp.where(keep, x, jnp.zeros_like(x))
        if self.scale:
            y = y / (1.0 - self.p)
        return y, state

    def __repr__(self):
        return f"Dropout({self.p})"


class L1Penalty(Module):
    """Identity forward that adds an L1 sparsity gradient in backward
    (reference nn/L1Penalty.scala). Implemented with a custom VJP so
    autodiff reproduces ``gradInput += l1weight * sign(input)``."""

    def __init__(self, l1weight: float, size_average: bool = False,
                 provide_output: bool = True):
        super().__init__()
        self.l1weight = l1weight
        self.size_average = size_average

    def apply(self, params, state, x, *, training=False, rng=None):
        w = self.l1weight
        if self.size_average:
            w = w / jnp.size(x)

        @jax.custom_vjp
        def pen(v):
            return v

        def fwd(v):
            return v, jnp.sign(v)

        def bwd(sign, g):
            return (g + w * sign,)

        pen.defvjp(fwd, bwd)
        return (pen(x) if training else x), state

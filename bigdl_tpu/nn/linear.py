"""Linear / embedding family.

Reference parity: Linear (nn/Linear.scala, 218 LoC), Bilinear, LookupTable
(nn/LookupTable.scala:32-105), Cosine, Euclidean, Add, CAdd, CMul, Mul, MM, MV
(all in dl/.../bigdl/nn/).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Module
from bigdl_tpu.tensor import activation_dtype, compute_dtype, default_dtype

__all__ = ["Linear", "Bilinear", "LookupTable", "Cosine", "Euclidean",
           "Add", "CAdd", "CMul", "Mul", "MM", "MV"]


class Linear(Module):
    """y = x W^T + b (reference nn/Linear.scala; default init
    stdv = 1/sqrt(inputSize))."""

    def __init__(self, input_size: int, output_size: int,
                 with_bias: bool = True,
                 init_method: str = init_mod.Default):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.init_method = init_method

    def init(self, rng):
        kw, kb = jax.random.split(rng)
        p = {"weight": init_mod.init_weight(
            self.init_method, kw, (self.output_size, self.input_size),
            fan_in=self.input_size, fan_out=self.output_size)}
        if self.with_bias:
            stdv = (1.0 / np.sqrt(self.input_size)
                    if self.init_method == init_mod.Default else 0.0)
            p["bias"] = (init_mod.uniform_reset(kb, (self.output_size,), stdv)
                         if stdv else jnp.zeros((self.output_size,),
                                                default_dtype()))
        return p

    def apply(self, params, state, x, *, training=False, rng=None):
        w = params["weight"].astype(compute_dtype())
        y = jnp.matmul(x.astype(compute_dtype()), w.T)
        if self.with_bias:
            y = y + params["bias"].astype(compute_dtype())
        return y.astype(activation_dtype()), state

    def __repr__(self):
        return f"Linear({self.input_size} -> {self.output_size})"


class Bilinear(Module):
    """y_k = x1 W_k x2^T + b_k over a table input (x1, x2)
    (reference nn/Bilinear.scala)."""

    def __init__(self, input_size1: int, input_size2: int, output_size: int,
                 bias_res: bool = True):
        super().__init__()
        self.n1, self.n2, self.n_out = input_size1, input_size2, output_size
        self.bias_res = bias_res

    def init(self, rng):
        kw, kb = jax.random.split(rng)
        stdv = 1.0 / np.sqrt(self.n1)
        p = {"weight": init_mod.uniform_reset(
            kw, (self.n_out, self.n1, self.n2), stdv)}
        if self.bias_res:
            p["bias"] = init_mod.uniform_reset(kb, (self.n_out,), stdv)
        return p

    def apply(self, params, state, x, *, training=False, rng=None):
        x1, x2 = x
        y = jnp.einsum("bi,kij,bj->bk", x1, params["weight"], x2)
        if self.bias_res:
            y = y + params["bias"]
        return y, state


class LookupTable(Module):
    """Embedding lookup (reference nn/LookupTable.scala:32-105).

    Indices are 1-based like the reference. ``padding_value`` rows embed to
    whatever is stored (the reference zeroes their gradient — autodiff does
    that automatically since a stop-gradient mask is applied), ``max_norm``
    renormalizes looked-up rows.
    """

    def __init__(self, n_index: int, n_output: int, padding_value: float = 0,
                 max_norm: float | None = None, norm_type: float = 2.0):
        super().__init__()
        self.n_index, self.n_output = n_index, n_output
        self.padding_value = int(padding_value)
        self.max_norm, self.norm_type = max_norm, norm_type

    def init(self, rng):
        return {"weight": jax.random.normal(
            rng, (self.n_index, self.n_output), default_dtype())}

    def apply(self, params, state, x, *, training=False, rng=None):
        idx = x.astype(jnp.int32) - 1  # reference is 1-based
        w = params["weight"]
        if self.max_norm is not None:
            norms = jnp.linalg.norm(w, ord=self.norm_type, axis=1,
                                    keepdims=True)
            w = w * jnp.minimum(1.0, self.max_norm / (norms + 1e-7))
        y = jnp.take(w, jnp.clip(idx, 0, self.n_index - 1), axis=0)
        if self.padding_value:
            mask = (idx != self.padding_value - 1)[..., None]
            y = jnp.where(mask, y, jax.lax.stop_gradient(y))
        return y, state


class Cosine(Module):
    """Cosine similarity vs each weight row (reference nn/Cosine.scala)."""

    def __init__(self, input_size: int, output_size: int):
        super().__init__()
        self.input_size, self.output_size = input_size, output_size

    def init(self, rng):
        stdv = 1.0 / np.sqrt(self.input_size)
        return {"weight": init_mod.uniform_reset(
            rng, (self.output_size, self.input_size), stdv)}

    def apply(self, params, state, x, *, training=False, rng=None):
        w = params["weight"]
        xn = x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-12)
        wn = w / (jnp.linalg.norm(w, axis=-1, keepdims=True) + 1e-12)
        return jnp.matmul(xn, wn.T), state


class Euclidean(Module):
    """L2 distance to each weight column (reference nn/Euclidean.scala)."""

    def __init__(self, input_size: int, output_size: int):
        super().__init__()
        self.input_size, self.output_size = input_size, output_size

    def init(self, rng):
        stdv = 1.0 / np.sqrt(self.input_size)
        return {"weight": init_mod.uniform_reset(
            rng, (self.output_size, self.input_size), stdv)}

    def apply(self, params, state, x, *, training=False, rng=None):
        diff = x[..., None, :] - params["weight"]
        return jnp.linalg.norm(diff, axis=-1), state


class Add(Module):
    """Learned bias add (reference nn/Add.scala)."""

    def __init__(self, input_size: int):
        super().__init__()
        self.input_size = input_size

    def init(self, rng):
        stdv = 1.0 / np.sqrt(self.input_size)
        return {"bias": init_mod.uniform_reset(rng, (self.input_size,), stdv)}

    def apply(self, params, state, x, *, training=False, rng=None):
        return x + params["bias"], state


class CAdd(Module):
    """Learned elementwise bias of arbitrary broadcast shape
    (reference nn/CAdd.scala)."""

    def __init__(self, size):
        super().__init__()
        self.size = tuple(size)

    def init(self, rng):
        stdv = 1.0 / np.sqrt(int(np.prod(self.size)))
        return {"bias": init_mod.uniform_reset(rng, self.size, stdv)}

    def apply(self, params, state, x, *, training=False, rng=None):
        return x + params["bias"], state


class CMul(Module):
    """Learned elementwise scale (reference nn/CMul.scala)."""

    def __init__(self, size):
        super().__init__()
        self.size = tuple(size)

    def init(self, rng):
        stdv = 1.0 / np.sqrt(int(np.prod(self.size)))
        return {"weight": init_mod.uniform_reset(rng, self.size, stdv)}

    def apply(self, params, state, x, *, training=False, rng=None):
        return x * params["weight"], state


class Mul(Module):
    """Single learned scalar scale (reference nn/Mul.scala)."""

    def init(self, rng):
        return {"weight": init_mod.uniform_reset(rng, (1,), 1.0)}

    def apply(self, params, state, x, *, training=False, rng=None):
        return x * params["weight"][0], state


class MM(Module):
    """Batch matrix-matrix product of a table (a, b)
    (reference nn/MM.scala)."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False):
        super().__init__()
        self.trans_a, self.trans_b = trans_a, trans_b

    def apply(self, params, state, x, *, training=False, rng=None):
        a, b = x
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b), state


class MV(Module):
    """Batch matrix-vector product of a table (m, v)
    (reference nn/MV.scala)."""

    def __init__(self, trans: bool = False):
        super().__init__()
        self.trans = trans

    def apply(self, params, state, x, *, training=False, rng=None):
        m, v = x
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v), state

"""Weight initialization methods.

Reference parity: nn/InitializationMethod.scala:24-47 — ``Default``,
``Xavier``, ``BilinearFiller``; the per-layer default stdv rules live in each
layer's ``reset()`` (e.g. Linear stdv = 1/sqrt(inputSize),
SpatialConvolution stdv = 1/sqrt(kW*kH*nInputPlane)).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.tensor import default_dtype

__all__ = ["Default", "Xavier", "BilinearFiller", "uniform_reset"]

Default = "default"
Xavier = "xavier"
BilinearFiller = "bilinear_filler"


def uniform_reset(rng, shape, stdv, dtype=None):
    """Torch-style reset: uniform(-stdv, stdv)."""
    return jax.random.uniform(rng, shape, dtype or default_dtype(),
                              minval=-stdv, maxval=stdv)


def init_weight(method, rng, shape, fan_in, fan_out, dtype=None):
    """Dispatch on init method (reference InitializationMethod.scala)."""
    dtype = dtype or default_dtype()
    if method == Default:
        stdv = 1.0 / np.sqrt(fan_in)
        return uniform_reset(rng, shape, stdv, dtype)
    if method == Xavier:
        limit = np.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, -limit, limit)
    if method == BilinearFiller:
        # reference SpatialFullConvolution bilinear upsampling kernel init
        assert len(shape) == 4, "BilinearFiller expects OIHW conv weights"
        _, _, kh, kw = shape
        f = np.ceil(kw / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        w = np.zeros(shape, np.float32)
        for i in range(kh):
            for j in range(kw):
                w[:, :, i, j] = (1 - abs(i / f - c)) * (1 - abs(j / f - c))
        return jnp.asarray(w, dtype)
    raise ValueError(f"unknown init method: {method}")

"""Detection helpers.

Reference parity: Nms (nn/Nms.scala — greedy non-max suppression used by
Fast-RCNN support code), alongside RoiPooling (pooling.py) and
SmoothL1CriterionWithWeights (criterion.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["Nms", "nms"]


def nms(boxes, scores, iou_threshold: float, max_output: int):
    """Greedy NMS with static output size (XLA-friendly).

    boxes: (N, 4) [x1, y1, x2, y2]; returns (indices, valid_mask) of length
    ``max_output``.
    """
    order = jnp.argsort(-scores)
    boxes = boxes[order]
    areas = (boxes[:, 2] - boxes[:, 0] + 1) * (boxes[:, 3] - boxes[:, 1] + 1)
    n = boxes.shape[0]

    def iou(i, j):
        xx1 = jnp.maximum(boxes[i, 0], boxes[j, 0])
        yy1 = jnp.maximum(boxes[i, 1], boxes[j, 1])
        xx2 = jnp.minimum(boxes[i, 2], boxes[j, 2])
        yy2 = jnp.minimum(boxes[i, 3], boxes[j, 3])
        w = jnp.maximum(0.0, xx2 - xx1 + 1)
        h = jnp.maximum(0.0, yy2 - yy1 + 1)
        inter = w * h
        return inter / (areas[i] + areas[j] - inter)

    def body(i, keep_mask):
        # suppress j>i overlapping with i if i is still kept
        js = jnp.arange(n)
        ious = jax.vmap(lambda j: iou(i, j))(js)
        suppress = (ious > iou_threshold) & (js > i) & keep_mask[i]
        return jnp.where(suppress, False, keep_mask)

    keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    kept_sorted = jnp.nonzero(keep, size=max_output, fill_value=-1)[0]
    valid = kept_sorted >= 0
    return jnp.where(valid, order[jnp.clip(kept_sorted, 0)], -1), valid


class Nms:
    """Object-style wrapper matching the reference's Nms API."""

    def __init__(self, iou_threshold: float = 0.3, max_output: int = 100):
        self.iou_threshold = iou_threshold
        self.max_output = max_output

    def __call__(self, boxes, scores):
        return nms(boxes, scores, self.iou_threshold, self.max_output)

"""Core module protocol: pure init/apply with a Torch-style stateful facade.

Reference parity:
- ``AbstractModule[A,B,T]`` (nn/abstractnn/AbstractModule.scala:40-323):
  forward/backward, cached output/gradInput, parameters(), getParameters()
  flat view, train/eval mode, per-module forward/backward wall-clock.
- ``Activity`` = Tensor | Table (nn/abstractnn/Activity.scala:25-44): here any
  JAX pytree (array, tuple/list/dict) is a valid activity.
- ``Container`` (nn/Container.scala:29-138): recursive composite.

TPU-first design: the reference mutates per-module ``output``/``gradInput``
buffers and hand-writes every backward pass. Here every module is a *pure
function pair*::

    params          = module.init(rng)                  # parameter pytree
    state           = module.init_state()               # running stats etc.
    y, new_state    = module.apply(params, state, x, training=..., rng=...)

which is what ``jax.jit`` / ``jax.grad`` / ``pjit`` consume — backward passes
come from autodiff, op parallelism from XLA (the reference's intra-op
``Engine.model.invoke`` threading, SURVEY §2.3, intentionally has no
equivalent here). The Torch-style stateful API (``forward``/``backward``/
``zero_grad_parameters``/``update_parameters``) is a thin facade over the pure
core so reference users keep their mental model and layer-level tests can be
written exactly like the reference's nn specs.
"""
from __future__ import annotations

import copy
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.tensor import flatten_params

__all__ = ["Module", "Container", "Criterion", "Identity", "Echo"]


def _fold(rng, i: int):
    return None if rng is None else jax.random.fold_in(rng, i)


class Module:
    """Base class of all layers (reference AbstractModule.scala:40)."""

    def __init__(self):
        self.training_mode: bool = True
        # cached activities (reference AbstractModule.scala:48-53)
        self.output: Any = None
        self.grad_input: Any = None
        # materialized state for the stateful facade
        self.params: Any = None
        self.state: Any = None
        self.grad_params: Any = None
        # per-module timing (reference AbstractModule.scala:124-135)
        self.forward_time: float = 0.0
        self.backward_time: float = 0.0
        self._name: Optional[str] = None
        self._rng = None

    # ------------------------------------------------------------------
    # pure protocol — subclasses override
    # ------------------------------------------------------------------
    def init(self, rng) -> Any:
        """Create the parameter pytree (dict of arrays; {} when
        parameterless)."""
        return {}

    def init_state(self) -> Any:
        """Create the non-trainable state pytree (e.g. BN running stats)."""
        return {}

    def apply(self, params, state, x, *, training: bool = False, rng=None):
        """Pure forward. Returns ``(output, new_state)``."""
        raise NotImplementedError

    # ------------------------------------------------------------------
    # stateful Torch-style facade (reference AbstractModule forward/backward)
    # ------------------------------------------------------------------
    def materialize(self, rng=None):
        """Instantiate ``self.params`` / ``self.state`` (idempotent)."""
        if self.params is None:
            if rng is None:
                rng = jax.random.PRNGKey(0)
            self._rng = rng
            self.params = self.init(rng)
            self.state = self.init_state()
            self.grad_params = jax.tree.map(jnp.zeros_like, self.params)
        return self

    #: when True (default), ``forward``/``backward`` bracket their timers
    #: with ``jax.block_until_ready`` so ``get_times()`` reports true
    #: wall time like the reference's ``getTimes()``
    #: (AbstractModule.scala:124-135), not async dispatch time. Set False
    #: to keep the facade fully asynchronous (then the times are
    #: dispatch-only; use ``Optimizer.set_profiler`` for device truth).
    #: NOTE: through this container's axon tunnel block_until_ready is a
    #: no-op — on that backend only the profiler gives per-op truth.
    sync_times: bool = True

    def forward(self, x, rng=None):
        """Timed stateful forward (reference AbstractModule.scala:144-150)."""
        self.materialize()
        if Module.sync_times:
            jax.block_until_ready(x)   # charge upstream work upstream
        t0 = time.perf_counter()
        if rng is None and self._rng is not None:
            self._rng, rng = jax.random.split(self._rng)
        self._forward_rng = rng  # reused by backward for identical masks
        self.output, self.state = self.apply(
            self.params, self.state, x, training=self.training_mode, rng=rng)
        if Module.sync_times:
            jax.block_until_ready(self.output)
        self.forward_time += time.perf_counter() - t0
        return self.output

    __call__ = forward

    def backward(self, x, grad_output, rng=None):
        """Stateful backward via autodiff (reference
        AbstractModule.scala:162-169).

        Computes grad wrt input (returned, like ``updateGradInput``) and
        *accumulates* parameter grads (like ``accGradParameters``).
        Stochastic layers (Dropout/RReLU) replay the SAME rng the preceding
        ``forward`` consumed so masks match between passes.
        """
        self.materialize()
        if rng is None:
            rng = getattr(self, "_forward_rng", None)
        if Module.sync_times:
            jax.block_until_ready((x, grad_output))
        t0 = time.perf_counter()

        def f(params, inp):
            y, _ = self.apply(params, self.state, inp,
                              training=self.training_mode, rng=rng)
            return y

        _, vjp = jax.vjp(f, self.params, x)
        d_params, d_input = vjp(grad_output)
        self.grad_params = jax.tree.map(jnp.add, self.grad_params, d_params)
        self.grad_input = d_input
        if Module.sync_times:
            jax.block_until_ready((self.grad_params, d_input))
        self.backward_time += time.perf_counter() - t0
        return self.grad_input

    # ------------------------------------------------------------------
    # parameter access (reference AbstractModule.scala:216-242)
    # ------------------------------------------------------------------
    def parameters(self):
        """(params, grad_params) pytrees (reference ``parameters()``)."""
        self.materialize()
        return self.params, self.grad_params

    def get_parameters(self):
        """Flat (weights, grads) vectors (reference ``getParameters()`` /
        Module.flatten, nn/Module.scala:41-69)."""
        p, g = self.parameters()
        fp, _ = flatten_params(p)
        fg, _ = flatten_params(g)
        return fp, fg

    def get_parameters_table(self):
        """name -> {weight, bias, ...} mapping for Caffe/Torch import
        (reference AbstractModule.scala:242)."""
        name = self.get_name()
        p, _ = self.parameters()
        return {name: p} if p else {}

    def set_parameters(self, params):
        self.params = params
        if self.grad_params is None or jax.tree.structure(
                self.grad_params) != jax.tree.structure(params):
            self.grad_params = jax.tree.map(jnp.zeros_like, params)
        return self

    def sync(self, params, state=None):
        """Point this module (and any children) at new params/state trees.

        Training loops donate the old parameter buffers to the jitted step
        (XLA updates weights in place in HBM); this rebinds the module
        facade to the live arrays afterwards.
        """
        self.params = params
        if state is not None:
            self.state = state
        return self

    def zero_grad_parameters(self):
        self.materialize()
        self.grad_params = jax.tree.map(jnp.zeros_like, self.grad_params)

    def update_parameters(self, lr: float):
        self.params = jax.tree.map(lambda p, g: p - lr * g,
                                   self.params, self.grad_params)

    # ------------------------------------------------------------------
    # modes, naming, timing, cloning (reference AbstractModule.scala:247-323)
    # ------------------------------------------------------------------
    def training(self):
        self.training_mode = True
        return self

    def evaluate(self):
        self.training_mode = False
        return self

    def is_training(self) -> bool:
        return self.training_mode

    def set_name(self, name: str):
        self._name = name
        return self

    def set_init_method(self, method: str):
        """Chainable init-method override (reference ``setInitMethod``).

        Must be called before ``materialize`` — init_method is only read
        when parameters are created."""
        if self.params is not None:
            raise RuntimeError(
                "set_init_method after materialize has no effect; call it "
                "before the first forward/materialize")
        self.init_method = method
        return self

    def get_name(self) -> str:
        return self._name or f"{type(self).__name__}@{id(self):x}"

    def get_times(self):
        """[(module, forward_s, backward_s)] (reference ``getTimes()``,
        AbstractModule.scala:124-135).

        With ``Module.sync_times`` (default True) the facade
        ``forward``/``backward`` bracket their timers with
        ``block_until_ready``, so these are true wall times on standard
        backends. Children of a Container accumulate only when their own
        ``forward`` is invoked — the Container's pure ``apply`` chain is
        jit-compiled and cannot host per-child syncs; use
        ``Optimizer.set_profiler`` for per-op device truth under jit."""
        return [(self, self.forward_time, self.backward_time)]

    def reset_times(self):
        self.forward_time = 0.0
        self.backward_time = 0.0

    def clear_state(self):
        self.output = None
        self.grad_input = None
        return self

    def clone_module(self):
        """Deep copy (reference ``cloneModule()``, Java serialization)."""
        return copy.deepcopy(self)

    def save(self, path: str, overwrite: bool = False):
        from bigdl_tpu.utils import file as _file
        _file.save_module(self, path, overwrite=overwrite)
        return self

    def save_torch(self, path: str, overwrite: bool = False):
        """Export as a Torch .t7 file (reference AbstractModule.saveTorch,
        :311-315)."""
        from bigdl_tpu.utils import torchfile
        torchfile.save_torch(self, path, overwrite)
        return self

    @staticmethod
    def load_torch(path: str):
        """(reference Module.loadTorch, nn/Module.scala:31-33)"""
        from bigdl_tpu.utils import torchfile
        return torchfile.load_torch(path)

    @staticmethod
    def load_caffe(model, def_path: str, model_path: str,
                   match_all: bool = True):
        """(reference Module.loadCaffe, nn/Module.scala:35-39)"""
        from bigdl_tpu.utils.caffe import load_caffe
        return load_caffe(model, def_path, model_path, match_all)

    def __repr__(self):
        return f"{type(self).__name__}()"


class Container(Module):
    """Composite module (reference nn/Container.scala:29-138).

    Child params/state are pytrees keyed by the child's position:
    ``{"0": ...}``.
    """

    def __init__(self, *modules: Module):
        super().__init__()
        self.modules: list[Module] = list(modules)

    def add(self, module: Module):
        self.modules.append(module)
        return self

    def __len__(self):
        return len(self.modules)

    def __getitem__(self, i):
        return self.modules[i]

    def init(self, rng):
        return {str(i): m.init(_fold(rng, i))
                for i, m in enumerate(self.modules)}

    def init_state(self):
        return {str(i): m.init_state() for i, m in enumerate(self.modules)}

    # containers recurse (reference Container.scala:71-78)
    def training(self):
        super().training()
        for m in self.modules:
            m.training()
        return self

    def evaluate(self):
        super().evaluate()
        for m in self.modules:
            m.evaluate()
        return self

    def get_times(self):
        """Timing rows: the container's own row first, then children.

        DEVIATION from reference Container.getTimes (Container.scala:71-73,
        children only): under jit the container facade's forward time covers
        the whole compiled chain while children read zero, so the self row
        is the only signal in the common path. It is emitted only when
        nonzero, and a summing aggregator that also forwards children
        individually should filter rows with ``isinstance(m, Container)``
        to avoid double counting."""
        out = ([(self, self.forward_time, self.backward_time)]
               if (self.forward_time or self.backward_time) else [])
        for m in self.modules:
            out.extend(m.get_times())
        return out

    def reset_times(self):
        super().reset_times()
        for m in self.modules:
            m.reset_times()

    def get_parameters_table(self):
        out = {}
        for m in self.modules:
            out.update(m.get_parameters_table())
        return out

    def sync(self, params, state=None):
        super().sync(params, state)
        for i, m in enumerate(self.modules):
            m.sync(params[str(i)],
                   None if state is None else state[str(i)])
        return self

    def materialize(self, rng=None):
        # keep child facades usable on their own AND consistent with ours
        if self.params is None:
            if rng is None:
                rng = jax.random.PRNGKey(0)
            self._rng = rng
            for i, m in enumerate(self.modules):
                m.materialize(_fold(rng, i))
            self.params = {str(i): m.params
                           for i, m in enumerate(self.modules)}
            self.state = {str(i): m.state for i, m in enumerate(self.modules)}
            self.grad_params = jax.tree.map(jnp.zeros_like, self.params)
        return self

    def __repr__(self):
        inner = "\n".join(f"  ({i}): {m!r}"
                          for i, m in enumerate(self.modules))
        return f"{type(self).__name__}(\n{inner}\n)"


class Criterion:
    """Loss base (reference AbstractCriterion,
    nn/abstractnn/AbstractCriterion.scala:29-75).

    Pure protocol: ``loss = criterion.apply(input, target)`` (scalar).
    Stateful facade: ``forward`` caches output; ``backward`` returns
    d loss / d input via autodiff.
    """

    size_average: bool = True

    def __init__(self):
        self.output = None
        self.grad_input = None

    def apply(self, x, target):
        raise NotImplementedError

    def forward(self, x, target):
        self.output = self.apply(x, target)
        return self.output

    __call__ = forward

    def backward(self, x, target):
        self.grad_input = jax.grad(lambda inp: self.apply(inp, target))(x)
        return self.grad_input

    def clone_criterion(self):
        return copy.deepcopy(self)

    def __repr__(self):
        return f"{type(self).__name__}()"


class Identity(Module):
    """Pass-through (reference nn/Identity.scala)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        return x, state


class Echo(Module):
    """Print activation shape then pass through (reference nn/Echo.scala)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        jax.debug.print("Echo: shape={s}", s=jnp.shape(x))
        return x, state

"""Convolution family (NCHW, matching the reference's Torch layout).

Reference parity: SpatialConvolution (nn/SpatialConvolution.scala, 579 LoC —
im2col + GEMM with per-sample ``Engine.model.invoke`` threading and a 1x1
fast path), SpatialShareConvolution, SpatialFullConvolution,
SpatialDilatedConvolution, SpatialConvolutionMap.

TPU-first: no im2col — ``lax.conv_general_dilated`` lowers straight onto the
MXU with XLA picking the layout; groups map to ``feature_group_count``; the
reference's intra-op threading and shared im2col buffers (optnet) have no
equivalent because XLA owns scheduling and buffer reuse.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Module
from bigdl_tpu.tensor import activation_dtype, compute_dtype, default_dtype

__all__ = ["SpatialConvolution", "SpatialShareConvolution",
           "SpatialFullConvolution", "SpatialDilatedConvolution",
           "SpatialConvolutionMap"]

_DIMS = ("NCHW", "OIHW", "NCHW")


class SpatialConvolution(Module):
    """2-D convolution (reference nn/SpatialConvolution.scala).

    Weight shape (nOutputPlane, nInputPlane/nGroup, kH, kW); default init
    stdv = 1/sqrt(kW*kH*nInputPlane) (reference ``reset()``).
    """

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 n_group: int = 1, propagate_back: bool = True,
                 init_method: str = init_mod.Default,
                 with_bias: bool = True):
        super().__init__()
        assert n_input_plane % n_group == 0
        assert n_output_plane % n_group == 0
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kw, self.kh = kernel_w, kernel_h
        self.dw, self.dh = stride_w, stride_h
        self.pw, self.ph = pad_w, pad_h
        self.n_group = n_group
        self.propagate_back = propagate_back
        self.init_method = init_method
        self.with_bias = with_bias

    def init(self, rng):
        kw_, kb_ = jax.random.split(rng)
        fan_in = self.kw * self.kh * self.n_input_plane
        fan_out = self.kw * self.kh * self.n_output_plane
        shape = (self.n_output_plane, self.n_input_plane // self.n_group,
                 self.kh, self.kw)
        p = {"weight": init_mod.init_weight(self.init_method, kw_, shape,
                                            fan_in=fan_in, fan_out=fan_out)}
        if self.with_bias:
            if self.init_method == init_mod.Default:
                stdv = 1.0 / np.sqrt(fan_in)
                p["bias"] = init_mod.uniform_reset(kb_, (self.n_output_plane,),
                                                   stdv)
            else:
                p["bias"] = jnp.zeros((self.n_output_plane,), default_dtype())
        return p

    def apply(self, params, state, x, *, training=False, rng=None):
        squeeze = x.ndim == 3
        if squeeze:  # reference accepts 3-D (C,H,W) input
            x = x[None]
        if not self.propagate_back:
            # cut d loss / d input at this layer (reference
            # SpatialConvolution propagateBack=false)
            x = jax.lax.stop_gradient(x)
        w = params["weight"].astype(compute_dtype())
        y = jax.lax.conv_general_dilated(
            x.astype(compute_dtype()), w,
            window_strides=(self.dh, self.dw),
            padding=[(self.ph, self.ph), (self.pw, self.pw)],
            dimension_numbers=_DIMS,
            feature_group_count=self.n_group)
        if self.with_bias:
            y = y + params["bias"].astype(compute_dtype())[None, :, None, None]
        y = y.astype(activation_dtype())
        if squeeze:
            y = y[0]
        return y, state

    def __repr__(self):
        return (f"SpatialConvolution({self.n_input_plane} -> "
                f"{self.n_output_plane}, {self.kw}x{self.kh}, "
                f"{self.dw},{self.dh}, {self.pw},{self.ph})")


class SpatialShareConvolution(SpatialConvolution):
    """Reference variant sharing im2col buffers across layers
    (nn/SpatialShareConvolution.scala). Identical math — XLA already shares
    scratch, so this is an alias kept for API parity."""


class SpatialDilatedConvolution(SpatialConvolution):
    """Atrous convolution (reference nn/SpatialDilatedConvolution.scala)."""

    def __init__(self, n_input_plane, n_output_plane, kw, kh,
                 dw: int = 1, dh: int = 1, pad_w: int = 0, pad_h: int = 0,
                 dilation_w: int = 1, dilation_h: int = 1,
                 init_method: str = init_mod.Default):
        super().__init__(n_input_plane, n_output_plane, kw, kh, dw, dh,
                         pad_w, pad_h, init_method=init_method)
        self.dil_w, self.dil_h = dilation_w, dilation_h

    def apply(self, params, state, x, *, training=False, rng=None):
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        y = jax.lax.conv_general_dilated(
            x.astype(compute_dtype()),
            params["weight"].astype(compute_dtype()),
            window_strides=(self.dh, self.dw),
            padding=[(self.ph, self.ph), (self.pw, self.pw)],
            rhs_dilation=(self.dil_h, self.dil_w),
            dimension_numbers=_DIMS)
        if self.with_bias:
            y = y + params["bias"].astype(compute_dtype())[None, :, None, None]
        y = y.astype(activation_dtype())
        if squeeze:
            y = y[0]
        return y, state


class SpatialFullConvolution(Module):
    """Transposed convolution (reference nn/SpatialFullConvolution.scala;
    supports ``adj`` output padding and BilinearFiller init for upsampling).

    Weight shape (nInputPlane, nOutputPlane, kH, kW) like Torch.
    """

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 adj_w: int = 0, adj_h: int = 0,
                 n_group: int = 1, no_bias: bool = False,
                 init_method: str = init_mod.Default):
        super().__init__()
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.kw, self.kh, self.dw, self.dh = kw, kh, dw, dh
        self.pw, self.ph, self.aw, self.ah = pad_w, pad_h, adj_w, adj_h
        self.n_group = n_group
        self.with_bias = not no_bias
        self.init_method = init_method

    def init(self, rng):
        kw_, kb_ = jax.random.split(rng)
        fan_in = self.kw * self.kh * self.n_input_plane
        shape = (self.n_input_plane, self.n_output_plane // self.n_group,
                 self.kh, self.kw)
        if self.init_method == init_mod.BilinearFiller:
            w = init_mod.init_weight(self.init_method, kw_, shape, fan_in,
                                     fan_in)
        else:
            stdv = 1.0 / np.sqrt(fan_in)
            w = init_mod.uniform_reset(kw_, shape, stdv)
        p = {"weight": w}
        if self.with_bias:
            stdv = 1.0 / np.sqrt(fan_in)
            p["bias"] = init_mod.uniform_reset(kb_, (self.n_output_plane,),
                                               stdv)
        return p

    def apply(self, params, state, x, *, training=False, rng=None):
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        # transposed conv = lhs-dilated conv with flipped kernel
        w = params["weight"].astype(compute_dtype())  # (I, O/g, kh, kw)
        w = jnp.flip(w, axis=(-1, -2))
        # regroup (I, O/g) -> OIHW (O, I/g) keeping group blocks aligned
        g = self.n_group
        I, Og, kh, kw = w.shape
        w = w.reshape(g, I // g, Og, kh, kw)
        w = jnp.swapaxes(w, 1, 2)  # (g, O/g, I/g, kh, kw)
        w = w.reshape(g * Og, I // g, kh, kw)
        pad_h = self.kh - 1 - self.ph
        pad_w = self.kw - 1 - self.pw
        y = jax.lax.conv_general_dilated(
            x.astype(compute_dtype()), w,
            window_strides=(1, 1),
            padding=[(pad_h, pad_h + self.ah), (pad_w, pad_w + self.aw)],
            lhs_dilation=(self.dh, self.dw),
            dimension_numbers=_DIMS,
            feature_group_count=self.n_group)
        if self.with_bias:
            y = y + params["bias"].astype(compute_dtype())[None, :, None, None]
        y = y.astype(activation_dtype())
        if squeeze:
            y = y[0]
        return y, state


class SpatialConvolutionMap(Module):
    """Convolution with an explicit input->output connection table
    (reference nn/SpatialConvolutionMap.scala). ``conn_table`` is an (n, 2)
    int array of 1-based (input_plane, output_plane) pairs."""

    def __init__(self, conn_table, kw: int, kh: int, dw: int = 1,
                 dh: int = 1, pad_w: int = 0, pad_h: int = 0):
        super().__init__()
        self.conn_table = np.asarray(conn_table, np.int32)
        self.kw, self.kh, self.dw, self.dh = kw, kh, dw, dh
        self.pw, self.ph = pad_w, pad_h
        self.n_input_plane = int(self.conn_table[:, 0].max())
        self.n_output_plane = int(self.conn_table[:, 1].max())

    @staticmethod
    def full(n_in: int, n_out: int):
        """Full connection table (reference SpatialConvolutionMap.full)."""
        return np.stack(np.meshgrid(np.arange(1, n_in + 1),
                                    np.arange(1, n_out + 1)),
                        axis=-1).reshape(-1, 2)

    @staticmethod
    def one_to_one(n: int):
        idx = np.arange(1, n + 1)
        return np.stack([idx, idx], axis=-1)

    def init(self, rng):
        kw_, kb_ = jax.random.split(rng)
        n_conn = len(self.conn_table)
        stdv = 1.0 / np.sqrt(self.kw * self.kh * n_conn / self.n_output_plane)
        return {"weight": init_mod.uniform_reset(
                    kw_, (n_conn, 1, self.kh, self.kw), stdv),
                "bias": init_mod.uniform_reset(kb_, (self.n_output_plane,),
                                               stdv)}

    def apply(self, params, state, x, *, training=False, rng=None):
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        # build a dense masked OIHW kernel; XLA folds the scatter at compile
        dense = jnp.zeros((self.n_output_plane, self.n_input_plane,
                           self.kh, self.kw), params["weight"].dtype)
        o = self.conn_table[:, 1] - 1
        i = self.conn_table[:, 0] - 1
        dense = dense.at[o, i].set(params["weight"][:, 0])
        y = jax.lax.conv_general_dilated(
            x.astype(compute_dtype()), dense.astype(compute_dtype()),
            window_strides=(self.dh, self.dw),
            padding=[(self.ph, self.ph), (self.pw, self.pw)],
            dimension_numbers=_DIMS)
        y = y + params["bias"].astype(compute_dtype())[None, :, None, None]
        y = y.astype(activation_dtype())
        if squeeze:
            y = y[0]
        return y, state

"""Table (tuple) arithmetic modules.

Reference parity (all in dl/.../bigdl/nn/): CAddTable, CSubTable, CMulTable,
CDivTable, CMaxTable, CMinTable, DotProduct, PairwiseDistance,
CosineDistance, CriterionTable mirror.
"""
from __future__ import annotations

import jax.numpy as jnp
from functools import reduce

from bigdl_tpu.nn.module import Module

__all__ = ["CAddTable", "CSubTable", "CMulTable", "CDivTable", "CMaxTable",
           "CMinTable", "DotProduct", "PairwiseDistance", "CosineDistance",
           "MixtureTable", "MaskedSelect"]


class CAddTable(Module):
    """(reference nn/CAddTable.scala)"""

    def __init__(self, inplace: bool = False):
        super().__init__()

    def apply(self, params, state, x, *, training=False, rng=None):
        return reduce(jnp.add, x), state


class CSubTable(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return x[0] - x[1], state


class CMulTable(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return reduce(jnp.multiply, x), state


class CDivTable(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return x[0] / x[1], state


class CMaxTable(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return reduce(jnp.maximum, x), state


class CMinTable(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return reduce(jnp.minimum, x), state


class DotProduct(Module):
    """Row-wise dot product of (a, b) (reference nn/DotProduct.scala)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        a, b = x
        return jnp.sum(a * b, axis=-1), state


class PairwiseDistance(Module):
    """Row-wise Lp distance (reference nn/PairwiseDistance.scala)."""

    def __init__(self, norm: int = 2):
        super().__init__()
        self.norm = norm

    def apply(self, params, state, x, *, training=False, rng=None):
        a, b = x
        d = jnp.power(jnp.sum(jnp.power(jnp.abs(a - b), self.norm), axis=-1),
                      1.0 / self.norm)
        return d, state


class CosineDistance(Module):
    """Row-wise cosine similarity (reference nn/CosineDistance.scala)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        a, b = x
        an = jnp.linalg.norm(a, axis=-1)
        bn = jnp.linalg.norm(b, axis=-1)
        return jnp.sum(a * b, axis=-1) / jnp.maximum(an * bn, 1e-12), state


class MixtureTable(Module):
    """Mixture-of-experts blend of a (gater, experts) table
    (reference nn/MixtureTable.scala:37-80).

    ``experts`` may be a table of E tensors (batch, ...) — blended with
    gater (batch, E) — or a single stacked tensor whose axis ``dim``
    indexes the experts. Unbatched 1-D gaters work like the reference's
    single-example path.
    """

    def __init__(self, dim: int | None = None):
        super().__init__()
        self.dim = dim

    def apply(self, params, state, x, *, training=False, rng=None):
        gater, experts = x[0], x[1]
        batched = gater.ndim >= 2
        if isinstance(experts, (tuple, list)):
            out = None
            for e, expert in enumerate(experts):
                g = gater[:, e] if batched else gater[e]
                shape = (g.shape + (1,) * (expert.ndim - g.ndim)
                         if batched else ())
                term = expert * (g.reshape(shape) if batched else g)
                out = term if out is None else out + term
            return out, state
        # stacked experts tensor: mix along self.dim (1-based like the
        # reference; default = first non-batch axis)
        dim = (self.dim - 1) if self.dim is not None else (1 if batched
                                                          else 0)
        e_count = experts.shape[dim]
        shape = [1] * experts.ndim
        if batched:
            shape[0] = gater.shape[0]
        shape[dim] = e_count
        g = gater.reshape(shape)
        return jnp.sum(experts * g, axis=dim), state


class MaskedSelect(Module):
    """torch.maskedSelect over a (tensor, mask) table
    (reference nn/MaskedSelect.scala:33-66).

    The output length depends on the mask VALUES, so this module is
    eager-only: calling it inside ``jit`` raises XLA's dynamic-shape
    error. Inside compiled code, multiply by the mask (static shape)
    instead; this module exists for API parity and host-side use.
    """

    def apply(self, params, state, x, *, training=False, rng=None):
        t, mask = x[0], x[1]
        return t[mask.astype(bool)], state

"""Table (tuple) arithmetic modules.

Reference parity (all in dl/.../bigdl/nn/): CAddTable, CSubTable, CMulTable,
CDivTable, CMaxTable, CMinTable, DotProduct, PairwiseDistance,
CosineDistance, CriterionTable mirror.
"""
from __future__ import annotations

import jax.numpy as jnp
from functools import reduce

from bigdl_tpu.nn.module import Module

__all__ = ["CAddTable", "CSubTable", "CMulTable", "CDivTable", "CMaxTable",
           "CMinTable", "DotProduct", "PairwiseDistance", "CosineDistance"]


class CAddTable(Module):
    """(reference nn/CAddTable.scala)"""

    def __init__(self, inplace: bool = False):
        super().__init__()

    def apply(self, params, state, x, *, training=False, rng=None):
        return reduce(jnp.add, x), state


class CSubTable(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return x[0] - x[1], state


class CMulTable(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return reduce(jnp.multiply, x), state


class CDivTable(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return x[0] / x[1], state


class CMaxTable(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return reduce(jnp.maximum, x), state


class CMinTable(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return reduce(jnp.minimum, x), state


class DotProduct(Module):
    """Row-wise dot product of (a, b) (reference nn/DotProduct.scala)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        a, b = x
        return jnp.sum(a * b, axis=-1), state


class PairwiseDistance(Module):
    """Row-wise Lp distance (reference nn/PairwiseDistance.scala)."""

    def __init__(self, norm: int = 2):
        super().__init__()
        self.norm = norm

    def apply(self, params, state, x, *, training=False, rng=None):
        a, b = x
        d = jnp.power(jnp.sum(jnp.power(jnp.abs(a - b), self.norm), axis=-1),
                      1.0 / self.norm)
        return d, state


class CosineDistance(Module):
    """Row-wise cosine similarity (reference nn/CosineDistance.scala)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        a, b = x
        an = jnp.linalg.norm(a, axis=-1)
        bn = jnp.linalg.norm(b, axis=-1)
        return jnp.sum(a * b, axis=-1) / jnp.maximum(an * bn, 1e-12), state

"""Activation modules.

Reference parity (all in dl/.../bigdl/nn/): ReLU, ReLU6, PReLU, RReLU,
LeakyReLU, ELU, Tanh, TanhShrink, Sigmoid, LogSigmoid, SoftMax, SoftMin,
LogSoftMax, SoftPlus, SoftSign, HardTanh, HardShrink, SoftShrink, Threshold,
Clamp, Power, Sqrt, Square, Abs, Log, Exp, GradientReversal, Scale.
The reference threads several of these over ``Engine.model.invoke``
(SURVEY §2.3); here XLA fuses them into neighbouring ops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module
from bigdl_tpu.tensor import default_dtype

__all__ = ["ReLU", "ReLU6", "PReLU", "RReLU", "LeakyReLU", "ELU", "Tanh",
           "TanhShrink", "Sigmoid", "LogSigmoid", "SoftMax", "SoftMin",
           "LogSoftMax", "SoftPlus", "SoftSign", "HardTanh", "HardShrink",
           "SoftShrink", "Threshold", "Clamp", "Power", "Sqrt", "Square",
           "Abs", "Log", "Exp", "GradientReversal", "Scale",
           "MulConstant", "AddConstant"]


class _Elementwise(Module):
    """Parameterless elementwise activation."""

    def fn(self, x):
        raise NotImplementedError

    def apply(self, params, state, x, *, training=False, rng=None):
        return self.fn(x), state


class Threshold(_Elementwise):
    """x > threshold ? x : value (reference nn/Threshold.scala; supports
    in-place in the reference — meaningless under XLA)."""

    def __init__(self, threshold: float = 1e-6, value: float = 0.0,
                 ip: bool = False):
        super().__init__()
        self.th, self.value = threshold, value

    def fn(self, x):
        return jnp.where(x > self.th, x, jnp.asarray(self.value, x.dtype))


class ReLU(Threshold):
    """(reference nn/ReLU.scala: Threshold(0, 0))"""

    def __init__(self, ip: bool = False):
        super().__init__(0.0, 0.0)

    def fn(self, x):
        return jax.nn.relu(x)


class ReLU6(_Elementwise):
    def fn(self, x):
        return jnp.clip(x, 0.0, 6.0)


class PReLU(Module):
    """Learned negative slope, shared or per-channel
    (reference nn/PReLU.scala; nOutputPlane=0 → single shared slope)."""

    def __init__(self, n_output_plane: int = 0):
        super().__init__()
        self.n_output_plane = n_output_plane

    def init(self, rng):
        n = max(self.n_output_plane, 1)
        return {"weight": jnp.full((n,), 0.25, default_dtype())}

    def apply(self, params, state, x, *, training=False, rng=None):
        w = params["weight"]
        if self.n_output_plane > 0:
            # channel axis is 1 for NCHW activations, -1 for (N, C)
            shape = [1] * x.ndim
            shape[1 if x.ndim > 2 else -1] = self.n_output_plane
            w = w.reshape(shape)
        return jnp.where(x >= 0, x, w * x), state


class RReLU(Module):
    """Randomized leaky ReLU (reference nn/RReLU.scala): slope ~ U(lower,
    upper) in training, (lower+upper)/2 in eval."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3,
                 ip: bool = False):
        super().__init__()
        self.lower, self.upper = lower, upper

    def apply(self, params, state, x, *, training=False, rng=None):
        if training:
            if rng is None:
                raise ValueError("RReLU needs an rng key in training mode")
            a = jax.random.uniform(rng, x.shape, x.dtype,
                                   self.lower, self.upper)
        else:
            a = jnp.asarray((self.lower + self.upper) / 2, x.dtype)
        return jnp.where(x >= 0, x, a * x), state


class LeakyReLU(_Elementwise):
    def __init__(self, negval: float = 0.01, ip: bool = False):
        super().__init__()
        self.negval = negval

    def fn(self, x):
        return jnp.where(x >= 0, x, self.negval * x)


class ELU(_Elementwise):
    def __init__(self, alpha: float = 1.0, ip: bool = False):
        super().__init__()
        self.alpha = alpha

    def fn(self, x):
        return jnp.where(x > 0, x, self.alpha * jnp.expm1(x))


class Tanh(_Elementwise):
    fn = staticmethod(jnp.tanh)


class TanhShrink(_Elementwise):
    def fn(self, x):
        return x - jnp.tanh(x)


class Sigmoid(_Elementwise):
    fn = staticmethod(jax.nn.sigmoid)


class LogSigmoid(_Elementwise):
    fn = staticmethod(jax.nn.log_sigmoid)


class SoftMax(_Elementwise):
    """Softmax over the feature axis (reference nn/SoftMax.scala, threaded;
    last axis here). Exponent/sum in f32 regardless of activation dtype."""

    def fn(self, x):
        f32 = jnp.promote_types(x.dtype, jnp.float32)
        return jax.nn.softmax(x.astype(f32), axis=-1).astype(x.dtype)


class SoftMin(_Elementwise):
    def fn(self, x):
        f32 = jnp.promote_types(x.dtype, jnp.float32)
        return jax.nn.softmax(-x.astype(f32), axis=-1).astype(x.dtype)


class LogSoftMax(_Elementwise):
    """(reference nn/LogSoftMax.scala, threaded per-sample).

    Always computed and returned in f32: log-probabilities are the one
    activation whose absolute accuracy feeds the loss directly, and the
    tensor is tiny (N x classes).
    """

    def fn(self, x):
        return jax.nn.log_softmax(x.astype(
            jnp.promote_types(x.dtype, jnp.float32)), axis=-1)


class SoftPlus(_Elementwise):
    def __init__(self, beta: float = 1.0):
        super().__init__()
        self.beta = beta

    def fn(self, x):
        return jax.nn.softplus(self.beta * x) / self.beta


class SoftSign(_Elementwise):
    def fn(self, x):
        return x / (1.0 + jnp.abs(x))


class HardTanh(_Elementwise):
    """(reference nn/HardTanh.scala, threaded)"""

    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 ip: bool = False):
        super().__init__()
        self.min_value, self.max_value = min_value, max_value

    def fn(self, x):
        return jnp.clip(x, self.min_value, self.max_value)


class Clamp(HardTanh):
    """(reference nn/Clamp.scala: HardTanh with int bounds)"""

    def __init__(self, min_value: int, max_value: int):
        super().__init__(float(min_value), float(max_value))


class HardShrink(_Elementwise):
    def __init__(self, lambd: float = 0.5):
        super().__init__()
        self.lambd = lambd

    def fn(self, x):
        return jnp.where(jnp.abs(x) > self.lambd, x,
                         jnp.zeros_like(x))


class SoftShrink(_Elementwise):
    def __init__(self, lambd: float = 0.5):
        super().__init__()
        self.lambd = lambd

    def fn(self, x):
        return jnp.sign(x) * jnp.maximum(jnp.abs(x) - self.lambd, 0.0)


class Power(_Elementwise):
    """(shift + scale * x)^power (reference nn/Power.scala)"""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0):
        super().__init__()
        self.power, self.scale, self.shift = power, scale, shift

    def fn(self, x):
        return jnp.power(self.shift + self.scale * x, self.power)


class Sqrt(_Elementwise):
    fn = staticmethod(jnp.sqrt)


class Square(_Elementwise):
    fn = staticmethod(jnp.square)


class Abs(_Elementwise):
    fn = staticmethod(jnp.abs)


class Log(_Elementwise):
    fn = staticmethod(jnp.log)


class Exp(_Elementwise):
    fn = staticmethod(jnp.exp)


class GradientReversal(_Elementwise):
    """Identity forward, -lambda * grad backward
    (reference nn/GradientReversal.scala)."""

    def __init__(self, lambd: float = 1.0):
        super().__init__()
        self.lambd = lambd

    def fn(self, x):
        lam = self.lambd

        @jax.custom_vjp
        def rev(v):
            return v

        def fwd(v):
            return v, None

        def bwd(_, g):
            return (-lam * g,)

        rev.defvjp(fwd, bwd)
        return rev(x)


class Scale(Module):
    """cmul + cadd by learned per-channel weight/bias
    (reference nn/Scale.scala)."""

    def __init__(self, size):
        super().__init__()
        self.size = tuple(size)

    def init(self, rng):
        return {"weight": jnp.ones(self.size, default_dtype()),
                "bias": jnp.zeros(self.size, default_dtype())}

    def apply(self, params, state, x, *, training=False, rng=None):
        return x * params["weight"] + params["bias"], state


class MulConstant(Module):
    """Multiply by a fixed scalar (reference nn/MulConstant.scala; used by
    ResNet shortcut type A zero-padding branch,
    models/resnet/ResNet.scala:142-148)."""

    def __init__(self, constant_scalar: float, inplace: bool = False):
        super().__init__()
        self.constant = constant_scalar

    def apply(self, params, state, x, *, training=False, rng=None):
        return x * self.constant, state


class AddConstant(Module):
    """Add a fixed scalar (reference nn/AddConstant.scala)."""

    def __init__(self, constant_scalar: float, inplace: bool = False):
        super().__init__()
        self.constant = constant_scalar

    def apply(self, params, state, x, *, training=False, rng=None):
        return x + self.constant, state

from bigdl_tpu.examples.loadmodel.dataset_util import (
    AlexNetPreprocessor, InceptionPreprocessor, ResNetPreprocessor)

"""Validation-set preprocessors for imported models (reference
example/loadmodel/DatasetUtil.scala:18-80).

Each builds: image-folder paths -> decode/resize -> normalize -> center
crop -> NCHW batches, with the published per-model recipes.
"""
from __future__ import annotations

from pathlib import Path

import numpy as np

from bigdl_tpu.dataset.dataset import LocalArrayDataSet
from bigdl_tpu.dataset.image import (BGRImgCropper, BGRImgNormalizer,
                                     BGRImgPixelNormalizer, BGRImgToBatch,
                                     CropCenter, LocalImageFiles,
                                     LocalImgReader)

__all__ = ["AlexNetPreprocessor", "InceptionPreprocessor",
           "ResNetPreprocessor"]


def _paths_dataset(source):
    """``source``: a class-per-subfolder tree path, or a pre-built list of
    (path, label) pairs (unlabeled flows pass label 0.0)."""
    if isinstance(source, (str, Path)):
        return LocalArrayDataSet(LocalImageFiles.paths(str(source)))
    return LocalArrayDataSet(list(source))


def AlexNetPreprocessor(path: str, batch_size: int, mean_file: str):
    """227 center crop over exact 256x256 resize, per-pixel mean subtract,
    raw 0-255 pixel range (reference DatasetUtil.scala:28-42)."""
    means = np.load(mean_file)
    return (_paths_dataset(str(path))
            >> LocalImgReader((256, 256), normalize=1.0)
            >> BGRImgPixelNormalizer(means)
            >> BGRImgCropper(227, 227, CropCenter)
            >> BGRImgToBatch(batch_size))


def InceptionPreprocessor(path: str, batch_size: int):
    """224 center crop, mean (123,117,104) subtract, raw pixel range
    (reference DatasetUtil.scala:45-59)."""
    return (_paths_dataset(str(path))
            >> LocalImgReader((256, 256), normalize=1.0)
            >> BGRImgCropper(224, 224, CropCenter)
            >> BGRImgNormalizer(123, 117, 104, 1, 1, 1)
            >> BGRImgToBatch(batch_size))


def ResNetPreprocessor(source, batch_size: int):
    """Shorter-side-256 resize, 224 center crop, ImageNet mean/std on [0,1]
    pixels (reference DatasetUtil.scala:62-80). ``source``: folder tree or
    (path, label) pairs — the single shared definition of this recipe."""
    return (_paths_dataset(source)
            >> LocalImgReader(256)
            >> BGRImgCropper(224, 224, CropCenter)
            >> BGRImgNormalizer(0.485, 0.456, 0.406, 0.229, 0.224, 0.225)
            >> BGRImgToBatch(batch_size))

"""ModelValidator — load an imported (Caffe/Torch/BigDL) model and test it
over an ImageNet-style validation folder.

Reference parity: example/loadmodel/ModelValidator.scala — model-type
dispatch (caffe: alexnet/inception; torch: resnet; bigdl: any snapshot),
per-model preprocessor, Top1+Top5 over a Validator.

Run::

    python -m bigdl_tpu.examples.loadmodel.model_validator \
        -t caffe -m alexnet --caffeDefPath deploy.prototxt \
        --modelPath bvlc_alexnet.caffemodel --meanFile mean.npy -f <dir>

``-f`` points at a folder with a ``val/`` class-per-subfolder tree.
"""
from __future__ import annotations

import argparse
import logging
from pathlib import Path

logger = logging.getLogger("bigdl_tpu.examples.loadmodel")

__all__ = ["build_model_and_data", "main"]


def build_model_and_data(args):
    """Model-type dispatch (reference ModelValidator.scala:125-147)."""
    from bigdl_tpu.examples.loadmodel.dataset_util import (
        AlexNetPreprocessor, InceptionPreprocessor, ResNetPreprocessor)

    val_path = str(Path(args.folder) / "val")
    name = args.modelName.lower()
    mtype = args.modelType.lower()
    if mtype == "caffe":
        from bigdl_tpu.utils.caffe import load_caffe
        if name == "alexnet":
            from bigdl_tpu.models.alexnet import AlexNet
            model = load_caffe(AlexNet(1000), args.caffeDefPath,
                               args.modelPath)
            data = AlexNetPreprocessor(val_path, args.batchSize,
                                       args.meanFile)
        elif name == "inception":
            from bigdl_tpu.models.inception import Inception_v1_NoAuxClassifier
            model = load_caffe(Inception_v1_NoAuxClassifier(1000),
                               args.caffeDefPath, args.modelPath)
            data = InceptionPreprocessor(val_path, args.batchSize)
        elif name == "resnet":
            from bigdl_tpu.models.resnet import ResNet
            model = load_caffe(
                ResNet(1000, {"depth": args.depth, "shortcutType": "B",
                              "dataset": "imagenet"}),
                args.caffeDefPath, args.modelPath, match_all=False)
            data = ResNetPreprocessor(val_path, args.batchSize)
        else:
            raise ValueError(
                "caffe type supports alexnet/inception/resnet, got " + name)
    elif mtype == "torch":
        from bigdl_tpu.utils.torchfile import load_torch
        model = load_torch(args.modelPath)
        data = ResNetPreprocessor(val_path, args.batchSize)
    elif mtype == "bigdl":
        from bigdl_tpu.utils import file as bfile
        model = bfile.load_module(args.modelPath)
        data = ResNetPreprocessor(val_path, args.batchSize)
    else:
        raise ValueError("only torch, caffe or bigdl supported")
    return model, data


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser("bigdl_tpu Image Classifier Example")
    p.add_argument("-f", "--folder", default="./",
                   help="folder holding the val/ image tree")
    p.add_argument("-m", "--modelName", required=True,
                   help="alexnet | inception | resnet")
    p.add_argument("-t", "--modelType", required=True,
                   help="torch | caffe | bigdl")
    p.add_argument("--caffeDefPath", default=None)
    p.add_argument("--modelPath", default="")
    p.add_argument("-b", "--batchSize", type=int, default=32)
    p.add_argument("--meanFile", default=None,
                   help=".npy per-pixel mean (alexnet)")
    p.add_argument("--depth", type=int, default=50,
                   help="resnet depth for caffe resnet import")
    args = p.parse_args(argv)

    from bigdl_tpu.optim import Top1Accuracy, Top5Accuracy, Validator

    model, data = build_model_and_data(args)
    print(model)
    validator = Validator(model, data)
    results = validator.test([Top1Accuracy(), Top5Accuracy()])
    for res, method in results:
        logger.info("%s is %s", method, res)
    return results


if __name__ == "__main__":
    main()

"""Integrated example apps (reference dl/.../bigdl/example/ — SURVEY §2.10):
textclassification, imageclassification, loadmodel."""

"""Text classification on 20 Newsgroups with pre-trained GloVe vectors.

Reference parity: example/textclassification/TextClassifier.scala:40-230 +
SimpleTokenizer (TextTransformer.scala:18-80) — the BASELINE tracked config
#5 proof that the stack composes: ~90% accuracy after a few epochs with the
published recipe (GloVe-100d vectorization -> 3x[conv5 + maxpool] CNN ->
Linear(128,100) -> Linear(100, classNum), Adagrad lr 0.01 decay 2e-4).

TPU-first notes: the reference vectorizes on Spark executors and trains
batch 128 through DistriOptimizer; here vectorization is host numpy and the
model trains through the jitted Local/Distri optimizer path. The conv stack
is NCHW (B, embedding, 1, seq) exactly like the reference's
SpatialConvolution usage, so the MXU sees a dense 2-D conv.

Run::

    python -m bigdl_tpu.examples.textclassification.text_classifier \
        --baseDir <dir>     # containing 20_newsgroup/ and glove.6B/
"""
from __future__ import annotations

import argparse
import logging
import os
import re
from collections import Counter
from pathlib import Path

import numpy as np

logger = logging.getLogger("bigdl_tpu.examples.textclassification")

__all__ = ["TextClassifier", "build_model", "to_tokens", "shaping",
           "vectorization"]


# ---------------------------------------------------------------------------
# SimpleTokenizer (reference TextTransformer.scala:18-80)
# ---------------------------------------------------------------------------

def to_tokens(text: str) -> list[str]:
    """Split on non-letters, lowercase, keep tokens longer than 2 chars."""
    return [t for t in re.sub("[^a-zA-Z]", " ", text).lower().split()
            if len(t) > 2]


def shaping(tokens: list, sequence_len: int, trunc: str = "pre") -> list:
    """Pad with 0 / truncate (``pre`` keeps the tail) to sequence_len."""
    if len(tokens) > sequence_len:
        return (tokens[-sequence_len:] if trunc == "pre"
                else tokens[:sequence_len])
    return list(tokens) + [0] * (sequence_len - len(tokens))


def vectorization(indices: list, embedding_dim: int,
                  word2vec: dict) -> np.ndarray:
    """Index sequence -> (seq_len, embedding_dim); unknown words are
    zero vectors."""
    out = np.zeros((len(indices), embedding_dim), np.float32)
    for i, w in enumerate(indices):
        vec = word2vec.get(w)
        if vec is not None:
            out[i] = vec
    return out


# ---------------------------------------------------------------------------
# model (reference TextClassifier.buildModel, :122-144)
# ---------------------------------------------------------------------------

def build_model(class_num: int, embedding_dim: int = 100,
                sequence_len: int = 1000):
    from bigdl_tpu.nn import (Linear, LogSoftMax, ReLU, Reshape, Sequential,
                              SpatialConvolution, SpatialMaxPooling)
    # pool sizes follow the reference for seq_len 1000; for shorter test
    # sequences scale the final catch-all pool to whatever length remains
    l1 = (sequence_len - 4) // 5          # after conv5 + pool5
    l2 = (l1 - 4) // 5                    # after second conv5 + pool5
    l3 = l2 - 4                           # after third conv5
    model = Sequential()
    model.add(Reshape((embedding_dim, 1, sequence_len), batch_mode=True))
    model.add(SpatialConvolution(embedding_dim, 128, 5, 1))
    model.add(ReLU())
    model.add(SpatialMaxPooling(5, 1, 5, 1))
    model.add(SpatialConvolution(128, 128, 5, 1))
    model.add(ReLU())
    model.add(SpatialMaxPooling(5, 1, 5, 1))
    model.add(SpatialConvolution(128, 128, 5, 1))
    model.add(ReLU())
    model.add(SpatialMaxPooling(l3, 1, l3, 1))
    model.add(Reshape((128,), batch_mode=True))
    model.add(Linear(128, 100))
    model.add(Linear(100, class_num))
    model.add(LogSoftMax())
    return model


# ---------------------------------------------------------------------------
# the example driver (reference TextClassifier class)
# ---------------------------------------------------------------------------

class TextClassifier:
    def __init__(self, base_dir: str, max_sequence_length: int = 1000,
                 max_words_num: int = 20000, training_split: float = 0.8,
                 batch_size: int = 128, embedding_dim: int = 100,
                 drop_top_words: int = 10):
        self.base_dir = base_dir
        self.glove_dir = os.path.join(base_dir, "glove.6B")
        self.text_dir = os.path.join(base_dir, "20_newsgroup")
        self.max_sequence_length = max_sequence_length
        self.max_words_num = max_words_num
        self.training_split = training_split
        self.batch_size = batch_size
        self.embedding_dim = embedding_dim
        self.drop_top_words = drop_top_words
        self.class_num = -1

    def load_raw_data(self) -> list[tuple[str, float]]:
        """Category-per-subfolder tree of digit-named files
        (reference :72-97)."""
        out = []
        categories = sorted(p for p in Path(self.text_dir).iterdir()
                            if p.is_dir())
        for label, cat in enumerate(categories, start=1):
            for f in sorted(p for p in cat.iterdir()
                            if p.is_file() and p.name.isdigit()):
                out.append((f.read_text(encoding="ISO-8859-1",
                                        errors="replace"), float(label)))
        self.class_num = len(categories)
        logger.info("Found %d texts across %d classes", len(out),
                    self.class_num)
        return out

    def analyze_texts(self, data: list[tuple[str, float]]):
        """Frequency-rank the vocabulary, drop the ~10 most frequent words,
        keep max_words_num (reference :103-117); then index the GloVe
        vectors for the kept words (reference buildWord2Vec, :44-60)."""
        freq = Counter()
        for text, _ in data:
            freq.update(to_tokens(text))
        ranked = freq.most_common()[self.drop_top_words:self.max_words_num]
        word2index = {w: i + 1 for i, (w, _) in enumerate(ranked)}
        word2vec = {}
        glove_path = os.path.join(self.glove_dir,
                                  f"glove.6B.{self.embedding_dim}d.txt")
        with open(glove_path, encoding="ISO-8859-1") as f:
            for line in f:
                values = line.rstrip().split(" ")
                idx = word2index.get(values[0])
                if idx is not None:
                    word2vec[idx] = np.asarray(values[1:], np.float32)
        logger.info("Found %d word vectors of %d indexed words",
                    len(word2vec), len(word2index))
        return word2index, word2vec

    def make_samples(self, data, word2index, word2vec):
        from bigdl_tpu.dataset.sample import Sample
        samples = []
        for text, label in data:
            idxs = [word2index[t] for t in to_tokens(text)
                    if t in word2index]
            idxs = shaping(idxs, self.max_sequence_length)
            feat = vectorization(idxs, self.embedding_dim, word2vec)
            # (seq, emb) -> (emb, seq), the reference's transpose(1,2)
            samples.append(Sample(feat.T.copy(), label))
        return samples

    def train(self, max_epoch: int = 20, mesh=None):
        from bigdl_tpu import nn
        from bigdl_tpu.dataset import array, SampleToBatch
        from bigdl_tpu.optim import (Adagrad, Optimizer, Top1Accuracy,
                                     every_epoch, max_epoch as max_epoch_t)
        from bigdl_tpu.utils.random import RandomGenerator

        data = self.load_raw_data()
        word2index, word2vec = self.analyze_texts(data)
        samples = self.make_samples(data, word2index, word2vec)
        RandomGenerator.RNG().shuffle(samples)
        split = int(len(samples) * self.training_split)
        train_set = array(samples[:split]) >> SampleToBatch(
            self.batch_size, drop_remainder=True)
        val_set = array(samples[split:] or samples[:1]) >> SampleToBatch(
            self.batch_size)

        model = build_model(self.class_num, self.embedding_dim,
                            self.max_sequence_length)
        optimizer = Optimizer(model, train_set, nn.ClassNLLCriterion(),
                              mesh=mesh)
        # reference state: lr 0.01, decay 0.0002, Adagrad (:178-186)
        optimizer.set_optim_method(
            Adagrad(learning_rate=0.01, learning_rate_decay=0.0002))
        optimizer.set_validation(every_epoch(), val_set, [Top1Accuracy()])
        optimizer.set_end_when(max_epoch_t(max_epoch))
        trained = optimizer.optimize()
        return trained, optimizer


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser("bigdl_tpu text classification")
    p.add_argument("-b", "--baseDir", required=True,
                   help="dir containing 20_newsgroup/ and glove.6B/")
    p.add_argument("--maxSequenceLength", type=int, default=1000)
    p.add_argument("--maxWordsNum", type=int, default=20000)
    p.add_argument("--trainingSplit", type=float, default=0.8)
    p.add_argument("--batchSize", type=int, default=128)
    p.add_argument("--embeddingDim", type=int, default=100)
    p.add_argument("-e", "--maxEpoch", type=int, default=20)
    args = p.parse_args(argv)
    tc = TextClassifier(args.baseDir, args.maxSequenceLength,
                        args.maxWordsNum, args.trainingSplit,
                        args.batchSize, args.embeddingDim)
    tc.train(max_epoch=args.maxEpoch)


if __name__ == "__main__":
    main()

from bigdl_tpu.examples.textclassification.text_classifier import (
    TextClassifier, build_model, to_tokens, shaping, vectorization)

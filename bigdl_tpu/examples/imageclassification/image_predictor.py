"""ImagePredictor — batch image classification with a trained model.

Reference parity: example/imageclassification/ImagePredictor.scala — the
DLClassifier inference showcase: read an image folder (no labels), run the
published ResNet-style preprocessing, predict a class per image, print the
first ``--showNum`` (imageName, predict) pairs.

The DataFrame + DLClassifier machinery maps to the Predictor API: the
ModelBroadcast role is mesh params replication, the batched forward+argmax
is Predictor.predict_class.

Run::

    python -m bigdl_tpu.examples.imageclassification.image_predictor \
        --modelPath model.bigdl -f <image_folder> [--showNum 100]
"""
from __future__ import annotations

import argparse
import logging
from pathlib import Path


logger = logging.getLogger("bigdl_tpu.examples.imageclassification")

__all__ = ["main", "predict_folder"]


def predict_folder(model, folder: str, batch_size: int = 32, mesh=None):
    """Returns [(image_name, predicted_class)] for every image file; the
    preprocessing recipe is the shared ResNetPreprocessor definition."""
    from bigdl_tpu.examples.loadmodel.dataset_util import ResNetPreprocessor
    from bigdl_tpu.optim import Predictor

    paths = sorted(str(p) for p in Path(folder).iterdir() if p.is_file())
    pairs = [(p, 0.0) for p in paths]   # hasLabel=false (reference :66)
    ds = ResNetPreprocessor(pairs, batch_size)
    classes = Predictor(model, batch_size, mesh=mesh).predict_class(ds)
    return list(zip((Path(p).name for p in paths), classes.tolist()))


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser("Predict with trained model")
    p.add_argument("-f", "--folder", required=True,
                   help="image folder (flat, no labels)")
    p.add_argument("--modelPath", required=True)
    p.add_argument("-b", "--batchSize", type=int, default=32)
    p.add_argument("--showNum", type=int, default=100)
    args = p.parse_args(argv)

    from bigdl_tpu.utils import file as bfile

    model = bfile.load_module(args.modelPath)
    results = predict_folder(model, args.folder, args.batchSize)
    for name, cls in results[:args.showNum]:
        print(f"[{name},{cls}]")
    return results


if __name__ == "__main__":
    main()

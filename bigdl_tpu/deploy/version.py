"""Versioned weight sets: what the publisher loads, qualifies, rolls.

One :class:`WeightManifest` is one READY-TO-SERVE weight set — a host
module loaded from a manifest-committed elastic checkpoint
(``bigdl_tpu/elastic/``), stamped with the version string that tags
every replica serving it and every KV snapshot exported under it (the
``weight_version`` plumbed through ``ContinuousBatcher`` /
``KVSnapshot`` / the router). The version is derived from the
checkpoint's ``neval`` — monotone by construction, because the trainer
only ever commits forward.

``quantize=True`` is the int8-at-rest conversion
(``serving/quantized.py``): the candidate params pass through
``quantize_params`` -> ``dequantize_params`` once, so the fleet serves
exactly the weights an int8 artifact would reconstruct — parity between
the canary and the rolled fleet is then parity of ONE weight tree, not
of two quantization passes. ``quantize_params``'s idempotence guard
keeps a second accidental conversion loud.

HOST-ONLY CONTRACT (jaxlint JX5): no module-level jax import — the
publisher thread must construct in supervisors that never initialize a
device runtime; jax enters only via the lazy checkpoint/quantize calls.
"""
from __future__ import annotations

__all__ = ["WeightManifest", "load_weight_version",
           "write_model_checkpoint", "version_string"]


def version_string(neval: int) -> str:
    """The canonical version tag for a checkpoint: ``v<neval>``."""
    return f"v{int(neval)}"


class WeightManifest:
    """One versioned, ready-to-serve weight set (see module
    docstring). ``model`` is the live host module every replica of this
    version shares read-only; ``manifest`` is the checkpoint manifest
    it was committed under (None for a fleet's synthetic baseline
    version)."""

    __slots__ = ("version", "neval", "epoch", "source", "model",
                 "quantized", "manifest")

    def __init__(self, version: str, model, *, neval: int = -1,
                 epoch: int = 0, source: str | None = None,
                 quantized: bool = False, manifest: dict | None = None):
        self.version = str(version)
        self.model = model
        self.neval = int(neval)
        self.epoch = int(epoch)
        self.source = source
        self.quantized = bool(quantized)
        self.manifest = manifest

    def param_bytes(self) -> int:
        """Total bytes of the served parameter leaves."""
        import jax
        return sum(int(getattr(l, "nbytes", 0))
                   for l in jax.tree_util.tree_leaves(self.model.params))

    def __repr__(self):
        return (f"WeightManifest({self.version!r}, neval={self.neval}, "
                f"quantized={self.quantized}, source={self.source!r})")


def load_weight_version(path: str, *, neval: int | None = None,
                        quantize: bool = False) -> WeightManifest:
    """Load one committed checkpoint into a :class:`WeightManifest`.

    ``neval=None`` takes the newest manifest under ``path``
    (:func:`~bigdl_tpu.elastic.latest_checkpoint` — only COMPLETE
    snapshots are ever eligible; the manifest is the commit point). The
    module is switched to evaluate mode (serving never wants dropout)
    and, with ``quantize=True``, its params are round-tripped through
    the int8 codec so the fleet serves the int8-at-rest
    reconstruction."""
    from bigdl_tpu.elastic import load_checkpoint
    model, _state, man = load_checkpoint(path, neval=neval)
    model.evaluate()
    quantized = False
    if quantize:
        from bigdl_tpu.serving.quantized import (dequantize_params,
                                                 quantize_params)
        model.params = dequantize_params(quantize_params(model.params))
        model.sync(model.params, model.state)
        quantized = True
    return WeightManifest(version_string(man["neval"]), model,
                          neval=int(man["neval"]),
                          epoch=int(man.get("epoch", 0)),
                          source=str(path), quantized=quantized,
                          manifest=man)


def write_model_checkpoint(path: str, model, *, neval: int,
                           epoch: int = 0) -> str:
    """Commit a model-only checkpoint in the elastic three-file format
    (``model.N`` + ``state.N`` + ``manifest.N.json``, manifest LAST) —
    what a trainer's ``set_checkpoint`` produces, minus optimizer
    state. The publisher's drills and an offline conversion pipeline
    (e.g. a quantized export) publish through this. Returns the
    manifest path."""
    import os

    from bigdl_tpu.elastic import (build_manifest, manifest_name,
                                   write_manifest)
    from bigdl_tpu.elastic.checkpoint_writer import snapshot_to_host
    from bigdl_tpu.utils import file as _file
    _file.ensure_writable_dir(path)
    suffix = f".{int(neval)}"
    model_file, state_file = f"model{suffix}", f"state{suffix}"
    _file.save_module(model, os.path.join(path, model_file),
                      overwrite=True)
    _file.save({"neval": int(neval), "epoch": int(epoch)},
               os.path.join(path, state_file), overwrite=True)
    man = build_manifest(neval=int(neval), epoch=int(epoch),
                         model_file=model_file, state_file=state_file,
                         params=snapshot_to_host(model.params))
    man_path = os.path.join(path, manifest_name(suffix))
    write_manifest(man, man_path)
    return man_path

"""bigdl_tpu.deploy — continuous deployment into the serving fleet.

The control plane that closes the train-to-serve loop (ROADMAP item 1;
arXiv:1804.05839's one-cluster pipeline): a trainer keeps committing
elastic checkpoints, the fleet keeps serving, and the
:class:`WeightPublisher` thread carries each new commit into production
with zero downtime — warm canary qualification, replica-by-replica
rollout with version-tagged in-flight migration, automatic rollback.
Three modules:

- ``version``   — :class:`WeightManifest`, the versioned ready-to-serve
  weight set loaded from a manifest-committed checkpoint (optionally
  through the int8 round-trip), plus the checkpoint-writing helper
  drills and offline converters publish through.
- ``canary``    — :func:`qualify`: pinned-prompt parity + latency SLO +
  zero-compile gates over a quarantined warm replica;
  :class:`ShadowTap` mirrors live traffic for output agreement.
- ``publisher`` — :class:`WeightPublisher`, the poll -> load -> canary
  -> roll -> (rollback) loop, with ``publisher_*`` metrics, trace
  instants, flight-recorder events and a liveness check.

Quick start::

    pub = WeightPublisher(router, "ckpts/", config=PublisherConfig(
        CanaryConfig(prompts=[(pinned_prompt, expected_tokens)],
                     slo=SLOConfig(), require_zero_compiles=True)))
    pub.start()            # rolls every newer checkpoint the trainer
    ...                    # commits; pub.history_snapshot() has the
    pub.close()            # outcomes, pub.serving the live manifest

HOST-ONLY CONTRACT: nothing in this package imports jax at module top
level (jaxlint JX5) — deployment is host orchestration; device work
happens inside the batchers the pool owns. docs/DEPLOYMENT.md covers
architecture, qualification gates, version-skew semantics and the
rollback runbook.
"""
from bigdl_tpu.deploy.canary import (CanaryConfig, CanaryReport,
                                     ShadowTap, qualify, replay)
from bigdl_tpu.deploy.publisher import (PublisherConfig, PublishReport,
                                        WeightPublisher)
from bigdl_tpu.deploy.version import (WeightManifest,
                                      load_weight_version,
                                      version_string,
                                      write_model_checkpoint)

__all__ = ["CanaryConfig", "CanaryReport", "ShadowTap", "qualify",
           "replay", "PublisherConfig", "PublishReport",
           "WeightPublisher", "WeightManifest", "load_weight_version",
           "version_string", "write_model_checkpoint"]

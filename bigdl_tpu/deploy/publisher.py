"""WeightPublisher: continuous deployment for the serving fleet.

The control loop that closes BigDL's train-to-serve story (ROADMAP
item 1; arXiv:1804.05839's one-cluster pipeline, BigDL 2.0's
production framing in arXiv:2204.01715): a trainer keeps committing
checkpoints (``elastic/`` — manifest written LAST, so torn snapshots
are never eligible) while the fleet keeps serving, and this thread
carries each new commit into production with zero downtime:

1. **poll** ``latest_checkpoint(dir)`` every few seconds (the
   mtime+size fast path re-parses only changed manifests);
2. **load** the new weights into a versioned
   :class:`~bigdl_tpu.deploy.version.WeightManifest` (optionally
   through the int8 round-trip);
3. **canary**: quarantine a name at the router, spin it up WARM on the
   candidate weights (``pool.add_replica(warm=True, model=...)`` —
   zero compiles off the shared AOT cache), and qualify it:
   pinned-prompt parity + latency SLO + optional live-traffic
   shadowing (``deploy/canary.py``);
4. **roll** the fleet replica by replica on pass:
   ``router.drain(name, policy=...)`` (each in-flight request either
   finishes on the old weights or migrates its KV — bitwise — to an
   old-version survivor) -> ``Replica.set_weights`` -> ``resume``;
5. **rollback** on any failure: a failed canary never touches the
   fleet, and a mid-rollout error or SLO breach re-installs the prior
   version on every already-rolled replica before the publisher
   reports — the fleet is never left partially downgraded.

Version-skew contract (docs/DEPLOYMENT.md): every replica and every
exported KV snapshot carries a ``weight_version``; the router only
places a snapshot on a matching replica, the batcher re-validates on
adopt, and a snapshot whose version no longer exists anywhere restarts
from its prompt — every request completes exactly once, attributable
to exactly one version.

Observability: ``publisher_*`` metrics, ``publish``-kind trace
instants and flight-recorder events, a ``weight_publisher`` liveness
check, and a bounded ``history`` of publish outcomes (the postmortem
log).

HOST-ONLY CONTRACT (jaxlint JX5): no module-level jax import; device
work happens inside the batchers the pool already owns.

Lock order (enforced by dev/analysis/raceguard.py TS1): the
publisher's ``_mu`` is a leaf — it guards only the poll-thread state
(``current``/``history``/``_last_poll``) and is never held while
calling into the router or a replica; the rollout path (drain ->
set_weights -> resume) runs entirely lock-free on the poll thread.
"""
# raceguard: order weightpublisher.mu < state_lock < replica.lock
from __future__ import annotations

import logging
import threading
import time
from collections import deque

from bigdl_tpu.deploy.canary import CanaryConfig, ShadowTap, qualify
from bigdl_tpu.deploy.version import (WeightManifest,
                                      load_weight_version,
                                      version_string)
from bigdl_tpu.observability import trace
from bigdl_tpu.observability.exporter import default_health
from bigdl_tpu.observability.registry import default_registry
from bigdl_tpu.serving.autoscaler import _delta_snapshot
from bigdl_tpu.serving.slo import percentile

__all__ = ["PublisherConfig", "PublishReport", "WeightPublisher"]

logger = logging.getLogger(__name__)


class _RollbackSignal(Exception):
    """Internal: a qualification-style failure DURING the rollout."""


class PublisherConfig:
    """Knobs for one :class:`WeightPublisher`.

    - ``canary``: the qualification gates
      (:class:`~bigdl_tpu.deploy.canary.CanaryConfig`).
    - ``poll_interval_s``: checkpoint-directory poll cadence.
    - ``quantize``: publish the int8-at-rest reconstruction of each
      checkpoint instead of raw f32 (``serving/quantized.py``).
    - ``canary_name``: the quarantined replica name the canary uses.
    - ``slo``: when set, a mid-rollout SLO watch — after each replica
      swap the ROLLOUT-WINDOW fleet p99s (histogram deltas since the
      rollout began) are checked against these targets and a breach
      triggers rollback.
    - ``migrate_policy``: ``policy(request_id) -> "finish"|"migrate"``
      for in-flight requests on a draining replica (None = all finish
      on the old weights). "migrate" exports the KV mid-decode to an
      old-version survivor (bitwise continuation); the publisher forces
      "finish" when no survivor of that version remains.
    - ``drain_timeout_s`` / ``liveness_grace_s``: drain budget per
      replica; how stale the poll loop may go before the
      ``weight_publisher`` health check flips.
    """

    def __init__(self, canary: CanaryConfig | None = None, *,
                 poll_interval_s: float = 2.0, quantize: bool = False,
                 canary_name: str = "canary", slo=None,
                 migrate_policy=None, drain_timeout_s: float = 60.0,
                 liveness_grace_s: float = 30.0):
        self.canary = canary if canary is not None else CanaryConfig()
        self.poll_interval_s = float(poll_interval_s)
        self.quantize = bool(quantize)
        self.canary_name = str(canary_name)
        self.slo = slo
        self.migrate_policy = migrate_policy
        self.drain_timeout_s = float(drain_timeout_s)
        self.liveness_grace_s = float(liveness_grace_s)


class PublishReport:
    """What one publish attempt did — the ``history`` entry."""

    __slots__ = ("outcome", "version", "neval", "canary", "rolled",
                 "rolled_back", "duration_s", "error")

    def __init__(self, outcome, version, neval, *, canary=None,
                 rolled=(), rolled_back=(), duration_s=0.0,
                 error=None):
        self.outcome = outcome        # ok|canary_failed|rolled_back|error
        self.version = version
        self.neval = int(neval)
        self.canary = canary          # CanaryReport | None
        self.rolled = list(rolled)
        self.rolled_back = list(rolled_back)
        self.duration_s = float(duration_s)
        self.error = error

    def as_dict(self) -> dict:
        return {"outcome": self.outcome, "version": self.version,
                "neval": self.neval,
                "canary": (self.canary.as_dict()
                           if self.canary is not None else None),
                "rolled": list(self.rolled),
                "rolled_back": list(self.rolled_back),
                "duration_s": self.duration_s,
                "error": self.error}

    def __repr__(self):
        return (f"PublishReport({self.outcome!r}, {self.version!r}, "
                f"rolled={self.rolled}, duration_s="
                f"{self.duration_s:.3f})")


class WeightPublisher:
    """See module docstring. ``router`` fronts the pool being rolled;
    ``checkpoint_dir`` is the trainer's commit directory.

    The fleet's CURRENT version at construction: the newest manifest
    already under ``checkpoint_dir`` is assumed to be what the fleet
    was started from (the operator loaded it to build the pool) and
    becomes the baseline — only NEWER commits publish. No manifest
    means an unversioned fleet, stamped ``v0``. Every existing replica
    that carries no version is stamped with the baseline so snapshot
    version checks bite from the first publish on.

    ``start()``/``close()`` run the poll loop on a daemon thread;
    ``poll_once()`` runs one iteration synchronously (tests, drills,
    and supervisors that already own a loop)."""

    def __init__(self, router, checkpoint_dir: str, *,
                 config: PublisherConfig | None = None, registry=None,
                 health=None, recorder=None):
        # local import: elastic.manifest is host-only too, but keep the
        # publisher constructible without the elastic package loaded
        from bigdl_tpu.elastic.manifest import latest_checkpoint
        self._latest_checkpoint = latest_checkpoint
        self.router = router
        self.pool = router.pool
        self.checkpoint_dir = str(checkpoint_dir)
        self.config = config if config is not None else PublisherConfig()
        self._poll_cache: dict = {}
        self.history: deque = deque(maxlen=64)

        reg = default_registry() if registry is None else registry
        self._m_polls = reg.counter(
            "publisher_polls_total",
            "checkpoint-directory polls (fast path included)")
        self._m_publishes = reg.counter(
            "publisher_publishes_total",
            "publish attempts by outcome",
            labelnames=("outcome",))
        self._m_rollbacks = reg.counter(
            "publisher_rollbacks_total",
            "publishes that rolled the fleet back to the prior version")
        self._m_rolled = reg.counter(
            "publisher_replicas_rolled_total",
            "replica weight swaps performed (rollbacks included)")
        self._g_neval = reg.gauge(
            "publisher_current_neval",
            "checkpoint neval the fleet currently serves")
        self._g_inprog = reg.gauge(
            "publisher_rollout_in_progress",
            "1 while a canary/rollout is running")

        self._recorder = recorder
        self._health = health if health is not None else default_health()
        self._health.register("weight_publisher", self._alive,
                              kind="liveness")

        # baseline: what the fleet already serves (docstring)
        man = self._latest_checkpoint(self.checkpoint_dir,
                                      cache=self._poll_cache)
        neval = -1 if man is None else int(man["neval"])
        version = "v0" if man is None else version_string(neval)
        self.current = WeightManifest(version, self.pool.model,
                                      neval=neval,
                                      source=self.checkpoint_dir,
                                      manifest=man)
        for rep in self.pool:
            if rep.weight_version is None:
                rep.set_weights(weight_version=version)
        self.pool.set_default_model(self.pool.model,
                                    weight_version=version)
        self._g_neval.set(neval)

        self._stop = False
        self._started = False
        # _mu guards the state the poll thread writes and other
        # threads read: ``current``, ``history``, ``_last_poll``. It
        # is a leaf lock — never held across router/replica calls
        # (see the "raceguard: order" declaration at module top).
        self._mu = threading.Lock()
        self._last_poll = time.monotonic()
        self._wake = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="bigdl-weight-publisher", daemon=True)

    # -- lifecycle --
    def start(self) -> "WeightPublisher":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def close(self, timeout: float = 10.0) -> None:
        self._stop = True
        self._wake.set()
        if self._started:
            self._thread.join(timeout)
        self._health.unregister("weight_publisher")

    def __enter__(self) -> "WeightPublisher":
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    def _run(self):
        while not self._stop:
            try:
                self.poll_once()
            except Exception:
                logger.exception("weight publisher poll failed")
            self._wake.wait(self.config.poll_interval_s)
            self._wake.clear()

    def _alive(self):
        if self._started and not self._thread.is_alive() \
                and not self._stop:
            return False, "publisher thread died"
        # health checks run on the MetricsServer thread: snapshot the
        # poll-thread-written state under _mu, format outside it
        with self._mu:
            age = time.monotonic() - self._last_poll
            version, neval = self.current.version, self.current.neval
        if self._started and age > max(self.config.liveness_grace_s,
                                       2 * self.config.poll_interval_s):
            return False, (f"no poll for {age:.1f}s (serving "
                           f"{version})")
        return True, (f"serving {version} (neval={neval}); "
                      f"last poll {age:.1f}s ago")

    def history_snapshot(self) -> list:
        """Atomic copy of the publish history. The deque is appended
        on the poll thread; callers iterating ``history`` live would
        race a concurrent publish — take the snapshot instead."""
        with self._mu:
            return list(self.history)

    @property
    def serving(self) -> WeightManifest:
        """The manifest the fleet currently serves, read atomically
        (``current`` is swapped by the poll thread at rollout end)."""
        with self._mu:
            return self.current

    # -- the loop body --
    def poll_once(self):
        """One poll: return ``None`` when nothing new is committed,
        else the :class:`PublishReport` of the publish it triggered."""
        self._m_polls.inc()
        with self._mu:
            self._last_poll = time.monotonic()
        man = self._latest_checkpoint(self.checkpoint_dir,
                                      cache=self._poll_cache)
        if man is None or int(man["neval"]) <= self.current.neval:
            return None
        return self.publish(man)

    def publish(self, man: dict) -> PublishReport:
        """Qualify and roll the checkpoint behind manifest ``man``
        (module docstring steps 2-5). Never raises for a qualification
        or rollout failure — the report's ``outcome`` says what
        happened; only the poll loop's own crash-fence sees unexpected
        errors."""
        t0 = time.monotonic()
        neval = int(man["neval"])
        version = version_string(neval)
        old = self.current
        self._g_inprog.set(1)
        trace.instant("publish detected", cat="deploy", neval=neval,
                      version=version, current=old.version)
        self._record("detected", neval=neval, version=version)
        report = None
        try:
            report = self._publish_inner(man, neval, version, old, t0)
        except Exception as e:       # load/spin-up/unexpected failure
            logger.exception("publish of %s failed", version)
            report = PublishReport("error", version, neval,
                                   duration_s=time.monotonic() - t0,
                                   error=f"{type(e).__name__}: {e}")
        finally:
            self._g_inprog.set(0)
        self._m_publishes.inc(outcome=report.outcome)
        if report.outcome in ("canary_failed", "rolled_back"):
            self._m_rollbacks.inc()
        with self._mu:
            self.history.append(report)
        trace.instant("publish finished", cat="deploy",
                      outcome=report.outcome, version=version,
                      duration_s=round(report.duration_s, 4))
        self._record(report.outcome, version=version, neval=neval,
                     rolled=len(report.rolled),
                     duration_s=round(report.duration_s, 4))
        return report

    def _publish_inner(self, man, neval, version, old,
                       t0) -> PublishReport:
        cfg = self.config
        wm = load_weight_version(self.checkpoint_dir, neval=neval,
                                 quantize=cfg.quantize)
        aot_before = (int(self.pool.aot.misses)
                      if self.pool.aot is not None else None)
        cname = cfg.canary_name
        # fence BEFORE the replica exists: no dispatcher window
        self.router.quarantine(cname)
        canary = None
        tap = None
        try:
            canary = self.pool.add_replica(
                cname, warm=True, model=wm.model,
                weight_version=wm.version)
            trace.instant("canary up", cat="deploy", version=version,
                          warm=True)
            shadow_report = None
            if cfg.canary.shadow_fraction > 0.0:
                tap = ShadowTap(self.router, canary,
                                fraction=cfg.canary.shadow_fraction)
                self._shadow_window(tap)
                try:
                    tap.wait(cfg.canary.timeout_s)
                except TimeoutError:
                    pass              # score whatever pairs completed
                shadow_report = tap.report()
                tap.close()
                tap = None
            verdict = qualify(canary, cfg.canary, aot=self.pool.aot,
                              aot_misses_before=aot_before,
                              shadow_report=shadow_report)
            trace.instant("canary verdict", cat="deploy",
                          version=version, passed=verdict.passed,
                          reasons=len(verdict.reasons))
            if not verdict.passed:
                logger.warning("canary for %s failed: %s", version,
                               "; ".join(verdict.reasons))
                return PublishReport(
                    "canary_failed", version, neval, canary=verdict,
                    duration_s=time.monotonic() - t0,
                    error="; ".join(verdict.reasons))
            return self._roll_fleet(wm, old, verdict, t0)
        finally:
            if tap is not None:
                tap.close()
            if canary is not None and cname in self.pool.replicas:
                self._retire_canary(canary)
            self.router.unquarantine(cname)

    def _shadow_window(self, tap) -> None:
        """Hold the canary in shadow mode until enough live requests
        were mirrored (or the qualification budget runs out)."""
        cfg = self.config
        deadline = time.monotonic() + cfg.canary.timeout_s
        while (time.monotonic() < deadline
               and tap._n_shadowed < cfg.canary.min_shadow_samples):
            time.sleep(0.005)

    def _retire_canary(self, canary) -> None:
        try:
            canary.drain_begin()
            canary.wait_idle(self.config.drain_timeout_s)
            self.pool.remove_replica(canary.name)
        except Exception:
            logger.exception("could not retire canary %s", canary.name)

    # -- rollout --
    def _roll_fleet(self, wm, old, verdict, t0) -> PublishReport:
        cfg = self.config
        fleet = [n for n in self.pool.names if n != cfg.canary_name]
        baseline = {}
        if cfg.slo is not None:
            baseline = {
                n: (self.pool[n].histogram_snapshot(
                        "serving_ttft_seconds"),
                    self.pool[n].histogram_snapshot(
                        "serving_decode_token_seconds"))
                for n in fleet}
        rolled = []
        try:
            for name in fleet:
                self._install(name, wm)
                rolled.append(name)
                self._m_rolled.inc()
                trace.instant("replica rolled", cat="deploy",
                              replica=name, version=wm.version)
                breach = self._slo_breach(rolled, baseline)
                if breach:
                    raise _RollbackSignal(breach)
        except Exception as e:
            reason = (str(e) if isinstance(e, _RollbackSignal)
                      else f"{type(e).__name__}: {e}")
            logger.warning("rolling %s back mid-rollout (%d/%d "
                           "replicas were on %s): %s", rolled and
                           ", ".join(rolled) or "nothing", len(rolled),
                           len(fleet), wm.version, reason)
            rolled_back = []
            for name in reversed(rolled):
                # force finish-on-(new): no survivor serves wm.version
                # once the canary retires, so nothing may migrate out
                self._install(name, old, force_finish=True)
                rolled_back.append(name)
                self._m_rolled.inc()
            trace.instant("publish rollback", cat="deploy",
                          version=wm.version, restored=old.version,
                          replicas=len(rolled_back))
            return PublishReport(
                "rolled_back", wm.version, wm.neval, canary=verdict,
                rolled=rolled, rolled_back=rolled_back,
                duration_s=time.monotonic() - t0, error=reason)
        # the fleet is 100% on the new version: future spin-ups
        # (autoscaler add_replica) must build with it too
        self.pool.set_default_model(wm.model, weight_version=wm.version)
        with self._mu:
            self.current = wm
        self._g_neval.set(wm.neval)
        return PublishReport("ok", wm.version, wm.neval, canary=verdict,
                             rolled=rolled,
                             duration_s=time.monotonic() - t0)

    def _install(self, name, wm, *, force_finish: bool = False) -> None:
        """Drain -> swap -> resume for one replica. The drain policy
        only ever says "migrate" while a survivor still serves the
        draining replica's CURRENT version (the exported snapshot can
        only be adopted there)."""
        cfg = self.config
        rep = self.pool[name]
        draining_version = rep.weight_version
        survivors = [n for n in self.pool.names
                     if n != name and n != cfg.canary_name
                     and self.pool[n].weight_version == draining_version]
        policy = cfg.migrate_policy
        if force_finish or policy is None or not survivors:
            def policy(_rid):
                return "finish"
        self.router.drain(name, policy=policy,
                          timeout=cfg.drain_timeout_s)
        try:
            rep.set_weights(wm.model, weight_version=wm.version)
        finally:
            # a failed swap leaves the OLD weights in place — resume
            # unconditionally so the replica never parks in DRAINING
            # (zero downtime even when the install itself errors)
            self.router.resume(name)

    def _slo_breach(self, rolled, baseline) -> str | None:
        """Rollout-window SLO check: p99s of the histogram mass
        observed SINCE the rollout began, across the rolled replicas,
        vs the configured targets. None = healthy (or no watch/no
        observations yet)."""
        cfg = self.config
        if cfg.slo is None:
            return None
        for name in rolled:
            if name not in baseline:
                continue
            rep = self.pool[name]
            ttft = percentile(_delta_snapshot(
                rep.histogram_snapshot("serving_ttft_seconds"),
                baseline[name][0]), 0.99)
            dec = percentile(_delta_snapshot(
                rep.histogram_snapshot("serving_decode_token_seconds"),
                baseline[name][1]), 0.99)
            if ttft is not None and ttft > cfg.slo.ttft_p99_s:
                return (f"replica {name} ttft p99 {ttft:.4f}s > "
                        f"{cfg.slo.ttft_p99_s}s during rollout")
            if dec is not None and dec > cfg.slo.decode_token_p99_s:
                return (f"replica {name} decode-token p99 {dec:.4f}s "
                        f"> {cfg.slo.decode_token_p99_s}s during "
                        "rollout")
        return None

    # -- telemetry --
    def _record(self, action: str, **fields) -> None:
        if self._recorder is None:
            return
        try:
            self._recorder.record("publish", action, **fields)
        except Exception:
            logger.exception("flight-recorder publish event failed")

"""Canary qualification: prove candidate weights before they roll.

The publisher spins the candidate up as ONE extra replica
(``pool.add_replica(warm=True, model=candidate)`` — zero compiles off
the pool's shared AOT cache, because a same-geometry model re-uses
every executable) and this module decides pass/fail from three gates:

- **pinned-prompt parity** (:func:`replay`): every configured prompt is
  replayed on the canary and its greedy continuation compared
  token-for-token against the expected output. Greedy decode is a pure
  function of (params, KV, last token), so ANY mismatch means the
  weights do not behave as qualified — not noise.
- **latency SLO**: TTFT / decode-token p99 read off the canary's own
  histogram snapshots (the replica is freshly built, so its histograms
  contain exactly the qualification traffic) vs the fleet's
  :class:`~bigdl_tpu.serving.slo.SLOConfig` targets.
- **zero compiles** (optional): the pool AOT cache's ``misses`` counter
  must not move while the canary spins up and replays — a miss means
  the candidate changed geometry and every rolled replica would pay an
  XLA compile in production.

:class:`ShadowTap` adds live-traffic shadowing: it rides the router's
``on_submit``/``on_result`` observer taps, mirrors a deterministic
fraction of accepted prompts onto the canary (distinct request ids, so
the primary fleet's exactly-once accounting is untouched), and scores
agreement between primary and canary outputs. Shadowing compares
OUTPUTS only — shadow results are never returned to callers.

HOST-ONLY CONTRACT (jaxlint JX5): no jax imports — qualification is
pure host orchestration over the replica API.
"""
from __future__ import annotations

import time

from bigdl_tpu.serving.slo import SLOConfig, percentile

__all__ = ["CanaryConfig", "CanaryReport", "ShadowTap", "qualify",
           "replay"]

_CANARY_NS = "__canary__"
_SHADOW_NS = "__shadow__"


class CanaryConfig:
    """Qualification gates for one publish.

    - ``prompts``: the pinned prompt set — ``(prompt_tokens,
      expected_tokens)`` pairs; ``expected_tokens=None`` replays for
      latency only (no parity check on that prompt).
    - ``slo``: latency targets the canary must meet (None skips the
      latency gate).
    - ``require_zero_compiles``: fail if the shared AOT cache records
      any miss during canary spin-up + replay.
    - ``shadow_fraction`` / ``min_shadow_samples`` /
      ``min_shadow_agreement``: mirror that fraction of live traffic
      onto the canary and require the agreement rate over at least
      that many compared pairs (0.0 fraction disables shadowing).
    - ``timeout_s``: replay/shadow wall-clock budget.
    """

    def __init__(self, prompts=(), *, slo: SLOConfig | None = None,
                 require_zero_compiles: bool = False,
                 shadow_fraction: float = 0.0,
                 min_shadow_samples: int = 1,
                 min_shadow_agreement: float = 1.0,
                 timeout_s: float = 60.0):
        self.prompts = [(list(p), None if e is None else list(e))
                        for p, e in prompts]
        self.slo = slo
        self.require_zero_compiles = bool(require_zero_compiles)
        if not 0.0 <= float(shadow_fraction) <= 1.0:
            raise ValueError(f"shadow_fraction must be in [0, 1], got "
                             f"{shadow_fraction}")
        self.shadow_fraction = float(shadow_fraction)
        self.min_shadow_samples = int(min_shadow_samples)
        self.min_shadow_agreement = float(min_shadow_agreement)
        self.timeout_s = float(timeout_s)


class CanaryReport:
    """The qualification verdict: ``passed`` plus one human-readable
    reason per failed gate and the raw per-gate numbers."""

    __slots__ = ("passed", "reasons", "parity", "latency", "compiles",
                 "shadow")

    def __init__(self, passed, reasons, *, parity=None, latency=None,
                 compiles=None, shadow=None):
        self.passed = bool(passed)
        self.reasons = list(reasons)
        self.parity = parity
        self.latency = latency
        self.compiles = compiles
        self.shadow = shadow

    def as_dict(self) -> dict:
        return {"passed": self.passed, "reasons": list(self.reasons),
                "parity": self.parity, "latency": self.latency,
                "compiles": self.compiles, "shadow": self.shadow}

    def __repr__(self):
        verdict = "PASS" if self.passed else "FAIL"
        return f"CanaryReport({verdict}, reasons={self.reasons!r})"


def replay(replica, prompts, *, timeout_s: float = 60.0) -> dict:
    """Replay ``prompts`` (list of token lists) on a DETACHED replica
    (one the router holds no hooks on: results land in the batcher's
    own ``finished()`` buffer) and return ``{index: tokens}``. The
    replica's driver thread does the stepping; this call just waits for
    idle."""
    for i, prompt in enumerate(prompts):
        replica.submit((_CANARY_NS, i), list(prompt))
    if not replica.wait_idle(timeout_s):
        raise TimeoutError(
            f"canary {replica.name} did not finish its "
            f"{len(prompts)}-prompt replay in {timeout_s}s")
    out = {}
    with replica.lock:
        done = replica.batcher.finished()
    for rid, toks in done:
        if isinstance(rid, tuple) and rid[0] == _CANARY_NS:
            out[rid[1]] = list(toks)
    return out


def qualify(replica, config: CanaryConfig, *, aot=None,
            aot_misses_before: int | None = None,
            shadow_report: dict | None = None) -> CanaryReport:
    """Run the gates (module docstring) against ``replica`` and render
    the verdict. ``aot``/``aot_misses_before`` bound the zero-compile
    window (pass the pool's shared cache and its ``misses`` value from
    BEFORE ``add_replica``); ``shadow_report`` is a
    :meth:`ShadowTap.report` dict when live shadowing ran."""
    reasons = []

    replayed = replay(replica, [p for p, _ in config.prompts],
                      timeout_s=config.timeout_s)
    mismatches = []
    checked = 0
    for i, (_prompt, expected) in enumerate(config.prompts):
        if expected is None:
            continue
        checked += 1
        got = replayed.get(i)
        if got != expected:
            mismatches.append({"prompt_index": i, "expected": expected,
                               "got": got})
    parity = {"replayed": len(replayed), "checked": checked,
              "mismatched": len(mismatches), "mismatches": mismatches}
    if mismatches:
        reasons.append(
            f"parity: {len(mismatches)}/{checked} pinned prompts "
            "diverged from their expected greedy continuation")

    latency = None
    if config.slo is not None:
        ttft = percentile(
            replica.histogram_snapshot("serving_ttft_seconds"), 0.99)
        dec = percentile(
            replica.histogram_snapshot("serving_decode_token_seconds"),
            0.99)
        latency = {"ttft_p99_s": ttft, "decode_token_p99_s": dec}
        if ttft is not None and ttft > config.slo.ttft_p99_s:
            reasons.append(f"slo: canary ttft p99 {ttft:.4f}s > target "
                           f"{config.slo.ttft_p99_s}s")
        if dec is not None and dec > config.slo.decode_token_p99_s:
            reasons.append(
                f"slo: canary decode-token p99 {dec:.4f}s > target "
                f"{config.slo.decode_token_p99_s}s")

    compiles = None
    if aot is not None and aot_misses_before is not None:
        compiles = int(aot.misses) - int(aot_misses_before)
        if config.require_zero_compiles and compiles > 0:
            reasons.append(
                f"aot: canary spin-up paid {compiles} compile(s) — the "
                "candidate's geometry misses the shared executable "
                "cache, so every rolled replica would recompile")

    if config.shadow_fraction > 0.0:
        sr = shadow_report or {"samples": 0, "agreed": 0,
                               "agreement": None}
        if sr["samples"] < config.min_shadow_samples:
            reasons.append(
                f"shadow: only {sr['samples']} compared pairs "
                f"(need >= {config.min_shadow_samples})")
        elif sr["agreement"] < config.min_shadow_agreement:
            reasons.append(
                f"shadow: agreement {sr['agreement']:.3f} < required "
                f"{config.min_shadow_agreement:.3f} over "
                f"{sr['samples']} pairs")
        shadow = sr
    else:
        shadow = shadow_report

    return CanaryReport(not reasons, reasons, parity=parity,
                        latency=latency, compiles=compiles,
                        shadow=shadow)


class ShadowTap:
    """Mirror a deterministic fraction of live router traffic onto a
    canary replica and score output agreement (module docstring).

    Installs itself on ``router.on_submit``/``router.on_result`` at
    construction and restores the previous taps on :meth:`close` (use
    as a context manager). Sampling is counter-based — every accepted
    prompt advances a phase accumulator, so ``fraction=0.25`` shadows
    exactly every 4th request with no RNG. A saturated canary drops the
    shadow copy rather than back-pressuring live traffic."""

    def __init__(self, router, replica, *, fraction: float = 0.1,
                 max_shadow: int = 256):
        if not 0.0 < float(fraction) <= 1.0:
            raise ValueError(f"fraction must be in (0, 1], got "
                             f"{fraction}")
        self.router = router
        self.replica = replica
        self.fraction = float(fraction)
        self.max_shadow = int(max_shadow)
        self._n_seen = 0
        self._n_shadowed = 0
        self._n_dropped = 0
        self._primary: dict = {}    # rid -> tokens (shadowed only)
        self._awaited: set = set()
        self._prev_submit = router.on_submit
        self._prev_result = router.on_result
        self._prev_complete = replica.batcher.on_complete
        self._canary: dict = {}     # rid -> tokens
        replica.batcher.on_complete = self._on_canary_complete
        router.on_submit = self._on_submit
        router.on_result = self._on_result

    # -- hooks --
    def _on_submit(self, rid, prompt):
        if self._prev_submit is not None:
            self._prev_submit(rid, prompt)
        self._n_seen += 1
        take = (int(self._n_seen * self.fraction)
                > int((self._n_seen - 1) * self.fraction))
        if not take or self._n_shadowed >= self.max_shadow:
            return
        try:
            self.replica.submit((_SHADOW_NS, rid), list(prompt))
        except Exception:
            self._n_dropped += 1      # canary saturated/draining: skip
            return
        self._n_shadowed += 1
        self._awaited.add(rid)

    def _on_result(self, rid, toks):
        if self._prev_result is not None:
            self._prev_result(rid, toks)
        if rid in self._awaited:
            self._primary[rid] = list(toks)

    def _on_canary_complete(self, rid, toks):
        if isinstance(rid, tuple) and rid[0] == _SHADOW_NS:
            self._canary[rid[1]] = list(toks)
        elif self._prev_complete is not None:
            self._prev_complete(rid, toks)

    # -- results --
    def wait(self, timeout_s: float = 30.0) -> None:
        """Block until every shadow copy submitted so far completed."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if all(r in self._canary for r in list(self._awaited)):
                return
            time.sleep(0.005)
        missing = sum(r not in self._canary
                      for r in list(self._awaited))
        raise TimeoutError(
            f"{missing} shadow copies still running after {timeout_s}s")

    def report(self) -> dict:
        """Agreement over pairs where BOTH outputs arrived."""
        pairs = [(self._primary[r], self._canary[r])
                 for r in list(self._awaited)
                 if r in self._primary and r in self._canary]
        agreed = sum(a == b for a, b in pairs)
        return {"seen": self._n_seen, "shadowed": self._n_shadowed,
                "dropped": self._n_dropped, "samples": len(pairs),
                "agreed": agreed,
                "agreement": (agreed / len(pairs)) if pairs else None}

    def close(self) -> None:
        """Detach: restore the router taps and the canary hook."""
        self.router.on_submit = self._prev_submit
        self.router.on_result = self._prev_result
        self.replica.batcher.on_complete = self._prev_complete

    def __enter__(self) -> "ShadowTap":
        return self

    def __exit__(self, *exc):
        self.close()
        return False

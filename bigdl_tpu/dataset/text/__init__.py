"""Text data pipeline (reference dataset/text/, SURVEY §2.5)."""

from bigdl_tpu.dataset.text.transforms import (
    Dictionary, SentenceToken, SentenceSplitter, SentenceTokenizer,
    SentenceBiPadding, TextToLabeledSentence, LabeledSentenceToSample)

"""Text transformers (reference dataset/text/, ~730 LoC; SURVEY §2.5).

Reference parity: Dictionary (vocab build/save/load,
text/Dictionary.scala), SentenceSplitter/SentenceTokenizer (OpenNLP in the
reference; regex equivalents here), SentenceBiPadding (start/end tokens),
TextToLabeledSentence (next-word LM pairs), LabeledSentenceToSample
(one-hot / index encoding with fixed-length padding).
"""
from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Iterator

import numpy as np

from bigdl_tpu.dataset.sample import LabeledSentence, Sample
from bigdl_tpu.dataset.transformer import Transformer

__all__ = ["Dictionary", "SentenceToken", "SentenceSplitter",
           "SentenceTokenizer", "SentenceBiPadding", "TextToLabeledSentence",
           "LabeledSentenceToSample"]


class SentenceToken:
    """(reference text/utils/SentenceToken)"""
    start = "SENTENCESTART"
    end = "SENTENCEEND"


class Dictionary:
    """Frequency-ranked vocabulary (reference text/Dictionary.scala).

    Words beyond ``vocab_size`` go to the discard list and map to an
    out-of-vocab index == vocab_size (the reference's ``getIndex`` returns
    ``_vocabSize`` for unknown words). Indices are 0-based here.
    """

    def __init__(self, sentences=None, vocab_size: int = 10000):
        self._word2index: dict[str, int] = {}
        self._index2word: dict[int, str] = {}
        self._vocabulary: list[str] = []
        self._discard: list[str] = []
        if sentences is not None:
            freq: dict[str, int] = {}
            for sent in sentences:
                for w in sent:
                    freq[w] = freq.get(w, 0) + 1
            ranked = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
            keep = ranked[:vocab_size]
            self._vocabulary = [w for w, _ in keep]
            self._discard = [w for w, _ in ranked[vocab_size:]]
            self._word2index = {w: i for i, w in enumerate(self._vocabulary)}
            self._index2word = {i: w for w, i in self._word2index.items()}

    @classmethod
    def load(cls, directory: str) -> "Dictionary":
        """(reference Dictionary(directory) — dictionary.txt + discard.txt)"""
        d = cls()
        folder = Path(directory)
        d._word2index = json.loads((folder / "dictionary.txt").read_text())
        d._index2word = {i: w for w, i in d._word2index.items()}
        d._vocabulary = [w for w, _ in sorted(d._word2index.items(),
                                              key=lambda kv: kv[1])]
        discard_file = folder / "discard.txt"
        if discard_file.exists():
            d._discard = discard_file.read_text().split()
        return d

    def save(self, save_folder: str) -> None:
        """(reference Dictionary.save)"""
        folder = Path(save_folder)
        folder.mkdir(parents=True, exist_ok=True)
        (folder / "dictionary.txt").write_text(json.dumps(self._word2index))
        (folder / "discard.txt").write_text("\n".join(self._discard))

    def get_vocab_size(self) -> int:
        return len(self._vocabulary)

    def get_discard_size(self) -> int:
        return len(self._discard)

    def word2index(self) -> dict:
        return dict(self._word2index)

    def index2word(self) -> dict:
        return dict(self._index2word)

    def vocabulary(self):
        return list(self._vocabulary)

    def discard_vocab(self):
        return list(self._discard)

    def get_index(self, word: str) -> int:
        """Unknown words map to vocab_size (reference Dictionary.getIndex)."""
        return self._word2index.get(word, len(self._vocabulary))

    def get_word(self, index) -> str:
        return self._index2word[int(index)]


class SentenceSplitter(Transformer):
    """Text -> sentences (reference SentenceSplitter.scala; OpenNLP sentence
    model -> punctuation regex)."""

    _pat = re.compile(r"(?<=[.!?])\s+")

    def __call__(self, it: Iterator[str]):
        for text in it:
            for sent in self._pat.split(text.strip()):
                if sent:
                    yield sent


class SentenceTokenizer(Transformer):
    """Sentence -> word array (reference SentenceTokenizer.scala; OpenNLP
    tokenizer -> word/punct regex), with optional lowercase."""

    _pat = re.compile(r"\w+(?:'\w+)?|[^\w\s]")

    def __init__(self, lower: bool = True):
        self.lower = lower

    def __call__(self, it: Iterator[str]):
        for sent in it:
            if self.lower:
                sent = sent.lower()
            toks = self._pat.findall(sent)
            if toks:
                yield toks


class SentenceBiPadding(Transformer):
    """Wrap each sentence with start/end tokens
    (reference SentenceBiPadding.scala:196-215)."""

    def __init__(self, start: str | None = None, end: str | None = None):
        self.start = start or SentenceToken.start
        self.end = end or SentenceToken.end

    def __call__(self, it):
        for x in it:
            if isinstance(x, str):
                yield f"{self.start} {x} {self.end}"
            else:
                yield [self.start, *x, self.end]


class TextToLabeledSentence(Transformer):
    """Word array -> next-word LM pair: data = tokens[:-1] indices,
    label = tokens[1:] indices (reference TextToLabeledSentence.scala)."""

    def __init__(self, dictionary: Dictionary):
        self.dictionary = dictionary

    def __call__(self, it):
        for sentence in it:
            idx = np.asarray([self.dictionary.get_index(w) for w in sentence],
                             np.int32)
            if len(idx) < 2:
                continue
            yield LabeledSentence(idx[:-1], idx[1:])


class LabeledSentenceToSample(Transformer):
    """LabeledSentence -> Sample (reference LabeledSentenceToSample.scala).

    ``one_hot=True``: feature (T, vocab) one-hot like the reference's
    dense encoding; labels become 1-based class indices (ClassNLL
    convention). ``fixed_length`` pads data with the end-token index and
    truncates longer sequences.
    """

    def __init__(self, vocab_length: int, fixed_data_length: int | None = None,
                 fixed_label_length: int | None = None, one_hot: bool = True):
        self.vocab_length = vocab_length
        self.fixed_data_length = fixed_data_length
        self.fixed_label_length = fixed_label_length
        self.one_hot = one_hot

    def _fix(self, arr, length, pad_value):
        if length is None or len(arr) == length:
            return arr
        if len(arr) > length:
            return arr[:length]
        return np.concatenate(
            [arr, np.full(length - len(arr), pad_value, arr.dtype)])

    def __call__(self, it):
        for sent in it:
            data = np.asarray(sent.data, np.int32)
            label = np.asarray(sent.label, np.int32)
            end_idx = data[-1] if len(data) else 0
            data = self._fix(data, self.fixed_data_length, end_idx)
            label = self._fix(label, self.fixed_label_length,
                              label[-1] if len(label) else 0)
            if self.one_hot:
                feat = np.zeros((len(data), self.vocab_length), np.float32)
                feat[np.arange(len(data)), np.clip(data, 0,
                                                   self.vocab_length - 1)] = 1
            else:
                feat = data
            yield Sample(feat, label.astype(np.float32) + 1.0)

"""Composable data pipelines (reference: dl/.../bigdl/dataset/)."""

from bigdl_tpu.dataset.sample import (Sample, MiniBatch, ByteRecord,
                                      LabeledSentence)
from bigdl_tpu.dataset.transformer import (Transformer, ChainedTransformer,
                                           SampleToBatch)
from bigdl_tpu.dataset.dataset import (AbstractDataSet, LocalArrayDataSet,
                                       ShardedDataSet, DataSet, array,
                                       iterator_source)
from bigdl_tpu.dataset.prefetch import (PrefetchIterator, DevicePrefetcher,
                                        PadPartialBatches)
from bigdl_tpu.dataset.recordstore import (ChunkedRecordWriter,
                                           ChunkedRecordReader,
                                           write_sample_store)
from bigdl_tpu.dataset.distributed import (DistributedShuffleDataSet,
                                           chunk_assignment,
                                           redistribute_chunk_positions)

"""Data carriers.

Reference parity: Sample (dataset/Sample.scala:32-102), MiniBatch /
ByteRecord / Image / Sentence / Label (dataset/Types.scala:26-81).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np

__all__ = ["Sample", "MiniBatch", "ByteRecord", "LabeledSentence"]


class Sample:
    """One (feature, label) pair (reference dataset/Sample.scala)."""

    __slots__ = ("feature", "label")

    def __init__(self, feature, label):
        self.feature = np.asarray(feature)
        self.label = np.asarray(label)

    @staticmethod
    def from_ndarray(feature, label) -> "Sample":
        """(python reference util/common.py:59-98 Sample.from_ndarray)"""
        return Sample(feature, label)

    def clone(self) -> "Sample":
        return Sample(self.feature.copy(), self.label.copy())

    def __repr__(self):
        return f"Sample(feature={self.feature.shape}, " \
               f"label={self.label.shape})"


class MiniBatch:
    """One training batch (reference dataset/Types.scala:73).

    ``valid``: number of REAL rows when the batch was padded to a fixed
    shape (``dataset.prefetch.PadPartialBatches``); None means every row
    is real. Carried as a host int so record accounting never has to
    read a device array back."""

    __slots__ = ("data", "labels", "valid")

    def __init__(self, data, labels, valid=None):
        self.data = data
        self.labels = labels
        self.valid = valid

    def size(self) -> int:
        # shape attribute first: np and jax arrays both carry it, and
        # np.asarray on a device array would force a host transfer
        shape = getattr(self.data, "shape", None)
        if shape is not None:
            return int(shape[0])
        return int(np.asarray(self.data).shape[0])

    def narrow(self, offset: int, length: int) -> "MiniBatch":
        return MiniBatch(self.data[offset:offset + length],
                         self.labels[offset:offset + length])

    def __iter__(self):  # destructuring: data, labels = batch
        yield self.data
        yield self.labels


@dataclass
class ByteRecord:
    """Raw bytes + label (reference dataset/Types.scala ByteRecord).

    ``key``: optional stable identity (e.g. (shard path, record index),
    set by ``recordio.read_records``) — the decoded-RAM cache keys by it
    instead of re-hashing the payload bytes every epoch."""
    data: bytes
    label: float
    key: object = None


@dataclass
class LabeledSentence:
    """(reference dataset/text/Types.scala)"""
    data: Any
    label: Any

"""MNIST idx-ubyte reader (reference models/lenet/Utils.scala:load — big-
endian magic 2049/2051 label/image files) + the canonical normalization
constants used by the reference LeNet pipeline."""
from __future__ import annotations

import gzip
import struct
from pathlib import Path

import numpy as np

from bigdl_tpu.dataset.image.types import LabeledGreyImage

__all__ = ["load_images", "load_labels", "load", "TRAIN_MEAN", "TRAIN_STD",
           "TEST_MEAN", "TEST_STD"]

# reference models/lenet/Utils.scala trainMean/trainStd (of [0,1] pixels)
TRAIN_MEAN = 0.13066047740239506
TRAIN_STD = 0.3081078
TEST_MEAN = 0.13251460696903547
TEST_STD = 0.31048024


def _open(path):
    p = Path(path)
    if p.suffix == ".gz":
        return gzip.open(p, "rb")
    return open(p, "rb")


def load_images(path: str) -> np.ndarray:
    """(N, 28, 28) uint8 (reference Utils.load, magic 2051)."""
    with _open(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad image magic {magic}"
        buf = f.read(n * rows * cols)
    return np.frombuffer(buf, np.uint8).reshape(n, rows, cols)


def load_labels(path: str) -> np.ndarray:
    """(N,) float32 1-based labels (reference loads label+1 for
    ClassNLL)."""
    with _open(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad label magic {magic}"
        buf = f.read(n)
    return np.frombuffer(buf, np.uint8).astype(np.float32) + 1.0


def load(image_path: str, label_path: str):
    """List of LabeledGreyImage with [0,1] pixel values."""
    images = load_images(image_path).astype(np.float32) / 255.0
    labels = load_labels(label_path)
    return [LabeledGreyImage(img, float(lab))
            for img, lab in zip(images, labels)]

"""CIFAR-10 binary reader (reference models/vgg/Utils.scala /
models/resnet/Utils.scala — 3073-byte records: label + 32x32x3 RGB planes)
plus the reference training statistics."""
from __future__ import annotations

from pathlib import Path

import numpy as np

from bigdl_tpu.dataset.image.types import LabeledBGRImage

__all__ = ["load_bin", "load_folder", "TRAIN_MEAN", "TRAIN_STD",
           "TEST_MEAN", "TEST_STD"]

# reference models/vgg/Utils.scala trainMean/trainStd/testMean/testStd
# ((R,G,B), scaled to the [0,255] pixel range this reader emits)
TRAIN_MEAN = (125.33761, 122.96133, 113.8664)
TRAIN_STD = (62.99322675508508, 62.08871334906125, 66.70490641235472)
TEST_MEAN = (126.02464429303008, 123.70850706950385, 114.85432115955024)
TEST_STD = (62.89639202540039, 61.93752790239704, 66.7060575695284)


def load_bin(path: str):
    """One data_batch_*.bin file -> list of LabeledBGRImage (pixels [0,255],
    labels 1-based)."""
    raw = np.frombuffer(Path(path).read_bytes(), np.uint8)
    rec = raw.reshape(-1, 3073)
    labels = rec[:, 0].astype(np.float32) + 1.0
    # stored as RGB planes (3, 32, 32) -> HWC BGR
    imgs = rec[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    imgs = imgs[..., ::-1].astype(np.float32)
    return [LabeledBGRImage(img, float(lab))
            for img, lab in zip(imgs, labels)]


def load_folder(folder: str, train: bool = True):
    """data_batch_1..5.bin for train, test_batch.bin for eval (reference
    Utils.loadTrain/loadTest)."""
    folder = Path(folder)
    files = ([folder / f"data_batch_{i}.bin" for i in range(1, 6)]
             if train else [folder / "test_batch.bin"])
    out = []
    for f in files:
        out.extend(load_bin(str(f)))
    return out

"""Composable data transformers.

Reference parity: Transformer[A,B] (dataset/Transformer.scala:39-61) — a
serializable ``Iterator[A] -> Iterator[B]`` with ``->`` chaining — and
SampleToBatch (:98-240) with optional feature/label padding to a fixed
length (RNN support).

Here ``Transformer`` is a callable over iterators; chain with ``>>`` (the
Python rendering of the reference's ``->``) or ``.then()``.
"""
from __future__ import annotations

import copy
from typing import Iterator

import numpy as np

from bigdl_tpu.dataset.sample import MiniBatch

__all__ = ["Transformer", "ChainedTransformer", "SampleToBatch"]


class Transformer:
    """Iterator[A] -> Iterator[B] (reference Transformer.scala:39-54)."""

    def __call__(self, it: Iterator) -> Iterator:
        raise NotImplementedError

    def then(self, other: "Transformer") -> "ChainedTransformer":
        """(reference ``->`` composition)"""
        return ChainedTransformer(self, other)

    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        return self.then(other)

    def clone_transformer(self) -> "Transformer":
        """(reference cloneTransformer — used to give each worker its own
        stateful copy)"""
        return copy.deepcopy(self)


class ChainedTransformer(Transformer):
    def __init__(self, first: Transformer, last: Transformer):
        self.first, self.last = first, last

    def __call__(self, it):
        return self.last(self.first(it))


class SampleToBatch(Transformer):
    """Group Samples into MiniBatches (reference Transformer.scala:98-240).

    ``fixed_length``/``pad_value`` pad variable-length features and labels
    (the reference's padding branch for RNN pipelines); without them shapes
    must agree. Partial trailing batches are emitted (matching the
    reference's behavior when the iterator is exhausted); training datasets
    loop endlessly so only eval sees a short batch.
    """

    def __init__(self, batch_size: int, fixed_length: int | None = None,
                 pad_feature_value: float = 0.0,
                 pad_label_value: float = 0.0,
                 drop_remainder: bool = False):
        self.batch_size = batch_size
        self.fixed_length = fixed_length
        self.pad_feature_value = pad_feature_value
        self.pad_label_value = pad_label_value
        self.drop_remainder = drop_remainder

    def _pad(self, arr: np.ndarray, value: float) -> np.ndarray:
        if self.fixed_length is None or arr.shape[0] >= self.fixed_length:
            return arr[:self.fixed_length] if self.fixed_length else arr
        pad = [(0, self.fixed_length - arr.shape[0])] + \
              [(0, 0)] * (arr.ndim - 1)
        return np.pad(arr, pad, constant_values=value)

    def __call__(self, it):
        feats, labels = [], []
        for s in it:
            feats.append(self._pad(np.asarray(s.feature),
                                   self.pad_feature_value))
            labels.append(self._pad(np.atleast_1d(np.asarray(s.label)),
                                    self.pad_label_value))
            if len(feats) == self.batch_size:
                yield MiniBatch(np.stack(feats), self._stack_labels(labels))
                feats, labels = [], []
        if feats and not self.drop_remainder:
            yield MiniBatch(np.stack(feats), self._stack_labels(labels))

    @staticmethod
    def _stack_labels(labels):
        lab = np.stack(labels)
        # scalar labels arrive as (B, 1) — flatten ONLY that axis, never the
        # batch axis (np.squeeze() would collapse batch-size-1 batches)
        if lab.ndim == 2 and lab.shape[1] == 1:
            lab = lab[:, 0]
        return lab

"""Sharded record files — the ImageNet-scale input path.

Reference parity: ``DataSet.SeqFileFolder`` (dataset/DataSet.scala:383-454)
reads Hadoop SequenceFiles of (label-key, raw-JPEG-bytes) records;
``ImageNetSeqFileGenerator`` (models/utils/ImageNetSeqFileGenerator.scala)
converts a class-per-subfolder image tree into N such shard files;
``MTLabeledBGRImgToBatch`` decodes with per-core threads.

TPU-native design: a dependency-free binary record format (Hadoop
SequenceFile is a JVM artifact, not a wire standard worth emulating):

    shard file := MAGIC "BTR1", then per record:
                  float64 label (little-endian), uint32 len, len bytes
    sidecar    := <name>.idx — ASCII record count (cheap size() / resume)

Shards are independent files, so host processes map shards to themselves
(``process_index``) the way the reference maps partitions to executors, and
``MTImgToBatch`` + ``DevicePrefetcher`` overlap decode and host->device
transfer with the device step — the TPU equivalent of the reference's
per-core decode threads ahead of each Spark task.
"""
from __future__ import annotations

import json
import os
import struct
from pathlib import Path
from typing import Iterator

import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet, PassRotationMixin
# DevicePrefetcher moved to dataset/prefetch.py (ISSUE 5 input-pipeline
# subsystem); re-exported here for existing call sites
from bigdl_tpu.dataset.prefetch import DevicePrefetcher  # noqa: F401
from bigdl_tpu.dataset.sample import ByteRecord
from bigdl_tpu.utils.random import RandomGenerator

__all__ = ["RecordWriter", "read_records", "generate_shards",
           "RecordShardDataSet", "DevicePrefetcher", "SHARD_SUFFIX"]

_MAGIC = b"BTR1"
SHARD_SUFFIX = ".brec"


class RecordWriter:
    """Append (raw bytes, label) records to one shard file."""

    def __init__(self, path: str):
        self.path = str(path)
        self._f = open(self.path, "wb")
        self._f.write(_MAGIC)
        self.count = 0

    def write(self, data: bytes, label: float):
        self._f.write(struct.pack("<dI", float(label), len(data)))
        self._f.write(data)
        self.count += 1

    def close(self):
        self._f.close()
        Path(self.path + ".idx").write_text(str(self.count))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _headers(f, path):
    """Yield (label, size) per record, leaving ``f`` at the payload start;
    the caller must read or seek exactly ``size`` bytes before the next
    iteration. The single home of the BTR1 framing logic."""
    if f.read(4) != _MAGIC:
        raise ValueError(f"{path} is not a record shard file")
    while True:
        head = f.read(12)
        if len(head) < 12:
            return
        yield struct.unpack("<dI", head)


def read_records(path: str, skip: int = 0) -> Iterator[ByteRecord]:
    """Stream ByteRecords from one shard file (optionally skipping the
    first ``skip`` records without decoding)."""
    with open(path, "rb") as f:
        for n, (label, size) in enumerate(_headers(f, path)):
            if n < skip:
                f.seek(size, os.SEEK_CUR)
            else:
                yield ByteRecord(f.read(size), label, key=(path, n))


def shard_count(path: str) -> int:
    idx = Path(str(path) + ".idx")
    if idx.exists():
        return int(idx.read_text())
    # sidecar missing: count by seeking over payloads — header reads only,
    # never materializing record bytes
    n = 0
    with open(path, "rb") as f:
        for _, size in _headers(f, path):
            f.seek(size, os.SEEK_CUR)
            n += 1
    return n


def _reencode(path: str, scale_to: int) -> bytes:
    """Resize so the shorter side == ``scale_to`` (up OR down — croppers
    downstream assume at least crop-size images; the reference generator
    scales every image the same way, ImageNetSeqFileGenerator.scala) +
    JPEG re-encode."""
    import io
    from PIL import Image
    img = Image.open(path).convert("RGB")
    w, h = img.size
    if min(w, h) != scale_to:
        if w < h:
            img = img.resize((scale_to, max(1, round(h * scale_to / w))),
                             Image.BILINEAR)
        else:
            img = img.resize((max(1, round(w * scale_to / h)), scale_to),
                             Image.BILINEAR)
    buf = io.BytesIO()
    img.save(buf, "JPEG", quality=90)
    return buf.getvalue()


def generate_shards(image_folder: str, output_dir: str, num_shards: int = 8,
                    shuffle: bool = True, prefix: str = "shard",
                    scale_to: int | None = 256) -> list[str]:
    """Class-per-subfolder tree -> N shard files of raw image bytes +
    1-based labels (reference ImageNetSeqFileGenerator.scala — same
    round-robin record placement and 256-scaling, minus the Hadoop
    container). ``scale_to=None`` copies bytes verbatim."""
    from bigdl_tpu.dataset.image import LocalImageFiles
    pairs = LocalImageFiles.paths(image_folder)
    if shuffle:
        RandomGenerator.RNG().shuffle(pairs)
    os.makedirs(output_dir, exist_ok=True)
    paths = [os.path.join(output_dir,
                          f"{prefix}-{i:05d}-of-{num_shards:05d}"
                          f"{SHARD_SUFFIX}")
             for i in range(num_shards)]
    writers = [RecordWriter(p) for p in paths]
    try:
        for i, (path, label) in enumerate(pairs):
            if scale_to is not None:
                data = _reencode(path, scale_to)
            else:
                with open(path, "rb") as f:
                    data = f.read()
            writers[i % num_shards].write(data, label)
    finally:
        for w in writers:
            w.close()
    meta = {"num_shards": num_shards, "total": len(pairs),
            "counts": [w.count for w in writers]}
    Path(output_dir, "shards.json").write_text(json.dumps(meta))
    return paths


class RecordShardDataSet(PassRotationMixin, AbstractDataSet):
    """Sharded dataset over record files (the SeqFileFolder role).

    ``process_index``/``process_count`` split the SHARD FILES across host
    processes (reference: RDD partitions pinned to executors); the training
    iterator loops endlessly over the local shards, rotating the shard
    order per pass via PassRotationMixin — the same pure pass-counter
    scheme as ShardedDataSet, so mid-epoch resume replays exactly.

    Record counts come from ``shards.json`` (written by generate_shards)
    or the per-shard ``.idx`` sidecars, resolved lazily on first
    ``size()``; only the headers are seeked if both are missing.
    """

    def __init__(self, folder_or_paths, process_index: int = 0,
                 process_count: int = 1):
        if isinstance(folder_or_paths, (str, Path)):
            self._all_paths = sorted(
                str(p) for p in Path(folder_or_paths).iterdir()
                if p.name.endswith(SHARD_SUFFIX))
        else:
            self._all_paths = [str(p) for p in folder_or_paths]
        if not self._all_paths:
            raise ValueError("no record shard files found")
        self.process_index = process_index
        self.process_count = process_count
        self._seed_shard = process_index
        self._local = self._all_paths[process_index::process_count]
        if not self._local:
            raise ValueError(
                f"process {process_index}/{process_count} got no shards — "
                "fewer shard files than processes")
        self._counts: dict = {}
        self._meta_counts: dict | None = None
        self._meta_loaded = False
        self._index = np.arange(len(self._local))

    def _load_meta(self):
        """shards.json from the shards' own directory (works for both
        folder and path-list construction), loaded once on demand."""
        if self._meta_loaded:
            return
        self._meta_loaded = True
        parents = {str(Path(p).parent) for p in self._all_paths}
        if len(parents) != 1:
            return
        meta = Path(parents.pop()) / "shards.json"
        if meta.exists():
            m = json.loads(meta.read_text())
            if len(m.get("counts", [])) == len(self._all_paths):
                # generate_shards writes counts in sorted-path order
                self._meta_counts = dict(
                    zip(sorted(self._all_paths), m["counts"]))

    def _count(self, path: str) -> int:
        if path not in self._counts:
            # the .idx sidecar is written atomically with the shard by
            # RecordWriter.close, so it wins over the batch-level
            # shards.json (which goes stale if one shard is regenerated)
            if Path(path + ".idx").exists():
                self._counts[path] = shard_count(path)
            else:
                self._load_meta()
                self._counts[path] = (self._meta_counts[path]
                                      if self._meta_counts is not None
                                      else shard_count(path))
        return self._counts[path]

    def is_sharded(self):
        return self.process_count > 1

    def process_shard_count(self):
        return self.process_count

    def process_shard_index(self):
        return self.process_index

    def size(self) -> int:
        """Global record count (reference DistributedDataSet.size)."""
        return sum(self._count(p) for p in self._all_paths)

    def local_size(self) -> int:
        return sum(self._count(p) for p in self._local)

    def data(self, train: bool):
        if train:
            def endless():
                while True:
                    for i in self._next_pass_order():
                        yield from read_records(self._local[int(i)])
            return endless()

        def single():
            for i in self._index:
                yield from read_records(self._local[int(i)])
        return single()

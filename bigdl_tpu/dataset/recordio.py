"""Sharded record files — the ImageNet-scale input path.

Reference parity: ``DataSet.SeqFileFolder`` (dataset/DataSet.scala:383-454)
reads Hadoop SequenceFiles of (label-key, raw-JPEG-bytes) records;
``ImageNetSeqFileGenerator`` (models/utils/ImageNetSeqFileGenerator.scala)
converts a class-per-subfolder image tree into N such shard files;
``MTLabeledBGRImgToBatch`` decodes with per-core threads.

TPU-native design: a dependency-free binary record format (Hadoop
SequenceFile is a JVM artifact, not a wire standard worth emulating):

    shard file := MAGIC "BTR1", then per record:
                  float64 label (little-endian), uint32 len, len bytes
    sidecar    := <name>.idx — ASCII record count (cheap size() / resume)

Shards are independent files, so host processes map shards to themselves
(``process_index``) the way the reference maps partitions to executors, and
``MTImgToBatch`` + ``DevicePrefetcher`` overlap decode and host->device
transfer with the device step — the TPU equivalent of the reference's
per-core decode threads ahead of each Spark task.
"""
from __future__ import annotations

import json
import os
import struct
from pathlib import Path
from typing import Iterator, Sequence

import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.sample import ByteRecord
from bigdl_tpu.utils.random import RandomGenerator

__all__ = ["RecordWriter", "read_records", "generate_shards",
           "RecordShardDataSet", "DevicePrefetcher", "SHARD_SUFFIX"]

_MAGIC = b"BTR1"
SHARD_SUFFIX = ".brec"


class RecordWriter:
    """Append (raw bytes, label) records to one shard file."""

    def __init__(self, path: str):
        self.path = str(path)
        self._f = open(self.path, "wb")
        self._f.write(_MAGIC)
        self.count = 0

    def write(self, data: bytes, label: float):
        self._f.write(struct.pack("<dI", float(label), len(data)))
        self._f.write(data)
        self.count += 1

    def close(self):
        self._f.close()
        Path(self.path + ".idx").write_text(str(self.count))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_records(path: str, skip: int = 0) -> Iterator[ByteRecord]:
    """Stream ByteRecords from one shard file (optionally skipping the
    first ``skip`` records without decoding)."""
    with open(path, "rb") as f:
        if f.read(4) != _MAGIC:
            raise ValueError(f"{path} is not a record shard file")
        n = 0
        while True:
            head = f.read(12)
            if len(head) < 12:
                return
            label, size = struct.unpack("<dI", head)
            if n < skip:
                f.seek(size, os.SEEK_CUR)
            else:
                yield ByteRecord(f.read(size), label)
            n += 1


def shard_count(path: str) -> int:
    idx = Path(str(path) + ".idx")
    if idx.exists():
        return int(idx.read_text())
    return sum(1 for _ in read_records(str(path)))


def _reencode(path: str, scale_to: int) -> bytes:
    """Resize so the shorter side == ``scale_to`` (up OR down — croppers
    downstream assume at least crop-size images; the reference generator
    scales every image the same way, ImageNetSeqFileGenerator.scala) +
    JPEG re-encode."""
    import io
    from PIL import Image
    img = Image.open(path).convert("RGB")
    w, h = img.size
    if min(w, h) != scale_to:
        if w < h:
            img = img.resize((scale_to, max(1, round(h * scale_to / w))),
                             Image.BILINEAR)
        else:
            img = img.resize((max(1, round(w * scale_to / h)), scale_to),
                             Image.BILINEAR)
    buf = io.BytesIO()
    img.save(buf, "JPEG", quality=90)
    return buf.getvalue()


def generate_shards(image_folder: str, output_dir: str, num_shards: int = 8,
                    shuffle: bool = True, prefix: str = "shard",
                    scale_to: int | None = 256) -> list[str]:
    """Class-per-subfolder tree -> N shard files of raw image bytes +
    1-based labels (reference ImageNetSeqFileGenerator.scala — same
    round-robin record placement and 256-scaling, minus the Hadoop
    container). ``scale_to=None`` copies bytes verbatim."""
    from bigdl_tpu.dataset.image import LocalImageFiles
    pairs = LocalImageFiles.paths(image_folder)
    if shuffle:
        RandomGenerator.RNG().shuffle(pairs)
    os.makedirs(output_dir, exist_ok=True)
    paths = [os.path.join(output_dir,
                          f"{prefix}-{i:05d}-of-{num_shards:05d}"
                          f"{SHARD_SUFFIX}")
             for i in range(num_shards)]
    writers = [RecordWriter(p) for p in paths]
    try:
        for i, (path, label) in enumerate(pairs):
            if scale_to is not None:
                data = _reencode(path, scale_to)
            else:
                with open(path, "rb") as f:
                    data = f.read()
            writers[i % num_shards].write(data, label)
    finally:
        for w in writers:
            w.close()
    meta = {"num_shards": num_shards, "total": len(pairs),
            "counts": [w.count for w in writers]}
    Path(output_dir, "shards.json").write_text(json.dumps(meta))
    return paths


class RecordShardDataSet(AbstractDataSet):
    """Sharded dataset over record files (the SeqFileFolder role).

    ``process_index``/``process_count`` split the SHARD FILES across host
    processes (reference: RDD partitions pinned to executors); the training
    iterator loops endlessly over the local shards, rotating the shard
    order per pass via the same pure pass-counter scheme as
    ShardedDataSet so mid-epoch resume replays exactly.
    """

    def __init__(self, folder_or_paths, process_index: int = 0,
                 process_count: int = 1):
        if isinstance(folder_or_paths, (str, Path)):
            self._all_paths = sorted(
                str(p) for p in Path(folder_or_paths).iterdir()
                if p.name.endswith(SHARD_SUFFIX))
        else:
            self._all_paths = [str(p) for p in folder_or_paths]
        if not self._all_paths:
            raise ValueError("no record shard files found")
        self.process_index = process_index
        self.process_count = process_count
        self._local = self._all_paths[process_index::process_count]
        if not self._local:
            raise ValueError(
                f"process {process_index}/{process_count} got no shards — "
                "fewer shard files than processes")
        self._counts = {p: shard_count(p) for p in self._all_paths}
        self._order = np.arange(len(self._local))
        self._pass_count = 0

    def is_sharded(self):
        return self.process_count > 1

    def size(self) -> int:
        """Global record count (reference DistributedDataSet.size)."""
        return sum(self._counts.values())

    def local_size(self) -> int:
        return sum(self._counts[p] for p in self._local)

    def shuffle(self):
        RandomGenerator.RNG().shuffle(self._order)

    def get_position_state(self):
        return {"order": self._order.copy(),
                "passes_started": self._pass_count}

    def set_position_state(self, state, mid_pass: bool = False):
        self._order = np.asarray(state["order"]).copy()
        passes = int(np.asarray(state.get("passes_started", 0)))
        self._pass_count = passes - 1 if (mid_pass and passes > 0) else passes

    def _pass_rotation(self, k: int) -> int:
        mix = (RandomGenerator._default_seed * 2654435761
               + self.process_index * 40503 + k) % (2 ** 32)
        g = np.random.Generator(np.random.MT19937(mix))
        return int(g.integers(0, max(len(self._local), 1)))

    def data(self, train: bool):
        if train:
            def endless():
                while True:
                    k = self._pass_count
                    self._pass_count = k + 1
                    rot = self._pass_rotation(k)
                    order = np.roll(self._order, -rot)
                    for i in order:
                        yield from read_records(self._local[int(i)])
            return endless()

        def single():
            for i in self._order:
                yield from read_records(self._local[int(i)])
        return single()


class DevicePrefetcher:
    """Wrap a MiniBatch iterator; device_put batches ``depth`` ahead so
    host->device transfer overlaps the device step (the final stage of the
    reference's decode-ahead pipeline, MTLabeledBGRImgToBatch.scala:46-103,
    reborn as an input-pipeline stage feeding HBM)."""

    def __init__(self, sharding=None, depth: int = 2):
        self.sharding = sharding
        self.depth = depth

    def __call__(self, it):
        import jax
        from collections import deque
        from bigdl_tpu.dataset.sample import MiniBatch

        multi = jax.process_count() > 1

        def place(arr):
            if self.sharding is None:
                return jax.device_put(arr)
            if multi:
                # mesh spans non-addressable devices: assemble the global
                # array from this process's local batch, exactly like
                # DistriOptimizer._shard_batch's multi-host branch
                return jax.make_array_from_process_local_data(
                    self.sharding, arr)
            return jax.device_put(arr, self.sharding)

        def put(b):
            return MiniBatch(place(np.asarray(b.data)),
                             place(np.asarray(b.labels)))

        queue: deque = deque()
        for batch in it:
            queue.append(put(batch))
            if len(queue) > self.depth:
                yield queue.popleft()
        while queue:
            yield queue.popleft()

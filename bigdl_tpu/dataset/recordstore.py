"""Chunked record store — shard-local IO for the distributed data plane.

The reference's identity is deep learning over a big-data ingestion
pipeline (BigDL, arXiv:1804.05839): input lives in chunked, indexed
container files and each worker reads only its own partitions. The
``recordio`` shard files cover the many-files layout; this module is
the single-container rendering — one store file of fixed-size record
CHUNKS with a footer index, so a host process can open, map, and read
exactly the chunks assigned to its shard and nothing else (the same
chunked-layout thinking the checkpoint plane adopted, arXiv:2112.01075).

Layout (``.bcs``, dependency-free)::

    store := MAGIC "BCS1"
             chunk 0 bytes .. chunk K-1 bytes      (records back-to-back,
                                                    recordio BTR framing:
                                                    <d label, <I len, bytes)
             footer JSON (utf-8)
             <Q footer length
             MAGIC "BCS1"                           (trailer re-check)

    footer := {"version": 1, "chunk_records": N, "n_records": total,
               "codec": str|None,
               "chunks": [{"offset", "nbytes",
                           "record_offsets": [chunk-relative, ...]}, ...]}

Every chunk holds exactly ``chunk_records`` records except the last
(which may be short); per-record offsets in the footer give random
access WITHIN a chunk without scanning, which is what the per-chunk
shuffle in ``dataset/distributed.py`` needs.

The reader memory-maps the store lazily and accounts every chunk whose
bytes it actually touches (``chunks_opened`` / ``open_count``) — the
receipt the N-host bench drill pins to prove shard-local reads: a host
that opened a chunk outside its assignment is a bug, not a tuning
problem.

HOST-ONLY CONTRACT: no module-level jax import (jaxlint JX5 pins this
file) — the store is pure stdlib + numpy host machinery, importable and
testable with no device runtime.
"""
from __future__ import annotations

import json
import mmap
import struct
import threading

import numpy as np

from bigdl_tpu.dataset.sample import Sample

__all__ = ["ChunkedRecordWriter", "ChunkedRecordReader", "STORE_SUFFIX",
           "encode_sample", "decode_sample", "write_sample_store",
           "SAMPLE_CODEC"]

_MAGIC = b"BCS1"
_REC_HEAD = struct.Struct("<dI")      # float64 label, uint32 payload len
_TRAILER = struct.Struct("<Q4s")      # footer length + magic re-check

STORE_SUFFIX = ".bcs"
SAMPLE_CODEC = "sample-v1"


class ChunkedRecordWriter:
    """Append (raw bytes, label) records to one chunked store file.

    Records land in fixed-size chunks of ``chunk_records``; the footer
    index (chunk offsets + per-record offsets) is written by
    :meth:`close`, which is the commit point — a crash before it leaves
    a file the reader refuses (no trailer magic), never a torn index.
    """

    def __init__(self, path: str, chunk_records: int = 256,
                 codec: str | None = None):
        if int(chunk_records) < 1:
            raise ValueError(
                f"chunk_records must be >= 1, got {chunk_records}")
        self.path = str(path)
        self.chunk_records = int(chunk_records)
        self.codec = codec
        self._f = open(self.path, "wb")
        self._f.write(_MAGIC)
        self._chunks: list[dict] = []
        self._cur: dict | None = None
        self.count = 0
        self._closed = False

    def write(self, data: bytes, label: float = 0.0) -> None:
        if self._closed:
            raise ValueError(f"writer for {self.path} is closed")
        if self._cur is None or \
                len(self._cur["record_offsets"]) >= self.chunk_records:
            self._cur = {"offset": self._f.tell(), "nbytes": 0,
                         "record_offsets": []}
            self._chunks.append(self._cur)
        self._cur["record_offsets"].append(self._cur["nbytes"])
        head = _REC_HEAD.pack(float(label), len(data))
        self._f.write(head)
        self._f.write(data)
        self._cur["nbytes"] += len(head) + len(data)
        self.count += 1

    def close(self) -> dict:
        """Write the footer index + trailer; returns the footer."""
        if self._closed:
            return self._footer
        self._closed = True
        self._footer = {"version": 1, "chunk_records": self.chunk_records,
                        "n_records": self.count, "codec": self.codec,
                        "chunks": self._chunks}
        blob = json.dumps(self._footer).encode("utf-8")
        self._f.write(blob)
        self._f.write(_TRAILER.pack(len(blob), _MAGIC))
        self._f.close()
        return self._footer

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ChunkedRecordReader:
    """Footer-indexed, memory-mapped reader over one store file.

    Construction reads ONLY the footer (a tail seek); the store body is
    ``mmap``-ed lazily on the first chunk read, so a reader that never
    touches a chunk costs an index, not a dataset. Each chunk whose
    bytes are actually read is accounted in ``chunks_opened`` — the
    shard-local-IO receipt the distributed data plane pins.

    Thread use: the chunk-exchange thread (dataset/distributed.py)
    reads chunks while the consumer inspects the open accounting, so
    the lazy map + accounting are guarded by a small leaf lock.
    """

    def __init__(self, path: str):
        self.path = str(path)
        with open(self.path, "rb") as f:
            head = f.read(4)
            if head != _MAGIC:
                raise ValueError(f"{self.path} is not a chunked record "
                                 "store (bad magic)")
            f.seek(-_TRAILER.size, 2)
            blob_len, magic = _TRAILER.unpack(f.read(_TRAILER.size))
            if magic != _MAGIC:
                raise ValueError(
                    f"{self.path} has no store trailer — truncated or "
                    "the writer was never close()d")
            f.seek(-(_TRAILER.size + blob_len), 2)
            self._footer = json.loads(f.read(blob_len).decode("utf-8"))
        self.chunk_records = int(self._footer["chunk_records"])
        self.codec = self._footer.get("codec")
        self._chunks = self._footer["chunks"]
        self._mu = threading.Lock()
        self._file = None
        self._mm: mmap.mmap | None = None
        self._opened: list[int] = []    # chunk ids in first-touch order
        self._closed = False

    # -- index (footer only, never maps the body) ----------------------
    @property
    def n_chunks(self) -> int:
        return len(self._chunks)

    @property
    def n_records(self) -> int:
        return int(self._footer["n_records"])

    def chunk_record_count(self, chunk: int) -> int:
        return len(self._chunks[int(chunk)]["record_offsets"])

    # -- open accounting ------------------------------------------------
    @property
    def chunks_opened(self) -> list[int]:
        """Chunk ids whose BYTES this reader actually read, in
        first-touch order (the shard-local-IO receipt)."""
        with self._mu:
            return list(self._opened)

    @property
    def open_count(self) -> int:
        with self._mu:
            return len(self._opened)

    # -- mapped reads ---------------------------------------------------
    def _map(self, chunk: int) -> mmap.mmap:
        with self._mu:
            if self._closed:
                raise ValueError(f"reader for {self.path} is closed")
            if self._mm is None:
                self._file = open(self.path, "rb")
                self._mm = mmap.mmap(self._file.fileno(), 0,
                                     access=mmap.ACCESS_READ)
            if chunk not in self._opened:
                self._opened.append(chunk)
            return self._mm

    def _record_at(self, mm, base: int) -> tuple[bytes, float]:
        label, size = _REC_HEAD.unpack_from(mm, base)
        start = base + _REC_HEAD.size
        return bytes(mm[start:start + size]), float(label)

    def read_record(self, chunk: int, i: int) -> tuple[bytes, float]:
        """Random access to record ``i`` of ``chunk`` via the footer's
        per-record offsets — no scan."""
        c = self._chunks[int(chunk)]
        mm = self._map(int(chunk))
        return self._record_at(mm, c["offset"] + c["record_offsets"][i])

    def read_chunk(self, chunk: int) -> list[tuple[bytes, float]]:
        """All (payload, label) records of one chunk, in stored order."""
        c = self._chunks[int(chunk)]
        mm = self._map(int(chunk))
        return [self._record_at(mm, c["offset"] + off)
                for off in c["record_offsets"]]

    def close(self) -> None:
        # detach under the lock, release outside it: teardown must not
        # call into other objects while holding the reader's leaf lock
        with self._mu:
            self._closed = True
            mm, f = self._mm, self._file
            self._mm = None
            self._file = None
        if mm is not None:
            mm.close()
        if f is not None:
            f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# sample codec: ndarray feature + scalar label <-> store record
# ---------------------------------------------------------------------------

def encode_sample(feature, label) -> tuple[bytes, float]:
    """Serialize an ndarray feature to a store record payload: dtype
    string + shape header, then the raw bytes (C order)."""
    arr = np.ascontiguousarray(feature)
    dt = arr.dtype.str.encode("ascii")
    head = struct.pack("<BB", len(dt), arr.ndim) + dt \
        + struct.pack(f"<{arr.ndim}I", *arr.shape)
    return head + arr.tobytes(), float(label)


def decode_sample(data: bytes, label: float) -> Sample:
    """Inverse of :func:`encode_sample` (the default decode stage of
    ``DistributedShuffleDataSet`` for sample-codec stores)."""
    dt_len, ndim = struct.unpack_from("<BB", data, 0)
    pos = 2
    dt = np.dtype(data[pos:pos + dt_len].decode("ascii"))
    pos += dt_len
    shape = struct.unpack_from(f"<{ndim}I", data, pos)
    pos += 4 * ndim
    arr = np.frombuffer(data, dtype=dt, offset=pos).reshape(shape)
    return Sample(arr, label)


def write_sample_store(path: str, samples, chunk_records: int = 256) -> str:
    """Convenience: one store file from an iterable of Samples (scalar
    labels), tagged with the sample codec so readers decode by
    default."""
    with ChunkedRecordWriter(path, chunk_records=chunk_records,
                             codec=SAMPLE_CODEC) as w:
        for s in samples:
            data, label = encode_sample(s.feature, s.label)
            w.write(data, label)
    return str(path)

"""MiniBatch assembly through the native (C++) decode core.

``NativeBRecToBatch`` is the drop-in fast path for the record-shard
pipeline: ByteRecords -> (decode + crop + flip + normalize + NCHW stack)
in ``native/btr_loader.cpp``'s thread pool, with the NEXT batch decoding
in the background while the trainer consumes the current one. Semantics
mirror the Python chain ``BytesToBGRImg >> BGRImgCropper >> HFlip >>
BGRImgNormalizer >> MTImgToBatch`` (augment randomness comes from a
different — per-record, seed-stable — stream, like the reference's
per-thread generators differ from single-threaded order).
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as np

from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.utils.random import RandomGenerator

__all__ = ["NativeBRecToBatch"]


class NativeBRecToBatch(Transformer):
    def __init__(self, batch_size: int, crop_width: int, crop_height: int,
                 train: bool, mean_rgb, std_rgb, num_threads: int = 8,
                 flip_prob: float | None = None):
        from bigdl_tpu import native
        if not native.available():
            raise RuntimeError(
                "native loader unavailable — use MTImgToBatch instead")
        self.batch_size = batch_size
        self.cw, self.ch = crop_width, crop_height
        self.train = train
        r, g, b = mean_rgb
        self.mean_bgr = (b, g, r)
        r, g, b = std_rgb
        self.std_bgr = (b, g, r)
        self.num_threads = num_threads
        self.flip_prob = (0.5 if train else 0.0) if flip_prob is None \
            else flip_prob

    def _python_decode_one(self, rec, seed):
        """Fallback for records libjpeg rejects (e.g. ImageNet's CMYK
        JPEGs, which PIL converts): run the equivalent Python chain so the
        native path trains on EXACTLY the same records as the Python
        path — and a truly corrupt record raises loudly, as
        MTImgToBatch's pipeline would. The worker thread's RNG is seeded
        from the (checkpoint-replayable) batch seed so the fallback's
        crops/flips neither repeat per epoch nor break exact resume."""
        RandomGenerator.seed_thread(seed & (2 ** 63 - 1))
        from bigdl_tpu.dataset.image import (BGRImgCropper,
                                             BGRImgNormalizer,
                                             BytesToBGRImg, CropCenter,
                                             CropRandom, HFlip)
        mean_b, mean_g, mean_r = self.mean_bgr
        std_b, std_g, std_r = self.std_bgr
        pipe = (BytesToBGRImg()
                >> BGRImgCropper(self.cw, self.ch,
                                 CropRandom if self.train else CropCenter)
                >> HFlip(self.flip_prob)
                >> BGRImgNormalizer(mean_r, mean_g, mean_b,
                                    std_r, std_g, std_b))
        img = next(iter(pipe(iter([rec]))))
        return np.transpose(img.content, (2, 0, 1)).astype(np.float32)

    def _decode(self, records, seed):
        from bigdl_tpu import native
        jpegs = [r.data for r in records]
        labels = np.asarray([r.label for r in records], np.float32)
        batch, status = native.decode_crop_batch(
            jpegs, self.ch, self.cw, random_crop=self.train,
            flip_prob=self.flip_prob, mean_bgr=self.mean_bgr,
            std_bgr=self.std_bgr, seed=seed,
            num_threads=self.num_threads)
        for i in np.nonzero(status != 0)[0]:
            batch[i] = self._python_decode_one(records[int(i)],
                                               seed ^ (int(i) + 1))
        return MiniBatch(batch, labels)

    def __call__(self, it):
        def chunks():
            buf = []
            for rec in it:
                buf.append(rec)
                if len(buf) == self.batch_size:
                    yield buf
                    buf = []
            if buf:
                yield buf

        chunk_iter = chunks()

        def task(seed):
            # record READ + decode both live in the background thread, so
            # delivering batch k never waits on batch k+1's disk I/O
            chunk = next(chunk_iter, None)
            return None if chunk is None else self._decode(chunk, seed)

        eval_counter = [0]

        def draw_seed():
            # Train: drawn on the CONSUMER thread — one draw per batch
            # from the host RNG stream the checkpoint system snapshots
            # and fast-forwards, so augmentation survives exact mid-epoch
            # resume AND differs across epochs (a process-local counter
            # would reset on resume and replay epoch-1 seeds).
            # Eval: MUST NOT touch the checkpointed stream (a validation
            # pass would advance it past what resume replays) — a local
            # counter still varies per batch for flip_prob>0 eval setups.
            if not self.train:
                # distinct mixing constant from seed_worker's (so eval
                # streams never collide with train worker streams in the
                # same process)
                eval_counter[0] += 1
                return (RandomGenerator._default_seed
                        + 0x27D4EB2F * eval_counter[0] + 0x165667B1)
            return int(RandomGenerator.RNG().random_int(0, 2 ** 63))

        with ThreadPoolExecutor(max_workers=1) as pool:
            pending = pool.submit(task, draw_seed())
            while True:
                nxt = pool.submit(task, draw_seed())
                batch = pending.result()
                if batch is None:
                    break
                yield batch
                pending = nxt

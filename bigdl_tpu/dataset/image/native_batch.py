"""MiniBatch assembly through the native (C++) decode core.

``NativeBRecToBatch`` is the drop-in fast path for the record-shard
pipeline: ByteRecords -> (decode + crop + flip + normalize + NCHW stack)
in ``native/btr_loader.cpp``'s thread pool, with the NEXT batch decoding
in the background while the trainer consumes the current one. Semantics
mirror the Python chain ``BytesToBGRImg >> BGRImgCropper >> HFlip >>
BGRImgNormalizer >> MTImgToBatch`` (augment randomness comes from a
different — per-record, seed-stable — stream, like the reference's
per-thread generators differ from single-threaded order).
"""
from __future__ import annotations

import hashlib
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.utils.random import RandomGenerator

__all__ = ["NativeBRecToBatch"]


class NativeBRecToBatch(Transformer):
    """``device_normalize=True`` switches to the u8 fast path: the host
    emits raw (N, H, W, 3) uint8 RGB crops and the consumer must install
    ``self.device_transform()`` via ``Optimizer.set_input_transform`` so
    normalize/BGR/NCHW runs inside the jitted step (4x smaller transfers,
    2.2x host decode rate — docs/PERF.md round 4).

    ``cache_bytes > 0`` (u8 mode only) additionally keeps decoded full
    images in RAM up to the budget, content-keyed: epochs after warm-up
    crop/flip straight from memory (measured ~9k img/s vs ~1.9k with
    decode) — the FFCV/DALI-style decoded cache, for datasets (or
    dataset fractions) that fit host RAM. Augment draws are per-record
    seeded, so what is or isn't cached never changes the crops."""

    def __init__(self, batch_size: int, crop_width: int, crop_height: int,
                 train: bool, mean_rgb, std_rgb,
                 num_threads: int | None = None,
                 flip_prob: float | None = None,
                 device_normalize: bool = False, cache_bytes: int = 0,
                 fast_dct: bool = False):
        from bigdl_tpu import native
        if not native.available():
            raise RuntimeError(
                "native loader unavailable — use MTImgToBatch instead")
        self.batch_size = batch_size
        self.cw, self.ch = crop_width, crop_height
        self.train = train
        self.mean_rgb, self.std_rgb = tuple(mean_rgb), tuple(std_rgb)
        r, g, b = mean_rgb
        self.mean_bgr = (b, g, r)
        r, g, b = std_rgb
        self.std_bgr = (b, g, r)
        self.num_threads = num_threads or native.default_threads()
        self.flip_prob = (0.5 if train else 0.0) if flip_prob is None \
            else flip_prob
        self.device_normalize = device_normalize
        self.fast_dct = fast_dct
        self._cache: dict | None = None
        self._cache_left = 0
        if cache_bytes > 0:
            if not device_normalize:
                raise ValueError("cache_bytes needs device_normalize=True")
            self._cache = {}
            self._cache_left = int(cache_bytes)

    def device_transform(self, out_dtype=None):
        """The on-device tail for ``Optimizer.set_input_transform``."""
        from bigdl_tpu.dataset.image.device_transform import \
            u8_to_model_input
        return u8_to_model_input(self.mean_rgb, self.std_rgb, out_dtype)

    def _python_decode_one(self, rec, seed):
        """Fallback for records libjpeg rejects (e.g. ImageNet's CMYK
        JPEGs, which PIL converts): run the equivalent Python chain so the
        native path trains on EXACTLY the same records as the Python
        path — and a truly corrupt record raises loudly, as
        MTImgToBatch's pipeline would. The worker thread's RNG is seeded
        from the (checkpoint-replayable) batch seed so the fallback's
        crops/flips neither repeat per epoch nor break exact resume."""
        RandomGenerator.seed_thread(seed & (2 ** 63 - 1))
        from bigdl_tpu.dataset.image import (BGRImgCropper,
                                             BGRImgNormalizer,
                                             BytesToBGRImg, CropCenter,
                                             CropRandom, HFlip)
        mean_b, mean_g, mean_r = self.mean_bgr
        std_b, std_g, std_r = self.std_bgr
        pipe = (BytesToBGRImg()
                >> BGRImgCropper(self.cw, self.ch,
                                 CropRandom if self.train else CropCenter)
                >> HFlip(self.flip_prob)
                >> BGRImgNormalizer(mean_r, mean_g, mean_b,
                                    std_r, std_g, std_b))
        img = next(iter(pipe(iter([rec]))))
        return np.transpose(img.content, (2, 0, 1)).astype(np.float32)

    def _python_decode_one_u8(self, rec, seed):
        """u8-mode corrupt-record fallback: same chain as
        ``_python_decode_one`` minus the normalizer, mapped back to uint8
        RGB HWC (contents are k/255 floats, so rint recovers k exactly)."""
        RandomGenerator.seed_thread(seed & (2 ** 63 - 1))
        from bigdl_tpu.dataset.image import (BGRImgCropper, BytesToBGRImg,
                                             CropCenter, CropRandom, HFlip)
        pipe = (BytesToBGRImg()
                >> BGRImgCropper(self.cw, self.ch,
                                 CropRandom if self.train else CropCenter)
                >> HFlip(self.flip_prob))
        img = next(iter(pipe(iter([rec]))))
        return np.rint(img.content[:, :, ::-1] * 255.0).astype(np.uint8)

    def _decode_u8(self, records, seed):
        from bigdl_tpu import native
        n = len(records)
        labels = np.asarray([r.label for r in records], np.float32)
        seeds = native.record_seeds(seed, range(n))

        def run(idx, full_outs=None):
            jpegs = [records[i].data for i in idx]
            return native.decode_crop_batch_u8(
                jpegs, self.ch, self.cw, random_crop=self.train,
                flip_prob=self.flip_prob, fast_dct=self.fast_dct,
                seed=seeds[idx], num_threads=self.num_threads,
                full_outs=full_outs)

        all_idx = np.arange(n)
        if self._cache is None:
            batch, status = run(all_idx)
        else:
            # stable record identity when the source provides one
            # (read_records tags (shard, index)); digesting the payload is
            # the fallback — and measurably worse (~tens of ms per
            # 256-batch on the 1-core host), so sources should tag keys.
            # blake2b-128, not hash(): a 64-bit SipHash collision between
            # two JPEGs would silently serve the wrong cached image for
            # the rest of training (advisor finding, r4)
            keys = [r.key if r.key is not None
                    else hashlib.blake2b(r.data, digest_size=16).digest()
                    for r in records]
            hit = np.asarray([i for i in all_idx
                              if keys[i] in self._cache], np.int64)
            miss = np.asarray([i for i in all_idx
                               if keys[i] not in self._cache], np.int64)
            batch = np.empty((n, self.ch, self.cw, 3), np.uint8)
            status = np.zeros((n,), np.int8)
            if hit.size:
                batch[hit] = native.crop_batch_from_raw(
                    [self._cache[keys[i]] for i in hit], self.ch, self.cw,
                    random_crop=self.train, flip_prob=self.flip_prob,
                    seed=seeds[hit], num_threads=self.num_threads)
            if miss.size:
                # fill the cache while decoding, up to the byte budget
                full_outs, fill = [], []
                hs, ws = native.jpeg_dims([records[i].data for i in miss])
                for j, i in enumerate(miss):
                    sz = int(hs[j]) * int(ws[j]) * 3
                    if 0 < sz <= self._cache_left \
                            and keys[i] not in self._cache:
                        buf = np.empty((int(hs[j]), int(ws[j]), 3),
                                       np.uint8)
                        self._cache[keys[i]] = buf   # reserves dup keys too
                        self._cache_left -= sz
                        full_outs.append(buf)
                        fill.append(i)
                    else:
                        full_outs.append(None)
                sub, st = run(miss, full_outs=full_outs)
                batch[miss], status[miss] = sub, st
                for j, i in enumerate(miss):
                    if status[i] != 0 and keys[i] in self._cache \
                            and i in fill:
                        buf = self._cache.pop(keys[i])   # corrupt: unfill
                        self._cache_left += buf.nbytes
        for i in np.nonzero(status != 0)[0]:
            batch[i] = self._python_decode_one_u8(records[int(i)],
                                                  seed ^ (int(i) + 1))
        return MiniBatch(batch, labels)

    def _decode(self, records, seed):
        from bigdl_tpu import native
        if self.device_normalize:
            return self._decode_u8(records, seed)
        jpegs = [r.data for r in records]
        labels = np.asarray([r.label for r in records], np.float32)
        batch, status = native.decode_crop_batch(
            jpegs, self.ch, self.cw, random_crop=self.train,
            flip_prob=self.flip_prob, mean_bgr=self.mean_bgr,
            std_bgr=self.std_bgr, seed=seed,
            num_threads=self.num_threads)
        for i in np.nonzero(status != 0)[0]:
            batch[i] = self._python_decode_one(records[int(i)],
                                               seed ^ (int(i) + 1))
        return MiniBatch(batch, labels)

    def __call__(self, it):
        def chunks():
            buf = []
            for rec in it:
                buf.append(rec)
                if len(buf) == self.batch_size:
                    yield buf
                    buf = []
            if buf:
                yield buf

        chunk_iter = chunks()

        def task(seed):
            # record READ + decode both live in the background thread, so
            # delivering batch k never waits on batch k+1's disk I/O
            chunk = next(chunk_iter, None)
            return None if chunk is None else self._decode(chunk, seed)

        eval_counter = [0]

        def draw_seed():
            # Train: drawn on the CONSUMER thread — one draw per batch
            # from the host RNG stream the checkpoint system snapshots
            # and fast-forwards, so augmentation survives exact mid-epoch
            # resume AND differs across epochs (a process-local counter
            # would reset on resume and replay epoch-1 seeds).
            # Eval: MUST NOT touch the checkpointed stream (a validation
            # pass would advance it past what resume replays) — a local
            # counter still varies per batch for flip_prob>0 eval setups.
            if not self.train:
                # distinct mixing constant from seed_worker's (so eval
                # streams never collide with train worker streams in the
                # same process)
                eval_counter[0] += 1
                return (RandomGenerator._default_seed
                        + 0x27D4EB2F * eval_counter[0] + 0x165667B1)
            return int(RandomGenerator.RNG().random_int(0, 2 ** 63))

        # no `with`: a consumer abandoning the generator mid-stream (end
        # trigger, benchmark window) closes it during GC, where
        # ThreadPoolExecutor.__exit__'s join() can hit torn-down threading
        # internals at interpreter exit; shutdown(wait=False) is safe in
        # both lifecycles
        pool = ThreadPoolExecutor(max_workers=1)
        try:
            pending = pool.submit(task, draw_seed())
            while True:
                nxt = pool.submit(task, draw_seed())
                batch = pending.result()
                if batch is None:
                    break
                yield batch
                pending = nxt
        finally:
            pool.shutdown(wait=False)

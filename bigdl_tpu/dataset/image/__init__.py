"""Image data pipeline (reference dataset/image/, SURVEY §2.5)."""

from bigdl_tpu.dataset.image.types import (LabeledImage, LabeledBGRImage,
                                           LabeledGreyImage)
from bigdl_tpu.dataset.image.transforms import (
    BytesToBGRImg, BytesToGreyImg, LocalImgReader, LocalImgReaderWithName,
    BGRImgToImageVector, LocalImageFiles,
    BGRImgCropper, GreyImgCropper, BGRImgRdmCropper, CropRandom, CropCenter,
    BGRImgNormalizer, GreyImgNormalizer, BGRImgPixelNormalizer,
    HFlip, ColorJitter, Lighting,
    BGRImgToBatch, GreyImgToBatch, MTImgToBatch)

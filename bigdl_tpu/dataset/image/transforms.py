"""Image transformers (reference dataset/image/, 22 files ~1,900 LoC).

Reference parity (SURVEY §2.5): decode (BytesToBGRImg/BytesToGreyImg/
LocalImgReader), crop (BGRImgCropper CropRandom|CropCenter, GreyImgCropper,
BGRImgRdmCropper), normalize (BGRImgNormalizer incl. dataset-statistics
fitting, GreyImgNormalizer, BGRImgPixelNormalizer), augment (HFlip,
ColorJitter, Lighting), batch (BGRImgToBatch/GreyImgToBatch emitting NCHW).

TPU-first: per-image ops are vectorized numpy on the host (they feed the
device, they don't run on it); batch assembly is one ``np.stack`` +
layout transpose into the NCHW arrays ``DistriOptimizer`` shards onto the
mesh. The reference's multi-threaded batch assembly
(MTLabeledBGRImgToBatch.scala:46-103) is ``MTImgToBatch`` backed by a
thread pool + prefetch queue.
"""
from __future__ import annotations

import io
import queue
import threading
from pathlib import Path

import numpy as np

from bigdl_tpu.dataset.image.types import (LabeledBGRImage, LabeledGreyImage)
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.utils.random import RandomGenerator

__all__ = [
    "BytesToBGRImg", "BytesToGreyImg", "LocalImgReader",
    "LocalImgReaderWithName", "BGRImgToImageVector", "LocalImageFiles",
    "BGRImgCropper", "GreyImgCropper", "BGRImgRdmCropper", "CropRandom",
    "CropCenter", "BGRImgNormalizer", "GreyImgNormalizer",
    "BGRImgPixelNormalizer", "HFlip", "ColorJitter", "Lighting",
    "BGRImgToBatch", "GreyImgToBatch", "MTImgToBatch",
]

CropRandom = "random"
CropCenter = "center"


def _decode(raw: bytes, grey: bool):
    from PIL import Image
    img = Image.open(io.BytesIO(raw))
    img = img.convert("L" if grey else "RGB")
    return np.asarray(img, np.float32)


class BytesToBGRImg(Transformer):
    """Decode raw image bytes -> LabeledBGRImage (reference
    BytesToBGRImg.scala; javax.imageio -> PIL). Input: ByteRecord."""

    def __init__(self, normalize: float = 255.0):
        self.normalize = normalize

    def __call__(self, it):
        for rec in it:
            rgb = _decode(rec.data, grey=False) / self.normalize
            yield LabeledBGRImage(rgb[:, :, ::-1], rec.label)


class BytesToGreyImg(Transformer):
    """(reference BytesToGreyImg.scala)"""

    def __init__(self, normalize: float = 255.0):
        self.normalize = normalize

    def __call__(self, it):
        for rec in it:
            yield LabeledGreyImage(_decode(rec.data, grey=True)
                                   / self.normalize, rec.label)


class LocalImgReader(Transformer):
    """(path, label) -> LabeledBGRImage. ``scale_to`` as an int resizes
    keeping aspect so the shorter side matches (reference
    LocalImgReader.scala); a ``(width, height)`` tuple resizes exactly
    (the reference's two-arg overload used by AlexNetPreprocessor)."""

    def __init__(self, scale_to=None, normalize: float = 255.0):
        self.scale_to = scale_to
        self.normalize = normalize

    def __call__(self, it):
        from PIL import Image
        for path, label in it:
            img = Image.open(path).convert("RGB")
            if isinstance(self.scale_to, (tuple, list)):
                img = img.resize(tuple(self.scale_to), Image.BILINEAR)
            elif self.scale_to is not None:
                w, h = img.size
                if w < h:
                    nw, nh = self.scale_to, int(h * self.scale_to / w)
                else:
                    nw, nh = int(w * self.scale_to / h), self.scale_to
                img = img.resize((nw, nh), Image.BILINEAR)
            rgb = np.asarray(img, np.float32) / self.normalize
            yield LabeledBGRImage(rgb[:, :, ::-1], label)


class LocalImgReaderWithName(LocalImgReader):
    """Like ``LocalImgReader`` but yields ``(image, file_name)`` pairs —
    the DataFrame-facing variant (reference
    LocalImgReaderWithName.scala:29-66: same decode/scale/normalize, plus
    the path's file name for joining predictions back to rows)."""

    def __call__(self, it):
        import os
        for path, label in it:
            img = next(iter(super().__call__(iter([(path, label)]))))
            yield img, os.path.basename(path)


class BGRImgToImageVector(Transformer):
    """LabeledBGRImage -> flat float64 feature vector (reference
    BGRImgToImageVector.scala:33-49: ``copyTo(..., toRGB=True)`` then a
    DenseVector — the Spark-ML ingestion shape). Channel order in the
    flat vector is RGB-interleaved per pixel, matching the reference's
    ``toRGB=true`` copy."""

    def __call__(self, it):
        for img in it:
            rgb = img.content[:, :, ::-1]          # BGR planes -> RGB
            yield rgb.reshape(-1).astype(np.float64)


class LocalImageFiles:
    """Scan a class-per-subfolder tree into (path, label) pairs with labels
    assigned by sorted folder name, 1-based (reference
    LocalImageFiles.scala)."""

    @staticmethod
    def paths(folder: str, shuffle: bool = False):
        root = Path(folder)
        classes = sorted(p.name for p in root.iterdir() if p.is_dir())
        out = []
        for li, cname in enumerate(classes):
            for f in sorted((root / cname).iterdir()):
                if f.is_file():
                    out.append((str(f), float(li + 1)))
        if shuffle:
            RandomGenerator.RNG().shuffle(out)
        return out


class _Cropper(Transformer):
    def __init__(self, crop_width: int, crop_height: int,
                 crop_method: str = CropRandom):
        self.cw, self.ch = crop_width, crop_height
        self.method = crop_method

    def _offsets(self, h, w):
        if self.method == CropRandom:
            rng = RandomGenerator.RNG()
            y = int(rng.random_int(0, h - self.ch + 1))
            x = int(rng.random_int(0, w - self.cw + 1))
        else:
            y = (h - self.ch) // 2
            x = (w - self.cw) // 2
        return y, x

    def __call__(self, it):
        for img in it:
            h, w = img.content.shape[:2]
            y, x = self._offsets(h, w)
            yield img.with_content(img.content[y:y + self.ch, x:x + self.cw])


class BGRImgCropper(_Cropper):
    """(reference BGRImgCropper.scala; CropRandom|CropCenter)"""


class GreyImgCropper(_Cropper):
    """(reference GreyImgCropper.scala)"""


class BGRImgRdmCropper(Transformer):
    """Random crop after zero-padding by ``padding`` on each spatial side
    (reference BGRImgRdmCropper.scala — the CIFAR pad-4-crop-32 augment)."""

    def __init__(self, crop_width: int, crop_height: int, padding: int):
        self.cw, self.ch, self.pad = crop_width, crop_height, padding

    def __call__(self, it):
        rng = RandomGenerator.RNG()
        for img in it:
            c = np.pad(img.content,
                       [(self.pad, self.pad), (self.pad, self.pad), (0, 0)])
            h, w = c.shape[:2]
            y = int(rng.random_int(0, h - self.ch + 1))
            x = int(rng.random_int(0, w - self.cw + 1))
            yield img.with_content(c[y:y + self.ch, x:x + self.cw])


class BGRImgNormalizer(Transformer):
    """Per-channel (x - mean) / std, channels given R,G,B like the
    reference's ctor (reference BGRImgNormalizer.scala)."""

    def __init__(self, mean_r, mean_g=None, mean_b=None,
                 std_r=None, std_g=None, std_b=None):
        if mean_g is None:  # ((r,g,b), (r,g,b)) overload
            (mean_r, mean_g, mean_b), (std_r, std_g, std_b) = mean_r, std_r
        # contents are BGR: reverse to per-channel [B, G, R]
        self.mean = np.asarray([mean_b, mean_g, mean_r], np.float32)
        self.std = np.asarray([std_b, std_g, std_r], np.float32)

    @classmethod
    def fit(cls, dataset, samples: int = -1):
        """Estimate mean/std from a LocalDataSet of images (reference
        BGRImgNormalizer.apply(dataSet, samples))."""
        it = dataset.data(train=False)
        n = dataset.size() if samples < 0 else samples
        acc = np.zeros(3, np.float64)
        acc2 = np.zeros(3, np.float64)
        count = 0
        for _ in range(n):
            c = next(it).content.reshape(-1, 3)
            acc += c.sum(0)
            acc2 += (c.astype(np.float64) ** 2).sum(0)
            count += c.shape[0]
        mean = acc / count                      # [B, G, R]
        std = np.sqrt(acc2 / count - mean ** 2)
        return cls(mean[2], mean[1], mean[0], std[2], std[1], std[0])

    def get_mean(self):
        return tuple(self.mean[::-1])

    def get_std(self):
        return tuple(self.std[::-1])

    def __call__(self, it):
        for img in it:
            yield img.with_content((img.content - self.mean) / self.std)


class GreyImgNormalizer(Transformer):
    """(reference GreyImgNormalizer.scala)"""

    def __init__(self, mean: float, std: float):
        self.mean, self.std = mean, std

    @classmethod
    def fit(cls, dataset, samples: int = -1):
        it = dataset.data(train=False)
        n = dataset.size() if samples < 0 else samples
        acc = acc2 = 0.0
        count = 0
        for _ in range(n):
            c = next(it).content
            acc += float(c.sum())
            acc2 += float((c.astype(np.float64) ** 2).sum())
            count += c.size
        mean = acc / count
        return cls(mean, float(np.sqrt(acc2 / count - mean ** 2)))

    def __call__(self, it):
        for img in it:
            yield img.with_content((img.content - self.mean) / self.std)


class BGRImgPixelNormalizer(Transformer):
    """Subtract a per-pixel mean image (reference
    BGRImgPixelNormalizer.scala — used with Caffe mean files)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def __call__(self, it):
        for img in it:
            yield img.with_content(
                img.content - self.means.reshape(img.content.shape))


class HFlip(Transformer):
    """Horizontal flip with probability ``threshold``
    (reference HFlip.scala)."""

    def __init__(self, threshold: float = 0.0):
        self.threshold = threshold

    def __call__(self, it):
        rng = RandomGenerator.RNG()
        for img in it:
            if rng.uniform() < self.threshold:
                yield img.with_content(img.content[:, ::-1].copy())
            else:
                yield img


class ColorJitter(Transformer):
    """Random brightness/contrast/saturation in random order, each
    alpha ~ U(1-v, 1+v), v=0.4, blending with grey/mean targets
    (reference ColoJitter.scala — the fb.resnet.torch recipe)."""

    def __init__(self, brightness: float = 0.4, contrast: float = 0.4,
                 saturation: float = 0.4):
        self.variances = {"brightness": brightness, "contrast": contrast,
                          "saturation": saturation}

    @staticmethod
    def _grey(c):
        # contents are BGR
        g = (c[..., 2] * 0.299 + c[..., 1] * 0.587 + c[..., 0] * 0.114)
        return g[..., None]

    def _jitter(self, c, rng):
        order = rng.permutation(3)
        for k in order:
            name = ("brightness", "contrast", "saturation")[int(k)]
            alpha = 1.0 + float(rng.uniform(-self.variances[name],
                                            self.variances[name]))
            if name == "brightness":
                target = np.zeros_like(c)
            elif name == "saturation":
                target = np.broadcast_to(self._grey(c), c.shape)
            else:  # contrast: blend toward the grey mean
                target = np.full_like(c, self._grey(c).mean())
            c = c * alpha + target * (1.0 - alpha)
        return c

    def __call__(self, it):
        rng = RandomGenerator.RNG()
        for img in it:
            yield img.with_content(
                self._jitter(img.content, rng).astype(np.float32))


class Lighting(Transformer):
    """AlexNet-style PCA lighting noise (reference Lighting.scala —
    alphastd 0.1, fixed ImageNet eigenvalues/vectors; channel order in the
    reference's arrays is RGB-indexed but applied to BGR content — here
    applied to the true channels)."""

    ALPHASTD = 0.1
    EIGVAL = np.asarray([0.2175, 0.0188, 0.0045], np.float32)
    EIGVEC = np.asarray([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]], np.float32)

    def __call__(self, it):
        rng = RandomGenerator.RNG()
        for img in it:
            alpha = rng.uniform(0, self.ALPHASTD, 3).astype(np.float32)
            rgb = (self.EIGVEC * alpha[None, :] *
                   self.EIGVAL[None, :]).sum(1)
            yield img.with_content(img.content + rgb[::-1][None, None, :])


class _ToBatch(Transformer):
    """Stack images into NCHW MiniBatches (reference BGRImgToBatch.scala /
    GreyImgToBatch.scala)."""

    def __init__(self, batch_size: int, drop_remainder: bool = False):
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder

    @staticmethod
    def _to_chw(content: np.ndarray) -> np.ndarray:
        if content.ndim == 2:
            return content[None]            # grey -> (1, H, W)
        return np.transpose(content, (2, 0, 1))

    def __call__(self, it):
        feats, labels = [], []
        for img in it:
            feats.append(self._to_chw(img.content))
            labels.append(img.label)
            if len(feats) == self.batch_size:
                yield MiniBatch(np.stack(feats),
                                np.asarray(labels, np.float32))
                feats, labels = [], []
        if feats and not self.drop_remainder:
            yield MiniBatch(np.stack(feats), np.asarray(labels, np.float32))


class BGRImgToBatch(_ToBatch):
    """(reference BGRImgToBatch.scala)"""


class GreyImgToBatch(_ToBatch):
    """(reference GreyImgToBatch.scala)"""


class MTImgToBatch(Transformer):
    """Multi-threaded batch assembly with bounded prefetch (reference
    MTLabeledBGRImgToBatch.scala:46-103 — one transformer clone per core,
    atomic slot claim).

    ``inner`` is the per-record transformer pipeline to run in parallel
    (e.g. decode >> crop >> normalize); each worker owns a clone
    (``clone_transformer``, matching the reference's per-thread clones).
    Batches come out in order; up to ``prefetch`` batches are buffered so
    host decode overlaps device compute — the TPU input-pipeline equivalent.
    """

    def __init__(self, batch_size: int, inner: Transformer,
                 num_threads: int = 4, prefetch: int = 4,
                 to_chw: bool = True):
        self.batch_size = batch_size
        self.inner = inner
        self.num_threads = num_threads
        self.prefetch = prefetch
        self.to_chw = to_chw
        self._invocation = 0

    def _assemble(self, records):
        feats, labels = [], []
        for img in records:
            c = img.content
            if self.to_chw:
                c = _ToBatch._to_chw(c)
            feats.append(c)
            labels.append(img.label)
        return MiniBatch(np.stack(feats), np.asarray(labels, np.float32))

    def __call__(self, it):
        out_q: "queue.Queue" = queue.Queue(maxsize=max(1, self.prefetch))
        # bounded: backpressure must reach the decode workers, or with an
        # endless source they decode ahead without limit
        claim_q: "queue.Queue" = queue.Queue(
            maxsize=max(1, self.prefetch) + self.num_threads)
        stop = object()
        shutdown = threading.Event()
        errors: list = []  # first worker/producer exception, re-raised
        invocation = self._invocation
        self._invocation += 1

        def safe_put(q, item) -> bool:
            while not shutdown.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                workers = [self.inner.clone_transformer()
                           for _ in range(self.num_threads)]
                lock = threading.Lock()
                seq_counter = [0]

                def pull_chunk():
                    """Claim the next chunk under the lock: (seq, records).
                    Chunks are full batch_size except the final one, so
                    at most one short tail batch is ever emitted."""
                    with lock:
                        chunk = []
                        try:
                            for _ in range(self.batch_size):
                                if shutdown.is_set():
                                    break
                                chunk.append(next(it))
                        except StopIteration:
                            pass
                        seq = seq_counter[0]
                        if chunk:
                            seq_counter[0] += 1
                        return seq, chunk

                def worker(widx, w):
                    # a decode/transform exception must not kill the thread
                    # silently: record it and wake the pipeline, or the
                    # dispatcher waits on finished<num_threads forever
                    RandomGenerator.seed_worker(widx, invocation)
                    try:
                        while not shutdown.is_set():
                            seq, chunk = pull_chunk()
                            if not chunk:
                                break
                            if not safe_put(claim_q,
                                            (seq, list(w(iter(chunk))))):
                                return
                    except BaseException as e:  # noqa: BLE001
                        errors.append(e)
                        shutdown.set()
                    finally:
                        safe_put(claim_q, (None, stop))

                threads = [threading.Thread(target=worker, args=(i, w),
                                            daemon=True)
                           for i, w in enumerate(workers)]
                for t in threads:
                    t.start()
                # emit strictly in claim order (reference emits batches in
                # slot-claim order, MTLabeledBGRImgToBatch.scala:46-103)
                pending: dict = {}
                next_seq = 0
                finished = 0
                while finished < self.num_threads and not shutdown.is_set():
                    try:
                        seq, got = claim_q.get(timeout=0.1)
                    except queue.Empty:
                        continue
                    if got is stop:
                        finished += 1
                        continue
                    pending[seq] = got
                    while next_seq in pending:
                        if not safe_put(
                                out_q,
                                self._assemble(pending.pop(next_seq))):
                            return
                        next_seq += 1
                # seqs are claimed contiguously and every claimed chunk is
                # enqueued before its worker's stop marker, so the in-order
                # drain above must have emptied pending on a clean finish
                assert shutdown.is_set() or not pending, \
                    f"unflushed chunks: {sorted(pending)}"
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
            finally:
                shutdown.set()   # unblock any worker stuck on claim_q
                try:
                    out_q.put_nowait(stop)
                except queue.Full:
                    pass

        threading.Thread(target=producer, daemon=True).start()
        try:
            while True:
                try:
                    batch = out_q.get(timeout=0.1)
                except queue.Empty:
                    if shutdown.is_set():
                        if errors:
                            raise errors[0]
                        return
                    continue
                if batch is stop:
                    if errors:
                        raise errors[0]
                    return
                yield batch
        finally:
            # consumer abandoned the iterator (epoch rollover over an
            # endless source): wind every thread down
            shutdown.set()

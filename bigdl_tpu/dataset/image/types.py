"""Image carriers (reference dataset/image/Types.scala — LabeledBGRImage /
LabeledGreyImage, 375 LoC of manual float-array plumbing).

TPU-first: images are numpy ``(H, W, C)`` float32 arrays (C=3 BGR to match
the reference's channel order, or C absent for grey); the to-batch
transformers emit NCHW MiniBatches ready for ``jax.device_put``.
"""
from __future__ import annotations

import numpy as np

__all__ = ["LabeledImage", "LabeledBGRImage", "LabeledGreyImage"]


class LabeledImage:
    """content (H, W[, C]) float32 + float label."""

    __slots__ = ("content", "label")

    def __init__(self, content: np.ndarray, label: float = 0.0):
        self.content = np.asarray(content, np.float32)
        self.label = label

    @property
    def height(self) -> int:
        return self.content.shape[0]

    @property
    def width(self) -> int:
        return self.content.shape[1]

    def set_label(self, label: float):
        self.label = label
        return self

    def clone(self):
        return type(self)(self.content.copy(), self.label)

    def with_content(self, content: np.ndarray) -> "LabeledImage":
        """New carrier around ``content`` with the same label. Transformers
        must yield fresh carriers instead of rebinding ``content`` on the
        input — sources cache decoded images across epochs, so in-place
        rebinding would compound transforms every pass."""
        out = type(self).__new__(type(self))
        out.content = np.asarray(content, np.float32)
        out.label = self.label
        return out

    def __repr__(self):
        return (f"{type(self).__name__}(shape={self.content.shape}, "
                f"label={self.label})")


class LabeledBGRImage(LabeledImage):
    """(H, W, 3) in B,G,R channel order (reference Types.scala)."""


class LabeledGreyImage(LabeledImage):
    """(H, W) single channel."""

"""On-device tail of the u8 input pipeline.

The reference's host pipeline finishes with BGRImgNormalizer +
MTLabeledBGRImgToBatch (dl/.../dataset/image/BGRImgNormalizer.scala:44-60,
MTLabeledBGRImgToBatch.scala:46-103): float normalize and NCHW assembly on
CPU threads. On a TPU host that work is the input-pipeline bottleneck
(measured: the f32 host path runs at 867 img/s vs 1,915 img/s for
decode-only, docs/PERF.md round 4), and it quadruples the host->device
transfer (f32 vs u8). So the native loader ships raw uint8 HWC RGB crops
and this transform — meant for ``Optimizer.set_input_transform`` so it
lands INSIDE the jitted train/eval step — does scale/normalize/BGR/NCHW
on-device, where XLA fuses it into the first convolution's input read.

The math reproduces the host chain op-for-op in f32 (u8/255, subtract
mean, divide std — division, not reciprocal-multiply, so results are
bit-identical to BGRImgNormalizer's).
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["u8_to_model_input"]


def u8_to_model_input(mean_rgb, std_rgb, out_dtype=None):
    """Build the jit-safe batch transform: (N, H, W, 3) uint8 RGB ->
    (N, 3, H, W) normalized BGR in f32 (or ``out_dtype``, e.g. bf16 under
    a mixed-precision policy — the cast happens after f32 normalize, the
    same place DTypePolicy casts host f32 batches)."""
    r, g, b = (float(v) for v in mean_rgb)
    mean_bgr = jnp.asarray([b, g, r], jnp.float32)
    r, g, b = (float(v) for v in std_rgb)
    std_bgr = jnp.asarray([b, g, r], jnp.float32)

    def transform(x):
        if x.dtype != jnp.uint8:     # already normalized (f32 host path)
            return x
        y = x.astype(jnp.float32) / 255.0
        y = (y[..., ::-1] - mean_bgr) / std_bgr      # RGB -> BGR, normalize
        y = jnp.transpose(y, (0, 3, 1, 2))           # NHWC -> NCHW
        return y if out_dtype is None else y.astype(out_dtype)

    return transform

"""Distributed data plane: shard-local chunk reads + windowed global shuffle.

The reference trains over a partitioned big-data ingestion layer
(arXiv:1804.05839): every worker reads only its own partitions, and the
global stream reshuffles across epochs without any node ever holding the
whole dataset. This module is that contract over the chunked record
store (``dataset/recordstore.py``):

* **Assignment, not exchange.** Cross-host shuffle is a deterministic
  rotation of chunk *ownership* per pass — a seed-pure function of
  ``(seed, shard, pass)`` — so hosts never ship records to each other;
  they just open a different subset of chunks next epoch. No pass is
  ever materialized globally.
* **Windowed per-host shuffle.** Within a pass a host interleaves
  records from a small window of its assigned chunks, each chunk
  internally permuted. Record order WITHIN a chunk is deliberately
  shard-independent (pure in ``(seed, pass, chunk)``), which is what
  makes mid-epoch resume reconstructible across a host-count resize.
* **Chunk-granular elastic resume.** Positions checkpoint as
  (pass, drained-chunk ids) — the ids actually finished, because the
  window interleave drains chunks OUT of assignment order —
  plus the in-flight pass's chunk list so post-resize snapshots carry
  their override universe. :func:`redistribute_chunk_positions` deals
  the not-yet-consumed chunks of the interrupted pass across a NEW host
  count the same way elastic checkpoints redistribute optimizer shards
  (docs/ELASTICITY.md) — partially-consumed chunks replay in full
  (chunk granularity), fully-consumed chunks never repeat. Snapshot at
  a quiesced pipeline: draining is accounted where THIS iterator is
  pulled, so records still sitting in a PrefetchIterator queue count as
  consumed (the optimizers are immune — they snapshot at pipeline
  creation and replay with a consumer-side batch skip).

Decode/augment stages attach as ordinary transforms and therefore run on
the ``PrefetchIterator`` worker that pulls this dataset — per-host
decode overlap comes for free from the existing pipeline.

HOST-ONLY CONTRACT: no module-level jax import (jaxlint JX5 pins this
file); pure numpy + stdlib threading.
"""
from __future__ import annotations

import threading

import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet, PassRotationMixin
from bigdl_tpu.dataset.recordstore import (ChunkedRecordReader, SAMPLE_CODEC,
                                           decode_sample)
from bigdl_tpu.dataset.sample import ByteRecord
from bigdl_tpu.utils.random import RandomGenerator

__all__ = ["pass_chunk_order", "chunk_assignment", "chunk_record_order",
           "ChunkExchange", "DistributedShuffleDataSet",
           "redistribute_chunk_positions"]

# Domain salts so the three streams drawn from one (seed, pass, ...) key
# never alias: chunk order / record order / window picks.
_SALT_CHUNK_ORDER = 1
_SALT_RECORD_ORDER = 2
_SALT_WINDOW = 3


def _mixed_generator(*parts, seed=None) -> np.random.Generator:
    """Seed-pure generator keyed by ``parts`` — same fold constants as
    ``PassRotationMixin._pass_offset`` so the whole resume contract hangs
    off one seeding discipline (``RandomGenerator.set_seed``)."""
    if seed is None:
        seed = RandomGenerator._default_seed
    mix = int(seed) % (2 ** 32)
    for p in parts:
        mix = (mix * 2654435761 + int(p) + 0x9E3779B9) % (2 ** 32)
    return np.random.Generator(np.random.MT19937(mix))


def pass_chunk_order(n_chunks: int, pass_k: int, seed=None) -> list[int]:
    """Global chunk permutation for pass ``pass_k`` — identical on every
    host (no shard in the key), which is what lets hosts agree on
    ownership without talking."""
    g = _mixed_generator(_SALT_CHUNK_ORDER, pass_k, seed=seed)
    return [int(c) for c in g.permutation(int(n_chunks))]


def chunk_assignment(n_chunks: int, num_shards: int, pass_k: int,
                     seed=None) -> list[list[int]]:
    """Per-shard chunk ownership for one pass: the global pass order
    dealt round-robin. Disjoint and exhaustive by construction — every
    chunk lands on exactly one shard each pass, and the deal rotates
    with the permutation so ownership reshuffles across passes."""
    order = pass_chunk_order(n_chunks, pass_k, seed=seed)
    return [order[s::int(num_shards)] for s in range(int(num_shards))]


def chunk_record_order(n_records: int, pass_k: int, chunk_id: int,
                       seed=None) -> list[int]:
    """Within-chunk record permutation — pure in (seed, pass, chunk),
    deliberately NOT in shard: whichever host owns the chunk this pass
    reads it in the same order, so a host-count resize replays the exact
    record stream (the bit-identity the resize drill pins)."""
    g = _mixed_generator(_SALT_RECORD_ORDER, pass_k, chunk_id, seed=seed)
    return [int(i) for i in g.permutation(int(n_records))]


def _window_picks(pass_k: int, shard: int, seed=None):
    """Endless pick stream for the window interleave (which active chunk
    yields next). Shard IS in the key — interleave is a per-host
    presentation choice and never crosses hosts."""
    g = _mixed_generator(_SALT_WINDOW, pass_k, shard, seed=seed)
    while True:
        yield int(g.integers(0, 2 ** 31))


class ChunkExchange:
    """Read-ahead thread staging permuted chunks for one pass.

    Decouples chunk IO + permutation from the consumer so the mmap read
    overlaps the window interleave (the PrefetchIterator worker is this
    iterator's consumer; the exchange keeps IT fed at chunk granularity).
    Bounded to ``depth`` staged chunks with backpressure.
    """
    # raceguard: order chunkexchange.mu < pos_lock

    def __init__(self, reader: ChunkedRecordReader, chunks,
                 record_order_fn, depth: int = 2):
        self._reader = reader
        self._chunks = list(chunks)
        self._order_fn = record_order_fn
        self._depth = max(1, int(depth))
        self._mu = threading.Condition()
        self._staged: list[tuple[int, list]] = []
        self._done = False
        self._stop = False
        self._exc = None
        self._thread = threading.Thread(target=self._work,
                                        name="chunk-exchange", daemon=True)
        self._thread.start()

    def _work(self):
        try:
            for cid in self._chunks:
                with self._mu:
                    while len(self._staged) >= self._depth and not self._stop:
                        self._mu.wait()
                    if self._stop:
                        return
                # chunk IO + permutation OUTSIDE the condition: the
                # consumer keeps draining while the next chunk loads
                records = self._reader.read_chunk(cid)
                order = self._order_fn(len(records), cid)
                permuted = [(records[i], i) for i in order]
                with self._mu:
                    if self._stop:
                        return
                    self._staged.append((cid, permuted))
                    self._mu.notify_all()
        except BaseException as e:  # surfaced to the consumer
            with self._mu:
                self._exc = e
                self._mu.notify_all()
        finally:
            with self._mu:
                self._done = True
                self._mu.notify_all()

    def next_chunk(self):
        """Next (chunk_id, [((data, label), stored_index), ...]) or None
        when the pass's chunk list is exhausted."""
        with self._mu:
            while not self._staged and not self._done and self._exc is None:
                self._mu.wait()
            if self._exc is not None:
                raise self._exc
            if self._staged:
                item = self._staged.pop(0)
                self._mu.notify_all()
                return item
            return None

    def close(self):
        with self._mu:
            self._stop = True
            self._mu.notify_all()
        self._thread.join(timeout=10.0)


class DistributedShuffleDataSet(PassRotationMixin, AbstractDataSet):
    """Sharded training stream over a chunked record store.

    Each host opens ONLY the chunks its shard owns this pass (the
    reader's ``chunks_opened`` accounting is the receipt); ownership
    rotates per pass via :func:`chunk_assignment`, so the global stream
    reshuffles across epochs without a global materialization.

    ``window_chunks`` bounds host memory: at most that many chunks are
    decoded-and-interleaving at once (plus ``exchange_depth`` staged
    read-ahead chunks), independent of dataset size.
    """

    def __init__(self, store, *, num_shards: int = 1, shard_index: int = 0,
                 window_chunks: int = 2, decode=None, exchange_depth: int = 2):
        self._reader = store if isinstance(store, ChunkedRecordReader) \
            else ChunkedRecordReader(store)
        if self._reader.n_chunks < num_shards:
            raise ValueError(
                f"store has {self._reader.n_chunks} chunks for "
                f"{num_shards} shards — at least one chunk per shard is "
                "required (write with a smaller chunk_records)")
        self.num_shards = int(num_shards)
        self.shard_index = int(shard_index)
        self._seed_shard = self.shard_index
        self._window = max(1, int(window_chunks))
        self._exchange_depth = int(exchange_depth)
        # decode: None = by store codec, False = raw ByteRecords,
        # callable = custom per-record decode (runs on whatever thread
        # pulls this iterator — the PrefetchIterator worker in training)
        if decode is None and self._reader.codec == SAMPLE_CODEC:
            decode = decode_sample
        self._decode = decode or None
        self._pos_lock = threading.Lock()
        self._pass_count = 0
        # ids actually drained from the in-flight pass — a SET of ids,
        # not a count: the window interleave finishes chunks out of
        # assignment order, so a prefix count would mark partially-read
        # chunks consumed (kept in drain order for debuggability)
        self._drained: list[int] = []
        # the in-flight pass's full chunk list (the resume override
        # after a resize, else the canonical assignment) — snapshots
        # must report the list actually being iterated
        self._pass_chunks = None
        self._resume_chunks = None

    # -- identity -------------------------------------------------------
    @property
    def reader(self) -> ChunkedRecordReader:
        return self._reader

    def is_sharded(self):
        return self.num_shards > 1

    def process_shard_count(self):
        return self.num_shards

    def process_shard_index(self):
        return self.shard_index

    def size(self):
        """Global record count (same semantics as ShardedDataSet.size)."""
        return self._reader.n_records

    def local_size(self) -> int:
        """Records in this shard's pass-0 assignment (pass-to-pass the
        count can shift by one short chunk; epoch accounting is global)."""
        chunks = chunk_assignment(self._reader.n_chunks, self.num_shards,
                                  0)[self.shard_index]
        return sum(self._reader.chunk_record_count(c) for c in chunks)

    # -- streams --------------------------------------------------------
    def _wrap(self, data, label, chunk_id, stored_i):
        if self._decode is not None:
            return self._decode(data, label)
        return ByteRecord(data, label,
                          key=(self._reader.path, chunk_id, stored_i))

    def _iter_pass(self, k: int, chunks):
        ex = ChunkExchange(self._reader, chunks,
                           lambda n, cid: chunk_record_order(n, k, cid),
                           depth=self._exchange_depth)
        picks = _window_picks(k, self.shard_index)
        active: list[list] = []   # [chunk_id, permuted_records, next_idx]
        try:
            feed_dry = False
            while True:
                while len(active) < self._window and not feed_dry:
                    item = ex.next_chunk()
                    if item is None:
                        feed_dry = True
                    else:
                        active.append([item[0], item[1], 0])
                if not active:
                    break
                j = next(picks) % len(active)
                cid, records, idx = active[j]
                (data, label), stored_i = records[idx]
                active[j][2] = idx + 1
                if idx + 1 >= len(records):
                    active.pop(j)
                    with self._pos_lock:
                        self._drained.append(cid)
                yield self._wrap(data, label, cid, stored_i)
        finally:
            ex.close()

    def data(self, train: bool):
        if train:
            if self._reader.n_records == 0:
                raise ValueError("cannot build a training iterator over an "
                                 "empty record store")

            def endless():
                while True:
                    with self._pos_lock:
                        k = self._pass_count
                        self._pass_count = k + 1
                        override = self._resume_chunks
                        self._resume_chunks = None
                        if override is not None:
                            chunks = list(override)
                        else:
                            chunks = chunk_assignment(
                                self._reader.n_chunks, self.num_shards,
                                k)[self.shard_index]
                        self._pass_chunks = list(chunks)
                        self._drained = []
                    yield from self._iter_pass(k, chunks)
            return endless()

        def single():
            chunks = sorted(chunk_assignment(
                self._reader.n_chunks, self.num_shards, 0)[self.shard_index])
            for c in chunks:
                for i, (data, label) in enumerate(self._reader.read_chunk(c)):
                    yield self._wrap(data, label, c, i)
        return single()

    def shuffle(self):
        """No-op: cross-pass reshuffle IS the per-pass assignment
        rotation — nothing to draw from the host RNG stream."""

    # -- resume contract ------------------------------------------------
    def get_position_state(self):
        """Chunk-granular pipeline position.

        ``drained_chunks`` are the ids actually drained from the
        in-flight pass — NOT an assignment prefix (the window interleave
        finishes chunks out of assignment order whenever
        ``window_chunks`` > 1). ``remaining_chunks`` + ``override_pass``
        carry the chunk list the in-flight (or pending resumed) pass
        iterates, so a snapshot taken after a resize-resume round-trips
        through checkpoints and a second
        :func:`redistribute_chunk_positions` sees the real universe
        instead of recomputing the canonical assignment.

        QUIESCE CAVEAT: a chunk is accounted drained when its last
        record is pulled from THIS iterator. Under a ``PrefetchIterator``
        the puller is the worker thread, so records still sitting in the
        prefetch queue count as consumed — snapshot with the pipeline
        quiesced (worker closed / epoch boundary), or do what the
        optimizers do: snapshot at pipeline creation, advance by the
        consumer's pass-start, and replay with a consumer-side batch
        skip (optim/optimizer.py ``_checkpoint``).
        """
        with self._pos_lock:
            st = {"passes_started": self._pass_count,
                  "chunks_done": len(self._drained),
                  "drained_chunks": [int(c) for c in self._drained],
                  "num_shards": self.num_shards,
                  "shard_index": self.shard_index,
                  "n_chunks": self._reader.n_chunks}
            if self._resume_chunks is not None:
                # resumed but not yet started: the override governs the
                # NEXT pass to start (0-based index == _pass_count)
                st["remaining_chunks"] = [int(c)
                                          for c in self._resume_chunks]
                st["override_pass"] = self._pass_count
            elif self._pass_chunks is not None:
                # the started pass's FULL list, drained ids included — a
                # mid-pass replay restarts the pass (the optimizer's
                # batch skip fast-forwards); redistribution subtracts
                # drained_chunks itself
                st["remaining_chunks"] = [int(c)
                                          for c in self._pass_chunks]
                st["override_pass"] = self._pass_count - 1
            return st

    def set_position_state(self, state, mid_pass: bool = False):
        passes = int(np.asarray(state.get("passes_started", 0)))
        rc = state.get("remaining_chunks")
        op = state.get("override_pass")
        with self._pos_lock:
            # mid_pass: replay pass k = passes-1 (mixin semantics)
            self._pass_count = passes - 1 if (mid_pass and passes > 0) \
                else passes
            self._drained = []
            self._pass_chunks = None
            # one-shot ownership override for the next pass to start —
            # honored only when it was recorded FOR that pass (a state
            # whose override names an already-completed pass falls back
            # to the canonical assignment)
            if rc is not None and (
                    op is None or int(np.asarray(op)) == self._pass_count):
                self._resume_chunks = [int(c) for c in rc]
            else:
                self._resume_chunks = None

    def advance_position_state(self, state):
        """``state`` as it reads after the next pass STARTED from it
        (the optimizers advance their pipeline-creation snapshot by the
        consumer's progress — dataset/prefetch.py). A pending resume
        override survives the advance — the pass being started IS the
        override pass — while one describing the already-started pass
        is dropped."""
        out = dict(state)
        passes = int(np.asarray(state.get("passes_started", 0)))
        out["passes_started"] = passes + 1
        out["chunks_done"] = 0
        out["drained_chunks"] = []
        op = state.get("override_pass")
        if op is None or int(np.asarray(op)) != passes:
            out.pop("remaining_chunks", None)
            out.pop("override_pass", None)
        return out

    def close(self):
        self._reader.close()


def redistribute_chunk_positions(states, new_num_shards: int, *, seed=None):
    """Deal an interrupted pass's unconsumed chunks across a NEW host
    count — the data-plane analogue of elastic checkpoint
    redistribution (docs/ELASTICITY.md).

    ``states``: one ``get_position_state()`` dict per OLD shard (any
    order), snapshotted at a QUIESCED pipeline (see
    ``get_position_state``). Chunk-granular contract: a chunk counts as
    consumed only when fully drained — the ``drained_chunks`` id set,
    which under the window interleave is NOT an assignment prefix —
    so partially-read chunks replay in full on the new fleet,
    fully-consumed chunks never repeat, and because within-chunk record
    order is shard-independent the remaining stream reconstructs
    bit-identically. Chained resizes work: a snapshot taken during (or
    before) a replayed pass carries its override chunk list, and the
    re-deal is computed against THAT universe rather than the canonical
    assignment. Returns one state per NEW shard; apply each with
    ``set_position_state(state, mid_pass=True)``.
    """
    if not states:
        raise ValueError("need at least one old-shard position state")
    first = states[0]
    n_chunks = int(first["n_chunks"])
    old_shards = int(first["num_shards"])
    passes = int(first["passes_started"])
    new_num_shards = int(new_num_shards)
    if new_num_shards < 1 or new_num_shards > n_chunks:
        raise ValueError(f"new_num_shards={new_num_shards} out of range "
                         f"for a {n_chunks}-chunk store")
    if len(states) != old_shards:
        raise ValueError(f"got {len(states)} states for "
                         f"{old_shards} old shards")
    seen = set()
    for st in states:
        if (int(st["n_chunks"]), int(st["num_shards"]),
                int(st["passes_started"])) != (n_chunks, old_shards, passes):
            raise ValueError("inconsistent position states — not one "
                             "snapshot of one fleet")
        seen.add(int(st["shard_index"]))
    if seen != set(range(old_shards)):
        raise ValueError(f"shard indices {sorted(seen)} do not cover "
                         f"0..{old_shards - 1}")

    base = {"chunks_done": 0, "drained_chunks": [],
            "num_shards": new_num_shards, "n_chunks": n_chunks}

    # Which pass is interrupted? Normally the last STARTED one
    # (passes-1). A fleet snapshotted after a resize-restore but before
    # the replay began reports a PENDING override for pass == passes —
    # that pass is the interrupted one, with nothing drained yet.
    def _op(st):
        op = st.get("override_pass")
        return None if op is None else int(np.asarray(op))

    pending = [st for st in states if _op(st) == passes
               and st.get("remaining_chunks") is not None]
    if pending and len(pending) != len(states):
        raise ValueError("mixed pending-resume and in-flight position "
                         "states — not one quiesced snapshot of one "
                         "fleet")
    if pending:
        k = passes
    elif passes == 0:   # nothing started — fresh states, no override
        return [dict(base, passes_started=0, shard_index=s)
                for s in range(new_num_shards)]
    else:
        k = passes - 1  # the interrupted pass

    # Per-shard chunk universe for pass k: the state's own chunk list
    # when it carries one (post-resize override / in-flight snapshot),
    # else the canonical assignment. Consumed = union of the ids
    # actually drained; legacy states without drained_chunks fall back
    # to the prefix-count reading (only ever correct at
    # window_chunks == 1, the pre-drained-set format).
    assign = None

    def _assignment():
        nonlocal assign
        if assign is None:
            assign = chunk_assignment(n_chunks, old_shards, k, seed=seed)
        return assign

    universe = set()
    consumed = set()
    for st in states:
        s = int(st["shard_index"])
        rc = st.get("remaining_chunks")
        if rc is not None:
            universe.update(int(c) for c in rc)
        else:
            universe.update(_assignment()[s])
        dr = st.get("drained_chunks")
        if dr is not None:
            consumed.update(int(c) for c in dr)
        else:
            consumed.update(_assignment()[s][:int(st.get("chunks_done",
                                                         0))])
    remaining = [c for c in pass_chunk_order(n_chunks, k, seed=seed)
                 if c in universe and c not in consumed]
    return [dict(base, passes_started=k + 1, shard_index=s,
                 remaining_chunks=remaining[s::new_num_shards],
                 override_pass=k)
            for s in range(new_num_shards)]

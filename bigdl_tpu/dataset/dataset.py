"""DataSet abstractions and factories.

Reference parity: AbstractDataSet / LocalDataSet / LocalArrayDataSet /
DistributedDataSet / CachedDistriDataSet (dataset/DataSet.scala:46-259) and
the ``DataSet`` factory object (:264-456).

TPU-first: the reference's DistributedDataSet is an RDD cached per Spark
executor with locality-zipped model partitions; here a ``ShardedDataSet``
splits the sample stream across mesh data-parallel shards per host process
(``process_index``/``process_count``) — the same per-worker-cache semantics
without a cluster framework. Global batches are assembled per step and laid
out for ``jax.make_array_from_process_local_data`` by the distributed
optimizer.
"""
from __future__ import annotations

from typing import Iterator, Sequence

import numpy as np

from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.utils.random import RandomGenerator

__all__ = ["AbstractDataSet", "LocalArrayDataSet", "ShardedDataSet",
           "DataSet", "array", "iterator_source"]


class AbstractDataSet:
    """(reference DataSet.scala:46-104)"""

    def data(self, train: bool) -> Iterator:
        """Endless looped iterator when ``train`` (reference semantics);
        single pass otherwise."""
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def shuffle(self) -> None:
        raise NotImplementedError

    def transform(self, transformer: Transformer) -> "AbstractDataSet":
        """(reference ``transform``/``->``)"""
        return TransformedDataSet(self, transformer)

    def is_sharded(self) -> bool:
        """True when this dataset (or its base, through transforms) is a
        data-parallel ShardedDataSet — drives Optimizer factory dispatch."""
        return False

    def process_shard_count(self):
        """Number of process shards this dataset was built for (through
        transforms), or None when unknown. Multi-host validation guards
        compare it against jax.process_count() to refuse double-counting
        setups."""
        return None

    def process_shard_index(self):
        """Which process shard this dataset holds (through transforms),
        or None when unknown — the multi-host guards assert indices are
        distinct across processes."""
        return None

    def get_position_state(self):
        """Checkpointable pipeline position (shuffle permutation etc.);
        None when the source has no such state. Paired with
        ``set_position_state`` so a resumed run replays the exact data
        order of the stopped run."""
        return None

    def set_position_state(self, state, mid_pass: bool = False) -> None:
        pass

    def advance_position_state(self, state):
        """Return ``state`` as it reads after one training pass has
        STARTED from it. The prefetch-era checkpoint path
        (dataset/prefetch.py): the worker's read-ahead may already have
        crossed into the next pass, so the optimizers checkpoint the
        epoch-start snapshot advanced by the CONSUMER's progress instead
        of the live (worker-polluted) state — unconsumed prefetched
        batches fold back into the saved position. Default: position
        state carries no per-pass component, return it unchanged."""
        return state

    def __rshift__(self, transformer: Transformer) -> "AbstractDataSet":
        return self.transform(transformer)


class TransformedDataSet(AbstractDataSet):
    def __init__(self, base: AbstractDataSet, transformer: Transformer):
        self.base = base
        self.transformer = transformer

    def data(self, train: bool):
        return self.transformer(self.base.data(train))

    def size(self):
        return self.base.size()

    def shuffle(self):
        self.base.shuffle()

    def is_sharded(self):
        return self.base.is_sharded()

    def process_shard_count(self):
        return self.base.process_shard_count()

    def process_shard_index(self):
        return self.base.process_shard_index()

    def get_position_state(self):
        return self.base.get_position_state()

    def set_position_state(self, state, mid_pass: bool = False):
        self.base.set_position_state(state, mid_pass)

    def advance_position_state(self, state):
        return self.base.advance_position_state(state)

    def local_size(self):
        base_local = getattr(self.base, "local_size", self.base.size)
        return base_local()


class LocalArrayDataSet(AbstractDataSet):
    """Array-backed local dataset (reference DataSet.scala:110-156):
    training iterator loops endlessly over a shuffled index array."""

    def __init__(self, data: Sequence):
        self._data = list(data)
        self._index = np.arange(len(self._data))

    def data(self, train: bool):
        if train:
            if not self._data:
                raise ValueError("cannot build a training iterator over an "
                                 "empty dataset")
            def endless():
                while True:
                    for i in self._index:
                        yield self._data[i]
            return endless()
        return iter([self._data[i] for i in self._index])

    def size(self):
        return len(self._data)

    def shuffle(self):
        """(reference shuffle: re-randomize the index array)"""
        RandomGenerator.RNG().shuffle(self._index)

    def get_position_state(self):
        return {"index": self._index.copy()}

    def set_position_state(self, state, mid_pass: bool = False):
        self._index = np.asarray(state["index"]).copy()


class PassRotationMixin:
    """Exact-resume machinery shared by the sharded datasets.

    Requires ``self._index`` (np permutation of local items) and
    ``self._seed_shard`` (this process's shard index). The per-pass start
    offset is a pure function of (seed, shard, pass) — NOT a draw from the
    shared host RNG stream — so a resumed run can replay the exact pass
    the stopped run was in. One implementation so the checkpoint-replay
    invariant cannot drift between in-memory and record-file datasets.
    """

    _pass_count = 0

    def _pass_offset(self, k: int) -> int:
        if len(self._index) == 0:
            return 0
        mix = (RandomGenerator._default_seed * 2654435761
               + self._seed_shard * 40503 + k) % (2 ** 32)
        g = np.random.Generator(np.random.MT19937(mix))
        return int(g.integers(0, len(self._index)))

    def _next_pass_order(self):
        k = self._pass_count
        self._pass_count = k + 1
        return np.roll(self._index, -self._pass_offset(k))

    def shuffle(self):
        RandomGenerator.RNG().shuffle(self._index)

    def get_position_state(self):
        return {"index": self._index.copy(),
                "passes_started": self._pass_count}

    def set_position_state(self, state, mid_pass: bool = False):
        # "order" is the key RecordShardDataSet checkpoints used before
        # this machinery was unified; keep reading it so those resume
        key = "index" if "index" in state else "order"
        self._index = np.asarray(state[key]).copy()
        passes = int(np.asarray(state.get("passes_started", 0)))
        # mid_pass: the stopped run was inside pass k = passes-1; the fresh
        # training iterator must replay that same pass (the optimizer then
        # fast-forwards past the consumed batches)
        self._pass_count = passes - 1 if (mid_pass and passes > 0) else passes

    def advance_position_state(self, state):
        """One consumer pass started from ``state``: passes_started + 1.
        Within one epoch exactly one pass starts (the boundary crossing
        into the NEXT pass happens only on the epoch's final batch,
        after which the optimizers re-snapshot), so the epoch-start
        snapshot advanced once equals what the synchronous loop's live
        read would have said mid-epoch — read-ahead folded back."""
        out = dict(state)
        out["passes_started"] = \
            int(np.asarray(state.get("passes_started", 0))) + 1
        return out


class ShardedDataSet(PassRotationMixin, AbstractDataSet):
    """Data-parallel sharded dataset (replaces the reference's
    CachedDistriDataSet, DataSet.scala:163-259).

    Each host process keeps the shard ``process_index`` of ``num_shards``;
    training iterators loop endlessly from a random offset per epoch like
    the reference (:216-247).
    """

    def __init__(self, data: Sequence, num_shards: int = 1,
                 shard_index: int = 0, keep_all: bool = False):
        data = list(data)
        self.num_shards = num_shards
        self.shard_index = shard_index
        self._seed_shard = shard_index
        self._local = data[shard_index::num_shards]
        self._global_size = len(data)
        # Host RAM must scale with the SHARD, not the dataset: drop the
        # full list once sliced. ``keep_all`` is the documented opt-out
        # for callers that re-shard the same instance (tests, notebooks).
        self._all = data if (keep_all or num_shards <= 1) else None
        self._index = np.arange(len(self._local))

    def process_shard_count(self):
        return self.num_shards

    def process_shard_index(self):
        return self.shard_index

    def is_sharded(self):
        return True

    def data(self, train: bool):
        if train:
            if not self._local:
                raise ValueError(
                    f"shard {self.shard_index}/{self.num_shards} is empty — "
                    "fewer samples than shards")
            def endless():
                while True:
                    for i in self._next_pass_order():
                        yield self._local[i]
            return endless()
        return iter([self._local[i] for i in self._index])

    def size(self):
        """Global size (reference DistributedDataSet.size counts all)."""
        return self._global_size

    def local_size(self) -> int:
        return len(self._local)


class _BatchIterable(AbstractDataSet):
    """Wrap an iterable of MiniBatch (pre-batched source)."""

    def __init__(self, make_iter, size):
        self._make_iter = make_iter
        self._size = size

    def data(self, train: bool):
        if train:
            if self._size <= 0:
                raise ValueError("cannot build a training iterator over an "
                                 "empty source")
            def endless():
                while True:
                    yielded = False
                    for item in self._make_iter():
                        yielded = True
                        yield item
                    if not yielded:
                        raise ValueError("source iterator yielded nothing")
            return endless()
        return self._make_iter()

    def size(self):
        return self._size

    def shuffle(self):
        pass


# ---------------------------------------------------------------------------
# Factories (reference DataSet object, DataSet.scala:264-456)
# ---------------------------------------------------------------------------

def array(data: Sequence, num_shards: int | None = None,
          shard_index: int = 0, keep_all: bool = False) -> AbstractDataSet:
    """Local or sharded dataset from an in-memory array
    (reference DataSet.array, :281-294 — distributed when a SparkContext
    is passed; here when ``num_shards`` is given)."""
    if num_shards is None:
        return LocalArrayDataSet(data)
    return ShardedDataSet(data, num_shards, shard_index, keep_all=keep_all)


def iterator_source(make_iter, size: int) -> AbstractDataSet:
    """Dataset from a re-creatable iterator factory (covers the
    reference's ``DataSet.rdd`` ingestion role for arbitrary sources)."""
    return _BatchIterable(make_iter, size)


class DataSet:
    """Namespace matching the reference's ``DataSet`` factory object."""

    array = staticmethod(array)
    iterator = staticmethod(iterator_source)


def batches_per_epoch(dataset: AbstractDataSet, batch_size: int) -> int:
    size = dataset.local_size() if isinstance(dataset, ShardedDataSet) \
        else dataset.size()
    return max(1, (size + batch_size - 1) // batch_size)


def to_jax_batch(batch: MiniBatch):
    import jax.numpy as jnp
    return jnp.asarray(batch.data), jnp.asarray(batch.labels)

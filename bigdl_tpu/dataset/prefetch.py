"""Overlapped input pipeline: threaded prefetch + early device placement.

The reference hid host input cost behind per-executor cached RDD
partitions (BigDL, arXiv:1804.05839) and BigDL 2.0 made pipeline-stage
overlap a headline feature (arXiv:2204.01715). The TPU-native rendering:
the async-dispatch train loop (docs/PERFORMANCE.md) already keeps
``max_in_flight`` device steps in the air; this module moves the host
side of the NEXT batch — ``next(data_iter)`` + transforms +
``to_jax_batch`` + sharded placement — off the critical path and onto a
worker thread, so the loop's ``host input`` phase collapses to a queue
pop (the ``input wait`` span).

Pieces:

- :class:`PrefetchIterator` — bounded-queue, daemon-worker prefetch
  over any MiniBatch iterator. Exceptions raised by the source or the
  stage propagate to the consumer; :meth:`close` joins the worker.
- :class:`DevicePrefetcher` — the placement stage: ``device_put`` /
  ``jax.make_array_from_process_local_data`` in the worker, so batches
  arrive in HBM before the loop ever sees them. Also callable on an
  iterator (the historic ``recordio.DevicePrefetcher`` dispatch-ahead
  form, kept for user pipelines).
- :class:`PadPartialBatches` — host-side stage padding the final
  partial batch of a pass to the full batch shape, carrying the real
  row count in ``MiniBatch.valid`` so the train step can mask the
  padding out of the loss (``nn.MaskedCriterion``) — one compiled
  signature per step name instead of one per distinct batch shape.

EXACT CHECKPOINT/REPLAY SEMANTICS. The shipped datasets checkpoint
(permutation, passes_started) — never an intra-pass offset — and the
optimizers replay a mid-epoch resume by fast-forwarding the consumed
batch count under the epoch-start host-RNG snapshot. Prefetch preserves
that contract because the worker is EPOCH-BOUNDED: ``max_records``
stops it at exactly the batch where the consumer's epoch ends, so the
worker performs precisely the pull sequence (and host-RNG draws) the
synchronous loop would have — read-ahead never leaks into the next
pass, and unconsumed prefetched batches are simply dropped on resume
and re-produced by the replay. Equivalently: everything the worker ran
ahead on is folded back into the (position state, consumed-batch
count) pair the checkpoint already carries.

THREAD-SAFETY CONTRACT. ``shuffle()`` / ``set_position_state()`` on the
source dataset may NOT race the prefetch worker — both mutate the
order the worker is iterating. The optimizers therefore ``close()``
(drain + join) the pipeline BEFORE the epoch-boundary ``shuffle()`` and
build a fresh one after; wrapping a dataset that still has a live
worker raises (``_LIVE_SOURCES`` guard). tests/test_prefetch.py
stress-tests the handoff (many epochs, depth-1 queue).

HOST-ONLY CONTRACT: no module-level jax import (jaxlint JX5 pins this
file) — the queue/thread machinery must be importable and testable with
no device runtime; jax is lazily imported only inside the sanctioned
placement calls.
"""
from __future__ import annotations

import queue
import threading
import weakref

import numpy as np

from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.observability import trace
from bigdl_tpu.observability.registry import default_registry

__all__ = ["PrefetchIterator", "DevicePrefetcher", "PadPartialBatches",
           "open_input_pipeline"]

_DONE = object()


def _is_device_array(x) -> bool:
    """jax.Array check without importing jax (host-only contract): the
    module path is enough, and a host batch is never a jax type."""
    return type(x).__module__.split(".")[0] in ("jax", "jaxlib")


class _Raised:
    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


# datasets with a live prefetch worker (enforces the thread-safety
# contract above: one worker per source, close() before re-wrapping)
_LIVE_SOURCES: "weakref.WeakSet" = weakref.WeakSet()


class PrefetchIterator:
    """Bounded-queue threaded prefetch over a MiniBatch iterator.

    A daemon worker pulls from ``source``, applies ``stage`` (e.g.
    :class:`DevicePrefetcher`), and enqueues up to ``depth`` finished
    batches. The consumer's ``next()`` is a queue pop; when the queue
    is empty with the worker still producing, the pop is counted as
    ``input_starvation_total`` and marked with an ``input starvation``
    trace instant — the signal that ``depth`` (or the host) is too
    small for the step time. Queue occupancy is exported as the
    ``prefetch_queue_depth`` gauge.

    ``max_records`` bounds the worker to one epoch of the consumer's
    accounting: it stops (without closing the source) right after the
    batch whose cumulative ``shape[0] * records_scale`` reaches the
    bound — exactly where the training loop declares epoch end. A
    finite source simply ends the stream (StopIteration propagates).

    Exceptions from source/stage re-raise in the consumer; ``close()``
    is idempotent, drains the queue, and joins the worker (raising if
    it refuses to die — a deadlock should be loud, not silent).
    """

    def __init__(self, source, *, depth: int = 2, stage=None,
                 max_records: int | None = None, records_scale: int = 1,
                 name: str = "input", dataset=None, shard=None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        if dataset is not None:
            if dataset in _LIVE_SOURCES:
                raise RuntimeError(
                    "dataset already has a live prefetch worker — close() "
                    "the previous PrefetchIterator before shuffle()/"
                    "set_position_state()/re-wrapping (thread-safety "
                    "contract, dataset/prefetch.py)")
            _LIVE_SOURCES.add(dataset)
        self._source = source
        self._stage = stage
        self._depth = depth
        self._max_records = max_records
        self._scale = max(1, int(records_scale))
        self._name = name
        # per-host starvation attribution: which process shard this
        # pipeline feeds ("0" for single-host / unsharded sources)
        self._labels = {"pipeline": name,
                        "shard": str(shard if shard is not None else 0)}
        self._dataset = dataset
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._done = False
        self._closed = False
        reg = default_registry()
        self._gauge = reg.gauge(
            "prefetch_queue_depth",
            "batches ready in the prefetch queue",
            labelnames=("pipeline", "shard"))
        self._starved = reg.counter(
            "input_starvation_total",
            "consumer blocked on an empty prefetch queue",
            labelnames=("pipeline", "shard"))
        # the worker continues the CREATOR's host-RNG stream: transforms
        # drawing augmentation randomness must land exactly where the
        # synchronous loop's draws would (bit-identical contract). The
        # creator thread must not draw from it while the worker runs —
        # the optimizers only touch host RNG (shuffle, snapshots) with
        # the pipeline closed, per the epoch-boundary handoff.
        from bigdl_tpu.utils.random import RandomGenerator
        self._host_rng = RandomGenerator.RNG()
        self._worker = threading.Thread(
            target=self._work, name=f"prefetch:{name}", daemon=True)
        self._worker.start()

    # -- worker side --
    def _put(self, item) -> bool:
        """Blocking put that stays responsive to close(); returns False
        when the pipeline was closed underneath us."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _work(self):
        from bigdl_tpu.utils.random import RandomGenerator
        RandomGenerator.adopt(self._host_rng)
        pulled = 0
        try:
            while not self._stop.is_set():
                if self._max_records is not None and \
                        pulled * self._scale >= self._max_records:
                    break  # epoch bound: the consumer ends here too
                try:
                    with trace.span("input produce", pipeline=self._name):
                        b = next(self._source)
                        n = b.size() if isinstance(b, MiniBatch) \
                            else int(np.asarray(
                                getattr(b, "data", b)).shape[0])
                        if self._stage is not None:
                            b = self._stage(b)
                except StopIteration:
                    break
                pulled += n
                if not self._put(b):
                    return
            self._put(_DONE)
        except BaseException as e:  # propagate into the consumer
            self._put(_Raised(e))

    # -- consumer side --
    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        if self._q.empty() and self._worker.is_alive():
            self._starved.inc(**self._labels)
            trace.instant("input starvation", pipeline=self._name)
        item = self._q.get()
        self._gauge.set(self._q.qsize(), **self._labels)
        if item is _DONE:
            self._finish()
            raise StopIteration
        if isinstance(item, _Raised):
            self._finish()
            raise item.exc
        return item

    def _finish(self):
        self._done = True
        self._worker.join(timeout=10.0)
        self._release()

    def _release(self):
        if self._dataset is not None:
            _LIVE_SOURCES.discard(self._dataset)
            self._dataset = None

    @property
    def running(self) -> bool:
        return self._worker.is_alive()

    def close(self, timeout: float = 10.0) -> None:
        """Stop the worker and join it. Idempotent; safe mid-stream
        (unconsumed batches are dropped — replay re-produces them,
        see the module docstring)."""
        if self._closed:
            return
        self._closed = True
        self._done = True
        self._stop.set()
        deadline = timeout
        while self._worker.is_alive() and deadline > 0:
            try:  # unblock a worker stuck in put()
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
            self._worker.join(timeout=0.1)
            deadline -= 0.1
        self._release()
        if self._worker.is_alive():
            raise RuntimeError(
                f"prefetch worker '{self._name}' did not stop within "
                f"{timeout}s — source iterator is wedged")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class _SyncPipeline:
    """depth=0 path: the same stage composition run inline, same
    interface (``input produce`` span included so depth-0 and depth-2
    traces stay comparable)."""

    def __init__(self, source, stage=None, name: str = "input"):
        self._source = source
        self._stage = stage
        self._name = name

    def __iter__(self):
        return self

    def __next__(self):
        with trace.span("input produce", pipeline=self._name):
            b = next(self._source)
            if self._stage is not None:
                b = self._stage(b)
            return b

    @property
    def running(self) -> bool:
        return False

    def close(self, timeout: float = 0.0) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def open_input_pipeline(source, *, depth: int, stage=None,
                        max_records: int | None = None,
                        records_scale: int = 1, name: str = "input",
                        dataset=None, shard=None):
    """Factory the optimizers use: ``depth == 0`` is today's synchronous
    path (stages run inline on the consumer thread), ``depth >= 1``
    overlaps them on a prefetch worker. ``shard`` labels the starvation
    metrics with the process shard index (per-host attribution)."""
    if depth <= 0:
        return _SyncPipeline(source, stage, name=name)
    return PrefetchIterator(source, depth=depth, stage=stage,
                            max_records=max_records,
                            records_scale=records_scale, name=name,
                            dataset=dataset, shard=shard)


class PadPartialBatches:
    """Pad partial batches up to the largest batch shape seen.

    A pre-batched source (``DataSet.iterator``) ends each pass with a
    short batch; every distinct shape costs the train step a fresh XLA
    compile (``compile_watch`` counts them). This stage edge-repeats the
    last row of data AND labels up to the full batch size and records
    the real row count in ``MiniBatch.valid`` — the optimizers turn that
    into an in-step validity mask (``nn.MaskedCriterion``) so padded
    rows contribute exactly zero to loss and gradient.

    Stateful across passes: ``full_size`` is learned from the largest
    batch seen (checkpoints carry it so a resume that starts on the
    partial batch still pads to the original shape). Host batches only —
    padding an already-placed device batch would mean a readback, so
    that is refused loudly.
    """

    def __init__(self, full_size: int | None = None):
        self.full_size = int(full_size or 0)

    def __call__(self, b: MiniBatch) -> MiniBatch:
        if _is_device_array(b.data):
            raise ValueError(
                "pad_partial_batches needs host batches, but the dataset "
                "yields already-placed device arrays — drop the "
                "dataset-level DevicePrefetcher (the optimizer's input "
                "pipeline places batches itself)")
        data = np.asarray(b.data)
        labels = np.asarray(b.labels)
        n = int(data.shape[0])
        if n >= self.full_size:
            self.full_size = n
            return MiniBatch(data, labels, valid=n)
        pad = self.full_size - n
        # edge-repeat keeps padded rows valid inputs (a zero-filled
        # label would be out of range for 1-based class targets); the
        # mask guarantees they still contribute nothing
        data = np.concatenate([data, np.repeat(data[-1:], pad, axis=0)])
        labels = np.concatenate(
            [labels, np.repeat(labels[-1:], pad, axis=0)])
        return MiniBatch(data, labels, valid=n)


class DevicePrefetcher:
    """Early device placement (moved here from ``dataset.recordio``).

    Stage form (:meth:`place_batch` / passing the instance as a
    ``PrefetchIterator`` stage): ``device_put`` — or, multi-host,
    ``jax.make_array_from_process_local_data`` over ``sharding`` — runs
    on the prefetch worker, so the train loop dequeues batches that are
    already in HBM (the final stage of the reference's decode-ahead
    pipeline, MTLabeledBGRImgToBatch.scala:46-103, reborn as an
    input-pipeline stage feeding HBM).

    Iterator form (``DevicePrefetcher(sharding)(it)``) keeps the
    historic dispatch-ahead generator for user-built dataset pipelines:
    placement of ``depth`` batches is issued ahead of consumption on
    the calling thread (no worker).
    """

    def __init__(self, sharding=None, depth: int = 2,
                 label_sharding=None):
        self.sharding = sharding
        self.label_sharding = label_sharding
        self.depth = depth

    def _place(self, arr, sharding):
        import jax
        if sharding is None:
            return jax.device_put(arr)
        if jax.process_count() > 1:
            # mesh spans non-addressable devices: assemble the global
            # array from this process's local batch, exactly like
            # DistriOptimizer._shard_batch's multi-host branch
            return jax.make_array_from_process_local_data(sharding, arr)
        return jax.device_put(arr, sharding)

    def place_batch(self, b: MiniBatch) -> MiniBatch:
        import jax
        if isinstance(b.data, jax.Array):
            return b  # a user pipeline already placed it upstream
        data = np.asarray(b.data)
        if self.sharding is not None:
            # raise the friendly misconfiguration error BEFORE
            # device_put/make_array produce a low-level sharding error
            # (the consumer's check can't fire: placement happens here)
            n_dev = len(self.sharding.device_set)
            global_n = data.shape[0] * (jax.process_count()
                                        if jax.process_count() > 1 else 1)
            if global_n % n_dev != 0:
                raise ValueError(
                    f"global batch {global_n} not divisible by {n_dev} "
                    "mesh devices (reference Utils.getBatchSize "
                    "divisibility requirement, dataset/Utils.scala:25-47)")
        labels = np.asarray(b.labels)
        label_sharding = self.label_sharding
        if label_sharding is None:
            label_sharding = self.sharding
        return MiniBatch(self._place(data, self.sharding),
                         self._place(labels, label_sharding),
                         valid=b.valid)

    def __call__(self, it):
        from collections import deque
        queue_: deque = deque()
        for batch in it:
            queue_.append(self.place_batch(batch))
            if len(queue_) > self.depth:
                yield queue_.popleft()
        while queue_:
            yield queue_.popleft()

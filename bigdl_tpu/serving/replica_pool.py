"""Replica threads: N ``ContinuousBatcher`` step loops behind one pool.

A :class:`Replica` owns one batcher (its own metric registry, its own
KV page pool — conceptually one device/slice), a re-entrant lock that
serializes every batcher touch, and a daemon driver thread that keeps
calling ``step()`` while work is queued. :class:`ReplicaPool` builds N
identically configured replicas over a shared (read-only) model and
manages their lifecycle.

Per-replica registries are the isolation the router needs: gauges like
``serving_queue_depth`` are name-keyed, so two batchers writing one
process-wide registry would overwrite each other. Live load (queue
depth, free slots, page pressure) is read straight off batcher host
state under the replica lock; latency percentiles come from the
replica-local histograms (``Replica.histogram_snapshot``), and the
router republishes the fleet view into the process registry with a
``replica`` label.

Health: every replica answers two checks in the (shared) health
registry — ``serving_batcher_<name>`` (the batcher's own
admitting/saturated readiness) and ``serving_replica_<name>``
(lifecycle: flips not-ready the moment a drain begins, which is the
load-balancer signal for rolling restarts). The ``MetricsServer``'s
``/readyz?check=serving_replica_<name>`` filter gates one replica
without consulting the others.

Thread contract: the driver thread is the only caller of ``step()``;
router threads call ``submit``/``cancel``/``export``/``stats`` under
the same lock. A ``step()`` in flight simply delays those calls by one
burst. Locks are re-entrant so batcher hooks (``on_complete``) may
fire router code on the driver thread — that hook takes the router's
``_state_lock`` while holding ``replica.lock``, which is the ONE
sanctioned nesting direction; the declaration below has raceguard
(TS1) reject the inverse anywhere in the plane.

HOST-ONLY CONTRACT: never imports jax (jaxlint JX5). The batcher class
is imported lazily inside :class:`ReplicaPool` construction, so this
module stays importable in jax-free tooling.
"""
# raceguard: order state_lock < replica.lock
from __future__ import annotations

import threading
import time

from bigdl_tpu.observability.exporter import default_health
from bigdl_tpu.observability.registry import MetricRegistry
from bigdl_tpu.serving.slo import ReplicaStats

__all__ = ["Replica", "ReplicaPool", "ACTIVE", "DRAINING", "STOPPED"]

ACTIVE = "active"
DRAINING = "draining"
STOPPED = "stopped"

_EMPTY_SNAPSHOT = {"buckets": {}, "sum": 0.0, "count": 0}


class Replica:
    """One batcher + driver thread. Construct via :class:`ReplicaPool`
    (which wires registries and health names) or directly for tests."""

    def __init__(self, name: str, batcher, *, registry, burst=None,
                 health=None, poll_interval: float = 0.005):
        self.name = str(name)
        self.batcher = batcher
        # stamp our name onto the batcher so its request-timeline
        # events (observability/request_trace.py) carry the replica
        # identity — the same post-construction idiom as
        # ``batcher.weight_version``
        batcher.replica_name = self.name
        self.registry = registry
        self.lock = threading.RLock()
        self._burst = burst
        self._poll = float(poll_interval)
        self._state = ACTIVE
        self._stop = False
        self._wake = threading.Event()
        self._health = health if health is not None else default_health()
        self._health.register(f"serving_replica_{self.name}",
                              self._ready, kind="readiness")
        self._thread = threading.Thread(
            target=self._run, name=f"bigdl-serving-{self.name}",
            daemon=True)
        self._started = False

    # -- lifecycle --
    def start(self) -> "Replica":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def _run(self):
        import logging
        log = logging.getLogger(__name__)
        while not self._stop:
            stepped = 0
            try:
                with self.lock:
                    if not self._stop and not self.batcher.idle:
                        stepped = self.batcher.step(self._burst)
            except Exception:
                # a crashing step must not silently kill the driver —
                # log and keep serving (the health check reports the
                # batcher's own admitting/saturated verdict)
                log.exception("replica %s step failed", self.name)
                stepped = 0
            if not stepped:
                # idle, or queued work that cannot admit yet: park
                # until a submit wakes us (or the poll tick re-checks)
                self._wake.wait(self._poll)
                self._wake.clear()

    def stop(self, timeout: float = 10.0) -> None:
        """Stop the driver thread and unregister health checks (a dead
        replica must stop answering for the process)."""
        self._stop = True
        self._wake.set()
        if self._started:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise RuntimeError(
                    f"replica {self.name} driver did not stop in "
                    f"{timeout}s")
        with self.lock:
            self._state = STOPPED
        self._health.unregister(f"serving_replica_{self.name}")
        self._health.unregister(self.batcher.health_name)

    # -- state --
    @property
    def state(self) -> str:
        return self._state

    def drain_begin(self) -> None:
        """Stop admissions: lifecycle readiness flips immediately; the
        driver keeps stepping so in-flight sequences finish."""
        with self.lock:
            if self._state == STOPPED:
                raise RuntimeError(f"replica {self.name} is stopped")
            self._state = DRAINING

    def resume(self) -> None:
        with self.lock:
            if self._state == STOPPED:
                raise RuntimeError(f"replica {self.name} is stopped")
            self._state = ACTIVE
        self._wake.set()

    def _ready(self):
        # lock-free racy read: a health probe must never block behind
        # a decode burst (HealthCheck.run already fences crashes)
        if self._state != ACTIVE:
            return False, f"replica {self.name} is {self._state}"
        ok, detail = self.batcher._ready()
        return ok, f"{self.name}: {detail}"

    # -- weight version (deploy plane; bigdl_tpu/deploy/) --
    @property
    def weight_version(self):
        """Which published weight set this replica serves (None =
        unversioned). Lives on the batcher so exported KV snapshots
        carry it."""
        return getattr(self.batcher, "weight_version", None)

    def set_weights(self, model=None, *, weight_version) -> None:
        """Swap the served weights (``model=None`` just re-stamps the
        version — the publisher uses that to mark a pre-existing fleet
        as version v0). The batcher enforces idleness and identical
        geometry; callers drain first (``Router.drain``) and ``resume``
        after."""
        with self.lock:
            if model is None:
                self.batcher.weight_version = weight_version
            else:
                self.batcher.set_weights(model, weight_version)

    # -- request plane (router-facing; all under the replica lock) --
    def submit(self, request_id, prompt=None, *, snapshot=None,
               prefill_from=None) -> None:
        with self.lock:
            if self._state != ACTIVE:
                raise RuntimeError(
                    f"replica {self.name} is {self._state}: not "
                    "admitting")
            self.batcher.submit(request_id, prompt, snapshot=snapshot,
                                prefill_from=prefill_from)
        self._wake.set()

    def cancel(self, request_id) -> bool:
        with self.lock:
            return self.batcher.cancel(request_id)

    def prefill_only(self, request_id, prompt):
        """Disaggregation entry: run a prefill here (the lock means it
        interleaves with THIS replica's bursts, never a decode
        replica's) and hand the KV snapshot back."""
        with self.lock:
            return self.batcher.prefill_only(request_id, prompt)

    def export_requests(self) -> list:
        with self.lock:
            return self.batcher.export_requests()

    def export_request(self, request_id):
        """Export ONE in-flight request's KV snapshot (frees its slot).
        The router's per-request drain policy uses this to migrate a
        chosen subset while the rest finish here."""
        with self.lock:
            return self.batcher.export_request(request_id)

    def inflight_ids(self) -> list:
        """Ids currently occupying slots (not the queue)."""
        with self.lock:
            return [s[0] for s in self.batcher.slots if s is not None]

    def pop_queued(self) -> list:
        with self.lock:
            return self.batcher.pop_queued()

    def wait_idle(self, timeout: float = 60.0) -> bool:
        """Block until the batcher has nothing queued or in flight."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self.lock:
                if self.batcher.idle:
                    return True
            time.sleep(self._poll)
        with self.lock:
            return self.batcher.idle

    # -- telemetry --
    def histogram_snapshot(self, name: str) -> dict:
        m = self.registry.get(name)
        return m.snapshot() if m is not None else dict(_EMPTY_SNAPSHOT)

    def stats(self) -> ReplicaStats:
        from bigdl_tpu.serving.slo import percentile
        with self.lock:
            b = self.batcher
            free_slots = sum(s is None for s in b.slots)
            queue_depth = len(b.queue)
            pages_free = b.cache.pages_free
            util = 1.0 - pages_free / b.cache.num_pages
            skips = int(b._m_skips.value())
            state = self._state
        ttft = self.histogram_snapshot("serving_ttft_seconds")
        dec = self.histogram_snapshot("serving_decode_token_seconds")
        return ReplicaStats(
            name=self.name, state=state, queue_depth=queue_depth,
            active_slots=b.max_batch - free_slots,
            free_slots=free_slots, pages_free=pages_free,
            kv_utilization=util,
            ttft_p50=percentile(ttft, 0.5),
            ttft_p99=percentile(ttft, 0.99),
            decode_token_p99=percentile(dec, 0.99),
            prefill_skips=skips)


class ReplicaPool:
    """N identically configured batcher replicas over one model.

    ``batcher_kwargs`` forwards to ``ContinuousBatcher`` (``max_batch``,
    ``num_pages``, ``page_size``, ``max_new_tokens``, ``max_burst``,
    ``eos_id``); identical geometry across replicas is what makes KV
    snapshots portable between them (the batcher validates on adopt).
    Each replica gets a private :class:`MetricRegistry` and health
    checks named per replica in the SHARED health registry."""

    def __init__(self, model, n_replicas: int = 2, *, names=None,
                 burst=None, health=None, start: bool = True,
                 aot_cache=None, weight_version=None, **batcher_kwargs):
        if n_replicas < 1:
            raise ValueError(f"need >= 1 replica, got {n_replicas}")
        if names is None:
            names = [f"r{i}" for i in range(n_replicas)]
        if len(names) != n_replicas or len(set(names)) != n_replicas:
            raise ValueError(f"need {n_replicas} distinct names, got "
                             f"{names}")
        self._health = health if health is not None else default_health()
        self._model = model
        self._weight_version = weight_version
        self._burst = burst
        self._batcher_kwargs = dict(batcher_kwargs)
        # ONE shared AOT pipeline for every replica this pool ever
        # builds (autoscaler spin-ups included): the first replica
        # compiles each step and stores the executable; the Nth replica
        # of identical geometry compiles nothing. ``aot_cache`` accepts
        # a PagedStepCompilers, an AOTCache, or a cache directory path.
        self.aot = None
        if aot_cache is not None:
            # lazy: keeps this module importable without jax (JX5)
            from bigdl_tpu.models.transformer.serving import \
                PagedStepCompilers
            self.aot = (aot_cache
                        if isinstance(aot_cache, PagedStepCompilers)
                        else PagedStepCompilers(aot_cache))
            self._batcher_kwargs["aot_cache"] = self.aot
        self._running = False
        self._next_auto = n_replicas
        self.replicas: dict[str, Replica] = {}
        for name in names:
            self._build_replica(name)
        if start:
            self.start()

    def _build_replica(self, name: str, *, model=None,
                       weight_version=None) -> Replica:
        # lazy: keeps this module importable without jax (JX5 contract)
        from bigdl_tpu.models.transformer.serving import ContinuousBatcher
        reg = MetricRegistry()
        batcher = ContinuousBatcher(
            model if model is not None else self._model,
            registry=reg, health=self._health,
            health_name=f"serving_batcher_{name}",
            **self._batcher_kwargs)
        # stamped post-construction so monkeypatched batcher fakes that
        # predate the kwarg keep working
        batcher.weight_version = (weight_version
                                  if weight_version is not None
                                  else self._weight_version)
        rep = Replica(name, batcher, registry=reg, burst=self._burst,
                      health=self._health)
        self.replicas[name] = rep
        return rep

    @property
    def model(self):
        """The default model newly built replicas serve."""
        return self._model

    def set_default_model(self, model, *, weight_version=None) -> None:
        """Point FUTURE replica builds (``add_replica`` — autoscaler
        spin-ups included) at a new weight set. Does not touch running
        replicas; the publisher rolls those one by one
        (``Replica.set_weights``) and then calls this so scale-ups
        never resurrect the old version."""
        self._model = model
        self._weight_version = weight_version

    # -- elastic membership (the autoscaler's primitives) --
    def add_replica(self, name: str | None = None, *, start: bool = True,
                    warm: bool = True, model=None,
                    weight_version=None) -> Replica:
        """Build one more identically configured replica and (with the
        pool running) put it in rotation. With the pool's shared AOT
        pipeline the new batcher compiles nothing — its executables
        come from the in-process table or the cache directory; with
        ``warm=True`` its default decode executable is readied before
        the driver starts, so the first routed request never waits on
        construction. Auto-names ``rN`` when ``name`` is omitted.
        Registers the replica's two health checks as a side effect of
        construction. Callers fronting the pool with a Router must also
        ``router.attach_replica(name)`` to wire completion hooks.
        ``model``/``weight_version`` override the pool defaults — the
        weight publisher's canary spins up on the CANDIDATE weights
        while the fleet keeps serving the current ones."""
        if name is None:
            while f"r{self._next_auto}" in self.replicas:
                self._next_auto += 1
            name = f"r{self._next_auto}"
            self._next_auto += 1
        if name in self.replicas:
            raise ValueError(f"replica {name!r} already exists")
        rep = self._build_replica(name, model=model,
                                  weight_version=weight_version)
        if warm:
            rep.batcher.warmup()
        if start and self._running:
            rep.start()
        return rep

    def remove_replica(self, name: str, timeout: float = 10.0) -> None:
        """Stop and drop replica ``name``: the driver thread joins and
        BOTH its health checks unregister, so ``/readyz`` of a
        scaled-down fleet reports only live replicas. The caller drains
        first (``Router.drain(name, migrate=True)``) — work still
        queued or in flight here is lost. KeyError for unknown names."""
        rep = self.replicas.pop(name)
        rep.stop(timeout)

    @property
    def names(self) -> list[str]:
        return list(self.replicas)

    def __getitem__(self, name: str) -> Replica:
        return self.replicas[name]

    def __iter__(self):
        # snapshot: scale events mutate the dict from other threads
        # while health probes / fleet-stats scrapes iterate it
        return iter(list(self.replicas.values()))

    def __len__(self) -> int:
        return len(self.replicas)

    def start(self) -> "ReplicaPool":
        self._running = True
        for r in list(self.replicas.values()):
            r.start()
        return self

    def stats(self) -> list[ReplicaStats]:
        return [r.stats() for r in list(self.replicas.values())]

    def close(self, timeout: float = 10.0) -> None:
        for r in list(self.replicas.values()):
            r.stop(timeout)

    def __enter__(self) -> "ReplicaPool":
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

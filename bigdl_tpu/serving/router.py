"""SLO-aware request router over a :class:`ReplicaPool`.

The front door that turns the single-replica serving stack into a
service (ROADMAP item 1; BigDL 2.0's pipeline-to-serving story,
arXiv:2204.01715). One ``submit()`` call per request; the router

- **places** it on the best admissible replica — admission gates on
  each replica's live queue depth, KV-page utilization and observed
  TTFT/decode p99 vs the :class:`SLOConfig` targets
  (``slo.admissible``), ranking survivors by ``slo.load_score``;
- **reuses prefixes**: a prompt seen before routes sticky to the
  replica that served it and ADOPTS the retained KV snapshot instead
  of re-prefilling (``router_prefix_hits_total`` at the router,
  ``serving_prefill_skips_total`` on the adopting replica); a prompt
  sharing only a PREFIX with a cached entry (the longest-prefix radix
  walk, page-granular) adopts the truncated snapshot and prefills
  just the suffix (``router_prefix_partial_hits_total``,
  ``router_prefix_tokens_reused_total``);
- **disaggregates** long prefills: prompts past
  ``slo.long_prefill_tokens`` prefill on the designated (or
  lowest-load) replica via ``prefill_only`` and the KV snapshot is
  handed to a different decode replica, so decode bursts never stall
  behind a long prompt;
- **overflows** to a bounded router-level pending queue when no
  replica admits, and raises :class:`RouterSaturated` past
  ``slo.max_pending`` (explicit load-shedding);
- **drains** replicas for rolling restarts: ``drain(name)`` stops
  admissions (the replica's ``/readyz`` check flips immediately),
  re-dispatches its still-queued requests to survivors, then either
  lets in-flight sequences finish or — ``migrate=True`` — exports
  their KV mid-decode and resumes them elsewhere, bitwise.

Results fan in through the batchers' ``on_complete`` hooks into one
``finished()`` stream; every accepted request completes exactly once
(no drops, no duplicates — test-pinned).

Every accepted request also carries a per-request TIMELINE
(observability/request_trace.py): admission, pending park,
prefix-cache outcome, placement, disaggregated handoff, migration and
completion land as structured events on ONE timeline that follows the
request across replicas and weight versions; the tracker tail-samples
at completion so only the interesting tail is retained in full. The
router-side wait (submit -> replica placement, admission plus any
pending park) is observed into ``router_queue_wait_seconds`` for
EVERY request — the component the batcher's TTFT clock cannot see —
with the request id attached to its histogram bucket as an
OpenMetrics exemplar, so a breached ``/metrics`` bucket links
straight to ``/requests/<id>``. ``latency_summary()`` carries the
queue-wait percentiles and the tracker's tail attribution
(docs/SERVING.md "diagnosing a slow request").

Locking: ``_state_lock`` guards only the router's own dicts and is
never held while a replica lock is being acquired; replica driver
threads call back into ``_on_complete`` holding their replica lock and
take ``_state_lock`` briefly, and the prefix-capture hook takes the
prefix cache's internal lock the same way (replica -> prefixcache).
The dispatch path queries the cache BEFORE touching any replica lock,
so ``prefixcache._lock`` nests strictly inside ``replica.lock`` and
never the reverse. The request tracker's lock is a strict LEAF inside
all of them: timeline events are recorded while ``_state_lock`` or a
replica lock is held, and the tracker never calls back into the
serving plane. Those one-way orders are what make the plane
deadlock-free, and the declaration below turns them into a
machine-checked gate (dev/analysis/raceguard.py TS1): acquiring
``replica.lock`` anywhere while ``state_lock`` or the cache lock is
held is a lint failure. The pending queue is flushed by a single
dispatcher thread, so batcher-level arrival order is preserved.

HOST-ONLY CONTRACT: never imports jax (jaxlint JX5) — routing is pure
host orchestration over the batcher API.
"""
# raceguard: order requesttracker.mu < state_lock
# raceguard: order state_lock < prefixcache._lock < replica.lock
from __future__ import annotations

import threading
import time
from collections import deque

from bigdl_tpu.observability import trace
from bigdl_tpu.observability.exporter import default_health
from bigdl_tpu.observability.registry import default_registry
from bigdl_tpu.observability.request_trace import default_tracker
from bigdl_tpu.serving.prefix_cache import PrefixCache
from bigdl_tpu.serving.slo import (SLOConfig, admissible, load_score,
                                   merge_snapshots, percentile)

__all__ = ["Router", "RouterSaturated"]


class RouterSaturated(RuntimeError):
    """No replica admits and the router-level pending queue is full."""


class Router:
    """See module docstring. ``pool`` is a started
    :class:`~bigdl_tpu.serving.replica_pool.ReplicaPool`; the router
    takes over each batcher's ``on_complete``/``on_prefill`` hooks.

    - ``prefill_replica``: name of the designated prefill replica for
      disaggregation (default: pick the lowest-load admissible one per
      request).
    - ``capture_prefixes``: snapshot prompts >= the prefix cache's
      ``min_tokens`` after their first prefill for later reuse.
    - ``registry``/``health``: the process-wide fleet view — labeled
      per-replica gauges, router counters, and the
      ``serving_router`` readiness check (ready while >= 1 replica
      admits).
    """

    def __init__(self, pool, *, slo: SLOConfig | None = None,
                 prefix_cache: PrefixCache | None = None,
                 registry=None, health=None, prefill_replica=None,
                 capture_prefixes: bool = True, tracker=None):
        self.pool = pool
        self.slo = slo if slo is not None else SLOConfig()
        # tracker=None -> the process-wide default; tracker=False ->
        # timelines off (queue-wait histogram still observed)
        if tracker is False:
            self._tracker = None
        else:
            self._tracker = (tracker if tracker is not None
                             else default_tracker())
        if self._tracker is not None and self._tracker.slo is None:
            # teach the default tracker this fleet's SLO so retention
            # (ttft > slo) and stall thresholds mean something
            self._tracker.slo = self.slo
        self.prefix = (prefix_cache if prefix_cache is not None
                       else PrefixCache())
        self._capture = bool(capture_prefixes)
        if prefill_replica is not None and \
                prefill_replica not in pool.replicas:
            raise ValueError(f"unknown prefill replica "
                             f"{prefill_replica!r} (have {pool.names})")
        self._prefill_name = prefill_replica

        reg = default_registry() if registry is None else registry
        self._m_requests = reg.counter(
            "router_requests_total", "requests accepted by the router")
        self._m_completed = reg.counter(
            "router_completed_total", "requests completed and collected")
        self._m_prefix_hits = reg.counter(
            "router_prefix_hits_total",
            "requests served from the prefix KV cache (prefill skipped)")
        self._m_prefix_partial = reg.counter(
            "router_prefix_partial_hits_total",
            "requests that adopted a truncated prefix snapshot and "
            "prefilled only their suffix (longest-prefix radix hits)")
        self._m_tokens_reused = reg.counter(
            "router_prefix_tokens_reused_total",
            "prompt tokens whose KV was adopted from the prefix cache "
            "instead of prefilled (exact + partial hits)")
        self._m_prompt_tokens = reg.counter(
            "router_prompt_tokens_total",
            "prompt tokens across all accepted requests (denominator "
            "for the tokens-reused fraction)")
        self._m_disagg = reg.counter(
            "router_disagg_prefills_total",
            "long prompts prefilled on one replica, decoded on another")
        self._m_rejected = reg.counter(
            "router_rejected_total",
            "requests shed because router + replicas were saturated")
        self._m_migrated = reg.counter(
            "router_migrations_total",
            "in-flight requests moved between replicas during drain")
        self._m_restarts = reg.counter(
            "router_version_restarts_total",
            "orphaned KV snapshots (weight version no longer served "
            "anywhere) restarted from their prompt on the current fleet")
        self._m_pending = reg.gauge(
            "router_pending_depth",
            "requests waiting at the router for an admissible replica")
        self._m_rq = reg.gauge(
            "router_replica_queue_depth",
            "per-replica batcher queue depth as last seen by the router",
            labelnames=("replica",))
        self._m_rutil = reg.gauge(
            "router_replica_kv_utilization",
            "per-replica KV page utilization as last seen by the router",
            labelnames=("replica",))
        self._m_qwait = reg.histogram(
            "router_queue_wait_seconds",
            "seconds between submit() and replica placement (admission "
            "+ pending park) — the TTFT component the batcher clock "
            "cannot see; observed for EVERY accepted request")

        self._health = health if health is not None else default_health()
        self._health.register("serving_router", self._ready,
                              kind="readiness")

        # _state_lock guards the dicts below; NEVER held while taking a
        # replica lock (see module docstring)
        self._state_lock = threading.Lock()
        self._inflight: dict = {}       # rid -> replica name | None
        self._enq: dict = {}            # rid -> (t_monotonic, cause)
        self._pending: deque = deque()  # (rid, payload, session)
        self._results: deque = deque()
        self._sessions: dict = {}       # session id -> replica name
        self._closed = False

        # replicas the router must NOT place live traffic on even
        # though they sit in the pool: the weight publisher's canary
        # qualifies on candidate weights and is driven directly, never
        # through live routing. Quarantine a name BEFORE add_replica
        # and there is no window where the dispatcher can see it.
        self._quarantined: set = set()

        # observer taps (assignable; both optional, crash-fenced): the
        # deploy plane's ShadowTap mirrors a fraction of live traffic
        # onto a canary replica through these without sitting in the
        # request path. on_submit(rid, prompt) fires once per ACCEPTED
        # prompt; on_result(rid, tokens) once per completion.
        self.on_submit = None
        self.on_result = None

        for name, rep in pool.replicas.items():
            rep.batcher.on_complete = self._make_on_complete(name)
            rep.batcher.tracker = self._tracker
            if self._capture:
                rep.batcher.on_prefill = self._make_on_prefill(name)

        self._pump_wake = threading.Event()
        self._pump_thread = threading.Thread(
            target=self._pump, name="bigdl-serving-router", daemon=True)
        self._pump_thread.start()

    # -- request timelines (tracker lock is a leaf; no-ops when off) --
    def _tev(self, rid, event, **fields) -> None:
        if self._tracker is not None:
            self._tracker.event(rid, event, **fields)

    def _t_finish(self, rid, status: str = "ok") -> None:
        if self._tracker is not None:
            self._tracker.finish(rid, status=status)

    # -- hooks (run on replica driver threads, replica lock held) --
    def _make_on_complete(self, name):
        def hook(rid, toks):
            with self._state_lock:
                self._inflight.pop(rid, None)
                self._results.append((rid, list(toks)))
            self._m_completed.inc()
            self._tev(rid, "complete", replica=name, tokens=len(toks))
            self._t_finish(rid)
            tap = self.on_result
            if tap is not None:
                try:
                    tap(rid, list(toks))
                except Exception:
                    import logging
                    logging.getLogger(__name__).exception(
                        "on_result tap failed for %r", rid)
            self._pump_wake.set()
        return hook

    def _make_on_prefill(self, name):
        def hook(rid, prompt, snapshot_fn):
            if len(prompt) < self.prefix.min_tokens:
                return
            # peek, not lookup: a presence probe must not count a
            # hit/miss or reshuffle LRU order — capture traffic would
            # otherwise pollute the cache telemetry (and with the radix
            # index, skip when a LONGER entry already covers us)
            if self.prefix.peek(prompt) is not None:
                return          # already retained; skip the re-export
            self.prefix.put(prompt, name, snapshot_fn())
        return hook

    # -- health --
    def _ready(self):
        n_ok = 0
        for rep in self.pool:
            # racy read by design: probes must not block on locks
            if (rep.state == "active" and rep.name not in
                    self._quarantined and rep.batcher._ready()[0]):
                n_ok += 1
        return (n_ok > 0,
                f"{n_ok}/{len(self.pool)} replicas admitting")

    # -- quarantine (the publisher's canary fence) --
    def quarantine(self, name: str) -> None:
        """Exclude ``name`` from live placement (see the field comment
        in ``__init__``). Safe to call before the replica exists."""
        self._quarantined.add(name)

    def unquarantine(self, name: str) -> None:
        self._quarantined.discard(name)
        self._pump_wake.set()

    # -- submission --
    def submit(self, request_id, prompt, *, session=None):
        """Accept one request (list of 1-based token ids). Returns the
        replica name it was placed on, or ``None`` if it parked in the
        router's pending queue (dispatched as soon as a replica
        admits). Raises on duplicate in-flight ids and
        :class:`RouterSaturated` past ``slo.max_pending``."""
        if self._closed:
            raise RuntimeError("router is closed")
        prompt = list(prompt)
        with self._state_lock:
            if request_id in self._inflight:
                raise ValueError(
                    f"duplicate request_id {request_id!r}: still "
                    "pending or in flight")
            self._inflight[request_id] = None    # reserve
            self._enq[request_id] = (time.monotonic(), "submit")
        self._m_requests.inc()
        if self._tracker is not None:
            self._tracker.begin(request_id, prompt_len=len(prompt))
        try:
            placed = self._dispatch(request_id, prompt, session)
        except Exception:
            with self._state_lock:
                self._inflight.pop(request_id, None)
                self._enq.pop(request_id, None)
            self._t_finish(request_id, "error")
            raise
        if placed is None:
            with self._state_lock:
                if len(self._pending) >= self.slo.max_pending:
                    self._inflight.pop(request_id, None)
                    self._enq.pop(request_id, None)
                    self._m_rejected.inc()
                    self._t_finish(request_id, "shed")
                    raise RouterSaturated(
                        f"no replica admits and {len(self._pending)} "
                        f"requests already pending "
                        f"(slo.max_pending={self.slo.max_pending})")
                self._pending.append((request_id, prompt, session))
                self._m_pending.set(len(self._pending))
                depth = len(self._pending)
            self._tev(request_id, "park", depth=depth)
        # counted once per ACCEPTED request (after the shed gate), so
        # the tokens-reused fraction has a clean denominator even when
        # pending work is re-dispatched several times
        self._m_prompt_tokens.inc(len(prompt))
        tap = self.on_submit
        if tap is not None:
            try:
                tap(request_id, list(prompt))
            except Exception:
                import logging
                logging.getLogger(__name__).exception(
                    "on_submit tap failed for %r", request_id)
        return placed

    def cancel(self, request_id) -> bool:
        """Cancel wherever the request is: router pending queue, a
        replica queue, or an in-flight slot. False if unknown/already
        finished."""
        with self._state_lock:
            for i, (rid, _, _) in enumerate(self._pending):
                if rid == request_id:
                    del self._pending[i]
                    self._m_pending.set(len(self._pending))
                    self._inflight.pop(request_id, None)
                    self._enq.pop(request_id, None)
                    self._t_finish(request_id, "cancelled")
                    return True
            owner = self._inflight.get(request_id)
        if owner is not None and self.pool[owner].cancel(request_id):
            with self._state_lock:
                self._inflight.pop(request_id, None)
            self._t_finish(request_id, "cancelled")
            return True
        return False

    # -- placement --
    def _fleet_stats(self) -> dict:
        stats = {}
        for rep in self.pool:
            if rep.name in self._quarantined:
                continue
            s = rep.stats()
            stats[s.name] = s
            self._m_rq.set(s.queue_depth, replica=s.name)
            self._m_rutil.set(s.kv_utilization, replica=s.name)
        return stats

    def _version_of(self, name):
        rep = self.pool.replicas.get(name)
        return getattr(rep, "weight_version", None) if rep else None

    def _version_ok(self, snapshot, name) -> bool:
        """May ``name`` adopt ``snapshot``? None on either side means
        unversioned and matches anything (mirrors the batcher's own
        adopt-time check — the router filters up front so a mismatch
        never even reaches a replica)."""
        sv = getattr(snapshot, "weight_version", None)
        rv = self._version_of(name)
        return sv is None or rv is None or sv == rv

    def _dispatch(self, rid, payload, session):
        """Try to place ``payload`` (a prompt list, or a KVSnapshot
        when re-dispatching drained/migrated work). Returns the replica
        name or None when nothing admits right now."""
        # prompts arrive as lists; anything else is a KV snapshot
        is_prompt = isinstance(payload, list)
        if not is_prompt and not any(self._version_ok(payload, n)
                                     for n in self.pool.names):
            # the snapshot's weight version is no longer served by ANY
            # pool member (a rolling publish retired it while this sat
            # in pending): its KV can never be adopted again, so
            # restart the sequence from its prompt — the result is then
            # attributable to exactly ONE (the current) version, and
            # the request still completes exactly once
            self._m_restarts.inc()
            self._tev(rid, "orphan_restart",
                      weight_version=getattr(payload, "weight_version",
                                             None))
            with self._state_lock:
                # this wait attributes to migration, not admission
                if rid in self._enq:
                    self._enq[rid] = (self._enq[rid][0], "restart")
            payload = list(payload.prompt)
            is_prompt = True
        stats = self._fleet_stats()
        cands = [s for s in stats.values()
                 if admissible(s, self.slo)[0]]
        if not is_prompt:
            # a snapshot's KV is only valid under the params that wrote
            # it: place it on a version-matching replica or keep it
            # parked (during a rolling publish the old-version
            # survivors are exactly that set)
            cands = [s for s in cands
                     if self._version_ok(payload, s.name)]
        if cands:
            # emitted only when something admits: a parked request's
            # retry loop must not spam its timeline every flush tick
            self._tev(rid, "route", candidates=len(cands))
        with trace.span("route", cat="serving",
                        prompt_len=len(payload) if is_prompt else
                        len(payload.prompt),
                        candidates=len(cands)):
            if is_prompt:
                hit, matched = self.prefix.lookup_longest(payload)
                if hit is not None and cands:
                    # materialize once: int8-stored entries dequantize
                    # per access, and version filter + adopt must see
                    # the SAME snapshot object
                    snap = hit.snapshot
                    vcands = [s for s in cands
                              if self._version_ok(snap, s.name)]
                    if vcands:
                        target = (hit.replica
                                  if hit.replica in {s.name
                                                     for s in vcands}
                                  else min(vcands,
                                           key=load_score).name)
                        if list(hit.prompt) == payload:
                            # exact: adopt everything, skip prefill
                            self.pool[target].submit(rid, snapshot=snap)
                            self._m_prefix_hits.inc()
                            self._m_tokens_reused.inc(len(payload))
                            self._tev(rid, "prefix_cache",
                                      outcome="exact",
                                      tokens_reused=len(payload))
                            self._place(rid, target, session)
                            return target
                        placed = self._adopt_partial(
                            rid, payload, matched, snap, target,
                            session)
                        if placed is not None:
                            return placed
                    # retained prefix from a superseded weight version
                    # (or no adoptable full page after truncation):
                    # fall through to a fresh prefill (the rollout's
                    # drains forget stale entries replica by replica)
                if (len(payload) >= self.slo.long_prefill_tokens
                        and len(cands) > 1):
                    return self._dispatch_disaggregated(
                        rid, payload, session, stats, cands)
            if not cands:
                return None
            target = self._pick(cands, session)
            if is_prompt:
                self._tev(rid, "prefix_cache", outcome="miss",
                          tokens_reused=0)
                self.pool[target].submit(rid, payload)
            else:
                self.pool[target].submit(rid, snapshot=payload)
            self._place(rid, target, session)
            return target

    def _adopt_partial(self, rid, prompt, matched, snap, target,
                       session):
        """Adopt the matched full pages of ``snap`` on ``target`` and
        prefill only the suffix. Returns the replica name, or None to
        fall back to a fresh prefill (no usable page boundary after
        truncation, or the replica refused the job)."""
        try:
            # leave >= 1 suffix token so there is a logit to sample:
            # truncate floors to the snapshot's page boundary
            trunc = snap.truncate(min(matched, len(prompt) - 1))
        except ValueError:
            return None           # under one full page after flooring
        if list(trunc.prompt) != prompt[:trunc.n_cached]:
            return None           # never adopt mismatched KV
        try:
            with trace.span("suffix adopt", cat="serving",
                            prompt_len=len(prompt),
                            reused=trunc.n_cached):
                self.pool[target].submit(
                    rid, prompt, snapshot=trunc,
                    prefill_from=trunc.n_cached)
        except (RuntimeError, ValueError):
            return None           # transient refusal -> fresh prefill
        self._m_prefix_partial.inc()
        self._m_tokens_reused.inc(trunc.n_cached)
        self._tev(rid, "prefix_cache", outcome="partial",
                  tokens_reused=trunc.n_cached)
        self._place(rid, target, session)
        return target

    def _pick(self, cands, session) -> str:
        if session is not None:
            sticky = self._sessions.get(session)
            if sticky is not None and any(s.name == sticky
                                          for s in cands):
                return sticky
        return min(cands, key=load_score).name

    def _place(self, rid, target, session) -> None:
        with self._state_lock:
            self._inflight[rid] = target
            if session is not None:
                self._sessions[session] = target
            enq = self._enq.pop(rid, None)
        if enq is not None:
            # the common success point for EVERY placement path: exact
            # / partial adopt, disaggregated, plain, and requeued work.
            # The exemplar ties the bucket to /requests/<id>.
            t_enq, cause = enq
            wait = time.monotonic() - t_enq
            self._m_qwait.observe(wait, exemplar=str(rid))
            self._tev(rid, "place", replica=target, cause=cause,
                      wait_s=round(wait, 9))

    def _dispatch_disaggregated(self, rid, prompt, session, stats,
                                cands):
        """Prefill on the designated/lowest-load replica, decode on the
        best OTHER candidate — a long prompt never parks a decode
        replica's bursts behind its prefill."""
        self._tev(rid, "prefix_cache", outcome="miss", tokens_reused=0)
        names = {s.name for s in cands}
        if self._prefill_name is not None and self._prefill_name in names:
            pre = self._prefill_name
        else:
            pre = min(cands, key=load_score).name
        decode_cands = [s for s in cands if s.name != pre]
        if not decode_cands:      # pre is the lone candidate
            self.pool[pre].submit(rid, prompt)
            self._place(rid, pre, session)
            return pre
        dec = self._pick(decode_cands, session)
        t_pre = time.monotonic()
        try:
            with trace.span("disagg prefill", cat="serving",
                            prefill=pre, decode=dec,
                            prompt_len=len(prompt)):
                snap = self.pool[pre].prefill_only(rid, prompt)
        except RuntimeError:
            # transient page pressure on the prefill side: fall back
            # to a plain placement rather than failing the request
            target = self._pick(cands, session)
            self.pool[target].submit(rid, prompt)
            self._place(rid, target, session)
            return target
        pre_dur = time.monotonic() - t_pre
        self._m_disagg.inc()
        self._tev(rid, "disagg", prefill=pre, decode=dec)
        self._tev(rid, "prefill_end", kind="disagg", replica=pre,
                  dur_s=round(pre_dur, 9))
        with self._state_lock:
            # the synchronous disagg prefill is prefill time, not
            # queue wait: push the enqueue clock past it so the place
            # event's wait_s (and router_queue_wait_seconds) measure
            # only admission + park
            if rid in self._enq:
                t_enq, cause = self._enq[rid]
                self._enq[rid] = (t_enq + pre_dur, cause)
        if self._capture:
            # long prompts are exactly the ones worth retaining
            self.prefix.put(prompt, dec, snap)
        self.pool[dec].submit(rid, snapshot=snap)
        self._place(rid, dec, session)
        return dec

    # -- pending pump (single consumer preserves arrival order) --
    def _pump(self):
        while not self._closed:
            self._pump_wake.wait(0.02)
            self._pump_wake.clear()
            try:
                self._flush_pending()
            except Exception:
                import logging
                logging.getLogger(__name__).exception(
                    "router pending flush failed")

    def _flush_pending(self):
        while True:
            with self._state_lock:
                if not self._pending:
                    self._m_pending.set(0)
                    return
                rid, payload, session = self._pending[0]
            if self._dispatch(rid, payload, session) is None:
                return            # still saturated; next wake retries
            with self._state_lock:
                self._pending.popleft()
                self._m_pending.set(len(self._pending))

    # -- results --
    def finished(self) -> list:
        """Pop completed ``(request_id, tokens)`` pairs (every accepted
        request appears exactly once)."""
        with self._state_lock:
            out = list(self._results)
            self._results.clear()
        return out

    @property
    def inflight_count(self) -> int:
        with self._state_lock:
            return len(self._inflight)

    @property
    def pending_count(self) -> int:
        with self._state_lock:
            return len(self._pending)

    def wait_all(self, timeout: float = 120.0) -> None:
        """Block until every accepted request has completed."""
        deadline = time.monotonic() + timeout
        while True:
            with self._state_lock:
                busy = len(self._inflight) + len(self._pending)
            if not busy:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"{busy} requests still outstanding after "
                    f"{timeout}s")
            time.sleep(0.005)

    # -- drain / rolling restart --
    def drain(self, name: str, *, migrate: bool = False,
              timeout: float = 120.0, policy=None) -> dict:
        """Take replica ``name`` out of rotation: admissions stop and
        its ``serving_replica_<name>`` readiness flips immediately;
        still-queued requests re-dispatch to the survivors; in-flight
        sequences either finish here (default) or — ``migrate=True`` —
        export their KV mid-decode and resume on other replicas,
        bitwise. ``policy`` decides per request instead:
        ``policy(request_id) -> "finish" | "migrate"`` — the weight
        publisher's version-skew knob (migrated snapshots carry the OLD
        weight version and only ever land on old-version survivors;
        with none left they would park, so the publisher forces
        "finish" for the last replica of a version). Returns a summary
        dict. ``resume(name)`` puts the replica back."""
        rep = self.pool[name]
        with trace.span("drain", cat="serving", replica=name,
                        migrate=migrate, policy=policy is not None):
            rep.drain_begin()
            requeued = rep.pop_queued()
            for rid, payload in requeued:
                self._tev(rid, "requeue", from_replica=name)
                self._requeue(rid, payload)
            migrated = []
            if policy is not None:
                for rid in rep.inflight_ids():
                    if policy(rid) != "migrate":
                        continue
                    snap = rep.export_request(rid)
                    migrated.append((rid, snap))
                    self._m_migrated.inc()
                    self._tev(rid, "migrate", from_replica=name,
                              weight_version=getattr(
                                  snap, "weight_version", None))
                    self._requeue(rid, snap, cause="migrate")
                if not rep.wait_idle(timeout):
                    raise TimeoutError(
                        f"replica {name} did not finish its kept "
                        f"in-flight requests in {timeout}s")
            elif migrate:
                migrated = rep.export_requests()
                for rid, snap in migrated:
                    self._m_migrated.inc()
                    self._tev(rid, "migrate", from_replica=name,
                              weight_version=getattr(
                                  snap, "weight_version", None))
                    self._requeue(rid, snap, cause="migrate")
            elif not rep.wait_idle(timeout):
                raise TimeoutError(
                    f"replica {name} did not drain in {timeout}s")
            self.prefix.forget_replica(name)
            with self._state_lock:
                dead_sessions = [k for k, v in self._sessions.items()
                                 if v == name]
                for k in dead_sessions:
                    del self._sessions[k]
        self._pump_wake.set()
        return {"replica": name, "requeued": len(requeued),
                "migrated": len(migrated)}

    def _requeue(self, rid, payload, *, cause: str = "requeue") -> None:
        with self._state_lock:
            self._inflight[rid] = None
            self._enq[rid] = (time.monotonic(), cause)
            self._pending.append((rid, payload, None))
            self._m_pending.set(len(self._pending))

    def resume(self, name: str) -> None:
        self.pool[name].resume()
        self._pump_wake.set()

    def attach_replica(self, name: str) -> None:
        """Wire a replica added to the pool AFTER router construction
        (``pool.add_replica``) into the result stream: completion /
        prefix-capture hooks plus a dispatcher wake so pending work
        spills onto the new capacity immediately. Idempotent."""
        rep = self.pool[name]
        rep.batcher.on_complete = self._make_on_complete(name)
        rep.batcher.tracker = self._tracker
        if self._capture:
            rep.batcher.on_prefill = self._make_on_prefill(name)
        self._pump_wake.set()

    # -- fleet latency view (bench serving rows) --
    def latency_summary(self) -> dict:
        """Fleet-wide latency percentiles: per-replica histograms
        merged by bucket (conservative upper-bound estimates)."""
        ttft = merge_snapshots(
            r.histogram_snapshot("serving_ttft_seconds")
            for r in self.pool if r.name not in self._quarantined)
        dec = merge_snapshots(
            r.histogram_snapshot("serving_decode_token_seconds")
            for r in self.pool if r.name not in self._quarantined)
        qw = self._m_qwait.snapshot()
        return {
            "ttft_p50_s": percentile(ttft, 0.5),
            "ttft_p99_s": percentile(ttft, 0.99),
            "ttft_count": ttft["count"],
            "decode_token_p50_s": percentile(dec, 0.5),
            "decode_token_p99_s": percentile(dec, 0.99),
            "queue_wait_p50_s": percentile(qw, 0.5),
            "queue_wait_p99_s": percentile(qw, 0.99),
            "queue_wait_count": qw["count"],
            "prefix_hits": int(self._m_prefix_hits.value()),
            "prefix_partial_hits": int(self._m_prefix_partial.value()),
            "prefix_tokens_reused": int(self._m_tokens_reused.value()),
            "prefix_tokens_reused_fraction": (
                self._m_tokens_reused.value()
                / max(1.0, self._m_prompt_tokens.value())),
            "disagg_prefills": int(self._m_disagg.value()),
            # where the retained tail's time went (None with the
            # tracker disabled) — docs/SERVING.md's runbook entry point
            "attribution": (self._tracker.attribution()
                            if self._tracker is not None else None),
        }

    def queue_wait_snapshot(self) -> dict:
        """The router-level ``router_queue_wait_seconds`` histogram as
        a mergeable snapshot (``slo.percentile``-ready). The autoscaler
        scrapes this alongside per-replica TTFT so scale-out decisions
        see the queue-wait component TTFT cannot."""
        return self._m_qwait.snapshot()

    # -- lifecycle --
    def close(self, timeout: float = 10.0) -> None:
        """Stop the dispatcher and unregister the router health check.
        The pool is NOT closed (the owner that started it closes it)."""
        if self._closed:
            return
        self._closed = True
        self._pump_wake.set()
        self._pump_thread.join(timeout)
        self._health.unregister("serving_router")

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc):
        self.close()
        return False

"""Prefix/session KV-cache index: token prefix -> (replica, retained
KV snapshot).

Repeated system prompts are the serving workload's common case; without
an index every resubmission re-pays the full prefill. The router
captures a :class:`~bigdl_tpu.models.transformer.serving.KVSnapshot`
right after a prompt's first prefill (the batcher's ``on_prefill`` hook
fires before any decode write lands in the partial page, so the copy is
prefix-clean) and stores it here keyed by the token sequence. A later
request with the SAME prompt adopts the snapshot instead of prefilling
— the measured "prefill skip" (``serving_prefill_skips_total`` on the
adopting replica, ``router_prefix_hits_total`` at the router).

Entries remember the replica that produced them only as a STICKY
ROUTING PREFERENCE; the snapshot itself is a host-side copy, so a hit
can be adopted by any identically configured replica — which is what
lets prefix reuse survive a drain/rolling restart.

Correctness: the key is the exact token tuple and ``lookup`` verifies
it (dict hashing plus full equality), because adopting the wrong KV
would silently change outputs. Eviction is LRU with both an entry and a
byte budget (snapshots hold real page data).

HOST-ONLY CONTRACT: never imports jax (jaxlint JX5); snapshots are
numpy arrays produced by the batcher's packed export.
"""
from __future__ import annotations

import threading
from collections import OrderedDict

__all__ = ["PrefixCache", "PrefixEntry"]


class PrefixEntry:
    """One retained prefix: the snapshot plus its sticky-replica
    preference and hit count."""

    __slots__ = ("prompt", "replica", "snapshot", "hits")

    def __init__(self, prompt, replica, snapshot):
        self.prompt = tuple(prompt)
        self.replica = replica
        self.snapshot = snapshot
        self.hits = 0


class PrefixCache:
    """LRU map of token prefix -> :class:`PrefixEntry`.

    ``min_tokens`` gates what is worth retaining: short prompts
    re-prefill faster than their snapshot round-trips. ``max_bytes``
    bounds the host memory the retained KV may hold (oldest evicted
    first)."""

    def __init__(self, capacity: int = 64, min_tokens: int = 16,
                 max_bytes: int | None = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.min_tokens = int(min_tokens)
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, PrefixEntry] = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def lookup(self, prompt) -> PrefixEntry | None:
        """The entry for EXACTLY ``prompt``, refreshing its LRU
        position — or None."""
        key = tuple(prompt)
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            e.hits += 1
            self.hits += 1
            return e

    def put(self, prompt, replica, snapshot) -> bool:
        """Retain ``snapshot`` for ``prompt``; returns whether it was
        kept (prompts under ``min_tokens`` are not worth it). A repeat
        put refreshes the entry (latest snapshot/replica wins)."""
        key = tuple(prompt)
        if len(key) < self.min_tokens:
            return False
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.snapshot.nbytes
            e = PrefixEntry(key, replica, snapshot)
            self._entries[key] = e
            self._bytes += snapshot.nbytes
            while len(self._entries) > self.capacity or (
                    self.max_bytes is not None
                    and self._bytes > self.max_bytes
                    and len(self._entries) > 1):
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted.snapshot.nbytes
            return True

    def invalidate(self, prompt) -> bool:
        with self._lock:
            e = self._entries.pop(tuple(prompt), None)
            if e is not None:
                self._bytes -= e.snapshot.nbytes
            return e is not None

    def forget_replica(self, name) -> int:
        """Clear the sticky preference for a drained/retired replica.
        Snapshots stay valid (host copies) — only the routing hint is
        dropped. Returns how many entries pointed there."""
        n = 0
        with self._lock:
            for e in self._entries.values():
                if e.replica == name:
                    e.replica = None
                    n += 1
        return n

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "hits": self.hits, "misses": self.misses}

"""Fleet-global prefix index: token radix trie -> (replica, retained
KV snapshot), with longest-prefix matching at page granularity.

Repeated system prompts are the serving workload's common case; without
an index every resubmission re-pays the full prefill. The router
captures a :class:`~bigdl_tpu.models.transformer.serving.KVSnapshot`
right after a prompt's first prefill (the batcher's ``on_prefill`` hook
fires before any decode write lands in the partial page, so the copy is
prefix-clean) and stores it here keyed by the token sequence.

Two lookup contracts:

- ``lookup(prompt)`` — exact-equality, the original contract. A later
  request with the SAME prompt adopts the snapshot instead of
  prefilling (``serving_prefill_skips_total`` on the adopting replica,
  ``router_prefix_hits_total`` at the router).
- ``lookup_longest(prompt) -> (entry, matched_tokens)`` — the radix
  walk. A request sharing >= 1 full KV page with a cached entry gets
  that entry plus how many tokens matched; the router truncates the
  snapshot to the page boundary (``KVSnapshot.truncate``) and prefills
  only the suffix. Matching is PAGE-GRANULAR: the trie is keyed on
  ``page_size``-token blocks, because a partial page cannot be adopted
  (its tail slots would hold the wrong keys).

Insertion dedups shared prefixes: a put whose prompt extends an
existing entry supersedes it (the longer snapshot serves every lookup
the shorter one served, via truncation), and a put already covered by a
longer entry is skipped. ``store_int8=True`` keeps snapshots quantized
(symmetric per-vector int8, the ``parameters/compression.py`` codec
mirrored in numpy) — ~4x more prefixes per byte of budget — and
dequantizes on adopt.

Entries remember the replica that produced them only as a STICKY
ROUTING PREFERENCE; the snapshot itself is a host-side copy, so a hit
can be adopted by any identically configured replica — which is what
lets prefix reuse survive a drain/rolling restart.

Correctness: exact lookup verifies the full token tuple (dict hashing
plus equality); longest-prefix matches are only ever consumed through
page-boundary truncation, and the router re-verifies token equality of
the truncated prefix before adopting. Eviction is LRU with both an
entry and a byte budget; a single snapshot larger than the whole byte
budget is REJECTED at put (``prefix_cache_oversize_rejected_total``)
rather than retained forever.

HOST-ONLY CONTRACT: never imports jax (jaxlint JX5); snapshots are
numpy arrays produced by the batcher's packed export.
"""
from __future__ import annotations

import logging
import threading
from collections import OrderedDict

import numpy as np

__all__ = ["PrefixCache", "PrefixEntry"]

logger = logging.getLogger("bigdl_tpu.serving")

# Numpy mirror of parameters/compression.py int8_quantize/int8_dequantize
# (deterministic path): symmetric per-vector scale over the last axis
# with the same 1e-30 floor, so a cache-side round-trip is bit-identical
# to the device codec's.  np.round matches jnp.round (half-to-even).
_SCALE_FLOOR = 1e-30


def _q8_encode(a):
    a = np.ascontiguousarray(a, dtype=np.float32)
    scale = (np.max(np.abs(a), axis=-1) / 127.0 + _SCALE_FLOOR).astype(
        np.float32)
    q = np.clip(np.round(a / scale[..., None]), -127, 127).astype(np.int8)
    return q, scale


def _q8_decode(q, scale):
    return q.astype(np.float32) * scale[..., None]


class PrefixEntry:
    """One retained prefix: the snapshot (fp32, or int8 + scales when
    the cache stores quantized) plus its sticky-replica preference and
    hit count."""

    __slots__ = ("prompt", "replica", "hits", "nbytes",
                 "_snap", "_q8", "_meta", "_snap_cls")

    def __init__(self, prompt, replica, snapshot, *, store_int8=False):
        self.prompt = tuple(prompt)
        self.replica = replica
        self.hits = 0
        quantize = store_int8 and all(
            np.issubdtype(np.asarray(k).dtype, np.floating)
            and np.issubdtype(np.asarray(v).dtype, np.floating)
            for k, v in snapshot.kv)
        if quantize:
            self._snap = None
            # class ref, not an import: keeps this module jax-free
            # (constructing a KVSnapshot needs no jax either way).
            self._snap_cls = type(snapshot)
            self._q8 = [(_q8_encode(k) + _q8_encode(v))
                        for k, v in snapshot.kv]
            self._meta = {
                "prompt": tuple(snapshot.prompt),
                "n_cached": snapshot.n_cached,
                "last_token": snapshot.last_token,
                "emitted": list(snapshot.emitted),
                "page_size": snapshot.page_size,
                "weight_version": getattr(snapshot, "weight_version",
                                          None),
            }
            self.nbytes = sum(a.nbytes for layer in self._q8
                              for a in layer)
        else:
            self._snap = snapshot
            self._snap_cls = None
            self._q8 = None
            self._meta = None
            self.nbytes = snapshot.nbytes

    @property
    def quantized(self) -> bool:
        return self._q8 is not None

    @property
    def snapshot(self):
        """The adoptable snapshot (dequantized fresh per access when
        stored int8 — adopters may donate/truncate it)."""
        if self._q8 is None:
            return self._snap
        m = self._meta
        kv = [(_q8_decode(qk, sk), _q8_decode(qv, sv))
              for qk, sk, qv, sv in self._q8]
        return self._snap_cls(
            list(m["prompt"]), m["n_cached"], kv,
            last_token=m["last_token"], emitted=list(m["emitted"]),
            page_size=m["page_size"],
            weight_version=m["weight_version"])


class _RadixNode:
    """Trie node keyed on ``page_size``-token blocks. ``entries`` holds
    the entries whose prompt has exactly this many full blocks (their
    sub-page tail, if any, disambiguated by the full prompt key)."""

    __slots__ = ("children", "entries")

    def __init__(self):
        self.children: dict[tuple, _RadixNode] = {}
        self.entries: dict[tuple, PrefixEntry] = {}


class PrefixCache:
    """Radix-indexed LRU map of token prefix -> :class:`PrefixEntry`.

    ``min_tokens`` gates what is worth retaining: short prompts
    re-prefill faster than their snapshot round-trips. ``max_bytes``
    bounds the host memory the retained KV may hold (oldest evicted
    first; an entry alone exceeding the budget is rejected).
    ``page_size`` is the block width of the radix index — align it
    with the serving geometry's KV page size or partial matches floor
    to coarser boundaries than the batcher could adopt.
    ``longest_match=False`` restores exact-only behaviour
    (``lookup_longest`` degrades to ``lookup`` and puts neither dedup
    nor supersede)."""

    def __init__(self, capacity: int = 64, min_tokens: int = 16,
                 max_bytes: int | None = None, *, page_size: int = 16,
                 longest_match: bool = True, store_int8: bool = False,
                 registry=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.capacity = int(capacity)
        self.min_tokens = int(min_tokens)
        self.max_bytes = max_bytes
        self.page_size = int(page_size)
        self.longest_match = bool(longest_match)
        self.store_int8 = bool(store_int8)
        if registry is None:
            from bigdl_tpu.observability.registry import default_registry
            registry = default_registry()
        self._m_oversize = registry.counter(
            "prefix_cache_oversize_rejected_total",
            "puts rejected because a single snapshot exceeded the "
            "cache byte budget (previously retained forever)")
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, PrefixEntry] = OrderedDict()
        self._root = _RadixNode()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    # -- radix plumbing (all called under self._lock) --
    def _blocks(self, key: tuple) -> list:
        s = self.page_size
        return [key[i:i + s] for i in range(0, len(key) // s * s, s)]

    def _walk(self, key: tuple) -> list:
        """Nodes along ``key``'s full-block path, root first — stops at
        the first divergence."""
        path = [self._root]
        node = self._root
        for b in self._blocks(key):
            node = node.children.get(b)
            if node is None:
                break
            path.append(node)
        return path

    def _trie_insert(self, entry: PrefixEntry) -> None:
        node = self._root
        for b in self._blocks(entry.prompt):
            nxt = node.children.get(b)
            if nxt is None:
                nxt = node.children[b] = _RadixNode()
            node = nxt
        node.entries[entry.prompt] = entry

    def _trie_remove(self, entry: PrefixEntry) -> None:
        blocks = self._blocks(entry.prompt)
        path = [self._root]
        node = self._root
        for b in blocks:
            node = node.children.get(b)
            if node is None:      # never inserted (shouldn't happen)
                return
            path.append(node)
        node.entries.pop(entry.prompt, None)
        for i in range(len(path) - 1, 0, -1):
            n = path[i]
            if n.entries or n.children:
                break
            del path[i - 1].children[blocks[i - 1]]

    def _drop(self, entry: PrefixEntry) -> None:
        self._entries.pop(entry.prompt, None)
        self._bytes -= entry.nbytes
        self._trie_remove(entry)

    @staticmethod
    def _subtree_entry(node: _RadixNode) -> PrefixEntry | None:
        stack = [node]
        while stack:
            n = stack.pop()
            if n.entries:
                return next(iter(n.entries.values()))
            stack.extend(n.children.values())
        return None

    def _covering(self, key: tuple) -> PrefixEntry | None:
        """An entry whose prompt extends (or equals) ``key`` — i.e.
        ``key`` is already fully served by the index."""
        node = self._root
        for b in self._blocks(key):
            node = node.children.get(b)
            if node is None:
                return None
        tail = key[len(key) // self.page_size * self.page_size:]
        for e in node.entries.values():
            if len(e.prompt) >= len(key) and e.prompt[:len(key)] == key:
                return e
        stack = [c for blk, c in node.children.items()
                 if blk[:len(tail)] == tail]
        while stack:
            n = stack.pop()
            if n.entries:       # every entry below here starts with key
                return next(iter(n.entries.values()))
            stack.extend(n.children.values())
        return None

    # -- lookups --
    def lookup(self, prompt) -> PrefixEntry | None:
        """The entry for EXACTLY ``prompt``, refreshing its LRU
        position — or None. Counts a hit/miss."""
        key = tuple(prompt)
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            e.hits += 1
            self.hits += 1
            return e

    def lookup_longest(self, prompt) -> tuple:
        """``(entry, matched_tokens)`` for the longest page-aligned
        shared prefix — or ``(None, 0)``. An exact hit reports
        ``matched_tokens == len(prompt)``; a partial hit reports the
        full-page token count shared with the entry (always a multiple
        of ``page_size``, possibly less than the entry's own length —
        the caller truncates the snapshot to what it can use). Counts
        one hit or miss, like :meth:`lookup`."""
        key = tuple(prompt)
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
                e.hits += 1
                self.hits += 1
                return e, len(key)
            if not self.longest_match:
                self.misses += 1
                return None, 0
            path = self._walk(key)
            matched = (len(path) - 1) * self.page_size
            e = self._subtree_entry(path[-1]) if matched else None
            if e is None:
                self.misses += 1
                return None, 0
            self._entries.move_to_end(e.prompt)
            e.hits += 1
            self.hits += 1
            return e, matched

    def peek(self, prompt) -> PrefixEntry | None:
        """Non-counting presence probe: is ``prompt`` already served by
        the index (exactly, or covered by a longer entry)? No hit/miss
        accounting, no LRU reshuffle — the router's capture hook uses
        this so telemetry reflects only real dispatch traffic."""
        key = tuple(prompt)
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                return e
            if not self.longest_match:
                return None
            return self._covering(key)

    # -- mutation --
    def put(self, prompt, replica, snapshot) -> bool:
        """Retain ``snapshot`` for ``prompt``; returns whether it was
        kept. Prompts under ``min_tokens`` are not worth it; a snapshot
        alone exceeding ``max_bytes`` is rejected (counter + warning);
        a prompt already covered by a longer entry is deduped away. A
        repeat put refreshes the entry (latest snapshot/replica wins),
        and a put extending existing entries supersedes them."""
        key = tuple(prompt)
        if len(key) < self.min_tokens:
            return False
        entry = PrefixEntry(key, replica, snapshot,
                            store_int8=self.store_int8)
        if self.max_bytes is not None and entry.nbytes > self.max_bytes:
            self._m_oversize.inc()
            logger.warning(
                "prefix_cache: rejecting %d-token snapshot (%d bytes > "
                "max_bytes=%d) — it would evict the whole cache and "
                "still not fit", len(key), entry.nbytes, self.max_bytes)
            return False
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
                self._trie_remove(old)
            elif self.longest_match:
                cov = self._covering(key)
                if cov is not None:
                    # a longer entry already serves this prefix —
                    # refresh it instead of storing a duplicate
                    self._entries.move_to_end(cov.prompt)
                    cov.replica = replica
                    return False
            self._trie_insert(entry)
            self._entries[key] = entry
            self._bytes += entry.nbytes
            if self.longest_match:
                for n in self._walk(key):
                    for e in list(n.entries.values()):
                        if (len(e.prompt) < len(key)
                                and e.prompt == key[:len(e.prompt)]):
                            self._drop(e)   # superseded by this put
            while len(self._entries) > self.capacity or (
                    self.max_bytes is not None
                    and self._bytes > self.max_bytes
                    and len(self._entries) > 1):
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted.nbytes
                self._trie_remove(evicted)
            return True

    def invalidate(self, prompt) -> bool:
        with self._lock:
            e = self._entries.get(tuple(prompt))
            if e is not None:
                self._drop(e)
            return e is not None

    def forget_replica(self, name) -> int:
        """Clear the sticky preference for a drained/retired replica.
        Snapshots stay valid (host copies) — only the routing hint is
        dropped. Returns how many entries pointed there."""
        n = 0
        with self._lock:
            for e in self._entries.values():
                if e.replica == name:
                    e.replica = None
                    n += 1
        return n

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._root = _RadixNode()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "hits": self.hits, "misses": self.misses}

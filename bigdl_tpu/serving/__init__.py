"""bigdl_tpu.serving — the production serving plane.

The multi-replica front end over the continuous-batching stack
(``models/transformer/serving.py``): ROADMAP item 1, the gap between a
single ``ContinuousBatcher`` and a service (BigDL 2.0's end-to-end
pipeline-to-serving story, arXiv:2204.01715). Six modules:

- ``slo``           — :class:`SLOConfig` targets, :class:`ReplicaStats`,
  the admission predicate and histogram-percentile helpers.
- ``prefix_cache``  — :class:`PrefixCache`, the radix longest-prefix ->
  retained KV snapshot index (page-block granularity, optional int8
  storage) behind sticky routing, prefill skips and suffix-only
  prefills.
- ``replica_pool``  — :class:`Replica` / :class:`ReplicaPool`, N batcher
  step loops on daemon driver threads with per-replica registries and
  health checks.
- ``router``        — :class:`Router`, SLO-aware placement, prefix
  reuse, prefill/decode disaggregation, bounded overflow +
  :class:`RouterSaturated` load-shedding, and ``drain()`` for rolling
  restarts.
- ``autoscaler``    — :class:`Autoscaler`, the closed loop that adds
  (AOT-warm) and drains replicas from the live SLO signals; the pure
  :func:`decide` core is deterministic and test-table-driven.
- ``quantized``     — int8 serving: weights + KV page pool through the
  ``parameters/compression.py`` device codecs, shrinking per-replica
  HBM so one chip holds more replicas.

Quick start::

    pool = ReplicaPool(model, 2, max_batch=4, num_pages=128,
                       page_size=16, max_new_tokens=64)
    router = Router(pool, slo=SLOConfig(long_prefill_tokens=512))
    router.submit("req-0", prompt_tokens)
    router.wait_all()
    results = dict(router.finished())
    router.close(); pool.close()

HOST-ONLY CONTRACT: nothing in this package imports jax at module top
level (jaxlint JX5) — the router is host orchestration; all device
work happens inside the batchers it drives. docs/SERVING.md covers
architecture, SLO knobs, and the drain/rolling-restart runbook.
"""
from bigdl_tpu.serving.autoscaler import (Autoscaler, AutoscalerConfig,
                                          Decision, FleetView, decide)
from bigdl_tpu.serving.prefix_cache import PrefixCache, PrefixEntry
from bigdl_tpu.serving.replica_pool import (ACTIVE, DRAINING, STOPPED,
                                            Replica, ReplicaPool)
from bigdl_tpu.serving.router import Router, RouterSaturated
from bigdl_tpu.serving.slo import (ReplicaStats, SLOConfig, admissible,
                                   load_score, merge_snapshots,
                                   percentile)

__all__ = ["SLOConfig", "ReplicaStats", "admissible", "load_score",
           "percentile", "merge_snapshots", "PrefixCache",
           "PrefixEntry", "Replica", "ReplicaPool", "ACTIVE",
           "DRAINING", "STOPPED", "Router", "RouterSaturated",
           "Autoscaler", "AutoscalerConfig", "Decision", "FleetView",
           "decide"]

"""SLO model for the serving router: targets, live replica stats, and
the admission predicate.

The router's placement decisions are driven by three signal families
the batchers already export (PR 1/4 observability): live queue depth /
slot occupancy / KV-page utilization (read directly off host state),
and OBSERVED latency percentiles (TTFT, per-token decode) estimated
from the per-replica registry histograms. :class:`SLOConfig` names the
knobs; :func:`admissible` turns one replica's :class:`ReplicaStats`
into an admit/defer verdict with a human-readable reason (the same
string surfaces in /readyz details and router logs).

Percentiles come from cumulative histogram snapshots
(``Histogram.snapshot()``), so an estimate is the smallest bucket upper
bound covering the requested quantile — conservative (never
under-reports) and mergeable across replicas by summing bucket counts
(:func:`merge_snapshots`, used by the bench serving rows for
fleet-wide p50/p99).

HOST-ONLY CONTRACT: never imports jax (jaxlint JX5) — pure arithmetic
over host state.
"""
from __future__ import annotations

import dataclasses
import math

__all__ = ["SLOConfig", "ReplicaStats", "percentile", "merge_snapshots",
           "admissible", "load_score"]


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Serving objectives + admission limits.

    - ``ttft_p99_s`` / ``decode_token_p99_s``: latency targets; a
      replica whose OBSERVED p99 exceeds the target while it has a
      backlog stops admitting (it is already failing its SLO — sending
      more work makes every queued request later).
    - ``max_queue_depth``: per-replica bound on requests waiting for a
      slot.
    - ``max_kv_utilization``: fraction of the KV page pool in use past
      which a replica stops admitting (head-of-line admission would
      stall behind page pressure anyway).
    - ``long_prefill_tokens``: prompts at or past this length are
      disaggregated — prefilled on a designated/low-load replica and
      handed to a decode replica as a KV snapshot.
    - ``max_pending``: router-level overflow queue bound; past it
      ``submit`` raises ``RouterSaturated`` (load-shedding, not
      unbounded buffering).
    """

    ttft_p99_s: float = 2.0
    decode_token_p99_s: float = 1.0
    max_queue_depth: int = 8
    max_kv_utilization: float = 0.95
    long_prefill_tokens: int = 256
    max_pending: int = 1024

    def __post_init__(self):
        if self.ttft_p99_s <= 0 or self.decode_token_p99_s <= 0:
            raise ValueError("latency targets must be positive")
        if self.max_queue_depth < 0 or self.max_pending < 0:
            raise ValueError("queue bounds must be >= 0")
        if not 0.0 < self.max_kv_utilization <= 1.0:
            raise ValueError(
                f"max_kv_utilization must be in (0, 1], got "
                f"{self.max_kv_utilization}")
        if self.long_prefill_tokens < 1:
            raise ValueError("long_prefill_tokens must be >= 1")


@dataclasses.dataclass
class ReplicaStats:
    """One replica's live load + observed latency, as the router sees
    it (``Replica.stats()``). Latency fields are ``None`` until the
    replica has observations."""

    name: str
    state: str
    queue_depth: int
    active_slots: int
    free_slots: int
    pages_free: int
    kv_utilization: float
    ttft_p50: float | None = None
    ttft_p99: float | None = None
    decode_token_p99: float | None = None
    prefill_skips: int = 0


def _sorted_bounds(buckets: dict) -> list[tuple[float, str]]:
    """Numerically sorted ``(value, original_key)`` bucket boundaries.
    Unparseable keys are dropped rather than crashing a scrape — a
    half-written exposition from a replica mid-drain must never take
    the autoscaler's decision loop down with it."""
    bounds = []
    for le in buckets:
        try:
            bounds.append((float(le), le))
        except (TypeError, ValueError):
            continue
    bounds.sort(key=lambda bv: bv[0])
    return bounds


def percentile(snapshot: dict, q: float) -> float | None:
    """Quantile ``q`` in (0, 1] from a cumulative histogram snapshot
    (``{"buckets": {le: cumulative_count}, "count": n}``). Returns the
    smallest bucket upper bound covering the quantile — a conservative
    (never-under) estimate; ``None`` with no observations. Tolerates
    ``None``/empty/partially-garbled snapshots (a replica drained
    mid-scrape) by treating them as empty."""
    if not 0.0 < q <= 1.0:
        raise ValueError(f"q must be in (0, 1], got {q}")
    if not snapshot:
        return None
    try:
        n = int(snapshot.get("count", 0))
    except (TypeError, ValueError):
        return None
    if n <= 0:
        return None
    need = math.ceil(q * n)
    buckets = snapshot.get("buckets") or {}
    # numeric boundary order, NOT dict insertion order: merged or
    # hand-built snapshots may interleave boundaries
    for val, le in _sorted_bounds(buckets):
        try:
            if int(buckets[le]) >= need:
                return val
        except (TypeError, ValueError):
            continue
    return math.inf


def merge_snapshots(snapshots) -> dict:
    """Merge cumulative histogram snapshots into one fleet-wide
    cumulative snapshot, so :func:`percentile` applies directly.

    Snapshots from one metric name across replica registries share
    boundaries, and for those this is a plain per-bucket sum. But the
    autoscaler merges whatever the scrape returned — a replica drained
    mid-decision may contribute an empty dict, ``None``, or (across
    versions) different boundaries. The merge therefore re-evaluates
    each snapshot's cumulative count at the UNION of all boundaries:
    the count at boundary ``x`` is the snapshot's count at its largest
    own boundary ``<= x`` (a lower bound on the true cumulative count,
    keeping the percentile estimate conservative — never under)."""
    merged: dict = {"buckets": {}, "sum": 0.0, "count": 0}
    per_snap: list[tuple[list[tuple[float, str]], dict, int]] = []
    union: dict[float, str] = {}
    for s in snapshots or ():
        if not s:
            continue
        try:
            merged["sum"] += float(s.get("sum", 0.0))
        except (TypeError, ValueError):
            pass
        try:
            count = int(s.get("count", 0))
        except (TypeError, ValueError):
            count = 0
        merged["count"] += max(count, 0)
        buckets = s.get("buckets") or {}
        bounds = _sorted_bounds(buckets)
        if not bounds and count > 0:
            # count but no usable buckets: everything lands at +Inf so
            # the total stays covered (percentile degrades to inf
            # rather than silently dropping observations)
            bounds, buckets = [(math.inf, "+Inf")], {"+Inf": count}
        for val, le in bounds:
            union.setdefault(val, le)
        if bounds:
            per_snap.append((bounds, buckets, max(count, 0)))
    for val in sorted(union):
        total = 0
        for bounds, buckets, count in per_snap:
            cum = 0
            for bval, ble in bounds:
                if bval > val:
                    break
                try:
                    cum = max(cum, int(buckets[ble]))
                except (TypeError, ValueError):
                    continue
            total += min(cum, count) if count else cum
        merged["buckets"][union[val]] = total
    if merged["count"] and merged["buckets"]:
        # the top boundary must cover every merged observation
        top = union[max(union)]
        if math.isinf(max(union)):
            merged["buckets"][top] = max(merged["buckets"][top],
                                         merged["count"])
    return merged


def admissible(stats: ReplicaStats, slo: SLOConfig) -> tuple[bool, str]:
    """Should the router hand ``stats``'s replica one more request?

    Gates, in order: replica lifecycle state; queue depth; KV page
    pressure; then the latency SLOs — which only bite while the
    replica has a backlog (``queue_depth > 0``): an idle replica whose
    historical p99 is poor is still the fastest path for the next
    request."""
    if stats.state != "active":
        return False, f"replica is {stats.state}"
    if stats.queue_depth >= slo.max_queue_depth:
        return False, (f"queue full ({stats.queue_depth} >= "
                       f"{slo.max_queue_depth})")
    if stats.kv_utilization >= slo.max_kv_utilization:
        return False, (f"KV pool at {stats.kv_utilization:.0%} >= "
                       f"{slo.max_kv_utilization:.0%}")
    if stats.queue_depth > 0:
        if stats.ttft_p99 is not None and stats.ttft_p99 > slo.ttft_p99_s:
            return False, (f"observed TTFT p99 {stats.ttft_p99:.3g}s "
                           f"over the {slo.ttft_p99_s:.3g}s SLO with a "
                           "backlog")
        if (stats.decode_token_p99 is not None
                and stats.decode_token_p99 > slo.decode_token_p99_s):
            return False, (f"observed decode p99 "
                           f"{stats.decode_token_p99:.3g}s/token over "
                           f"the {slo.decode_token_p99_s:.3g}s SLO "
                           "with a backlog")
    return True, "admitting"


def load_score(stats: ReplicaStats) -> tuple:
    """Ranking key for placement among admissible replicas: fewest
    waiting+running requests, then lowest KV pressure, then name (a
    deterministic tiebreak keeps tests and reruns stable)."""
    return (stats.queue_depth + stats.active_slots,
            stats.kv_utilization, stats.name)

"""SLO model for the serving router: targets, live replica stats, and
the admission predicate.

The router's placement decisions are driven by three signal families
the batchers already export (PR 1/4 observability): live queue depth /
slot occupancy / KV-page utilization (read directly off host state),
and OBSERVED latency percentiles (TTFT, per-token decode) estimated
from the per-replica registry histograms. :class:`SLOConfig` names the
knobs; :func:`admissible` turns one replica's :class:`ReplicaStats`
into an admit/defer verdict with a human-readable reason (the same
string surfaces in /readyz details and router logs).

Percentiles come from cumulative histogram snapshots
(``Histogram.snapshot()``), so an estimate is the smallest bucket upper
bound covering the requested quantile — conservative (never
under-reports) and mergeable across replicas by summing bucket counts
(:func:`merge_snapshots`, used by the bench serving rows for
fleet-wide p50/p99).

HOST-ONLY CONTRACT: never imports jax (jaxlint JX5) — pure arithmetic
over host state.
"""
from __future__ import annotations

import dataclasses
import math

__all__ = ["SLOConfig", "ReplicaStats", "percentile", "merge_snapshots",
           "admissible", "load_score"]


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Serving objectives + admission limits.

    - ``ttft_p99_s`` / ``decode_token_p99_s``: latency targets; a
      replica whose OBSERVED p99 exceeds the target while it has a
      backlog stops admitting (it is already failing its SLO — sending
      more work makes every queued request later).
    - ``max_queue_depth``: per-replica bound on requests waiting for a
      slot.
    - ``max_kv_utilization``: fraction of the KV page pool in use past
      which a replica stops admitting (head-of-line admission would
      stall behind page pressure anyway).
    - ``long_prefill_tokens``: prompts at or past this length are
      disaggregated — prefilled on a designated/low-load replica and
      handed to a decode replica as a KV snapshot.
    - ``max_pending``: router-level overflow queue bound; past it
      ``submit`` raises ``RouterSaturated`` (load-shedding, not
      unbounded buffering).
    """

    ttft_p99_s: float = 2.0
    decode_token_p99_s: float = 1.0
    max_queue_depth: int = 8
    max_kv_utilization: float = 0.95
    long_prefill_tokens: int = 256
    max_pending: int = 1024

    def __post_init__(self):
        if self.ttft_p99_s <= 0 or self.decode_token_p99_s <= 0:
            raise ValueError("latency targets must be positive")
        if self.max_queue_depth < 0 or self.max_pending < 0:
            raise ValueError("queue bounds must be >= 0")
        if not 0.0 < self.max_kv_utilization <= 1.0:
            raise ValueError(
                f"max_kv_utilization must be in (0, 1], got "
                f"{self.max_kv_utilization}")
        if self.long_prefill_tokens < 1:
            raise ValueError("long_prefill_tokens must be >= 1")


@dataclasses.dataclass
class ReplicaStats:
    """One replica's live load + observed latency, as the router sees
    it (``Replica.stats()``). Latency fields are ``None`` until the
    replica has observations."""

    name: str
    state: str
    queue_depth: int
    active_slots: int
    free_slots: int
    pages_free: int
    kv_utilization: float
    ttft_p50: float | None = None
    ttft_p99: float | None = None
    decode_token_p99: float | None = None
    prefill_skips: int = 0


def percentile(snapshot: dict, q: float) -> float | None:
    """Quantile ``q`` in (0, 1] from a cumulative histogram snapshot
    (``{"buckets": {le: cumulative_count}, "count": n}``). Returns the
    smallest bucket upper bound covering the quantile — a conservative
    (never-under) estimate; ``None`` with no observations."""
    if not 0.0 < q <= 1.0:
        raise ValueError(f"q must be in (0, 1], got {q}")
    n = int(snapshot.get("count", 0))
    if n == 0:
        return None
    need = math.ceil(q * n)
    for le, cum in snapshot.get("buckets", {}).items():
        if cum >= need:
            return float(le)
    return math.inf


def merge_snapshots(snapshots) -> dict:
    """Sum cumulative histogram snapshots taken from IDENTICAL bucket
    boundaries (true for any one metric name across replica
    registries). The merge of cumulative counts is cumulative again, so
    :func:`percentile` applies directly — fleet-wide p50/p99."""
    out: dict = {"buckets": {}, "sum": 0.0, "count": 0}
    for s in snapshots:
        out["sum"] += float(s.get("sum", 0.0))
        out["count"] += int(s.get("count", 0))
        for le, cum in s.get("buckets", {}).items():
            out["buckets"][le] = out["buckets"].get(le, 0) + cum
    return out


def admissible(stats: ReplicaStats, slo: SLOConfig) -> tuple[bool, str]:
    """Should the router hand ``stats``'s replica one more request?

    Gates, in order: replica lifecycle state; queue depth; KV page
    pressure; then the latency SLOs — which only bite while the
    replica has a backlog (``queue_depth > 0``): an idle replica whose
    historical p99 is poor is still the fastest path for the next
    request."""
    if stats.state != "active":
        return False, f"replica is {stats.state}"
    if stats.queue_depth >= slo.max_queue_depth:
        return False, (f"queue full ({stats.queue_depth} >= "
                       f"{slo.max_queue_depth})")
    if stats.kv_utilization >= slo.max_kv_utilization:
        return False, (f"KV pool at {stats.kv_utilization:.0%} >= "
                       f"{slo.max_kv_utilization:.0%}")
    if stats.queue_depth > 0:
        if stats.ttft_p99 is not None and stats.ttft_p99 > slo.ttft_p99_s:
            return False, (f"observed TTFT p99 {stats.ttft_p99:.3g}s "
                           f"over the {slo.ttft_p99_s:.3g}s SLO with a "
                           "backlog")
        if (stats.decode_token_p99 is not None
                and stats.decode_token_p99 > slo.decode_token_p99_s):
            return False, (f"observed decode p99 "
                           f"{stats.decode_token_p99:.3g}s/token over "
                           f"the {slo.decode_token_p99_s:.3g}s SLO "
                           "with a backlog")
    return True, "admitting"


def load_score(stats: ReplicaStats) -> tuple:
    """Ranking key for placement among admissible replicas: fewest
    waiting+running requests, then lowest KV pressure, then name (a
    deterministic tiebreak keeps tests and reruns stable)."""
    return (stats.queue_depth + stats.active_slots,
            stats.kv_utilization, stats.name)

"""int8 quantized serving: weights + KV page pool at rest in one byte.

The autoscaler (``serving/autoscaler.py``) makes fleet size follow
load; this module shrinks what each replica costs, so one chip holds
more of them (ROADMAP item 3). Both halves ride the device codecs the
sharded-update wire already trusts
(``parameters/compression.py::int8_quantize`` — symmetric, last-axis
scale):

- **weights**: every float parameter leaf with ``ndim >= 2`` becomes
  ``{"q": int8, "s": f32 scale}`` (:func:`quantize_params`) — 4 bytes
  -> 1 + 4/k per element. LayerNorm gains/biases and other 1-D leaves
  stay f32 (they are tiny and precision-critical).
- **KV page pool**: :class:`QuantizedKVCache` holds the paged k/v
  pools as int8 with a per-(page, slot, kv_head) scale — the pool a
  replica parks between bursts drops ~4x.

Composition with the paged decode path is by DEQUANTIZE-THEN-COMPUTE
inside the compiled step: :func:`paged_decode_q8` /
:func:`paged_prefill_q8` take the quantized state as the executable's
*arguments* (that is what sits in HBM at rest and what the static
accounting counts), dequantize in-kernel, run the exact fp32 step —
including the Pallas paged-attention kernel; ``paged_kernel=`` is
honored unchanged — and re-quantize the updated pools before
returning. The dequantized copies are per-step temporaries the
compiler recycles; the at-rest footprint is the int8 state
(documented trade-off: this composes with any attention kernel at the
cost of transient dequantized pages in the step's working set).

Parity: quantization error is bounded by the codec (scale = amax/127
per row), and the dense and interpret-mode paged paths see IDENTICAL
quantized inputs — tests pin int8-dense == int8-interpret exactly,
and int8 vs fp32 within a documented tolerance
(tests/test_quantized_serving.py). Receipt: the ``int8`` section of
the ``serving_decode_hbm_bytes`` bench row — static byte accounting
of the decode step's resident weight+KV arguments, >= 3x smaller.

HOST-ONLY CONTRACT at import time (jaxlint JX5): jax only inside
functions.
"""
from __future__ import annotations

import functools

import numpy as np

__all__ = ["is_quantized_leaf", "quantize_params", "dequantize_params",
           "QuantizedKVCache", "paged_prefill_q8", "paged_decode_q8",
           "quantized_byte_report"]

_QKEYS = frozenset({"q", "s"})


def is_quantized_leaf(node) -> bool:
    """True for the ``{"q": int8, "s": scale}`` dicts this module puts
    in parameter/pool pytrees."""
    return isinstance(node, dict) and set(node) == _QKEYS


def quantize_params(params, *, min_ndim: int = 2):
    """f32 parameter leaves with ``ndim >= min_ndim`` ->
    ``{"q": int8, "s": scale}`` (codec: symmetric last-axis
    ``int8_quantize``). Smaller/integer leaves pass through untouched;
    :func:`dequantize_params` inverts the structure.

    Idempotence guard: a tree that ALREADY holds quantized leaves is
    rejected loudly. Re-quantizing would treat the int8 codes as
    floats and corrupt the weights silently — an easy foot-gun on the
    weight publisher's reload path, where a checkpoint may have been
    converted once already."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.parameters.compression import int8_quantize

    def one(leaf):
        if is_quantized_leaf(leaf):
            raise ValueError(
                "params are already int8-quantized (found a "
                "{'q', 's'} leaf) — quantizing twice would re-encode "
                "the int8 codes as floats and silently corrupt the "
                "weights; dequantize_params first if a re-quantize is "
                "really intended")
        x = jnp.asarray(leaf)
        if x.ndim < min_ndim or not jnp.issubdtype(x.dtype,
                                                   jnp.floating):
            return leaf
        q, s = int8_quantize(x.astype(jnp.float32))
        return {"q": q, "s": s}

    return jax.tree_util.tree_map(one, params,
                                  is_leaf=is_quantized_leaf)


def dequantize_params(qparams):
    """Invert :func:`quantize_params` (jit-traceable — this is the
    in-kernel half of dequantize-then-compute)."""
    import jax

    from bigdl_tpu.parameters.compression import int8_dequantize

    def one(node):
        if is_quantized_leaf(node):
            return int8_dequantize(node["q"], node["s"])
        return node

    return jax.tree_util.tree_map(one, qparams,
                                  is_leaf=is_quantized_leaf)


def _quantize_pools(pools):
    from bigdl_tpu.parameters.compression import int8_quantize
    out = []
    for p in pools:
        q, s = int8_quantize(p)
        out.append({"q": q, "s": s})
    return tuple(out)


def _dequantize_pools(qpools, dtype):
    from bigdl_tpu.parameters.compression import int8_dequantize
    return tuple(int8_dequantize(e["q"], e["s"]).astype(dtype)
                 for e in qpools)


class QuantizedKVCache:
    """int8-at-rest paged KV state over a
    :class:`~bigdl_tpu.models.transformer.serving.PagedKVCache`'s
    geometry.

    Built from an existing cache (adopting geometry, page allocator,
    and — quantizing — its current pool contents). ``qkp``/``qvp`` are
    per-layer ``{"q": (pages, S, KV, D) int8, "s": (pages, S, KV) f32}``
    dicts: one scale per page-slot per kv head, so a page's rows
    quantize independently and page migration stays local.
    ``alloc``/``free``/``pages_free`` delegate to the host-side
    allocator of the source cache (one allocator, whichever
    representation the pages live in)."""

    def __init__(self, cache):
        self._cache = cache
        self.num_pages, self.page_size = cache.num_pages, cache.page_size
        self.kv_heads, self.head_dim = cache.kv_heads, cache.head_dim
        self.num_layers = cache.num_layers
        self.dtype = cache.kp[0].dtype
        self.qkp = _quantize_pools(cache.kp)
        self.qvp = _quantize_pools(cache.vp)

    def alloc(self, n_tokens: int):
        return self._cache.alloc(n_tokens)

    def free(self, pages) -> None:
        self._cache.free(pages)

    @property
    def pages_free(self) -> int:
        return self._cache.pages_free

    def dequantize_into(self, cache=None):
        """Materialize float pools back into ``cache`` (default: the
        source cache) — the exit ramp to the fp32 serving path."""
        cache = cache if cache is not None else self._cache
        cache.kp = _dequantize_pools(self.qkp, self.dtype)
        cache.vp = _dequantize_pools(self.qvp, self.dtype)
        return cache

    @property
    def nbytes(self) -> int:
        return int(sum(e["q"].size * e["q"].dtype.itemsize
                       + e["s"].size * e["s"].dtype.itemsize
                       for e in (*self.qkp, *self.qvp)))


def _q8_impls():
    """The jitted q8 step impls, built lazily (module stays jax-free at
    import). Both take the QUANTIZED state as arguments — what HBM
    holds between steps — dequantize in-kernel, run the exact fp32
    paged step (``__wrapped__``: the un-jitted body, traced inline so
    no nested-jit donation), and re-quantize the updated pools."""
    import jax

    from bigdl_tpu.models.transformer.serving import (
        _paged_decode_impl, _paged_prefill_impl)

    @functools.partial(jax.jit, donate_argnums=(1, 2), static_argnames=(
        "num_layers", "num_heads", "page_size", "policy_key", "rope",
        "num_kv_heads", "paged_kernel", "pool_dtype"))
    def prefill_q8(qparams, qkp, qvp, table, prompt, lengths, *,
                   pool_dtype, **statics):
        params = dequantize_params(qparams)
        kp = _dequantize_pools(qkp, pool_dtype)
        vp = _dequantize_pools(qvp, pool_dtype)
        first, kp, vp = _paged_prefill_impl.__wrapped__(
            params, kp, vp, table, prompt, lengths, **statics)
        return first, _quantize_pools(kp), _quantize_pools(vp)

    @functools.partial(jax.jit, donate_argnums=(1, 2), static_argnames=(
        "num_layers", "num_heads", "n_new", "page_size", "temperature",
        "top_k", "policy_key", "rope", "num_kv_heads", "paged_kernel",
        "pool_dtype"))
    def decode_q8(qparams, qkp, qvp, table, lengths, tok0, rng, *,
                  pool_dtype, **statics):
        params = dequantize_params(qparams)
        kp = _dequantize_pools(qkp, pool_dtype)
        vp = _dequantize_pools(qvp, pool_dtype)
        toks, kp, vp, lengths = _paged_decode_impl.__wrapped__(
            params, kp, vp, table, lengths, tok0, rng, **statics)
        return (toks, _quantize_pools(kp), _quantize_pools(vp),
                lengths)

    return prefill_q8, decode_q8


@functools.lru_cache(maxsize=1)
def _impls_cached():
    return _q8_impls()


def _statics(model, qcache, *, paged_kernel):
    from bigdl_tpu.models.transformer.serving import (
        _pool_kernel_supported, _resolve_paged_kernel)
    from bigdl_tpu.tensor import activation_dtype, compute_dtype
    meta = model.lm_meta
    kernel = _resolve_paged_kernel(
        paged_kernel, lambda: _pool_kernel_supported(qcache))
    return dict(
        num_layers=meta["num_layers"], num_heads=meta["num_heads"],
        page_size=qcache.page_size,
        policy_key=(str(activation_dtype()), str(compute_dtype())),
        rope=meta.get("pos_encoding", "learned") == "rope",
        num_kv_heads=meta.get("num_kv_heads"), paged_kernel=kernel,
        pool_dtype=str(np.dtype(qcache.dtype)))


def paged_prefill_q8(model, qparams, qcache: QuantizedKVCache, table,
                     prompts, *, lengths=None, paged_kernel=None):
    """:func:`~bigdl_tpu.models.transformer.serving.paged_prefill` over
    int8 state: prompts prefill INTO the quantized pool (write-path
    quantization happens in-kernel after the fp32 step). Returns
    (greedy first tokens (B,), lengths (B,)); ``qcache`` pools are
    rebound."""
    import jax.numpy as jnp
    if lengths is None:
        lengths = np.asarray([len(p) for p in prompts], np.int32)
        pmax = int(lengths.max())
        batch = np.ones((len(prompts), pmax), np.int32)
        for i, p in enumerate(prompts):
            batch[i, :len(p)] = np.asarray(p, np.int32)
    else:
        batch = np.asarray(prompts, np.int32)
        lengths = np.asarray(lengths, np.int32)
    prefill_q8, _ = _impls_cached()
    statics = _statics(model, qcache, paged_kernel=paged_kernel)
    first, qkp, qvp = prefill_q8(
        qparams, qcache.qkp, qcache.qvp, jnp.asarray(table, jnp.int32),
        jnp.asarray(batch), jnp.asarray(lengths), **statics)
    qcache.qkp, qcache.qvp = qkp, qvp
    return first, lengths


def paged_decode_q8(model, qparams, qcache: QuantizedKVCache, table,
                    lengths, last_tokens, n_new: int, *, config=None,
                    rng=None, paged_kernel=None):
    """:func:`~bigdl_tpu.models.transformer.serving.paged_decode` over
    int8 state. Returns (tokens (B, n_new), updated lengths);
    ``qcache`` pools are rebound (functional update, donated)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer.generate import GenerationConfig
    config = config or GenerationConfig(max_new_tokens=n_new)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    _, decode_q8 = _impls_cached()
    statics = _statics(model, qcache, paged_kernel=paged_kernel)
    statics.update(n_new=n_new, temperature=config.temperature,
                   top_k=config.top_k)
    toks, qkp, qvp, new_len = decode_q8(
        qparams, qcache.qkp, qcache.qvp, jnp.asarray(table, jnp.int32),
        jnp.asarray(lengths, jnp.int32),
        jnp.asarray(last_tokens, jnp.int32), rng, **statics)
    qcache.qkp, qcache.qvp = qkp, qvp
    return toks, new_len


def _leaf_bytes(tree) -> int:
    import jax
    return int(sum(np.prod(x.shape) * np.dtype(x.dtype).itemsize
                   for x in jax.tree_util.tree_leaves(tree)))


def quantized_byte_report(model, cache) -> dict:
    """Static accounting of the decode step's RESIDENT weight + KV
    arguments, fp32 vs int8 (the ``serving_decode_hbm_bytes`` int8
    receipt — no execution, pure shape arithmetic over the actual
    quantized pytrees)."""
    qparams = quantize_params(model.params)
    qcache = QuantizedKVCache(cache)
    w_fp32 = _leaf_bytes(model.params)
    w_int8 = _leaf_bytes(qparams)
    kv_fp32 = _leaf_bytes((cache.kp, cache.vp))
    kv_int8 = qcache.nbytes
    return {
        "weight_bytes_fp32": w_fp32, "weight_bytes_int8": w_int8,
        "kv_pool_bytes_fp32": kv_fp32, "kv_pool_bytes_int8": kv_int8,
        "weight_kv_bytes_fp32": w_fp32 + kv_fp32,
        "weight_kv_bytes_int8": w_int8 + kv_int8,
        "reduction": (w_fp32 + kv_fp32) / max(w_int8 + kv_int8, 1),
    }

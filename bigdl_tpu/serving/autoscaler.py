"""Closed-loop fleet autoscaler: capacity that follows load.

The router (PR 6) made the serving plane SLO-aware but fixed-N; this
module closes the loop (ROADMAP item 3, BigDL 2.0's
laptop-to-cluster elasticity story, arXiv:2204.01715). An
:class:`Autoscaler` watches the signals the replicas already export —
TTFT / per-token decode p99 (via :func:`slo.merge_snapshots` over the
per-replica histograms), router pending-queue depth, and KV-page
utilization — and

- **scales up** (``pool.add_replica`` + ``router.attach_replica``)
  when a fleet percentile breaches the :class:`SLOConfig` target, the
  pending queue outgrows the fleet, or KV pages run out. Spin-up is
  cheap because the pool's shared AOT pipeline
  (``ReplicaPool(aot_cache=...)``) means the Nth replica of identical
  geometry compiles nothing — executables load from the in-process
  table or the persistent cache;
- **scales down** through the existing ``router.drain(name,
  migrate=True)`` path — queued work re-dispatches and in-flight
  sequences migrate bitwise, so conservation (every accepted request
  completes exactly once) holds across scale events by construction —
  but only after a *sustained* low-load window (``hysteresis_evals``
  consecutive quiet evaluations), never below ``min_replicas``;
- **holds** during cooldown windows after any scale event, so one
  spike produces one measured response instead of oscillation.

Latency percentiles are evaluated over WINDOWED deltas: cumulative
histograms never decrease, so a fleet that was slow once would
otherwise breach its p99 forever and scale up without bound. Each
evaluation subtracts the per-replica snapshot taken at the previous
evaluation, giving "p99 over the last window" — breaches clear when
the fleet recovers.

The decision core is the pure function :func:`decide` over a frozen
:class:`FleetView` — deterministic, no I/O, no clocks — which is what
the tier-1 table tests drive with synthetic histograms (no drivers, no
sleeps). :class:`Autoscaler` is the shell: scrape, decide, apply,
observe (``autoscaler_*`` gauges/counters, ``autoscale`` trace
instants, flight-recorder decision events, bounded decision log).

HOST-ONLY CONTRACT: never imports jax (jaxlint JX5) — decisions are
pure arithmetic over scraped host state; the heavy lifting rides the
pool/router primitives.
"""
from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import deque

from bigdl_tpu.observability import trace
from bigdl_tpu.observability.registry import default_registry
from bigdl_tpu.serving.slo import (SLOConfig, load_score,
                                   merge_snapshots, percentile)

__all__ = ["AutoscalerConfig", "FleetView", "Decision", "decide",
           "Autoscaler"]


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    """Scaling policy knobs (SLO *targets* live in :class:`SLOConfig`;
    this is how aggressively the fleet chases them).

    - ``min_replicas`` / ``max_replicas``: hard fleet-size bounds; the
      autoscaler never acts outside them.
    - ``scale_step``: replicas added per scale-up decision.
    - ``pending_per_replica``: router pending-queue depth tolerated per
      live replica before the backlog itself is a breach (the queue is
      demand the fleet failed to absorb — it breaches before p99
      does).
    - ``low_load_utilization``: slot-occupancy fraction at or below
      which an evaluation counts as "quiet".
    - ``hysteresis_evals``: consecutive quiet evaluations required
      before a scale-down — one idle tick between bursts must not cost
      a replica.
    - ``cooldown_evals``: evaluations to hold after any scale event,
      letting the new fleet shape show up in the windowed percentiles
      before the next decision.
    - ``interval_s``: background-loop period (``Autoscaler.start``).
    """

    min_replicas: int = 1
    max_replicas: int = 8
    scale_step: int = 1
    pending_per_replica: int = 4
    low_load_utilization: float = 0.25
    hysteresis_evals: int = 3
    cooldown_evals: int = 2
    interval_s: float = 0.25

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) < min_replicas "
                f"({self.min_replicas})")
        if self.scale_step < 1:
            raise ValueError("scale_step must be >= 1")
        if self.pending_per_replica < 1:
            raise ValueError("pending_per_replica must be >= 1")
        if not 0.0 <= self.low_load_utilization <= 1.0:
            raise ValueError("low_load_utilization must be in [0, 1]")
        if self.hysteresis_evals < 1 or self.cooldown_evals < 0:
            raise ValueError("hysteresis_evals >= 1, cooldown_evals "
                             ">= 0 required")
        if self.interval_s <= 0:
            raise ValueError("interval_s must be positive")


@dataclasses.dataclass(frozen=True)
class FleetView:
    """One evaluation's frozen inputs: per-replica
    :class:`~bigdl_tpu.serving.slo.ReplicaStats`, the fleet-merged
    TTFT / decode-token histogram snapshots for the window (already
    windowed deltas when the :class:`Autoscaler` built them), the
    router's pending-queue depth, and — when the router exposes it —
    the windowed ``router_queue_wait_seconds`` snapshot (the TTFT
    component the per-replica clocks cannot see)."""

    replicas: tuple
    ttft: dict
    decode: dict
    pending: int = 0
    queue_wait: dict | None = None


@dataclasses.dataclass(frozen=True)
class Decision:
    """One evaluation's verdict. ``action`` is ``"up"``, ``"down"`` or
    ``"hold"``; ``target`` is the fleet size the action aims for (==
    ``n_live`` on hold); ``low_streak``/``cooldown`` are the NEXT
    evaluation's carried state; ``signals`` records what the decision
    saw (the decision log / flight recorder payload)."""

    action: str
    reason: str
    n_live: int
    target: int
    low_streak: int
    cooldown: int
    signals: dict


def decide(view: FleetView, *, config: AutoscalerConfig,
           slo: SLOConfig, low_streak: int = 0,
           cooldown: int = 0) -> Decision:
    """Pure decision core: fleet view + carried state -> verdict.

    Scale-up triggers (any one suffices): windowed TTFT or decode p99
    over the SLO target (``inf`` — observations past every bucket —
    breaches too), pending depth past ``pending_per_replica`` x fleet,
    or any replica's KV pool past ``slo.max_kv_utilization``. A breach
    resets the low-load streak; the action is still ``hold`` while a
    cooldown is pending or the fleet is at ``max_replicas``.

    Scale-down requires ``hysteresis_evals`` CONSECUTIVE quiet
    evaluations (nothing pending, nothing queued, slot occupancy at or
    under ``low_load_utilization``), no pending cooldown, and a fleet
    above ``min_replicas`` — then retires exactly one replica.
    """
    live = [s for s in view.replicas if s.state == "active"]
    n = len(live)
    ttft_p99 = percentile(view.ttft, 0.99) if view.ttft else None
    dec_p99 = percentile(view.decode, 0.99) if view.decode else None
    kv_max = max((s.kv_utilization for s in live), default=0.0)
    queued = sum(s.queue_depth for s in live)
    slots = sum(s.active_slots + s.free_slots for s in live)
    busy = (sum(s.active_slots for s in live) / slots) if slots else 0.0
    qwait_p99 = (percentile(view.queue_wait, 0.99)
                 if view.queue_wait else None)
    signals = {
        "ttft_p99_s": ttft_p99, "decode_token_p99_s": dec_p99,
        "pending": int(view.pending), "queued": queued,
        "kv_utilization_max": kv_max, "busy_fraction": busy,
        # observed, not (yet) acted on: the router-side wait rides the
        # decision log so a pending-driven scale-up can be attributed
        "queue_wait_p99_s": qwait_p99,
    }

    breaches = []
    if ttft_p99 is not None and ttft_p99 > slo.ttft_p99_s:
        breaches.append(
            f"ttft p99 {_fmt_s(ttft_p99)} > {slo.ttft_p99_s:.3g}s")
    if dec_p99 is not None and dec_p99 > slo.decode_token_p99_s:
        breaches.append(f"decode p99 {_fmt_s(dec_p99)} > "
                        f"{slo.decode_token_p99_s:.3g}s/token")
    if view.pending > config.pending_per_replica * max(n, 1):
        breaches.append(
            f"{view.pending} pending > "
            f"{config.pending_per_replica}/replica x {max(n, 1)}")
    if kv_max >= slo.max_kv_utilization:
        breaches.append(f"KV pool at {kv_max:.0%} >= "
                        f"{slo.max_kv_utilization:.0%}")

    if breaches:
        reason = "; ".join(breaches)
        if cooldown > 0:
            return Decision("hold", f"cooling down ({cooldown} evals "
                            f"left): {reason}", n, n, 0,
                            cooldown - 1, signals)
        if n >= config.max_replicas:
            return Decision("hold", f"at max_replicas "
                            f"({config.max_replicas}): {reason}",
                            n, n, 0, 0, signals)
        target = min(n + config.scale_step, config.max_replicas)
        return Decision("up", reason, n, target, 0,
                        config.cooldown_evals, signals)

    low = (view.pending == 0 and queued == 0
           and busy <= config.low_load_utilization)
    if not low:
        return Decision("hold", "within SLO under load", n, n, 0,
                        max(cooldown - 1, 0), signals)
    streak = low_streak + 1
    if cooldown > 0:
        return Decision("hold", f"quiet but cooling down ({cooldown} "
                        "evals left)", n, n, streak, cooldown - 1,
                        signals)
    if n <= config.min_replicas:
        return Decision("hold", f"quiet at min_replicas "
                        f"({config.min_replicas})", n, n, streak, 0,
                        signals)
    if streak < config.hysteresis_evals:
        return Decision("hold", f"quiet {streak}/"
                        f"{config.hysteresis_evals} evals", n, n,
                        streak, 0, signals)
    return Decision("down", f"quiet for {streak} evals", n, n - 1,
                    0, config.cooldown_evals, signals)


def _fmt_s(v: float) -> str:
    return "inf" if math.isinf(v) else f"{v:.3g}s"


_LATENCY_METRICS = ("serving_ttft_seconds",
                    "serving_decode_token_seconds")


def _delta_snapshot(cur: dict, prev: dict | None) -> dict:
    """Windowed histogram: cumulative snapshot minus the previous
    evaluation's (same metric, same replica, so boundaries match;
    missing previous keys count from zero). Clamped at zero so a
    replica restart (counts reset) degrades to "whole new history" not
    negative mass."""
    if not prev:
        return cur
    pb = prev.get("buckets") or {}
    buckets = {le: max(int(c) - int(pb.get(le, 0)), 0)
               for le, c in (cur.get("buckets") or {}).items()}
    return {
        "buckets": buckets,
        "sum": max(float(cur.get("sum", 0.0))
                   - float(prev.get("sum", 0.0)), 0.0),
        "count": max(int(cur.get("count", 0))
                     - int(prev.get("count", 0)), 0),
    }


class Autoscaler:
    """The closed loop over a :class:`Router` (and its pool): scrape ->
    :func:`decide` -> apply -> observe. ``evaluate()`` runs one
    iteration synchronously (what tests and drills call);
    ``start()``/``close()`` run it on a daemon thread every
    ``config.interval_s``.

    - ``recorder``: an optional
      :class:`~bigdl_tpu.observability.flight_recorder.FlightRecorder`;
      every decision lands in its event ring (postmortems answer "why
      did the fleet resize?").
    - ``max_decisions``: bound on the in-memory decision log
      (``.decisions``).
    """

    def __init__(self, router, *, config: AutoscalerConfig | None = None,
                 slo: SLOConfig | None = None, registry=None,
                 recorder=None, max_decisions: int = 256):
        self.router = router
        self.pool = router.pool
        self.config = config if config is not None else AutoscalerConfig()
        self.slo = slo if slo is not None else router.slo
        self._recorder = recorder
        self.decisions: deque = deque(maxlen=int(max_decisions))
        self._low_streak = 0
        self._cooldown = 0
        self._prev: dict = {}     # replica -> metric -> last snapshot
        self._prev_qwait: dict | None = None  # router queue-wait window
        self._eval_lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

        reg = default_registry() if registry is None else registry
        self._g_replicas = reg.gauge(
            "autoscaler_replicas", "live replicas at last evaluation")
        self._g_target = reg.gauge(
            "autoscaler_target_replicas",
            "fleet size the last decision aimed for")
        self._g_streak = reg.gauge(
            "autoscaler_low_load_streak",
            "consecutive quiet evaluations toward the scale-down "
            "hysteresis window")
        self._g_cooldown = reg.gauge(
            "autoscaler_cooldown_evals",
            "evaluations left in the post-scale-event cooldown")
        self._m_decisions = reg.counter(
            "autoscaler_decisions_total",
            "autoscaler evaluations by decided action",
            labelnames=("action",))
        self._m_up = reg.counter(
            "autoscaler_scale_up_total", "replicas added by scale-up")
        self._m_down = reg.counter(
            "autoscaler_scale_down_total",
            "replicas drained+removed by scale-down")

    # -- scrape --
    def observe(self) -> FleetView:
        """One fleet scrape: per-replica stats plus WINDOWED latency
        snapshots (cumulative minus the previous evaluation's — see
        module docstring). A replica stopped mid-scrape is skipped, not
        fatal."""
        stats, ttft, dec = [], [], []
        prev_next: dict = {}
        for rep in self.pool:
            try:
                stats.append(rep.stats())
                cur = {m: rep.histogram_snapshot(m)
                       for m in _LATENCY_METRICS}
            except Exception:
                continue        # drained/stopped mid-scrape
            last = self._prev.get(rep.name)
            ttft.append(_delta_snapshot(
                cur[_LATENCY_METRICS[0]],
                last and last.get(_LATENCY_METRICS[0])))
            dec.append(_delta_snapshot(
                cur[_LATENCY_METRICS[1]],
                last and last.get(_LATENCY_METRICS[1])))
            prev_next[rep.name] = cur
        self._prev = prev_next    # removed replicas fall out here
        # router-level queue wait, same windowing (getattr-guarded so
        # test doubles without the method keep working)
        qwait = None
        snap_fn = getattr(self.router, "queue_wait_snapshot", None)
        if callable(snap_fn):
            try:
                cur_q = snap_fn()
            except Exception:
                cur_q = None
            if cur_q is not None:
                qwait = _delta_snapshot(cur_q, self._prev_qwait)
                self._prev_qwait = cur_q
        return FleetView(replicas=tuple(stats),
                         ttft=merge_snapshots(ttft),
                         decode=merge_snapshots(dec),
                         pending=self.router.pending_count,
                         queue_wait=qwait)

    # -- the loop body --
    def evaluate(self) -> Decision:
        """Scrape, decide, apply, record. Thread-safe; one evaluation
        at a time."""
        with self._eval_lock:
            view = self.observe()
            d = decide(view, config=self.config, slo=self.slo,
                       low_streak=self._low_streak,
                       cooldown=self._cooldown)
            self._low_streak, self._cooldown = d.low_streak, d.cooldown
            applied = {}
            if d.action == "up":
                applied["added"] = self._scale_up(d)
            elif d.action == "down":
                applied["removed"] = self._scale_down(d)
            self._observe_decision(d, applied)
            return d

    def _scale_up(self, d: Decision) -> list:
        added = []
        for _ in range(d.target - d.n_live):
            rep = self.pool.add_replica()
            self.router.attach_replica(rep.name)
            added.append(rep.name)
            self._m_up.inc()
        trace.instant("autoscale up", cat="serving", reason=d.reason,
                      added=added, n_live=d.n_live, target=d.target)
        return added

    def _pick_victim(self) -> str | None:
        """Lowest-load active replica, sparing the designated prefill
        replica while any alternative exists (retiring the
        disaggregation target forces per-request fallbacks)."""
        live = [s for s in self.pool.stats() if s.state == "active"]
        if len(live) <= self.config.min_replicas:
            return None
        spared = getattr(self.router, "_prefill_name", None)
        cands = [s for s in live if s.name != spared] or live
        return min(cands, key=load_score).name

    def _scale_down(self, d: Decision) -> str | None:
        victim = self._pick_victim()
        if victim is None:
            return None
        # drain migrates queued + in-flight work to the survivors
        # BEFORE the stop, so nothing is dropped or duplicated
        self.router.drain(victim, migrate=True)
        self.pool.remove_replica(victim)
        self._m_down.inc()
        trace.instant("autoscale down", cat="serving", reason=d.reason,
                      removed=victim, n_live=d.n_live, target=d.target)
        return victim

    def _observe_decision(self, d: Decision, applied: dict) -> None:
        self._g_replicas.set(len(self.pool))
        self._g_target.set(d.target)
        self._g_streak.set(d.low_streak)
        self._g_cooldown.set(d.cooldown)
        self._m_decisions.inc(action=d.action)
        entry = {"t": time.time(), "action": d.action,
                 "reason": d.reason, "n_live": d.n_live,
                 "target": d.target, "low_streak": d.low_streak,
                 "cooldown": d.cooldown, **applied}
        entry.update({f"signal_{k}": v for k, v in d.signals.items()})
        self.decisions.append(entry)
        if self._recorder is not None:
            try:
                self._recorder.record("autoscale", d.action, **entry)
            except Exception:
                pass            # observability must not break scaling

    # -- background loop --
    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="bigdl-serving-autoscaler",
            daemon=True)
        self._thread.start()
        return self

    def _run(self):
        import logging
        log = logging.getLogger(__name__)
        while not self._stop.wait(self.config.interval_s):
            try:
                self.evaluate()
            except Exception:
                # one bad evaluation (replica racing a manual drain,
                # say) must not kill the loop
                log.exception("autoscaler evaluation failed")

    def close(self, timeout: float = 10.0) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout)
        self._thread = None

    def __enter__(self) -> "Autoscaler":
        return self

    def __exit__(self, *exc):
        self.close()
        return False

"""AllReduceParameter — the distributed parameter-aggregation seam.

Reference parity: parameters/AllReduceParameter.scala:53-229, the
slice-owned parameter server over Spark's BlockManager:

  init           -> slice weights across N partitions          (:99-116)
  getWeights     -> all-gather FP16 weight slices              (:134-159)
  putGradients   -> send my gradient sliced to each owner      (:201-215)
  aggregate      -> owner sums its N incoming slices           (:161-199)
  sendWeight     -> republish my updated slice                 (:217-228)

TPU-native design: the five phases are THE two XLA collectives —
``reduce_scatter`` (putGradients+aggregate) and ``all_gather``
(sendWeight+getWeights) — over the mesh's data axis, or a single fused
``psum`` when slice ownership isn't wanted. This class keeps the
reference's slice bookkeeping (balanced ``task_size + (pid < extra)``
layout, :100-102) so optimizer state can be owned per-slice (ZeRO-1) and
checkpoints of sliced optimizer state stay layout-compatible.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.parallel.engine import get_mesh
from bigdl_tpu.parallel import collective as C
from bigdl_tpu.tensor import flatten_params

__all__ = ["AllReduceParameter", "slice_bounds"]


def slice_bounds(size: int, partition_num: int, pid: int) -> tuple[int, int]:
    """Balanced slice layout (reference AllReduceParameter.scala:100-102:
    ``taskSize + (pid < extraSize ? 1 : 0)``). Returns (offset, length)."""
    task_size = size // partition_num
    extra = size % partition_num
    start = task_size * pid + min(pid, extra)
    length = task_size + (1 if pid < extra else 0)
    return start, length


class AllReduceParameter:
    """Collective-backed flat-parameter aggregation over the data axis."""

    def __init__(self, partition_num: int | None = None,
                 size: int | None = None,
                 *, axis: str = "data", mesh=None,
                 wire_dtype=jnp.bfloat16):
        self.mesh = mesh or get_mesh()
        self.axis = axis
        self.partition_num = partition_num or int(self.mesh.shape[axis])
        self.size = size
        self.wire_dtype = wire_dtype
        self._unravel = None

    # -- canonical fused path (what DistriOptimizer compiles) --
    def all_reduce_gradients(self, per_shard_grads, *, mean: bool = True):
        """Reduce per-shard gradient pytrees into one global gradient.

        ``per_shard_grads``: a sequence of N gradient trees (one per mesh
        shard along ``axis``). Returns the mean (or sum) tree, replicated.
        A single tree is rejected — leaves whose leading dim happens to
        equal the mesh size would be silently mis-reduced. Note
        DistriOptimizer doesn't need this — its allreduce is induced by
        batch sharding inside the jitted step; this is the eager emulation
        of the reference's N-party protocol."""
        if not isinstance(per_shard_grads, (list, tuple)):
            raise ValueError(
                "all_reduce_gradients wants a sequence of N per-shard "
                "gradient trees (one per mesh shard), not a single tree")
        grads = jax.tree.map(lambda *ls: jnp.stack(ls), *per_shard_grads)
        return C.psum_tree(grads, self.axis, self.mesh, mean=mean,
                           wire_dtype=self.wire_dtype)

    # -- slice-owned path (reference's phase structure, ZeRO-style) --
    def init(self, parameter):
        """Record the flat layout (reference ``init`` slicing, :99-116)."""
        flat, unravel = flatten_params(parameter)
        self.size = int(flat.size)
        self._unravel = unravel
        return flat

    def put_gradients(self, per_shard_grads, *, mean: bool = False):
        """reduce-scatter per-shard gradients: each mesh shard ends up
        owning the SUM (or mean) of its slice of the N distinct
        contributions (reference putGradients +
        aggregrateGradientPartition collapsed, :161-215).

        ``per_shard_grads``: a sequence of N gradient trees / flat vectors
        (one per shard), or a pre-stacked ``(N, S)`` array. Returns the
        sharded flat gradient of global shape ``(S,)``."""
        grads = per_shard_grads
        if isinstance(grads, (list, tuple)):
            flats = []
            for g in grads:
                if not (hasattr(g, "ndim") and g.ndim == 1):
                    g, _ = flatten_params(g)
                flats.append(g)
            stacked = jnp.stack(flats)
        else:
            if not hasattr(grads, "ndim") or grads.ndim != 2:
                raise ValueError(
                    "put_gradients wants N per-shard contributions (a "
                    "sequence of trees/vectors or an (N, S) stack); a "
                    "single replicated gradient/tree would be summed N "
                    "times")
            stacked = jnp.asarray(grads)
        pad = (-stacked.shape[1]) % self.partition_num
        if pad:
            stacked = jnp.concatenate(
                [stacked, jnp.zeros((stacked.shape[0], pad), stacked.dtype)],
                axis=1)
        return C.reduce_scatter(stacked, self.axis, self.mesh, mean=mean,
                                wire_dtype=self.wire_dtype)

    def get_weights(self, sharded_flat):
        """all-gather the updated slices back into the full flat weight
        (reference sendWeightPartition + getWeights, :134-159,217-228)."""
        full = C.all_gather(sharded_flat, self.axis, self.mesh)
        if self.size is not None:
            full = full[:self.size]
        return self._unravel(full) if self._unravel is not None else full

    def aggregrate_gradient_partition(self, grads):
        """Reference-named alias (sic) for the reduce-scatter phase."""
        return self.put_gradients(grads)

"""AllReduceParameter — the distributed parameter-aggregation seam.

Reference parity: parameters/AllReduceParameter.scala:53-229, the
slice-owned parameter server over Spark's BlockManager:

  init           -> slice weights across N partitions          (:99-116)
  getWeights     -> all-gather FP16 weight slices              (:134-159)
  putGradients   -> send my gradient sliced to each owner      (:201-215)
  aggregate      -> owner sums its N incoming slices           (:161-199)
  sendWeight     -> republish my updated slice                 (:217-228)

TPU-native design: the five phases are THE two XLA collectives —
``reduce_scatter`` (putGradients+aggregate) and ``all_gather``
(sendWeight+getWeights) — over the mesh's data axis, or a single fused
``psum`` when slice ownership isn't wanted. This class keeps the
reference's slice bookkeeping (balanced ``task_size + (pid < extra)``
layout, :100-102) so optimizer state can be owned per-slice (ZeRO-1) and
checkpoints of sliced optimizer state stay layout-compatible.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.parallel.engine import get_mesh
from bigdl_tpu.parallel import collective as C
from bigdl_tpu.tensor import flatten_params

__all__ = ["AllReduceParameter", "slice_bounds"]


def slice_bounds(size: int, partition_num: int, pid: int) -> tuple[int, int]:
    """Balanced slice layout (reference AllReduceParameter.scala:100-102:
    ``taskSize + (pid < extraSize ? 1 : 0)``). Returns (offset, length)."""
    task_size = size // partition_num
    extra = size % partition_num
    start = task_size * pid + min(pid, extra)
    length = task_size + (1 if pid < extra else 0)
    return start, length


class AllReduceParameter:
    """Collective-backed flat-parameter aggregation over the data axis."""

    def __init__(self, partition_num: int | None = None, size: int | None = None,
                 *, axis: str = "data", mesh=None,
                 wire_dtype=jnp.bfloat16):
        self.mesh = mesh or get_mesh()
        self.axis = axis
        self.partition_num = partition_num or int(self.mesh.shape[axis])
        self.size = size
        self.wire_dtype = wire_dtype
        self._unravel = None

    # -- canonical fused path (what DistriOptimizer compiles) --
    def all_reduce_gradients(self, grads, *, mean: bool = True):
        """One fused collective for a gradient pytree — inside a jitted
        step this lowers to the backward-pass allreduce."""
        return C.psum_tree(grads, self.axis, self.mesh, mean=mean,
                           wire_dtype=self.wire_dtype)

    # -- slice-owned path (reference's phase structure, ZeRO-style) --
    def init(self, parameter):
        """Record the flat layout (reference ``init`` slicing, :99-116)."""
        flat, unravel = flatten_params(parameter)
        self.size = int(flat.size)
        self._unravel = unravel
        return flat

    def _padded(self, flat):
        pad = (-flat.size) % self.partition_num
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros(pad, flat.dtype)])
        return flat

    def put_gradients(self, grad_tree_or_flat):
        """reduce-scatter the flat gradient: each mesh shard ends up owning
        the SUM of its slice (reference putGradients +
        aggregrateGradientPartition collapsed, :161-215). Returns the
        sharded flat gradient."""
        flat = grad_tree_or_flat
        if not isinstance(flat, jnp.ndarray) or flat.ndim != 1:
            flat, _ = flatten_params(grad_tree_or_flat)
        return C.reduce_scatter(self._padded(flat), self.axis, self.mesh,
                                wire_dtype=self.wire_dtype)

    def get_weights(self, sharded_flat):
        """all-gather the updated slices back into the full flat weight
        (reference sendWeightPartition + getWeights, :134-159,217-228)."""
        full = C.all_gather(sharded_flat, self.axis, self.mesh)
        if self.size is not None:
            full = full[:self.size]
        return self._unravel(full) if self._unravel is not None else full

    def aggregrate_gradient_partition(self, grads):
        """Reference-named alias (sic) for the reduce-scatter phase."""
        return self.put_gradients(grads)
